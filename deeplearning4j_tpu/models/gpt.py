"""GPT — decoder-only causal transformer for generative serving.

The generative tier of the model zoo (ROADMAP item 2): the block layout —
post-LN residual attention + FFN with the same param names (Wq/bq … W2/b2,
ln_gamma/ln_beta) — is ``models/bert.py``'s encoder block reused verbatim,
so TP sharding rules (`parallel.mesh.DEFAULT_TP_RULES`) and checkpoint
mapping apply unchanged. What differs is the attention pattern and the
execution split the serving engine needs:

* **prefill** (:func:`gpt_prefill`): the whole prompt in ONE causal
  attention pass through the registry's ``dot_product_attention`` — the
  Pallas flash platform helper fires on TPU above the ``flash_min_t()``
  crossover, the XLA path below it — returning per-position logits AND the
  per-layer K/V the serving engine scatters into its paged cache.
* **decode** (:func:`gpt_decode_step`): ONE token per sequence against the
  block-paged KV cache via the registry's ``paged_decode_attention``
  (Pallas on TPU, gather fallback elsewhere). All shapes are functions of
  the slot capacity, never of the number of active sequences, so the
  serving loop compiles exactly once (docs/SERVING.md).
* **verify** (:func:`gpt_verify`): the speculative-decoding target pass
  (docs/SERVING.md § Speculative decoding) — ``K+1`` proposed tokens per
  sequence in ONE forward against the paged cache, scoring every draft
  proposal at once. Shapes depend on ``(max_slots, spec_k, page
  geometry)`` only, so speculation joins the compile-once family.

Draft/target pairing: :func:`draft_config_for` builds the GPT-tiny-sized
draft config that shares a target's vocab/eos/positions — the pairing the
zoo exposes as ``models.GPT(preset).init_draft()``.

Tied embeddings: logits project through ``embeddings.word.T`` (the BERT MLM
head convention), so the checkpoint is exactly the param pytree.
"""

from __future__ import annotations

import dataclasses
import json
import math
import zipfile
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.models.bert import _layer_norm


@dataclasses.dataclass(frozen=True)
class GptConfig:
    """GPT-2-small defaults; ``tiny()`` for tests and CPU smoke serving."""

    vocab_size: int = 50257
    hidden: int = 768
    layers: int = 12
    heads: int = 12
    intermediate: int = 3072
    max_position: int = 1024
    layer_norm_eps: float = 1e-5
    eos_token: int = 0

    @staticmethod
    def base(**kw) -> "GptConfig":
        return GptConfig(**kw)

    @staticmethod
    def tiny(**kw) -> "GptConfig":
        """Test-sized config (mirrors BertConfig.tiny)."""
        d = dict(vocab_size=256, hidden=64, layers=2, heads=4,
                 intermediate=128, max_position=128)
        d.update(kw)
        return GptConfig(**d)

    # ------------------------------------------------------------- round-trip
    def to_json(self) -> str:
        return json.dumps({"@type": "GptConfig",
                           **dataclasses.asdict(self)}, indent=1)

    @staticmethod
    def from_json(s: str) -> "GptConfig":
        d = json.loads(s)
        d.pop("@type", None)
        return GptConfig(**d)


def draft_config_for(cfg: GptConfig, **overrides) -> "GptConfig":
    """The paired DRAFT config for speculative decoding against ``cfg``
    (docs/SERVING.md § Speculative decoding): GPT-tiny-sized transformer
    dims, but vocab_size/eos_token/max_position copied from the target —
    draft proposals are target token ids at target positions, so those
    three must agree (the serving engine validates them again at
    construction). ``overrides`` widen/narrow the draft dims."""
    d = dict(vocab_size=cfg.vocab_size, max_position=cfg.max_position,
             eos_token=cfg.eos_token, hidden=64, layers=2, heads=4,
             intermediate=128)
    d.update(overrides)
    return GptConfig(**d)


def init_gpt_params(key, cfg: GptConfig, dtype=jnp.float32) -> Dict[str, Any]:
    """Parameter pytree; block layout and names identical to
    ``init_bert_params`` encoder blocks (attn Wq…Wo + ln, ffn W1/W2 + ln)."""
    ks = iter(jax.random.split(key, 4 + cfg.layers * 16))

    def nrm(shape):
        return 0.02 * jax.random.normal(next(ks), shape, dtype)

    p: Dict[str, Any] = {
        "embeddings": {
            "word": nrm((cfg.vocab_size, cfg.hidden)),
            "position": nrm((cfg.max_position, cfg.hidden)),
            "ln_gamma": jnp.ones((cfg.hidden,), dtype),
            "ln_beta": jnp.zeros((cfg.hidden,), dtype),
        },
        "blocks": [],
    }
    for _ in range(cfg.layers):
        p["blocks"].append({
            "attn": {
                "Wq": nrm((cfg.hidden, cfg.hidden)), "bq": jnp.zeros((cfg.hidden,), dtype),
                "Wk": nrm((cfg.hidden, cfg.hidden)), "bk": jnp.zeros((cfg.hidden,), dtype),
                "Wv": nrm((cfg.hidden, cfg.hidden)), "bv": jnp.zeros((cfg.hidden,), dtype),
                "Wo": nrm((cfg.hidden, cfg.hidden)), "bo": jnp.zeros((cfg.hidden,), dtype),
                "ln_gamma": jnp.ones((cfg.hidden,), dtype),
                "ln_beta": jnp.zeros((cfg.hidden,), dtype),
            },
            "ffn": {
                "W1": nrm((cfg.hidden, cfg.intermediate)),
                "b1": jnp.zeros((cfg.intermediate,), dtype),
                "W2": nrm((cfg.intermediate, cfg.hidden)),
                "b2": jnp.zeros((cfg.hidden,), dtype),
                "ln_gamma": jnp.ones((cfg.hidden,), dtype),
                "ln_beta": jnp.zeros((cfg.hidden,), dtype),
            },
        })
    return p


def _ffn(blk, x, eps):
    f = blk["ffn"]
    hdn = jax.nn.gelu(x @ f["W1"] + f["b1"])
    return _layer_norm(x + hdn @ f["W2"] + f["b2"],
                       f["ln_gamma"], f["ln_beta"], eps)


def gpt_prefill(params, ids, cfg: GptConfig, *, mask=None
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Causal full-prompt forward.

    ids: (N, T) int32; mask: optional (N, T) 1=real token (end padding).
    Returns ``(logits (N, T, V), kv (L, 2, N, T, H, Dh))`` — the per-layer
    keys/values the serving engine scatters into its paged cache.
    """
    from deeplearning4j_tpu.ops import exec_op

    emb = params["embeddings"]
    n, t = ids.shape
    if t > cfg.max_position:
        # the position gather would silently CLAMP indices past
        # max_position (every excess token reusing the last embedding) —
        # reject instead of returning quietly-wrong logits
        raise ValueError(
            f"sequence length {t} exceeds max_position={cfg.max_position}")
    h, dh = cfg.heads, cfg.hidden // cfg.heads
    x = emb["word"][ids] + emb["position"][jnp.arange(t)][None]
    x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)

    def split(a):  # (N, T, E) -> (N, H, T, Dh)
        return a.reshape(n, t, h, dh).transpose(0, 2, 1, 3)

    m4 = None if mask is None else mask[:, None, None, :].astype(bool)
    kvs = []
    for blk in params["blocks"]:
        a = blk["attn"]
        q = split(x @ a["Wq"] + a["bq"])
        k = split(x @ a["Wk"] + a["bk"])
        v = split(x @ a["Wv"] + a["bv"])
        # (2, N, T, H, Dh) — token-major, the paged-cache scatter layout
        kvs.append(jnp.stack([k.transpose(0, 2, 1, 3),
                              v.transpose(0, 2, 1, 3)]))
        out = exec_op("dot_product_attention", q, k, v, m4, scaled=True,
                      causal=True)
        out = out.transpose(0, 2, 1, 3).reshape(n, t, cfg.hidden)
        x = _layer_norm(x + out @ a["Wo"] + a["bo"],
                        a["ln_gamma"], a["ln_beta"], cfg.layer_norm_eps)
        x = _ffn(blk, x, cfg.layer_norm_eps)
    logits = x @ emb["word"].T
    return logits, jnp.stack(kvs)


def gpt_prefill_suffix(params, ids, prefix_kv, prefix_len, suffix_len,
                       cfg: GptConfig) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Suffix-only prefill against a cached prefix (the radix prefix
    cache's fast path, docs/SERVING.md § Radix prefix cache).

    ids: (1, B) int32 — the prompt's UNCACHED tail, zero-padded to the
    engine's suffix bucket; prefix_kv: (L, 2, Tpre, H, Dh) — the cached
    prefix K/V gathered from the paged cache (positions >= ``prefix_len``
    are garbage and masked); prefix_len/suffix_len: scalars. Suffix token
    i sits at absolute position ``prefix_len + i`` and attends to every
    valid prefix position plus suffix positions <= i — the same causal
    math as :func:`gpt_prefill`, computed for B tokens instead of the
    whole prompt. Returns ``(logits (1, B, V), kv (L, 2, B, H, Dh))`` —
    the suffix K/V for the cache scatter (token-major, like the prefill
    layout the engine already writes).
    """
    from deeplearning4j_tpu.ops import exec_op

    emb = params["embeddings"]
    n, b = ids.shape
    t_pre = prefix_kv.shape[2]
    h, dh = cfg.heads, cfg.hidden // cfg.heads
    pos = jnp.clip(prefix_len + jnp.arange(b), 0, cfg.max_position - 1)
    x = emb["word"][ids] + emb["position"][pos][None]
    x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)

    def split(a):  # (1, B, E) -> (1, H, B, Dh)
        return a.reshape(n, b, h, dh).transpose(0, 2, 1, 3)

    # (1, 1, B, Tpre + B) bool: query i -> prefix j < prefix_len, then
    # suffix j' <= i (causal) and j' < suffix_len (padding)
    qi = jnp.arange(b)[:, None]
    m_pre = jnp.broadcast_to(jnp.arange(t_pre)[None, :] < prefix_len,
                             (b, t_pre))
    js = jnp.arange(b)[None, :]
    m_suf = (js <= qi) & (js < suffix_len)
    m4 = jnp.concatenate([m_pre, m_suf], axis=1)[None, None]
    kvs = []
    for li, blk in enumerate(params["blocks"]):
        a = blk["attn"]
        q = split(x @ a["Wq"] + a["bq"])
        k = split(x @ a["Wk"] + a["bk"])
        v = split(x @ a["Wv"] + a["bv"])
        kvs.append(jnp.stack([k.transpose(0, 2, 1, 3)[0],
                              v.transpose(0, 2, 1, 3)[0]]))  # (2, B, H, Dh)
        kp = prefix_kv[li, 0].transpose(1, 0, 2)[None]  # (1, H, Tpre, Dh)
        vp = prefix_kv[li, 1].transpose(1, 0, 2)[None]
        out = exec_op("dot_product_attention", q,
                      jnp.concatenate([kp, k], axis=2),
                      jnp.concatenate([vp, v], axis=2), m4, scaled=True)
        out = out.transpose(0, 2, 1, 3).reshape(n, b, cfg.hidden)
        x = _layer_norm(x + out @ a["Wo"] + a["bo"],
                        a["ln_gamma"], a["ln_beta"], cfg.layer_norm_eps)
        x = _ffn(blk, x, cfg.layer_norm_eps)
    logits = x @ emb["word"].T
    return logits, jnp.stack(kvs)


def gpt_verify(params, kv_pages, tokens, seq_lens, page_table, write_pages,
               write_offsets, cfg: GptConfig, *, page_size: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Speculative-decoding verification: score ``B = K + 1`` proposed
    tokens per slot in ONE causal forward against the paged KV cache
    (docs/SERVING.md § Speculative decoding).

    kv_pages: (L, 2, P, page, H, Dh) — functionally updated (donate it);
    tokens: (S, B) int32 — per slot, the last committed token followed by
    the draft's K proposals; seq_lens: (S,) tokens already CACHED for the
    slot (the fed run occupies absolute positions ``seq_lens + i``);
    page_table: (S, max_pages) int32; write_pages/write_offsets: (S, B)
    where each fed token's K/V lands (the engine points inactive slots at
    its trash page). Fed token ``i`` attends to every cached position
    ``< seq_lens`` plus fed positions ``<= i`` — the same causal math as
    :func:`gpt_prefill`, restricted to the B-token window. Returns
    ``(kv_pages, greedy (S, B) int32)`` — the target's argmax at every
    fed position, which is all greedy acceptance needs: proposal ``d_i``
    is accepted iff it equals the argmax at position ``i - 1``, and the
    argmax after the accepted prefix is the correction/bonus token.

    The K/V of EVERY fed token is scattered (positions past the accepted
    prefix become garbage beyond the engine's rewound ``seq_lens`` —
    never read, overwritten by the next pass), so acceptance costs no
    second write pass.
    """
    from deeplearning4j_tpu.ops import exec_op

    emb = params["embeddings"]
    s_n, b = tokens.shape
    t_v = page_table.shape[1] * page_size
    h, dh = cfg.heads, cfg.hidden // cfg.heads
    pos = jnp.clip(seq_lens[:, None] + jnp.arange(b)[None, :], 0,
                   cfg.max_position - 1)
    x = emb["word"][tokens] + emb["position"][pos]
    x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)

    def split(a):  # (S, B, E) -> (S, H, B, Dh)
        return a.reshape(s_n, b, h, dh).transpose(0, 2, 1, 3)

    # (S, 1, B, Tv + B) bool: query i -> cached j < seq_lens, then fed
    # j' <= i (causal within the window). Fed tokens also land in the
    # gathered page range at positions >= seq_lens, but the cached-side
    # mask excludes them — their fresh K/V enters via the concat instead.
    tpos = jnp.arange(t_v)
    m_ctx = jnp.broadcast_to((tpos[None, None, :]
                              < seq_lens[:, None, None]), (s_n, b, t_v))
    qi = jnp.arange(b)[:, None]
    m_fed = jnp.broadcast_to(jnp.arange(b)[None, :] <= qi, (b, b))
    m4 = jnp.concatenate(
        [m_ctx, jnp.broadcast_to(m_fed[None], (s_n, b, b))],
        axis=2)[:, None]
    gpage = page_table[:, tpos // page_size]          # (S, Tv)
    goff = tpos % page_size
    for li, blk in enumerate(params["blocks"]):
        a = blk["attn"]
        q = split(x @ a["Wq"] + a["bq"])
        k = split(x @ a["Wk"] + a["bk"])
        v = split(x @ a["Wv"] + a["bv"])
        # scatter fed K/V token-major; trash-page duplicates are benign
        kv_pages = kv_pages.at[li, 0, write_pages, write_offsets].set(
            k.transpose(0, 2, 1, 3))
        kv_pages = kv_pages.at[li, 1, write_pages, write_offsets].set(
            v.transpose(0, 2, 1, 3))
        kc = kv_pages[li, 0][gpage, goff].transpose(0, 2, 1, 3)  # (S,H,Tv,Dh)
        vc = kv_pages[li, 1][gpage, goff].transpose(0, 2, 1, 3)
        out = exec_op("dot_product_attention", q,
                      jnp.concatenate([kc, k], axis=2),
                      jnp.concatenate([vc, v], axis=2), m4, scaled=True)
        out = out.transpose(0, 2, 1, 3).reshape(s_n, b, cfg.hidden)
        x = _layer_norm(x + out @ a["Wo"] + a["bo"],
                        a["ln_gamma"], a["ln_beta"], cfg.layer_norm_eps)
        x = _ffn(blk, x, cfg.layer_norm_eps)
    logits = x @ emb["word"].T
    return kv_pages, jnp.argmax(logits, axis=-1).astype(jnp.int32)


def gpt_decode_step(params, kv_pages, tokens, positions, page_table,
                    seq_lens_incl, write_page, write_offset, cfg: GptConfig
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One decode token for every slot, against the paged KV cache.

    kv_pages: (L, 2, P, page, H, Dh) — functionally updated (donate it);
    tokens/positions: (S,) int32 — the token being fed and its position;
    page_table: (S, max_pages) int32; seq_lens_incl: (S,) valid length
    INCLUDING this token; write_page/write_offset: (S,) where this token's
    K/V land (the engine points inactive slots at its trash page).
    Returns ``(kv_pages, logits (S, V))``.
    """
    from deeplearning4j_tpu.ops import exec_op

    emb = params["embeddings"]
    s_n = tokens.shape[0]
    h, dh = cfg.heads, cfg.hidden // cfg.heads
    pos = jnp.clip(positions, 0, cfg.max_position - 1)
    x = emb["word"][tokens] + emb["position"][pos]
    x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)
    for li, blk in enumerate(params["blocks"]):
        a = blk["attn"]
        q = (x @ a["Wq"] + a["bq"]).reshape(s_n, h, dh)
        k = (x @ a["Wk"] + a["bk"]).reshape(s_n, h, dh)
        v = (x @ a["Wv"] + a["bv"]).reshape(s_n, h, dh)
        kv_pages = kv_pages.at[li, 0, write_page, write_offset].set(k)
        kv_pages = kv_pages.at[li, 1, write_page, write_offset].set(v)
        attn = exec_op("paged_decode_attention", q, kv_pages[li, 0],
                       kv_pages[li, 1], page_table, seq_lens_incl,
                       scale=1.0 / math.sqrt(dh))
        attn = attn.reshape(s_n, cfg.hidden)
        x = _layer_norm(x + attn @ a["Wo"] + a["bo"],
                        a["ln_gamma"], a["ln_beta"], cfg.layer_norm_eps)
        x = _ffn(blk, x, cfg.layer_norm_eps)
    logits = x @ emb["word"].T
    return kv_pages, logits


def reference_generate(params, cfg: GptConfig, prompt, n_new: int
                       ) -> np.ndarray:
    """Greedy autoregressive oracle: re-runs the FULL causal prefill for
    every generated token — O(T²) per token, test-sized only. The paged
    decode path must reproduce these tokens exactly (tests/test_serving.py
    greedy-equivalence gate)."""
    toks = list(np.asarray(prompt).tolist())
    for _ in range(n_new):
        ids = jnp.asarray(np.array(toks, np.int32)[None])
        logits, _ = gpt_prefill(params, ids, cfg)
        toks.append(int(jnp.argmax(logits[0, -1])))
    return np.array(toks[len(prompt):], np.int32)


class GptModel:
    """Decoder model handle: config + params (+ serde). The serving loop
    (``serving.GenerativeEngine``) owns batching, cache, and sampling."""

    def __init__(self, cfg: GptConfig, seed: int = 0, dtype=jnp.float32,
                 params: Optional[Dict[str, Any]] = None):
        self.cfg = cfg
        self.params = params if params is not None else init_gpt_params(
            jax.random.key(seed), cfg, dtype)

    def num_params(self) -> int:
        return sum(int(np.prod(l.shape))
                   for l in jax.tree.leaves(self.params))

    def logits(self, ids) -> np.ndarray:
        """Convenience full-sequence forward (no cache)."""
        out, _ = gpt_prefill(self.params, jnp.asarray(ids, jnp.int32),
                             self.cfg)
        return np.asarray(out)


# ---------------------------------------------------------------------------
# serde — the ModelSerializer zip layout (nn/serde.py) for the raw pytree
# ---------------------------------------------------------------------------


def save_gpt(model: GptModel, path: str) -> None:
    """configuration.json + coefficients.bin, the nn/serde.py zip layout.
    The coefficients buffer is f32 (widening bf16 losslessly); meta.json
    records the param dtype so restore casts back instead of silently
    promoting a bf16 model to f32 (2x param + KV-cache memory)."""
    from deeplearning4j_tpu.nn.serde import flatten_pytree

    dtype = str(jnp.dtype(jax.tree.leaves(model.params)[0].dtype))
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        z.writestr("configuration.json", model.cfg.to_json())
        z.writestr("meta.json", json.dumps({"dtype": dtype}))
        z.writestr("coefficients.bin", flatten_pytree(model.params).tobytes())


def restore_gpt(path: str) -> GptModel:
    from deeplearning4j_tpu.nn.serde import unflatten_pytree

    with zipfile.ZipFile(path, "r") as z:
        cfg = GptConfig.from_json(z.read("configuration.json").decode())
        flat = np.frombuffer(z.read("coefficients.bin"), np.float32)
        dtype = jnp.float32
        if "meta.json" in z.namelist():
            dtype = jnp.dtype(json.loads(z.read("meta.json"))["dtype"])
    # abstract template: same structure/shapes/dtypes, zero materialization
    # cost (a real init would burn the full param memory + PRNG time just
    # to be overwritten)
    template = jax.eval_shape(
        lambda: init_gpt_params(jax.random.key(0), cfg, dtype))
    return GptModel(cfg, params=unflatten_pytree(template, flat))
