"""Goodput-under-overload ramp — the ROADMAP 2(d) success metric.

One harness, three consumers (``BENCH_MODEL=generate`` +
``BENCH_OVERLOAD=1`` in bench.py, ``tools/slo.py`` / the ``slo`` gate
stage, and the chaos harness's frontend leg): drive a fresh
:class:`GenerativeEngine` with an OPEN-LOOP arrival stream past its
measured capacity and report **goodput** — tokens of requests that
completed (``eos``/``length``) WITHIN their deadline, per second of wall
time. Tokens decoded for a request that missed its deadline are real
work the hardware did and the user never saw; goodput is the number that
punishes it.

The ramp runs once with the :class:`SLOFrontend` in front of the engine
and once with raw ``engine.submit`` — same seed, same prompts, same
class mix, same deadlines, same offered schedule (the second leg reuses
the first leg's measured capacity so both see an identical arrival
rate). The frontend leg should WIN: predictive early shed refuses work
that cannot meet its deadline before it costs decode steps, priority
ordering keeps interactive TTFT flat while batch sheds, and the
degradation ladder trades answer length for deadline hits. The baseline
leg still expires queued requests at their deadline (PR-10 semantics) —
what it cannot do is refuse doomed work early, protect one class from
another, or shorten answers under pressure, which is exactly the gap
this measures.

Every request (including frontend burst injections) must reach a
terminal state, and the RecompileLedger must show ZERO ``new_shape``
serving events across all degradation transitions — overload management
must never cost a recompile (asserted by ``tools/slo.py`` and the
acceptance tests).
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import numpy as np

from deeplearning4j_tpu import observe

#: (class name, mix weight, deadline multiplier on the base deadline).
#: Interactive gets the tight deadline, batch 2.5× the slack — the mix a
#: chat product with a background lane actually sees. Interactive alone
#: fits inside capacity (0.3 × overload factor < 1 for factors < ~3.3),
#: so a frontend that PRIORITIZES can meet its deadlines while the FIFO
#: baseline drowns every class equally; deadlines are tight enough that
#: a deep queue position is genuinely hopeless, so early sheds cost no
#: goodput.
DEFAULT_MIX = (("interactive", 0.3, 1.0),
               ("standard", 0.3, 1.5),
               ("batch", 0.4, 2.5))


def _serving_new_shape_count() -> int:
    return sum(1 for e in observe.ledger().events()
               if e.graph == "serving" and e.cause == "new_shape")


def run_overload_ramp(*, frontend_on: bool, n_requests: int = 24,
                      gen_tokens: int = 12, max_slots: int = 2,
                      overload_factor: float = 2.5,
                      deadline_slack: float = 2.0, seed: int = 0,
                      vocab: int = 256,
                      capacity_tokens_per_sec: Optional[float] = None,
                      frontend_kwargs: Optional[Dict[str, Any]] = None,
                      slow_decode: bool = False,
                      result_timeout_s: float = 600.0) -> Dict[str, Any]:
    """One overload-ramp leg on a fresh tiny-GPT engine.

    ``capacity_tokens_per_sec``: reuse a previous leg's measured capacity
    so both legs offer the IDENTICAL arrival schedule (pass leg 1's
    ``capacity_tokens_per_sec`` into leg 2); measured inline when None.
    ``slow_decode``: arm the ``slow_decode`` fault point at probability
    1.0 for the whole leg (including the capacity probe) — every decode
    step pays the injected 50ms, so service time dominates host
    scheduling jitter and the on/off comparison is reproducible on a
    noisy CPU (the ``slo`` gate mode; leave False when the caller — the
    chaos harness — arms its own schedule). Returns a dict with goodput,
    per-reason/-class accounting, ladder states visited, and the serving
    ``new_shape`` delta.
    """
    from deeplearning4j_tpu import faults

    if slow_decode:
        faults.arm("slow_decode", prob=1.0, seed=0)
    try:
        return _run_leg(
            frontend_on=frontend_on, n_requests=n_requests,
            gen_tokens=gen_tokens, max_slots=max_slots,
            overload_factor=overload_factor, deadline_slack=deadline_slack,
            seed=seed, vocab=vocab,
            capacity_tokens_per_sec=capacity_tokens_per_sec,
            frontend_kwargs=frontend_kwargs,
            result_timeout_s=result_timeout_s)
    finally:
        if slow_decode:
            faults.disarm("slow_decode")


def _run_leg(*, frontend_on: bool, n_requests: int, gen_tokens: int,
             max_slots: int, overload_factor: float, deadline_slack: float,
             seed: int, vocab: int,
             capacity_tokens_per_sec: Optional[float],
             frontend_kwargs: Optional[Dict[str, Any]],
             result_timeout_s: float) -> Dict[str, Any]:
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine, SLOFrontend

    cfg = GptConfig.tiny(vocab_size=vocab)
    model = GptModel(cfg, seed=0)
    max_prompt = 16
    pages_per_seq = -(-(max_prompt + gen_tokens + 1) // 8) + 1
    eng = GenerativeEngine(model, max_slots=max_slots, page_size=8,
                           max_pages_per_seq=pages_per_seq,
                           max_prompt=max_prompt, seed=0)
    new_shape_before = _serving_new_shape_count()

    # warm the compiled paths: the ramp measures serving, not XLA
    eng.generate([np.asarray([1, 2], np.int32)], max_new_tokens=2,
                 eos_token=-1)

    if capacity_tokens_per_sec is None:
        # capacity probe: saturate the slot bank inline and time it
        probe = [np.asarray([3, 5, 7], np.int32)] * (2 * max_slots)
        t0 = time.perf_counter()
        res = eng.generate(probe, max_new_tokens=gen_tokens, eos_token=-1)
        dt = time.perf_counter() - t0
        capacity_tokens_per_sec = sum(len(r.tokens) for r in res) / dt

    # base deadline: the time a request needs when admitted IMMEDIATELY
    # into a fully-busy bank, times the slack; offered request rate is
    # overload_factor × the capacity request rate — past saturation by
    # construction
    per_req_s = gen_tokens * max_slots / capacity_tokens_per_sec
    base_deadline = deadline_slack * per_req_s
    offered_rps = overload_factor * capacity_tokens_per_sec / gen_tokens

    fe = None
    if frontend_on:
        from deeplearning4j_tpu.serving import (LadderThresholds,
                                                default_classes)
        classes = default_classes()
        # the default batch queue share is sized for a small engine —
        # scale it with the slot bank so the bound sheds GENUINE excess,
        # not viable batch work
        classes["batch"].max_queued = 4 * max_slots
        kw = dict(max_queue_total=6 * max_slots,
                  degraded_max_new_tokens=max(2, gen_tokens // 2),
                  est_tokens_per_request=float(gen_tokens),
                  classes=classes,
                  # admit only work whose estimated completion fits in
                  # 90% of its deadline: the headroom absorbs host-load
                  # spikes between the capacity probe and the ramp
                  shed_margin=0.9,
                  thresholds=LadderThresholds(
                      degraded_queue=2 * max_slots,
                      shedding_queue=5 * max_slots))
        kw.update(frontend_kwargs or {})
        fe = SLOFrontend(eng, **kw)

    r = np.random.RandomState(seed)
    names = [m[0] for m in DEFAULT_MIX]
    weights = np.asarray([m[1] for m in DEFAULT_MIX], np.float64)
    weights /= weights.sum()
    dl_mult = {m[0]: m[2] for m in DEFAULT_MIX}
    plan = []
    for i in range(n_requests):
        cls = names[int(r.choice(len(names), p=weights))]
        prompt = r.randint(1, vocab, size=int(r.randint(2, 8))) \
            .astype(np.int32)
        plan.append((cls, prompt, base_deadline * dl_mult[cls]))

    eng.start()
    done_t: Dict[int, float] = {}

    def _mark(i: int):
        def _cb(_fut) -> None:
            done_t[i] = time.perf_counter()
        return _cb

    futs, sub_t = [], []
    try:
        t_start = time.perf_counter()
        for i, (cls, prompt, deadline) in enumerate(plan):
            delay = (t_start + i / offered_rps) - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
            sub_t.append(time.perf_counter())
            if fe is not None:
                fut = fe.submit(prompt, slo_class=cls,
                                max_new_tokens=gen_tokens, eos_token=-1,
                                deadline_s=deadline)
            else:
                fut = eng.submit(prompt, max_new_tokens=gen_tokens,
                                 eos_token=-1, deadline_s=deadline,
                                 slo_class=cls)
            fut.add_done_callback(_mark(i))
            futs.append(fut)
        results = [f.result(timeout=result_timeout_s) for f in futs]
        burst_results = []
        if fe is not None:
            burst_results = [f.result(timeout=result_timeout_s)
                             for f in fe.burst_futures]
        # result() can return before Future's done-callbacks run (they
        # fire after waiters wake) — wait for every _mark so no request
        # is scored deadline-missed for a timestamp that hadn't landed
        wait_until = time.perf_counter() + 5.0
        while len(done_t) < len(futs) and time.perf_counter() < wait_until:
            time.sleep(0.001)
        t_end = max(done_t.values()) if done_t else time.perf_counter()
    finally:
        eng.stop()

    wall = max(1e-9, t_end - t_start)
    good_tokens = 0
    reasons: Dict[str, int] = {}
    degraded = 0
    ttft_by_class: Dict[str, list] = {}
    met_by_class: Dict[str, int] = {}
    for i, res in enumerate(results):
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
        degraded += int(res.degraded)
        cls, _prompt, deadline = plan[i]
        if res.ttft_s is not None:
            ttft_by_class.setdefault(cls, []).append(res.ttft_s)
        if (res.finish_reason in ("eos", "length")
                and done_t.get(i, float("inf")) - sub_t[i] <= deadline):
            good_tokens += len(res.tokens)
            met_by_class[cls] = met_by_class.get(cls, 0) + 1
    for res in burst_results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1

    all_terminal = (all(f.done() for f in futs)
                    and (fe is None
                         or all(f.done() for f in fe.burst_futures)))
    out = {
        "frontend_on": frontend_on,
        "requests": n_requests,
        "burst_requests": 0 if fe is None else len(fe.burst_futures),
        "offered_rps": round(offered_rps, 3),
        "capacity_tokens_per_sec": round(capacity_tokens_per_sec, 2),
        "base_deadline_s": round(base_deadline, 3),
        "goodput_tokens_per_sec": round(good_tokens / wall, 3),
        "good_tokens": int(good_tokens),
        "deadline_met": dict(sorted(met_by_class.items())),
        "reasons": dict(sorted(reasons.items())),
        "degraded_results": degraded,
        "all_terminal": bool(all_terminal),
        "wall_s": round(wall, 3),
        "new_shape_events": max(
            0, _serving_new_shape_count() - new_shape_before),
    }
    if frontend_on and fe is not None:
        out["states_visited"] = sorted(fe.states_visited)
        out["frontend"] = fe.snapshot()
    itx = ttft_by_class.get("interactive")
    if itx:
        itx = sorted(itx)
        out["interactive_ttft_p99_ms"] = round(
            itx[min(len(itx) - 1, int(0.99 * len(itx)))] * 1e3, 3)
    return out
