"""Radix prefix cache — shared-prompt KV reuse across requests.

ROADMAP item 2(a): "millions of users" traffic is dominated by shared
system prompts and few-shot prefixes, yet the engine re-prefilled every
request from token zero. SGLang's RadixAttention named the fix: a radix
tree over token sequences whose nodes map to KV **pages** (the
PagedAttention indirection ``serving/cache.py`` already has), refcounted
so one physical page serves every request that shares the prefix.

Layout (docs/SERVING.md § Radix prefix cache):

* Interior structure is a **per-page trie**: each node holds exactly ONE
  page and is keyed by that page's ``page_size`` token ids, so a cached
  prefix of ``n`` tokens is a path of ``n // page_size`` full-page nodes.
* A node may additionally hold **partial children** — leaf nodes keyed by
  1..page_size-1 tokens whose page is only partially valid (the tail a
  donor prompt ended in). Sharing a partial page with a slot that will
  write into it is forbidden; the engine **copies it first**
  (:meth:`PagedKVCache.cow_page` — the copy-on-write rule). A FULL node
  can also serve as a CoW tail when a new prompt diverges mid-page: the
  match counts the common tokens and the engine CoWs the page.
* Every node's page carries one tree reference in the cache's refcounts
  (``retain`` at insert, ``release`` at evict/clear), so a page shared by
  the tree and N slots returns to the free list only when the last holder
  lets go.

Policy:

* **Insert / LRU-refresh** happens when a sequence retires complete
  (``eos``/``length``): the engine hands the pages covering its PROMPT to
  :meth:`insert`. Existing nodes are refreshed (and deduplicate — the
  slot's duplicate page is simply released with the slot), new nodes
  retain the slot's pages.
* **Eviction** walks unpinned LEAVES, least-recently-used first, under a
  configurable page budget (``max_pages``) — and on demand
  (:meth:`evict_to_free`) when admission needs pages the free list cannot
  supply. Pinned nodes (pre-warmed per-class system prompts — the
  ``ClassPolicy.shared_prefix`` knob) are never evicted.
* **Clear** (supervisor crash recovery): ``reset_kv`` zeroes the device
  pages, so every cached prefix is garbage — the tree drops wholesale and
  rebuilds from live traffic. Pin INTENTS survive a clear: the next
  insert covering a pinned token sequence re-pins it automatically.

Thread model: the engine's scheduler loop is the only writer
(match/insert/evict/clear); :meth:`pin` may arrive from a frontend
thread. One lock guards all of it — operations are O(prompt) dict walks,
never device work.

Observability: ``dl4j_tpu_prefix_{lookups,hits,hit_tokens,inserted_pages,
evicted_pages,cow_copies}_total`` counters and
``dl4j_tpu_prefix_{tree_pages,pinned_pages}`` gauges
(docs/OBSERVABILITY.md); ``prefix_evict``/``prefix_clear`` JSONL events.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.serving.cache import PagedKVCache


@dataclasses.dataclass
class PrefixMatch:
    """Longest cached prefix for a prompt: ``matched`` tokens covered by
    ``pages`` (``matched // page_size`` full pages, plus — when
    ``matched % page_size != 0`` — one tail page the engine must CoW
    before the slot writes into it)."""

    matched: int
    pages: List[int]


class _Node:
    __slots__ = ("tokens", "page", "parent", "children", "partials",
                 "last_used", "pinned", "partial")

    def __init__(self, tokens: Tuple[int, ...], page: int,
                 parent: Optional["_Node"], partial: bool):
        self.tokens = tokens
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], "_Node"] = {}
        self.partials: Dict[Tuple[int, ...], "_Node"] = {}
        self.last_used = 0
        self.pinned = False
        self.partial = partial


def _common_prefix_len(a: Tuple[int, ...], b: Tuple[int, ...]) -> int:
    n = 0
    for x, y in zip(a, b):
        if x != y:
            break
        n += 1
    return n


class RadixPrefixCache:
    """Refcounted radix/trie over token sequences -> KV page runs, layered
    on one :class:`PagedKVCache` (module docstring has the full design)."""

    def __init__(self, cache: PagedKVCache, *, max_pages: int,
                 min_match: Optional[int] = None):
        if max_pages <= 0:
            raise ValueError("max_pages must be positive (0 pages would "
                             "make every insert evict itself — construct "
                             "no prefix cache instead)")
        self.cache = cache
        self.page_size = cache.page_size
        self.max_pages = int(max_pages)
        # a hit below one full page saves almost nothing and costs a CoW
        self.min_match = int(min_match) if min_match else cache.page_size
        self._root = _Node((), -1, None, partial=False)
        self._n_nodes = 0
        self._n_pinned = 0
        self._ticks = 0
        self._pin_intents: set = set()
        self._lock = threading.Lock()
        m = observe.metrics()
        self._c_lookups = m.counter("dl4j_tpu_prefix_lookups_total")
        self._c_hits = m.counter("dl4j_tpu_prefix_hits_total")
        self._c_hit_tokens = m.counter("dl4j_tpu_prefix_hit_tokens_total")
        self._c_inserted = m.counter("dl4j_tpu_prefix_inserted_pages_total")
        self._c_evicted = m.counter("dl4j_tpu_prefix_evicted_pages_total")
        self._c_cow = m.counter("dl4j_tpu_prefix_cow_copies_total")
        self._g_pages = m.gauge("dl4j_tpu_prefix_tree_pages")
        self._g_pinned = m.gauge("dl4j_tpu_prefix_pinned_pages")
        self._g_pages.set(0.0)
        self._g_pinned.set(0.0)

    # -------------------------------------------------------------- internals
    def _tick(self) -> int:
        self._ticks += 1
        return self._ticks

    def _update_gauges(self) -> None:
        self._g_pages.set(float(self._n_nodes))
        self._g_pinned.set(float(self._n_pinned))

    def _all_nodes(self) -> List[_Node]:
        out, stack = [], [self._root]
        while stack:
            n = stack.pop()
            kids = list(n.children.values()) + list(n.partials.values())
            out.extend(kids)
            stack.extend(kids)
        return out

    # ----------------------------------------------------------------- match
    def match(self, prompt,
              max_suffix: Optional[int] = None) -> Optional[PrefixMatch]:
        """Longest cached prefix of ``prompt``, capped at ``len - 1``
        tokens (at least one suffix token always re-prefills, so the
        first-token logits are always computed fresh). Returns None on a
        miss, a match below ``min_match``, or — with ``max_suffix`` (the
        engine's compiled suffix bucket) — a match whose uncached tail
        could not be suffix-prefilled anyway. LRU stamps refresh ONLY on
        a usable match: a path that can never serve hits must not stay
        artificially hot and crowd serving entries out of the budget.
        Counting (lookups/hits) is the ENGINE's job — a match is only a
        hit once admission actually lands."""
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        p = self.page_size
        with self._lock:
            node, path, pages, i = self._root, [], [], 0
            while len(toks) - i >= p:
                child = node.children.get(toks[i:i + p])
                if child is None:
                    break
                path.append(child)
                pages.append(child.page)
                node, i = child, i + p
            matched = i
            # divergence tail: the best partially-matching page at this
            # node — a stored partial tail, or a full child the prompt
            # diverges from mid-page. The engine CoWs it before writing.
            rem = toks[i:]
            best, best_common = None, 0
            for cand in list(node.partials.values()) + \
                    list(node.children.values()):
                common = _common_prefix_len(cand.tokens, rem)
                if common > best_common:
                    best_common, best = common, cand
            if best is not None:
                path.append(best)
                pages.append(best.page)
                matched += best_common
            if matched >= len(toks):  # always leave >= 1 suffix token
                matched = len(toks) - 1
            pages = pages[:-(-matched // p)] if matched else []
            path = path[:len(pages)]  # a trimmed-out tail page serves no
            #                           hit — don't keep its node hot
            if matched < self.min_match:
                return None
            if max_suffix is not None and len(toks) - matched > max_suffix:
                # unusable: a shorter match only grows the suffix, so no
                # usable match exists for this prompt
                return None
            tick = self._tick()
            for n in path:
                n.last_used = tick
            return PrefixMatch(matched=matched, pages=pages)

    # ---------------------------------------------------------------- insert
    def insert(self, prompt, pages: List[int]) -> int:
        """Record a completed prompt's prefix: walk/create nodes for its
        full pages and (if it ends mid-page) one partial tail. ``pages``
        is the slot's page run covering the prompt, position-ordered; the
        tree RETAINS the pages it keeps (the caller's ``free_slot``
        release then leaves them alive), existing nodes deduplicate (the
        slot's copy is simply released with the slot). Returns the number
        of pages newly retained; enforces the page budget by LRU-evicting
        unpinned leaves afterwards."""
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        p = self.page_size
        with self._lock:
            tick = self._tick()
            node, i, pi, inserted = self._root, 0, 0, 0
            while len(toks) - i >= p:
                key = toks[i:i + p]
                child = node.children.get(key)
                if child is None:
                    child = _Node(key, pages[pi], node, partial=False)
                    self.cache.retain(pages[pi])
                    node.children[key] = child
                    self._n_nodes += 1
                    inserted += 1
                child.last_used = tick
                node, i, pi = child, i + p, pi + 1
            rem = toks[i:]
            if rem:
                tail = node.partials.get(rem)
                if tail is None:
                    tail = _Node(rem, pages[pi], node, partial=True)
                    self.cache.retain(pages[pi])
                    node.partials[rem] = tail
                    self._n_nodes += 1
                    inserted += 1
                tail.last_used = tick
            if inserted:
                self._c_inserted.inc(inserted)
            for intent in self._pin_intents:
                if toks[:len(intent)] == intent:
                    self._pin_locked(intent)
            self._evict_over_budget_locked()
            self._update_gauges()
            return inserted

    def note_hit(self, match: PrefixMatch) -> None:
        """Count a match that actually admitted (engine calls this once
        the slot's pages are mapped and the suffix prefill is committed)."""
        self._c_hits.inc()
        self._c_hit_tokens.inc(match.matched)

    def note_lookup(self) -> None:
        self._c_lookups.inc()

    def note_cow(self) -> None:
        self._c_cow.inc()

    # -------------------------------------------------------------- eviction
    def _evictable_leaves(self) -> List[_Node]:
        return [n for n in self._all_nodes()
                if not n.children and not n.partials and not n.pinned]

    def _remove_leaf_locked(self, victim: _Node) -> None:
        parent = victim.parent
        if victim.partial:
            del parent.partials[victim.tokens]
        else:
            del parent.children[victim.tokens]
        self.cache.release(victim.page)
        self._n_nodes -= 1
        self._c_evicted.inc()

    def _evict_one_locked(self) -> bool:
        leaves = self._evictable_leaves()
        if not leaves:
            return False
        self._remove_leaf_locked(min(leaves, key=lambda n: n.last_used))
        return True

    def _evict_over_budget_locked(self) -> int:
        evicted = 0
        while self._n_nodes > self.max_pages:
            if not self._evict_one_locked():
                break  # everything left is pinned (or an ancestor of one)
            evicted += 1
        if evicted:
            observe.log_event("prefix_evict", pages=evicted,
                              cause="budget", tree_pages=self._n_nodes)
        return evicted

    def _tree_page_refs_locked(self) -> Dict[int, int]:
        refs: Dict[int, int] = {}
        for n in self._all_nodes():
            refs[n.page] = refs.get(n.page, 0) + 1
        return refs

    def evict_to_free(self, n_pages: int) -> int:
        """Pool-pressure reclaim: evict unpinned LRU leaves until
        ``n_pages`` pages actually reached the free list or nothing
        evictable remains. Leaves whose page the tree alone holds are
        preferred (they free NOW); a leaf an active slot still maps is
        evicted only as a fallback — it frees nothing immediately, but
        it releases the tree's reference (the page frees at slot retire)
        and unblocks freeable ancestors behind it. Returns pages freed.
        The per-evict leaf scans are O(tree); the tree is bounded by
        ``max_pages``, so a whole reclaim batch is budget², not
        pool-sized."""
        with self._lock:
            before = self.cache.free_pages
            refs = self._tree_page_refs_locked()
            while self.cache.free_pages - before < n_pages:
                leaves = self._evictable_leaves()
                if not leaves:
                    break
                freeable = [n for n in leaves
                            if self.cache.refcount[n.page] == refs[n.page]]
                victim = min(freeable or leaves,
                             key=lambda n: n.last_used)
                refs[victim.page] -= 1
                if not refs[victim.page]:
                    del refs[victim.page]
                self._remove_leaf_locked(victim)
            freed = self.cache.free_pages - before
            if freed:
                observe.log_event("prefix_evict", pages=freed,
                                  cause="pool_pressure",
                                  tree_pages=self._n_nodes)
            self._update_gauges()
            return freed

    def reclaimable_pages(self, exclude=()) -> int:
        """Pages pool-pressure eviction could ACTUALLY free right now:
        unpinned nodes in fully-unpinned subtrees (a pinned descendant
        keeps every ancestor resident) whose page has no holder besides
        the tree (a slot-shared page would not reach the free list when
        the tree lets go). ``exclude`` removes pages the caller is about
        to USE — a matched prefix's own pages are supply being consumed,
        not supply eviction can produce; counting them would admit a
        request whose reclaim then frees nothing (and wipes the match as
        collateral) instead of waiting out the pool pressure."""
        excl = set(exclude)
        with self._lock:
            refs = self._tree_page_refs_locked()

            def walk(n: _Node) -> Tuple[int, bool]:
                cnt, fully = 0, True
                for c in list(n.children.values()) + list(n.partials.values()):
                    c_cnt, c_fully = walk(c)
                    cnt += c_cnt
                    fully = fully and c_fully
                if n is self._root:
                    return cnt, fully
                if fully and not n.pinned:
                    freeable = (n.page not in excl
                                and self.cache.refcount[n.page]
                                == refs[n.page])
                    return cnt + (1 if freeable else 0), True
                return cnt, False

            return walk(self._root)[0]

    # ---------------------------------------------------------------- pinning
    def pin(self, prompt, record: bool = True) -> int:
        """Pin the cached path covering ``prompt`` (pre-warmed per-class
        system prompts — never evicted). With ``record``, the intent
        survives :meth:`clear`: the next insert covering these tokens
        re-pins automatically. Returns the number of nodes pinned."""
        toks = tuple(int(t) for t in np.asarray(prompt).reshape(-1))
        with self._lock:
            if record:
                self._pin_intents.add(toks)
            n = self._pin_locked(toks)
            self._update_gauges()
            return n

    def _pin_locked(self, toks: Tuple[int, ...]) -> int:
        p, node, i, pinned = self.page_size, self._root, 0, 0
        while len(toks) - i >= p:
            child = node.children.get(toks[i:i + p])
            if child is None:
                return pinned
            if not child.pinned:
                child.pinned = True
                self._n_pinned += 1
                pinned += 1
            node, i = child, i + p
        rem = toks[i:]
        if rem:
            # the intent's mid-page remainder: pin the exact tail when
            # present, else ONE node COVERING rem (its tokens extend it —
            # after a clear() the tree rebuilds from traffic, whose
            # divergence tails embed the system prompt's remainder but
            # never equal it). One covering pin suffices to keep the
            # mid-page KV resident and matchable; pinning every covering
            # tail would grow pins without bound.
            cands = [t for key, t in list(node.partials.items())
                     + list(node.children.items())
                     if key[:len(rem)] == rem]
            exact = node.partials.get(rem)
            if exact is not None:
                cands = [exact] + cands
            if cands and not any(t.pinned for t in cands):
                cands[0].pinned = True
                self._n_pinned += 1
                pinned += 1
        return pinned

    # ------------------------------------------------------------------ clear
    def clear(self) -> int:
        """Drop the whole tree, releasing every tree reference (supervisor
        crash recovery: ``reset_kv`` zeroed the device pages, so every
        cached prefix is garbage). Pin INTENTS survive — re-inserted
        pinned prefixes re-pin. Returns pages released."""
        with self._lock:
            nodes = self._all_nodes()
            for n in nodes:
                self.cache.release(n.page)
            self._root.children.clear()
            self._root.partials.clear()
            released = len(nodes)
            self._n_nodes = 0
            self._n_pinned = 0
            self._update_gauges()
            if released:
                observe.log_event("prefix_clear", pages=released)
            return released

    # ------------------------------------------------------------ inspection
    def page_refs(self) -> Dict[int, int]:
        """Per-page tree reference counts (for
        :meth:`PagedKVCache.check_invariants` exact accounting)."""
        with self._lock:
            return self._tree_page_refs_locked()

    @property
    def tree_pages(self) -> int:
        return self._n_nodes

    @property
    def pinned_pages(self) -> int:
        return self._n_pinned

    def check_invariants(self) -> None:
        """Tree soundness (test hook): node/page accounting agrees, every
        tree page is live in the cache (never on the free list), keys
        match node tokens, partial tails are real partials."""
        with self._lock:
            nodes = self._all_nodes()
            assert len(nodes) == self._n_nodes, (
                f"node count drifted: counted {len(nodes)} "
                f"tracked {self._n_nodes}")
            assert sum(1 for n in nodes if n.pinned) == self._n_pinned
            free = set(self.cache.free)
            for n in nodes:
                assert self.cache.refcount[n.page] >= 1, (
                    f"tree node {n.tokens} holds dead page {n.page}")
                assert n.page not in free, (
                    f"tree node {n.tokens} holds FREE page {n.page}")
                if n.partial:
                    assert 0 < len(n.tokens) < self.page_size
                    assert not n.children and not n.partials, (
                        "partial tails must be leaves")
                else:
                    assert len(n.tokens) == self.page_size
                for key, c in list(n.children.items()) + \
                        list(n.partials.items()):
                    assert key == c.tokens and c.parent is n
