"""Generative serving engine — prefill/decode dispatch over the paged cache.

The device half of the serving subsystem (docs/SERVING.md): three jitted
functions whose signatures depend ONLY on server-start configuration
(slot capacity, page geometry, prompt bucket) — never on the number of
active sequences — so the RecompileLedger records exactly one
``first_compile`` per function and NO ``new_shape`` events across
admits/evicts (asserted in tests/test_serving.py):

* **prefill** — the whole (padded) prompt through one causal
  ``gpt_prefill`` pass + first-token sampling; returns the per-layer K/V
  for the cache scatter. TTFT is measured across this call.
* **write-prompt** — scatter the prefill K/V into the slot's pages
  (donated cache array; unused prompt-pad positions land on the trash
  page).
* **decode** — one token for EVERY slot (inactive slots ride along masked:
  they write to the trash page and their outputs are ignored), paged
  attention via the registry's ``paged_decode_attention``, then the
  vectorized temperature/top-k/top-p sampler with per-slot keys split from
  this step's fresh key.

With ``prefix_pages > 0`` a fourth compiled function joins them —
**suffix-prefill**: on a radix-prefix-cache hit (``serving/prefix.py``)
the shared pages are mapped into the slot's page-table row by reference
and only the prompt's uncached tail (padded to the static
``suffix_bucket``) is prefilled against the cached prefix K/V, so shared
system prompts admit in O(suffix) instead of O(prompt). All four
signatures stay config-only — prefix hits never recompile.

With ``spec_k > 0`` (plus a ``draft_model``) a fifth joins —
**verify** (``models.gpt.gpt_verify``): speculative decoding
(docs/SERVING.md § Speculative decoding, ``serving/speculative.py``).
Each step, greedy slots run K draft-model decode steps (one compiled
``draft_decode`` scan over a dense per-slot draft cache) to propose K
tokens, then ONE target forward over the ``K+1``-token window scores
every proposal; the accepted prefix plus the target's correction/bonus
token commits — 1..K+1 tokens per step per slot, bit-identical to
non-speculative greedy decoding (scoped to verify/decode argmax
agreement across kernels — docs/SERVING.md § Speculative decoding,
"On-device caveat"). A rejection REWINDS the slot's cached length (and
the draft's) instead of freeing pages, so rollback is O(1) and
refcount-safe. Slots with ``temperature > 0`` (or
``spec_disabled`` requests) fall back to the plain decode step. Verify's
shape depends only on ``(max_slots, spec_k, page geometry)`` — the
ledger stays at one ``first_compile`` per function, zero ``new_shape``.

Observability (docs/OBSERVABILITY.md catalog additions): admitted/evicted/
generated-token counters, slot-occupancy gauge, decode-step latency
histogram, TTFT + inter-token histograms, ``serving_prefill``/
``serving_decode`` spans, and ledger notes on both compiled functions.

**Supervision** (docs/ROBUSTNESS.md): a decode-step exception or worker
death no longer kills the engine. The supervisor frees every slot,
re-queues requests with retry budget left (front of the queue, original
submit time), completes the rest terminally as ``error``, reallocates the
possibly-donated KV buffer (same shape — the cached jit functions survive,
so recovery shows ZERO ``new_shape`` ledger events), and restarts the
worker under capped exponential backoff up to ``max_restarts``. Per-request
deadlines retire overdue work as ``deadline`` whether queued or mid-decode,
and a bounded pending queue (``max_queue``) sheds over-capacity
submissions immediately as ``shed`` — every submitted request reaches a
terminal finish reason, which is the property the ``chaos`` gate stage
asserts under an injected fault schedule (deeplearning4j_tpu/faults/).
"""

from __future__ import annotations

import functools
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.models.gpt import (
    GptModel, gpt_decode_step, gpt_prefill, gpt_prefill_suffix, gpt_verify)
from deeplearning4j_tpu.serving.cache import PagedKVCache
from deeplearning4j_tpu.serving.prefix import PrefixMatch, RadixPrefixCache
from deeplearning4j_tpu.serving.speculative import SpeculativeDecoder
from deeplearning4j_tpu.serving.sampling import sample_tokens
from deeplearning4j_tpu.serving.scheduler import (
    GenerationRequest, GenerationResult, SlotScheduler, count_terminal)

logger = logging.getLogger(__name__)


class GenerativeEngine:
    """Continuous-batching text generation over a ``GptModel``.

    Synchronous use (tests, batch jobs)::

        eng = GenerativeEngine(model, max_slots=4)
        results = eng.generate([prompt1, prompt2], max_new_tokens=32)

    Serving use (the ``ParallelInference`` shape)::

        eng.start()
        fut = eng.submit(prompt, temperature=0.8, top_p=0.95)
        result = fut.result()
        eng.stop()
    """

    def __init__(self, model: GptModel, *, max_slots: int = 4,
                 page_size: int = 16, num_pages: Optional[int] = None,
                 max_pages_per_seq: int = 8, max_prompt: int = 32,
                 seed: int = 0, supervise: bool = True,
                 max_restarts: int = 3, restart_backoff_s: float = 0.05,
                 max_backoff_s: float = 2.0, max_queue: Optional[int] = None,
                 default_deadline_s: Optional[float] = None,
                 prefix_pages: int = 0,
                 suffix_bucket: Optional[int] = None,
                 prefix_min_match: Optional[int] = None,
                 spec_k: int = 0,
                 draft_model: Optional[GptModel] = None,
                 engine_id: int = 0):
        cfg = model.cfg
        if cfg.hidden % cfg.heads:
            raise ValueError("hidden must be divisible by heads")
        if max_prompt > cfg.max_position:
            # gpt_prefill's position gather would silently CLAMP indices
            # past max_position — reject the misconfiguration instead
            raise ValueError(
                f"max_prompt={max_prompt} exceeds the model's "
                f"max_position={cfg.max_position}")
        self.model = model
        self.cfg = cfg
        self.max_prompt = int(max_prompt)
        if num_pages is None:
            # full reservation by default; oversubscribe explicitly to make
            # the free-list pressure (oom evictions) reachable. A prefix
            # cache gets its page budget ON TOP so the tree never starves
            # the slot bank by default.
            num_pages = max_slots * max_pages_per_seq + max(0, prefix_pages)
        self.cache = PagedKVCache(
            layers=cfg.layers, heads=cfg.heads,
            head_dim=cfg.hidden // cfg.heads, page_size=page_size,
            num_pages=num_pages, max_slots=max_slots,
            max_pages_per_seq=max_pages_per_seq,
            dtype=jax.tree.leaves(model.params)[0].dtype)
        if self.max_prompt + 1 > self.cache.max_context():
            raise ValueError(
                f"max_prompt={max_prompt} + 1 exceeds per-slot context "
                f"{self.cache.max_context()} "
                f"(page_size*max_pages_per_seq)")
        self.scheduler = SlotScheduler(max_slots)
        # ---------------------------------------- radix prefix cache (2a)
        # prefix_pages > 0 enables shared-prompt KV reuse: a radix tree
        # over token sequences whose nodes hold refcounted cache pages
        # (docs/SERVING.md § Radix prefix cache). suffix_bucket is the
        # compiled suffix-prefill width — a hit whose uncached tail
        # exceeds it falls back to the full prefill (static shapes keep
        # the compile-once property: zero new_shape, test-asserted).
        self.prefix: Optional[RadixPrefixCache] = None
        self.suffix_bucket = min(self.max_prompt,
                                 int(suffix_bucket) if suffix_bucket
                                 else 2 * self.cache.page_size)
        if prefix_pages:
            self.prefix = RadixPrefixCache(
                self.cache, max_pages=int(prefix_pages),
                min_match=prefix_min_match)
        # ------------------------------------------ speculative decoding (2b)
        # spec_k > 0 (plus a draft model sharing the target's vocab) turns
        # greedy slots speculative: K draft proposals per step, one target
        # verify pass, 1..K+1 committed tokens (docs/SERVING.md
        # § Speculative decoding). Off by default — spec_k=0 is the plain
        # one-token decode loop, byte-for-byte.
        self.spec: Optional[SpeculativeDecoder] = None
        self._spec_slots: set = set()
        self._spec_limit = 0
        if spec_k:
            if draft_model is None:
                raise ValueError("spec_k > 0 requires a draft_model "
                                 "(models.GPT(...).init_draft() builds the "
                                 "paired one)")
            dcfg = draft_model.cfg
            if dcfg.vocab_size != cfg.vocab_size:
                # draft proposals are TARGET token ids — a vocab mismatch
                # would silently verify garbage
                raise ValueError(
                    f"draft vocab_size={dcfg.vocab_size} != target "
                    f"vocab_size={cfg.vocab_size}")
            if dcfg.eos_token != cfg.eos_token:
                # eos rides the request, but a config disagreement is a
                # mispairing worth failing fast on (draft_config_for's
                # contract: vocab/eos/positions agree)
                raise ValueError(
                    f"draft eos_token={dcfg.eos_token} != target "
                    f"eos_token={cfg.eos_token}")
            self.spec = SpeculativeDecoder(
                draft_model, k=int(spec_k), max_slots=max_slots,
                max_ctx=self.cache.max_context(),
                max_prompt=self.max_prompt)
            self._spec_limit = min(cfg.max_position, dcfg.max_position)
        self._key = jax.random.key(seed)
        # key-hygiene audit trail: raw key data of every key handed to a
        # jitted sampler, bounded; tests assert no value ever repeats
        self.key_trail: "deque[bytes]" = deque(maxlen=4096)
        self._prefill_fn = None
        self._write_fn = None
        self._decode_fn = None
        self._suffix_fn = None
        self._verify_fn = None
        # per-slot prefix match staged between _admit_pages and
        # _prefill_into — set (or cleared) on EVERY admission, so a crash
        # between the two can never leak a stale match into the slot's
        # next tenant. Kept out of _prefill_into's signature: the
        # robustness tests wrap that method with (slot, req) shims.
        self._slot_match: dict = {}
        self._worker: Optional[threading.Thread] = None
        self._stop_flag = False
        self._error: Optional[Exception] = None
        # ------------------------------------------ supervisor configuration
        self.supervise = bool(supervise)
        self.max_restarts = int(max_restarts)
        self.restart_backoff_s = float(restart_backoff_s)
        self.max_backoff_s = float(max_backoff_s)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.default_deadline_s = default_deadline_s
        self.restarts = 0            # lifetime crash recoveries (<= cap)
        self.stopped_cleanly = True  # last stop() joined its worker in time
        # ------------------------------------------------- cluster membership
        # engine_id names this engine inside a ClusterRouter
        # (serving/cluster.py); on_unrecoverable, when set, is called ONCE
        # from the dying worker thread after the restart budget is spent —
        # the router's hook drains this scheduler and migrates retryable
        # requests to a surviving engine BEFORE fail_all retires the rest.
        self.engine_id = int(engine_id)
        self.on_unrecoverable: Optional[Callable[[Exception], None]] = None
        self._lifecycle = threading.Lock()  # guards _worker hand-off
        m = observe.metrics()
        self._obs = {
            "admitted": m.counter("dl4j_tpu_serving_admitted_total"),
            "generated": m.counter("dl4j_tpu_serving_generated_tokens_total"),
            "occupancy": m.gauge("dl4j_tpu_serving_slot_occupancy"),
            "decode_h": m.histogram("dl4j_tpu_serving_decode_step_seconds"),
            "ttft_h": m.histogram("dl4j_tpu_serving_ttft_seconds"),
            "itl_h": m.histogram("dl4j_tpu_serving_intertoken_seconds"),
            "restarts": m.counter("dl4j_tpu_serving_engine_restarts_total"),
            "retries": m.counter("dl4j_tpu_serving_retries_total"),
            # written ONLY by stop(): the gauge is process-global, and a
            # constructor write here would clobber a previous engine's
            # hung-stop indication while that engine is still wedged
            "stopped_g": m.gauge("dl4j_tpu_serving_stopped_cleanly"),
        }
        # AOT warm boot (serving/aot.py): with $DL4J_TPU_COMPILE_CACHE
        # set, every compiled-fn slot fills from the persistent export
        # cache BEFORE the first request — or, on a cache miss, compiles
        # now and persists for the next process. Inert without the env.
        from deeplearning4j_tpu.serving import aot as _aot

        _aot.maybe_warm_boot(self)

    # ------------------------------------------------------------------ keys
    def _next_key(self):
        """Split a fresh subkey off the root key — the ONLY way keys leave
        the engine, so the audit trail sees every one exactly once."""
        self._key, sub = jax.random.split(self._key)
        self.key_trail.append(np.asarray(jax.random.key_data(sub)).tobytes())
        return sub

    # ---------------------------------------------------------- compiled fns
    def _build_prefill(self):
        cfg = self.cfg

        @jax.jit
        def prefill(params, ids, prompt_len, key, temp, top_k, top_p):
            mask = (jnp.arange(ids.shape[1]) < prompt_len)[None, :]
            logits, kv = gpt_prefill(params, ids, cfg,
                                     mask=mask.astype(jnp.int32))
            last = logits[0, prompt_len - 1][None]  # (1, V)
            tok = sample_tokens(last, key, temp, top_k, top_p)[0]
            return kv[:, :, 0], tok  # (L, 2, T, H, Dh), scalar

        return prefill

    def _build_write(self):
        cache = self.cache
        page, trash = cache.page_size, cache.trash_page

        @functools.partial(jax.jit, donate_argnums=(0,))
        def write_prompt(kv_pages, kv_prompt, pt_row, prompt_len):
            pos = jnp.arange(kv_prompt.shape[2])
            valid = pos < prompt_len
            page_idx = jnp.where(valid, pt_row[pos // page], trash)
            off = pos % page
            return kv_pages.at[:, :, page_idx, off].set(kv_prompt)

        return write_prompt

    def _build_suffix(self):
        """Suffix-only prefill for prefix-cache hits: gather the cached
        prefix K/V out of the slot's pages, run the (bucketed) suffix
        through :func:`gpt_prefill_suffix`, sample the first token from
        the last suffix position, and scatter the suffix K/V back into
        the pages. Shapes depend only on server config (max_prompt,
        suffix_bucket, page geometry) — ONE first_compile, zero
        new_shape, same as the other three."""
        cfg, cache = self.cfg, self.cache
        page, trash = cache.page_size, cache.trash_page
        t_pre = self.max_prompt

        @functools.partial(jax.jit, donate_argnums=(1,))
        def suffix_prefill(params, kv_pages, ids, prefix_len, suffix_len,
                           pt_row, key, temp, top_k, top_p):
            pos = jnp.arange(t_pre)
            prefix_kv = kv_pages[:, :, pt_row[pos // page], pos % page]
            logits, kv_suf = gpt_prefill_suffix(
                params, ids, prefix_kv, prefix_len, suffix_len, cfg)
            last = logits[0, suffix_len - 1][None]  # (1, V)
            tok = sample_tokens(last, key, temp, top_k, top_p)[0]
            b = ids.shape[1]
            apos = prefix_len + jnp.arange(b)
            valid = jnp.arange(b) < suffix_len
            row_idx = jnp.clip(apos // page, 0, pt_row.shape[0] - 1)
            wpage = jnp.where(valid, pt_row[row_idx], trash)
            kv_pages = kv_pages.at[:, :, wpage, apos % page].set(kv_suf)
            return kv_pages, tok

        return suffix_prefill

    def _build_decode(self):
        cfg, cache = self.cfg, self.cache
        page, trash = cache.page_size, cache.trash_page

        @functools.partial(jax.jit, donate_argnums=(1,))
        def decode(params, kv_pages, page_table, seq_lens, tokens, active,
                   key, temp, top_k, top_p):
            s_n = tokens.shape[0]
            on = active > 0
            write_page = jnp.where(
                on, page_table[jnp.arange(s_n), seq_lens // page], trash)
            write_off = seq_lens % page
            seq_incl = seq_lens + on.astype(jnp.int32)
            kv_pages, logits = gpt_decode_step(
                params, kv_pages, tokens, seq_lens, page_table, seq_incl,
                write_page, write_off, cfg)
            toks = sample_tokens(logits, key, temp, top_k, top_p)
            return kv_pages, toks, logits

        return decode

    def _build_verify(self):
        """Speculative verification (docs/SERVING.md § Speculative
        decoding): ONE target forward over each slot's ``spec_k + 1``
        fed tokens (last committed + K draft proposals) against the paged
        cache, returning the target's greedy argmax at every fed
        position. Inactive/non-speculating slots ride along masked —
        their writes land on the trash page, their outputs are ignored.
        Shapes depend only on (max_slots, spec_k, page geometry): ONE
        first_compile, zero new_shape, same as the other four."""
        cfg, cache = self.cfg, self.cache
        page, trash = cache.page_size, cache.trash_page

        @functools.partial(jax.jit, donate_argnums=(1,))
        def verify(params, kv_pages, tokens, seq_lens, page_table, active):
            s_n, b = tokens.shape
            on = active > 0
            pos = seq_lens[:, None] + jnp.arange(b)[None, :]
            row = jnp.clip(pos // page, 0, page_table.shape[1] - 1)
            wpage = jnp.where(
                on[:, None],
                page_table[jnp.arange(s_n)[:, None], row], trash)
            return gpt_verify(params, kv_pages, tokens, seq_lens,
                              page_table, wpage, pos % page, cfg,
                              page_size=page)

        return verify

    # ------------------------------------------------------------------- api
    def submit(self, prompt, *, max_new_tokens: int = 16,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token: Optional[int] = None,
               deadline_s: Optional[float] = None, max_retries: int = 1,
               priority: int = 1, slo_class: str = "standard"
               ) -> "Future[GenerationResult]":
        """Queue one generation; returns a Future (thread-safe). A stopped
        engine rejects new work — build a fresh one.

        ``deadline_s`` bounds submit->terminal wall time (engine default
        when None); ``max_retries`` is this request's crash re-admission
        budget (docs/ROBUSTNESS.md). ``priority`` orders the pending queue
        (lower admits first; ties FIFO) and ``slo_class`` labels the
        request for the SLO frontend's metrics — plain callers can ignore
        both. When the pending queue is at ``max_queue``, the request is
        SHED: the future completes immediately with the terminal reason
        ``"shed"`` — callers always get a terminal state, never a hang."""
        eos = self.cfg.eos_token if eos_token is None else eos_token
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        req = GenerationRequest(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, eos_token=eos,
            deadline_s=deadline_s, max_retries=max_retries,
            priority=priority, slo_class=slo_class)
        return self.submit_request(req)

    def validate_request(self, req: GenerationRequest) -> None:
        """Raise on a request this engine can never serve. Shared by
        :meth:`submit_request` and the SLO frontend, which must validate
        BEFORE displacing queued work to make room for an arrival."""
        if req.prompt.size > self.max_prompt:
            raise ValueError(
                f"prompt length {req.prompt.size} exceeds the engine's "
                f"prefill bucket max_prompt={self.max_prompt}")
        lo, hi = int(req.prompt.min()), int(req.prompt.max())
        if lo < 0 or hi >= self.cfg.vocab_size:
            # the embedding gather would silently clamp/wrap out-of-range
            # ids into plausible-but-wrong generations
            raise ValueError(
                f"prompt token ids must be in [0, {self.cfg.vocab_size}), "
                f"got range [{lo}, {hi}]")

    def submit_request(self, req: GenerationRequest
                       ) -> "Future[GenerationResult]":
        """Queue a pre-built :class:`GenerationRequest` (the SLO frontend's
        entry point — it constructs requests carrying class/priority/
        degradation state). Same contract as :meth:`submit`."""
        if self._error is not None:
            raise RuntimeError("engine loop died") from self._error
        if self._stop_flag:
            raise RuntimeError("engine stopped — submit rejected")
        if req.deadline_s is None:
            req.deadline_s = self.default_deadline_s
        self.validate_request(req)
        if (self.max_queue is not None
                and len(self.scheduler.pending) >= self.max_queue):
            # admission gate: shedding is a TERMINAL result, not an
            # exception — overload is an expected state the SLO frontend
            # steers by, and every caller still gets a definitive answer
            fut: "Future[GenerationResult]" = Future()
            self._finish_unslotted(req, fut, "shed")
            return fut
        fut = self.scheduler.submit(req)
        if self._error is not None:
            # the loop died between the checks above and our enqueue — its
            # fail_all may have drained pending before we appended; fail
            # everything (incl. this future) so result() can never hang
            self.scheduler.fail_all(RuntimeError("engine loop died"))
        elif self._stop_flag:
            # stop() started concurrently and may still be JOINING a live
            # worker: rescue only the queued (never-admitted) futures —
            # touching active slots here would race the worker's step,
            # corrupt page accounting, and burn a restart on a KeyError.
            # stop() itself retires the active slots after the join.
            self.scheduler.fail_pending(RuntimeError("engine stopped"))
        return fut

    def generate(self, prompts: Sequence, **kw) -> List[GenerationResult]:
        """Synchronous batch generation: submit everything, run the
        scheduler loop inline until drained. Crash recovery applies here
        too (same supervisor, no worker thread): a step that dies inside
        the retry budget re-admits and continues; past the budget the
        original exception propagates to the caller."""
        # graftlock: justified(GL012): advisory mode check — start_serving/stop are caller-serialized
        if self._worker is not None:
            raise RuntimeError("generate() is the inline mode — the engine "
                               "is already running a serving loop; use "
                               "submit()")
        futs = [self.submit(p, **kw) for p in prompts]
        while self.scheduler.has_work():
            try:
                self.step()
            except Exception as e:
                if not self._recover(e):
                    self._die(e)
                    raise
        return [f.result() for f in futs]

    def start(self) -> "GenerativeEngine":
        with self._lifecycle:
            if self._worker is not None:
                return self
            self._stop_flag = False
            self._worker = threading.Thread(target=self._serve_loop,
                                            daemon=True)
            self._worker.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        """Stop the serving loop. In-flight sequences retire with their
        partial output and the documented ``"stopped"`` reason; queued
        requests fail. A worker that does not join within ``timeout``
        (a stuck decode step) is detected and reported — logged ONCE,
        ``stopped_cleanly`` False, ``dl4j_tpu_serving_stopped_cleanly``
        gauge 0 — instead of silently abandoning the thread; the engine
        is left not restartable and active-slot futures stay with the
        stuck worker (completing them here would race it)."""
        self._stop_flag = True
        while True:
            with self._lifecycle:
                w = self._worker
            if w is None or w is threading.current_thread():
                break
            w.join(timeout=timeout)
            if w.is_alive():
                # do NOT null _worker: a restart would race the stuck
                # thread over the same cache/scheduler (double page frees,
                # double-donated kv buffer)
                self.stopped_cleanly = False
                self._obs["stopped_g"].set(0.0)
                logger.error(
                    "serving loop still running after %.0fs (a decode step "
                    "is stuck); engine left stopping, not restartable — "
                    "failing queued requests only", timeout)
                observe.log_event("engine_stop_hung", timeout_s=timeout)
                self.scheduler.fail_pending(
                    RuntimeError("GenerativeEngine stop timed out with the "
                                 "worker hung; queued request failed"),
                    reason="stopped")
                return
            with self._lifecycle:
                if self._worker is w:
                    self._worker = None
                    break
                # a crash-recovery respawn won the hand-off before we set
                # the flag — loop again and join the replacement too
        self.stopped_cleanly = True
        self._obs["stopped_g"].set(1.0)
        # in-flight sequences retire with their partial output and the
        # documented "stopped" reason (the worker is joined — no race);
        # queued-but-never-admitted requests fail
        for slot in self.scheduler.active_slots():
            self._retire(slot, "stopped")
        self.scheduler.fail_all(
            RuntimeError("GenerativeEngine stopped before this request "
                         "completed"), reason="stopped")

    def _serve_loop(self) -> None:
        while not self._stop_flag:
            if not self.scheduler.has_work():
                time.sleep(1e-3)
                continue
            try:
                if faults.should_fire("engine_death"):
                    # a HARD whole-engine kill: spend the restart budget
                    # first so _recover cannot resurrect the worker — the
                    # cluster router (serving/cluster.py) owns this
                    # failure domain, not the supervisor
                    self.restarts = self.max_restarts
                    raise faults.InjectedFault("engine_death")
                faults.maybe_fail("worker_death")
                self.step()
            except Exception as e:
                if self._recover(e):
                    # this worker retires; a REPLACEMENT thread owns the
                    # loop from here (observable restart: new thread, new
                    # ident, engine_restarts_total incremented) — unless
                    # stop() raced us, in which case it joins this thread
                    # and finds no work to hand over
                    with self._lifecycle:
                        if self._stop_flag:
                            return
                        self._worker = threading.Thread(
                            target=self._serve_loop, daemon=True)
                        self._worker.start()
                    return
                logger.exception("serving loop died (unrecoverable)")
                self._die(e)
                return

    # ------------------------------------------------------------ supervisor
    def _die(self, exc: Exception) -> None:
        """Unrecoverable escalation: mark the engine dead, give a cluster
        router's ``on_unrecoverable`` hook one shot at migrating this
        scheduler's requests onto a surviving engine (the hook runs on the
        dying worker thread, after the last step — nothing races it), then
        fail whatever the hook left behind. Without a hook this is exactly
        the old fail-everything path."""
        self._error = exc
        observe.log_event("engine_dead", engine=self.engine_id,
                          restarts=self.restarts, error=repr(exc))
        hook = self.on_unrecoverable
        if hook is not None:
            try:
                hook(exc)
            except Exception:
                logger.exception("on_unrecoverable hook failed; failing "
                                 "the remaining requests terminally")
        self.scheduler.fail_all(exc)

    def adopt_requests(self, items: Sequence[tuple]) -> None:
        """Splice migrated ``(request, future, submit_t)`` tuples — a dead
        sibling's in-flight and queued work, handed over by the cluster
        router — onto the FRONT of the pending queue, preserving their
        order. The tuples keep their ORIGINAL futures, submit times and
        priorities: deadlines keep counting across the migration and
        ``peek_best_pending`` ordering never inverts (the PR-10/11
        re-admission discipline, now cluster-wide). Mirrors
        :meth:`submit_request`'s post-enqueue race handling so an adopted
        future can never hang on an engine that died or stopped under us."""
        items = list(items)
        if not items:
            return
        sched = self.scheduler
        with sched._plock:
            # appendleft reverses; iterate reversed so items[0] ends up
            # at the very front (it was the oldest in-flight request)
            for item in reversed(items):
                sched.pending.appendleft(item)
        if self._error is not None:
            sched.fail_all(RuntimeError("engine loop died"))
        elif self._stop_flag:
            sched.fail_pending(RuntimeError("engine stopped"))

    def _finish_unslotted(self, req, fut, reason: str) -> None:
        """Complete a future that never held (or no longer holds) a slot
        with a terminal result: shed at admission, deadline in queue,
        error past the retry budget."""
        if not fut.done():
            fut.set_result(GenerationResult(
                tokens=np.zeros((0,), np.int32), finish_reason=reason,
                prompt_len=int(req.prompt.size), ttft_s=None,
                intertoken_s=[], slo_class=req.slo_class,
                degraded=req.degraded, spec_disabled=req.spec_disabled))
        count_terminal(reason)
        observe.log_event("serving_terminal", reason=reason,
                          slo_class=req.slo_class)

    def _recover(self, exc: Exception) -> bool:
        """Crash recovery (docs/ROBUSTNESS.md state machine): free every
        slot, re-queue requests with retry budget left (front of queue,
        original submit time), fail the rest terminally as ``error``,
        reallocate the possibly-donated KV buffer, and back off
        exponentially (capped). Returns False when unsupervised or the
        restart budget is spent — the caller escalates to fail_all."""
        if not self.supervise or self.restarts >= self.max_restarts:
            return False
        # graftlock: justified(GL012): single-writer — only the (one) worker/inline step thread recovers
        self.restarts += 1
        self._obs["restarts"].inc()
        logger.warning("engine worker died (%r) — restart %d/%d",
                       exc, self.restarts, self.max_restarts)
        sched, cache = self.scheduler, self.cache
        # reversed: appendleft re-queues LAST-iterated first, and slots are
        # assigned lowest-free-first, so reverse slot order restores the
        # requests' original arrival order at the front of the queue
        for slot in reversed(sched.active_slots()):
            st = sched.slots.pop(slot)
            cache.free_slot(slot)
            req = st.request
            if req.retries_used < req.max_retries:
                # retryable: back to the FRONT of the queue with its
                # original submit time (deadline keeps counting across
                # the crash) — generation restarts from the prompt
                req.retries_used += 1
                self._obs["retries"].inc()
                with sched._plock:
                    sched.pending.appendleft((req, st.future, st.submit_t))
            else:
                self._finish_unslotted(req, st.future, "error")
        if self.prefix is not None:
            # reset_kv is about to zero the device pages, so every cached
            # prefix is garbage: drop the tree wholesale (pin intents
            # survive — re-inserted pinned prefixes re-pin) and rebuild
            # from live traffic
            self.prefix.clear()
        if self.spec is not None:
            # the crash may have died mid-donation of the draft KV buffer
            # too; same-shape reallocation keeps the compiled draft fns
            # (zero new_shape across restarts). Retried requests restart
            # from the prompt, so their draft rows re-prefill — recovery
            # stays lossless.
            self._spec_slots.clear()
            self.spec.reset()
        # the crash may have killed a decode step AFTER the donation of
        # cache.kv; same-shape reallocation keeps the cached jit fns (and
        # therefore the ledger's zero-new_shape property) intact
        cache.reset_kv()
        # cold-start restore: an in-process recovery keeps its compiled
        # fns (every slot non-None — no-op), but a recovery driven from a
        # FRESH process with a populated $DL4J_TPU_COMPILE_CACHE refills
        # any empty slot from the export cache instead of re-jitting
        from deeplearning4j_tpu.serving import aot as _aot

        _aot.maybe_warm_boot(self)
        observe.log_event("engine_restart", restart=self.restarts,
                          error=repr(exc))
        delay = min(self.max_backoff_s,
                    self.restart_backoff_s * (2 ** (self.restarts - 1)))
        if delay > 0:
            time.sleep(delay)
        return True

    # ---------------------------------------------------------- prefix cache
    def _match_prefix(self, req: GenerationRequest) -> Optional[PrefixMatch]:
        """Longest usable cached prefix for an arrival: present, at least
        ``min_match`` tokens, and with an uncached tail that fits the
        compiled suffix bucket (otherwise the full prefill is the only
        compile-once path — match() neither returns nor LRU-refreshes
        such entries). Lookup counting happens in _admit_pages, once per
        admission, so pool-pressure retries don't deflate the hit rate."""
        if self.prefix is None:
            return None
        return self.prefix.match(req.prompt, max_suffix=self.suffix_bucket)

    def _admit_pages(self, slot: int, req: GenerationRequest,
                     match: Optional[PrefixMatch]) -> tuple:
        """Build ``slot``'s page run for ``req`` (``prompt + 1`` tokens).
        Without a match this is plain ``ensure_capacity``. With one: map
        the shared full pages (taking references), copy-on-write the
        partially-filled tail page the prompt diverges in, then allocate
        the rest fresh — evicting unpinned tree leaves first when the
        free list cannot cover it. Any failure (including injected
        ``page_oom`` mid-match) unwinds the slot completely and returns a
        terminal status; the caller completes the request. Returns
        ``(status, prefix_hit_tokens)``."""
        cache = self.cache
        p_len = int(req.prompt.size)
        if self.prefix is not None:
            self.prefix.note_lookup()
        if match is None:
            return cache.ensure_capacity(slot, p_len + 1), 0
        full = match.matched // cache.page_size
        tail_len = match.matched % cache.page_size
        for page in match.pages[:full]:
            cache.map_shared(slot, page)
        if faults.should_fire("page_oom"):
            # injected pool pressure MID-MATCH: unwind the shared
            # mappings (references only — the tree keeps its pages) and
            # report the same terminal oom the real arm would
            cache.free_slot(slot)
            return "oom", 0
        guard = None
        try:
            if tail_len:
                # guard the CoW source FIRST: the pool-pressure eviction
                # below may otherwise drop the tree's (only) reference on
                # it before we copy
                guard = match.pages[full]
                cache.retain(guard)
            need_rest = cache.pages_for(p_len + 1) - full
            if need_rest > cache.free_pages:
                self.prefix.evict_to_free(need_rest - cache.free_pages)
            if tail_len:
                if cache.cow_page(slot, guard) is None:
                    cache.free_slot(slot)
                    return "oom", 0
                self.prefix.note_cow()
            status = cache.ensure_capacity(slot, p_len + 1)
        finally:
            if guard is not None:
                cache.release(guard)
        if status != "ok":
            cache.free_slot(slot)
            return status, 0
        self.prefix.note_hit(match)
        return "ok", match.matched

    def prewarm_prefix(self, prompt, *, pin: bool = True):
        """Run ``prompt`` through one 1-token generation so its KV pages
        land in the prefix tree, then (by default) PIN them — pre-warmed
        per-class system prompts are never evicted (the SLO frontend's
        ``ClassPolicy.shared_prefix`` knob calls this). Works on both an
        idle engine (inline) and a running one (through the queue)."""
        if self.prefix is None:
            raise RuntimeError("prefix cache disabled — construct the "
                               "engine with prefix_pages > 0")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < self.prefix.min_match:
            # a prefix shorter than min_match can never match — pinning
            # it would hold pages forever for zero hits
            logger.warning(
                "shared prefix of %d tokens is below the prefix cache's "
                "min_match=%d — it will never produce a hit (use a longer "
                "prefix or lower prefix_min_match)", prompt.size,
                self.prefix.min_match)
        # graftlock: justified(GL012): advisory mode check — serving mode does not flip mid-prewarm
        if self._worker is None:
            res = self.generate([prompt], max_new_tokens=1, eos_token=-1)[0]
        else:
            res = self.submit(prompt, max_new_tokens=1,
                              eos_token=-1).result(timeout=600)
        if res.finish_reason not in ("eos", "length"):
            logger.warning("prefix pre-warm retired as %r — prefix not "
                           "cached", res.finish_reason)
            return res
        if pin:
            self.prefix.pin(prompt)
        return res

    def check_invariants(self) -> None:
        """Allocator + prefix-tree soundness with EXACT refcount
        accounting, plus draft/target length agreement when speculative
        decoding is on (test/chaos hook)."""
        if self.prefix is not None:
            self.prefix.check_invariants()
            self.cache.check_invariants(tree_refs=self.prefix.page_refs())
        else:
            self.cache.check_invariants()
        if self.spec is not None:
            assert self._spec_slots <= set(self.scheduler.slots), (
                f"speculating slots {self._spec_slots} outside the active "
                f"bank {sorted(self.scheduler.slots)}")
            self.spec.check_invariants(self._spec_slots, self.cache.seq_lens)

    # ------------------------------------------------------------ scheduling
    def _retire(self, slot: int, reason: str) -> None:
        if self.prefix is not None and reason in ("eos", "length"):
            # a COMPLETED sequence donates its prompt's pages to the
            # radix tree (insert or LRU-refresh) before the slot lets go
            st = self.scheduler.slots.get(slot)
            if st is not None:
                n = self.cache.pages_for(st.prompt_len)
                self.prefix.insert(st.request.prompt,
                                   list(self.cache.owned[slot][:n]))
        self.scheduler.retire(slot, reason)
        self.cache.free_slot(slot)
        if self.spec is not None:
            self._spec_slots.discard(slot)
            self.spec.free(slot)
        count_terminal(reason)

    def step(self) -> int:
        """ONE scheduler iteration: capacity-evict, admit, retire finished,
        then one decode step for the whole slot bank. Returns the number of
        tokens generated (0 when idle)."""
        cache, sched = self.cache, self.scheduler

        # 1. retire sequences completed by the previous iteration FIRST:
        #    a finished slot must neither grab capacity pages it will never
        #    write nor be mis-retired as oom/overflow (which would skip the
        #    eos trim and steal pages a live neighbour needed)
        for slot in sched.active_slots():
            reason = sched.should_finish(slot)
            if reason:
                self._retire(slot, reason)

        # 1b. deadlines — AFTER completion so a finished sequence keeps its
        #     honest eos/length reason; overdue work retires as "deadline"
        #     (active: partial tokens; queued: empty result, no slot taken)
        now = time.perf_counter()
        for slot in sched.active_slots():
            dl = sched.slots[slot].request.deadline_s
            if dl is not None and now - sched.slots[slot].submit_t > dl:
                self._retire(slot, "deadline")
        expired = []
        with sched._plock:
            for _ in range(len(sched.pending)):
                item = sched.pending.popleft()
                if (item[0].deadline_s is not None
                        and now - item[2] > item[0].deadline_s):
                    expired.append(item)
                else:
                    sched.pending.append(item)
        for req, fut, _t in expired:  # complete OUTSIDE the queue lock —
            # future callbacks (frontend accounting) must not run under it
            self._finish_unslotted(req, fut, "deadline")

        # 2. capacity: every surviving slot needs room for one more token
        for slot in sched.active_slots():
            need = int(cache.seq_lens[slot]) + 1
            if need > self.cfg.max_position:
                self._retire(slot, "overflow")
                continue
            status = cache.ensure_capacity(slot, need)
            if status != "ok":
                self._retire(slot, status)

        # 3. admissions into free slots, highest-priority first (FIFO
        #    within a priority — peek_best_pending orders by (priority,
        #    submit time), so supervisor retries with their ORIGINAL
        #    submit time re-admit ahead of younger same-class work and
        #    recovery never inverts priority). submit() already bounds
        #    prompts to the max_prompt bucket, which __init__ bounds to
        #    the per-slot context — no per-request overflow check here.
        while True:
            free = sched.free_slot_ids()
            if not free:
                break
            item = sched.peek_best_pending()
            if item is None:
                break
            req, fut, t_sub = item
            p_len = int(req.prompt.size)
            # p_len + 1 everywhere: the SAME iteration's decode writes the
            # first generated token's K/V at position p_len, so a page-
            # aligned prompt needs its next page NOW — allocating only the
            # prompt's pages would send that write to the trash page.
            # A prefix-cache match discounts its shared full pages from
            # the bill (the CoW tail still costs a fresh page), and the
            # tree's unpinned pages count as reclaimable supply.
            match = self._match_prefix(req)
            need_new = cache.pages_for(p_len + 1) - (
                match.matched // cache.page_size if match else 0)
            if need_new > cache.free_pages:
                # only now pay the O(tree) reclaimable walk: tree pages
                # eviction would ACTUALLY free (no slot holders, and not
                # the match's own pages — those are being consumed, not
                # freed) count as supply — overcounting here would turn
                # this wait into a spurious terminal oom downstream
                reclaimable = (self.prefix.reclaimable_pages(
                    exclude=match.pages if match else ())
                    if self.prefix is not None else 0)
                if need_new > cache.free_pages + reclaimable:
                    if not sched.slots:
                        # nothing active to ever free pages —
                        # config-impossible
                        if sched.remove_pending(item) and not fut.done():
                            fut.set_exception(RuntimeError(
                                f"prompt needs {need_new} free pages but "
                                f"the pool only has {cache.num_pages} "
                                f"({reclaimable} reclaimable from the "
                                f"prefix tree)"))
                            count_terminal("error")
                        continue
                    break  # pool pressure: wait for evictions
            if not sched.remove_pending(item):
                continue  # a frontend steal raced us — re-select
            slot = free[0]
            try:
                status, hit_tokens = self._admit_pages(slot, req, match)
            except BaseException:
                # same unwind as the prefill crash below: admission may
                # have mapped shared pages / grown the slot before dying
                # (eviction callback, allocator fault) — release whatever
                # the slot holds and put the request back at the queue
                # FRONT so supervision retries it instead of leaking the
                # pages and stranding the future
                cache.free_slot(slot)
                with sched._plock:
                    sched.pending.appendleft(item)
                raise
            if status != "ok":
                # the free-pages precheck passed, so this is injected pool
                # pressure (faults.page_oom) or an allocator race: complete
                # the request terminally instead of prefilling into a
                # trash-page-only row (which would corrupt the invariants)
                self._finish_unslotted(req, fut, status)
                continue
            self._slot_match[slot] = match if hit_tokens else None
            try:
                first_tok = self._prefill_into(slot, req)
            except BaseException:
                # the request sits in neither pending nor a slot right
                # now — put it back at the queue FRONT (original submit
                # time) and release the just-grown pages, so supervision
                # retries it instead of stranding its future forever
                cache.free_slot(slot)
                with sched._plock:
                    sched.pending.appendleft(item)
                raise
            cache.seq_lens[slot] = p_len
            now = time.perf_counter()
            sched.admit(slot, req, fut, t_sub, first_tok, now,
                        prefix_hit_tokens=hit_tokens)
            self._obs["admitted"].inc()
            self._obs["generated"].inc()
            self._obs["ttft_h"].observe(now - t_sub)
            if (self.spec is not None and req.temperature <= 0.0
                    and not req.spec_disabled):
                # greedy slots speculate: the draft prefills the SAME
                # prompt (full — the draft cache has no prefix tree) so
                # draft and target agree on a cached length of p_len.
                # Sampling (temperature > 0) and spec_disabled requests
                # stay on the plain decode path. A crash in here is
                # supervised like any admission crash: the request
                # already holds its slot, so _recover re-queues it.
                self.spec.prefill(slot, req.prompt)
                self._spec_slots.add(slot)

        # 4. a just-admitted sequence can already be done (first token was
        #    its eos, or max_new_tokens == 1) — retire before decoding
        for slot in sched.active_slots():
            reason = sched.should_finish(slot)
            if reason:
                self._retire(slot, reason)

        self._obs["occupancy"].set(sched.occupancy())
        active = sched.active_slots()
        if not active:
            return 0

        # 5. one decode iteration over the whole slot bank. With
        #    speculation on, the bank splits: slots that can take a
        #    spec_k+1-token verify window this step go the draft+verify
        #    path; everything else (sampling slots, spec-disabled
        #    requests, sequences near their context/position limit) rides
        #    the plain one-token decode. Both dispatches keep config-only
        #    shapes, so a mixed bank still never recompiles.
        spec_now: List[int] = []
        plain: List[int] = []
        for slot in active:
            if self.spec is not None and slot in self._spec_slots:
                need = int(cache.seq_lens[slot]) + self.spec.k + 1
                if (need <= self._spec_limit
                        and cache.pages_for(need) <= cache.max_pages_per_seq
                        and cache.ensure_capacity(slot, need) == "ok"):
                    spec_now.append(slot)
                    continue
                # a slot that cannot host the verify window finishes its
                # sequence NON-speculatively: one plain step would advance
                # the target past the draft cache (length drift), so the
                # draft row is abandoned rather than resynced
                self._spec_slots.discard(slot)
                self.spec.free(slot)
            plain.append(slot)

        # chaos hooks (docs/ROBUSTNESS.md): both fire BEFORE any dispatch
        # so an injected crash never leaves a donated kv buffer half
        # consumed inside a real XLA call; _step_speculative arms a
        # second decode_step_error point between draft and verify (the
        # mid-speculation state the chaos leg drives)
        faults.maybe_fail("decode_step_error")
        faults.maybe_sleep("slow_decode", 0.05)

        produced = 0
        if plain:
            produced += self._step_decode(plain)
        if spec_now:
            produced += self._step_speculative(spec_now)
        return produced

    def _step_decode(self, active: List[int]) -> int:
        """The plain one-token decode iteration over ``active`` (the
        whole bank when speculation is off)."""
        cache, sched = self.cache, self.scheduler
        s_n = cache.max_slots
        tokens = np.zeros((s_n,), np.int32)
        act = np.zeros((s_n,), np.int32)
        temp = np.zeros((s_n,), np.float32)
        top_k = np.zeros((s_n,), np.int32)
        top_p = np.ones((s_n,), np.float32)
        for slot in active:
            st = sched.slots[slot]
            tokens[slot] = st.tokens[-1]
            act[slot] = 1
            temp[slot] = st.request.temperature
            top_k[slot] = st.request.top_k
            top_p[slot] = st.request.top_p
        if self._decode_fn is None:
            self._decode_fn = self._build_decode()
        key = self._next_key()
        args = (jnp.asarray(cache.page_table), jnp.asarray(cache.seq_lens),
                jnp.asarray(tokens), jnp.asarray(act))
        observe.note_jit_signature(
            self._decode_fn, graph="serving", key="decode",
            signature=observe.signature_of(
                page_table=cache.page_table, seq_lens=cache.seq_lens,
                tokens=tokens, active=act))
        t0 = time.perf_counter()
        with observe.tracer().span("serving_decode", category="serving",
                                   slots=len(active)):
            cache.kv, next_toks, _logits = self._decode_fn(
                self.model.params, cache.kv, *args, key,
                jnp.asarray(temp), jnp.asarray(top_k), jnp.asarray(top_p))
            next_toks = np.asarray(next_toks)
        dt = time.perf_counter() - t0
        self._obs["decode_h"].observe(dt)
        now = time.perf_counter()
        for slot in active:
            cache.seq_lens[slot] += 1  # the fed token is cached now
            st = sched.slots[slot]
            if st.last_token_t is not None:
                self._obs["itl_h"].observe(now - st.last_token_t)
            sched.on_decode_token(slot, int(next_toks[slot]), now)
        self._obs["generated"].inc(len(active))
        observe.log_event("serving_decode", slots=len(active),
                          step_seconds=round(dt, 6))
        return len(active)

    def _step_speculative(self, spec_now: List[int]) -> int:
        """One speculative iteration for ``spec_now`` (docs/SERVING.md
        § Speculative decoding): K draft proposals per slot (one compiled
        scan), ONE target verify pass over the K+1-token window, then
        greedy exact-match acceptance on the host — commit the agreed
        draft prefix plus the target's correction/bonus token, REWIND the
        cached lengths past it (rejected positions become garbage beyond
        the length: never read, refcount-untouched, overwritten next
        pass). Capacity for the full window was reserved by the caller.

        Latency accounting is per COMMITTED token: a step that lands m
        tokens contributes m observations of (step/m) to the decode and
        inter-token histograms, so spec-on percentiles — and the SLO
        frontend's rolling decode-p50 built on the decode histogram —
        price a token, not a step, and stay comparable to spec-off.
        """
        spec, cache, sched = self.spec, self.cache, self.scheduler
        s_n = cache.max_slots
        pend = np.zeros((s_n,), np.int32)
        act = np.zeros((s_n,), np.int32)
        for slot in spec_now:
            pend[slot] = sched.slots[slot].tokens[-1]
            act[slot] = 1
        t0 = time.perf_counter()
        props = spec.propose(pend, act)          # (S, K) — draft phase
        # second decode_step_error arm, MID-speculation: the draft KV was
        # just donated-and-advanced but nothing committed — the exact
        # state SpeculativeDecoder.reset() exists for; still outside any
        # XLA call, so no buffer is ever half consumed (chaos-leg-driven)
        faults.maybe_fail("decode_step_error")
        vtokens = np.zeros((s_n, spec.k + 1), np.int32)
        vtokens[:, 0] = pend
        vtokens[:, 1:] = props
        if self._verify_fn is None:
            self._verify_fn = self._build_verify()
        observe.note_jit_signature(
            self._verify_fn, graph="serving", key="verify",
            signature=observe.signature_of(
                tokens=vtokens, seq_lens=cache.seq_lens,
                page_table=cache.page_table, active=act))
        with observe.tracer().span("serving_verify", category="serving",
                                   slots=len(spec_now)):
            cache.kv, greedy = self._verify_fn(
                self.model.params, cache.kv, jnp.asarray(vtokens),
                jnp.asarray(cache.seq_lens),
                jnp.asarray(cache.page_table), jnp.asarray(act))
            greedy = np.asarray(greedy)          # (S, K+1) target argmax
        dt = time.perf_counter() - t0
        now = time.perf_counter()
        committed_total = 0
        accepted_total = 0
        for slot in spec_now:
            st = sched.slots[slot]
            # greedy exact-match acceptance: proposal i is accepted iff
            # it equals the target's argmax after the previous token
            j = 0
            while j < spec.k and props[slot, j] == greedy[slot, j]:
                j += 1
            toks = [int(t) for t in props[slot, :j]]
            toks.append(int(greedy[slot, j]))    # correction / bonus
            # truncation: never exceed the request's remaining budget,
            # and never commit past an eos (retire trims the eos itself)
            rem = st.request.max_new_tokens - len(st.tokens)
            toks = toks[:max(1, rem)]
            eos = st.request.eos_token
            for i, t in enumerate(toks):
                if t == eos:
                    toks = toks[:i + 1]
                    break
            m = len(toks)
            # the rewind: t0 and the first m-1 commits are cached (their
            # K/V was written at seq_lens..seq_lens+m-1); the LAST commit
            # is the next step's feed, and positions seq_lens+m.. hold
            # rejected garbage beyond the length
            cache.seq_lens[slot] += m
            spec.commit(slot, m)
            from_draft = min(j, m)               # drafts that landed
            spec.note_outcome(spec.k, j, from_draft)
            gap = sched.on_spec_tokens(slot, toks, now, spec.k, from_draft)
            per_tok = dt / m
            for _ in range(m):
                self._obs["decode_h"].observe(per_tok)
                if gap is not None:
                    self._obs["itl_h"].observe(gap)
            committed_total += m
            accepted_total += from_draft
        self._obs["generated"].inc(committed_total)
        observe.log_event(
            "serving_spec", slots=len(spec_now), proposed=spec.k
            * len(spec_now), accepted=accepted_total,
            committed=committed_total, step_seconds=round(dt, 6))
        return committed_total

    def _prefill_into(self, slot: int, req: GenerationRequest) -> int:
        """Run the (bucketed) prefill, scatter K/V into the slot's pages,
        return the first sampled token. With a prefix-cache match staged
        for this slot the shared pages are already mapped and only the
        SUFFIX runs — TTFT is measured across this (much shorter) pass."""
        match = self._slot_match.pop(slot, None)
        if match is not None:
            return self._prefill_suffix_into(slot, req, match)
        cache = self.cache
        p_len = int(req.prompt.size)
        ids = np.zeros((1, self.max_prompt), np.int32)
        ids[0, :p_len] = req.prompt
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        if self._write_fn is None:
            self._write_fn = self._build_write()
        key = self._next_key()
        observe.note_jit_signature(
            self._prefill_fn, graph="serving", key="prefill",
            signature=observe.signature_of(ids=ids))
        observe.note_jit_signature(
            self._write_fn, graph="serving", key="write_prompt",
            signature=observe.signature_of(ids=ids))
        with observe.tracer().span("serving_prefill", category="serving",
                                   prompt_len=p_len):
            kv_prompt, tok = self._prefill_fn(
                self.model.params, jnp.asarray(ids),
                jnp.asarray(p_len, jnp.int32), key,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32))
            cache.kv = self._write_fn(
                cache.kv, kv_prompt, jnp.asarray(cache.page_table[slot]),
                jnp.asarray(p_len, jnp.int32))
            tok = int(tok)
        return tok

    def _prefill_suffix_into(self, slot: int, req: GenerationRequest,
                             match: PrefixMatch) -> int:
        """Prefix-hit admission: prefill ONLY the uncached suffix against
        the cached prefix pages already mapped into the slot's row."""
        cache = self.cache
        p_len = int(req.prompt.size)
        suffix = np.asarray(req.prompt).reshape(-1)[match.matched:]
        ids = np.zeros((1, self.suffix_bucket), np.int32)
        ids[0, :suffix.size] = suffix
        if self._suffix_fn is None:
            self._suffix_fn = self._build_suffix()
        key = self._next_key()
        observe.note_jit_signature(
            self._suffix_fn, graph="serving", key="suffix_prefill",
            signature=observe.signature_of(ids=ids))
        with observe.tracer().span("serving_prefill", category="serving",
                                   prompt_len=p_len,
                                   prefix_hit=match.matched):
            cache.kv, tok = self._suffix_fn(
                self.model.params, cache.kv, jnp.asarray(ids),
                jnp.asarray(match.matched, jnp.int32),
                jnp.asarray(suffix.size, jnp.int32),
                jnp.asarray(cache.page_table[slot]), key,
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32))
            tok = int(tok)
        return tok
