"""Engine warm boot from the persistent AOT export cache.

The serving engine's compiled functions (``prefill`` / ``write_prompt`` /
``decode`` and, when configured, ``suffix_prefill`` / ``verify`` /
``copy_page`` plus the draft ``draft_prefill`` / ``draft_decode``) have
signatures that depend ONLY on server config — the bucketing discipline
PRs 7–18 enforce. That makes them perfect AOT-cache citizens: one export
per (engine fingerprint, device_kind, jax version) serves every process
with that config. :func:`warm_boot` pre-populates all of them BEFORE the
first request:

* **hit** — the entry deserializes into the live fn slot; the ledger
  records ``cache_hit``, the process pays zero fresh traces for it (the
  XLA backend compile of the deserialized StableHLO additionally hits
  jax's persistent compilation cache, armed by ExportCache).
* **miss** — the builder compiles as usual, the export is persisted, and
  the engine runs the SAME exported executable it just stored — so the
  populating (cold) leg and every warm restore are bit-identical by
  construction, not by luck.

Key-taking fns (``prefill``/``suffix_prefill``/``decode``) export as
raw-key computations (typed PRNG keys cannot cross ``jax.export``; see
autodiff/export.py) behind a thin wrapper that feeds
``jax.random.key_data(key)`` — the engine's dispatch sites are unchanged.

Activation: ``$DL4J_TPU_COMPILE_CACHE`` (:func:`maybe_warm_boot`, called
from ``GenerativeEngine.__init__`` and ``_recover``), or an explicit
:class:`~deeplearning4j_tpu.autodiff.export.ExportCache` passed to
:func:`warm_boot` (tests).
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
from jax import export as jexport

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.autodiff.export import (
    ENV_DIR, ExportCache, _tree_spec_tokens, fingerprint_tokens,
    restore_callable, spec_of)


def engine_fingerprint(engine) -> str:
    """Identity of an engine's compiled-fn family: model config + param
    tree structure + the full serving geometry (prompt bucket, page
    geometry, prefix/speculative arms). Weight VALUES are excluded — the
    executables are functions of structure; params are arguments."""
    cache = engine.cache
    toks: List[Any] = [
        "serving", repr(engine.cfg), _tree_spec_tokens(engine.model.params),
        engine.max_prompt, engine.suffix_bucket,
        cache.page_size, cache.num_pages, cache.max_slots,
        cache.max_pages_per_seq, tuple(cache.kv.shape),
        str(cache.kv.dtype), engine.prefix is not None,
    ]
    if engine.spec is not None:
        spec = engine.spec
        toks += ["spec", repr(spec.draft.cfg),
                 _tree_spec_tokens(spec.draft.params), spec.k,
                 tuple(spec._kv_shape), str(spec._kv_dtype)]
    return fingerprint_tokens(*toks)


def _raw_key_adapter(inner, key_idx: int):
    """Export-side: take uint32 key data where ``inner`` takes a typed
    PRNG key (which cannot cross the export boundary)."""
    def raw(*args):
        args = list(args)
        args[key_idx] = jax.random.wrap_key_data(args[key_idx])
        return inner(*args)
    return raw


def _typed_key_adapter(call, key_idx: int):
    """Restore-side: the engine dispatches typed keys; the exported
    computation wants their raw data. Ledger markers mirror onto the
    wrapper — it is the object the dispatch sites register."""
    def fn(*args):
        args = list(args)
        args[key_idx] = jax.random.key_data(args[key_idx])
        return call(*args)

    fn._aot_restored = getattr(call, "_aot_restored", False)
    fn._obs_sigs = set(getattr(call, "_obs_sigs", ()))
    return fn


def _fn_table(engine) -> List[Dict[str, Any]]:
    """One descriptor per warm-bootable fn: cache key, live slot
    (owner object + attribute), builder, export arg specs, and the key
    arg index for raw-key adaptation (None for keyless fns). Specs
    mirror the dispatch sites' exact shapes/dtypes — config-stable by
    the bucketing contract."""
    cfg, cache = engine.cfg, engine.cache
    S, P = cache.page_table.shape
    SDS = jax.ShapeDtypeStruct
    i32, f32 = jnp.int32, jnp.float32
    kd = jax.random.key_data(jax.random.key(0))
    KD = SDS(tuple(kd.shape), kd.dtype)
    PARAMS = spec_of(engine.model.params)
    KV = SDS(tuple(cache.kv.shape), cache.kv.dtype)
    kv_prompt = SDS((cfg.layers, 2, engine.max_prompt, cfg.heads,
                     cfg.hidden // cfg.heads), cache.kv.dtype)
    table = [
        dict(key="prefill", owner=engine, attr="_prefill_fn",
             build=engine._build_prefill, key_idx=3, donate=(),
             specs=(PARAMS, SDS((1, engine.max_prompt), i32), SDS((), i32),
                    KD, SDS((1,), f32), SDS((1,), i32), SDS((1,), f32))),
        dict(key="write_prompt", owner=engine, attr="_write_fn",
             build=engine._build_write, key_idx=None,
             specs=(KV, kv_prompt, SDS((P,), i32), SDS((), i32))),
        dict(key="decode", owner=engine, attr="_decode_fn",
             build=engine._build_decode, key_idx=6, donate=(1,),
             specs=(PARAMS, KV, SDS((S, P), i32), SDS((S,), i32),
                    SDS((S,), i32), SDS((S,), i32), KD, SDS((S,), f32),
                    SDS((S,), i32), SDS((S,), f32))),
    ]
    if engine.prefix is not None:
        table += [
            dict(key="suffix_prefill", owner=engine, attr="_suffix_fn",
                 build=engine._build_suffix, key_idx=6, donate=(1,),
                 specs=(PARAMS, KV, SDS((1, engine.suffix_bucket), i32),
                        SDS((), i32), SDS((), i32), SDS((P,), i32), KD,
                        SDS((1,), f32), SDS((1,), i32), SDS((1,), f32))),
            dict(key="copy_page", owner=cache, attr="_copy_fn",
                 build=cache._build_copy, key_idx=None,
                 specs=(KV, SDS((), i32), SDS((), i32))),
        ]
    if engine.spec is not None:
        spec = engine.spec
        DPARAMS = spec_of(spec.draft.params)
        DKV = SDS(tuple(spec._kv_shape), spec._kv_dtype)
        table += [
            dict(key="verify", owner=engine, attr="_verify_fn",
                 build=engine._build_verify, key_idx=None,
                 specs=(PARAMS, KV, SDS((S, spec.k + 1), i32),
                        SDS((S,), i32), SDS((S, P), i32), SDS((S,), i32))),
            dict(key="draft_prefill", owner=spec, attr="_prefill_fn",
                 build=spec._build_prefill, key_idx=None,
                 specs=(DPARAMS, DKV, SDS((1, spec.max_prompt), i32),
                        SDS((), i32), SDS((), i32))),
            dict(key="draft_decode", owner=spec, attr="_propose_fn",
                 build=spec._build_propose, key_idx=None,
                 specs=(DPARAMS, DKV, SDS((S,), i32), SDS((S,), i32),
                        SDS((S,), i32))),
        ]
    return table


def warm_boot(engine, cache: Optional[ExportCache] = None) -> Dict[str, Any]:
    """Pre-populate every unbuilt compiled-fn slot from the AOT cache
    (hit) or by building+exporting+persisting (miss). Slots already
    holding a live fn are left alone — an in-process ``_recover`` keeps
    its compiled fns. Returns ``{"restored": [...], "exported": [...],
    "fingerprint": ...}``."""
    cache = cache or ExportCache.from_env()
    if cache is None:
        return {"restored": [], "exported": [], "fingerprint": None}
    fp = engine_fingerprint(engine)
    restored: List[str] = []
    exported_keys: List[str] = []
    for d in _fn_table(engine):
        if getattr(d["owner"], d["attr"]) is not None:
            continue
        exported = cache.load(fp, d["key"])
        if exported is not None:
            inner = restore_callable(exported, graph="serving",
                                     key=d["key"], hit=True)
            restored.append(d["key"])
        else:
            built = d["build"]()
            if d["key_idx"] is None:
                jitted = built
            else:
                jitted = jax.jit(_raw_key_adapter(built, d["key_idx"]),
                                 donate_argnums=d.get("donate", ()))
            t0 = time.perf_counter()
            exported = jexport.export(jitted)(*d["specs"])
            cache.observe_export_seconds(time.perf_counter() - t0)
            cache.store(fp, d["key"], exported, meta={"graph": "serving"})
            # run the freshly exported executable, not the in-process jit:
            # the populating leg and every warm restore share ONE artifact,
            # so bit-identity across legs holds by construction
            inner = restore_callable(exported, graph="serving",
                                     key=d["key"], hit=False)
            exported_keys.append(d["key"])
        fn = (inner if d["key_idx"] is None
              else _typed_key_adapter(inner, d["key_idx"]))
        setattr(d["owner"], d["attr"], fn)
    if restored or exported_keys:
        observe.log_event("aot_warm_boot", consumer="serving",
                          restored=restored, exported=exported_keys)
    return {"restored": restored, "exported": exported_keys,
            "fingerprint": fp}


def maybe_warm_boot(engine) -> Dict[str, Any]:
    """Env-gated :func:`warm_boot` — inert unless
    ``$DL4J_TPU_COMPILE_CACHE`` is set, so default construction (tests,
    unconfigured deployments) pays nothing."""
    if not os.environ.get(ENV_DIR):
        return {"restored": [], "exported": [], "fingerprint": None}
    return warm_boot(engine)
