"""Continuous-batching generative serving (docs/SERVING.md).

The generative-inference tier ROADMAP item 2 names: a decoder-only
transformer (``models/gpt.py``) served through

* :class:`PagedKVCache` — block-paged KV memory with a free-list allocator
  (the PagedAttention/vLLM layout), sized once at server start;
* :class:`SlotScheduler` — iteration-level (Orca-style) continuous
  batching: admit into free slots / evict finished + overflowing sequences
  between decode steps;
* :class:`GenerativeEngine` — the compiled prefill/decode/write functions
  whose jit signatures depend only on server configuration (compile once,
  serve any mix of sequences) plus temperature/top-k/top-p sampling with
  per-slot split PRNG keys (``serving/sampling.py``); SUPERVISED since the
  robustness tier (docs/ROBUSTNESS.md): worker crashes restart under
  capped backoff with retry re-admission, per-request deadlines, and a
  bounded-queue admission gate that sheds overload as a terminal ``shed``
  reason — exercised by ``make chaos-smoke`` over the
  ``deeplearning4j_tpu/faults/`` injection points.

* :class:`RadixPrefixCache` (``serving/prefix.py``) — shared-prompt KV
  reuse: a radix tree over token sequences whose nodes hold refcounted
  cache pages, so repeated system prompts/few-shot prefixes map by
  reference and only the uncached suffix prefills
  (copy-on-write for mid-page divergence, LRU leaf eviction under a page
  budget, per-class pre-warm + pinning via the frontend's
  ``ClassPolicy.shared_prefix``; ``BENCH_PREFIX=1`` / ``make
  prefix-smoke`` measure the TTFT win);

* :class:`SpeculativeDecoder` (``serving/speculative.py``) — speculative
  decoding (draft-then-verify, lossless): ``GenerativeEngine(spec_k=K,
  draft_model=...)`` runs a small draft model over a dense per-slot KV
  cache to propose K greedy tokens per step, verifies all of them in ONE
  target forward (``models.gpt.gpt_verify``, the fifth compiled fn), and
  commits the agreed prefix plus the target's correction token —
  bit-identical outputs at 1..K+1 tokens per target step, rollback as an
  O(1) length rewind (``BENCH_SPEC=1`` / ``make spec-smoke`` measure the
  tokens/sec win);

* :class:`SLOFrontend` (``serving/frontend.py``) — the SLO-driven
  admission layer: priority classes over a priority-ordered pending
  queue, token-bucket rate limits, predictive early shed against
  per-request deadlines, an ``ok``/``degraded``/``shedding`` hysteresis
  ladder, and a circuit breaker on the supervisor's restart rate —
  overload becomes goodput management instead of a failure mode
  (``serving/overload.py`` measures it; ``make slo-smoke`` gates it).

* :class:`ClusterRouter` (``serving/cluster.py``) — N engines behind one
  health- and prefix-affinity-routed front: whole-engine death (restart
  budget spent, or the hard ``engine_death`` fault) becomes a managed
  failure domain — in-flight retryable requests migrate to survivors at
  queue front with their original submit time and priority, pinned
  prefixes re-warm on the destination, and the frontend's per-engine
  circuit breaker quarantines only the dead engine (``make
  cluster-chaos-smoke`` gates it).

Serve it directly or through the ``ParallelInference.generative`` facade
(``parallel/mesh.py``). ``BENCH_MODEL=generate`` (bench.py) measures
tokens/sec with p50/p99 TTFT and inter-token latency;
``BENCH_OVERLOAD=1`` switches it to the overload ramp reporting goodput
(completed-within-deadline tokens/sec) with vs without the frontend.
"""

from deeplearning4j_tpu.serving.cache import PagedKVCache
from deeplearning4j_tpu.serving.cluster import ClusterRouter
from deeplearning4j_tpu.serving.engine import GenerativeEngine
from deeplearning4j_tpu.serving.prefix import PrefixMatch, RadixPrefixCache
from deeplearning4j_tpu.serving.frontend import (
    ClassPolicy,
    LadderThresholds,
    OVERLOAD_STATES,
    SLOFrontend,
    default_classes,
)
from deeplearning4j_tpu.serving.sampling import sample_tokens
from deeplearning4j_tpu.serving.scheduler import (
    FINISH_REASONS,
    GenerationRequest,
    GenerationResult,
    SlotScheduler,
)
from deeplearning4j_tpu.serving.speculative import (
    SpeculativeDecoder,
    perturbed_draft,
)

__all__ = [
    "PagedKVCache", "GenerativeEngine", "ClusterRouter", "sample_tokens",
    "GenerationRequest", "GenerationResult", "SlotScheduler",
    "FINISH_REASONS", "SLOFrontend", "ClassPolicy", "LadderThresholds",
    "OVERLOAD_STATES", "default_classes", "RadixPrefixCache",
    "PrefixMatch", "SpeculativeDecoder", "perturbed_draft",
]
