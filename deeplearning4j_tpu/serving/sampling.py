"""Per-slot token sampling — temperature / top-k / top-p, jit-stable.

One vectorized function over the whole slot axis: every knob is a device
array of shape ``(S,)`` so heterogeneous requests (a greedy slot next to a
temperature-1.2 top-p slot) share ONE compiled sampler — no per-request
recompiles, which is the entire point of the fixed-capacity decode step.

PRNG hygiene (graftlint GL004): the caller passes ONE fresh step key; it is
split into per-slot keys HERE, once, and every key is consumed exactly once
by its slot's categorical draw. The serving engine derives the step key by
splitting its root key every iteration — ``tests/test_serving.py`` asserts
no key value ever repeats across the scheduler loop.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_tokens(logits, step_key, temperature, top_k, top_p):
    """Sample one token per slot.

    logits: (S, V) f32; step_key: ONE jax PRNG key for this decode step;
    temperature: (S,) f32 — ``<= 0`` means greedy argmax for that slot;
    top_k: (S,) int32 — ``0`` disables the k cutoff;
    top_p: (S,) f32 — ``1.0`` disables the nucleus cutoff.
    Returns (S,) int32.
    """
    s_n, vocab = logits.shape
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    # top-k: keep scores >= the k-th largest per row (k=0 -> keep all)
    k = jnp.clip(jnp.where(top_k > 0, top_k, vocab), 1, vocab)
    desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    kth = jnp.take_along_axis(desc, (k - 1)[:, None], axis=-1)
    masked = jnp.where(scaled >= kth, scaled, -jnp.inf)

    # top-p (nucleus) on the k-masked distribution: keep the smallest
    # prefix of descending probs whose mass reaches top_p. A sorted token
    # is kept when the mass BEFORE it is < top_p, so the cutoff prob is
    # the smallest kept prob; >= maps the cutoff back to vocab order.
    probs = jax.nn.softmax(masked, axis=-1)
    sp = jnp.sort(probs, axis=-1)[:, ::-1]
    cum = jnp.cumsum(sp, axis=-1)
    keep_sorted = (cum - sp) < top_p[:, None]
    cutoff = jnp.min(jnp.where(keep_sorted, sp, jnp.inf), axis=-1,
                     keepdims=True)
    masked = jnp.where(probs >= cutoff, masked, -jnp.inf)

    keys = jax.random.split(step_key, s_n)
    sampled = jax.vmap(jax.random.categorical)(keys, masked).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)
