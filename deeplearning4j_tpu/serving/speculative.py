"""Speculative decoding — draft-model propose, single-pass target verify.

ROADMAP item 2(b): decode is the memory-bound hot path of the serving
tier — every generated token costs one full target forward whose time is
dominated by weight/KV traffic, not FLOPs. Draft-then-verify (Leviathan
et al. 2023, Chen et al. 2023) buys tokens-per-forward without changing
the output distribution: a cheap DRAFT model proposes ``K`` greedy
continuations, then ONE batched target forward (``models.gpt.gpt_verify``
— the PR-12 suffix-prefill shape, K+1 tokens against the paged cache)
scores all proposals at once. Greedy exact-match acceptance commits the
agreed prefix plus the target's own next token at the first disagreement
(the correction) or after a full accept (the bonus) — so every verify
commits between 1 and K+1 tokens and the committed stream is
**bit-identical** to non-speculative greedy decoding, by construction —
scoped to the verify and decode attention paths agreeing on argmax:
``gpt_verify`` runs the registry's dense attention while plain decode
runs the paged kernel, so on-device a near-tie logit could in principle
resolve differently between them (docs/SERVING.md § Speculative
decoding, "On-device caveat"; the CPU gates share one implementation,
and ``tests/test_serving.py`` asserts Pallas-vs-XLA greedy agreement at
test scale).

This module owns the DRAFT half:

* a **dense per-slot draft KV cache** ``(L, 2, max_slots, max_ctx + 1,
  H, Dh)`` — the draft is small, so the paged indirection would cost more
  than it saves; the final position is the trash position (inactive
  slots' writes land there, mirroring the page trick);
* ``draft_prefill`` — the draft's full-prompt pass at admission (the
  prompt rides the same ``max_prompt`` bucket as the target prefill);
* ``draft_decode`` — ONE compiled fn proposing all K tokens: a
  ``lax.scan`` of K greedy decode steps over the whole slot bank.

Both signatures depend only on server-start configuration
``(max_slots, max_prompt, max_ctx, spec_k)`` — the RecompileLedger shows
exactly one ``first_compile`` each (keys ``draft_prefill`` /
``draft_decode``) and ZERO ``new_shape`` across admits/evicts/rejections/
restarts (gate-asserted, like the four target functions).

**Rollback** is O(1) host bookkeeping: the verify pass writes K/V for
every fed token, and a rejection simply REWINDS the committed length —
target-side ``cache.seq_lens`` and draft-side :attr:`lens` — leaving the
rejected positions as garbage beyond the length that attention (which
masks ``>= seq_len``) never reads and the next pass overwrites. No pages
are freed on rollback (refcount-safe: shared prefix-cache pages are
never written past the prompt, so a rewind cannot corrupt the radix
tree — tests/test_speculative.py exercises page-boundary rollbacks on
shared pages).

**Supervision**: a crash recovery reallocates the (possibly mid-donation)
draft KV buffer with :meth:`reset` — same shape, so the compiled draft
fns survive and retried requests re-prefill from the prompt, staying
lossless.

Metrics: ``dl4j_tpu_spec_{proposed,accepted,rejected}_tokens_total``
counters plus the ``dl4j_tpu_spec_accept_ratio`` histogram (per-verify
accepted/K — the acceptance-rate signal); ``serving_draft`` /
``serving_verify`` spans come from the engine (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import functools
from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.models.bert import _layer_norm
from deeplearning4j_tpu.models.gpt import GptModel, _ffn, gpt_prefill

#: acceptance-ratio histogram bounds — fractions of K, not latencies
_ACCEPT_BOUNDS = tuple(i / 10.0 for i in range(11))


def perturbed_draft(model: GptModel, *, scale: float = 1e-2,
                    seed: int = 0) -> GptModel:
    """A deterministic distillation STAND-IN for harnesses: the target's
    own params plus small seeded Gaussian noise, same config. Greedy
    agreement with the target is high but not total, so replay/gate legs
    exercise accepts AND rejections reproducibly — a real deployment
    pairs a trained GPT-tiny draft (``models.GPT(...).init_draft()``)
    instead; the harness floor (``slow_decode``) stands in for the big
    model's step time the same way the slo gate's does."""
    leaves, treedef = jax.tree.flatten(model.params)
    keys = jax.random.split(jax.random.key(seed), len(leaves))
    noisy = [l + jnp.asarray(scale, l.dtype)
             * jax.random.normal(k, l.shape, l.dtype)
             for l, k in zip(leaves, keys)]
    return GptModel(model.cfg, params=jax.tree.unflatten(treedef, noisy))


def _draft_decode_step(params, kv, tokens, pos, active, cfg):
    """One greedy draft token for every slot against the dense cache.

    kv: (L, 2, S, T+1, H, Dh) — position T is the trash position;
    tokens/pos: (S,) the fed token and its absolute position; active:
    (S,) int32. Writes the fed token's K/V at ``pos`` (trash when
    inactive), attends over positions ``<= pos``, returns
    ``(kv, logits (S, V))``.
    """
    from deeplearning4j_tpu.ops import exec_op

    emb = params["embeddings"]
    s_n = tokens.shape[0]
    t_all = kv.shape[3]
    h, dh = cfg.heads, cfg.hidden // cfg.heads
    p = jnp.clip(pos, 0, cfg.max_position - 1)
    x = emb["word"][tokens] + emb["position"][p]
    x = _layer_norm(x, emb["ln_gamma"], emb["ln_beta"], cfg.layer_norm_eps)
    wpos = jnp.where(active > 0, pos, t_all - 1)
    rows = jnp.arange(s_n)
    # (S, 1, 1, T): key j is readable once written — j <= pos (history
    # plus the token this very step writes); the trash position never
    # enters the mask
    m4 = (jnp.arange(t_all - 1)[None, :] <= pos[:, None])[:, None, None, :]
    for li, blk in enumerate(params["blocks"]):
        a = blk["attn"]
        q = (x @ a["Wq"] + a["bq"]).reshape(s_n, h, 1, dh)
        k = (x @ a["Wk"] + a["bk"]).reshape(s_n, h, dh)
        v = (x @ a["Wv"] + a["bv"]).reshape(s_n, h, dh)
        kv = kv.at[li, 0, rows, wpos].set(k)
        kv = kv.at[li, 1, rows, wpos].set(v)
        kc = kv[li, 0, :, :t_all - 1].transpose(0, 2, 1, 3)  # (S, H, T, Dh)
        vc = kv[li, 1, :, :t_all - 1].transpose(0, 2, 1, 3)
        out = exec_op("dot_product_attention", q, kc, vc, m4, scaled=True)
        out = out.reshape(s_n, cfg.hidden)
        x = _layer_norm(x + out @ a["Wo"] + a["bo"],
                        a["ln_gamma"], a["ln_beta"], cfg.layer_norm_eps)
        x = _ffn(blk, x, cfg.layer_norm_eps)
    return kv, x @ emb["word"].T


class SpeculativeDecoder:
    """The draft half of speculative decoding: dense per-slot draft KV,
    the two compiled draft functions, and the commit/rollback/reset
    bookkeeping the engine drives (module docstring has the design).

    Invariant (``GenerativeEngine.check_invariants`` asserts it): for
    every speculating slot, :attr:`lens` equals the target cache's
    ``seq_lens`` — draft and target always agree on how many tokens are
    committed-and-cached; for every other slot it is zero.
    """

    def __init__(self, draft_model: GptModel, *, k: int, max_slots: int,
                 max_ctx: int, max_prompt: int):
        if k <= 0:
            raise ValueError(f"spec_k must be positive, got {k}")
        self.draft = draft_model
        cfg = draft_model.cfg
        self.k = int(k)
        self.max_slots = int(max_slots)
        self.max_ctx = int(max_ctx)
        self.max_prompt = int(max_prompt)
        if cfg.max_position < self.max_prompt:
            raise ValueError(
                f"draft max_position={cfg.max_position} cannot prefill the "
                f"engine's max_prompt={max_prompt} bucket")
        dtype = jax.tree.leaves(draft_model.params)[0].dtype
        # +1: the trash position — inactive slots' scan writes land there
        self._kv_shape = (cfg.layers, 2, self.max_slots, self.max_ctx + 1,
                          cfg.heads, cfg.hidden // cfg.heads)
        self._kv_dtype = dtype
        self.kv = jnp.zeros(self._kv_shape, dtype)
        self.lens = np.zeros((self.max_slots,), np.int32)
        self._prefill_fn = None
        self._propose_fn = None
        m = observe.metrics()
        self._c_proposed = m.counter("dl4j_tpu_spec_proposed_tokens_total")
        self._c_accepted = m.counter("dl4j_tpu_spec_accepted_tokens_total")
        self._c_rejected = m.counter("dl4j_tpu_spec_rejected_tokens_total")
        self._h_ratio = m.histogram("dl4j_tpu_spec_accept_ratio",
                                    bounds=_ACCEPT_BOUNDS)

    # ---------------------------------------------------------- compiled fns
    def _build_prefill(self):
        cfg = self.draft.cfg

        @functools.partial(jax.jit, donate_argnums=(1,))
        def draft_prefill(params, kv, ids, prompt_len, slot):
            mask = (jnp.arange(ids.shape[1]) < prompt_len)[None, :]
            _logits, kvp = gpt_prefill(params, ids, cfg,
                                       mask=mask.astype(jnp.int32))
            # kvp (L, 2, 1, Tpre, H, Dh) drops into the slot's row;
            # positions >= prompt_len hold pad garbage the <= pos decode
            # mask never reads (the first propose overwrites position
            # prompt_len before attending to it)
            return jax.lax.dynamic_update_slice(
                kv, kvp, (0, 0, slot, 0, 0, 0))

        return draft_prefill

    def _build_propose(self):
        cfg, k = self.draft.cfg, self.k

        @functools.partial(jax.jit, donate_argnums=(1,))
        def draft_decode(params, kv, tokens, lens, active):
            def body(carry, _):
                kv, toks, pos = carry
                kv, logits = _draft_decode_step(params, kv, toks, pos,
                                                active, cfg)
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                return (kv, nxt, pos + (active > 0).astype(jnp.int32)), nxt

            # k + 1 steps for k proposals: the LAST iteration exists only
            # to write d_K's K/V (feeding it, discarding its output) — a
            # full accept commits K+1 tokens and :meth:`commit` advances
            # the draft length over position lens+K, so that position
            # must hold real K/V or every later draft step for the slot
            # would attend to a garbage hole INSIDE the claimed length,
            # silently decaying acceptance for the rest of the sequence
            (kv, _, _), props = jax.lax.scan(body, (kv, tokens, lens),
                                             None, length=k + 1)
            return kv, jnp.transpose(props)[:, :k]  # (S, K)

        return draft_decode

    # ------------------------------------------------------------- lifecycle
    def prefill(self, slot: int, prompt) -> None:
        """Run the draft over ``slot``'s (bucket-padded) prompt at
        admission; afterwards the draft agrees with the target on a
        cached length of ``prompt_len``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        p_len = int(prompt.size)
        ids = np.zeros((1, self.max_prompt), np.int32)
        ids[0, :p_len] = prompt
        if self._prefill_fn is None:
            self._prefill_fn = self._build_prefill()
        observe.note_jit_signature(
            self._prefill_fn, graph="serving", key="draft_prefill",
            signature=observe.signature_of(ids=ids))
        with observe.tracer().span("serving_draft", category="serving",
                                   phase="prefill", prompt_len=p_len):
            self.kv = self._prefill_fn(
                self.draft.params, self.kv, jnp.asarray(ids),
                jnp.asarray(p_len, jnp.int32), jnp.asarray(slot, jnp.int32))
        self.lens[slot] = p_len

    def propose(self, tokens: np.ndarray, active: np.ndarray) -> np.ndarray:
        """Propose K greedy draft tokens for every active slot, feeding
        each slot's pending token first. Advances the draft KV (rejected
        tails are rewound by :meth:`commit`); returns (S, K) int32."""
        if self._propose_fn is None:
            self._propose_fn = self._build_propose()
        observe.note_jit_signature(
            self._propose_fn, graph="serving", key="draft_decode",
            signature=observe.signature_of(tokens=tokens, lens=self.lens,
                                           active=active))
        with observe.tracer().span("serving_draft", category="serving",
                                   phase="decode",
                                   slots=int(active.sum())):
            self.kv, props = self._propose_fn(
                self.draft.params, self.kv, jnp.asarray(tokens),
                jnp.asarray(self.lens), jnp.asarray(active))
            return np.asarray(props)

    def commit(self, slot: int, n_tokens: int) -> None:
        """Advance ``slot``'s draft length by the tokens the verify pass
        actually committed — everything past it is the rollback: garbage
        beyond the length, overwritten by the next propose."""
        self.lens[slot] += int(n_tokens)

    def note_outcome(self, proposed: int, accepted: int,
                     committed_from_draft: int) -> None:
        """Count one slot's verify outcome. The counters are ADDITIVE by
        construction — ``proposed == accepted + rejected`` always:
        ``accepted`` counts draft tokens that actually COMMITTED,
        ``rejected`` everything proposed that did not land (target
        disagreement OR eos/budget truncation). The pure
        disagreement-rate signal (verified agreement ``accepted``/K,
        truncation excluded) is the ``accept_ratio`` histogram."""
        self._c_proposed.inc(proposed)
        self._c_accepted.inc(committed_from_draft)
        self._c_rejected.inc(proposed - committed_from_draft)
        if proposed:
            self._h_ratio.observe(accepted / proposed)

    def free(self, slot: int) -> None:
        """Retire ``slot``'s draft row (length 0; the KV bytes are
        garbage-beyond-length until the next tenant's prefill)."""
        self.lens[slot] = 0

    def reset(self) -> None:
        """Supervised crash recovery: reallocate the (possibly
        mid-donation) draft KV buffer — same shape, so the compiled draft
        fns survive and the ledger's zero-new_shape property holds across
        restarts — and zero every draft length (retried requests
        re-prefill from the prompt)."""
        self.kv = jnp.zeros(self._kv_shape, self._kv_dtype)
        self.lens[:] = 0

    # ------------------------------------------------------------ inspection
    def check_invariants(self, active_spec_slots, seq_lens) -> None:
        """Draft/target length agreement (test/chaos hook): every
        speculating slot's draft length equals the target cache's, every
        other slot's is zero. Raises AssertionError on violation."""
        for slot in range(self.max_slots):
            if slot in active_spec_slots:
                assert self.lens[slot] == seq_lens[slot], (
                    f"slot {slot}: draft cached {self.lens[slot]} tokens "
                    f"but the target cache holds {seq_lens[slot]}")
            else:
                assert self.lens[slot] == 0, (
                    f"slot {slot} is not speculating but holds a draft "
                    f"length of {self.lens[slot]}")


__all__: List[str] = ["SpeculativeDecoder", "perturbed_draft"]
