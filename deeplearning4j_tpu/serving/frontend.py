"""SLO-driven admission frontend — overload as a managed state.

ROADMAP item 2(d): the layer between "millions of users" and ONE
:class:`~deeplearning4j_tpu.serving.engine.GenerativeEngine`. The engine's
own overload handling is a blunt ``max_queue`` cutoff — a hopeless request
still queues until its deadline burns, a burst of batch traffic starves
interactive traffic, and there is no middle ground between "serve fully"
and "shed". :class:`SLOFrontend` wraps ``engine.submit`` and makes
overload *managed*:

1. **Admission control.** Per-class token-bucket rate limits and
   in-flight concurrency caps, plus **predictive early shed**: estimated
   time-to-first-token (live queue depth, slot occupancy, and a rolling
   decode-step p50 read from the ``observe`` histograms) plus the decode
   time of the (possibly degraded) answer already past the request's
   deadline means the request completes as ``shed`` AT SUBMIT — capacity
   is never spent decoding work that cannot meet its SLO, and a
   completion landing past its deadline is priced at what it is worth:
   nothing.
2. **Priority classes.** ``interactive`` > ``standard`` > ``batch``
   (configurable): the engine's pending queue is priority-ordered (FIFO
   within a class — :meth:`SlotScheduler.peek_best_pending`), each class
   has its own queue-depth bound, and when the TOTAL queue bound is hit
   the LOWEST class queued is stolen and shed first. Supervisor retries
   re-queue the same request object — original class, priority, and
   submit time — so crash recovery never inverts priority.
3. **Graceful-degradation ladder.** Explicit overload states ``ok`` →
   ``degraded`` → ``shedding``, driven by hysteresis thresholds on queue
   depth and the ROLLING decode p99 (bucket-delta quantiles — the
   process-lifetime histogram never forgets, the ladder must). In
   ``degraded``, degradable (low) classes get ``max_new_tokens`` capped
   and the expensive sampling extras (top-k/top-p masking) disabled, and
   the trim is recorded on the request so the caller's
   ``GenerationResult.degraded`` is honest. In ``shedding``, classes
   marked ``reject_in_shedding`` (batch) are rejected outright.
4. **Circuit breaker.** When the supervisor is thrashing (engine
   restarts/minute above threshold) the frontend fast-fails NEW
   admissions terminally as ``error`` for a cooldown window instead of
   feeding a dying engine; existing work keeps its retry budget.

Every decision is observable: ``dl4j_tpu_slo_state`` (0/1/2),
``dl4j_tpu_slo_admitted_total{class}``,
``dl4j_tpu_slo_shed_total{class,reason}``,
``dl4j_tpu_slo_degraded_total{class}``,
``dl4j_tpu_slo_transitions_total{to}``, ``dl4j_tpu_slo_breaker_open``,
plus ``slo_state``/``slo_shed``/``slo_breaker`` JSONL events
(docs/OBSERVABILITY.md). Frontend sheds complete with the SAME terminal
taxonomy as the engine (``FINISH_REASONS``; counted once in
``dl4j_tpu_serving_evicted_total{reason}`` via
:func:`~deeplearning4j_tpu.serving.scheduler.count_terminal`).

The ``burst_arrival`` fault point (deeplearning4j_tpu/faults/) hooks
:meth:`SLOFrontend.submit`: a fire injects a burst of lowest-class
synthetic arrivals so the chaos harness can drive the ladder end-to-end
(tools/chaos.py). Goodput under overload — completed-within-deadline
tokens/sec, with vs without this frontend — is measured by
``serving/overload.py`` (``BENCH_MODEL=generate`` + ``BENCH_OVERLOAD=1``,
``tools/slo.py``, the ``slo`` gate stage).

All timing uses ``time.perf_counter`` (graftlint GL010): wall-clock jumps
must never expire a deadline or refill a bucket.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import faults, observe
from deeplearning4j_tpu.serving.scheduler import (
    GenerationRequest, GenerationResult, count_terminal)

logger = logging.getLogger(__name__)

#: The overload ladder, in escalation order. ``dl4j_tpu_slo_state`` carries
#: the index (0 = ok, 1 = degraded, 2 = shedding).
OVERLOAD_STATES = ("ok", "degraded", "shedding")
_STATE_LEVEL = {s: i for i, s in enumerate(OVERLOAD_STATES)}

#: Frontend shed reasons — the ``reason`` label on
#: ``dl4j_tpu_slo_shed_total{class,reason}``. Each maps onto ONE terminal
#: ``FINISH_REASONS`` outcome: ``circuit_open`` completes as ``error``,
#: everything else as ``shed``. ``engine_queue`` marks a request the
#: frontend admitted but the ENGINE's own ``max_queue`` gate shed —
#: counted so admitted-vs-evicted accounting never double-books it.
SHED_REASONS = ("rate_limit", "concurrency", "queue_full",
                "predicted_deadline", "shedding_state", "circuit_open",
                "engine_queue")


@dataclasses.dataclass
class ClassPolicy:
    """Admission policy for one priority class.

    ``priority`` orders the engine's pending queue (lower admits first).
    ``rate``/``burst`` arm a token bucket (None disables rate limiting);
    ``max_queued`` bounds this class's share of the pending queue;
    ``max_concurrent`` caps in-flight (queued + active) requests of the
    class; ``deadline_s`` is the class default when the caller passes
    none. ``degradable`` classes get trimmed in the ``degraded`` state;
    ``reject_in_shedding`` classes are refused outright in ``shedding``.
    ``disable_spec`` is the speculative-decoding degraded-mode knob
    (docs/SERVING.md § Speculative decoding): in the ``shedding`` state
    the class's requests decode NON-speculatively — the draft model's
    compute goes back to the drowning target — recorded on the result as
    ``GenerationResult.spec_disabled``, like the existing degraded
    fields.
    ``shared_prefix`` (token ids) is this class's shared system prompt:
    at frontend construction it is run through the engine once and PINNED
    in the radix prefix cache (docs/SERVING.md § Radix prefix cache), so
    the class's traffic admits with a prefix hit from the first request
    and eviction pressure can never drop it.
    """

    name: str
    priority: int
    rate: Optional[float] = None          # sustained requests/sec
    burst: int = 8                        # token-bucket capacity
    max_queued: Optional[int] = None      # per-class pending bound
    max_concurrent: Optional[int] = None  # in-flight cap (queued + active)
    deadline_s: Optional[float] = None    # class-default deadline
    degradable: bool = True               # ladder may trim this class
    reject_in_shedding: bool = False      # refused outright in "shedding"
    disable_spec: bool = False            # "shedding" turns speculation off
    shared_prefix: Optional[Sequence[int]] = None  # pre-warmed + pinned
    #                                     system-prompt token ids

    def __post_init__(self):
        if self.priority < 0:
            raise ValueError(f"priority must be >= 0, got {self.priority}")
        if self.rate is not None and self.rate <= 0:
            raise ValueError(f"rate must be > 0 (None disables), "
                             f"got {self.rate}")
        if self.burst < 1:
            raise ValueError(f"burst must be >= 1, got {self.burst}")


def default_classes() -> Dict[str, ClassPolicy]:
    """The three-class default ladder: ``interactive`` (never degraded,
    admitted first), ``standard``, ``batch`` (first shed, refused in
    ``shedding``, bounded queue share)."""
    return {
        "interactive": ClassPolicy("interactive", priority=0,
                                   degradable=False),
        "standard": ClassPolicy("standard", priority=1),
        "batch": ClassPolicy("batch", priority=2, max_queued=8,
                             reject_in_shedding=True),
    }


@dataclasses.dataclass
class LadderThresholds:
    """Hysteresis thresholds driving the ``ok``/``degraded``/``shedding``
    ladder. Escalation is immediate when EITHER signal crosses its enter
    threshold; de-escalation drops one level at a time and only once BOTH
    signals sit below ``exit_fraction`` of the current level's enter
    thresholds — flapping at a boundary cannot thrash the ladder."""

    degraded_queue: int = 8          # pending depth entering "degraded"
    shedding_queue: int = 16         # pending depth entering "shedding"
    degraded_p99_s: float = 0.5      # rolling decode p99 entering "degraded"
    shedding_p99_s: float = 2.0      # rolling decode p99 entering "shedding"
    exit_fraction: float = 0.5       # exit below fraction × enter threshold

    def __post_init__(self):
        if not 0.0 < self.exit_fraction < 1.0:
            raise ValueError("exit_fraction must be in (0, 1)")
        if (self.shedding_queue < self.degraded_queue
                or self.shedding_p99_s < self.degraded_p99_s):
            raise ValueError("shedding thresholds must be >= degraded ones")


class _TokenBucket:
    """Classic token bucket on an injectable monotonic clock."""

    def __init__(self, rate: float, burst: int, now: float):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._last = now

    def try_take(self, now: float) -> bool:
        self.tokens = min(self.burst,
                          self.tokens + (now - self._last) * self.rate)
        self._last = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False

    def refund(self) -> None:
        """Return a taken token (the admission was denied downstream —
        a denial must not burn rate budget)."""
        self.tokens = min(self.burst, self.tokens + 1.0)


class _RollingQuantiles:
    """Recent decode-step p50/p99 from HISTOGRAM BUCKET DELTAS.

    The registry's histograms accumulate for the process lifetime, so
    their quantiles can only rise — useless for de-escalation. This
    reader snapshots the bucket counts each poll and estimates quantiles
    over the delta (the steps decoded since the last poll), EWMA-blended
    for stability. Decay is IDLE-TIME based, not poll based: polls can be
    arbitrarily frequent (one per submit), far faster than decode steps
    complete — only a genuinely idle engine (no new samples for
    ``idle_decay_s``) drifts back toward calm, at most one decay step per
    idle window."""

    def __init__(self, hist, alpha: float = 0.5, decay: float = 0.8,
                 idle_decay_s: float = 5.0,
                 clock: Callable[[], float] = time.perf_counter):
        self._hist = hist
        self._alpha = float(alpha)
        self._decay = float(decay)
        self._idle_decay_s = float(idle_decay_s)
        self._clock = clock
        now = clock()
        self._last_sample_t = now
        self._last_decay_t = now
        with hist._lock:
            self._last = list(hist.counts)
        self.p50: Optional[float] = None
        self.p99: Optional[float] = None

    @staticmethod
    def _delta_quantile(bounds, counts, q: float) -> Optional[float]:
        total = sum(counts)
        if not total:
            return None
        rank = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c and cum + c >= rank:
                lo = bounds[i - 1] if i > 0 else 0.0
                hi = bounds[i] if i < len(bounds) else bounds[-1] * 2.0
                frac = (rank - cum) / c
                return lo + (hi - lo) * max(0.0, min(1.0, frac))
            cum += c
        return bounds[-1]

    def poll(self) -> None:
        with self._hist._lock:
            cur = list(self._hist.counts)
        delta = [a - b for a, b in zip(cur, self._last)]
        self._last = cur
        now = self._clock()
        if sum(delta) <= 0:
            # no NEW samples — decay only once the engine has been idle
            # a full window, and at most once per window
            if (now - self._last_sample_t > self._idle_decay_s
                    and now - self._last_decay_t > self._idle_decay_s):
                self._last_decay_t = now
                if self.p50 is not None:
                    self.p50 *= self._decay
                if self.p99 is not None:
                    self.p99 *= self._decay
            return
        self._last_sample_t = now
        q50 = self._delta_quantile(self._hist.bounds, delta, 0.50)
        q99 = self._delta_quantile(self._hist.bounds, delta, 0.99)
        a = self._alpha
        self.p50 = q50 if self.p50 is None else a * q50 + (1 - a) * self.p50
        self.p99 = q99 if self.p99 is None else a * q99 + (1 - a) * self.p99


class SLOFrontend:
    """SLO-driven admission wrapper around a running
    :class:`GenerativeEngine` (module docstring has the full design).

    Use::

        eng = GenerativeEngine(model, max_slots=8).start()
        fe = SLOFrontend(eng)
        fut = fe.submit(prompt, slo_class="interactive", deadline_s=0.5)
        result = fut.result()      # ALWAYS terminal — shed is a result

    Thread-safe: clients submit from any thread; all frontend state is
    guarded by one reentrant lock, and pending-queue surgery goes through
    the scheduler's own lock.
    """

    def __init__(self, engine, *,
                 classes: Optional[Dict[str, ClassPolicy]] = None,
                 thresholds: Optional[LadderThresholds] = None,
                 max_queue_total: Optional[int] = None,
                 degraded_max_new_tokens: int = 8,
                 est_tokens_per_request: float = 16.0,
                 est_decode_s: Optional[float] = None,
                 shed_margin: float = 1.0,
                 breaker_window_s: float = 60.0,
                 breaker_restarts: Optional[int] = None,
                 breaker_cooldown_s: float = 5.0,
                 burst_size: int = 4,
                 clock: Callable[[], float] = time.perf_counter):
        self.engine = engine
        self.classes = dict(classes) if classes else default_classes()
        if not self.classes:
            raise ValueError("at least one class policy is required")
        if thresholds is None:
            slots = getattr(engine.scheduler, "max_slots", 4)
            thresholds = LadderThresholds(
                degraded_queue=max(4, 2 * slots),
                shedding_queue=max(8, 4 * slots))
        self.thresholds = thresholds
        self.max_queue_total = max_queue_total
        self.degraded_max_new_tokens = int(degraded_max_new_tokens)
        self.shed_margin = float(shed_margin)
        self.breaker_window_s = float(breaker_window_s)
        if breaker_restarts is None:
            # scale to THIS engine's lifetime restart budget: a fixed
            # threshold above engine.max_restarts would be unreachable —
            # the supervisor fail_alls first and the breaker never opens
            breaker_restarts = max(2, int(getattr(engine, "max_restarts",
                                                  6)))
        self.breaker_restarts = int(breaker_restarts)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.burst_size = int(burst_size)
        self._clock = clock
        now = clock()
        self._lock = threading.RLock()
        self._buckets: Dict[str, _TokenBucket] = {
            p.name: _TokenBucket(p.rate, p.burst, now)
            for p in self.classes.values() if p.rate is not None}
        self._inflight: Dict[str, int] = {n: 0 for n in self.classes}
        # ladder state
        self.state = "ok"
        self.states_visited = {"ok"}
        self._rolling = _RollingQuantiles(
            observe.metrics().histogram(
                "dl4j_tpu_serving_decode_step_seconds"),
            clock=clock)
        # predictive-shed model state: EWMA of requested generation length
        # (seed from config), optional fixed decode-time prior for cold
        # starts (no decode samples yet -> no estimate -> no early shed)
        self._est_tokens = float(est_tokens_per_request)
        self._est_decode_s = est_decode_s
        # circuit breaker — keyed by ENGINE id (docs/ROBUSTNESS.md
        # § Cluster failure domains): behind a ClusterRouter one thrashing
        # engine must not fast-fail admissions a healthy sibling could
        # serve, so window/cooldown state is per engine and the fast-fail
        # fires only when EVERY routable engine's breaker is open. For a
        # single engine this reduces exactly to the pre-cluster behavior.
        self._seen_restarts: Dict[int, int] = {}
        self._restart_times: Dict[int, "deque[float]"] = {}
        self._breaker_open_until: Dict[int, float] = {}
        for i, eng in enumerate(self._cluster_engines()):
            eid = int(getattr(eng, "engine_id", i))
            self._seen_restarts[eid] = int(getattr(eng, "restarts", 0))
            self._restart_times[eid] = deque()
            self._breaker_open_until[eid] = -1.0
        self.breaker_opens = 0
        # burst_arrival bookkeeping: the injected synthetic arrivals'
        # futures, so harnesses can assert they too reach terminal states.
        # Bounded: a long chaos soak must not pin every burst's result
        # forever (old entries roll off; harnesses read a recent window)
        self.burst_futures: "deque[Future[GenerationResult]]" = \
            deque(maxlen=1024)
        m = observe.metrics()
        self._g_state = m.gauge("dl4j_tpu_slo_state")
        self._g_breaker = m.gauge("dl4j_tpu_slo_breaker_open")
        self._g_state.set(0.0)
        self._g_breaker.set(0.0)
        self._prewarm_shared_prefixes()

    def _prewarm_shared_prefixes(self) -> None:
        """Run each class's ``shared_prefix`` through the engine once and
        pin it in the radix prefix cache (docs/SERVING.md § Radix prefix
        cache) — per-class system prompts hit from the FIRST real
        request, and eviction can never drop them."""
        for pol in self.classes.values():
            if pol.shared_prefix is None:
                continue
            if getattr(self.engine, "prefix", None) is None:
                logger.info(
                    "class %r declares shared_prefix but the engine's "
                    "prefix cache is disabled (prefix_pages=0) — skipping "
                    "pre-warm", pol.name)
                continue
            self.engine.prewarm_prefix(pol.shared_prefix, pin=True)
            observe.log_event("prefix_prewarm", slo_class=pol.name,
                              tokens=int(np.asarray(pol.shared_prefix).size))

    # ----------------------------------------------------------------- admit
    def submit(self, prompt, *, slo_class: str = "standard",
               max_new_tokens: int = 16, temperature: float = 0.0,
               top_k: int = 0, top_p: float = 1.0,
               eos_token: Optional[int] = None,
               deadline_s: Optional[float] = None,
               max_retries: int = 1) -> "Future[GenerationResult]":
        """Admit one generation through the SLO ladder. ALWAYS returns a
        future that reaches a terminal state: admitted work flows through
        the engine; denied work completes immediately as ``shed`` (or
        ``error`` when the circuit breaker is open)."""
        policy = self.classes.get(slo_class)
        if policy is None:
            raise ValueError(f"unknown SLO class {slo_class!r}; "
                             f"known: {sorted(self.classes)}")
        if faults.should_fire("burst_arrival"):
            self._inject_burst()
        return self._admit(prompt, policy, max_new_tokens, temperature,
                           top_k, top_p, eos_token, deadline_s, max_retries)

    def _admit(self, prompt, policy: ClassPolicy, max_new_tokens: int,
               temperature: float, top_k: int, top_p: float,
               eos_token: Optional[int], deadline_s: Optional[float],
               max_retries: int) -> "Future[GenerationResult]":
        # Completing a caller-visible future (_deny / _shed_victim) runs
        # its done-callbacks synchronously on THIS thread — foreign code
        # inside our critical section if it happened under self._lock
        # (graftlock GL014: a callback that blocks on another thread
        # needing this lock deadlocks the frontend). Denial/displacement
        # completions are therefore DEFERRED until the lock is released.
        deferred: List[Callable[[], None]] = []
        try:
            with self._lock:
                # The only completer reached under the lock is
                # add_done_callback on a FRESH, not-yet-completed future —
                # it registers, never invokes, the callback; denial paths
                # defer their set_result into `deferred` below.
                # graftlock: justified(GL014): registers a cb on an incomplete future; never invokes foreign code
                return self._admit_locked(
                    prompt, policy, max_new_tokens, temperature, top_k,
                    top_p, eos_token, deadline_s, max_retries, deferred)
        finally:
            for complete in deferred:
                complete()

    def _admit_locked(self, prompt, policy: ClassPolicy,
                      max_new_tokens: int, temperature: float, top_k: int,
                      top_p: float, eos_token: Optional[int],
                      deadline_s: Optional[float], max_retries: int,
                      deferred: List[Callable[[], None]]
                      ) -> "Future[GenerationResult]":
        now = self._clock()
        p_len = int(np.asarray(prompt).size)  # honest prompt_len on
        self._update_state(now)               # denied-result metadata

        # 1. circuit breaker: a thrashing engine gets NO new work —
        #    fast-fail terminally as "error" instead of queueing into
        #    a supervisor that keeps dying. Per-engine: only when
        #    EVERY routable engine is open (a cluster with one
        #    healthy sibling keeps admitting)
        if self._breaker_open_fraction(now) >= 1.0:
            return self._deny(policy, "circuit_open", terminal="error",
                              prompt_len=p_len, deferred=deferred)

        # 2. shedding state refuses the classes configured for it
        if self.state == "shedding" and policy.reject_in_shedding:
            return self._deny(policy, "shedding_state", prompt_len=p_len,
                              deferred=deferred)

        # 3. per-class in-flight concurrency cap (queued + active)
        cap = policy.max_concurrent
        if cap is not None and self._inflight[policy.name] >= cap:
            return self._deny(policy, "concurrency", prompt_len=p_len,
                              deferred=deferred)

        # 5. effective deadline: request > class default > engine
        #    default (None = no deadline, no predictive shed)
        if deadline_s is None:
            deadline_s = policy.deadline_s
        if deadline_s is None:
            deadline_s = getattr(self.engine, "default_deadline_s", None)

        # 6. degradation ladder: trim degradable classes FIRST, so the
        #    predictive estimate below prices the trimmed answer (the
        #    degraded counter increments only on actual ADMISSION —
        #    a trimmed-then-denied request was shed, not degraded)
        degraded = False
        if self.state != "ok" and policy.degradable:
            degraded = True
            max_new_tokens = min(max_new_tokens,
                                 self.degraded_max_new_tokens)
            top_k, top_p = 0, 1.0
        # 6b. speculative-decoding degraded-mode knob: in "shedding"
        #     a disable_spec class decodes non-speculatively — the
        #     draft model's compute goes back to the target (recorded
        #     on the result like the degraded flag; the engine reads
        #     it off the request at admission)
        spec_disabled = (self.state == "shedding"
                         and policy.disable_spec)

        # 7. predictive early shed: if the estimated TTFT plus the
        #    time to decode the (possibly trimmed) answer already
        #    blows the deadline, shedding NOW costs nothing —
        #    admitting costs queue space and decode steps the SLO can
        #    never recover, and a completion that lands PAST its
        #    deadline is worth exactly as little as a shed
        if deadline_s is not None:
            est = self.estimate_ttft_s(priority=policy.priority)
            if est is not None:
                p50 = self._rolling.p50
                if p50 is None:
                    p50 = self._est_decode_s or 0.0
                est += max_new_tokens * p50
                if est > deadline_s * self.shed_margin:
                    return self._deny(policy, "predicted_deadline",
                                      prompt_len=p_len,
                                      degraded=degraded,
                                      spec_disabled=spec_disabled,
                                      deferred=deferred)

        # 8. build + validate the request NOW — an invalid submission
        #    must raise to its caller BEFORE it can burn a rate token
        #    or displace a queued victim it will never replace
        eos = (self.engine.cfg.eos_token if eos_token is None
               else eos_token)
        req = GenerationRequest(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            eos_token=eos, deadline_s=deadline_s,
            max_retries=max_retries, priority=policy.priority,
            slo_class=policy.name, degraded=degraded,
            spec_disabled=spec_disabled)
        self.engine.validate_request(req)

        # 8b. per-class token bucket — after the cheap caps and the
        #     predictive check so denials there never burn rate
        #     budget, but BEFORE the queue bounds so a rate-limited
        #     arrival cannot displace a queued victim for nothing
        bucket = self._buckets.get(policy.name)
        if bucket is not None and not bucket.try_take(now):
            return self._deny(policy, "rate_limit", prompt_len=p_len,
                              degraded=degraded,
                              spec_disabled=spec_disabled,
                              deferred=deferred)

        # 9. queue-depth bounds: per-class share first, then the total
        #    bound with shed-lowest-first — an important arrival
        #    displaces the worst queued request instead of being
        #    refused behind it. A denial here refunds the rate token.
        sched = self.engine.scheduler
        snapshot = sched.pending_snapshot()
        eff_quota = self._class_quota(policy)
        if eff_quota is not None:
            queued = sum(1 for it in snapshot
                         if it[0].slo_class == policy.name)
            if queued >= eff_quota:
                if bucket is not None:
                    bucket.refund()
                return self._deny(policy, "queue_full", prompt_len=p_len,
                                  degraded=degraded,
                                  spec_disabled=spec_disabled,
                                  deferred=deferred)
        if (self.max_queue_total is not None
                and len(snapshot) >= self.max_queue_total):
            victim = sched.steal_lowest_pending(policy.priority)
            if victim is None:
                # nothing lower-priority to displace: the arrival is
                # itself the worst — it sheds
                if bucket is not None:
                    bucket.refund()
                return self._deny(policy, "queue_full", prompt_len=p_len,
                                  degraded=degraded,
                                  spec_disabled=spec_disabled,
                                  deferred=deferred)
            self._shed_victim(victim, deferred)

        # 10. hand to the engine. Its own max_queue gate may still
        #     shed — it completes the future IMMEDIATELY and counts
        #     the terminal itself, so that case is slo_shed
        #     (engine_queue), never slo_admitted: the admitted counter
        #     means "actually queued", not "passed the frontend"
        fut = self.engine.submit_request(req)
        if fut.done():
            # the engine's gate shed it: refund the rate token (a
            # denial never burns budget) and keep the predictive
            # model untouched — nothing was actually queued
            if bucket is not None:
                bucket.refund()
            observe.metrics().counter(
                "dl4j_tpu_slo_shed_total",
                **{"class": policy.name, "reason": "engine_queue"}).inc()
            return fut
        self._est_tokens = 0.9 * self._est_tokens + 0.1 * max_new_tokens
        self._inflight[policy.name] += 1
        fut.add_done_callback(self._make_done_cb(policy.name))
        observe.metrics().counter("dl4j_tpu_slo_admitted_total",
                                  **{"class": policy.name}).inc()
        if degraded:
            observe.metrics().counter("dl4j_tpu_slo_degraded_total",
                                      **{"class": policy.name}).inc()
        return fut

    def _make_done_cb(self, cls: str):
        def _done(_fut) -> None:
            with self._lock:
                self._inflight[cls] = max(0, self._inflight[cls] - 1)
        return _done

    def _class_quota(self, policy: ClassPolicy) -> Optional[int]:
        """The class's queue bound under the CURRENT ladder state: under
        pressure the lowest classes' share shrinks first (halved in
        ``degraded``, quartered in ``shedding`` for priorities below the
        best class) — "the lowest class sheds first" even before the
        total bound engages."""
        quota = policy.max_queued
        if quota is None:
            return None
        level = _STATE_LEVEL[self.state]
        if level and policy.priority > min(
                p.priority for p in self.classes.values()):
            quota = max(1, quota // (2 ** level))
        return quota

    # ----------------------------------------------------------------- denial
    def _terminal_result(self, reason: str, cls: str, prompt_len: int = 0,
                         degraded: bool = False,
                         spec_disabled: bool = False) -> GenerationResult:
        return GenerationResult(
            tokens=np.zeros((0,), np.int32), finish_reason=reason,
            prompt_len=prompt_len, ttft_s=None, intertoken_s=[],
            slo_class=cls, degraded=degraded, spec_disabled=spec_disabled)

    def _deny(self, policy: ClassPolicy, slo_reason: str,
              terminal: str = "shed", prompt_len: int = 0,
              degraded: bool = False, spec_disabled: bool = False,
              deferred: Optional[List[Callable[[], None]]] = None
              ) -> "Future[GenerationResult]":
        """Complete a denied admission terminally (never an exception:
        overload is an expected state, and callers always get an answer).
        Counts ONCE in the slo_shed family AND once in the shared
        terminal-reason taxonomy.

        ``deferred`` is the post-lock completion list from ``_admit``:
        ``set_result`` fires done-callbacks synchronously, so completing
        here — under ``self._lock`` — would run foreign code inside the
        frontend's critical section (deadlock if it blocks on a thread
        that needs this lock)."""
        fut: "Future[GenerationResult]" = Future()
        result = self._terminal_result(
            terminal, policy.name, prompt_len=prompt_len,
            degraded=degraded, spec_disabled=spec_disabled)
        if deferred is not None:
            deferred.append(lambda: fut.set_result(result))
        else:
            fut.set_result(result)
        observe.metrics().counter(
            "dl4j_tpu_slo_shed_total",
            **{"class": policy.name, "reason": slo_reason}).inc()
        count_terminal(terminal)
        observe.log_event("slo_shed", slo_class=policy.name,
                          reason=slo_reason, state=self.state,
                          terminal=terminal)
        return fut

    def _shed_victim(self, item: Tuple,
                     deferred: Optional[List[Callable[[], None]]] = None
                     ) -> None:
        """Complete a stolen pending item (queue-bound displacement) as a
        terminal ``shed``.  Completion is deferred past lock release for
        the same reason as ``_deny`` — the victim's owner may have hung a
        done-callback on the future."""
        req, fut, _t = item
        result = self._terminal_result(
            "shed", req.slo_class, prompt_len=int(req.prompt.size),
            degraded=req.degraded)
        if deferred is not None:
            deferred.append(
                lambda: None if fut.done() else fut.set_result(result))
        elif not fut.done():
            fut.set_result(result)
        observe.metrics().counter(
            "dl4j_tpu_slo_shed_total",
            **{"class": req.slo_class, "reason": "queue_full"}).inc()
        count_terminal("shed")
        observe.log_event("slo_shed", slo_class=req.slo_class,
                          reason="queue_full", state=self.state,
                          displaced=True)

    # ------------------------------------------------------------- estimation
    def estimate_ttft_s(self, priority: Optional[int] = None
                        ) -> Optional[float]:
        """Predicted submit->first-token wall time for an arrival NOW at
        ``priority`` (None = behind the whole queue).

        Model: the slot bank serves ``max_slots`` sequences per decode
        step; a queued request waits for the busy slots plus the queued
        work that admits AHEAD of it (its own priority or better — the
        pending queue is priority-ordered) to drain, i.e. roughly
        ``(queue_ahead + busy) / max_slots`` service "waves", each lasting
        (EWMA generation length) × (rolling decode-step p50). Deliberately
        simple — the estimate only needs to be right about HOPELESS
        (order-of-magnitude-late) requests, which is what predictive
        shedding acts on. None when no decode latency signal exists yet
        (cold start: never early-shed blind)."""
        p50 = self._rolling.p50
        if p50 is None:
            p50 = self._est_decode_s
        if p50 is None or p50 <= 0:
            return None
        sched = self.engine.scheduler
        if priority is None:
            ahead = len(sched.pending)
        else:
            ahead = sum(1 for it in sched.pending_snapshot()
                        if it[0].priority <= priority)
        # busy slots are on average HALF-done — counting them as full
        # service waves would overestimate TTFT ~2× at steady state and
        # shed viable interactive work
        waves = ((ahead + 0.5 * len(sched.slots))
                 / max(1, sched.max_slots))
        return waves * self._est_tokens * p50

    # ------------------------------------------------------------ the ladder
    def _signals(self) -> Tuple[int, Optional[float]]:
        """(pending queue depth, rolling decode p99) — the two overload
        signals. Split out as a method so tests can monkeypatch it."""
        self._rolling.poll()
        return len(self.engine.scheduler.pending), self._rolling.p99

    def _update_state(self, now: float) -> None:
        """Re-evaluate the ladder. Called on every admission (there is no
        background ticker — between arrivals the gauge holds the last
        evaluated state). Escalation jumps straight to the highest matched
        level; de-escalation steps down one level per iteration but loops
        while the exit condition keeps holding, so the first arrival after
        a calm lull lands in the TRUE state instead of being needlessly
        degraded by a stale one."""
        self._update_breaker(now)
        q, p99 = self._signals()
        th = self.thresholds
        while True:
            level = _STATE_LEVEL[self.state]
            if q >= th.shedding_queue or (p99 is not None
                                          and p99 >= th.shedding_p99_s):
                target = 2
            elif q >= th.degraded_queue or (p99 is not None
                                            and p99 >= th.degraded_p99_s):
                target = max(level, 1)
            else:
                target = level
            if target == level and level > 0:
                # de-escalation: only below the hysteresis exit band of
                # the CURRENT level
                enter_q = (th.shedding_queue if level == 2
                           else th.degraded_queue)
                enter_p = (th.shedding_p99_s if level == 2
                           else th.degraded_p99_s)
                if (q <= th.exit_fraction * enter_q
                        and (p99 is None
                             or p99 <= th.exit_fraction * enter_p)):
                    target = level - 1
            if target == level:
                return
            self._transition(OVERLOAD_STATES[target], q, p99)

    def _transition(self, new_state: str, q: int,
                    p99: Optional[float]) -> None:
        old = self.state
        self.state = new_state
        self.states_visited.add(new_state)
        self._g_state.set(float(_STATE_LEVEL[new_state]))
        observe.metrics().counter("dl4j_tpu_slo_transitions_total",
                                  to=new_state).inc()
        observe.log_event("slo_state", from_state=old, to_state=new_state,
                          queue_depth=q,
                          decode_p99_ms=None if p99 is None
                          else round(p99 * 1e3, 3))
        logger.info("SLO state %s -> %s (queue=%d, rolling decode p99=%s)",
                    old, new_state, q,
                    "n/a" if p99 is None else f"{p99 * 1e3:.1f}ms")

    # ------------------------------------------------------- circuit breaker
    def _cluster_engines(self) -> list:
        """The engines the breaker watches: a ClusterRouter's LIVE
        members (a dead engine can never restart again — its stale
        window must not veto the all-open fast-fail), the router's full
        list when nothing is live, or the single engine itself."""
        live = getattr(self.engine, "live_engines", None)
        if callable(live):
            engs = live()
            if engs:
                return list(engs)
        engs = getattr(self.engine, "engines", None)
        return list(engs) if engs else [self.engine]

    def _breaker_open_fraction(self, now: float) -> float:
        engs = self._cluster_engines()
        n_open = sum(
            1 for i, e in enumerate(engs)
            if now < self._breaker_open_until.get(
                int(getattr(e, "engine_id", i)), -1.0))
        return n_open / max(1, len(engs))

    def _update_breaker(self, now: float) -> None:
        for i, eng in enumerate(self._cluster_engines()):
            eid = int(getattr(eng, "engine_id", i))
            times = self._restart_times.setdefault(eid, deque())
            cur = int(getattr(eng, "restarts", 0))
            seen = self._seen_restarts.setdefault(eid, cur)
            if cur > seen:
                times.extend([now] * (cur - seen))
            self._seen_restarts[eid] = cur
            while times and now - times[0] > self.breaker_window_s:
                times.popleft()
            was_open = now < self._breaker_open_until.get(eid, -1.0)
            if not was_open and len(times) >= self.breaker_restarts:
                self._breaker_open_until[eid] = now + self.breaker_cooldown_s
                self.breaker_opens += 1
                # consume the window: the breaker re-opens only on NEW
                # restarts after the cooldown, not on the same thrash burst
                times.clear()
                observe.log_event(
                    "slo_breaker", action="open", engine=eid,
                    restarts_in_window=self.breaker_restarts,
                    cooldown_s=self.breaker_cooldown_s)
                logger.warning(
                    "SLO circuit breaker OPEN for engine %d: %d restarts "
                    "inside %.0fs — fast-failing admissions for %.1fs",
                    eid, self.breaker_restarts, self.breaker_window_s,
                    self.breaker_cooldown_s)
        # the gauge reports the open FRACTION (1.0 == full fast-fail);
        # single-engine keeps the historical 0.0/1.0 values
        self._g_breaker.set(self._breaker_open_fraction(now))

    @property
    def breaker_open(self) -> bool:
        """True when admissions fast-fail: EVERY routable engine's
        breaker is open (the single-engine degenerate case is unchanged)."""
        return self._breaker_open_fraction(self._clock()) >= 1.0

    # ---------------------------------------------------------- chaos: burst
    def _inject_burst(self) -> None:
        """``burst_arrival`` fault hook: flood the admission path with
        ``burst_size`` synthetic arrivals of the LOWEST class — the chaos
        harness's way of driving the ladder without a client fleet. The
        synthetic futures go through normal admission (they may shed) and
        are retained in :attr:`burst_futures` so every injected request is
        still provably terminal."""
        lowest = max(self.classes.values(), key=lambda p: p.priority)
        vocab = int(self.engine.cfg.vocab_size)
        prompt = np.asarray([1 % vocab, 2 % vocab], np.int32)
        for _ in range(self.burst_size):
            fut = self._admit(prompt, lowest,
                              max_new_tokens=max(1, int(self._est_tokens)),
                              temperature=0.0, top_k=0, top_p=1.0,
                              eos_token=-1, deadline_s=None, max_retries=0)
            self.burst_futures.append(fut)
        observe.log_event("slo_burst_injected", size=self.burst_size,
                          slo_class=lowest.name)

    # -------------------------------------------------------------- snapshot
    def snapshot(self) -> Dict[str, object]:
        """Compact state dump (harnesses, debugging)."""
        with self._lock:
            return {
                "state": self.state,
                "states_visited": sorted(self.states_visited),
                "breaker_open": self.breaker_open,
                "breaker_opens": self.breaker_opens,
                "inflight": dict(self._inflight),
                "est_tokens_per_request": round(self._est_tokens, 2),
                "rolling_decode_p50_ms": None if self._rolling.p50 is None
                else round(self._rolling.p50 * 1e3, 3),
                "rolling_decode_p99_ms": None if self._rolling.p99 is None
                else round(self._rolling.p99 * 1e3, 3),
                "burst_requests": len(self.burst_futures),
            }
