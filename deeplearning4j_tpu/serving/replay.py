"""Serving traffic replay — the prefix-cache AND speculative-decoding
acceptance harnesses.

:func:`run_prefix_replay` (``BENCH_MODEL=generate BENCH_PREFIX=1`` in
bench.py, ``tools/prefix.py`` / the ``prefix`` gate stage, and the
prefix tests) drives a fresh :class:`GenerativeEngine` with the traffic
shape the radix prefix cache exists for — a handful of shared "system
prompts" each followed by a short unique tail — and measures what the
cache buys:

* **TTFT** (submit -> first token): with the cache, admission prefills
  only the suffix (``suffix_bucket`` tokens against the cached prefix)
  instead of the whole ``max_prompt`` bucket — the p50 should drop hard;
* **hit accounting**: ``GenerationResult.prefix_hit_tokens`` per request
  plus the ``dl4j_tpu_prefix_*`` counters;
* **correctness**: both legs run GREEDY, so the caller can assert the
  cache-on outputs are token-for-token identical to cache-off;
* **compile-once**: the RecompileLedger must show ZERO ``new_shape``
  serving events — prefix hits ride a fourth compiled function, they
  never change a jit signature.

Requests run CLOSED-LOOP, one at a time on an inline engine (no worker
thread): TTFT then measures prefill service time, not queueing — the
queueing story under load belongs to ``serving/overload.py``. The warm
rounds populate the tree AND compile every path (full prefill, suffix
prefill, decode) on both legs, so the timed window is compile-free.

The default model is deliberately bigger than ``GptConfig.tiny`` (hidden
256, 4 layers): the TTFT comparison must be dominated by prefill compute,
not by per-call dispatch overhead, to be meaningful on a CPU host.

:func:`run_spec_replay` is the speculative-decoding sibling
(``BENCH_SPEC=1``, ``tools/spec.py`` / the ``spec`` gate stage,
tests/test_speculative.py): the SAME greedy request plan run spec-on and
spec-off, measuring decode tokens/sec. Like the slo gate it is a
MECHANISM bench, not a kernel bench: both legs arm the deterministic
50ms ``slow_decode`` floor (one fire per engine step, i.e. per TARGET
forward), standing in for the big model's memory-bound step time, while
the draft's real compute rides on top — so "K accepted tokens amortize
one target step" is measured against a reproducible service-time model
instead of host-scheduling jitter. The default draft is
:func:`~deeplearning4j_tpu.serving.speculative.perturbed_draft` (the
target's params plus seeded noise — a deterministic distillation
stand-in with high-but-not-total greedy agreement, so both accepts and
rejections are exercised); pass ``draft_model`` to measure a real one.
Outputs must be bit-identical across the legs — losslessness is part of
the contract, asserted by every consumer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

# one definition of "a serving recompile" for every gate harness
from deeplearning4j_tpu.serving.overload import _serving_new_shape_count


def _pct(sorted_xs: List[float], q: float) -> Optional[float]:
    if not sorted_xs:
        return None
    return sorted_xs[min(len(sorted_xs) - 1, int(q * len(sorted_xs)))]


def run_prefix_replay(*, prefix_on: bool, n_requests: int = 12,
                      n_prefixes: int = 3, sys_len: int = 88,
                      tail_max: int = 5, gen_tokens: int = 4,
                      max_slots: int = 2, seed: int = 0, vocab: int = 512,
                      max_prompt: int = 96, page_size: int = 8,
                      suffix_bucket: int = 16,
                      prefix_pages: Optional[int] = None,
                      warm_rounds: int = 2,
                      model=None) -> Dict[str, Any]:
    """One replay leg on a fresh engine. Identical ``seed`` on both legs
    yields an identical request plan, so outputs are comparable
    token-for-token. Returns TTFT percentiles, per-request outputs, hit
    accounting, and the serving ``new_shape`` delta."""
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine

    if model is None:
        cfg = GptConfig(vocab_size=vocab, hidden=256, layers=4, heads=8,
                        intermediate=1024, max_position=2 * max_prompt,
                        eos_token=0)
        model = GptModel(cfg, seed=0)
    cfg = model.cfg
    if sys_len + tail_max > max_prompt:
        raise ValueError("sys_len + tail_max must fit the max_prompt bucket")
    pages_per_seq = -(-(max_prompt + gen_tokens + 1) // page_size) + 1
    if prefix_pages is None:
        # budget: every shared prefix fully resident plus a few tails
        prefix_pages = n_prefixes * (-(-max_prompt // page_size))
    num_pages = max_slots * pages_per_seq + (prefix_pages if prefix_on
                                             else 0)
    eng = GenerativeEngine(
        model, max_slots=max_slots, page_size=page_size,
        num_pages=num_pages, max_pages_per_seq=pages_per_seq,
        max_prompt=max_prompt, seed=0,
        prefix_pages=prefix_pages if prefix_on else 0,
        suffix_bucket=suffix_bucket)
    new_shape_before = _serving_new_shape_count()

    r = np.random.RandomState(seed)
    prefixes = [r.randint(1, cfg.vocab_size, size=sys_len).astype(np.int32)
                for _ in range(n_prefixes)]
    plan = []
    for _ in range(n_requests):
        pfx = prefixes[int(r.randint(n_prefixes))]
        tail = r.randint(1, cfg.vocab_size,
                         size=int(r.randint(1, tail_max + 1))) \
            .astype(np.int32)
        plan.append(np.concatenate([pfx, tail]))

    def run_one(prompt):
        fut = eng.submit(prompt, max_new_tokens=gen_tokens, eos_token=-1)
        while eng.scheduler.has_work():
            eng.step()
        return fut.result(timeout=0)

    # warm: round 0 inserts each shared prefix; round 1 HITS it on the
    # cache-on leg, compiling the suffix-prefill path — so the timed
    # window below pays zero XLA compiles on either leg
    for round_ in range(warm_rounds):
        for pfx in prefixes:
            run_one(np.concatenate(
                [pfx, np.asarray([1 + round_], np.int32)]))

    results = [run_one(p) for p in plan]

    ttfts = sorted(res.ttft_s for res in results if res.ttft_s is not None)
    hit_tokens = sum(res.prefix_hit_tokens for res in results)
    reasons: Dict[str, int] = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    out: Dict[str, Any] = {
        "prefix_on": prefix_on,
        "requests": n_requests,
        "outputs": [res.tokens.tolist() for res in results],
        "prompts": [p.tolist() for p in plan],
        "reasons": dict(sorted(reasons.items())),
        "all_terminal": all(res.finish_reason in ("eos", "length")
                            for res in results),
        "ttft_p50_ms": round(_pct(ttfts, 0.50) * 1e3, 3) if ttfts else None,
        "ttft_p99_ms": round(_pct(ttfts, 0.99) * 1e3, 3) if ttfts else None,
        "prefix_hit_tokens": int(hit_tokens),
        "hit_requests": sum(1 for res in results
                            if res.prefix_hit_tokens > 0),
        "new_shape_events": max(
            0, _serving_new_shape_count() - new_shape_before),
    }
    if prefix_on and eng.prefix is not None:
        eng.check_invariants()
        out["tree_pages"] = eng.prefix.tree_pages
        out["pinned_pages"] = eng.prefix.pinned_pages
    return out


def _serving_first_compile_keys(before: int) -> List[str]:
    """The serving-graph ``first_compile`` ledger keys recorded after
    event index ``before`` — the gate's "exactly the expected compiled
    functions" evidence."""
    from deeplearning4j_tpu import observe

    evs = observe.ledger().events()
    return sorted(e.key for e in evs[before:]
                  if e.graph == "serving" and e.cause == "first_compile")


def _serving_cache_hit_keys(before: int) -> List[str]:
    """The serving-graph ``cache_hit`` ledger keys after event index
    ``before`` — the AOT warm-boot gate's "restored, not recompiled"
    evidence (deduplicated: polymorphic fns record one hit per
    signature)."""
    from deeplearning4j_tpu import observe

    evs = observe.ledger().events()
    return sorted({e.key for e in evs[before:]
                   if e.graph == "serving" and e.cause == "cache_hit"})


def run_spec_replay(*, spec_on: bool, n_requests: int = 6,
                    prompt_len: int = 10, gen_tokens: int = 12,
                    spec_k: int = 4, max_slots: int = 2, seed: int = 0,
                    vocab: int = 512, page_size: int = 8,
                    max_prompt: int = 16, draft_model=None,
                    draft_noise: float = 1e-2, slow_decode: bool = True,
                    warm_rounds: int = 2, model=None) -> Dict[str, Any]:
    """One speculative-decoding replay leg on a fresh engine (module
    docstring has the measurement model). Identical ``seed`` on both
    legs yields an identical greedy request plan, so outputs are
    comparable token-for-token. Returns decode tokens/sec over the timed
    window, per-request outputs, proposal/acceptance accounting, the
    serving ``new_shape`` delta, and the leg's ``first_compile`` key
    set."""
    from deeplearning4j_tpu import faults, observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.speculative import perturbed_draft

    if model is None:
        cfg = GptConfig.tiny(vocab_size=vocab,
                             max_position=4 * max_prompt)
        model = GptModel(cfg, seed=0)
    cfg = model.cfg
    if spec_on and draft_model is None:
        draft_model = perturbed_draft(model, scale=draft_noise, seed=1)
    pages_per_seq = -(-(max_prompt + gen_tokens + spec_k + 1)
                      // page_size) + 1
    eng = GenerativeEngine(
        model, max_slots=max_slots, page_size=page_size,
        max_pages_per_seq=pages_per_seq, max_prompt=max_prompt, seed=0,
        spec_k=spec_k if spec_on else 0,
        draft_model=draft_model if spec_on else None)
    led_before = len(observe.ledger().events())
    new_shape_before = _serving_new_shape_count()

    r = np.random.RandomState(seed)
    plan = [r.randint(1, cfg.vocab_size, size=prompt_len).astype(np.int32)
            for _ in range(n_requests)]

    def run_one(prompt):
        fut = eng.submit(prompt, max_new_tokens=gen_tokens, eos_token=-1)
        while eng.scheduler.has_work():
            eng.step()
        return fut.result(timeout=0)

    # warm: compile every path on this leg (prefill + decode or
    # prefill + draft_prefill + draft_decode + verify) OUTSIDE the timed
    # window, floor unarmed — the window below measures steps, not XLA
    for round_ in range(warm_rounds):
        run_one(r.randint(1, cfg.vocab_size,
                          size=prompt_len).astype(np.int32))

    if slow_decode:
        # the deterministic per-target-step service floor (one fire per
        # engine step — docs/SERVING.md § Speculative decoding)
        faults.arm("slow_decode", prob=1.0, seed=0)
    try:
        t0 = time.perf_counter()
        results = [run_one(p) for p in plan]
        wall = time.perf_counter() - t0
    finally:
        if slow_decode:
            faults.disarm("slow_decode")

    eng.check_invariants()
    n_tokens = sum(len(res.tokens) for res in results)
    proposed = sum(res.spec_proposed_tokens for res in results)
    accepted = sum(res.spec_accepted_tokens for res in results)
    reasons: Dict[str, int] = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    return {
        "spec_on": spec_on,
        "spec_k": spec_k if spec_on else 0,
        "requests": n_requests,
        "outputs": [res.tokens.tolist() for res in results],
        "prompts": [p.tolist() for p in plan],
        "reasons": dict(sorted(reasons.items())),
        "all_terminal": all(res.finish_reason in ("eos", "length")
                            for res in results),
        "generated_tokens": int(n_tokens),
        "tokens_per_sec": round(n_tokens / wall, 3) if wall else None,
        "wall_s": round(wall, 3),
        "proposed_tokens": int(proposed),
        "accepted_tokens": int(accepted),
        "acceptance_rate": round(accepted / proposed, 4) if proposed
        else None,
        "new_shape_events": max(
            0, _serving_new_shape_count() - new_shape_before),
        "first_compile_keys": _serving_first_compile_keys(led_before),
    }


def run_randomized_replay(*, n_requests: int = 16, seed: int = 0,
                          vocab: int = 256, max_prompt: int = 32,
                          page_size: int = 8, suffix_bucket: int = 8,
                          gen_max: int = 6, spec_k: int = 3,
                          max_slots: int = 2, n_prefixes: int = 2,
                          draft_noise: float = 1e-2,
                          model=None) -> Dict[str, Any]:
    """Shape-DIVERSE replay — the graftshape cross-validation workload
    (``BENCH_MODEL=generate BENCH_RANDOM_SHAPES=1`` in bench.py, and the
    serving leg of ``tools/shapetrace.py`` / the ``shapetrace`` gate
    stage).

    Where :func:`run_prefix_replay` fixes the traffic shape to measure
    the cache, this leg does the opposite: prompt lengths are drawn from
    the FULL ``1..max_prompt`` range (deliberately straddling page and
    ``suffix_bucket`` boundaries), generation lengths vary per request,
    and a fraction of requests share one of ``n_prefixes`` system
    prompts so both the full-prefill and suffix-prefill paths fire —
    with the prefix cache AND speculative decoding armed at once.  The
    engine's bucketing contract says none of that diversity may reach a
    jit signature: the ledger must show only ``first_compile`` events,
    ZERO serving ``new_shape``.  That is the assertion this function
    exists to feed (the caller makes it — this function only reports).
    """
    from deeplearning4j_tpu import observe
    from deeplearning4j_tpu.models.gpt import GptConfig, GptModel
    from deeplearning4j_tpu.serving import GenerativeEngine
    from deeplearning4j_tpu.serving.speculative import perturbed_draft

    if model is None:
        cfg = GptConfig.tiny(vocab_size=vocab,
                             max_position=4 * max_prompt)
        model = GptModel(cfg, seed=0)
    cfg = model.cfg
    draft_model = perturbed_draft(model, scale=draft_noise, seed=1)
    pages_per_seq = -(-(max_prompt + gen_max + spec_k + 1)
                      // page_size) + 1
    prefix_pages = n_prefixes * (-(-max_prompt // page_size))
    # boot covers engine construction INCLUDING the AOT warm boot when
    # $DL4J_TPU_COMPILE_CACHE is set (serving/aot.py) — the cold-restart
    # TTFT the aot gate compares is boot_s + first-request TTFT
    t_boot = time.perf_counter()
    eng = GenerativeEngine(
        model, max_slots=max_slots, page_size=page_size,
        num_pages=max_slots * pages_per_seq + prefix_pages,
        max_pages_per_seq=pages_per_seq, max_prompt=max_prompt, seed=0,
        prefix_pages=prefix_pages, suffix_bucket=suffix_bucket,
        spec_k=spec_k, draft_model=draft_model)
    boot_s = time.perf_counter() - t_boot
    led_before = len(observe.ledger().events())
    new_shape_before = _serving_new_shape_count()

    r = np.random.RandomState(seed)
    # shared system prompts sized to cross a page boundary, so prefix
    # hits exercise the suffix-prefill path too
    pfx_len = max(page_size + 1, max_prompt // 2)
    prefixes = [r.randint(1, cfg.vocab_size, size=pfx_len).astype(np.int32)
                for _ in range(n_prefixes)]
    plan = []
    for i in range(n_requests):
        if i % 3 == 0 and n_prefixes:
            # shared-prefix request with a ragged unique tail
            pfx = prefixes[int(r.randint(n_prefixes))]
            tail_max = max(1, max_prompt - pfx_len)
            tail = r.randint(1, cfg.vocab_size,
                             size=int(r.randint(1, tail_max + 1))) \
                .astype(np.int32)
            plan.append(np.concatenate([pfx, tail]))
        else:
            # fully random length across the whole admissible range
            plen = int(r.randint(1, max_prompt + 1))
            plan.append(r.randint(1, cfg.vocab_size,
                                  size=plen).astype(np.int32))
    gens = [int(r.randint(1, gen_max + 1)) for _ in range(n_requests)]

    def run_one(prompt, n_gen):
        fut = eng.submit(prompt, max_new_tokens=n_gen, eos_token=-1)
        while eng.scheduler.has_work():
            eng.step()
        return fut.result(timeout=0)

    results = [run_one(p, g) for p, g in zip(plan, gens)]
    eng.check_invariants()

    reasons: Dict[str, int] = {}
    for res in results:
        reasons[res.finish_reason] = reasons.get(res.finish_reason, 0) + 1
    return {
        "requests": n_requests,
        "outputs": [res.tokens.tolist() for res in results],
        "prompt_lens": sorted({len(p) for p in plan}),
        "gen_lens": sorted(set(gens)),
        "reasons": dict(sorted(reasons.items())),
        "all_terminal": all(res.finish_reason in ("eos", "length")
                            for res in results),
        "generated_tokens": int(sum(len(res.tokens) for res in results)),
        "prefix_hit_tokens": int(sum(res.prefix_hit_tokens
                                     for res in results)),
        "new_shape_events": max(
            0, _serving_new_shape_count() - new_shape_before),
        "first_compile_keys": _serving_first_compile_keys(led_before),
        "cache_hit_keys": _serving_cache_hit_keys(led_before),
        "boot_s": round(boot_s, 4),
        "ttft_first_ms": (round(results[0].ttft_s * 1e3, 3)
                          if results and results[0].ttft_s is not None
                          else None),
    }
