"""Block-paged KV cache — the PagedAttention memory model (SOSP '23).

Generative serving cannot pre-reserve ``max_slots × max_context`` of KV
memory per sequence: real prompts/outputs vary by two orders of magnitude
and the reserved-but-unused tail is the memory that would have held more
concurrent sequences. The vLLM answer, reproduced here:

* KV storage is ONE device array of fixed-size **pages**
  ``(layers, 2, num_pages + 1, page_size, heads, head_dim)`` allocated once
  at server start — decode steps never reallocate device memory and their
  jit signature never changes (the compile-once property
  ``tests/test_serving.py`` asserts through the RecompileLedger).
* Each sequence owns an ordered list of pages recorded in a **page table**
  row ``(max_slots, max_pages_per_seq)``; logical token position ``t`` lives
  at ``(page_table[slot, t // page_size], t % page_size)``.
* A host-side **free list** hands out pages at admit/growth and takes them
  back at evict — allocation is O(1) list ops between decode iterations,
  never device work.

The LAST page (index ``num_pages``) is the **trash page**: inactive slots'
decode writes and unallocated page-table entries point at it, so the fully
vectorized decode step needs no scatter masking — garbage lands where
nothing ever reads it (attention masks positions ``>= seq_len``).

Invariants (exercised by tests/test_serving.py):
  * every page is either in the free list or owned by exactly one slot;
  * ``len(free) + sum(owned) == num_pages`` at all times;
  * a freed slot's page-table row points wholly at the trash page.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults


class PagedKVCache:
    """Fixed-pool paged KV storage + free-list allocator (host-side
    bookkeeping, device-side ``kv`` array threaded through the jitted
    decode step functionally)."""

    def __init__(self, *, layers: int, heads: int, head_dim: int,
                 page_size: int = 16, num_pages: int = 64,
                 max_slots: int = 4, max_pages_per_seq: int = 8,
                 dtype=jnp.float32):
        if page_size <= 0 or num_pages <= 0:
            raise ValueError("page_size and num_pages must be positive")
        self.layers = layers
        self.heads = heads
        self.head_dim = head_dim
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_slots = int(max_slots)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.trash_page = self.num_pages
        # +1: the trash page — see module docstring
        self._kv_shape = (layers, 2, self.num_pages + 1, self.page_size,
                          heads, head_dim)
        self._kv_dtype = dtype
        self.kv = jnp.zeros(self._kv_shape, self._kv_dtype)
        self.free: List[int] = list(range(self.num_pages))
        self.page_table = np.full((self.max_slots, self.max_pages_per_seq),
                                  self.trash_page, np.int32)
        self.seq_lens = np.zeros((self.max_slots,), np.int32)
        self.owned: List[List[int]] = [[] for _ in range(self.max_slots)]

    # ----------------------------------------------------------- accounting
    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` tokens."""
        return -(-int(n_tokens) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self.free)

    def used_pages(self) -> int:
        return sum(len(o) for o in self.owned)

    def max_context(self) -> int:
        """Longest sequence one slot can hold."""
        return self.max_pages_per_seq * self.page_size

    # ----------------------------------------------------------- allocation
    def ensure_capacity(self, slot: int, n_tokens: int) -> str:
        """Grow ``slot``'s page list to cover ``n_tokens`` tokens.

        Returns ``"ok"`` on success, ``"overflow"`` when the sequence would
        exceed its page-table row (evict: the sequence is at max context),
        ``"oom"`` when the free list is exhausted (evict: pool pressure).
        Partial growth never happens — the slot's pages are untouched on
        either failure."""
        need = self.pages_for(n_tokens)
        have = len(self.owned[slot])
        if need <= have:
            return "ok"
        if faults.should_fire("page_oom"):
            # injected pool pressure: report exhaustion WITHOUT touching
            # the slot's pages — identical contract to the real oom arm
            return "oom"
        if need > self.max_pages_per_seq:
            return "overflow"
        if need - have > len(self.free):
            return "oom"
        for i in range(have, need):
            page = self.free.pop()
            self.owned[slot].append(page)
            self.page_table[slot, i] = page
        return "ok"

    def free_slot(self, slot: int) -> int:
        """Return ``slot``'s pages to the free list; reset its row to the
        trash page. Returns the number of pages released."""
        released = len(self.owned[slot])
        self.free.extend(self.owned[slot])
        self.owned[slot] = []
        self.page_table[slot, :] = self.trash_page
        self.seq_lens[slot] = 0
        return released

    def reset_kv(self) -> None:
        """Reallocate the device page pool (supervised crash recovery): a
        decode step that died mid-call may have consumed the DONATED kv
        buffer, leaving ``self.kv`` pointing at deleted device memory.
        Shape and dtype are unchanged, so the engine's cached jit
        signatures stay valid — recovery never recompiles. Host-side page
        accounting is untouched; the caller frees/retries slots."""
        self.kv = jnp.zeros(self._kv_shape, self._kv_dtype)

    def check_invariants(self) -> None:
        """Allocator soundness (test hook): partition property + table/owned
        agreement. Raises AssertionError on violation."""
        all_pages = sorted(self.free + [p for o in self.owned for p in o])
        assert all_pages == list(range(self.num_pages)), (
            f"page pool corrupt: free={sorted(self.free)} "
            f"owned={self.owned}")
        for slot, pages in enumerate(self.owned):
            row = self.page_table[slot]
            assert list(row[:len(pages)]) == pages, (
                f"slot {slot} page-table row {row} disagrees with owned "
                f"{pages}")
            assert all(int(p) == self.trash_page
                       for p in row[len(pages):]), (
                f"slot {slot} has stale table entries past its pages: {row}")
            assert self.seq_lens[slot] <= len(pages) * self.page_size
