"""Block-paged KV cache — the PagedAttention memory model (SOSP '23).

Generative serving cannot pre-reserve ``max_slots × max_context`` of KV
memory per sequence: real prompts/outputs vary by two orders of magnitude
and the reserved-but-unused tail is the memory that would have held more
concurrent sequences. The vLLM answer, reproduced here:

* KV storage is ONE device array of fixed-size **pages**
  ``(layers, 2, num_pages + 1, page_size, heads, head_dim)`` allocated once
  at server start — decode steps never reallocate device memory and their
  jit signature never changes (the compile-once property
  ``tests/test_serving.py`` asserts through the RecompileLedger).
* Each sequence owns an ordered list of pages recorded in a **page table**
  row ``(max_slots, max_pages_per_seq)``; logical token position ``t`` lives
  at ``(page_table[slot, t // page_size], t % page_size)``.
* A host-side **free list** hands out pages at admit/growth and takes them
  back at evict — allocation is O(1) list ops between decode iterations,
  never device work.

Since the radix prefix cache (``serving/prefix.py``, docs/SERVING.md
§ Radix prefix cache) pages are **refcounted**: a page may be mapped into
several slots' page-table rows at once (shared system-prompt KV) and/or
pinned by the prefix tree, so "owned by exactly one slot" became "held by
``refcount`` holders"; a page returns to the free list only when the last
holder releases it. Writes into shared pages are forbidden by construction
— the engine's admission path **copies** a partially-filled tail page
before a slot may write into it (:meth:`cow_page`, the copy-on-write rule).

The LAST page (index ``num_pages``) is the **trash page**: inactive slots'
decode writes and unallocated page-table entries point at it, so the fully
vectorized decode step needs no scatter masking — garbage lands where
nothing ever reads it (attention masks positions ``>= seq_len``).

Invariants (exercised by tests/test_serving.py + tests/test_prefix.py):
  * every page is either in the free list XOR has ``refcount >= 1``;
  * ``len(free) + |{p : refcount(p) > 0}| == num_pages`` at all times;
  * ``refcount(p) == (#slot rows mapping p) + (#prefix-tree refs on p)``;
  * a freed slot's page-table row points wholly at the trash page.
"""

from __future__ import annotations

import functools
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu import faults, observe


class PagedKVCache:
    """Fixed-pool paged KV storage + refcounted free-list allocator
    (host-side bookkeeping, device-side ``kv`` array threaded through the
    jitted decode step functionally)."""

    def __init__(self, *, layers: int, heads: int, head_dim: int,
                 page_size: int = 16, num_pages: int = 64,
                 max_slots: int = 4, max_pages_per_seq: int = 8,
                 dtype=jnp.float32):
        if page_size <= 0 or num_pages <= 0:
            raise ValueError("page_size and num_pages must be positive")
        self.layers = layers
        self.heads = heads
        self.head_dim = head_dim
        self.page_size = int(page_size)
        self.num_pages = int(num_pages)
        self.max_slots = int(max_slots)
        self.max_pages_per_seq = int(max_pages_per_seq)
        self.trash_page = self.num_pages
        # +1: the trash page — see module docstring
        self._kv_shape = (layers, 2, self.num_pages + 1, self.page_size,
                          heads, head_dim)
        self._kv_dtype = dtype
        self.kv = jnp.zeros(self._kv_shape, self._kv_dtype)
        self.free: List[int] = list(range(self.num_pages))
        self.refcount: List[int] = [0] * self.num_pages
        self.page_table = np.full((self.max_slots, self.max_pages_per_seq),
                                  self.trash_page, np.int32)
        self.seq_lens = np.zeros((self.max_slots,), np.int32)
        self.owned: List[List[int]] = [[] for _ in range(self.max_slots)]
        self._copy_fn = None

    # ----------------------------------------------------------- accounting
    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` tokens."""
        return -(-int(n_tokens) // self.page_size)

    @property
    def free_pages(self) -> int:
        return len(self.free)

    def used_pages(self) -> int:
        return sum(len(o) for o in self.owned)

    def max_context(self) -> int:
        """Longest sequence one slot can hold."""
        return self.max_pages_per_seq * self.page_size

    # ------------------------------------------------------------- refcounts
    def alloc_page(self) -> Optional[int]:
        """Pop a page off the free list with ``refcount == 1``. None when
        the pool is exhausted (callers translate to their oom arm)."""
        if not self.free:
            return None
        page = self.free.pop()
        self.refcount[page] = 1
        return page

    def retain(self, page: int) -> None:
        """Add one reference to a LIVE page (a prefix-tree insert, or a
        slot mapping a shared page). Retaining a free page is a bug — it
        would hand the same page to two unrelated holders."""
        if self.refcount[page] <= 0:
            raise AssertionError(
                f"retain of page {page} with refcount "
                f"{self.refcount[page]} (page is on the free list)")
        self.refcount[page] += 1

    def release(self, page: int) -> None:
        """Drop one reference; the page returns to the free list only at
        refcount zero — the exactly-once property under sharing."""
        if self.refcount[page] <= 0:
            raise AssertionError(
                f"release of page {page} with refcount "
                f"{self.refcount[page]} (double free)")
        self.refcount[page] -= 1
        if self.refcount[page] == 0:
            self.free.append(page)

    def map_shared(self, slot: int, page: int) -> None:
        """Map an already-live page (a prefix-cache hit) into ``slot``'s
        next page-table position, taking a reference. The slot must never
        WRITE into a shared page — the engine CoWs the partial tail first."""
        self.retain(page)
        idx = len(self.owned[slot])
        self.owned[slot].append(page)
        self.page_table[slot, idx] = page

    def cow_page(self, slot: int, src: int) -> Optional[int]:
        """Copy-on-write: allocate a fresh page, device-copy ``src`` into
        it, and map it into ``slot``'s next page-table position. Returns
        the new page id, or None when the pool is exhausted (the caller
        unwinds the admission). The copy is ONE jitted device op whose
        signature depends only on the kv geometry — compile once."""
        dst = self.alloc_page()
        if dst is None:
            return None
        idx = len(self.owned[slot])
        self.owned[slot].append(dst)
        self.page_table[slot, idx] = dst
        if self._copy_fn is None:
            self._copy_fn = self._build_copy()
        observe.note_jit_signature(
            self._copy_fn, graph="serving", key="copy_page",
            signature=observe.signature_of(shape=self._kv_shape))
        self.kv = self._copy_fn(self.kv, jnp.asarray(src, jnp.int32),
                                jnp.asarray(dst, jnp.int32))
        return dst

    def _build_copy(self):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def copy_page(kv_pages, src, dst):
            return kv_pages.at[:, :, dst].set(kv_pages[:, :, src])

        return copy_page

    # ----------------------------------------------------------- allocation
    def ensure_capacity(self, slot: int, n_tokens: int) -> str:
        """Grow ``slot``'s page list to cover ``n_tokens`` tokens.

        Returns ``"ok"`` on success, ``"overflow"`` when the sequence would
        exceed its page-table row (evict: the sequence is at max context),
        ``"oom"`` when the free list is exhausted (evict: pool pressure).
        Partial growth never happens — the slot's pages are untouched on
        either failure."""
        need = self.pages_for(n_tokens)
        have = len(self.owned[slot])
        if need <= have:
            return "ok"
        if faults.should_fire("page_oom"):
            # injected pool pressure: report exhaustion WITHOUT touching
            # the slot's pages — identical contract to the real oom arm
            return "oom"
        if need > self.max_pages_per_seq:
            return "overflow"
        if need - have > len(self.free):
            return "oom"
        for i in range(have, need):
            page = self.alloc_page()
            self.owned[slot].append(page)
            self.page_table[slot, i] = page
        return "ok"

    def free_slot(self, slot: int) -> int:
        """Release ``slot``'s references; reset its row to the trash page.
        Under sharing a page only returns to the free list when its LAST
        holder (another slot, or the prefix tree) releases it — each
        holder releases exactly once, so a page can never enter the free
        list twice. Returns the number of page references released."""
        released = len(self.owned[slot])
        for page in self.owned[slot]:
            self.release(page)
        self.owned[slot] = []
        self.page_table[slot, :] = self.trash_page
        self.seq_lens[slot] = 0
        return released

    def reset_kv(self) -> None:
        """Reallocate the device page pool (supervised crash recovery): a
        decode step that died mid-call may have consumed the DONATED kv
        buffer, leaving ``self.kv`` pointing at deleted device memory.
        Shape and dtype are unchanged, so the engine's cached jit
        signatures stay valid — recovery never recompiles. Host-side page
        accounting is untouched; the caller frees/retries slots (and drops
        the prefix tree — its cached KV died with the buffer)."""
        self.kv = jnp.zeros(self._kv_shape, self._kv_dtype)

    def check_invariants(self, tree_refs=None) -> None:
        """Allocator soundness (test hook), refcount era: partition
        property (free XOR refcount >= 1, jointly covering the pool),
        table/owned agreement, and — when the prefix tree's per-page
        reference counts are passed as ``tree_refs`` — exact refcount
        accounting: rc(p) == slot holders + tree holders. Raises
        AssertionError on violation."""
        live = [p for p in range(self.num_pages) if self.refcount[p] > 0]
        assert sorted(self.free + live) == list(range(self.num_pages)), (
            f"page pool corrupt: free={sorted(self.free)} "
            f"live={live} owned={self.owned}")
        holders = {}
        for slot, pages in enumerate(self.owned):
            row = self.page_table[slot]
            assert list(row[:len(pages)]) == pages, (
                f"slot {slot} page-table row {row} disagrees with owned "
                f"{pages}")
            assert all(int(p) == self.trash_page
                       for p in row[len(pages):]), (
                f"slot {slot} has stale table entries past its pages: {row}")
            assert self.seq_lens[slot] <= len(pages) * self.page_size
            for p in pages:
                holders[p] = holders.get(p, 0) + 1
        for p in range(self.num_pages):
            assert self.refcount[p] >= holders.get(p, 0), (
                f"page {p}: refcount {self.refcount[p]} below its "
                f"{holders.get(p, 0)} slot holders")
        if tree_refs is not None:
            for p in range(self.num_pages):
                want = holders.get(p, 0) + int(tree_refs.get(p, 0))
                assert self.refcount[p] == want, (
                    f"page {p}: refcount {self.refcount[p]} != "
                    f"{holders.get(p, 0)} slot holders + "
                    f"{tree_refs.get(p, 0)} tree refs")
