"""Multi-engine cluster router — whole-engine loss as a managed failure
domain (docs/ROBUSTNESS.md § Cluster failure domains, docs/SERVING.md
§ Cluster router).

PRs 10–15 made ONE :class:`GenerativeEngine` hard to kill: supervised
restarts, retry re-admission, SLO shedding, prefix reuse, speculation.
This module is the next rung of that ladder — N engines behind one
:class:`ClusterRouter`, so the failure unit the system must absorb grows
from "a worker thread" to "an entire engine" (its supervisor's restart
budget spent, or the hard ``engine_death`` fault).

**Routing.** Each arrival is scored against every routable engine on two
axes, cheapest signal first:

* **prefix affinity** — the engine's radix tree (:meth:`RadixPrefixCache
  .match`) is the affinity oracle: the engine holding the longest cached
  prefix of the prompt serves it in O(suffix) instead of O(prompt), so
  shared-prompt traffic lands where its KV pages already live.
* **load** — busy slots plus queue depth, normalised by ``max_slots``
  (the same signals the occupancy gauge and queue-depth metric export).
  Affinity yields to load once the cached engine is more than
  ``affinity_max_imbalance`` waves deeper than the least-loaded engine —
  cache locality must not pile work onto a drowning engine.

**Health.** The router watches each engine's ``restarts`` counter through
a sliding window (the same signal the SLO frontend's circuit breaker
keys on, now per engine): an engine absorbing ``quarantine_restarts``
crashes within ``quarantine_window_s`` is QUARANTINED for
``quarantine_cooldown_s`` — deprioritised for new arrivals while it
proves itself, but never a hard exclusion: if every engine is
quarantined, the least-bad one still serves.

**Migration.** Engine death is final (the supervisor already spent its
budget). The dying worker thread runs the router's ``on_unrecoverable``
hook as its last act — nothing races it — and the hook applies the
PR-10/11 re-admission discipline cluster-wide: in-flight requests with
retry budget left re-admit at the FRONT of a survivor's queue with their
ORIGINAL submit time and priority (deadlines keep counting; the pending
order never inverts), queued requests migrate wholesale without charging
a retry (they never held a slot), and everything else retires terminally
as ``error`` — exactly one labelled terminal count per request, same as
every other exit path. Pinned per-class prefixes re-warm on the
destination engines (fire-and-forget 1-token generations; the recorded
pin intents re-pin on insert). Zero ``new_shape`` on survivors: migrated
requests restart from the prompt against already-compiled functions.

The router quacks like an engine where the SLO frontend needs it to
(``submit_request``/``validate_request``/``cfg``/``prewarm_prefix`` plus
a combined scheduler view), so ``SLOFrontend(ClusterRouter([...]))``
composes without frontend changes beyond the per-engine breaker.

Telemetry: ``dl4j_tpu_cluster_engines_live``,
``dl4j_tpu_cluster_routed_total{engine,reason}``,
``dl4j_tpu_cluster_deaths_total``, ``dl4j_tpu_cluster_migrated_total``,
``dl4j_tpu_cluster_migration_failed_total``,
``dl4j_tpu_cluster_quarantined_total``,
``dl4j_tpu_cluster_prefix_rewarm_total``; JSONL kinds ``cluster_route``,
``cluster_migrate``, ``cluster_quarantine`` (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.serving.engine import GenerativeEngine
from deeplearning4j_tpu.serving.scheduler import (
    GenerationRequest, GenerationResult)

logger = logging.getLogger(__name__)


class _ClusterSchedulerView:
    """The combined scheduler the SLO frontend steers by: pending depth,
    busy slots and capacity summed over LIVE engines only, so the
    frontend's wave estimates — and therefore its admission ladder —
    degrade proportionally when an engine dies instead of pretending the
    dead capacity still exists. Slot keys are ``(engine_id, slot)``."""

    def __init__(self, router: "ClusterRouter"):
        self._router = router

    def _live(self) -> List[GenerativeEngine]:
        live = self._router.live_engines()
        # a fully-dead cluster still needs a non-empty denominator for
        # the frontend's max(1, ...) guards; report the original shape
        return live or list(self._router.engines)

    @property
    def max_slots(self) -> int:
        return sum(e.scheduler.max_slots for e in self._live())

    @property
    def pending(self) -> List[tuple]:
        out: List[tuple] = []
        for e in self._live():
            out.extend(e.scheduler.pending_snapshot())
        return out

    @property
    def slots(self) -> Dict[tuple, object]:
        out: Dict[tuple, object] = {}
        for e in self._live():
            for slot, st in list(e.scheduler.slots.items()):
                out[(e.engine_id, slot)] = st
        return out

    def pending_snapshot(self) -> List[tuple]:
        return self.pending

    def has_work(self) -> bool:
        return any(e.scheduler.has_work() for e in self._live())

    def occupancy(self) -> float:
        cap = self.max_slots
        return len(self.slots) / cap if cap else 0.0

    def steal_lowest_pending(self, than_priority: int) -> Optional[tuple]:
        """Shed the GLOBALLY worst queued item: find the engine holding
        the worst victim (snapshot scan), then delegate to its scheduler's
        atomic steal. A racing admit may hand us a different — but by
        construction no better — victim from that engine; None when no
        engine queues anything lower-priority."""
        worst_sched, worst_key = None, None
        for e in self._live():
            for item in e.scheduler.pending_snapshot():
                if item[0].priority <= than_priority:
                    continue
                key = (item[0].priority, item[2])
                if worst_key is None or key > worst_key:
                    worst_key, worst_sched = key, e.scheduler
        if worst_sched is None:
            return None
        return worst_sched.steal_lowest_pending(than_priority)


class ClusterRouter:
    """Health- and affinity-routed serving over N engines; see the module
    docstring for the design. Engines must share the model contract
    (vocab, prompt bucket) — a request routable to one must be routable
    to all, or migration could strand work."""

    def __init__(self, engines: Sequence[GenerativeEngine], *,
                 quarantine_restarts: int = 3,
                 quarantine_window_s: float = 30.0,
                 quarantine_cooldown_s: float = 5.0,
                 affinity_max_imbalance: float = 2.0):
        engines = list(engines)
        if not engines:
            raise ValueError("ClusterRouter needs at least one engine")
        head = engines[0]
        for e in engines[1:]:
            if (e.cfg.vocab_size != head.cfg.vocab_size
                    or e.max_prompt != head.max_prompt):
                raise ValueError(
                    "cluster engines must share vocab_size and max_prompt "
                    "(a request routable to one must be routable to all)")
        if len({e.engine_id for e in engines}) != len(engines):
            # default-constructed engines all carry id 0 — renumber so
            # metrics/JSONL rows and the _dead set can tell them apart
            for i, e in enumerate(engines):
                e.engine_id = i
        self.engines = engines
        self.quarantine_restarts = int(quarantine_restarts)
        self.quarantine_window_s = float(quarantine_window_s)
        self.quarantine_cooldown_s = float(quarantine_cooldown_s)
        self.affinity_max_imbalance = float(affinity_max_imbalance)
        self._lock = threading.RLock()
        self._dead: set = set()                       # engine_ids, final
        self._quarantined_until: Dict[int, float] = {}
        self._restart_seen: Dict[int, int] = {
            e.engine_id: e.restarts for e in engines}
        self._restart_times: Dict[int, Deque[float]] = {
            e.engine_id: deque() for e in engines}
        self._pin_intents: List[Tuple[int, ...]] = []  # ordered, deduped
        self.deaths = 0
        self.migrations = 0
        # migrated request objects, for harnesses asserting bit-identical
        # outputs across a migration (bounded: telemetry, not state)
        self.migrated_requests: Deque[GenerationRequest] = deque(maxlen=4096)
        self.scheduler = _ClusterSchedulerView(self)
        m = observe.metrics()
        self._obs = {
            "live": m.gauge("dl4j_tpu_cluster_engines_live"),
            "deaths": m.counter("dl4j_tpu_cluster_deaths_total"),
            "migrated": m.counter("dl4j_tpu_cluster_migrated_total"),
            "migration_failed":
                m.counter("dl4j_tpu_cluster_migration_failed_total"),
            "quarantined": m.counter("dl4j_tpu_cluster_quarantined_total"),
            "rewarm": m.counter("dl4j_tpu_cluster_prefix_rewarm_total"),
        }
        self._obs["live"].set(float(len(engines)))
        for e in engines:
            # bind per-engine: the hook runs on e's dying worker thread
            e.on_unrecoverable = (
                lambda exc, eng=e: self._on_engine_death(eng, exc))

    # ------------------------------------------------------- engine facade
    # the attributes the SLO frontend (and plain callers) read off an
    # engine, delegated so SLOFrontend(ClusterRouter([...])) composes
    @property
    def cfg(self):
        return self.engines[0].cfg

    @property
    def max_prompt(self) -> int:
        return self.engines[0].max_prompt

    @property
    def default_deadline_s(self):
        return self.engines[0].default_deadline_s

    @property
    def max_restarts(self) -> int:
        return self.engines[0].max_restarts

    @property
    def restarts(self) -> int:
        """Cluster-total crash recoveries — the legacy single-keyed read;
        the frontend's per-engine breaker walks :attr:`engines` instead."""
        return sum(e.restarts for e in self.engines)

    @property
    def prefix(self):
        return self.engines[0].prefix

    def validate_request(self, req: GenerationRequest) -> None:
        self.engines[0].validate_request(req)

    # ------------------------------------------------------------- routing
    def live_engines(self) -> List[GenerativeEngine]:
        with self._lock:
            return [e for e in self.engines
                    if e.engine_id not in self._dead
                    and e._error is None and not e._stop_flag]

    def _health_check(self, now: float) -> None:
        """Slide each live engine's restart window; quarantine thrashers.
        Caller holds the router lock."""
        for e in self.engines:
            eid = e.engine_id
            if eid in self._dead:
                continue
            cur = int(e.restarts)
            new = cur - self._restart_seen.get(eid, 0)
            self._restart_seen[eid] = cur
            times = self._restart_times.setdefault(eid, deque())
            for _ in range(max(0, new)):
                times.append(now)
            while times and now - times[0] > self.quarantine_window_s:
                times.popleft()
            if (len(times) >= self.quarantine_restarts
                    and now >= self._quarantined_until.get(eid, -1.0)):
                self._quarantined_until[eid] = (
                    now + self.quarantine_cooldown_s)
                times.clear()  # a fresh thrash re-opens, not this one
                self._obs["quarantined"].inc()
                observe.log_event("cluster_quarantine", engine=eid,
                                  permanent=False,
                                  cooldown_s=self.quarantine_cooldown_s)
                logger.warning(
                    "engine %d quarantined for %.1fs (%d restarts inside "
                    "%.1fs window)", eid, self.quarantine_cooldown_s,
                    self.quarantine_restarts, self.quarantine_window_s)

    def _routable(self) -> List[GenerativeEngine]:
        now = time.monotonic()
        with self._lock:
            self._health_check(now)
            live = [e for e in self.engines
                    if e.engine_id not in self._dead
                    and e._error is None and not e._stop_flag]
            healthy = [e for e in live
                       if now >= self._quarantined_until.get(
                           e.engine_id, -1.0)]
        # quarantine deprioritises, never strands: a cluster whose every
        # engine is in cooldown still serves from the least-bad one
        return healthy or live

    @staticmethod
    def _load(e: GenerativeEngine) -> float:
        s = e.scheduler
        return (len(s.slots) + len(s.pending)) / max(1, s.max_slots)

    @staticmethod
    def _affinity(e: GenerativeEngine, prompt) -> int:
        if e.prefix is None:
            return 0
        m = e.prefix.match(prompt, max_suffix=e.suffix_bucket)
        return int(m.matched) if m is not None else 0

    def _select(self, req: GenerationRequest
                ) -> Optional[Tuple[GenerativeEngine, str, int, float]]:
        """Pick the engine for ``req``: longest usable cached prefix wins,
        load breaks ties (and overrides affinity past the imbalance cap),
        engine id makes the order total and deterministic."""
        cands = self._routable()
        if not cands:
            return None
        loads = {e.engine_id: self._load(e) for e in cands}
        min_load = min(loads.values())
        best = best_key = None
        for e in cands:
            aff = self._affinity(e, req.prompt)
            if loads[e.engine_id] - min_load > self.affinity_max_imbalance:
                aff = 0  # cache locality must not pile onto a drowning engine
            key = (-aff, loads[e.engine_id], e.engine_id)
            if best_key is None or key < best_key:
                best_key, best = key, e
        reason = "affinity" if -best_key[0] > 0 else "load"
        return best, reason, -best_key[0], best_key[1]

    # ---------------------------------------------------------- submission
    def submit(self, prompt, *, max_new_tokens: int = 16,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               eos_token: Optional[int] = None,
               deadline_s: Optional[float] = None, max_retries: int = 1,
               priority: int = 1, slo_class: str = "standard"
               ) -> "Future[GenerationResult]":
        """Same contract as :meth:`GenerativeEngine.submit`, routed."""
        eos = self.cfg.eos_token if eos_token is None else eos_token
        req = GenerationRequest(
            prompt=prompt, max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p, eos_token=eos,
            deadline_s=deadline_s, max_retries=max_retries,
            priority=priority, slo_class=slo_class)
        return self.submit_request(req)

    def submit_request(self, req: GenerationRequest
                       ) -> "Future[GenerationResult]":
        """Route a pre-built request (the SLO frontend's entry point) to
        the affinity/load winner. The chosen engine applies its own
        default deadline and ``max_queue`` shed. An engine that died or
        stopped inside the selection race window is marked and the next
        candidate tried; only a fully-dead cluster raises."""
        last_exc: Optional[BaseException] = None
        for _ in range(len(self.engines)):
            sel = self._select(req)
            if sel is None:
                break
            eng, reason, aff, load = sel
            try:
                fut = eng.submit_request(req)
            except RuntimeError as exc:
                # died/stopped between selection and enqueue — the death
                # hook (or stop()) already settled its queue; route on
                last_exc = exc
                with self._lock:
                    if eng._error is not None:
                        self._dead.add(eng.engine_id)
                        self._obs["live"].set(float(len({
                            e.engine_id for e in self.engines}
                            - self._dead)))
                continue
            observe.metrics().counter(
                "dl4j_tpu_cluster_routed_total",
                engine=str(eng.engine_id), reason=reason).inc()
            observe.log_event("cluster_route", engine=eng.engine_id,
                              reason=reason, affinity_tokens=aff,
                              load=round(load, 3))
            return fut
        raise RuntimeError("no live engine in cluster") from last_exc

    # ------------------------------------------------------------ migration
    def _on_engine_death(self, eng: GenerativeEngine,
                         exc: Exception) -> None:
        """The ``on_unrecoverable`` hook: runs ONCE on ``eng``'s dying
        worker thread (or the caller's thread in inline mode) after the
        supervisor gave up. Drains the dead scheduler and migrates —
        see the module docstring for the re-admission discipline. What
        this hook retires or migrates, ``fail_all`` afterwards never
        sees: each request exits exactly once."""
        with self._lock:
            if eng.engine_id in self._dead:
                return
            self._dead.add(eng.engine_id)
            n_live = len({e.engine_id for e in self.engines} - self._dead)
            # two engines can die concurrently, each on its own worker
            # thread — the counter bump must share the de-dup critical
            # section or increments are lost
            self.deaths += 1
        self._obs["deaths"].inc()
        self._obs["live"].set(float(n_live))
        observe.log_event("cluster_quarantine", engine=eng.engine_id,
                          permanent=True, error=repr(exc))
        logger.error("engine %d is DEAD (%r) — migrating its requests "
                     "across %d survivors", eng.engine_id, exc, n_live)
        sched, cache = eng.scheduler, eng.cache
        items: List[tuple] = []
        # in-flight first: active slots in ascending order is admission
        # (arrival) order, and they are strictly older than anything
        # still queued behind them
        for slot in sched.active_slots():
            st = sched.slots.pop(slot)
            cache.free_slot(slot)
            req = st.request
            if req.retries_used < req.max_retries:
                # the cluster-wide retry charge: a migration consumes one
                # re-admission, exactly like a supervised restart did
                req.retries_used += 1
                items.append((req, st.future, st.submit_t))
            else:
                self._obs["migration_failed"].inc()
                eng._finish_unslotted(req, st.future, "error")
        with sched._plock:
            queued = list(sched.pending)
            sched.pending.clear()
        items.extend(queued)  # queued work migrates without a retry charge
        groups: Dict[int, List[tuple]] = {}
        dests: Dict[int, GenerativeEngine] = {}
        n_failed = 0
        for item in items:
            sel = self._select(item[0])
            if sel is None:
                n_failed += 1
                self._obs["migration_failed"].inc()
                eng._finish_unslotted(item[0], item[1], "error")
                continue
            dest = sel[0]
            groups.setdefault(dest.engine_id, []).append(item)
            dests[dest.engine_id] = dest
        for eid, group in groups.items():
            dest = dests[eid]
            dest.adopt_requests(group)
            with self._lock:
                # concurrent deaths migrate on separate threads; keep the
                # tally and the audit list consistent with each other
                self.migrations += len(group)
                self.migrated_requests.extend(item[0] for item in group)
            self._obs["migrated"].inc(len(group))
            observe.log_event("cluster_migrate", from_engine=eng.engine_id,
                              to_engine=eid, n=len(group))
            self._rewarm_pins(dest)
        if n_failed:
            logger.error("%d requests could not migrate off dead engine "
                         "%d (no survivor / retry budget spent)",
                         n_failed, eng.engine_id)

    # --------------------------------------------------------- prefix pins
    def prewarm_prefix(self, prompt, *, pin: bool = True):
        """Pre-warm (and by default pin) a shared prefix on EVERY live
        engine, and record the intent so a later migration re-warms it on
        the destination. The frontend's ``ClassPolicy.shared_prefix``
        calls this exactly as it would the single-engine method."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if pin:
            toks = tuple(int(t) for t in prompt)
            with self._lock:
                if toks not in self._pin_intents:
                    self._pin_intents.append(toks)
        res = None
        for e in self.live_engines():
            res = e.prewarm_prefix(prompt, pin=pin)
        return res

    def _rewarm_pins(self, dest: GenerativeEngine) -> None:
        """Re-warm recorded pin intents on a migration destination,
        fire-and-forget: record the pin intent now (so the insert
        re-pins), skip prefixes the destination already holds, and let a
        1-token generation carry the pages in behind the migrated work."""
        # racy emptiness pre-check is benign: a concurrent pin either lands
        # before the locked copy below (re-warmed now) or is re-warmed by
        # the NEXT migration; never dropped, only possibly delayed.
        # graftlock: justified(GL012): advisory fast-path read; locked copy below is authoritative
        if dest.prefix is None or not self._pin_intents:
            return
        with self._lock:
            intents = list(self._pin_intents)
        for toks in intents:
            arr = np.asarray(toks, np.int32)
            m = dest.prefix.match(arr)
            dest.prefix.pin(arr)  # records the intent either way
            if m is not None and m.matched >= arr.size - 1:
                continue  # already resident (and now re-pinned)
            try:
                dest.submit(arr, max_new_tokens=1, eos_token=-1,
                            priority=0, slo_class="prefix_rewarm")
            except RuntimeError:
                continue  # destination raced to death; its own hook runs
            self._obs["rewarm"].inc()

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "ClusterRouter":
        for e in self.live_engines():
            e.start()
        return self

    def stop(self, timeout: float = 30.0) -> None:
        for e in self.engines:
            e.stop(timeout=timeout)

    def check_invariants(self) -> None:
        """Page/refcount invariants on every LIVE engine (a dead engine's
        accounting died with it)."""
        for e in self.live_engines():
            e.check_invariants()
