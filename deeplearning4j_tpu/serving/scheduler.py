"""Slot-based continuous-batching scheduler — iteration-level scheduling.

Orca (OSDI '22) named the policy this module implements: schedule at the
**decode-iteration** boundary, not the request boundary. A fixed bank of
``max_slots`` slots rides one compiled decode step; between iterations the
scheduler (a) retires slots whose sequence finished (EOS / token budget) or
must leave (page-table overflow, page-pool exhaustion), returning their
pages to the free list, and (b) admits queued requests into the freed slots
— so a 5-token reply never holds its slot hostage for a 500-token
neighbour's lifetime, which is what fixed-window batching
(``ParallelInference``'s request path) does for stateless inference.

The scheduler is pure host-side policy/state: no jax, no device work — the
``GenerativeEngine`` owns prefill/decode dispatch and calls in here between
iterations. Timing fields use ``time.perf_counter`` only (graftlint GL010).

Slot lifecycle::

    FREE --admit(prefill ok)--> ACTIVE --finish(eos|length)--> FREE
                                   \\--evict(overflow|oom|stopped)--> FREE
                                   \\--expire(deadline)--> FREE
                                   \\--crash(retryable)--> PENDING (retry)
                                   \\--crash(budget spent: error)--> FREE

``GenerationResult.finish_reason`` records which arc retired the request.
``shed`` never reaches a slot: the engine's bounded-queue admission gate
completes over-capacity submissions immediately (docs/ROBUSTNESS.md).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import observe

# Terminal states. The last three are the robustness tier's
# (docs/ROBUSTNESS.md): "shed" = an admission gate (the engine's bounded
# queue or the SLO frontend, serving/frontend.py) rejected the request,
# "deadline" = its per-request deadline expired (queued or mid-decode),
# "error" = a worker crash consumed its whole retry budget OR the
# frontend's circuit breaker fast-failed it. The SLO frontend consumes
# these as load signals AND produces them — one shared taxonomy, so
# ``dl4j_tpu_serving_evicted_total{reason}`` is the single place every
# terminal outcome is counted (asserted in tests/test_frontend.py).
FINISH_REASONS = ("eos", "length", "overflow", "oom", "stopped",
                  "shed", "deadline", "error")


def count_terminal(reason: str) -> None:
    """Increment the ONE terminal-outcome counter family. Every path that
    completes a request — retire, unslotted finish, fail_all/fail_pending,
    frontend sheds — funnels through here so the taxonomy cannot drift."""
    if reason not in FINISH_REASONS:
        raise ValueError(f"unknown finish reason {reason!r}")
    observe.metrics().counter(
        "dl4j_tpu_serving_evicted_total", reason=reason).inc()


@dataclasses.dataclass
class GenerationRequest:
    """One text-generation request (token-id space; tokenization is the
    caller's concern)."""

    prompt: np.ndarray               # (t,) int32 token ids
    max_new_tokens: int = 16
    temperature: float = 0.0         # <= 0 -> greedy
    top_k: int = 0                   # 0 -> disabled
    top_p: float = 1.0               # 1.0 -> disabled
    eos_token: int = -1              # -1 -> never stop on a token
    deadline_s: Optional[float] = None  # submit -> terminal budget (wall)
    max_retries: int = 1             # crash re-admissions before "error"
    retries_used: int = 0            # supervisor bookkeeping, not user-set
    # SLO-frontend fields (serving/frontend.py). ``priority`` orders the
    # pending queue (lower admits first); supervisor retries re-queue the
    # SAME request object, so class/priority/submit-time survive a crash
    # and recovery can never invert priority. ``degraded`` records that
    # the degradation ladder trimmed this request's parameters — it rides
    # into the GenerationResult so callers can see they got a degraded
    # answer.
    priority: int = 1                # 0 = most important
    slo_class: str = "standard"      # frontend class name (label value)
    degraded: bool = False           # ladder trimmed max_new_tokens/extras
    # ``spec_disabled``: the frontend's ``ClassPolicy.disable_spec``
    # degraded-mode knob turned speculative decoding off for this request
    # (shedding state frees the draft model's compute for the target);
    # the engine then decodes it non-speculatively even when spec is on.
    # Rides into the GenerationResult like ``degraded``.
    spec_disabled: bool = False

    def __post_init__(self):
        self.prompt = np.asarray(self.prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise ValueError("empty prompt")
        if self.max_new_tokens <= 0:
            raise ValueError("max_new_tokens must be positive")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0 (0 disables), "
                             f"got {self.top_k}")
        if not 0.0 < self.top_p <= 1.0:
            # top_p == 0 would mask EVERY token and silently degenerate to
            # emitting id 0; "disable" is top_p=1.0
            raise ValueError(f"top_p must be in (0, 1] (1.0 disables), "
                             f"got {self.top_p}")
        if self.deadline_s is not None and self.deadline_s < 0:
            raise ValueError(f"deadline_s must be >= 0 (None disables), "
                             f"got {self.deadline_s}")
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, "
                             f"got {self.max_retries}")
        if self.priority < 0:
            raise ValueError(f"priority must be >= 0, got {self.priority}")


@dataclasses.dataclass
class GenerationResult:
    """Completed (or evicted) generation + its latency raw material."""

    tokens: np.ndarray               # generated ids (no prompt, no eos)
    finish_reason: str
    prompt_len: int
    ttft_s: Optional[float]          # submit -> first token (perf_counter)
    intertoken_s: List[float]        # successive decode-token gaps
    slo_class: str = "standard"      # the request's admission class
    degraded: bool = False           # True: the ladder trimmed this answer
    prefix_hit_tokens: int = 0       # prompt tokens served from the radix
    #                                  prefix cache (0 = full prefill)
    # speculative-decoding accounting (docs/SERVING.md § Speculative
    # decoding): draft tokens proposed / committed for THIS request, and
    # whether the frontend's degraded-mode knob disabled speculation for
    # it. Zero/False on non-speculative requests.
    spec_proposed_tokens: int = 0
    spec_accepted_tokens: int = 0
    spec_disabled: bool = False


@dataclasses.dataclass
class _Slot:
    request: GenerationRequest
    future: "Future[GenerationResult]"
    submit_t: float
    prompt_len: int
    tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_s: Optional[float] = None
    intertoken_s: List[float] = dataclasses.field(default_factory=list)
    last_token_t: Optional[float] = None
    prefix_hit_tokens: int = 0
    spec_proposed_tokens: int = 0
    spec_accepted_tokens: int = 0


class SlotScheduler:
    """Pending queue + slot bank. Thread-safe for one engine loop plus
    submitting client threads AND the SLO frontend: every structural
    mutation of ``pending`` (append, best-pending removal, victim steal,
    drain) holds ``_plock``, because the frontend's shed-lowest-first
    steal removes items from the middle of the deque while the worker is
    index-scanning it — atomic deque ops alone no longer suffice."""

    def __init__(self, max_slots: int):
        self.max_slots = int(max_slots)
        self.pending: Deque[tuple] = deque()
        self.slots: Dict[int, _Slot] = {}
        self._plock = threading.Lock()

    # ------------------------------------------------------------ submission
    def submit(self, request: GenerationRequest) -> "Future[GenerationResult]":
        fut: "Future[GenerationResult]" = Future()
        with self._plock:
            self.pending.append((request, fut, time.perf_counter()))
        return fut

    # --------------------------------------------------------------- queries
    def active_slots(self) -> List[int]:
        return sorted(self.slots)

    def free_slot_ids(self) -> List[int]:
        return [s for s in range(self.max_slots) if s not in self.slots]

    def has_work(self) -> bool:
        return bool(self.slots) or bool(self.pending)

    def occupancy(self) -> float:
        return len(self.slots) / self.max_slots if self.max_slots else 0.0

    def pending_snapshot(self) -> List[tuple]:
        """A consistent copy of the pending queue (frontend accounting)."""
        with self._plock:
            return list(self.pending)

    # --------------------------------------------------- priority admission
    def peek_best_pending(self) -> Optional[tuple]:
        """The pending item that should admit NEXT: lowest
        ``request.priority`` first, then earliest submit time (FIFO within
        a class). Returns the item without removing it — the engine
        inspects page-pool feasibility before committing."""
        with self._plock:
            best, best_key = None, None
            for i, item in enumerate(self.pending):
                key = (item[0].priority, item[2], i)
                if best_key is None or key < best_key:
                    best_key, best = key, item
            return best

    def remove_pending(self, item: tuple) -> bool:
        """Remove ``item`` (by identity) from the pending queue. Returns
        False when a concurrent actor (a frontend victim steal, a deadline
        sweep) already took it — the caller must then re-select."""
        with self._plock:
            for i, it in enumerate(self.pending):
                if it is item:
                    del self.pending[i]
                    return True
        return False

    def steal_lowest_pending(self, than_priority: int) -> Optional[tuple]:
        """Remove and return the WORST queued item strictly lower-priority
        than ``than_priority`` (highest priority number; latest submit
        breaks ties — the newest of the worst class is shed, the oldest is
        closest to service). None when nothing lower-priority is queued.
        The shed-lowest-first arm of the SLO frontend's queue bound."""
        with self._plock:
            worst, worst_key, worst_i = None, None, -1
            for i, item in enumerate(self.pending):
                if item[0].priority <= than_priority:
                    continue
                key = (item[0].priority, item[2], i)
                if worst_key is None or key > worst_key:
                    worst_key, worst, worst_i = key, item, i
            if worst is not None:
                del self.pending[worst_i]
            return worst

    # ------------------------------------------------------------- lifecycle
    def admit(self, slot: int, request: GenerationRequest,
              future: "Future[GenerationResult]", submit_t: float,
              first_token: int, now: float,
              prefix_hit_tokens: int = 0) -> None:
        """Install a prefilled request into ``slot`` with its first sampled
        token (TTFT is measured here: prefill produced a token).
        ``prefix_hit_tokens`` records how much of the prompt the radix
        prefix cache served — it rides into the GenerationResult so
        callers and the replay bench can account hits per request."""
        st = _Slot(request=request, future=future, submit_t=submit_t,
                   prompt_len=int(request.prompt.size),
                   prefix_hit_tokens=int(prefix_hit_tokens))
        st.tokens.append(int(first_token))
        st.ttft_s = now - submit_t
        st.last_token_t = now
        self.slots[slot] = st

    def on_decode_token(self, slot: int, token: int, now: float) -> None:
        st = self.slots[slot]
        st.tokens.append(int(token))
        if st.last_token_t is not None:
            st.intertoken_s.append(now - st.last_token_t)
        st.last_token_t = now

    def on_spec_tokens(self, slot: int, tokens: List[int], now: float,
                       proposed: int, accepted: int) -> Optional[float]:
        """Commit a verify pass's tokens for ``slot`` — possibly several
        per engine step. Inter-token latency is accounted PER COMMITTED
        TOKEN (the step gap divided by the tokens it committed), not per
        step: a speculative step that lands 4 tokens in 50ms must read as
        12.5ms/token, or spec-on percentiles (and the SLO frontend's
        rolling decode estimate built on them) would overstate per-token
        latency by the acceptance factor. Returns the per-token gap (None
        on the first tokens after admission) so the engine can mirror the
        same value into the process histograms."""
        st = self.slots[slot]
        m = max(1, len(tokens))
        gap = (None if st.last_token_t is None
               else (now - st.last_token_t) / m)
        for t in tokens:
            st.tokens.append(int(t))
            if gap is not None:
                st.intertoken_s.append(gap)
        st.last_token_t = now
        st.spec_proposed_tokens += int(proposed)
        st.spec_accepted_tokens += int(accepted)
        return gap

    def should_finish(self, slot: int) -> Optional[str]:
        """``"eos"``/``"length"`` when the slot's sequence is complete."""
        st = self.slots[slot]
        if st.tokens and st.tokens[-1] == st.request.eos_token:
            return "eos"
        if len(st.tokens) >= st.request.max_new_tokens:
            return "length"
        return None

    def retire(self, slot: int, reason: str) -> GenerationResult:
        """Remove ``slot`` and complete its future. The caller frees the
        slot's cache pages (the scheduler never touches device state)."""
        if reason not in FINISH_REASONS:
            raise ValueError(f"unknown finish reason {reason!r}")
        st = self.slots.pop(slot)
        toks = st.tokens
        if reason == "eos" and toks and toks[-1] == st.request.eos_token:
            toks = toks[:-1]
        result = GenerationResult(
            tokens=np.asarray(toks, np.int32), finish_reason=reason,
            prompt_len=st.prompt_len, ttft_s=st.ttft_s,
            intertoken_s=list(st.intertoken_s),
            slo_class=st.request.slo_class, degraded=st.request.degraded,
            prefix_hit_tokens=st.prefix_hit_tokens,
            spec_proposed_tokens=st.spec_proposed_tokens,
            spec_accepted_tokens=st.spec_accepted_tokens,
            spec_disabled=st.request.spec_disabled)
        if not st.future.done():
            # graftlife: justified(GR003): retire() only forms the result —
            # its callers (engine._retire, frontend._shed_victim) own the
            # count_terminal(reason) increment, exactly once each
            st.future.set_result(result)
        return result

    def fail_all(self, exc: Exception, reason: str = "error") -> None:
        """Engine shutdown/crash: fail every in-flight and queued future so
        blocked callers wake instead of hanging (the ParallelInference.stop
        contract). Each future actually failed here counts ONCE under
        ``dl4j_tpu_serving_evicted_total{reason}`` — exception exits share
        the terminal-reason taxonomy with result exits."""
        for slot in list(self.slots):
            st = self.slots.pop(slot, None)  # tolerate a concurrent caller
            if st is not None and not st.future.done():
                st.future.set_exception(exc)
                count_terminal(reason)
        self.fail_pending(exc, reason=reason)

    def fail_pending(self, exc: Exception, reason: str = "error") -> None:
        """Fail ONLY the queued-but-never-admitted futures. Used alone when
        a hung worker may still own the active slots (stop() timeout):
        completing those futures here would race the stuck thread."""
        drained: List[tuple] = []
        while True:
            with self._plock:
                try:
                    drained.append(self.pending.popleft())
                except IndexError:  # drained (possibly by a concurrent one)
                    break
        for _req, fut, _t in drained:
            if not fut.done():
                fut.set_exception(exc)
                count_terminal(reason)
