"""GraphRunner — run imported TF/ONNX graphs directly.

Reference parity:
  * nd4j-tensorflow/.../graphrunner/GraphRunner.java — executes a frozen TF
    GraphDef with named feeds/fetches (used for verification and serving).
  * nd4j-onnxruntime OnnxRuntimeRunner — the same for ONNX models.

TPU-native realization: instead of embedding the TF C API / onnxruntime, the
model is converted ONCE through the shared import IR into a SameDiff graph
and executed as a single jitted XLA computation — the imported graph gets
the same compile-and-fuse treatment as native models, on TPU, with no
foreign runtime in the loop. Feed/fetch names match the source graph's
tensor names, as in the reference.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np


def _sniff_framework(data: bytes) -> str:
    """Distinguish ONNX ModelProto from TF GraphDef by the leading wire tag:
    ModelProto field 1 (ir_version) is a varint → first byte 0x08; GraphDef
    field 1 (node, repeated message) is length-delimited → 0x0A."""
    if not data:
        raise ValueError("empty graph bytes")
    if data[0] == 0x08:
        return "onnx"
    if data[0] == 0x0A:
        return "tensorflow"
    raise ValueError(
        "cannot sniff framework from graph bytes (expected an ONNX "
        "ModelProto or TF GraphDef); pass framework= explicitly")


class GraphRunner:
    """Load a frozen TF GraphDef or ONNX ModelProto and run it jitted.

    ``graph``: a file path (.pb / .onnx), raw protobuf bytes, or an already
    imported SameDiff. ``framework``: 'tensorflow' | 'onnx' | None (sniffed
    from the extension or wire format). ``outputs``: default fetch names
    (falls back to the graph's recorded outputs/terminal nodes).
    ``optimize``: run the pre-trace graph optimizer (docs/OPTIMIZER.md) on
    the imported graph before compiling (None = importer default, i.e. on;
    for an already-built SameDiff, None leaves its own flag untouched);
    per-compile instrumentation is surfaced as :attr:`compile_stats`.
    """

    def __init__(self, graph: Union[str, bytes, Any], *,
                 framework: Optional[str] = None,
                 outputs: Optional[Sequence[str]] = None,
                 optimize: Optional[bool] = None):
        from deeplearning4j_tpu.autodiff.samediff import SameDiff

        if isinstance(graph, SameDiff):
            self.sd = graph
            if optimize is not None:
                self.sd.optimize = optimize
        else:
            optimize = True if optimize is None else optimize
            data = graph
            if isinstance(graph, str):
                if framework is None:
                    low = graph.lower()
                    if low.endswith(".onnx"):
                        framework = "onnx"
                    elif low.endswith((".pb", ".graphdef")):
                        framework = "tensorflow"
                with open(graph, "rb") as f:
                    data = f.read()
            if framework is None:
                framework = _sniff_framework(bytes(data))
            if framework == "onnx":
                from deeplearning4j_tpu.imports.onnx_import import import_onnx
                self.sd = import_onnx(data, optimize=optimize)
            elif framework in ("tensorflow", "tf"):
                from deeplearning4j_tpu.imports.tf_import import TensorflowImporter
                self.sd = TensorflowImporter().run_import(data,
                                                          optimize=optimize)
            else:
                raise ValueError(f"unknown framework {framework!r}")
        self.framework = framework
        self._outputs = list(outputs) if outputs else list(
            getattr(self.sd, "graph_outputs", []) or [])
        if not self._outputs:
            raise ValueError("graph has no recorded outputs; pass outputs=")

    # ------------------------------------------------------------------ api
    @property
    def input_names(self) -> List[str]:
        return list(getattr(self.sd, "graph_inputs", []) or [])

    @property
    def output_names(self) -> List[str]:
        return list(self._outputs)

    @property
    def compile_stats(self):
        """OptimizeStats of the most recent compilation (None before the
        first run) — per-pass node deltas, trace and XLA compile seconds."""
        return self.sd.last_compile_stats

    def run(self, feeds: Dict[str, Any],
            outputs: Optional[Sequence[str]] = None) -> Dict[str, np.ndarray]:
        """Execute with named feeds; returns {fetch_name: np.ndarray}.
        (GraphRunner.run(Map<String, INDArray>) parity.)"""
        fetch = list(outputs) if outputs else self._outputs
        return self.sd.output(feeds, fetch)

    __call__ = run
