"""TF frozen-graph import → SameDiff graph.

Reference parity:
  * org/nd4j/imports/graphmapper/tf/TFGraphMapper.java (legacy) and the
    Kotlin IR-based samediff-import framework (SURVEY §3.2): per-op mapping
    rules from TF GraphDef nodes to SameDiff ops; Const tensors become
    VARIABLEs/CONSTANTs; Placeholders become placeholders.

Scope (SURVEY §8.3 hard part #2): the BERT-path op subset plus common
vision ops — enough to import graphs produced by in-env TF for golden-file
testing (the reference's TFGraphTestAllSameDiff pattern). The mapping-rule
table is extensible: register_tf_op(name)(fn).

Requires tensorflow only at import time of a GraphDef (TF 2.21 is in the
environment for golden-file generation; the runtime path is pure jax).
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

# op-name -> mapper(sd, node_inputs: List[SDVariable], attrs, tf_node) -> SDVariable
TF_OP_MAPPERS: Dict[str, Callable[..., Any]] = {}


def register_tf_op(name: str):
    def wrap(fn):
        TF_OP_MAPPERS[name] = fn
        return fn

    return wrap


# ---------------------------------------------------------------------------
# Mapping rules (TensorflowOpDeclarations analog)
# ---------------------------------------------------------------------------


@register_tf_op("MatMul")
def _matmul(sd, ins, attrs, node):
    return sd._record("mmul", ins, {
        "transpose_a": bool(attrs.get("transpose_a", False)),
        "transpose_b": bool(attrs.get("transpose_b", False))})


@register_tf_op("BatchMatMulV2")
@register_tf_op("BatchMatMul")
def _batch_matmul(sd, ins, attrs, node):
    return sd._record("mmul", ins, {
        "transpose_a": bool(attrs.get("adj_x", False)),
        "transpose_b": bool(attrs.get("adj_y", False))})


@register_tf_op("BiasAdd")
@register_tf_op("AddV2")
@register_tf_op("Add")
def _add(sd, ins, attrs, node):
    return sd._record("add", ins)


@register_tf_op("Sub")
def _sub(sd, ins, attrs, node):
    return sd._record("sub", ins)


@register_tf_op("Mul")
def _mul(sd, ins, attrs, node):
    return sd._record("mul", ins)


@register_tf_op("RealDiv")
@register_tf_op("Div")
def _div(sd, ins, attrs, node):
    return sd._record("div", ins)


@register_tf_op("Pow")
def _pow(sd, ins, attrs, node):
    return sd._record("pow", ins)


@register_tf_op("SquaredDifference")
def _sqdiff(sd, ins, attrs, node):
    return sd._record("squared_difference", ins)


@register_tf_op("Maximum")
def _max(sd, ins, attrs, node):
    return sd._record("maximum", ins)


@register_tf_op("Minimum")
def _min(sd, ins, attrs, node):
    return sd._record("minimum", ins)


for _tf, _ours in [
    ("Relu", "relu"), ("Relu6", "relu6"), ("Elu", "elu"), ("Selu", "selu"),
    ("Tanh", "tanh"), ("Sigmoid", "sigmoid"), ("Softplus", "softplus"),
    ("Softsign", "softsign"), ("Exp", "exp"), ("Log", "log"),
    ("Log1p", "log1p"), ("Sqrt", "sqrt"), ("Rsqrt", "rsqrt"),
    ("Square", "square"), ("Abs", "abs"), ("Neg", "neg"), ("Sign", "sign"),
    ("Floor", "floor"), ("Ceil", "ceil"), ("Round", "round"),
    ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"), ("Erf", "erf"),
    ("Reciprocal", "reciprocal"), ("Atan", "atan"), ("Asin", "asin"),
    ("Acos", "acos"), ("Sinh", "sinh"), ("Cosh", "cosh"),
]:
    def _make(ours):
        def f(sd, ins, attrs, node):
            return sd._record(ours, ins)

        return f

    TF_OP_MAPPERS[_tf] = _make(_ours)


@register_tf_op("Softmax")
def _softmax(sd, ins, attrs, node):
    return sd._record("softmax", ins, {"axis": -1})


@register_tf_op("LogSoftmax")
def _log_softmax(sd, ins, attrs, node):
    return sd._record("log_softmax", ins, {"axis": -1})


@register_tf_op("Identity")
@register_tf_op("StopGradient")
@register_tf_op("NoOp")
@register_tf_op("CheckNumerics")
def _identity(sd, ins, attrs, node):
    return ins[0] if ins else None


@register_tf_op("Reshape")
def _reshape(sd, ins, attrs, node, const_values=None):
    shape = const_values.get(node.input[1]) if const_values else None
    if shape is None:
        # tf.shape(...)-derived target: stays trace-time concrete through
        # the shape_of chain, so reshape_dynamic recovers the ints there
        return sd._record("reshape_dynamic", [ins[0], ins[1]])
    return sd._record("reshape", [ins[0]], {"shape": tuple(int(s) for s in shape)})


@register_tf_op("Transpose")
def _transpose(sd, ins, attrs, node, const_values=None):
    perm = _require_const(const_values, node, 1, "perm")
    return sd._record("transpose", [ins[0]], {"axes": tuple(int(p) for p in perm)})


@register_tf_op("ExpandDims")
def _expand(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "dim")
    return sd._record("expand_dims", [ins[0]], {"axis": int(axis)})


@register_tf_op("Squeeze")
def _squeeze(sd, ins, attrs, node):
    dims = attrs.get("squeeze_dims") or None
    axis = tuple(dims) if dims else None
    return sd._record("squeeze", ins, {"axis": axis})


@register_tf_op("ConcatV2")
def _concat(sd, ins, attrs, node, const_values=None):
    axis = const_values.get(node.input[-1])
    data_ins = [i for i in node.input[:-1] if not i.startswith("^")]
    if all(n in const_values for n in data_ins):
        # const-fold shape chains (Fill/Range → Concat → Reshape)
        const_values[node.name] = np.concatenate(
            [np.atleast_1d(const_values[n]) for n in data_ins],
            axis=int(axis))
    return sd._record("concat", ins[:-1], {"axis": int(axis)})


@register_tf_op("Mean")
def _mean(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_mean", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("Sum")
def _sum(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_sum", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("Max")
def _reduce_max(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_max", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("GatherV2")
def _gather(sd, ins, attrs, node, const_values=None):
    axis = const_values.get(node.input[2], 0)
    return sd._record("gather", ins[:2], {"axis": int(axis)})


@register_tf_op("Conv2D")
def _conv2d(sd, ins, attrs, node):
    strides = attrs.get("strides", [1, 1, 1, 1])
    padding = attrs.get("padding", b"SAME")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC Conv2D import supported")
    return sd._record("conv2d", ins, {"stride": (int(strides[1]), int(strides[2])),
                                      "padding": pad})


@register_tf_op("MaxPool")
def _maxpool(sd, ins, attrs, node):
    k = attrs.get("ksize", [1, 2, 2, 1])
    s = attrs.get("strides", [1, 2, 2, 1])
    padding = attrs.get("padding", b"VALID")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("maxpool2d", ins, {"kernel": (int(k[1]), int(k[2])),
                                         "stride": (int(s[1]), int(s[2])),
                                         "padding": pad})


@register_tf_op("AvgPool")
def _avgpool(sd, ins, attrs, node):
    k = attrs.get("ksize", [1, 2, 2, 1])
    s = attrs.get("strides", [1, 2, 2, 1])
    padding = attrs.get("padding", b"VALID")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("avgpool2d", ins, {"kernel": (int(k[1]), int(k[2])),
                                         "stride": (int(s[1]), int(s[2])),
                                         "padding": pad})


@register_tf_op("Cast")
def _cast(sd, ins, attrs, node, const_values=None):
    import tensorflow as tf

    dst = attrs.get("DstT")
    np_dtype = tf.dtypes.as_dtype(dst).as_numpy_dtype if dst is not None else np.float32
    if const_values is not None and node.input[0] in const_values:
        # constant-fold: shape/limit chains (e.g. Range's Cast'ed bounds)
        # stay resolvable as const operands downstream
        folded = np.asarray(const_values[node.input[0]]).astype(np_dtype)
        const_values[node.name] = folded
    return sd._record("cast", ins, {"dtype": str(np.dtype(np_dtype))})


@register_tf_op("Pack")
def _pack(sd, ins, attrs, node, const_values=None):
    data_ins = [i for i in node.input if not i.startswith("^")]
    if const_values is not None and all(n in const_values for n in data_ins):
        # const-fold shape chains (scalar dims → Pack → Reshape)
        const_values[node.name] = np.stack(
            [np.asarray(const_values[n]) for n in data_ins],
            axis=int(attrs.get("axis", 0)))
    return sd._record("stack", ins, {"axis": int(attrs.get("axis", 0))})


@register_tf_op("Tile")
def _tile(sd, ins, attrs, node, const_values=None):
    reps = _require_const(const_values, node, 1, "multiples")
    return sd._record("tile", [ins[0]], {"reps": tuple(int(r) for r in reps)})


@register_tf_op("Select")
@register_tf_op("SelectV2")
def _select(sd, ins, attrs, node):
    return sd._record("where", ins)


@register_tf_op("Greater")
def _greater(sd, ins, attrs, node):
    return sd._record("gt", ins)


@register_tf_op("Less")
def _less(sd, ins, attrs, node):
    return sd._record("lt", ins)


@register_tf_op("Equal")
def _equal(sd, ins, attrs, node):
    return sd._record("eq", ins)


@register_tf_op("DepthwiseConv2dNative")
def _depthwise_conv(sd, ins, attrs, node):
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC DepthwiseConv2dNative import supported")
    if [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])] != [1, 1, 1, 1]:
        raise NotImplementedError("dilated DepthwiseConv2dNative import")
    strides = attrs.get("strides", [1, 1, 1, 1])
    padding = attrs.get("padding", b"SAME")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("depthwise_conv2d", ins,
                      {"stride": (int(strides[1]), int(strides[2])),
                       "padding": pad})


@register_tf_op("FusedBatchNormV3")
@register_tf_op("FusedBatchNorm")
def _fused_bn(sd, ins, attrs, node):
    """inference-mode fused BN: inputs x, scale, offset, mean, var (NHWC)."""
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC FusedBatchNorm import supported")
    x, scale, offset, mean, var = ins[:5]
    return sd._record("batch_norm_graph", [x, mean, var, scale, offset],
                      {"eps": float(attrs.get("epsilon", 1e-3))})


@register_tf_op("LeakyRelu")
def _tf_leaky(sd, ins, attrs, node):
    return sd._record("leakyrelu", ins,
                      {"alpha": float(attrs.get("alpha", 0.2))})


@register_tf_op("Pad")
@register_tf_op("PadV2")
def _tf_pad(sd, ins, attrs, node, const_values=None):
    pads = _require_const(const_values, node, 1, "paddings")
    value = 0.0
    if len(node.input) > 2:
        cv = const_values.get(node.input[2].split(":")[0])
        if cv is not None:
            value = float(cv)
    return sd._record("pad", [ins[0]],
                      {"paddings": tuple((int(a), int(b)) for a, b in pads),
                       "value": value})


@register_tf_op("StridedSlice")
def _tf_strided_slice(sd, ins, attrs, node, const_values=None):
    """Full mask support (begin/end/shrink/new_axis/ellipsis) — everything
    Python slicing compiles to, resolved at trace time by the
    strided_slice_spec op (so ellipsis works on operands whose rank is
    only known at execution)."""
    begin = [int(b) for b in _require_const(const_values, node, 1, "begin")]
    end = [int(e) for e in _require_const(const_values, node, 2, "end")]
    strides = [int(s) for s in
               _require_const(const_values, node, 3, "strides")]
    return sd._record("strided_slice_spec", [ins[0]], {
        "begin": begin, "end": end, "strides": strides,
        "begin_mask": int(attrs.get("begin_mask", 0)),
        "end_mask": int(attrs.get("end_mask", 0)),
        "shrink_mask": int(attrs.get("shrink_axis_mask", 0)),
        "new_axis_mask": int(attrs.get("new_axis_mask", 0)),
        "ellipsis_mask": int(attrs.get("ellipsis_mask", 0))})


@register_tf_op("Unpack")
def _tf_unpack(sd, ins, attrs, node):
    # single-output use only: the common tf.unstack(x)[0] pattern — with
    # num > 1 every :k consumer would silently receive element 0
    if int(attrs.get("num", 1)) > 1 or int(attrs.get("axis", 0)) != 0:
        raise NotImplementedError(
            f"Unpack {node.name}: num={attrs.get('num')}/axis="
            f"{attrs.get('axis', 0)} — only single-element axis-0 unstack "
            "imports")
    return sd._record("unstack_first", ins)


@register_tf_op("ArgMax")
def _tf_argmax(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "dimension") \
        if len(node.input) > 1 else -1
    return sd._record("argmax", [ins[0]], {"axis": int(axis)})


@register_tf_op("ArgMin")
def _tf_argmin(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "dimension") \
        if len(node.input) > 1 else -1
    return sd._record("argmin", [ins[0]], {"axis": int(axis)})


@register_tf_op("Prod")
def _tf_prod(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_prod", [ins[0]], {
        "axes": tuple(int(a) for a in np.atleast_1d(axes)),
        "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("Min")
def _tf_reduce_min(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_min", [ins[0]], {
        "axes": tuple(int(a) for a in np.atleast_1d(axes)),
        "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("ClipByValue")
def _tf_clip(sd, ins, attrs, node, const_values=None):
    lo = float(_require_const(const_values, node, 1, "clip_value_min"))
    hi = float(_require_const(const_values, node, 2, "clip_value_max"))
    return sd._record("clip_by_value_graph", [ins[0]],
                      {"min_value": lo, "max_value": hi})


@register_tf_op("Cumsum")
def _tf_cumsum(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "axis")
    return sd._record("cumsum", [ins[0]], {
        "axis": int(axis),
        "exclusive": bool(attrs.get("exclusive", False)),
        "reverse": bool(attrs.get("reverse", False))})


@register_tf_op("GreaterEqual")
def _tf_gte(sd, ins, attrs, node):
    return sd._record("gte", ins)


@register_tf_op("LessEqual")
def _tf_lte(sd, ins, attrs, node):
    return sd._record("lte", ins)


@register_tf_op("NotEqual")
def _tf_neq(sd, ins, attrs, node):
    return sd._record("neq", ins)


@register_tf_op("ZerosLike")
def _tf_zeros_like(sd, ins, attrs, node):
    return sd._record("zeros_like", ins)


@register_tf_op("OnesLike")
def _tf_ones_like(sd, ins, attrs, node):
    return sd._record("ones_like", ins)


def _require_const(const_values, node, idx, what):
    name = node.input[idx].split(":")[0]
    val = (const_values or {}).get(name)
    if val is None:
        raise ValueError(
            f"{node.op_type} {node.name}: dynamic (non-Const) {what} operand "
            f"'{node.input[idx]}' is unsupported")
    return val


@register_tf_op("AvgPool3D")
@register_tf_op("MaxPool3D")
def _tf_pool3d_unsupported(sd, ins, attrs, node):
    raise NotImplementedError("3-D pooling import is not supported yet")


# ---------------------------------------------------------------------------
# The importer
# ---------------------------------------------------------------------------

_CONST_ONLY_OPS = {"Const", "Placeholder", "PlaceholderWithDefault"}
# mappers that need raw const operand values (shape/perm/axis inputs)
_NEEDS_CONSTS = {"Cast", "Pack", "Reshape", "Transpose", "ExpandDims", "ConcatV2", "Mean",
                 "Sum", "Max", "Min", "Prod", "GatherV2", "Tile", "Pad",
                 "PadV2", "StridedSlice", "ArgMax", "ArgMin", "ClipByValue",
                 "Cumsum"}


def graphdef_to_ir(graph_def, variable_values=None) -> "IRGraph":
    """TF GraphDef → framework-neutral IRGraph (imports/ir.py): Const nodes
    become initializers, Placeholders become graph inputs, everything else
    an IRNode with normalized attrs."""
    from tensorflow.python.framework import tensor_util

    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

    nodes: List = []
    initializers: Dict[str, np.ndarray] = {}
    inputs: List = []
    library = {f.signature.name: f for f in graph_def.library.function}
    for node in graph_def.node:
        if node.op == "Const":
            initializers[node.name] = tensor_util.MakeNdarray(
                node.attr["value"].tensor)
            continue
        if node.op in ("Placeholder", "PlaceholderWithDefault"):
            shape = None
            if "shape" in node.attr:
                dims = node.attr["shape"].shape.dim
                shape = tuple(d.size if d.size > 0 else None for d in dims)
            inputs.append((node.name, shape))
            continue
        attrs = {k: _attr_value(v) for k, v in node.attr.items()}

        def norm(i):
            # keep multi-output slot addressing ("op:1"); the default ":0"
            # slot normalizes to the bare name
            if ":" in i:
                base, slot = i.rsplit(":", 1)
                if slot == "0":
                    return base
            return i

        # control-dep inputs ("^name") are ordering-only — XLA's dataflow
        # subsumes them; they are NOT data operands
        in_names = [norm(i) for i in node.input if not i.startswith("^")]
        if node.op in _CONTROL_FLOW_OPS or node.op in _CALL_OPS:
            attrs["_library"] = library  # branch/body lookup for the mapper
        if node.op in _VARIABLE_OPS:
            attrs["_var_values"] = variable_values or {}
        nodes.append(IRNode(name=node.name, op_type=node.op,
                            inputs=in_names, outputs=[node.name],
                            attrs=attrs))
    return IRGraph(nodes=nodes, initializers=initializers, inputs=inputs,
                   outputs=[], name="tensorflow")


class TensorflowImporter:
    """FrameworkImporter analog for TF frozen GraphDefs — a thin frontend
    over the shared IR walker (imports/ir.IRImporter): parse to IRGraph,
    dispatch the TF dialect rule table."""

    def __init__(self, extra_mappers: Optional[Dict[str, Callable]] = None):
        self.mappers = dict(TF_OP_MAPPERS)
        if extra_mappers:
            self.mappers.update(extra_mappers)

    def supported_ops(self) -> List[str]:
        return sorted(self.mappers)

    def run_import(self, graph_def, *, trainable_consts: bool = True,
                   variable_values=None, outputs=None,
                   optimize: bool = True,
                   validate: bool = True) -> SameDiff:
        """GraphDef (or serialized bytes / .pb path) → SameDiff.

        ``variable_values``: name → ndarray table for VarHandleOp /
        VariableV2 nodes (the TFGraphMapper checkpoint-restore path,
        SURVEY §4.3 step 1) — restored values become VARIABLE-role
        SDVariables, so fine-tuning starts from the trained weights.
        ``optimize=False`` disables the pre-trace graph optimizer;
        ``validate=False`` skips the post-import graftcheck."""
        from deeplearning4j_tpu.imports.ir import IRImporter

        graph_def = _coerce_graph_def(graph_def)
        ir = graphdef_to_ir(graph_def, variable_values=variable_values)
        if outputs:
            ir.outputs = list(outputs)
        ir = _inline_function_calls(ir, variable_values)
        ir = _collapse_tf1_control_flow(ir)
        walker = IRImporter(self.mappers, needs_consts=_NEEDS_CONSTS,
                            trainable_consts=trainable_consts,
                            optimize=optimize, validate=validate)
        return walker.run_import(ir)


def _coerce_graph_def(g):
    import tensorflow as tf

    if isinstance(g, (str, bytes)):
        gd = tf.compat.v1.GraphDef()
        if isinstance(g, str):
            with open(g, "rb") as f:
                gd.ParseFromString(f.read())
        else:
            gd.ParseFromString(g)
        return gd
    return g


def _attr_value(v):
    kind = v.WhichOneof("value")
    if kind == "func":
        return v.func.name  # function-library reference (While/If branches)
    if kind == "i":
        return v.i
    if kind == "f":
        return v.f
    if kind == "b":
        return v.b
    if kind == "s":
        return v.s
    if kind == "list":
        lst = v.list
        for field in ("i", "f", "b", "s"):
            vals = list(getattr(lst, field))
            if vals:
                return vals
        return []
    if kind == "type":
        return v.type
    if kind == "shape":
        return v.shape
    return v


def import_frozen_graph(path_or_bytes) -> SameDiff:
    """Convenience one-call import (KerasModelImport-style facade)."""
    return TensorflowImporter().run_import(path_or_bytes)


# ---------------------------------------------------------------------------
# Dialect widening, round 3 continued: shape/indexing + math + image ops.
# ---------------------------------------------------------------------------


@register_tf_op("Split")
def _split(sd, ins, attrs, node, const_values=None):
    # TF Split: (axis, value); num_split is an attr
    axis = _require_const(const_values, node, 0, "axis")
    n = int(attrs.get("num_split"))
    return sd._record("split", [ins[-1]],
                      {"num_split": n, "axis": int(axis)}, n_out=n)


@register_tf_op("SplitV")
def _split_v(sd, ins, attrs, node, const_values=None):
    sizes = _require_const(const_values, node, 1, "size_splits")
    axis = _require_const(const_values, node, 2, "axis")
    sizes = tuple(int(s) for s in np.atleast_1d(sizes))
    return sd._record("split_v", [ins[0]],
                      {"sizes": sizes, "axis": int(axis)},
                      n_out=len(sizes))


@register_tf_op("OneHot")
def _one_hot(sd, ins, attrs, node, const_values=None):
    depth = _require_const(const_values, node, 1, "depth")
    on = _require_const(const_values, node, 2, "on_value") \
        if len(node.input) > 2 else None
    off = _require_const(const_values, node, 3, "off_value") \
        if len(node.input) > 3 else None
    if int(attrs.get("axis", -1)) != -1:
        raise NotImplementedError("OneHot with axis != -1 import")
    oh = sd._record("one_hot_graph", [ins[0]], {"depth": int(depth)})
    on_v = 1.0 if on is None else float(np.asarray(on).item())
    off_v = 0.0 if off is None else float(np.asarray(off).item())
    if on_v == 1.0 and off_v == 0.0:
        return oh
    # label-smoothing style: off + (on - off) * onehot
    scaled = sd._record("mul", [oh, sd.constant(
        node.name + "_scale", np.asarray(on_v - off_v, np.float32))])
    return sd._record("add", [scaled, sd.constant(
        node.name + "_off", np.asarray(off_v, np.float32))])


@register_tf_op("Range")
def _range(sd, ins, attrs, node, const_values=None):
    start = _require_const(const_values, node, 0, "start")
    limit = _require_const(const_values, node, 1, "limit")
    delta = _require_const(const_values, node, 2, "delta") \
        if len(node.input) > 2 else 1
    arr = np.arange(np.asarray(start).item(), np.asarray(limit).item(),
                    np.asarray(delta).item())
    const_values[node.name] = arr  # keep shape chains const-resolvable
    return sd.constant(node.name + "_range", arr)


@register_tf_op("Fill")
def _fill(sd, ins, attrs, node, const_values=None):
    dims = _require_const(const_values, node, 0, "dims")
    value = _require_const(const_values, node, 1, "value")
    arr = np.full(tuple(int(d) for d in np.atleast_1d(dims)),
                  np.asarray(value).item())
    const_values[node.name] = arr  # keep shape chains const-resolvable
    return sd.constant(node.name + "_fill", arr)


@register_tf_op("Slice")
def _slice(sd, ins, attrs, node, const_values=None):
    begin = _require_const(const_values, node, 1, "begin")
    size = _require_const(const_values, node, 2, "size")
    return sd._record("slice", [ins[0]],
                      {"begin": tuple(int(b) for b in np.atleast_1d(begin)),
                       "size": tuple(int(s) for s in np.atleast_1d(size))})


@register_tf_op("BroadcastTo")
def _broadcast_to(sd, ins, attrs, node, const_values=None):
    shape = _require_const(const_values, node, 1, "shape")
    return sd._record("broadcast_to", [ins[0]],
                      {"shape": tuple(int(s) for s in np.atleast_1d(shape))})


@register_tf_op("FloorDiv")
def _floordiv(sd, ins, attrs, node):
    return sd._record("floordiv", ins)


@register_tf_op("FloorMod")
def _floormod(sd, ins, attrs, node):
    return sd._record("floormod", ins)


@register_tf_op("Atan2")
def _atan2(sd, ins, attrs, node):
    return sd._record("atan2", ins)


@register_tf_op("SpaceToDepth")
def _space_to_depth(sd, ins, attrs, node):
    fmt = attrs.get("data_format", b"NHWC")
    fmt = fmt.decode() if isinstance(fmt, bytes) else str(fmt)
    return sd._record("space_to_depth", ins,
                      {"block_size": int(attrs["block_size"]),
                       "data_format": fmt})


@register_tf_op("DepthToSpace")
def _depth_to_space(sd, ins, attrs, node):
    fmt = attrs.get("data_format", b"NHWC")
    fmt = fmt.decode() if isinstance(fmt, bytes) else str(fmt)
    return sd._record("depth_to_space", ins,
                      {"block_size": int(attrs["block_size"]),
                       "data_format": fmt})


@register_tf_op("ResizeBilinear")
def _resize_bilinear_tf(sd, ins, attrs, node, const_values=None):
    if not bool(attrs.get("half_pixel_centers", False)):
        raise NotImplementedError(
            "legacy ResizeBilinear (half_pixel_centers=false) import — "
            "re-export with tf.image.resize (TF2 semantics)")
    size = _require_const(const_values, node, 1, "size")
    return sd._record("resize_bilinear", [ins[0]],
                      {"size": tuple(int(s) for s in np.atleast_1d(size))})


@register_tf_op("ResizeNearestNeighbor")
def _resize_nn_tf(sd, ins, attrs, node, const_values=None):
    if not bool(attrs.get("half_pixel_centers", False)) \
            or bool(attrs.get("align_corners", False)):
        raise NotImplementedError(
            "legacy ResizeNearestNeighbor (half_pixel_centers=false or "
            "align_corners=true) import — re-export with tf.image.resize "
            "(TF2 semantics)")
    size = _require_const(const_values, node, 1, "size")
    return sd._record("resize_nearest_neighbor", [ins[0]],
                      {"size": tuple(int(s) for s in np.atleast_1d(size))})


_NEEDS_CONSTS |= {"Split", "SplitV", "OneHot", "Range", "Fill", "Slice",
                  "BroadcastTo", "ResizeBilinear", "ResizeNearestNeighbor"}


@register_tf_op("TopKV2")
def _topk(sd, ins, attrs, node, const_values=None):
    k = _require_const(const_values, node, 1, "k")
    return sd._record("top_k", [ins[0]], {"k": int(k)}, n_out=2)


_NEEDS_CONSTS.add("TopKV2")


# ---------------------------------------------------------------------------
# TF2 function-graph control flow (round 4).
#
# Reference parity: org/nd4j/imports/graphmapper/tf/TFGraphMapper.java +
# org/nd4j/autodiff/samediff/internal/AbstractSession.java loop frames —
# the reference executes While/If by interpreting frames; here each branch
# FunctionDef imports into its own SameDiff and lowers onto
# lax.while_loop / lax.cond via SameDiff.while_loop_multi / cond_multi
# (SURVEY §4.3 maps TF frames to lax control flow).
# ---------------------------------------------------------------------------

_CONTROL_FLOW_OPS = {"While", "StatelessWhile", "If", "StatelessIf"}


def _function_ir(fdef, library):
    """FunctionDef → IRGraph. Function-body tensor addressing is
    'node:out_arg:idx' (vs the main graph's 'node:idx'); both normalize to
    the bare node name for slot 0 and 'node:idx' otherwise."""
    from tensorflow.python.framework import tensor_util

    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

    def norm(t):
        parts = t.split(":")
        if len(parts) == 1:
            return t  # plain input-arg reference
        if len(parts) == 3:
            base, _out_arg, idx = parts
            return base if idx == "0" else f"{base}:{idx}"
        base, idx = parts
        return base if idx == "0" else t

    nodes: List = []
    initializers: Dict[str, np.ndarray] = {}
    inputs = [(arg.name, None) for arg in fdef.signature.input_arg]
    for node in fdef.node_def:
        if node.op == "Const":
            initializers[node.name] = tensor_util.MakeNdarray(
                node.attr["value"].tensor)
            continue
        attrs = {k: _attr_value(v) for k, v in node.attr.items()}
        if node.op in _CONTROL_FLOW_OPS or node.op in _CALL_OPS:
            attrs["_library"] = library  # nested control flow recurses
        in_names = [norm(i) for i in node.input if not i.startswith("^")]
        nodes.append(IRNode(name=node.name, op_type=node.op,
                            inputs=in_names, outputs=[node.name],
                            attrs=attrs))
    outputs = [norm(fdef.ret[arg.name]) for arg in fdef.signature.output_arg]
    return IRGraph(nodes=nodes, initializers=initializers, inputs=inputs,
                   outputs=outputs, name="tf_function")


def _function_callable(fname, library):
    """Import a library FunctionDef and wrap it as a jnp-traceable callable
    (*vals) -> value | tuple(values) — a thin FunctionDef frontend over
    _ir_callable (the shared sub-graph execution wrapper)."""
    fdef = library.get(fname)
    if fdef is None:
        raise ValueError(f"control-flow branch function '{fname}' is not in "
                         f"the GraphDef function library")
    in_names = [a.name for a in fdef.signature.input_arg]
    return _ir_callable(_function_ir(fdef, library), in_names)


@register_tf_op("While")
@register_tf_op("StatelessWhile")
def _tf_while(sd, ins, attrs, node):
    library = attrs["_library"]
    cond_call, _ = _function_callable(attrs["cond"], library)
    body_call, n_body_out = _function_callable(attrs["body"], library)
    if n_body_out != len(ins):
        raise ValueError(
            f"While {node.name}: body returns {n_body_out} values for "
            f"{len(ins)} loop variables")

    def cond_fn(carry):
        import jax.numpy as jnp

        return jnp.asarray(cond_call(*carry)).astype(bool).reshape(())

    def body_fn(carry):
        out = body_call(*carry)
        return out if isinstance(out, tuple) else (out,)

    return sd.while_loop_multi(cond_fn, body_fn, ins)


@register_tf_op("If")
@register_tf_op("StatelessIf")
def _tf_if(sd, ins, attrs, node):
    library = attrs["_library"]
    then_call, n_then = _function_callable(attrs["then_branch"], library)
    else_call, n_else = _function_callable(attrs["else_branch"], library)
    if n_then != n_else:
        raise ValueError(f"If {node.name}: branch arities differ "
                         f"({n_then} vs {n_else})")

    if n_then == 1:
        # single-output branches return the bare value (a 1-tuple would
        # leak into the recorded node's single output slot)
        return sd.cond_multi(ins[0], then_call, else_call, ins[1:], n_out=1)

    def tuple_of(call):
        def fn(*vals):
            out = call(*vals)
            return out if isinstance(out, tuple) else (out,)

        return fn

    return sd.cond_multi(ins[0], tuple_of(then_call), tuple_of(else_call),
                         ins[1:], n_out=n_then)


# ---------------------------------------------------------------------------
# TF1 frame control flow (round 4): the form `convert_variables_to_constants_v2`
# emits by DEFAULT (lower_control_flow=True) and the form every legacy
# frozen .pb carries. Enter/Merge/Switch/Exit/NextIteration/LoopCond frames
# collapse into one synthetic while node per frame; frameless Switch/Merge
# conditionals collapse into pred-selects (both branches run eagerly — pure
# frozen graphs make that safe, and XLA prunes the unused side when the
# predicate is constant).
#
# Reference parity: org/nd4j/autodiff/samediff/internal/AbstractSession.java
# interprets these frames at runtime; SURVEY §4.3 maps them onto lax loops.
# ---------------------------------------------------------------------------


def _base(t: str) -> str:
    return t.split(":")[0]


def _collect_subgraph(roots, leaf_names, producer, initializers):
    """Backward ancestor walk from ``roots`` stopping at ``leaf_names``
    (exact tensor refs or bare node names) and at initializers. Returns
    (nodes in topological order, initializer subset)."""
    nodes, inits, seen = [], {}, set()
    # iterative post-order (ADVICE r4 #2: deep sequential graphs blow the
    # Python recursion limit) — the `expanded` flag marks the second visit,
    # after all ancestors are emitted, preserving topological order
    stack = [(r, False) for r in reversed(list(roots))]
    while stack:
        t, expanded = stack.pop()
        if expanded:
            nodes.append(producer[_base(t)])
            continue
        if t in leaf_names:
            continue
        base = _base(t)
        if base in leaf_names or base in seen:
            continue
        if base in initializers:
            inits[base] = initializers[base]
            continue
        n = producer.get(base)
        if n is None:
            continue  # main-graph placeholder or unresolvable — walker errors later
        seen.add(base)
        stack.append((t, True))
        for i in reversed(n.inputs):
            stack.append((i, False))
    return nodes, inits


def _collapse_tf1_control_flow(ir):
    """IRGraph → IRGraph with TF1 frames and frameless conds collapsed."""
    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

    ops = {n.op_type for n in ir.nodes}
    if not ({"Enter", "Switch", "Merge"} & ops):
        return ir

    producer = {n.name: n for n in ir.nodes}
    consumers: Dict[str, List] = {}
    for n in ir.nodes:
        for i in n.inputs:
            consumers.setdefault(_base(i), []).append(n)

    # ---- frames ------------------------------------------------------------
    frames: Dict[str, List] = {}
    for n in ir.nodes:
        if n.op_type == "Enter":
            fname = n.attrs.get("frame_name", b"")
            fname = fname.decode() if isinstance(fname, bytes) else str(fname)
            frames.setdefault(fname, []).append(n)

    removed: set = set()
    synthetic: List[Tuple[int, IRNode]] = []  # (insert position, node)
    order = {n.name: i for i, n in enumerate(ir.nodes)}

    for fname, enters in frames.items():
        # forward BFS from the Enter outputs to find the frame's control nodes
        member: set = set()
        frontier = [e.name for e in enters]
        loopcond = None
        while frontier:
            nm = frontier.pop()
            for c in consumers.get(nm, []):
                if c.name in member:
                    continue
                if c.op_type == "Enter":
                    raise NotImplementedError(
                        f"nested TF1 loop frames (frame '{fname}' feeds "
                        f"Enter '{c.name}') are not supported")
                member.add(c.name)
                if c.op_type == "LoopCond":
                    loopcond = c
                if c.op_type != "Exit":  # frame boundary: don't cross
                    frontier.append(c.name)
        if loopcond is None:
            raise ValueError(f"TF1 frame '{fname}' has no LoopCond node")

        # per-variable chains: Enter -> Merge -> Switch -> (Exit?, NextIteration)
        real_vars, invariants = [], []
        for e in enters:
            merge = next((c for c in consumers.get(e.name, [])
                          if c.op_type == "Merge"), None)
            if merge is None:
                invariants.append(e)  # loop-invariant (is_constant) Enter
                continue
            switch = next((c for c in consumers.get(merge.name, [])
                           if c.op_type == "Switch"), None)
            if switch is None:
                raise ValueError(f"frame '{fname}': Merge {merge.name} has "
                                 f"no Switch consumer")
            exit_n = next((c for c in consumers.get(switch.name, [])
                           if c.op_type == "Exit"), None)
            ni_name = _base(merge.inputs[1])
            next_it = producer.get(ni_name)
            if next_it is None or next_it.op_type != "NextIteration":
                raise ValueError(f"frame '{fname}': Merge {merge.name} second "
                                 f"input is not a NextIteration")
            real_vars.append((e, merge, switch, exit_n, next_it))

        cond_inputs = [m.name for _, m, _, _, _ in real_vars] + \
            [e.name for e in invariants]
        body_inputs = [f"{s.name}:1" for _, _, s, _, _ in real_vars] + \
            [e.name for e in invariants]

        cond_root = loopcond.inputs[0]
        body_roots = [ni.inputs[0] for _, _, _, _, ni in real_vars]
        leafset = set(cond_inputs) | set(body_inputs)
        cond_nodes, cond_inits = _collect_subgraph(
            [cond_root], leafset, producer, ir.initializers)
        body_nodes, body_inits = _collect_subgraph(
            body_roots, leafset, producer, ir.initializers)

        cond_ir = IRGraph(nodes=cond_nodes, initializers=cond_inits,
                          inputs=[(nm, None) for nm in cond_inputs],
                          outputs=[cond_root], name="tf1_cond")
        body_ir = IRGraph(nodes=body_nodes, initializers=body_inits,
                          inputs=[(nm, None) for nm in body_inputs],
                          outputs=list(body_roots), name="tf1_body")

        init_inputs = [e.inputs[0] for e, _, _, _, _ in real_vars] + \
            [e.inputs[0] for e in invariants]
        exit_outputs, exit_slots = [], []
        for j, (_, _, _, exit_n, _) in enumerate(real_vars):
            if exit_n is not None:
                exit_outputs.append(exit_n.name)
                exit_slots.append(j)
        if not exit_outputs:
            raise ValueError(f"frame '{fname}' has no Exit outputs")

        syn = IRNode(
            name=fname or exit_outputs[0], op_type="_TF1While",
            inputs=init_inputs, outputs=exit_outputs,
            attrs={"cond_ir": cond_ir, "body_ir": body_ir,
                   "cond_inputs": cond_inputs, "body_inputs": body_inputs,
                   "n_real": len(real_vars), "exit_slots": exit_slots})

        frame_removed = member | {e.name for e in enters} | \
            {n.name for n in cond_nodes} | {n.name for n in body_nodes}
        removed |= frame_removed
        pos = min(order[nm] for nm in frame_removed if nm in order)
        synthetic.append((pos, syn))

    # ---- frameless conds ---------------------------------------------------
    def switch_crossings(t, seen, out):
        """Collect pred -> {slots} for every Switch crossed on any path
        upstream of tensor ``t``. The walk continues THROUGH a Switch's
        data input (so outer conds are visible past inner ones) but not
        into its pred input (the pred is evaluated before branching).
        Iterative (ADVICE r4 #2: deep graphs overflow Python recursion)."""
        stack = [t]
        while stack:
            t = stack.pop()
            base = _base(t)
            # memo on the full tensor ref: the same Switch may be crossed at
            # BOTH slots within one branch (a cond nested inside it) and
            # each slot must be recorded
            if t in seen or base in removed:
                continue
            seen.add(t)
            n = producer.get(base)
            if n is None:
                continue
            if n.op_type == "Switch":
                slot = t.split(":")[1] if ":" in t else "0"
                out.setdefault(n.inputs[1], set()).add(slot)
                stack.append(n.inputs[0])
                continue
            stack.extend(n.inputs)

    def resolve_merge_pred(merge):
        """The cond a Merge closes is the pred whose switches are crossed
        with slot 1 on exactly one input and slot 0 on the other — a pred
        crossed with BOTH slots inside one input belongs to a cond nested
        within that branch, not to this Merge."""
        cA: Dict[str, set] = {}
        cB: Dict[str, set] = {}
        switch_crossings(merge.inputs[0], set(), cA)
        switch_crossings(merge.inputs[1], set(), cB)
        for pred in set(cA) | set(cB):
            sA, sB = cA.get(pred, set()), cB.get(pred, set())
            if sA == {"1"} and sB == {"0"}:
                return pred, 0
            if sA == {"0"} and sB == {"1"}:
                return pred, 1
        # one branch never crosses a switch (e.g. constant-only branch):
        # the other branch's single consistent slot decides
        for cX, idx in ((cA, 0), (cB, 1)):
            other = cB if idx == 0 else cA
            for pred, slots in cX.items():
                if len(slots) == 1 and pred not in other:
                    s = next(iter(slots))
                    return pred, idx if s == "1" else 1 - idx
        return None, None

    new_nodes: List[IRNode] = []
    for n in ir.nodes:
        if n.name in removed:
            continue
        if n.op_type == "Switch":
            n = IRNode(name=n.name, op_type="_TFSwitchPassthrough",
                       inputs=[n.inputs[0]],
                       outputs=[n.name, f"{n.name}:1"], attrs={})
        elif n.op_type == "Merge":
            for c in consumers.get(n.name, []):
                if any(i == f"{n.name}:1" for i in c.inputs):
                    raise NotImplementedError(
                        f"Merge {n.name}: value_index output is consumed")
            pred, true_idx = resolve_merge_pred(n)
            if pred is None:
                raise NotImplementedError(
                    f"frameless Merge {n.name}: no switch predicate with "
                    f"consistent branch slots; cannot recover the cond")
            n = IRNode(name=n.name, op_type="_TFMergeSelect",
                       inputs=[n.inputs[0], n.inputs[1], pred],
                       outputs=[n.name], attrs={"true_idx": true_idx})
        new_nodes.append(n)

    for pos, syn in sorted(synthetic, key=lambda x: x[0]):
        # insert before the first surviving node whose original position
        # follows the frame, so consumers of the Exit names come later
        idx = 0
        for idx, nn in enumerate(new_nodes):
            if order.get(nn.name, -1) > pos:
                break
        else:
            idx = len(new_nodes)
        new_nodes.insert(idx, syn)

    return IRGraph(nodes=new_nodes, initializers=ir.initializers,
                   inputs=ir.inputs, outputs=ir.outputs, name=ir.name)


def _ir_callable(ir, in_names):
    """Import a sub-IRGraph into a private SameDiff and wrap as a
    jnp-traceable callable (*vals) -> value | tuple(values)."""
    from deeplearning4j_tpu.imports.ir import IRImporter

    ir = _inline_function_calls(ir)  # helper tf.functions inside bodies
    ir = _collapse_tf1_control_flow(ir)  # conds nested inside loop bodies
    walker = IRImporter(TF_OP_MAPPERS, needs_consts=_NEEDS_CONSTS,
                        trainable_consts=False)
    sub = walker.run_import(ir)
    out_names = list(sub.graph_outputs)

    def call(*vals):
        import jax.numpy as jnp

        env = dict(sub._arrays)
        for n, v in zip(in_names, vals):
            env[n] = jnp.asarray(v)
        res = sub._interpret(env, out_names)
        outs = [res[n] for n in out_names]
        return outs[0] if len(outs) == 1 else tuple(outs)

    return call, len(out_names)


@register_tf_op("_TF1While")
def _tf1_while(sd, ins, attrs, node):
    cond_call, _ = _ir_callable(attrs["cond_ir"], attrs["cond_inputs"])
    body_call, _ = _ir_callable(attrs["body_ir"], attrs["body_inputs"])
    n_real = attrs["n_real"]

    def cond_fn(carry):
        import jax.numpy as jnp

        return jnp.asarray(cond_call(*carry)).astype(bool).reshape(())

    def body_fn(carry):
        out = body_call(*carry)
        out = out if isinstance(out, tuple) else (out,)
        return tuple(out) + tuple(carry[n_real:])  # invariants pass through

    finals = sd.while_loop_multi(cond_fn, body_fn, ins)
    if not isinstance(finals, tuple):
        finals = (finals,)
    return [finals[j] for j in attrs["exit_slots"]]


@register_tf_op("_TFSwitchPassthrough")
def _tf_switch_passthrough(sd, ins, attrs, node):
    # both branches run eagerly; the paired _TFMergeSelect picks by pred
    a = sd._record("identity", [ins[0]])
    b = sd._record("identity", [ins[0]])
    return (a, b)


@register_tf_op("_TFMergeSelect")
def _tf_merge_select(sd, ins, attrs, node):
    t = attrs["true_idx"]
    return sd._record("select", [ins[2], ins[t], ins[1 - t]])


# ---------------------------------------------------------------------------
# SavedModel import with variable restore (round 4).
#
# Reference parity: TFGraphMapper step (1) — restore TF checkpoint variables
# into VARIABLE-role arrays before mapping ops (SURVEY §4.3), so fine-tuning
# an imported model starts from its trained weights. TF2 SavedModels route
# the serving computation through StatefulPartitionedCall into the function
# library with VarHandleOp resource captures; the importer inlines the call
# tree into one flat graph, turns each VarHandleOp into a trainable
# SDVariable holding its checkpoint value, and ReadVariableOp into a
# pass-through.
# ---------------------------------------------------------------------------

_CALL_OPS = {"PartitionedCall", "StatefulPartitionedCall"}
_VARIABLE_OPS = {"VarHandleOp", "VariableV2", "VarIsInitializedOp"}


def _inline_function_calls(ir, variable_values=None):
    """Expand PartitionedCall/StatefulPartitionedCall nodes in place: the
    callee's nodes join the graph under a '<call>/' name prefix, its input
    args remap to the call operands, and a tuple alias keeps the call's own
    output names ('call', 'call:1', ...) resolvable. Repeats until no call
    nodes remain (nested wrapper functions)."""
    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

    for _ in range(32):  # nesting depth bound
        if not any(n.op_type in _CALL_OPS for n in ir.nodes):
            return ir
        new_nodes: List[IRNode] = []
        for n in ir.nodes:
            if n.op_type not in _CALL_OPS:
                new_nodes.append(n)
                continue
            library = n.attrs.get("_library") or {}
            fname = n.attrs.get("f")
            fdef = library.get(fname)
            if fdef is None:
                raise ValueError(
                    f"{n.op_type} {n.name}: function '{fname}' is not in "
                    f"the GraphDef library")
            fir = _function_ir(fdef, library)
            prefix = n.name + "/"
            arg_names = [a.name for a in fdef.signature.input_arg]
            argmap = dict(zip(arg_names, n.inputs))
            local = {fn.name for fn in fir.nodes} | set(fir.initializers)

            def remap(t, _argmap=argmap, _local=local, _prefix=prefix):
                base, sep, slot = t.partition(":")
                if base in _argmap:
                    mapped = _argmap[base]
                    return mapped + sep + slot if slot else mapped
                if base in _local:
                    return _prefix + t
                return t  # outer-graph reference (rare; left as-is)

            for iname, arr in fir.initializers.items():
                ir.initializers[prefix + iname] = arr
            for fn_node in fir.nodes:
                attrs = fn_node.attrs
                if fn_node.op_type in _VARIABLE_OPS:
                    # a variable op living INSIDE a function body still
                    # needs the checkpoint table the outer call carried
                    attrs = dict(attrs)
                    attrs.setdefault("_var_values", variable_values or {})
                new_nodes.append(IRNode(
                    name=prefix + fn_node.name, op_type=fn_node.op_type,
                    inputs=[remap(i) for i in fn_node.inputs],
                    outputs=[prefix + fn_node.name], attrs=attrs))
            rets = [remap(o) for o in fir.outputs]
            if not rets:
                continue  # side-effect-only call (init path): nothing to alias
            new_nodes.append(IRNode(name=n.name, op_type="_TFTuple",
                                    inputs=rets, outputs=[n.name], attrs={}))
        ir = IRGraph(nodes=new_nodes, initializers=ir.initializers,
                     inputs=ir.inputs, outputs=ir.outputs, name=ir.name)
    raise ValueError("function-call nesting exceeds 32 levels")


@register_tf_op("_TFTuple")
def _tf_tuple(sd, ins, attrs, node):
    # alias node: exposes an inlined call's return values under the call's
    # own output names (slot addressing included)
    return ins[0] if len(ins) == 1 else tuple(ins)


@register_tf_op("VarHandleOp")
@register_tf_op("VariableV2")
def _var_handle(sd, ins, attrs, node):
    values = attrs.get("_var_values") or {}
    shared = attrs.get("shared_name", b"") or node.name
    shared = shared.decode() if isinstance(shared, bytes) else str(shared)
    if shared in values:
        return sd.var(node.name, np.asarray(values[shared]))
    # object-based checkpoints key by attribute path, not variable name:
    # fall back to a UNIQUE shape match
    want = attrs.get("shape")
    shape = tuple(d.size for d in want.dim) if want is not None else None
    matches = [k for k, v in values.items() if np.shape(v) == shape]
    if len(matches) == 1:
        # a silent mis-bind here would fine-tune from the wrong weights, so
        # name the matched key loudly (ADVICE r4 #1)
        warnings.warn(
            f"{node.op_type} {node.name}: variable '{shared}' not in the "
            f"checkpoint by name; bound by unique shape {shape} to "
            f"checkpoint key '{matches[0]}' — verify this is the intended "
            f"weight", stacklevel=2)
        return sd.var(node.name, np.asarray(values[matches[0]]))
    raise ValueError(
        f"{node.op_type} {node.name}: no checkpoint value for variable "
        f"'{shared}' (shape {shape}); checkpoint has "
        f"{sorted(values)[:10]}{'…' if len(values) > 10 else ''} — pass "
        f"variable_values= with matching names")


@register_tf_op("ReadVariableOp")
def _read_variable(sd, ins, attrs, node):
    return ins[0]


def _prune_to_outputs(graph_def, output_names):
    """Drop nodes that are not ancestors of the requested outputs — the
    SavedModel init/restore subgraph (RestoreV2, AssignVariableOp) must not
    reach the importer."""
    keep = set()
    by_name = {n.name: n for n in graph_def.node}
    stack = [o.split(":")[0] for o in output_names]
    while stack:
        nm = stack.pop()
        if nm in keep:
            continue
        keep.add(nm)
        node = by_name.get(nm)
        if node is None:
            continue
        for i in node.input:
            stack.append(i.lstrip("^").split(":")[0])
    import copy

    out = copy.deepcopy(graph_def)
    del out.node[:]
    for n in graph_def.node:
        if n.name in keep:
            out.node.add().CopyFrom(n)
    return out


def load_saved_model_variables(path: str) -> Dict[str, np.ndarray]:
    """Read every variable value from a SavedModel's object-based
    checkpoint, keyed by the variable's ``full_name`` (e.g. 'dense/kernel'
    — what VarHandleOp.shared_name carries) when the trackable object
    graph provides it, with the raw object path as a fallback key.
    Optimizer slot variables (Adam m/v, momentum) and the save_counter are
    excluded — they are not model weights and would poison shape-based
    matching."""
    import os

    import tensorflow as tf

    reader = tf.train.load_checkpoint(os.path.join(path, "variables",
                                                   "variables"))
    suffix = "/.ATTRIBUTES/VARIABLE_VALUE"
    values: Dict[str, np.ndarray] = {}
    for key in reader.get_variable_to_shape_map():
        if (key.endswith(suffix) and "/.OPTIMIZER_SLOT/" not in key
                and key != "save_counter" + suffix):
            obj_path = key[: -len(suffix)]
            if obj_path != "save_counter":
                values[obj_path] = reader.get_tensor(key)
    try:
        from tensorflow.core.protobuf import trackable_object_graph_pb2

        og = trackable_object_graph_pb2.TrackableObjectGraph()
        og.ParseFromString(
            reader.get_tensor("_CHECKPOINTABLE_OBJECT_GRAPH"))
        for node in og.nodes:
            for attr in node.attributes:
                if attr.full_name and attr.checkpoint_key.endswith(suffix):
                    values[attr.full_name] = reader.get_tensor(
                        attr.checkpoint_key)
    except Exception:
        pass  # older layout without the object graph: object paths only
    return values


def import_saved_model(path: str, *, signature: str = "serving_default",
                       extra_variable_values=None) -> SameDiff:
    """SavedModel directory → SameDiff with trained weights restored as
    VARIABLE-role SDVariables (TFGraphMapper checkpoint restore +
    SameDiffServlet-style signature IO resolution)."""
    import os

    from tensorflow.core.protobuf import saved_model_pb2

    sm = saved_model_pb2.SavedModel()
    with open(os.path.join(path, "saved_model.pb"), "rb") as f:
        sm.ParseFromString(f.read())
    mg = sm.meta_graphs[0]
    if signature not in mg.signature_def:
        raise ValueError(f"SavedModel has no signature '{signature}'; "
                         f"found {sorted(mg.signature_def)}")
    sig = mg.signature_def[signature]
    # protobuf map iteration order is not contractual — sort by signature key
    # so multi-output order is stable across environments (ADVICE r4 #3)
    out_tensors = [t.name for _, t in sorted(sig.outputs.items())]
    in_tensors = [t.name for _, t in sorted(sig.inputs.items())]

    def norm(t):
        base, _, slot = t.partition(":")
        return base if slot in ("", "0") else f"{base}:{slot}"

    gd = _prune_to_outputs(mg.graph_def, out_tensors)
    values = load_saved_model_variables(path)
    if extra_variable_values:
        values.update(extra_variable_values)
    # slot-qualified outputs ('call:1') ride ir.outputs so the walker
    # aliases them to fetchable variables instead of collapsing to slot 0
    sd = TensorflowImporter().run_import(gd, variable_values=values,
                                         outputs=[norm(t) for t in out_tensors])
    sd.graph_inputs = [t.split(":")[0] for t in in_tensors]
    sd.graph_outputs = [norm(t) for t in out_tensors]
    return sd


# ---------------------------------------------------------------------------
# Round-4 breadth: the remaining common-frozen-graph ops (Einsum, GatherNd,
# AddN, logical reductions, MirrorPad, Conv2DBackpropInput, ...).
# ---------------------------------------------------------------------------


@register_tf_op("Einsum")
def _einsum_tf(sd, ins, attrs, node):
    eq = attrs.get("equation", b"")
    eq = eq.decode() if isinstance(eq, bytes) else str(eq)
    return sd._record("einsum", ins, {"equation": eq})


@register_tf_op("GatherNd")
def _gather_nd_tf(sd, ins, attrs, node):
    return sd._record("gather_nd", ins)


@register_tf_op("AddN")
def _add_n(sd, ins, attrs, node):
    out = ins[0]
    for x in ins[1:]:
        out = sd._record("add", [out, x])
    return out


@register_tf_op("Cumprod")
def _cumprod_tf(sd, ins, attrs, node, const_values=None):
    axis = int(np.asarray(_require_const(const_values, node, 1,
                                         "axis")).reshape(-1)[0])
    return sd._record("cumprod", [ins[0]],
                      {"axis": axis,
                       "exclusive": bool(attrs.get("exclusive", False)),
                       "reverse": bool(attrs.get("reverse", False))})


@register_tf_op("MirrorPad")
def _mirror_pad_tf(sd, ins, attrs, node, const_values=None):
    pads = _require_const(const_values, node, 1, "paddings")
    mode = attrs.get("mode", b"REFLECT")
    mode = mode.decode() if isinstance(mode, bytes) else str(mode)
    return sd._record("mirror_pad", [ins[0]],
                      {"paddings": tuple((int(a), int(b)) for a, b in pads),
                       "mode": mode.lower()})


for _tf, _ours in [("Erfc", "erfc"), ("Atanh", "atanh"), ("Asinh", "asinh"),
                   ("Acosh", "acosh"), ("Expm1", "expm1")]:
    def _mk_unary(ours):
        def f(sd, ins, attrs, node):
            return sd._record(ours, ins)

        return f

    TF_OP_MAPPERS[_tf] = _mk_unary(_ours)


@register_tf_op("LogicalAnd")
def _logical_and(sd, ins, attrs, node):
    return sd._record("boolean_and", ins)


@register_tf_op("LogicalOr")
def _logical_or(sd, ins, attrs, node):
    return sd._record("boolean_or", ins)


@register_tf_op("LogicalNot")
def _logical_not(sd, ins, attrs, node):
    return sd._record("boolean_not", ins)


@register_tf_op("Xdivy")
def _xdivy(sd, ins, attrs, node):
    # x/y where x != 0, else 0 — composed from recorded catalog ops
    zero = sd._record("zeros_like", [ins[0]])
    safe_y = sd._record("select", [sd._record("eq", [ins[0], zero]),
                                   sd._record("ones_like", [ins[1]]),
                                   ins[1]])
    quot = sd._record("div", [ins[0], safe_y])
    return sd._record("select", [sd._record("eq", [ins[0], zero]),
                                 zero, quot])


@register_tf_op("SelectV2")
def _select_v2_tf(sd, ins, attrs, node):
    return sd._record("select", ins)


@register_tf_op("Select")
def _select_tf(sd, ins, attrs, node):
    # TF v1 Select: rank-1 cond broadcasts over the FIRST dim of x/y
    return sd._record("select_v1", ins)


@register_tf_op("Where")
def _where_tf(sd, ins, attrs, node):
    raise NotImplementedError(
        "1-arg tf.where (argwhere) has a data-dependent output shape XLA "
        "cannot express — use tf.where(cond, x, y), which imports as "
        "Select/SelectV2")


@register_tf_op("All")
def _reduce_all_tf(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_all", [ins[0]],
                      {"axis": tuple(int(a) for a in np.atleast_1d(axes)),
                       "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("Any")
def _reduce_any_tf(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_any", [ins[0]],
                      {"axis": tuple(int(a) for a in np.atleast_1d(axes)),
                       "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("Conv2DBackpropInput")
def _conv2d_backprop_input(sd, ins, attrs, node, const_values=None):
    """tf.nn.conv2d_transpose lowers to this op: (output_shape, filter,
    value) with the FORWARD filter (kh, kw, out, in) — exactly keras
    Conv2DTranspose, so it lowers onto deconv2d the same way."""
    strides = attrs.get("strides", [1, 1, 1, 1])
    padding = attrs.get("padding", b"SAME")
    pad = padding.decode() if isinstance(padding, bytes) else str(padding)
    if pad not in ("SAME", "VALID"):
        raise NotImplementedError(f"Conv2DBackpropInput padding={pad}")
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise NotImplementedError("only NHWC Conv2DBackpropInput import")
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])]
    if dil != [1, 1, 1, 1]:
        raise NotImplementedError(
            f"Conv2DBackpropInput with dilations={dil} import")
    if int(strides[0]) != 1 or int(strides[3]) != 1:
        raise NotImplementedError(
            "Conv2DBackpropInput with batch/channel strides import")
    w = sd._record("transpose", [ins[1]], {"axes": (0, 1, 3, 2)})
    return sd._record("deconv2d", [ins[2], w],
                      {"stride": (int(strides[1]), int(strides[2])),
                       "padding": pad.lower() if pad == "SAME" else "valid"})


_NEEDS_CONSTS |= {"Cumprod", "MirrorPad", "All", "Any",
                  "Conv2DBackpropInput"}


@register_tf_op("ResourceGather")
def _resource_gather(sd, ins, attrs, node):
    """tf.gather on a resource variable (embedding lookup path): the
    VarHandleOp mapper already resolved the resource to its value."""
    if int(attrs.get("batch_dims", 0)):
        raise NotImplementedError("ResourceGather with batch_dims import")
    return sd._record("gather", [ins[0], ins[1]], {"axis": 0})


@register_tf_op("Shape")
def _shape_tf(sd, ins, attrs, node):
    return sd._record("shape_of", ins)


@register_tf_op("SpaceToBatchND")
def _space_to_batch_nd_tf(sd, ins, attrs, node, const_values=None):
    block = _require_const(const_values, node, 1, "block_shape")
    pads = _require_const(const_values, node, 2, "paddings")
    return sd._record("space_to_batch", [ins[0]], {
        "block_shape": tuple(int(b) for b in np.atleast_1d(block)),
        "paddings": tuple((int(a), int(b)) for a, b in np.atleast_2d(pads))})


@register_tf_op("BatchToSpaceND")
def _batch_to_space_nd_tf(sd, ins, attrs, node, const_values=None):
    block = _require_const(const_values, node, 1, "block_shape")
    crops = _require_const(const_values, node, 2, "crops")
    return sd._record("batch_to_space", [ins[0]], {
        "block_shape": tuple(int(b) for b in np.atleast_1d(block)),
        "crops": tuple((int(a), int(b)) for a, b in np.atleast_2d(crops))})


_NEEDS_CONSTS |= {"SpaceToBatchND", "BatchToSpaceND"}


# ---------------------------------------------------------------------------
# Dialect widening, round 5: segment/scatter/linalg/image/math tails toward
# the reference tensorflow mapping ruleset (samediff-import-tensorflow,
# SURVEY §3.2). All map 1:1 onto catalog declarables.
# ---------------------------------------------------------------------------

for _tf2, _ours2 in [("Rint", "rint"), ("Digamma", "digamma"),
                     ("Lgamma", "lgamma"), ("Cholesky", "cholesky"),
                     ("MatrixInverse", "matrix_inverse"),
                     ("MatrixSolve", "solve"), ("Diag", "diag"),
                     ("DiagPart", "diag_part"),
                     ("MatrixDiag", "matrix_diag"),
                     ("InvertPermutation", "invert_permutation"),
                     ("Betainc", "betainc"), ("Igamma", "igamma"),
                     ("Igammac", "igammac"), ("Polygamma", "polygamma")]:
    def _mk_direct(ours):
        def f(sd, ins, attrs, node):
            return sd._record(ours, ins)

        return f

    TF_OP_MAPPERS[_tf2] = _mk_direct(_ours2)


def _mk_segment(ours, needs_num: bool):
    def f(sd, ins, attrs, node, const_values=None):
        if needs_num:
            num = int(np.asarray(
                _require_const(const_values, node, 2, "num_segments")))
            return sd._record(ours, ins[:2], {"num_segments": num})
        # sorted Segment* ops carry no num_segments input — it must come
        # from the (constant) segment id tensor itself
        ids = (const_values or {}).get(node.input[1].split(":")[0])
        if ids is None:
            raise ValueError(
                f"{node.op_type} {node.name}: segment_ids must be constant "
                f"(XLA needs a static segment count)")
        return sd._record(ours, ins[:2],
                          {"num_segments": int(np.asarray(ids).max()) + 1})

    return f


for _tf2, _ours2 in [("SegmentSum", "segment_sum"),
                     ("SegmentMax", "segment_max"),
                     ("SegmentMin", "segment_min"),
                     ("SegmentMean", "segment_mean"),
                     ("SegmentProd", "segment_prod")]:
    TF_OP_MAPPERS[_tf2] = _mk_segment(_ours2, needs_num=False)
    _NEEDS_CONSTS.add(_tf2)

for _tf2, _ours2 in [("UnsortedSegmentSum", "unsorted_segment_sum"),
                     ("UnsortedSegmentMax", "unsorted_segment_max"),
                     ("UnsortedSegmentMin", "unsorted_segment_min"),
                     ("UnsortedSegmentProd", "unsorted_segment_prod")]:
    TF_OP_MAPPERS[_tf2] = _mk_segment(_ours2, needs_num=True)
    _NEEDS_CONSTS.add(_tf2)


@register_tf_op("ScatterNd")
def _tf_scatter_nd(sd, ins, attrs, node, const_values=None):
    shape = tuple(int(s) for s in np.asarray(
        _require_const(const_values, node, 2, "shape")).reshape(-1))
    return sd._record("scatter_nd", ins[:2], {"shape": shape})


_NEEDS_CONSTS.add("ScatterNd")


@register_tf_op("TensorScatterUpdate")
def _tf_tensor_scatter_update(sd, ins, attrs, node):
    return sd._record("scatter_nd_update", ins)


@register_tf_op("TensorScatterAdd")
def _tf_tensor_scatter_add(sd, ins, attrs, node):
    return sd._record("scatter_nd_add", ins)


@register_tf_op("ReverseV2")
def _tf_reverse(sd, ins, attrs, node, const_values=None):
    axis = np.asarray(_require_const(const_values, node, 1, "axis")).reshape(-1)
    return sd._record("reverse", [ins[0]],
                      {"axis": tuple(int(a) for a in axis)})


@register_tf_op("Reverse")
def _tf_reverse_v1(sd, ins, attrs, node, const_values=None):
    # TF1 Reverse's second operand is a PER-DIMENSION bool mask
    dims = np.asarray(_require_const(const_values, node, 1, "dims")).reshape(-1)
    axes = tuple(i for i, flag in enumerate(dims) if bool(flag))
    if not axes:
        return sd._record("identity", [ins[0]])
    return sd._record("reverse", [ins[0]], {"axis": axes})


_NEEDS_CONSTS.add("Reverse")


_NEEDS_CONSTS.add("ReverseV2")


@register_tf_op("Roll")
def _tf_roll(sd, ins, attrs, node, const_values=None):
    shift = np.asarray(_require_const(const_values, node, 1, "shift")).reshape(-1)
    axis = np.asarray(_require_const(const_values, node, 2, "axis")).reshape(-1)
    return sd._record("roll", [ins[0]],
                      {"shift": tuple(int(s) for s in shift),
                       "axis": tuple(int(a) for a in axis)})


_NEEDS_CONSTS.add("Roll")


@register_tf_op("MatrixBandPart")
def _tf_band_part(sd, ins, attrs, node, const_values=None):
    lo = int(np.asarray(_require_const(const_values, node, 1, "num_lower")))
    hi = int(np.asarray(_require_const(const_values, node, 2, "num_upper")))
    return sd._record("matrix_band_part", [ins[0]],
                      {"num_lower": lo, "num_upper": hi})


_NEEDS_CONSTS.add("MatrixBandPart")


@register_tf_op("MatrixSetDiag")
@register_tf_op("MatrixSetDiagV3")
def _tf_set_diag(sd, ins, attrs, node):
    return sd._record("matrix_set_diag", ins[:2])


from deeplearning4j_tpu.autodiff.samediff import GRAPH_OPS as _GRAPH_OPS

if "pad_to_matrix_shape" not in _GRAPH_OPS:
    def _pad_to_matrix_shape(a, *, rows, cols):
        import jax.numpy as _jnp

        pr = rows - a.shape[-2]
        pc = cols - a.shape[-1]
        if pr < 0 or pc < 0:
            raise ValueError(
                f"pad_to_matrix_shape: target ({rows},{cols}) smaller than "
                f"diag matrix {a.shape[-2:]}")
        cfg = [(0, 0)] * (a.ndim - 2) + [(0, pr), (0, pc)]
        return _jnp.pad(a, cfg)

    _GRAPH_OPS["pad_to_matrix_shape"] = _pad_to_matrix_shape


@register_tf_op("MatrixDiagV3")
def _tf_matrix_diag_v3(sd, ins, attrs, node, const_values=None):
    # 5-operand form (diagonal, k, num_rows, num_cols, padding_value) —
    # what tf.eye/tf.linalg.diag lower to. Supported: main diagonal,
    # default/square sizing, zero padding.
    def cval(i):
        return (const_values or {}).get(node.input[i].split(":")[0])

    k = cval(1)
    if k is not None and np.any(np.asarray(k) != 0):
        raise NotImplementedError(
            f"MatrixDiagV3 {node.name}: off-main diagonals (k != 0)")
    rows, cols = cval(2), cval(3)
    pad = cval(4)
    if pad is not None and np.any(np.asarray(pad) != 0):
        raise NotImplementedError(
            f"MatrixDiagV3 {node.name}: non-zero padding_value")
    out = sd._record("matrix_diag", [ins[0]])
    if rows is not None and int(np.asarray(rows)) != -1:
        if cols is None:
            raise NotImplementedError(
                f"MatrixDiagV3 {node.name}: constant num_rows with dynamic "
                f"num_cols")
        r_ = int(np.asarray(rows))
        c_ = int(np.asarray(cols)) if int(np.asarray(cols)) != -1 else r_
        # matrix_diag emits (…, d, d) for a length-d diagonal; a larger
        # requested shape zero-pads on the high side (tf.linalg.diag
        # num_rows/num_cols semantics with the main diagonal)
        out = sd._record("pad_to_matrix_shape", [out],
                         {"rows": r_, "cols": c_})
    return out


_NEEDS_CONSTS.add("MatrixDiagV3")


@register_tf_op("Qr")
def _tf_qr(sd, ins, attrs, node):
    return sd._record("qr", ins, {"full_matrices":
                                  bool(attrs.get("full_matrices", False))},
                      n_out=2)


@register_tf_op("LinSpace")
def _tf_linspace(sd, ins, attrs, node, const_values=None):
    start = float(np.asarray(_require_const(const_values, node, 0, "start")))
    stop = float(np.asarray(_require_const(const_values, node, 1, "stop")))
    num = int(np.asarray(_require_const(const_values, node, 2, "num")))
    return sd._record("linspace", [], {"start": start, "stop": stop,
                                       "num": num})


_NEEDS_CONSTS.add("LinSpace")


@register_tf_op("HistogramFixedWidth")
def _tf_hist(sd, ins, attrs, node, const_values=None):
    rng = np.asarray(_require_const(const_values, node, 1, "value_range")
                     ).reshape(-1)
    nbins = int(np.asarray(_require_const(const_values, node, 2, "nbins"))) \
        if len(node.input) > 2 else 100
    return sd._record("histogram_fixed_width", [ins[0]],
                      {"range": (float(rng[0]), float(rng[1])),
                       "num_bins": nbins})


_NEEDS_CONSTS.add("HistogramFixedWidth")


@register_tf_op("ExtractImagePatches")
def _tf_patches(sd, ins, attrs, node):
    ksizes = [int(k) for k in attrs["ksizes"]]
    strides = [int(s) for s in attrs["strides"]]
    rates = [int(r) for r in attrs.get("rates", [1, 1, 1, 1])]
    pad = attrs.get("padding", b"VALID")
    pad = pad.decode() if isinstance(pad, bytes) else str(pad)
    return sd._record("extract_image_patches", [ins[0]],
                      {"kernel": (ksizes[1], ksizes[2]),
                       "strides": (strides[1], strides[2]),
                       "rates": (rates[1], rates[2]), "padding": pad})


@register_tf_op("InTopKV2")
def _tf_in_top_k(sd, ins, attrs, node, const_values=None):
    k = int(np.asarray(_require_const(const_values, node, 2, "k")))
    return sd._record("in_top_k", ins[:2], {"k": k})


_NEEDS_CONSTS.add("InTopKV2")


@register_tf_op("NthElement")
def _tf_nth_element(sd, ins, attrs, node, const_values=None):
    n = int(np.asarray(_require_const(const_values, node, 1, "n")))
    return sd._record("nth_element", [ins[0]],
                      {"n": n, "reverse": bool(attrs.get("reverse", False))})


_NEEDS_CONSTS.add("NthElement")


@register_tf_op("CropAndResize")
def _tf_crop_and_resize(sd, ins, attrs, node, const_values=None):
    size = np.asarray(_require_const(const_values, node, 3, "crop_size")
                      ).reshape(-1)
    return sd._record("crop_and_resize", ins[:3],
                      {"crop_size": (int(size[0]), int(size[1]))})


_NEEDS_CONSTS.add("CropAndResize")


@register_tf_op("ListDiff")
def _tf_listdiff(sd, ins, attrs, node, const_values=None):
    # dynamic output length: supported only when both operands are Const
    x = (const_values or {}).get(node.input[0].split(":")[0])
    y = (const_values or {}).get(node.input[1].split(":")[0])
    if x is None or y is None:
        raise ValueError(
            f"ListDiff {node.name}: dynamic-length output needs constant "
            f"operands under XLA static shapes")
    xa = np.asarray(x).reshape(-1)
    ys = set(np.asarray(y).reshape(-1).tolist())
    keep = [i for i, v in enumerate(xa.tolist()) if v not in ys]
    # TF semantics: preserve x's order AND duplicates (np.setdiff1d sorts
    # and dedups — wrong here)
    return (sd.constant(node.name + "_out", xa[keep]),
            sd.constant(node.name + "_idx", np.asarray(keep, np.int32)))


_NEEDS_CONSTS.add("ListDiff")


@register_tf_op("Bincount")
@register_tf_op("DenseBincount")
def _tf_bincount(sd, ins, attrs, node, const_values=None):
    size = (const_values or {}).get(node.input[1].split(":")[0])
    if size is None:
        raise ValueError(f"Bincount {node.name}: size must be constant")
    n = int(np.asarray(size))
    if len(node.input) > 2 and node.input[2]:
        w = (const_values or {}).get(node.input[2].split(":")[0])
        # reject ANY weights operand unless it is a constant empty tensor
        # (silently dropping runtime weights would yield unweighted counts)
        if w is None or np.asarray(w).size:
            raise NotImplementedError(
                f"Bincount {node.name}: weighted bincount import is not "
                f"supported — precompute outside the graph")
    out = sd._record("bincount", [ins[0]], {"minlength": n, "maxlength": n})
    if bool(attrs.get("binary_output", False)):
        zero = sd.constant(node.name + "_z", np.asarray(0, np.int32))
        out = sd._record("cast", [sd._record("gt", [out, zero])],
                         {"dtype": "int32"})
    return out


_NEEDS_CONSTS.add("Bincount")
_NEEDS_CONSTS.add("DenseBincount")


@register_tf_op("BroadcastArgs")
def _tf_broadcast_args(sd, ins, attrs, node, const_values=None):
    # shape-arithmetic helper tf.linspace/broadcasting emit; both operands
    # are shape tensors — constant in frozen graphs
    s0 = (const_values or {}).get(node.input[0].split(":")[0])
    s1 = (const_values or {}).get(node.input[1].split(":")[0])
    if s0 is None or s1 is None:
        raise ValueError(
            f"BroadcastArgs {node.name}: dynamic shape operands unsupported")
    out = np.broadcast_shapes(tuple(np.asarray(s0).reshape(-1)),
                              tuple(np.asarray(s1).reshape(-1)))
    arr = np.asarray(out, np.int32)
    if const_values is not None:
        # downstream shape consumers (BroadcastTo/Reshape) resolve their
        # shape operand through const_values — publish the folded result
        const_values[node.name] = arr
    return sd.constant(node.name, arr)


_NEEDS_CONSTS.add("BroadcastArgs")


# -- round-5 continued: linalg decompositions, Conv3D, seeded random ops ----

TF_OP_MAPPERS["BatchMatMulV3"] = TF_OP_MAPPERS["BatchMatMulV2"]


if "matrix_transpose" not in _GRAPH_OPS:
    import jax.numpy as _jnp_mt

    _GRAPH_OPS["matrix_transpose"] = lambda a: _jnp_mt.swapaxes(a, -1, -2)


@register_tf_op("Svd")
def _tf_svd(sd, ins, attrs, node):
    # TF Svd outputs (s, u, v); the catalog op (jnp convention) returns
    # (u, s, vh) — reorder and un-hermitian v
    cuv = bool(attrs.get("compute_uv", True))
    if not cuv:
        return sd._record("svd", ins, {"full_matrices": False,
                                       "compute_uv": False})
    u, s_, vh = sd._record("svd", ins, {
        "full_matrices": bool(attrs.get("full_matrices", False)),
        "compute_uv": True}, n_out=3)
    v = sd._record("matrix_transpose", [vh])
    return [s_, u, v]


@register_tf_op("MatrixTriangularSolve")
def _tf_tri_solve(sd, ins, attrs, node):
    return sd._record("triangular_solve", ins, {
        "lower": bool(attrs.get("lower", True)),
        "adjoint": bool(attrs.get("adjoint", False))})


@register_tf_op("Cross")
def _tf_cross(sd, ins, attrs, node):
    return sd._record("cross", ins)


if "lu_tf_outputs" not in _GRAPH_OPS:
    def _lu_tf_outputs(a):
        import jax.numpy as _jnp
        import jax as _jx

        lu_, ipiv = _jx.scipy.linalg.lu_factor(a)
        # LAPACK ipiv (row i swapped with ipiv[i], sequential) → TF's
        # permutation-of-rows vector
        n = a.shape[-1]

        def to_perm(ip):
            def body(i, perm):
                j = ip[i]
                pi = perm[i]
                perm = perm.at[i].set(perm[j])
                return perm.at[j].set(pi)

            return _jx.lax.fori_loop(0, n, body, _jnp.arange(n))

        if a.ndim == 2:
            perm = to_perm(ipiv)
        else:
            perm = _jx.vmap(to_perm)(ipiv.reshape(-1, n)).reshape(
                ipiv.shape[:-1] + (n,))
        return lu_, perm.astype(_jnp.int32)

    _GRAPH_OPS["lu_tf_outputs"] = _lu_tf_outputs


@register_tf_op("Lu")
def _tf_lu(sd, ins, attrs, node):
    return sd._record("lu_tf_outputs", ins, n_out=2)


if "eigh_pair" not in _GRAPH_OPS:
    def _eigh_pair(a):
        import jax.numpy as _jnp

        e, v = _jnp.linalg.eigh(a)
        return e, v

    _GRAPH_OPS["eigh_pair"] = _eigh_pair


@register_tf_op("SelfAdjointEigV2")
def _tf_eigh(sd, ins, attrs, node):
    if not attrs.get("compute_v", True):
        return sd._record("eigh_pair", ins, n_out=2)[0]
    return sd._record("eigh_pair", ins, n_out=2)


@register_tf_op("Conv3D")
def _tf_conv3d(sd, ins, attrs, node):
    fmt = attrs.get("data_format", b"NDHWC")
    fmt = fmt.decode() if isinstance(fmt, bytes) else str(fmt)
    if fmt != "NDHWC":
        raise ValueError(
            f"Conv3D {node.name}: only NDHWC import supported (got {fmt})")
    strides = [int(s) for s in attrs["strides"]]
    pad = attrs.get("padding", b"SAME")
    pad = pad.decode() if isinstance(pad, bytes) else str(pad)
    dil = [int(d) for d in attrs.get("dilations", [1, 1, 1, 1, 1])]
    return sd._record("conv3d", ins[:2], {
        "stride": tuple(strides[1:4]), "padding": pad.lower(),
        "dilation": tuple(dil[1:4])})


def _seeded_random(op_kind):
    """TF stateful random ops under XLA static semantics: a fixed stream
    keyed by the op's seed attrs (seed=0 falls back to a name hash), the
    same contract the ONNX random mappers use."""
    def rule(sd, ins, attrs, node, const_values=None):
        import zlib

        shape = (const_values or {}).get(node.input[0].split(":")[0])
        if shape is None:
            raise ValueError(
                f"{node.op_type} {node.name}: shape operand must be constant")
        shp = tuple(int(s) for s in np.asarray(shape).reshape(-1))
        s1 = int(attrs.get("seed", 0))
        s2 = int(attrs.get("seed2", 0))
        if s1 or s2:
            # TF puts the graph seed in `seed` and the per-op seed in
            # `seed2` — COMBINE them (first-nonzero would collapse every
            # op in a seeded graph onto one stream)
            seed = (s1 * 1000003 + s2) & 0x7FFFFFFF
        else:
            # unseeded: stable per-name stream (hash() is
            # PYTHONHASHSEED-randomized across processes)
            seed = zlib.crc32(node.name.encode()) & 0x7FFFFFFF
        dt = attrs.get("dtype")
        kw = {"shape": shp, "seed": seed}
        if dt is not None:
            import tensorflow as _tf

            np_dt = _tf.dtypes.as_dtype(dt).as_numpy_dtype
            if not np.issubdtype(np_dt, np.floating):
                raise NotImplementedError(
                    f"{node.op_type} {node.name}: non-float random dtype "
                    f"{np_dt} import")
            kw["dtype"] = np.dtype(np_dt).name
        return sd._record(op_kind, [], kw)

    return rule


if "tf_random_normal" not in _GRAPH_OPS:
    import jax as _jax_mod
    import jax.numpy as _jnp_mod

    _GRAPH_OPS["tf_random_normal"] = (
        lambda *, shape, seed, dtype="float32": _jax_mod.random.normal(
            _jax_mod.random.key(seed), tuple(shape), _jnp_mod.dtype(dtype)))
    _GRAPH_OPS["tf_random_uniform"] = (
        lambda *, shape, seed, dtype="float32": _jax_mod.random.uniform(
            _jax_mod.random.key(seed), tuple(shape), _jnp_mod.dtype(dtype)))
    _GRAPH_OPS["tf_truncated_normal"] = (
        lambda *, shape, seed, dtype="float32":
        _jax_mod.random.truncated_normal(
            _jax_mod.random.key(seed), -2.0, 2.0, tuple(shape),
            _jnp_mod.dtype(dtype)))

TF_OP_MAPPERS["RandomStandardNormal"] = _seeded_random("tf_random_normal")
TF_OP_MAPPERS["RandomUniform"] = _seeded_random("tf_random_uniform")
TF_OP_MAPPERS["TruncatedNormal"] = _seeded_random("tf_truncated_normal")
for _r in ("RandomStandardNormal", "RandomUniform", "TruncatedNormal"):
    _NEEDS_CONSTS.add(_r)


if "tf_softmax_xent" not in _GRAPH_OPS:
    import jax as _jax_xe
    import jax.numpy as _jnp_x

    def _tf_softmax_xent_impl(logits, labels):
        loss = -_jnp_x.sum(labels * _jax_xe.nn.log_softmax(logits), axis=-1)
        grad = _jax_xe.nn.softmax(logits) - labels
        return loss, grad

    def _tf_sparse_softmax_xent_impl(logits, labels):
        oh = _jax_xe.nn.one_hot(labels, logits.shape[-1], dtype=logits.dtype)
        return _tf_softmax_xent_impl(logits, oh)

    _GRAPH_OPS["tf_softmax_xent"] = _tf_softmax_xent_impl
    _GRAPH_OPS["tf_sparse_softmax_xent"] = _tf_sparse_softmax_xent_impl


@register_tf_op("SoftmaxCrossEntropyWithLogits")
def _tf_softmax_xent(sd, ins, attrs, node):
    # outputs (loss, backprop-gradient) — training-graph freezes carry both
    return sd._record("tf_softmax_xent", ins[:2], n_out=2)


@register_tf_op("SparseSoftmaxCrossEntropyWithLogits")
def _tf_sparse_softmax_xent(sd, ins, attrs, node):
    return sd._record("tf_sparse_softmax_xent", ins[:2], n_out=2)


# -- image-adjustment / resize / dynamic-partition tail ---------------------

@register_tf_op("RGBToHSV")
def _tf_rgb_to_hsv(sd, ins, attrs, node):
    return sd._record("rgb_to_hsv", ins)


@register_tf_op("HSVToRGB")
def _tf_hsv_to_rgb(sd, ins, attrs, node):
    return sd._record("hsv_to_rgb", ins)


def _mk_scalar_image_op(ours, what):
    def rule(sd, ins, attrs, node, const_values=None):
        v = float(np.asarray(_require_const(const_values, node, 1, what)))
        return sd._record(ours, [ins[0]], {what: v})

    return rule


TF_OP_MAPPERS["AdjustContrastv2"] = _mk_scalar_image_op("adjust_contrast",
                                                        "factor")
TF_OP_MAPPERS["AdjustHue"] = _mk_scalar_image_op("adjust_hue", "delta")
TF_OP_MAPPERS["AdjustSaturation"] = _mk_scalar_image_op("adjust_saturation",
                                                        "factor")
for _r in ("AdjustContrastv2", "AdjustHue", "AdjustSaturation"):
    _NEEDS_CONSTS.add(_r)


@register_tf_op("ResizeBicubic")
def _tf_resize_bicubic(sd, ins, attrs, node, const_values=None):
    if not bool(attrs.get("half_pixel_centers", False)) \
            or bool(attrs.get("align_corners", False)):
        raise NotImplementedError(
            "legacy ResizeBicubic (half_pixel_centers=false or "
            "align_corners=true) import — re-export with tf.image.resize "
            "(TF2 semantics)")
    size = np.asarray(_require_const(const_values, node, 1, "size")).reshape(-1)
    return sd._record("resize_bicubic", [ins[0]],
                      {"size": (int(size[0]), int(size[1]))})


_NEEDS_CONSTS.add("ResizeBicubic")


@register_tf_op("DynamicPartition")
def _tf_dynamic_partition(sd, ins, attrs, node):
    raise NotImplementedError(
        f"DynamicPartition {node.name}: per-partition output sizes are "
        f"data-dependent, which XLA's static shapes cannot express. The "
        f"catalog op 'dynamic_partition' offers the padded+mask form for "
        f"hand-built graphs; restructure the imported model (boolean "
        f"masking or segment ops usually substitute).")


if "stitch_pair" not in _GRAPH_OPS:
    def _stitch_pair_impl(*args):
        from deeplearning4j_tpu.ops import exec_op

        half = len(args) // 2
        return exec_op("dynamic_stitch", list(args[:half]),
                       list(args[half:]))

    _GRAPH_OPS["stitch_pair"] = _stitch_pair_impl


@register_tf_op("DynamicStitch")
@register_tf_op("ParallelDynamicStitch")
def _tf_dynamic_stitch(sd, ins, attrs, node, const_values=None):
    n = int(attrs.get("N", len(ins) // 2))
    # the catalog op sizes the output by TOTAL index count; that matches TF
    # only when the indices form a dense 0..n-1 permutation — validate when
    # the index operands are constants (the frozen-graph norm), reject
    # otherwise rather than silently mis-shape
    idx_vals = [(const_values or {}).get(node.input[i].split(":")[0])
                for i in range(n)]
    if all(v is not None for v in idx_vals):
        flat = np.concatenate([np.asarray(v).reshape(-1) for v in idx_vals]) \
            if idx_vals else np.zeros(0, np.int64)
        if sorted(flat.tolist()) != list(range(len(flat))):
            raise NotImplementedError(
                f"DynamicStitch {node.name}: indices {sorted(flat.tolist())} "
                f"are not a dense permutation — duplicate/sparse index "
                f"semantics (later-wins, implicit zero rows) are unsupported")
    else:
        raise NotImplementedError(
            f"DynamicStitch {node.name}: non-constant index operands — "
            f"cannot validate the dense-permutation requirement at import")
    return sd._record("stitch_pair", list(ins[:n]) + list(ins[n:2 * n]))


_NEEDS_CONSTS.add("DynamicStitch")
_NEEDS_CONSTS.add("ParallelDynamicStitch")
