"""TF frozen-graph import → SameDiff graph.

Reference parity:
  * org/nd4j/imports/graphmapper/tf/TFGraphMapper.java (legacy) and the
    Kotlin IR-based samediff-import framework (SURVEY §3.2): per-op mapping
    rules from TF GraphDef nodes to SameDiff ops; Const tensors become
    VARIABLEs/CONSTANTs; Placeholders become placeholders.

Scope (SURVEY §8.3 hard part #2): the BERT-path op subset plus common
vision ops — enough to import graphs produced by in-env TF for golden-file
testing (the reference's TFGraphTestAllSameDiff pattern). The mapping-rule
table is extensible: register_tf_op(name)(fn).

Requires tensorflow only at import time of a GraphDef (TF 2.21 is in the
environment for golden-file generation; the runtime path is pure jax).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable

# op-name -> mapper(sd, node_inputs: List[SDVariable], attrs, tf_node) -> SDVariable
TF_OP_MAPPERS: Dict[str, Callable[..., Any]] = {}


def register_tf_op(name: str):
    def wrap(fn):
        TF_OP_MAPPERS[name] = fn
        return fn

    return wrap


# ---------------------------------------------------------------------------
# Mapping rules (TensorflowOpDeclarations analog)
# ---------------------------------------------------------------------------


@register_tf_op("MatMul")
def _matmul(sd, ins, attrs, node):
    return sd._record("mmul", ins, {
        "transpose_a": bool(attrs.get("transpose_a", False)),
        "transpose_b": bool(attrs.get("transpose_b", False))})


@register_tf_op("BatchMatMulV2")
@register_tf_op("BatchMatMul")
def _batch_matmul(sd, ins, attrs, node):
    return sd._record("mmul", ins, {
        "transpose_a": bool(attrs.get("adj_x", False)),
        "transpose_b": bool(attrs.get("adj_y", False))})


@register_tf_op("BiasAdd")
@register_tf_op("AddV2")
@register_tf_op("Add")
def _add(sd, ins, attrs, node):
    return sd._record("add", ins)


@register_tf_op("Sub")
def _sub(sd, ins, attrs, node):
    return sd._record("sub", ins)


@register_tf_op("Mul")
def _mul(sd, ins, attrs, node):
    return sd._record("mul", ins)


@register_tf_op("RealDiv")
@register_tf_op("Div")
def _div(sd, ins, attrs, node):
    return sd._record("div", ins)


@register_tf_op("Pow")
def _pow(sd, ins, attrs, node):
    return sd._record("pow", ins)


@register_tf_op("SquaredDifference")
def _sqdiff(sd, ins, attrs, node):
    return sd._record("squared_difference", ins)


@register_tf_op("Maximum")
def _max(sd, ins, attrs, node):
    return sd._record("maximum", ins)


@register_tf_op("Minimum")
def _min(sd, ins, attrs, node):
    return sd._record("minimum", ins)


for _tf, _ours in [
    ("Relu", "relu"), ("Relu6", "relu6"), ("Elu", "elu"), ("Selu", "selu"),
    ("Tanh", "tanh"), ("Sigmoid", "sigmoid"), ("Softplus", "softplus"),
    ("Softsign", "softsign"), ("Exp", "exp"), ("Log", "log"),
    ("Log1p", "log1p"), ("Sqrt", "sqrt"), ("Rsqrt", "rsqrt"),
    ("Square", "square"), ("Abs", "abs"), ("Neg", "neg"), ("Sign", "sign"),
    ("Floor", "floor"), ("Ceil", "ceil"), ("Round", "round"),
    ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"), ("Erf", "erf"),
    ("Reciprocal", "reciprocal"), ("Atan", "atan"), ("Asin", "asin"),
    ("Acos", "acos"), ("Sinh", "sinh"), ("Cosh", "cosh"),
]:
    def _make(ours):
        def f(sd, ins, attrs, node):
            return sd._record(ours, ins)

        return f

    TF_OP_MAPPERS[_tf] = _make(_ours)


@register_tf_op("Softmax")
def _softmax(sd, ins, attrs, node):
    return sd._record("softmax", ins, {"axis": -1})


@register_tf_op("LogSoftmax")
def _log_softmax(sd, ins, attrs, node):
    return sd._record("log_softmax", ins, {"axis": -1})


@register_tf_op("Identity")
@register_tf_op("StopGradient")
@register_tf_op("NoOp")
@register_tf_op("CheckNumerics")
def _identity(sd, ins, attrs, node):
    return ins[0] if ins else None


@register_tf_op("Reshape")
def _reshape(sd, ins, attrs, node, const_values=None):
    shape = const_values.get(node.input[1]) if const_values else None
    if shape is None:
        raise ValueError(f"Reshape {node.name}: dynamic shape input unsupported")
    return sd._record("reshape", [ins[0]], {"shape": tuple(int(s) for s in shape)})


@register_tf_op("Transpose")
def _transpose(sd, ins, attrs, node, const_values=None):
    perm = const_values.get(node.input[1]) if const_values else None
    if perm is None:
        raise ValueError(f"Transpose {node.name}: dynamic perm unsupported")
    return sd._record("transpose", [ins[0]], {"axes": tuple(int(p) for p in perm)})


@register_tf_op("ExpandDims")
def _expand(sd, ins, attrs, node, const_values=None):
    axis = const_values.get(node.input[1])
    return sd._record("expand_dims", [ins[0]], {"axis": int(axis)})


@register_tf_op("Squeeze")
def _squeeze(sd, ins, attrs, node):
    dims = attrs.get("squeeze_dims") or None
    axis = tuple(dims) if dims else None
    return sd._record("squeeze", ins, {"axis": axis})


@register_tf_op("ConcatV2")
def _concat(sd, ins, attrs, node, const_values=None):
    axis = const_values.get(node.input[-1])
    data_ins = [i for i in node.input[:-1] if not i.startswith("^")]
    if all(n in const_values for n in data_ins):
        # const-fold shape chains (Fill/Range → Concat → Reshape)
        const_values[node.name] = np.concatenate(
            [np.atleast_1d(const_values[n]) for n in data_ins],
            axis=int(axis))
    return sd._record("concat", ins[:-1], {"axis": int(axis)})


@register_tf_op("Mean")
def _mean(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_mean", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("Sum")
def _sum(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_sum", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("Max")
def _reduce_max(sd, ins, attrs, node, const_values=None):
    axes = const_values.get(node.input[1])
    keep = bool(attrs.get("keep_dims", False))
    axes = tuple(int(a) for a in np.atleast_1d(axes))
    return sd._record("reduce_max", [ins[0]], {"axes": axes, "keepdims": keep})


@register_tf_op("GatherV2")
def _gather(sd, ins, attrs, node, const_values=None):
    axis = const_values.get(node.input[2], 0)
    return sd._record("gather", ins[:2], {"axis": int(axis)})


@register_tf_op("Conv2D")
def _conv2d(sd, ins, attrs, node):
    strides = attrs.get("strides", [1, 1, 1, 1])
    padding = attrs.get("padding", b"SAME")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC Conv2D import supported")
    return sd._record("conv2d", ins, {"stride": (int(strides[1]), int(strides[2])),
                                      "padding": pad})


@register_tf_op("MaxPool")
def _maxpool(sd, ins, attrs, node):
    k = attrs.get("ksize", [1, 2, 2, 1])
    s = attrs.get("strides", [1, 2, 2, 1])
    padding = attrs.get("padding", b"VALID")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("maxpool2d", ins, {"kernel": (int(k[1]), int(k[2])),
                                         "stride": (int(s[1]), int(s[2])),
                                         "padding": pad})


@register_tf_op("AvgPool")
def _avgpool(sd, ins, attrs, node):
    k = attrs.get("ksize", [1, 2, 2, 1])
    s = attrs.get("strides", [1, 2, 2, 1])
    padding = attrs.get("padding", b"VALID")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("avgpool2d", ins, {"kernel": (int(k[1]), int(k[2])),
                                         "stride": (int(s[1]), int(s[2])),
                                         "padding": pad})


@register_tf_op("Cast")
def _cast(sd, ins, attrs, node, const_values=None):
    import tensorflow as tf

    dst = attrs.get("DstT")
    np_dtype = tf.dtypes.as_dtype(dst).as_numpy_dtype if dst is not None else np.float32
    if const_values is not None and node.input[0] in const_values:
        # constant-fold: shape/limit chains (e.g. Range's Cast'ed bounds)
        # stay resolvable as const operands downstream
        folded = np.asarray(const_values[node.input[0]]).astype(np_dtype)
        const_values[node.name] = folded
    return sd._record("cast", ins, {"dtype": str(np.dtype(np_dtype))})


@register_tf_op("Pack")
def _pack(sd, ins, attrs, node, const_values=None):
    data_ins = [i for i in node.input if not i.startswith("^")]
    if const_values is not None and all(n in const_values for n in data_ins):
        # const-fold shape chains (scalar dims → Pack → Reshape)
        const_values[node.name] = np.stack(
            [np.asarray(const_values[n]) for n in data_ins],
            axis=int(attrs.get("axis", 0)))
    return sd._record("stack", ins, {"axis": int(attrs.get("axis", 0))})


@register_tf_op("Tile")
def _tile(sd, ins, attrs, node, const_values=None):
    reps = const_values.get(node.input[1])
    return sd._record("tile", [ins[0]], {"reps": tuple(int(r) for r in reps)})


@register_tf_op("Select")
@register_tf_op("SelectV2")
def _select(sd, ins, attrs, node):
    return sd._record("where", ins)


@register_tf_op("Greater")
def _greater(sd, ins, attrs, node):
    return sd._record("gt", ins)


@register_tf_op("Less")
def _less(sd, ins, attrs, node):
    return sd._record("lt", ins)


@register_tf_op("Equal")
def _equal(sd, ins, attrs, node):
    return sd._record("eq", ins)


@register_tf_op("DepthwiseConv2dNative")
def _depthwise_conv(sd, ins, attrs, node):
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC DepthwiseConv2dNative import supported")
    if [int(d) for d in attrs.get("dilations", [1, 1, 1, 1])] != [1, 1, 1, 1]:
        raise NotImplementedError("dilated DepthwiseConv2dNative import")
    strides = attrs.get("strides", [1, 1, 1, 1])
    padding = attrs.get("padding", b"SAME")
    pad = padding.decode().lower() if isinstance(padding, bytes) else str(padding).lower()
    return sd._record("depthwise_conv2d", ins,
                      {"stride": (int(strides[1]), int(strides[2])),
                       "padding": pad})


@register_tf_op("FusedBatchNormV3")
@register_tf_op("FusedBatchNorm")
def _fused_bn(sd, ins, attrs, node):
    """inference-mode fused BN: inputs x, scale, offset, mean, var (NHWC)."""
    if attrs.get("data_format", b"NHWC") not in (b"NHWC", "NHWC"):
        raise ValueError("only NHWC FusedBatchNorm import supported")
    x, scale, offset, mean, var = ins[:5]
    return sd._record("batch_norm_graph", [x, mean, var, scale, offset],
                      {"eps": float(attrs.get("epsilon", 1e-3))})


@register_tf_op("LeakyRelu")
def _tf_leaky(sd, ins, attrs, node):
    return sd._record("leakyrelu", ins,
                      {"alpha": float(attrs.get("alpha", 0.2))})


@register_tf_op("Pad")
@register_tf_op("PadV2")
def _tf_pad(sd, ins, attrs, node, const_values=None):
    pads = _require_const(const_values, node, 1, "paddings")
    value = 0.0
    if len(node.input) > 2:
        cv = const_values.get(node.input[2].split(":")[0])
        if cv is not None:
            value = float(cv)
    return sd._record("pad", [ins[0]],
                      {"paddings": tuple((int(a), int(b)) for a, b in pads),
                       "value": value})


@register_tf_op("StridedSlice")
def _tf_strided_slice(sd, ins, attrs, node, const_values=None):
    """Handles begin_mask/end_mask/shrink_axis_mask — what ANY python
    slicing (``t[:, :2]``, ``t[0]``) compiles to; ellipsis/new_axis masks
    (``t[..., None]``) still raise."""
    if attrs.get("ellipsis_mask", 0) or attrs.get("new_axis_mask", 0):
        raise NotImplementedError(
            f"StridedSlice {node.name}: ellipsis/new_axis masks not "
            "supported — rewrite without '...'/None indexing")
    begin = [int(b) for b in _require_const(const_values, node, 1, "begin")]
    end = [int(e) for e in _require_const(const_values, node, 2, "end")]
    strides = [int(s) for s in
               _require_const(const_values, node, 3, "strides")]
    from deeplearning4j_tpu.imports.ir import SLICE_TO_END

    bmask = int(attrs.get("begin_mask", 0))
    emask = int(attrs.get("end_mask", 0))
    smask = int(attrs.get("shrink_axis_mask", 0))
    big = SLICE_TO_END
    shrink_axes = []
    for i in range(len(begin)):
        if smask & (1 << i):
            # shrink: select exactly index begin[i], then squeeze the axis
            end[i] = begin[i] + 1 if begin[i] != -1 else big
            strides[i] = 1
            shrink_axes.append(i)
            continue
        if bmask & (1 << i):
            begin[i] = 0 if strides[i] > 0 else big
        if emask & (1 << i):
            end[i] = big if strides[i] > 0 else -big
    out = sd._record("strided_slice", [ins[0]], {
        "begin": begin, "end": end, "strides": strides})
    if shrink_axes:
        out = sd._record("squeeze", [out], {"axis": tuple(shrink_axes)})
    return out


@register_tf_op("Unpack")
def _tf_unpack(sd, ins, attrs, node):
    # single-output use only: the common tf.unstack(x)[0] pattern — with
    # num > 1 every :k consumer would silently receive element 0
    if int(attrs.get("num", 1)) > 1 or int(attrs.get("axis", 0)) != 0:
        raise NotImplementedError(
            f"Unpack {node.name}: num={attrs.get('num')}/axis="
            f"{attrs.get('axis', 0)} — only single-element axis-0 unstack "
            "imports")
    return sd._record("unstack_first", ins)


@register_tf_op("ArgMax")
def _tf_argmax(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "dimension") \
        if len(node.input) > 1 else -1
    return sd._record("argmax", [ins[0]], {"axis": int(axis)})


@register_tf_op("ArgMin")
def _tf_argmin(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "dimension") \
        if len(node.input) > 1 else -1
    return sd._record("argmin", [ins[0]], {"axis": int(axis)})


@register_tf_op("Prod")
def _tf_prod(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_prod", [ins[0]], {
        "axes": tuple(int(a) for a in np.atleast_1d(axes)),
        "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("Min")
def _tf_reduce_min(sd, ins, attrs, node, const_values=None):
    axes = _require_const(const_values, node, 1, "reduction axes")
    return sd._record("reduce_min", [ins[0]], {
        "axes": tuple(int(a) for a in np.atleast_1d(axes)),
        "keepdims": bool(attrs.get("keep_dims", False))})


@register_tf_op("ClipByValue")
def _tf_clip(sd, ins, attrs, node, const_values=None):
    lo = float(_require_const(const_values, node, 1, "clip_value_min"))
    hi = float(_require_const(const_values, node, 2, "clip_value_max"))
    return sd._record("clip_by_value_graph", [ins[0]],
                      {"min_value": lo, "max_value": hi})


@register_tf_op("Cumsum")
def _tf_cumsum(sd, ins, attrs, node, const_values=None):
    axis = _require_const(const_values, node, 1, "axis")
    return sd._record("cumsum", [ins[0]], {
        "axis": int(axis),
        "exclusive": bool(attrs.get("exclusive", False)),
        "reverse": bool(attrs.get("reverse", False))})


@register_tf_op("GreaterEqual")
def _tf_gte(sd, ins, attrs, node):
    return sd._record("gte", ins)


@register_tf_op("LessEqual")
def _tf_lte(sd, ins, attrs, node):
    return sd._record("lte", ins)


@register_tf_op("NotEqual")
def _tf_neq(sd, ins, attrs, node):
    return sd._record("neq", ins)


@register_tf_op("ZerosLike")
def _tf_zeros_like(sd, ins, attrs, node):
    return sd._record("zeros_like", ins)


@register_tf_op("OnesLike")
def _tf_ones_like(sd, ins, attrs, node):
    return sd._record("ones_like", ins)


def _require_const(const_values, node, idx, what):
    name = node.input[idx].split(":")[0]
    val = (const_values or {}).get(name)
    if val is None:
        raise ValueError(
            f"{node.op_type} {node.name}: dynamic (non-Const) {what} operand "
            f"'{node.input[idx]}' is unsupported")
    return val


@register_tf_op("AvgPool3D")
@register_tf_op("MaxPool3D")
def _tf_pool3d_unsupported(sd, ins, attrs, node):
    raise NotImplementedError("3-D pooling import is not supported yet")


# ---------------------------------------------------------------------------
# The importer
# ---------------------------------------------------------------------------

_CONST_ONLY_OPS = {"Const", "Placeholder", "PlaceholderWithDefault"}
# mappers that need raw const operand values (shape/perm/axis inputs)
_NEEDS_CONSTS = {"Cast", "Pack", "Reshape", "Transpose", "ExpandDims", "ConcatV2", "Mean",
                 "Sum", "Max", "Min", "Prod", "GatherV2", "Tile", "Pad",
                 "PadV2", "StridedSlice", "ArgMax", "ArgMin", "ClipByValue",
                 "Cumsum"}


def graphdef_to_ir(graph_def) -> "IRGraph":
    """TF GraphDef → framework-neutral IRGraph (imports/ir.py): Const nodes
    become initializers, Placeholders become graph inputs, everything else
    an IRNode with normalized attrs."""
    from tensorflow.python.framework import tensor_util

    from deeplearning4j_tpu.imports.ir import IRGraph, IRNode

    nodes: List = []
    initializers: Dict[str, np.ndarray] = {}
    inputs: List = []
    for node in graph_def.node:
        if node.op == "Const":
            initializers[node.name] = tensor_util.MakeNdarray(
                node.attr["value"].tensor)
            continue
        if node.op in ("Placeholder", "PlaceholderWithDefault"):
            shape = None
            if "shape" in node.attr:
                dims = node.attr["shape"].shape.dim
                shape = tuple(d.size if d.size > 0 else None for d in dims)
            inputs.append((node.name, shape))
            continue
        attrs = {k: _attr_value(v) for k, v in node.attr.items()}

        def norm(i):
            # keep multi-output slot addressing ("op:1"); the default ":0"
            # slot normalizes to the bare name
            if ":" in i:
                base, slot = i.rsplit(":", 1)
                if slot == "0":
                    return base
            return i

        # control-dep inputs ("^name") are ordering-only — XLA's dataflow
        # subsumes them; they are NOT data operands
        in_names = [norm(i) for i in node.input if not i.startswith("^")]
        nodes.append(IRNode(name=node.name, op_type=node.op,
                            inputs=in_names, outputs=[node.name],
                            attrs=attrs))
    return IRGraph(nodes=nodes, initializers=initializers, inputs=inputs,
                   outputs=[], name="tensorflow")


class TensorflowImporter:
    """FrameworkImporter analog for TF frozen GraphDefs — a thin frontend
    over the shared IR walker (imports/ir.IRImporter): parse to IRGraph,
    dispatch the TF dialect rule table."""

    def __init__(self, extra_mappers: Optional[Dict[str, Callable]] = None):
        self.mappers = dict(TF_OP_MAPPERS)
        if extra_mappers:
            self.mappers.update(extra_mappers)

    def supported_ops(self) -> List[str]:
        return sorted(self.mappers)

    def run_import(self, graph_def, *, trainable_consts: bool = True) -> SameDiff:
        """GraphDef (or serialized bytes / .pb path) → SameDiff."""
        from deeplearning4j_tpu.imports.ir import IRImporter

        graph_def = _coerce_graph_def(graph_def)
        ir = graphdef_to_ir(graph_def)
        walker = IRImporter(self.mappers, needs_consts=_NEEDS_CONSTS,
                            trainable_consts=trainable_consts)
        return walker.run_import(ir)


def _coerce_graph_def(g):
    import tensorflow as tf

    if isinstance(g, (str, bytes)):
        gd = tf.compat.v1.GraphDef()
        if isinstance(g, str):
            with open(g, "rb") as f:
                gd.ParseFromString(f.read())
        else:
            gd.ParseFromString(g)
        return gd
    return g


def _attr_value(v):
    kind = v.WhichOneof("value")
    if kind == "i":
        return v.i
    if kind == "f":
        return v.f
    if kind == "b":
        return v.b
    if kind == "s":
        return v.s
    if kind == "list":
        lst = v.list
        for field in ("i", "f", "b", "s"):
            vals = list(getattr(lst, field))
            if vals:
                return vals
        return []
    if kind == "type":
        return v.type
    if kind == "shape":
        return v.shape
    return v


def import_frozen_graph(path_or_bytes) -> SameDiff:
    """Convenience one-call import (KerasModelImport-style facade)."""
    return TensorflowImporter().run_import(path_or_bytes)


# ---------------------------------------------------------------------------
# Dialect widening, round 3 continued: shape/indexing + math + image ops.
# ---------------------------------------------------------------------------


@register_tf_op("Split")
def _split(sd, ins, attrs, node, const_values=None):
    # TF Split: (axis, value); num_split is an attr
    axis = _require_const(const_values, node, 0, "axis")
    n = int(attrs.get("num_split"))
    return sd._record("split", [ins[-1]],
                      {"num_split": n, "axis": int(axis)}, n_out=n)


@register_tf_op("SplitV")
def _split_v(sd, ins, attrs, node, const_values=None):
    sizes = _require_const(const_values, node, 1, "size_splits")
    axis = _require_const(const_values, node, 2, "axis")
    sizes = tuple(int(s) for s in np.atleast_1d(sizes))
    return sd._record("split_v", [ins[0]],
                      {"sizes": sizes, "axis": int(axis)},
                      n_out=len(sizes))


@register_tf_op("OneHot")
def _one_hot(sd, ins, attrs, node, const_values=None):
    depth = _require_const(const_values, node, 1, "depth")
    on = _require_const(const_values, node, 2, "on_value") \
        if len(node.input) > 2 else None
    off = _require_const(const_values, node, 3, "off_value") \
        if len(node.input) > 3 else None
    if int(attrs.get("axis", -1)) != -1:
        raise NotImplementedError("OneHot with axis != -1 import")
    oh = sd._record("one_hot_graph", [ins[0]], {"depth": int(depth)})
    on_v = 1.0 if on is None else float(np.asarray(on).item())
    off_v = 0.0 if off is None else float(np.asarray(off).item())
    if on_v == 1.0 and off_v == 0.0:
        return oh
    # label-smoothing style: off + (on - off) * onehot
    scaled = sd._record("mul", [oh, sd.constant(
        node.name + "_scale", np.asarray(on_v - off_v, np.float32))])
    return sd._record("add", [scaled, sd.constant(
        node.name + "_off", np.asarray(off_v, np.float32))])


@register_tf_op("Range")
def _range(sd, ins, attrs, node, const_values=None):
    start = _require_const(const_values, node, 0, "start")
    limit = _require_const(const_values, node, 1, "limit")
    delta = _require_const(const_values, node, 2, "delta") \
        if len(node.input) > 2 else 1
    arr = np.arange(np.asarray(start).item(), np.asarray(limit).item(),
                    np.asarray(delta).item())
    const_values[node.name] = arr  # keep shape chains const-resolvable
    return sd.constant(node.name + "_range", arr)


@register_tf_op("Fill")
def _fill(sd, ins, attrs, node, const_values=None):
    dims = _require_const(const_values, node, 0, "dims")
    value = _require_const(const_values, node, 1, "value")
    arr = np.full(tuple(int(d) for d in np.atleast_1d(dims)),
                  np.asarray(value).item())
    const_values[node.name] = arr  # keep shape chains const-resolvable
    return sd.constant(node.name + "_fill", arr)


@register_tf_op("Slice")
def _slice(sd, ins, attrs, node, const_values=None):
    begin = _require_const(const_values, node, 1, "begin")
    size = _require_const(const_values, node, 2, "size")
    return sd._record("slice", [ins[0]],
                      {"begin": tuple(int(b) for b in np.atleast_1d(begin)),
                       "size": tuple(int(s) for s in np.atleast_1d(size))})


@register_tf_op("BroadcastTo")
def _broadcast_to(sd, ins, attrs, node, const_values=None):
    shape = _require_const(const_values, node, 1, "shape")
    return sd._record("broadcast_to", [ins[0]],
                      {"shape": tuple(int(s) for s in np.atleast_1d(shape))})


@register_tf_op("FloorDiv")
def _floordiv(sd, ins, attrs, node):
    return sd._record("floordiv", ins)


@register_tf_op("FloorMod")
def _floormod(sd, ins, attrs, node):
    return sd._record("floormod", ins)


@register_tf_op("Atan2")
def _atan2(sd, ins, attrs, node):
    return sd._record("atan2", ins)


@register_tf_op("SpaceToDepth")
def _space_to_depth(sd, ins, attrs, node):
    fmt = attrs.get("data_format", b"NHWC")
    fmt = fmt.decode() if isinstance(fmt, bytes) else str(fmt)
    return sd._record("space_to_depth", ins,
                      {"block_size": int(attrs["block_size"]),
                       "data_format": fmt})


@register_tf_op("DepthToSpace")
def _depth_to_space(sd, ins, attrs, node):
    fmt = attrs.get("data_format", b"NHWC")
    fmt = fmt.decode() if isinstance(fmt, bytes) else str(fmt)
    return sd._record("depth_to_space", ins,
                      {"block_size": int(attrs["block_size"]),
                       "data_format": fmt})


@register_tf_op("ResizeBilinear")
def _resize_bilinear_tf(sd, ins, attrs, node, const_values=None):
    if not bool(attrs.get("half_pixel_centers", False)):
        raise NotImplementedError(
            "legacy ResizeBilinear (half_pixel_centers=false) import — "
            "re-export with tf.image.resize (TF2 semantics)")
    size = _require_const(const_values, node, 1, "size")
    return sd._record("resize_bilinear", [ins[0]],
                      {"size": tuple(int(s) for s in np.atleast_1d(size))})


@register_tf_op("ResizeNearestNeighbor")
def _resize_nn_tf(sd, ins, attrs, node, const_values=None):
    if not bool(attrs.get("half_pixel_centers", False)) \
            or bool(attrs.get("align_corners", False)):
        raise NotImplementedError(
            "legacy ResizeNearestNeighbor (half_pixel_centers=false or "
            "align_corners=true) import — re-export with tf.image.resize "
            "(TF2 semantics)")
    size = _require_const(const_values, node, 1, "size")
    return sd._record("resize_nearest_neighbor", [ins[0]],
                      {"size": tuple(int(s) for s in np.atleast_1d(size))})


_NEEDS_CONSTS |= {"Split", "SplitV", "OneHot", "Range", "Fill", "Slice",
                  "BroadcastTo", "ResizeBilinear", "ResizeNearestNeighbor"}


@register_tf_op("TopKV2")
def _topk(sd, ins, attrs, node, const_values=None):
    k = _require_const(const_values, node, 1, "k")
    return sd._record("top_k", [ins[0]], {"k": int(k)}, n_out=2)


_NEEDS_CONSTS.add("TopKV2")
