"""ONNX import → SameDiff graph (samediff-import-onnx analog).

Reference parity: nd4j/samediff-import/samediff-import-onnx
(OnnxFrameworkImporter.kt + onnx-mapping-ruleset.pbtxt): ONNX ModelProto →
IR → per-op mapping rules → SameDiff. Here the ModelProto is decoded by
the in-repo wire codec (imports/protowire.py — no onnx package in the
environment), normalized to imports/ir.IRGraph, and mapped by the ONNX
dialect table below onto the same SameDiff op catalog the TF frontend
targets.

Layout note: ONNX is NCHW; the graph records transposes around conv/pool
(our declarable conv2d/maxpool2d are NHWC, the TPU-friendly layout) and
XLA folds adjacent transposes away.

Supported surface (round 5): 151 mapped ops — MLP/CNN/RNN graphs (Gemm,
MatMul, Conv/ConvTranspose, pooling, BatchNormalization, LSTM/GRU/RNN,
Resize, Einsum), CONTROL FLOW (Loop/If/Scan onto lax.while_loop/cond/scan
with outer-scope subgraph captures), detection ops (NonMaxSuppression with
padded static output, exact RoiAlign), the Scatter/Gather families, the
QuantizeLinear family, random ops, and documented rejects for
dynamic-output-shape ops (NonZero/Unique/Compress) that XLA's static
shapes cannot express.
"""

from __future__ import annotations

import struct
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff
from deeplearning4j_tpu.imports import protowire as pw
from deeplearning4j_tpu.imports.ir import IRGraph, IRImporter, IRNode

# ---------------------------------------------------------------------------
# ModelProto decoding (field numbers from the public onnx.proto3 schema)
# ---------------------------------------------------------------------------

# TensorProto.DataType
_DT_NP = {1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
          6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16,
          11: np.float64, 12: np.uint32, 13: np.uint64}


def _decode_tensor(buf: bytes) -> Tuple[str, np.ndarray]:
    f = pw.parse_message(buf)
    dims = pw.get_packed_or_repeated_varints(f, 1)
    dtype = _DT_NP.get(pw.get_varint(f, 2, 1), np.float32)
    name = pw.get_string(f, 8)
    raw = pw.get_byte(f, 9)
    if raw:
        arr = np.frombuffer(raw, dtype=dtype)
    elif dtype == np.float32:
        arr = np.asarray(pw.get_packed_floats(f, 4), np.float32)
    elif dtype in (np.int64, np.uint64):
        arr = np.asarray(pw.get_packed_or_repeated_varints(f, 7), np.int64)
    elif dtype in (np.int32, np.int8, np.int16, np.uint8, np.uint16, np.bool_):
        arr = np.asarray(pw.get_packed_or_repeated_varints(f, 5)).astype(dtype)
    elif dtype == np.float64:
        raw10 = b"".join(v for wt, v in f.get(10, []) if wt == pw.LEN)
        arr = np.frombuffer(raw10, np.float64) if raw10 else np.asarray(
            [struct.unpack("<d", v)[0] for wt, v in f.get(10, []) if wt == pw.I64])
    else:  # pragma: no cover
        raise NotImplementedError(f"tensor dtype {dtype}")
    return name, arr.reshape(dims) if dims else arr


def _decode_attr(buf: bytes) -> Tuple[str, Any]:
    f = pw.parse_message(buf)
    name = pw.get_string(f, 1)
    atype = pw.get_varint(f, 20, 0)
    if atype == 1:  # FLOAT
        return name, pw.get_float(f, 2)
    if atype == 2:  # INT
        return name, pw._to_signed64(pw.get_varint(f, 3))
    if atype == 3:  # STRING
        return name, pw.get_byte(f, 4).decode("utf-8", "replace")
    if atype == 4:  # TENSOR
        return name, _decode_tensor(pw.get_byte(f, 5))[1]
    if atype == 5:  # GRAPH (Loop/If/Scan bodies)
        return name, _graph_to_ir(pw.parse_message(pw.get_byte(f, 6)),
                                  name=f"onnx_sub:{name}")
    if atype == 6:  # FLOATS
        return name, pw.get_packed_floats(f, 7)
    if atype == 7:  # INTS
        return name, pw.get_packed_or_repeated_varints(f, 8)
    if atype == 8:  # STRINGS
        return name, [b.decode() for b in pw.get_bytes(f, 9)]
    return name, None


def _decode_value_info(buf: bytes) -> Tuple[str, Optional[Tuple]]:
    f = pw.parse_message(buf)
    name = pw.get_string(f, 1)
    shape = None
    t = pw.get_byte(f, 2)
    if t:
        tt = pw.get_byte(pw.parse_message(t), 1)  # TypeProto.tensor_type
        if tt:
            sh = pw.get_byte(pw.parse_message(tt), 2)  # TensorTypeProto.shape
            if sh:
                dims = []
                for d in pw.get_bytes(pw.parse_message(sh), 1):
                    df = pw.parse_message(d)
                    v = pw.get_varint(df, 1, 0)
                    dims.append(int(v) if v > 0 else None)
                shape = tuple(dims)
    return name, shape


def _graph_to_ir(graph, name: str = "onnx") -> IRGraph:
    """Parsed GraphProto message → IRGraph (used for the top-level graph and
    for GRAPH-typed attributes: Loop/If/Scan bodies)."""
    initializers: Dict[str, np.ndarray] = {}
    for tbuf in pw.get_bytes(graph, 5):
        tname, arr = _decode_tensor(tbuf)
        initializers[tname] = arr
    nodes: List[IRNode] = []
    for nbuf in pw.get_bytes(graph, 1):
        nf = pw.parse_message(nbuf)
        attrs = dict(_decode_attr(a) for a in pw.get_bytes(nf, 5))
        outputs = [b.decode() for b in pw.get_bytes(nf, 2)]
        nodes.append(IRNode(
            name=pw.get_string(nf, 3) or (outputs[0] if outputs else ""),
            op_type=pw.get_string(nf, 4),
            inputs=[b.decode() for b in pw.get_bytes(nf, 1)],
            outputs=outputs,
            attrs=attrs))
    inputs = []
    for vbuf in pw.get_bytes(graph, 11):
        vname, shape = _decode_value_info(vbuf)
        if vname not in initializers:  # opset<9 lists initializers as inputs
            inputs.append((vname, shape))
    outputs = [_decode_value_info(v)[0] for v in pw.get_bytes(graph, 12)]
    return IRGraph(nodes=nodes, initializers=initializers, inputs=inputs,
                   outputs=outputs, name=name)


def parse_model(data: bytes) -> IRGraph:
    """ONNX ModelProto bytes → IRGraph."""
    model = pw.parse_message(data)
    return _graph_to_ir(pw.parse_message(pw.get_byte(model, 7)))


# ---------------------------------------------------------------------------
# ONNX dialect rules
# ---------------------------------------------------------------------------

ONNX_OP_MAPPERS: Dict[str, Callable[..., Any]] = {}

_NEEDS_CONSTS = {"Reshape", "Transpose", "Squeeze", "Unsqueeze", "Gather", "Conv",
                 "ReduceMean", "ReduceSum", "ReduceMax", "ReduceMin", "Clip",
                 "Pad", "Concat"}


def register_onnx_op(name: str):
    def wrap(fn):
        ONNX_OP_MAPPERS[name] = fn
        return fn

    return wrap


def _unary(sd_op: str):
    def rule(sd, ins, attrs, node):
        return sd._record(sd_op, [ins[0]])

    return rule


for _onnx, _sd in [("Relu", "relu"), ("Sigmoid", "sigmoid"),
                   ("Tanh", "tanh"), ("Softplus", "softplus"),
                   ("Softsign", "softsign"), ("Exp", "exp"), ("Log", "log"),
                   ("Sqrt", "sqrt"), ("Neg", "neg"), ("Abs", "abs"),
                   ("Floor", "floor"), ("Ceil", "ceil"), ("Round", "round"),
                   ("Erf", "erf"), ("Sign", "sign"), ("Reciprocal", "reciprocal"),
                   ("Sin", "sin"), ("Cos", "cos"), ("Mish", "mish"),
                   ("HardSigmoid", "hardsigmoid"), ("Gelu", "gelu")]:
    ONNX_OP_MAPPERS[_onnx] = _unary(_sd)

for _onnx, _sd in [("Add", "add"), ("Sub", "sub"), ("Mul", "mul"),
                   ("Div", "div"), ("Pow", "pow"), ("Max", "maximum"),
                   ("Min", "minimum")]:
    def _bin_rule(sd, ins, attrs, node, _op=_sd):
        return sd._record(_op, ins)

    ONNX_OP_MAPPERS[_onnx] = _bin_rule


@register_onnx_op("LeakyRelu")
def _leaky(sd, ins, attrs, node):
    return sd._record("leakyrelu", [ins[0]],
                      {"alpha": float(attrs.get("alpha", 0.01))})


@register_onnx_op("Elu")
def _elu(sd, ins, attrs, node):
    return sd._record("elu", [ins[0]])


@register_onnx_op("Selu")
def _selu(sd, ins, attrs, node):
    return sd._record("selu", [ins[0]])


@register_onnx_op("Softmax")
def _softmax(sd, ins, attrs, node):
    return sd._record("softmax", [ins[0]],
                      {"axis": int(attrs.get("axis", -1))})


@register_onnx_op("LogSoftmax")
def _log_softmax(sd, ins, attrs, node):
    return sd._record("log_softmax", [ins[0]],
                      {"axis": int(attrs.get("axis", -1))})


@register_onnx_op("MatMul")
def _matmul(sd, ins, attrs, node):
    return sd._record("mmul", ins)


@register_onnx_op("Gemm")
def _gemm(sd, ins, attrs, node):
    """Y = alpha·op(A)·op(B) + beta·C."""
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    y = sd._record("mmul", ins[:2], {
        "transpose_a": bool(attrs.get("transA", 0)),
        "transpose_b": bool(attrs.get("transB", 0))})
    if alpha != 1.0:
        y = y * alpha
    if len(ins) > 2:
        c = ins[2] if beta == 1.0 else ins[2] * beta
        y = y + c
    return y


@register_onnx_op("Identity")
@register_onnx_op("Dropout")
def _identity(sd, ins, attrs, node):
    return sd._record("identity", [ins[0]])


@register_onnx_op("Flatten")
def _flatten(sd, ins, attrs, node):
    return sd._record("flatten_from", [ins[0]],
                      {"axis": int(attrs.get("axis", 1))})


@register_onnx_op("Reshape")
def _reshape(sd, ins, attrs, node, const_values=None):
    shape = const_values.get(node.inputs[1])
    if shape is None:
        raise NotImplementedError("Reshape with dynamic shape input")
    return sd._record("reshape", [ins[0]],
                      {"shape": tuple(int(s) for s in shape)})


@register_onnx_op("Transpose")
def _transpose(sd, ins, attrs, node, const_values=None):
    perm = attrs.get("perm")
    return sd._record("transpose", [ins[0]],
                      {"axes": None if perm is None else tuple(int(p) for p in perm)})


@register_onnx_op("Squeeze")
def _squeeze(sd, ins, attrs, node, const_values=None):
    axes = attrs.get("axes")
    if axes is None and len(node.inputs) > 1:
        axes = const_values.get(node.inputs[1])
    ax = None if axes is None else tuple(int(a) for a in axes)
    if ax is not None and len(ax) == 1:
        ax = ax[0]
    return sd._record("squeeze", [ins[0]], {"axis": ax})


@register_onnx_op("Unsqueeze")
def _unsqueeze(sd, ins, attrs, node, const_values=None):
    axes = attrs.get("axes")
    if axes is None and len(node.inputs) > 1:
        axes = const_values.get(node.inputs[1])
    y = ins[0]
    # insert in ascending order so later axes account for earlier inserts
    for ax in sorted(int(a) for a in axes):
        y = sd._record("expand_dims", [y], {"axis": ax})
    return y


@register_onnx_op("Concat")
def _concat(sd, ins, attrs, node, const_values=None):
    return sd._record("concat", ins, {"axis": int(attrs.get("axis", 0))})


@register_onnx_op("Gather")
def _gather(sd, ins, attrs, node, const_values=None):
    return sd._record("gather", ins, {"axis": int(attrs.get("axis", 0))})


def _reduce_rule(sd_op):
    def rule(sd, ins, attrs, node, const_values=None):
        axes = attrs.get("axes")
        if axes is None and len(node.inputs) > 1:
            axes = const_values.get(node.inputs[1])
        return sd._record(sd_op, [ins[0]], {
            "axes": None if axes is None else tuple(int(a) for a in axes),
            "keepdims": bool(attrs.get("keepdims", 1))})

    return rule


ONNX_OP_MAPPERS["ReduceMean"] = _reduce_rule("reduce_mean")
ONNX_OP_MAPPERS["ReduceSum"] = _reduce_rule("reduce_sum")
ONNX_OP_MAPPERS["ReduceMax"] = _reduce_rule("reduce_max")
ONNX_OP_MAPPERS["ReduceMin"] = _reduce_rule("reduce_min")


@register_onnx_op("Clip")
def _clip(sd, ins, attrs, node, const_values=None):
    lo = attrs.get("min")
    hi = attrs.get("max")
    if lo is None and len(node.inputs) > 1 and node.inputs[1]:
        lo = float(const_values.get(node.inputs[1]))
    if hi is None and len(node.inputs) > 2 and node.inputs[2]:
        hi = float(const_values.get(node.inputs[2]))
    return sd._record("clip_by_value_graph", [ins[0]], {
        "min_value": -np.inf if lo is None else float(lo),
        "max_value": np.inf if hi is None else float(hi)})


@register_onnx_op("Pad")
def _pad(sd, ins, attrs, node, const_values=None):
    pads = attrs.get("pads")
    if pads is None and len(node.inputs) > 1:
        pads = const_values.get(node.inputs[1])
    pads = [int(p) for p in pads]
    ndim = len(pads) // 2
    paddings = tuple((pads[i], pads[i + ndim]) for i in range(ndim))
    return sd._record("pad", [ins[0]], {"paddings": paddings,
                                        "value": float(attrs.get("value", 0.0))})


@register_onnx_op("Cast")
def _cast(sd, ins, attrs, node):
    to = _DT_NP.get(int(attrs.get("to", 1)), np.float32)
    return sd._record("cast", [ins[0]], {"dtype": np.dtype(to).name})


def _to_nhwc(sd, x):
    return sd._record("transpose", [x], {"axes": (0, 2, 3, 1)})


def _to_nchw(sd, x):
    return sd._record("transpose", [x], {"axes": (0, 3, 1, 2)})


@register_onnx_op("Conv")
def _conv(sd, ins, attrs, node, const_values=None):
    """NCHW Conv → (transpose) NHWC conv2d (transpose back). XLA folds the
    sandwiched transposes into the convolution's layout assignment."""
    strides = [int(s) for s in attrs.get("strides", [1, 1])]
    pads = [int(p) for p in attrs.get("pads", [0, 0, 0, 0])]
    dilations = [int(d) for d in attrs.get("dilations", [1, 1])]
    group = int(attrs.get("group", 1))
    if dilations != [1, 1]:
        raise NotImplementedError("dilated Conv import")
    if group > 1 and const_values.get(node.inputs[1]) is not None \
            and const_values[node.inputs[1]].shape[1] != 1:
        raise NotImplementedError(
            f"Conv {node.name}: grouped conv with group={group} and "
            "C/group > 1 (ResNeXt-style) is not supported — only depthwise "
            "(C/group == 1)")
    x = _to_nhwc(sd, ins[0])
    if any(pads):
        x = sd._record("pad", [x], {"paddings": (
            (0, 0), (pads[0], pads[2]), (pads[1], pads[3]), (0, 0))})
    w = ins[1]  # (M, C/g, kH, kW)
    if group == 1:
        wt = sd._record("transpose", [w], {"axes": (2, 3, 1, 0)})  # → kh,kw,C,M
        y = sd._record("conv2d", [x, wt],
                       {"stride": tuple(strides), "padding": "valid"})
    else:
        # depthwise: group == C_in, weights (C, 1, kH, kW) → (kh, kw, C, 1)
        wt = sd._record("transpose", [w], {"axes": (2, 3, 0, 1)})
        y = sd._record("depthwise_conv2d", [x, wt],
                       {"stride": tuple(strides), "padding": "valid"})
    if len(ins) > 2:  # bias (M,) broadcasts over NHWC channels-last
        y = y + ins[2]
    return _to_nchw(sd, y)


def _pool_rule(sd_op, is_global):
    def rule(sd, ins, attrs, node):
        x = _to_nhwc(sd, ins[0])
        if is_global:
            y = sd._record(sd_op, [x])  # NHWC global pool → (N, C)
            # ONNX keeps unit spatial dims: (N, C, 1, 1)
            y = sd._record("expand_dims", [y], {"axis": -1})
            return sd._record("expand_dims", [y], {"axis": -1})
        kernel = [int(k) for k in attrs["kernel_shape"]]
        strides = [int(s) for s in attrs.get("strides", kernel)]
        pads = [int(p) for p in attrs.get("pads", [0, 0, 0, 0])]
        if any(pads):
            pp = ((0, 0), (pads[0], pads[2]), (pads[1], pads[3]), (0, 0))
            x = sd._record("pad", [x], {
                "paddings": pp,
                "value": -np.inf if sd_op == "maxpool2d" else 0.0})
        y = sd._record(sd_op, [x], {"kernel": tuple(kernel),
                                    "stride": tuple(strides)})
        if sd_op == "avgpool2d" and any(pads) \
                and not int(attrs.get("count_include_pad", 0)):
            # ONNX default excludes padding from the average denominator:
            # divide by the pooled fraction of a ones-mask padded with zeros
            ones = sd._record("ones_like", [_to_nhwc(sd, ins[0])])
            ones = sd._record("pad", [ones], {"paddings": pp})
            frac = sd._record(sd_op, [ones], {"kernel": tuple(kernel),
                                              "stride": tuple(strides)})
            y = sd._record("div", [y, frac])
        return _to_nchw(sd, y)

    return rule


ONNX_OP_MAPPERS["MaxPool"] = _pool_rule("maxpool2d", False)
ONNX_OP_MAPPERS["AveragePool"] = _pool_rule("avgpool2d", False)
ONNX_OP_MAPPERS["GlobalAveragePool"] = _pool_rule("global_avg_pool", True)
ONNX_OP_MAPPERS["GlobalMaxPool"] = _pool_rule("global_max_pool", True)


@register_onnx_op("BatchNormalization")
def _batchnorm(sd, ins, attrs, node):
    """inference-mode BN; params reshape to (C,1,1) for NCHW broadcast."""
    x, scale, bias, mean, var = ins[:5]
    eps = float(attrs.get("epsilon", 1e-5))

    def chan(v, tag):
        return sd._record("reshape", [v], {"shape": (-1, 1, 1)})

    return sd._record("batch_norm_graph",
                      [x, chan(mean, "m"), chan(var, "v"),
                       chan(scale, "g"), chan(bias, "b")], {"eps": eps})


@register_onnx_op("LRN")
def _lrn(sd, ins, attrs, node):
    x = _to_nhwc(sd, ins[0])
    y = sd._record("lrn", [x], {
        "depth": int(attrs.get("size", 5)),
        "bias": float(attrs.get("bias", 1.0)),
        "alpha": float(attrs.get("alpha", 1e-4)) / int(attrs.get("size", 5)),
        "beta": float(attrs.get("beta", 0.75))})
    return _to_nchw(sd, y)


@register_onnx_op("Constant")
def _constant(sd, ins, attrs, node):
    val = attrs.get("value")
    return sd.constant(node.outputs[0], np.asarray(val))


@register_onnx_op("PRelu")
def _prelu(sd, ins, attrs, node):
    x, slope = ins
    pos = sd._record("relu", [x])
    neg = sd._record("minimum", [x, sd.constant(
        node.name + "_z", np.zeros((1,), np.float32))])
    return pos + sd._record("mul", [slope, neg])


# "flatten_from": keep leading `axis` dims, flatten the rest (ONNX Flatten)
from deeplearning4j_tpu.autodiff import samediff as _sdmod

if "flatten_from" not in _sdmod.GRAPH_OPS:
    def _flatten_from(a, *, axis=1):
        lead = 1
        for s in a.shape[:axis]:
            lead *= s
        return a.reshape(lead, -1)

    _sdmod.GRAPH_OPS["flatten_from"] = _flatten_from
if "identity" not in _sdmod.GRAPH_OPS:
    _sdmod.GRAPH_OPS["identity"] = lambda a: a


# ---------------------------------------------------------------------------
# Facade
# ---------------------------------------------------------------------------


class OnnxImporter(IRImporter):
    """OnnxFrameworkImporter analog."""

    def __init__(self, extra_mappers: Optional[Dict[str, Callable]] = None,
                 optimize: bool = True, validate: bool = True):
        rules = dict(ONNX_OP_MAPPERS)
        if extra_mappers:
            rules.update(extra_mappers)
        super().__init__(rules, needs_consts=_NEEDS_CONSTS,
                         needs_scope=_NEEDS_SCOPE, optimize=optimize,
                         validate=validate)

    def run_import(self, model) -> SameDiff:  # type: ignore[override]
        if isinstance(model, str):
            with open(model, "rb") as f:
                model = f.read()
        if isinstance(model, (bytes, bytearray)):
            model = parse_model(bytes(model))
        return super().run_import(model)


def import_onnx(path_or_bytes, optimize: bool = True,
                validate: bool = True) -> SameDiff:
    """One-call facade (KerasModelImport-style). ``optimize=False`` disables
    the pre-trace graph optimizer (docs/OPTIMIZER.md); ``validate=False``
    skips the post-import graftcheck (docs/ANALYSIS.md)."""
    return OnnxImporter(optimize=optimize,
                        validate=validate).run_import(path_or_bytes)


# ---------------------------------------------------------------------------
# Dialect widening, round 3 continued.
# ---------------------------------------------------------------------------

for _onnx, _sd in [("Tan", "tan"), ("Atan", "atan"), ("Asin", "asin"),
                   ("Acos", "acos"), ("Sinh", "sinh"), ("Cosh", "cosh")]:
    ONNX_OP_MAPPERS[_onnx] = _unary(_sd)

for _onnx, _sd in [("Equal", "eq"), ("Greater", "gt"), ("Less", "lt"),
                   ("And", "boolean_and"), ("Or", "boolean_or"),
                   ("Xor", "boolean_xor")]:
    def _bin_rule2(sd, ins, attrs, node, _op=_sd):
        return sd._record(_op, ins)

    ONNX_OP_MAPPERS[_onnx] = _bin_rule2


def _mod_rule(sd, ins, attrs, node):
    """ONNX Mod: fmod=0 -> Python-style floor mod, fmod=1 -> C-style trunc mod.

    The spec requires fmod=1 for float tensors; both variants lower to real
    ops so neither silently changes sign semantics.
    """
    if int(attrs.get("fmod", 0)):
        return sd._record("truncatemod", ins)
    return sd._record("floormod", ins)


ONNX_OP_MAPPERS["Mod"] = _mod_rule

ONNX_OP_MAPPERS["ReduceProd"] = _reduce_rule("reduce_prod")


def _arg_rule(sd_op):
    def rule(sd, ins, attrs, node):
        axis = int(attrs.get("axis", 0))
        v = sd._record(sd_op, [ins[0]], {"axis": axis})
        if int(attrs.get("keepdims", 1)):
            v = sd._record("expand_dims", [v], {"axis": axis})
        return v

    return rule


ONNX_OP_MAPPERS["ArgMax"] = _arg_rule("argmax")
ONNX_OP_MAPPERS["ArgMin"] = _arg_rule("argmin")


@register_onnx_op("Where")
def _where_onnx(sd, ins, attrs, node):
    return sd._record("where", ins)


@register_onnx_op("Expand")
def _expand_onnx(sd, ins, attrs, node, const_values=None):
    shape = [int(s) for s in np.atleast_1d(const_values.get(node.inputs[1]))]
    in_shape = ins[0].shape
    if in_shape is None:
        raise NotImplementedError("Expand on an unknown-rank input")
    # ONNX Expand broadcasts BIDIRECTIONALLY: out dim = max(in, shape) with
    # numpy alignment — a shape dim of 1 keeps the input dim
    aligned = [1] * (len(shape) - len(in_shape)) + [int(d) for d in in_shape]         if len(shape) >= len(in_shape) else list(in_shape)
    target = list(shape)
    if len(target) < len(aligned):
        target = [1] * (len(aligned) - len(target)) + target
    eff = tuple(max(a, t) for a, t in zip(aligned, target))
    return sd._record("broadcast_to", [ins[0]], {"shape": eff})


@register_onnx_op("Tile")
def _tile_onnx(sd, ins, attrs, node, const_values=None):
    reps = const_values.get(node.inputs[1])
    return sd._record("tile", [ins[0]],
                      {"reps": tuple(int(r) for r in np.atleast_1d(reps))})


@register_onnx_op("Split")
def _split_onnx(sd, ins, attrs, node, const_values=None):
    axis = int(attrs.get("axis", 0))
    sizes = attrs.get("split")
    if sizes is None and len(node.inputs) > 1:
        sizes = const_values.get(node.inputs[1])
        if sizes is None:
            raise ValueError(
                f"Split {node.name}: dynamic sizes input unsupported")
    n = len(node.outputs)
    if sizes is not None:
        return sd._record("split_v", [ins[0]],
                          {"sizes": tuple(int(s) for s in sizes),
                           "axis": axis}, n_out=n)
    return sd._record("split", [ins[0]], {"num_split": n, "axis": axis},
                      n_out=n)


@register_onnx_op("Slice")
def _slice_onnx(sd, ins, attrs, node, const_values=None):
    # opset ≥ 10: starts/ends/axes/steps as const inputs
    starts = attrs.get("starts")
    ends = attrs.get("ends")
    axes = attrs.get("axes")
    steps = None
    if starts is None:
        starts = const_values.get(node.inputs[1])
        ends = const_values.get(node.inputs[2])
        axes = (const_values.get(node.inputs[3])
                if len(node.inputs) > 3 else None)
        steps = (const_values.get(node.inputs[4])
                 if len(node.inputs) > 4 else None)
    if steps is not None and any(int(s) != 1 for s in np.atleast_1d(steps)):
        raise NotImplementedError("Slice with steps != 1 import")
    starts = [int(s) for s in np.atleast_1d(starts)]
    ends = [int(e) for e in np.atleast_1d(ends)]
    if axes is not None:
        # expand axes-addressed starts/ends to full rank (strided_slice is
        # full-rank); rank comes from the traced input shape
        shape = ins[0].shape
        if shape is None:
            raise NotImplementedError(
                "Slice with axes on an unknown-rank input")
        from deeplearning4j_tpu.imports.ir import SLICE_TO_END

        rank = len(shape)
        b, e = [0] * rank, [SLICE_TO_END] * rank
        for a, s_, t_ in zip(np.atleast_1d(axes), starts, ends):
            b[int(a)], e[int(a)] = s_, t_
        starts, ends = b, e
    return sd._record("strided_slice", [ins[0]], {
        "begin": tuple(starts), "end": tuple(ends)})


@register_onnx_op("TopK")
def _topk_onnx(sd, ins, attrs, node, const_values=None):
    if not int(attrs.get("largest", 1)):
        raise NotImplementedError("TopK largest=0 (k-smallest) import")
    if int(attrs.get("axis", -1)) != -1:
        raise NotImplementedError("TopK with axis != -1 import")
    k = attrs.get("k")
    if k is None:
        k = const_values.get(node.inputs[1])
    if k is None:
        raise ValueError(f"TopK {node.name}: dynamic k input unsupported")
    return sd._record("top_k", [ins[0]], {"k": int(np.asarray(k).item())},
                      n_out=2)


@register_onnx_op("ConvTranspose")
def _conv_transpose_onnx(sd, ins, attrs, node, const_values=None):
    # ONNX is NCHW with OIHW→(in, out) transposed kernels; normalize to our
    # NHWC/HWIO path the same way the Conv rule does
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("pads", [0, 0, 0, 0])
    if any(int(p) != pads[0] for p in pads):
        raise NotImplementedError("asymmetric ConvTranspose pads import")
    x = _to_nhwc(sd, ins[0])
    w = sd._record("transpose", [ins[1]], {"axes": (2, 3, 0, 1)})  # (I,O,H,W)→HWIO
    # ONNX ConvTranspose SCATTERS the kernel as-is — exactly deconv2d's
    # semantics now that it matches TF conv_transpose at every stride
    # (round 4: the old path needed a compensating flip and still diverged
    # at stride>1)
    y = sd._record("deconv2d", [x, w] + ([ins[2]] if len(ins) > 2 else []), {
        "stride": (int(strides[0]), int(strides[1])),
        "padding": ((int(pads[0]), int(pads[2])), (int(pads[1]), int(pads[3])))
        if int(pads[0]) else "valid"})
    return _to_nchw(sd, y)


@register_onnx_op("SpaceToDepth")
def _s2d_onnx(sd, ins, attrs, node):
    x = _to_nhwc(sd, ins[0])
    y = sd._record("space_to_depth", [x],
                   {"block_size": int(attrs["blocksize"])})
    return _to_nchw(sd, y)


@register_onnx_op("DepthToSpace")
def _d2s_onnx(sd, ins, attrs, node):
    if attrs.get("mode", b"DCR") not in (b"DCR", "DCR"):
        raise NotImplementedError("DepthToSpace CRD mode import")
    x = _to_nhwc(sd, ins[0])
    y = sd._record("depth_to_space", [x],
                   {"block_size": int(attrs["blocksize"])})
    return _to_nchw(sd, y)


@register_onnx_op("InstanceNormalization")
def _instance_norm_onnx(sd, ins, attrs, node):
    eps = float(attrs.get("epsilon", 1e-5))
    x, scale, bias = ins
    # NCHW: normalize each (instance, channel) over spatial dims
    mean = sd._record("reduce_mean", [x], {"axes": (2, 3), "keepdims": True})
    d = sd._record("sub", [x, mean])
    var = sd._record("reduce_mean",
                     [sd._record("square", [d])],
                     {"axes": (2, 3), "keepdims": True})
    denom = sd._record("sqrt", [sd._record(
        "add", [var, sd.constant(node.name + "_eps",
                                 np.asarray(eps, np.float32))])])
    xhat = sd._record("div", [d, denom])
    sc = sd._record("reshape", [scale], {"shape": (1, -1, 1, 1)})
    bi = sd._record("reshape", [bias], {"shape": (1, -1, 1, 1)})
    return sd._record("add", [sd._record("mul", [xhat, sc]), bi])


_NEEDS_CONSTS |= {"Expand", "Tile", "Split", "Slice", "TopK", "ConvTranspose"}


# ---------------------------------------------------------------------------
# Round-4 widening: recurrent op imports + Resize (reference
# samediff-import-onnx LSTM/GRU/Resize declarations).
# ---------------------------------------------------------------------------


@register_onnx_op("LSTM")
def _lstm_onnx(sd, ins, attrs, node, const_values=None):
    """ONNX LSTM (single forward direction): X:(T,N,I), W:(1,4H,I) gates
    i,o,f,c; R:(1,4H,H); B:(1,8H). The gate/axis re-packing is RECORDED as
    graph ops over the original W/R/B variables, so an imported model
    fine-tunes through them (trainable_consts contract)."""
    _reject_extra_rnn_inputs(node, {4: "sequence_lens", 5: "initial_h",
                                    6: "initial_c", 7: "peepholes (P)"})
    hidden = int(attrs["hidden_size"])
    w_ih = _regate_matrix(sd, ins[1], 4, [0, 2, 3, 1])   # i,o,f,c -> i,f,c,o
    w_hh = _regate_matrix(sd, ins[2], 4, [0, 2, 3, 1])
    b = _rnn_bias(sd, ins, node, 3, 4, [0, 2, 3, 1], hidden)
    x_nt = sd._record("transpose", [ins[0]], {"axes": (1, 0, 2)})
    ys, h_t, c_t = sd._record("lstm_sequence", [x_nt, w_ih, w_hh, b],
                              n_out=3)
    y_tn = sd._record("transpose", [ys], {"axes": (1, 0, 2)})
    y = sd._record("expand_dims", [y_tn], {"axis": 1})
    h_out = sd._record("expand_dims", [h_t], {"axis": 0})
    c_out = sd._record("expand_dims", [c_t], {"axis": 0})
    return (y, h_out, c_out)


@register_onnx_op("GRU")
def _gru_onnx(sd, ins, attrs, node, const_values=None):
    """ONNX GRU (single forward direction): gates z,r,h -> our r,z,n;
    linear_before_reset maps directly onto gru_sequence. Weight re-packing
    is recorded in-graph (trainable like every other imported weight)."""
    _reject_extra_rnn_inputs(node, {4: "sequence_lens", 5: "initial_h"})
    hidden = int(attrs["hidden_size"])
    lbr = bool(int(attrs.get("linear_before_reset", 0)))
    w_ih = _regate_matrix(sd, ins[1], 3, [1, 0, 2])      # z,r,h -> r,z,h
    w_hh = _regate_matrix(sd, ins[2], 3, [1, 0, 2])
    if len(node.inputs) > 3 and node.inputs[3]:
        bb = sd._record("squeeze", [ins[3]], {"axis": (0,)})
        wb, rb = sd._record("split", [bb], {"num_split": 2, "axis": 0},
                            n_out=2)
        b_ih = _reorder_vector(sd, wb, 3, [1, 0, 2])
        b_hh = _reorder_vector(sd, rb, 3, [1, 0, 2])
    else:
        z = np.zeros(3 * hidden, np.float32)
        b_ih = sd.constant(node.name + "_bih", z)
        b_hh = sd.constant(node.name + "_bhh", z)
    x_nt = sd._record("transpose", [ins[0]], {"axes": (1, 0, 2)})
    ys, h_t = sd._record("gru_sequence", [x_nt, w_ih, w_hh, b_ih, b_hh],
                         {"linear_before_reset": lbr}, n_out=2)
    y_tn = sd._record("transpose", [ys], {"axes": (1, 0, 2)})
    y = sd._record("expand_dims", [y_tn], {"axis": 1})
    h_out = sd._record("expand_dims", [h_t], {"axis": 0})
    return (y, h_out)


def _reject_extra_rnn_inputs(node, slots):
    """Raise loudly for recurrent options we do not lower yet — checked on
    node.inputs (the wire slots), NOT the compacted ins list, so an absent
    bias cannot shift the check off its slot. Also rejects the attrs that
    would silently change numerics: layout=1 (batch-major), non-default
    activations, and clip."""
    attrs = getattr(node, "attrs", {}) or {}
    direction = attrs.get("direction", "forward")
    if isinstance(direction, bytes):
        direction = direction.decode()
    if direction != "forward":
        raise NotImplementedError(
            f"ONNX {node.op_type} direction={direction} import")
    if int(attrs.get("layout", 0)):
        raise NotImplementedError(
            f"ONNX {node.op_type} layout=1 (batch-major) import — "
            f"re-export with the default time-major layout")
    if attrs.get("activations"):
        raise NotImplementedError(
            f"ONNX {node.op_type} with non-default activations import")
    if attrs.get("clip"):
        raise NotImplementedError(
            f"ONNX {node.op_type} with cell clipping import")
    for idx, what in slots.items():
        if len(node.inputs) > idx and node.inputs[idx]:
            raise NotImplementedError(
                f"ONNX {node.op_type} with {what} input import")


def _regate_matrix(sd, v, parts, order):
    """(1, parts*H, D) gate-stacked weight -> (D, parts*H) in our gate
    order — recorded as squeeze/split/concat/transpose graph ops."""
    sq = sd._record("squeeze", [v], {"axis": (0,)})
    pieces = sd._record("split", [sq], {"num_split": parts, "axis": 0},
                        n_out=parts)
    cat = sd._record("concat", [pieces[j] for j in order], {"axis": 0})
    return sd._record("transpose", [cat], {"axes": (1, 0)})


def _reorder_vector(sd, v, parts, order):
    pieces = sd._record("split", [v], {"num_split": parts, "axis": 0},
                        n_out=parts)
    return sd._record("concat", [pieces[j] for j in order], {"axis": 0})


def _rnn_bias(sd, ins, node, slot, parts, order, hidden):
    """LSTM bias: B (1, 2*parts*H) = Wb ++ Rb, both reordered then summed;
    absent B -> zeros."""
    if len(node.inputs) > slot and node.inputs[slot]:
        bb = sd._record("squeeze", [ins[slot]], {"axis": (0,)})
        wb, rb = sd._record("split", [bb], {"num_split": 2, "axis": 0},
                            n_out=2)
        return sd._record("add", [_reorder_vector(sd, wb, parts, order),
                                  _reorder_vector(sd, rb, parts, order)])
    return sd.constant(node.name + "_b",
                       np.zeros(parts * hidden, np.float32))


@register_onnx_op("Resize")
def _resize_onnx(sd, ins, attrs, node, const_values=None):
    """ONNX Resize: NCHW X + sizes or scales. half_pixel coordinate
    transform (the opset-11+ default) matches jax.image.resize; other
    transforms are rejected loudly. The scales form needs a static input
    shape to derive sizes."""
    mode = attrs.get("mode", b"nearest")
    mode = mode.decode() if isinstance(mode, bytes) else str(mode)
    ct = attrs.get("coordinate_transformation_mode", b"half_pixel")
    ct = ct.decode() if isinstance(ct, bytes) else str(ct)
    if ct not in ("half_pixel", "pytorch_half_pixel"):
        raise NotImplementedError(
            f"ONNX Resize coordinate_transformation_mode={ct} import "
            f"(half_pixel only)")
    op_name = {"nearest": "resize_nearest_neighbor",
               "linear": "resize_bilinear",
               "cubic": "resize_bicubic"}.get(mode)
    if op_name is None:
        raise NotImplementedError(f"ONNX Resize mode={mode}")

    if len(node.inputs) > 3 and node.inputs[3]:
        sz = _require_const(const_values, node, 3, "sizes")
        sizes = (int(sz[2]), int(sz[3]))
    else:
        # opset-11+: (X, roi, scales); opset-10: (X, scales)
        slot = 2 if len(node.inputs) > 2 else 1
        if len(node.inputs) <= slot:
            raise ValueError(f"Resize {node.name}: no scales/sizes input")
        scales = np.asarray(_require_const(const_values, node, slot,
                                           "scales"))
        in_shape = getattr(ins[0], "shape", None)
        if not in_shape or len(in_shape) != 4 or None in in_shape[2:]:
            raise NotImplementedError(
                "ONNX Resize with a scales input needs a static NCHW input "
                "shape to derive the output size")
        sizes = (int(round(in_shape[2] * float(scales[2]))),
                 int(round(in_shape[3] * float(scales[3]))))
    x = _to_nhwc(sd, ins[0])
    y = sd._record(op_name, [x], {"size": sizes})
    return _to_nchw(sd, y)


def _require_const(const_values, node, idx, what):
    name = node.inputs[idx]  # ONNX names are used verbatim (may contain ':')
    val = (const_values or {}).get(name)
    if val is None:
        raise ValueError(
            f"{node.op_type} {node.name}: dynamic (non-initializer) {what} "
            f"operand '{name}' is unsupported")
    return val


_NEEDS_CONSTS |= {"LSTM", "GRU", "Resize"}


# ---------------------------------------------------------------------------
# Round-4 breadth, second pass: einsum, scatter/gather variants, norms.
# ---------------------------------------------------------------------------


@register_onnx_op("Einsum")
def _einsum_onnx(sd, ins, attrs, node):
    eq = attrs.get("equation", "")
    eq = eq.decode() if isinstance(eq, bytes) else str(eq)
    return sd._record("einsum", ins, {"equation": eq})


@register_onnx_op("GatherND")
def _gather_nd_onnx(sd, ins, attrs, node):
    if int(attrs.get("batch_dims", 0)):
        raise NotImplementedError("GatherND with batch_dims import")
    return sd._record("gather_nd", ins)


@register_onnx_op("CumSum")
def _cumsum_onnx(sd, ins, attrs, node, const_values=None):
    axis = int(np.asarray(_require_const(const_values, node, 1,
                                         "axis")).reshape(-1)[0])
    return sd._record("cumsum", [ins[0]],
                      {"axis": axis,
                       "exclusive": bool(int(attrs.get("exclusive", 0))),
                       "reverse": bool(int(attrs.get("reverse", 0)))})


ONNX_OP_MAPPERS["Not"] = _unary("boolean_not")
ONNX_OP_MAPPERS["IsNaN"] = _unary("isnan")


@register_onnx_op("IsInf")
def _isinf_onnx(sd, ins, attrs, node):
    if not int(attrs.get("detect_positive", 1)) or \
            not int(attrs.get("detect_negative", 1)):
        raise NotImplementedError("IsInf with one-sided detection import")
    return sd._record("isinf", [ins[0]])


@register_onnx_op("Trilu")
def _trilu_onnx(sd, ins, attrs, node, const_values=None):
    k = 0
    if len(node.inputs) > 1 and node.inputs[1]:
        k = int(_require_const(const_values, node, 1, "k"))
    op = "triu" if int(attrs.get("upper", 1)) else "tril"
    return sd._record(op, [ins[0]], {"diag": k})


@register_onnx_op("ThresholdedRelu")
def _thresholded_relu_onnx(sd, ins, attrs, node):
    return sd._record("thresholdedrelu", [ins[0]],
                      {"theta": float(attrs.get("alpha", 1.0))})


@register_onnx_op("Hardmax")
def _hardmax_onnx(sd, ins, attrs, node):
    """Documented divergence: ties mark EVERY max position (the spec keeps
    only the first occurrence) — shape-agnostic eq-based lowering."""
    axis = int(attrs.get("axis", -1))
    mx = sd._record("reduce_max", [ins[0]], {"axes": (axis,),
                                             "keepdims": True})
    eq = sd._record("eq", [ins[0], mx])
    one = sd.constant(node.name + "_one", np.asarray(1.0, np.float32))
    zero = sd.constant(node.name + "_zero", np.asarray(0.0, np.float32))
    return sd._record("select", [eq, one, zero])


@register_onnx_op("LpNormalization")
def _lp_norm_onnx(sd, ins, attrs, node):
    if int(attrs.get("p", 2)) != 2:
        raise NotImplementedError("LpNormalization p != 2 import")
    if int(attrs.get("axis", -1)) not in (-1,):
        raise NotImplementedError("LpNormalization axis != -1 import")
    sq = sd._record("mul", [ins[0], ins[0]])
    ssum = sd._record("reduce_sum", [sq], {"axes": (-1,), "keepdims": True})
    norm = sd._record("sqrt", [ssum])
    return sd._record("div", [ins[0], norm])


@register_onnx_op("MeanVarianceNormalization")
def _mvn_onnx(sd, ins, attrs, node):
    axes = tuple(int(a) for a in attrs.get("axes", [0, 2, 3]))
    mean = sd._record("reduce_mean", [ins[0]],
                      {"axes": axes, "keepdims": True})
    cent = sd._record("sub", [ins[0], mean])
    var = sd._record("reduce_mean", [sd._record("mul", [cent, cent])],
                     {"axes": axes, "keepdims": True})
    eps = sd.constant(node.name + "_eps", np.asarray(1e-9, np.float32))
    return sd._record("div", [cent, sd._record("sqrt",
                                               [sd._record("add", [var, eps])])])


_NEEDS_CONSTS |= {"CumSum", "Trilu"}


# ---------------------------------------------------------------------------
# Control flow (round 5): Loop / If / Scan on the same lax machinery the TF
# importer uses (tf_import.py While/If). ONNX subgraphs differ from TF
# function-style control flow in one way: they capture outer-scope tensors
# implicitly by NAME, so the walker passes its live scope to these rules
# (IRImporter needs_scope) and captures become extra explicit loop inputs.
# Reference: onnx/defs/controlflow (Loop/If/Scan), imported by the
# reference's samediff-import-onnx declarations (SURVEY §3.2).
# ---------------------------------------------------------------------------


def _subgraph_internal_names(ir) -> set:
    own = {o for n in ir.nodes for o in n.outputs}
    own |= set(ir.initializers)
    own |= {nm for nm, _ in ir.inputs}
    return own


def _implicit_inputs(ir) -> List[str]:
    """Names a subgraph reads from the enclosing scope (incl. names used by
    nested subgraph attributes), in first-use order."""
    internal = _subgraph_internal_names(ir)
    refs: List[str] = []

    def visit(g, outer_internal):
        for n in g.nodes:
            for i in n.inputs:
                if i and i not in outer_internal:
                    refs.append(i)
            for v in n.attrs.values():
                if isinstance(v, IRGraph):
                    visit(v, outer_internal | _subgraph_internal_names(v))

    visit(ir, internal)
    return list(dict.fromkeys(refs))


def _subgraph_callable(ir, extra_inputs: Sequence[str] = ()):
    """Import a subgraph IR into a private SameDiff and wrap it as a
    jnp-traceable callable over (declared inputs…, captured inputs…).
    Mirrors tf_import._ir_callable."""
    from deeplearning4j_tpu.imports.ir import IRImporter

    in_names = [nm for nm, _ in ir.inputs] + list(extra_inputs)
    if extra_inputs:
        # captured outer tensors become placeholders of the sub-graph
        ir = IRGraph(nodes=ir.nodes, initializers=ir.initializers,
                     inputs=list(ir.inputs) + [(nm, None)
                                               for nm in extra_inputs],
                     outputs=ir.outputs, name=ir.name)
    walker = IRImporter(ONNX_OP_MAPPERS, needs_consts=_NEEDS_CONSTS,
                        trainable_consts=False, needs_scope=_NEEDS_SCOPE)
    sub = walker.run_import(ir)
    out_names = list(sub.graph_outputs or ir.outputs)

    def call(*vals):
        import jax.numpy as jnp

        env = dict(sub._arrays)
        for nm, v in zip(in_names, vals):
            env[nm] = jnp.asarray(v)
        res = sub._interpret(env, out_names)
        return tuple(res[nm] for nm in out_names)

    return call, len(out_names)


def _capture_vars(names, scope, node):
    missing = [nm for nm in names if nm not in scope]
    if missing:
        raise ValueError(
            f"{node.op_type} {node.name}: subgraph captures {missing} which "
            f"are not produced in the enclosing scope")
    return [scope[nm] for nm in names]


@register_onnx_op("If")
def _onnx_if(sd, ins, attrs, node, scope=None, const_values=None):
    then_ir, else_ir = attrs["then_branch"], attrs["else_branch"]
    cap_then = _implicit_inputs(then_ir)
    cap_else = _implicit_inputs(else_ir)
    caps = list(dict.fromkeys(cap_then + cap_else))
    then_call, n_then = _subgraph_callable(then_ir, caps)
    else_call, n_else = _subgraph_callable(else_ir, caps)
    if n_then != n_else:
        raise ValueError(f"If {node.name}: branch arities differ "
                         f"({n_then} vs {n_else})")
    operands = _capture_vars(caps, scope or {}, node)

    # both branch callables were built with the capture UNION as their
    # trailing inputs, so each receives every union value positionally
    # (a name a branch doesn't read is simply an unused env binding)
    def mk(call):
        def fn(*vals):
            out = call(*vals)
            return out[0] if n_then == 1 else out
        return fn

    return sd.cond_multi(ins[0], mk(then_call), mk(else_call), operands,
                         n_out=n_then)


@register_onnx_op("Loop")
def _onnx_loop(sd, ins, attrs, node, scope=None, const_values=None):
    """ONNX Loop → lax.while_loop (no scan outputs) or masked lax.scan
    (scan outputs, static trip count).

    Node inputs: M (optional), cond (optional), v_initial…;
    body graph: (iter_num, cond_in, v_in…) → (cond_out, v_out…, scan_out…);
    node outputs: v_final… + stacked scan outputs.

    Divergence (documented): with scan outputs AND a runtime early-exit
    cond, XLA's static shapes force length-M outputs; rows past the exit
    hold the last live value. Dynamic-length scan outputs need a host-side
    interpreter (the reference's AbstractSession runs loops on the host;
    SURVEY §4.3 maps them to lax instead).
    """
    import jax.numpy as jnp

    body_ir = attrs["body"]
    caps = _implicit_inputs(body_ir)
    body_call, n_body_out = _subgraph_callable(body_ir, caps)
    cap_vars = _capture_vars(caps, scope or {}, node)

    node_in = list(node.inputs)  # keep empty-name optional slots
    it = iter(ins)
    m_var = next(it) if node_in and node_in[0] else None
    cond_var = next(it) if len(node_in) > 1 and node_in[1] else None
    v_init = list(it)
    n_v = len(v_init)
    n_scan = n_body_out - 1 - n_v
    if n_scan < 0:
        raise ValueError(f"Loop {node.name}: body returns {n_body_out} "
                         f"values for {n_v} loop-carried deps")

    m_static = None
    if m_var is not None and node_in[0] in (const_values or {}):
        m_static = int(np.asarray(const_values[node_in[0]]).reshape(()))

    if n_scan == 0:
        # pure while loop: carry = (i, cond, v…); captures close over
        def cond_fn(carry):
            i, cond = carry[0], carry[1]
            ok = jnp.asarray(cond).astype(bool).reshape(())
            if m_var is not None:
                # m rides the carry tail so the callable stays pure
                # (ONNX trip counts are often shape-(1,) tensors)
                m_val = jnp.asarray(carry[-1]).reshape(())
                ok = jnp.logical_and(ok, i < m_val)
            return ok

        def body_fn(carry):
            i, cond, vs = carry[0], carry[1], carry[2:2 + n_v]
            out = body_call(i, cond, *vs, *[carry[2 + n_v + j]
                                            for j in range(len(caps))])
            cond_out, v_out = out[0], out[1:1 + n_v]
            # keep carry types stable: cond stays a () bool, loop vars keep
            # their incoming shape/dtype (body outputs may differ in rank,
            # e.g. a (1,)-shaped cond tensor or promoted dtypes)
            v_new = tuple(jnp.asarray(nv).reshape(jnp.shape(ov))
                          .astype(jnp.asarray(ov).dtype)
                          for nv, ov in zip(v_out, vs))
            new = (i + 1, jnp.asarray(cond_out).astype(bool).reshape(()),
                   *v_new, *carry[2 + n_v:])
            return new

        one = sd.constant(node.name + "_i0", np.asarray(0, np.int64))
        cond0 = (cond_var if cond_var is not None
                 else sd.constant(node.name + "_true", np.asarray(True)))
        if cond_var is not None:
            # normalize a possibly (1,)-shaped runtime cond to a () bool
            cond0 = sd._record("reshape", [cond0], {"shape": ()})
            cond0 = sd._record("cast", [cond0], {"dtype": "bool"})
        init = [one, cond0] + v_init + cap_vars
        if m_var is not None:
            init = init + [m_var]
        finals = sd.while_loop_multi(cond_fn, body_fn, init)
        if not isinstance(finals, tuple):
            finals = (finals,)
        return [finals[2 + j] for j in range(n_v)]

    # scan outputs: need a static trip count
    if m_static is None:
        raise NotImplementedError(
            f"Loop {node.name}: scan outputs require a constant trip count "
            f"M (XLA static shapes); got a runtime M or none")

    def step(carry, _):
        i, cond, vs = carry[0], carry[1], carry[2:2 + n_v]
        cap = carry[2 + n_v:]
        out = body_call(i, cond, *vs, *cap)
        cond_out = jnp.asarray(out[0]).astype(bool).reshape(())
        v_out = out[1:1 + n_v]
        scans = out[1 + n_v:]
        # masked advance: once cond goes False the carry freezes and the
        # scan rows repeat the last live value (divergence documented above);
        # body outputs are normalized to the carry's shape/dtype like the
        # while branch (a (1,)-shaped body output would break scan)
        v_next = tuple(
            jnp.where(cond,
                      jnp.asarray(nv).reshape(jnp.shape(ov))
                      .astype(jnp.asarray(ov).dtype), ov)
            for nv, ov in zip(v_out, vs))
        new_cond = jnp.logical_and(cond, cond_out)
        return (i + 1, new_cond) + v_next + tuple(cap), scans

    zero = sd.constant(node.name + "_i0", np.asarray(0, np.int64))
    cond0 = (cond_var if cond_var is not None
             else sd.constant(node.name + "_true", np.asarray(True)))
    if cond_var is not None:
        cond0 = sd._record("reshape", [cond0], {"shape": ()})
        cond0 = sd._record("cast", [cond0], {"dtype": "bool"})
    init = [zero, cond0] + v_init + cap_vars
    outs = sd.scan_multi(step, init, [], n_scan, length=m_static)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    n_carry = len(init)
    v_final = [outs[2 + j] for j in range(n_v)]
    scan_outs = [outs[n_carry + j] for j in range(n_scan)]
    return v_final + scan_outs


@register_onnx_op("Scan")
def _onnx_scan(sd, ins, attrs, node, scope=None, const_values=None):
    """ONNX Scan → lax.scan. Supports the default axes (0) and per-input/
    output directions (reverse handled by flip)."""
    import jax.numpy as jnp

    body_ir = attrs["body"]
    n_scan_in = int(attrs["num_scan_inputs"])
    caps = _implicit_inputs(body_ir)
    body_call, n_body_out = _subgraph_callable(body_ir, caps)
    cap_vars = _capture_vars(caps, scope or {}, node)

    n_state = len(ins) - n_scan_in
    states, scan_ins = list(ins[:n_state]), list(ins[n_state:])
    n_scan_out = n_body_out - n_state
    in_dirs = list(attrs.get("scan_input_directions", [0] * n_scan_in))
    out_dirs = list(attrs.get("scan_output_directions", [0] * n_scan_out))
    in_axes = list(attrs.get("scan_input_axes", [0] * n_scan_in))
    out_axes = list(attrs.get("scan_output_axes", [0] * n_scan_out))
    if any(a != 0 for a in in_axes) or any(a != 0 for a in out_axes):
        raise NotImplementedError(
            f"Scan {node.name}: non-zero scan axes are not supported")
    for j, d in enumerate(in_dirs):
        if int(d):
            scan_ins[j] = sd._record("reverse", [scan_ins[j]], {"axis": (0,)})

    def fn(carry, xs):
        st = carry[:n_state]
        cap = carry[n_state:]
        out = body_call(*st, *xs, *cap)
        return (tuple(out[:n_state]) + tuple(cap),
                tuple(out[n_state:]))

    outs = sd.scan_multi(fn, list(states) + cap_vars, scan_ins, n_scan_out)
    if not isinstance(outs, (list, tuple)):
        outs = [outs]
    n_carry = n_state + len(cap_vars)
    final_states = [outs[j] for j in range(n_state)]
    ys = [outs[n_carry + j] for j in range(n_scan_out)]
    for j, d in enumerate(out_dirs):
        if int(d):
            ys[j] = sd._record("reverse", [ys[j]], {"axis": (0,)})
    return final_states + ys


_NEEDS_SCOPE = {"Loop", "If", "Scan"}
_NEEDS_CONSTS |= {"Loop"}


# ---------------------------------------------------------------------------
# Dialect widening, round 5: ~35 ops toward the reference samediff-import-onnx
# registry breadth (SURVEY §3.2), incl. NonMaxSuppression/RoiAlign/ScatterND
# and the QuantizeLinear family; dynamic-output-shape ops (NonZero, Unique,
# Compress) are DOCUMENTED REJECTS — XLA requires static shapes; the
# reference's runtime interpreter can produce dynamic shapes, we cannot.
# ---------------------------------------------------------------------------

import jax as _jax
import jax.numpy as _jnp


def _graph_op(name):
    def wrap(fn):
        _sdmod.GRAPH_OPS.setdefault(name, fn)
        return fn
    return wrap


for _onnx, _sd in [("Shape", "shape_of"), ("Size", "size"),
                   ("Det", "matrix_determinant"),
                   ("GatherND", "gather_nd")]:
    ONNX_OP_MAPPERS.setdefault(_onnx, _unary(_sd))

for _onnx, _sd in [("GreaterOrEqual", "greater_equal"),
                   ("LessOrEqual", "less_equal")]:
    def _bin_rule3(sd, ins, attrs, node, _op=_sd):
        return sd._record(_op, ins)
    ONNX_OP_MAPPERS[_onnx] = _bin_rule3


def _nary_rule(sd_op):
    def rule(sd, ins, attrs, node):
        out = ins[0]
        for i in ins[1:]:
            out = sd._record(sd_op, [out, i])
        return out
    return rule


ONNX_OP_MAPPERS["Sum"] = _nary_rule("add")


@register_onnx_op("Mean")
def _onnx_mean(sd, ins, attrs, node):
    out = ins[0]
    for i in ins[1:]:
        out = sd._record("add", [out, i])
    k = sd.constant(node.name + "_n", np.asarray(float(len(ins)), np.float32))
    return sd._record("div", [out, k])


def _reduce2(sd_op):
    def rule(sd, ins, attrs, node, const_values=None):
        axes = attrs.get("axes")
        if axes is None and len(ins) > 1:  # opset 18 moves axes to input 2
            axes = tuple(int(a) for a in np.asarray(
                const_values[node.inputs[1]]).reshape(-1))
        axes = tuple(int(a) for a in axes) if axes is not None else None
        kd = bool(int(attrs.get("keepdims", 1)))
        return sd._record(sd_op, [ins[0]], {"axes": axes, "keepdims": kd})
    return rule


for _onnx, _sd in [("ReduceL1", "reduce_norm1"), ("ReduceL2", "reduce_norm2"),
                   ("ReduceLogSumExp", "reduce_logsumexp"),
                   ("ReduceSumSquare", "reduce_sqnorm")]:
    ONNX_OP_MAPPERS[_onnx] = _reduce2(_sd)
    _NEEDS_CONSTS.add(_onnx)


@register_onnx_op("ReduceLogSum")
def _reduce_log_sum(sd, ins, attrs, node, const_values=None):
    s = _reduce2("reduce_sum")(sd, ins, attrs, node, const_values=const_values)
    return sd._record("log", [s])


_NEEDS_CONSTS.add("ReduceLogSum")


@_graph_op("onnx_constant_of_shape")
def _const_of_shape(shape_arr, *, value, dtype):
    shp = tuple(int(s) for s in np.asarray(shape_arr).reshape(-1))
    return _jnp.full(shp, value, dtype=_jnp.dtype(dtype))


@register_onnx_op("ConstantOfShape")
def _onnx_const_of_shape(sd, ins, attrs, node, const_values=None):
    v = attrs.get("value")
    v = np.asarray(0.0, np.float32) if v is None else np.asarray(v).reshape(())
    return sd._record("onnx_constant_of_shape", [ins[0]],
                      {"value": float(v), "dtype": str(v.dtype)})


_NEEDS_CONSTS.add("ConstantOfShape")


@register_onnx_op("Range")
def _onnx_range(sd, ins, attrs, node, const_values=None):
    cv = const_values or {}
    vals = [cv.get(n) for n in node.inputs]
    if any(v is None for v in vals):
        raise NotImplementedError(
            f"Range {node.name}: start/limit/delta must be graph constants "
            f"(XLA needs a static output length)")
    s, l, d = (np.asarray(v).reshape(()) for v in vals)
    return sd.constant(node.name, np.arange(s, l, d))


_NEEDS_CONSTS.add("Range")


@register_onnx_op("OneHot")
def _onnx_one_hot(sd, ins, attrs, node, const_values=None):
    cv = const_values or {}
    depth = cv.get(node.inputs[1])
    values = cv.get(node.inputs[2])
    if depth is None or values is None:
        raise NotImplementedError(
            f"OneHot {node.name}: depth and values must be constants")
    off, on = (float(v) for v in np.asarray(values).reshape(-1))
    axis = int(attrs.get("axis", -1))
    out = sd._record("one_hot", [ins[0]],
                     {"depth": int(np.asarray(depth).reshape(())),
                      "on_value": on, "off_value": off})
    if axis != -1:
        # one_hot writes the new axis last; move it where the model asked
        out = sd._record("onnx_move_last_axis", [out], {"axis": axis})
    return out


_NEEDS_CONSTS.add("OneHot")


@_graph_op("onnx_move_last_axis")
def _move_last_axis(x, *, axis):
    perm = list(range(x.ndim - 1))
    perm.insert(axis if axis >= 0 else axis + x.ndim, x.ndim - 1)
    return _jnp.transpose(x, perm)


@_graph_op("eye_like")
def _eye_like(x, *, k=0):
    return _jnp.eye(x.shape[-2], x.shape[-1], k=k, dtype=x.dtype)


@register_onnx_op("EyeLike")
def _onnx_eye_like(sd, ins, attrs, node):
    return sd._record("eye_like", [ins[0]], {"k": int(attrs.get("k", 0))})


@_graph_op("gather_elements")
def _gather_elements(data, idx, *, axis=0):
    return _jnp.take_along_axis(data, idx.astype(_jnp.int32), axis=axis)


@register_onnx_op("GatherElements")
def _onnx_gather_elements(sd, ins, attrs, node):
    return sd._record("gather_elements", ins,
                      {"axis": int(attrs.get("axis", 0))})


@_graph_op("scatter_elements")
def _scatter_elements(data, idx, upd, *, axis=0, reduction="none"):
    idx = idx.astype(_jnp.int32)
    grids = _jnp.meshgrid(*[_jnp.arange(s) for s in idx.shape], indexing="ij")
    grids[axis] = idx
    ref = data.at[tuple(grids)]
    if reduction == "add":
        return ref.add(upd)
    if reduction == "mul":
        return ref.multiply(upd)
    if reduction == "max":
        return ref.max(upd)
    if reduction == "min":
        return ref.min(upd)
    return ref.set(upd)


@register_onnx_op("ScatterElements")
@register_onnx_op("Scatter")  # deprecated opset-9 alias
def _onnx_scatter_elements(sd, ins, attrs, node):
    return sd._record("scatter_elements", ins,
                      {"axis": int(attrs.get("axis", 0)),
                       "reduction": attrs.get("reduction", "none") or "none"})


@_graph_op("onnx_scatter_nd")
def _onnx_scatter_nd_impl(data, indices, updates, *, reduction="none"):
    idx = tuple(_jnp.moveaxis(indices.astype(_jnp.int32), -1, 0))
    ref = data.at[idx]
    if reduction == "add":
        return ref.add(updates)
    if reduction == "mul":
        return ref.multiply(updates)
    if reduction == "max":
        return ref.max(updates)
    if reduction == "min":
        return ref.min(updates)
    return ref.set(updates)


@register_onnx_op("ScatterND")
def _onnx_scatter_nd(sd, ins, attrs, node):
    return sd._record("onnx_scatter_nd", ins,
                      {"reduction": attrs.get("reduction", "none") or "none"})


@_graph_op("onnx_nms")
def _onnx_nms_impl(boxes, scores, *, max_out, iou_threshold, score_threshold,
                   center_point_box=0):
    """ONNX NonMaxSuppression with STATIC output: (B*C*max_out, 3) index
    triples [batch, class, box], padded with -1 (the reference emits a
    dynamic-length list; XLA cannot — the pad rows carry the same info).

    center_point_box=1 (the torchvision export form) supplies boxes as
    [x_center, y_center, width, height]; the kernel consumes corner
    coordinates, so convert up front."""
    from deeplearning4j_tpu.ops.image_ops import non_max_suppression as nms

    nms_fn = getattr(nms, "fn", nms)
    if center_point_box:
        xc, yc, w, h = (boxes[..., 0], boxes[..., 1],
                        boxes[..., 2], boxes[..., 3])
        boxes = _jnp.stack([yc - h / 2, xc - w / 2,
                            yc + h / 2, xc + w / 2], axis=-1)
    b, n, _ = boxes.shape
    c = scores.shape[1]
    rows = []
    for bi in range(b):
        for ci in range(c):
            idx, valid = nms_fn(boxes[bi], scores[bi, ci],
                                max_output_size=max_out,
                                iou_threshold=float(iou_threshold),
                                score_threshold=float(score_threshold))
            sel = _jnp.stack([_jnp.full((max_out,), bi, _jnp.int32),
                              _jnp.full((max_out,), ci, _jnp.int32),
                              idx.astype(_jnp.int32)], axis=1)
            rows.append(_jnp.where(valid.astype(bool)[:, None], sel, -1))
    return _jnp.concatenate(rows, axis=0)


@register_onnx_op("NonMaxSuppression")
def _onnx_nms(sd, ins, attrs, node, const_values=None):
    cv = const_values or {}
    n_in = list(node.inputs)
    mo = int(np.asarray(cv.get(n_in[2], 0)).reshape(())) if len(n_in) > 2 and n_in[2] else 0
    iou = float(np.asarray(cv.get(n_in[3], 0.0)).reshape(())) if len(n_in) > 3 and n_in[3] else 0.0
    sc = float(np.asarray(cv.get(n_in[4], -np.inf)).reshape(())) if len(n_in) > 4 and n_in[4] else -np.inf
    if mo <= 0:
        raise NotImplementedError(
            f"NonMaxSuppression {node.name}: max_output_boxes_per_class must "
            f"be a positive constant (static shapes)")
    cpb = int(attrs.get("center_point_box", 0))
    if cpb not in (0, 1):
        raise NotImplementedError(
            f"NonMaxSuppression {node.name}: center_point_box={cpb} "
            f"(spec allows only 0 or 1)")
    return sd._record("onnx_nms", list(ins[:2]),
                      {"max_out": mo, "iou_threshold": iou,
                       "score_threshold": sc, "center_point_box": cpb})


_NEEDS_CONSTS.add("NonMaxSuppression")


@_graph_op("onnx_roi_align")
def _roi_align_impl(x, rois, batch_idx, *, output_height, output_width,
                    sampling_ratio, spatial_scale, mode, coord_offset):
    """RoiAlign (exact bilinear-sampled definition, NCHW like ONNX)."""
    n, c, h, w = x.shape
    sr = sampling_ratio if sampling_ratio > 0 else 2
    oh, ow = output_height, output_width

    def one(roi, bi):
        x1, y1, x2, y2 = [r * spatial_scale - coord_offset for r in roi]
        rh = _jnp.maximum(y2 - y1, 1e-6)
        rw = _jnp.maximum(x2 - x1, 1e-6)
        bh, bw = rh / oh, rw / ow
        ys = y1 + (_jnp.arange(oh)[:, None] + (_jnp.arange(sr) + 0.5)[None, :] / sr) * bh
        xs = x1 + (_jnp.arange(ow)[:, None] + (_jnp.arange(sr) + 0.5)[None, :] / sr) * bw
        ys = ys.reshape(-1)  # (oh*sr,)
        xs = xs.reshape(-1)
        y0 = _jnp.clip(_jnp.floor(ys), 0, h - 1)
        x0 = _jnp.clip(_jnp.floor(xs), 0, w - 1)
        y1i = _jnp.clip(y0 + 1, 0, h - 1).astype(_jnp.int32)
        x1i = _jnp.clip(x0 + 1, 0, w - 1).astype(_jnp.int32)
        wy = _jnp.clip(ys, 0, h - 1) - y0
        wx = _jnp.clip(xs, 0, w - 1) - x0
        y0 = y0.astype(_jnp.int32)
        x0 = x0.astype(_jnp.int32)
        img = x[bi]  # (C,H,W)
        g = lambda yy, xx: img[:, yy[:, None], xx[None, :]]  # (C,Y,X)
        v = (g(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])[None]
             + g(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])[None]
             + g(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])[None]
             + g(y1i, x1i) * (wy[:, None] * wx[None, :])[None])
        v = v.reshape(c, oh, sr, ow, sr)
        if mode == "max":
            return v.max(axis=(2, 4))
        return v.mean(axis=(2, 4))

    return _jax.vmap(one)(rois, batch_idx.astype(_jnp.int32))


@register_onnx_op("RoiAlign")
def _onnx_roi_align(sd, ins, attrs, node):
    mode = attrs.get("mode", "avg") or "avg"
    cam = attrs.get("coordinate_transformation_mode", "half_pixel")
    return sd._record("onnx_roi_align", ins, {
        "output_height": int(attrs.get("output_height", 1)),
        "output_width": int(attrs.get("output_width", 1)),
        "sampling_ratio": int(attrs.get("sampling_ratio", 0)),
        "spatial_scale": float(attrs.get("spatial_scale", 1.0)),
        "mode": mode,
        "coord_offset": 0.5 if cam == "half_pixel" else 0.0})


@register_onnx_op("GlobalLpPool")
def _onnx_global_lp(sd, ins, attrs, node):
    p = int(attrs.get("p", 2))
    op = {1: "reduce_norm1", 2: "reduce_norm2"}.get(p)
    if op is None:
        raise NotImplementedError(f"GlobalLpPool p={p}")
    x = _to_nhwc(sd, ins[0])
    out = sd._record(op, [x], {"axes": (1, 2), "keepdims": True})
    return _to_nchw(sd, out)


@register_onnx_op("Celu")
def _onnx_celu(sd, ins, attrs, node):
    return sd._record("onnx_celu", [ins[0]],
                      {"alpha": float(attrs.get("alpha", 1.0))})


@_graph_op("onnx_celu")
def _celu_impl(x, *, alpha):
    return (_jnp.maximum(x, 0.0)
            + _jnp.minimum(0.0, alpha * (_jnp.exp(x / alpha) - 1.0)))


@register_onnx_op("HardSwish")
def _onnx_hardswish(sd, ins, attrs, node):
    return sd._record("onnx_hardswish", [ins[0]])


@_graph_op("onnx_hardswish")
def _hardswish_impl(x):
    return x * _jnp.clip(x / 6.0 + 0.5, 0.0, 1.0)


@register_onnx_op("Shrink")
def _onnx_shrink(sd, ins, attrs, node):
    return sd._record("onnx_shrink", [ins[0]],
                      {"bias": float(attrs.get("bias", 0.0)),
                       "lambd": float(attrs.get("lambd", 0.5))})


@_graph_op("onnx_shrink")
def _shrink_impl(x, *, bias, lambd):
    return _jnp.where(x < -lambd, x + bias,
                      _jnp.where(x > lambd, x - bias, 0.0))


@register_onnx_op("LayerNormalization")
def _onnx_layernorm(sd, ins, attrs, node):
    axis = int(attrs.get("axis", -1))
    if axis != -1:
        raise NotImplementedError(
            f"LayerNormalization {node.name}: axis={axis} (only the trailing "
            f"axis maps to the catalog layer_norm)")
    out = sd._record("layer_norm", ins[:2] + (list(ins[2:3]) if len(ins) > 2 else []),
                     {"eps": float(attrs.get("epsilon", 1e-5))})
    return out


@_graph_op("onnx_bitshift")
def _bitshift_impl(x, y, *, direction):
    if direction == "LEFT":
        return _jnp.left_shift(x, y)
    return _jnp.right_shift(x, y)


@register_onnx_op("BitShift")
def _onnx_bitshift(sd, ins, attrs, node):
    return sd._record("onnx_bitshift", ins,
                      {"direction": attrs.get("direction", "LEFT") or "LEFT"})


@_graph_op("onnx_random_normal")
def _rand_normal_impl(*, shape, mean, scale, seed, dtype):
    k = _jax.random.key(seed)
    return mean + scale * _jax.random.normal(k, tuple(shape), _jnp.dtype(dtype))


@_graph_op("onnx_random_uniform")
def _rand_uniform_impl(*, shape, low, high, seed, dtype):
    k = _jax.random.key(seed)
    return _jax.random.uniform(k, tuple(shape), _jnp.dtype(dtype), low, high)


_ONNX_FLOAT_DT = {1: "float32", 10: "float16", 11: "float64"}


def _onnx_seed(attrs, node):
    """Stable stream key: the (float) seed attr when given, else a crc32 of
    the node name — unseeded ops must not all share key(0), and hash() is
    PYTHONHASHSEED-randomized across processes."""
    import zlib

    s = attrs.get("seed")
    if s is not None and float(s) != 0.0:
        return int(float(s)) & 0x7FFFFFFF
    return zlib.crc32(node.name.encode()) & 0x7FFFFFFF


def _onnx_float_dtype(attrs, node):
    code = attrs.get("dtype")
    if code is None:
        return "float32"
    dt = _ONNX_FLOAT_DT.get(int(code))
    if dt is None:
        raise NotImplementedError(
            f"{node.op_type} {node.name}: non-float random dtype code "
            f"{int(code)}")
    return dt


@register_onnx_op("RandomNormal")
def _onnx_random_normal(sd, ins, attrs, node):
    return sd._record("onnx_random_normal", [], {
        "shape": tuple(int(s) for s in attrs["shape"]),
        "mean": float(attrs.get("mean", 0.0)),
        "scale": float(attrs.get("scale", 1.0)),
        "seed": _onnx_seed(attrs, node),
        "dtype": _onnx_float_dtype(attrs, node)})


@register_onnx_op("RandomUniform")
def _onnx_random_uniform(sd, ins, attrs, node):
    return sd._record("onnx_random_uniform", [], {
        "shape": tuple(int(s) for s in attrs["shape"]),
        "low": float(attrs.get("low", 0.0)),
        "high": float(attrs.get("high", 1.0)),
        "seed": _onnx_seed(attrs, node),
        "dtype": _onnx_float_dtype(attrs, node)})


@_graph_op("onnx_random_normal_like")
def _rand_normal_like(x, *, mean, scale, seed):
    return mean + scale * _jax.random.normal(_jax.random.key(seed), x.shape,
                                             x.dtype)


@register_onnx_op("RandomNormalLike")
def _onnx_random_normal_like(sd, ins, attrs, node):
    return sd._record("onnx_random_normal_like", [ins[0]], {
        "mean": float(attrs.get("mean", 0.0)),
        "scale": float(attrs.get("scale", 1.0)),
        "seed": _onnx_seed(attrs, node)})


@_graph_op("onnx_random_uniform_like")
def _rand_uniform_like(x, *, low, high, seed):
    return _jax.random.uniform(_jax.random.key(seed), x.shape, x.dtype,
                               low, high)


@register_onnx_op("RandomUniformLike")
def _onnx_random_uniform_like(sd, ins, attrs, node):
    return sd._record("onnx_random_uniform_like", [ins[0]], {
        "low": float(attrs.get("low", 0.0)),
        "high": float(attrs.get("high", 1.0)),
        "seed": _onnx_seed(attrs, node)})


@_graph_op("onnx_bernoulli")
def _bernoulli_impl(x, *, seed):
    return _jax.random.bernoulli(_jax.random.key(seed), x).astype(x.dtype)


@register_onnx_op("Bernoulli")
def _onnx_bernoulli(sd, ins, attrs, node):
    return sd._record("onnx_bernoulli", [ins[0]],
                      {"seed": _onnx_seed(attrs, node)})


@register_onnx_op("Multinomial")
def _onnx_multinomial(sd, ins, attrs, node):
    return sd._record("onnx_multinomial", [ins[0]], {
        "sample_size": int(attrs.get("sample_size", 1)),
        "seed": _onnx_seed(attrs, node)})


@_graph_op("onnx_multinomial")
def _multinomial_impl(logprobs, *, sample_size, seed):
    k = _jax.random.key(seed)
    return _jax.random.categorical(k, logprobs, axis=-1,
                                   shape=(logprobs.shape[0], sample_size)
                                   ).astype(_jnp.int32)


@register_onnx_op("DequantizeLinear")
def _onnx_dequant(sd, ins, attrs, node):
    x = sd._record("cast", [ins[0]], {"dtype": "float32"})
    if len(ins) > 2:
        zp = sd._record("cast", [ins[2]], {"dtype": "float32"})
        x = sd._record("sub", [x, zp])
    return sd._record("mul", [x, ins[1]])


@register_onnx_op("QuantizeLinear")
def _onnx_quant(sd, ins, attrs, node, const_values=None):
    cv = const_values or {}
    zp_name = node.inputs[2] if len(node.inputs) > 2 and node.inputs[2] else None
    zp_arr = cv.get(zp_name) if zp_name else None
    # dtype comes from the zero point (spec); uint8 is the default
    qdt = (np.asarray(zp_arr).dtype if zp_arr is not None
           else np.dtype(np.uint8))
    lo_v, hi_v = ((0.0, 255.0) if qdt == np.dtype(np.uint8)
                  else (-128.0, 127.0))
    scaled = sd._record("div", [ins[0], ins[1]])
    r = sd._record("round", [scaled])
    if len(ins) > 2:
        zp = sd._record("cast", [ins[2]], {"dtype": "float32"})
        r = sd._record("add", [r, zp])
    lo = sd.constant(node.name + "_lo", np.asarray(lo_v, np.float32))
    hi = sd.constant(node.name + "_hi", np.asarray(hi_v, np.float32))
    r = sd._record("maximum", [r, lo])
    r = sd._record("minimum", [r, hi])
    return sd._record("cast", [r], {"dtype": str(qdt)})


_NEEDS_CONSTS.add("QuantizeLinear")


@register_onnx_op("DynamicQuantizeLinear")
def _onnx_dyn_quant(sd, ins, attrs, node):
    return sd._record("onnx_dynamic_quantize", [ins[0]], n_out=3)


@_graph_op("onnx_dynamic_quantize")
def _dyn_quant_impl(x):
    lo = _jnp.minimum(x.min(), 0.0)
    hi = _jnp.maximum(x.max(), 0.0)
    scale = (hi - lo) / 255.0
    zp = _jnp.clip(_jnp.round(-lo / _jnp.maximum(scale, 1e-12)), 0, 255)
    q = _jnp.clip(_jnp.round(x / _jnp.maximum(scale, 1e-12)) + zp, 0, 255
                  ).astype(_jnp.uint8)
    return q, scale, zp.astype(_jnp.uint8)


def _documented_reject(op_name, why):
    def rule(sd, ins, attrs, node):
        raise NotImplementedError(
            f"{op_name} ({node.name}): {why}. The reference's host-side "
            f"interpreter can produce dynamic shapes; XLA compilation cannot "
            f"— restructure the model (e.g. NonMaxSuppression's padded-"
            f"output form) or precompute this node outside the graph.")
    return rule


for _op_name, _why in [
        ("NonZero", "dynamic-length output (count of nonzeros)"),
        ("Unique", "dynamic-length output (count of distinct values)"),
        ("Compress", "dynamic-length output (count of selected rows)"),
        ("StringNormalizer", "string tensors are unsupported"),
        ("TfIdfVectorizer", "string/sequence processing is unsupported"),
        ("MatMulInteger", "int8 matmul maps to no TPU-profitable kernel"),
        ("ConvInteger", "int8 conv maps to no TPU-profitable kernel"),
        ("QLinearConv", "fused int8 conv: use DequantizeLinear + Conv"),
        ("QLinearMatMul", "fused int8 matmul: use DequantizeLinear + MatMul")]:
    ONNX_OP_MAPPERS[_op_name] = _documented_reject(_op_name, _why)


@register_onnx_op("Upsample")  # deprecated opset-9 form of Resize
def _onnx_upsample(sd, ins, attrs, node, const_values=None):
    return ONNX_OP_MAPPERS["Resize"](
        sd, [ins[0], None, ins[1] if len(ins) > 1 else None], attrs,
        node, const_values=const_values)


_NEEDS_CONSTS.add("Upsample")


@_graph_op("onnx_rnn")
def _onnx_rnn_impl(x, w, r, b, h_init, *, hidden_size, activation):
    """ONNX vanilla RNN (Elman), single direction. x: (T,B,I), w: (1,H,I),
    r: (1,H,H), b: (1,2H), h_init: (1,B,H). Returns (Y (T,1,B,H),
    Y_h (1,B,H))."""
    act = {"Tanh": _jnp.tanh, "Relu": lambda v: _jnp.maximum(v, 0.0),
           "Sigmoid": _jax.nn.sigmoid}[activation]
    wt = w[0].T
    rt = r[0].T
    bias = (b[0, :hidden_size] + b[0, hidden_size:]) if b is not None else 0.0
    h0 = _jnp.broadcast_to(h_init[0],
                           (x.shape[1], hidden_size)).astype(x.dtype)

    def step(h, xt):
        h = act(xt @ wt + h @ rt + bias)
        return h, h

    hT, ys = _jax.lax.scan(step, h0, x)
    return ys[:, None], hT[None]


@register_onnx_op("RNN")
def _onnx_rnn(sd, ins, attrs, node):
    if attrs.get("direction") == "bidirectional":
        raise NotImplementedError("bidirectional RNN import")
    acts = attrs.get("activations") or ["Tanh"]
    # optional inputs are positional with empty-name gaps — realign
    pos = [i for i, nm in enumerate(node.inputs) if nm]
    slot = dict(zip(pos, ins))
    h = int(attrs["hidden_size"])
    if 4 in slot:
        raise NotImplementedError(
            f"RNN {node.name}: sequence_lens input is not supported "
            f"(variable-length unrolling; pad or mask outside the graph)")
    b = slot.get(3)
    if b is None:
        b = sd.constant(node.name + "_b0", np.zeros((1, 2 * h), np.float32))
    h0 = slot.get(5)
    if h0 is None:
        # batch size is static in the X placeholder at execution; a zeros
        # initial state materializes lazily from X inside the impl via
        # broadcasting a (1,1,H) constant
        h0 = sd.constant(node.name + "_h0", np.zeros((1, 1, h), np.float32))
    use = [slot[0], slot[1], slot[2], b, h0]
    return sd._record("onnx_rnn", use,
                      {"hidden_size": int(attrs["hidden_size"]),
                       "activation": acts[0] if isinstance(acts[0], str)
                       else acts[0].decode()}, n_out=2)


@_graph_op("onnx_grid_sample")
def _grid_sample_impl(x, grid, *, mode, padding_mode, align_corners):
    """ONNX GridSample (NCHW x, NHW2 grid in [-1,1] xy order) — bilinear /
    nearest with zeros/border padding, the torch.nn.functional.grid_sample
    semantics detection/segmentation exports rely on."""
    if x.ndim != 4:
        raise NotImplementedError(
            f"GridSample: only 4-D NCHW input is supported (got rank "
            f"{x.ndim}; volumetric 5-D GridSample is an opset-16 extension)")
    n, c, h, w = x.shape
    gx = grid[..., 0]
    gy = grid[..., 1]
    if align_corners:
        fx = (gx + 1) * 0.5 * (w - 1)
        fy = (gy + 1) * 0.5 * (h - 1)
    else:
        fx = ((gx + 1) * w - 1) * 0.5
        fy = ((gy + 1) * h - 1) * 0.5

    def gather(yy, xx):
        yc = _jnp.clip(yy, 0, h - 1).astype(_jnp.int32)
        xc = _jnp.clip(xx, 0, w - 1).astype(_jnp.int32)
        # (N, Ho, Wo) index maps into (N, C, H, W) -> (N, C, Ho, Wo)
        vals = _jax.vmap(lambda img, y_, x_: img[:, y_, x_])(x, yc, xc)
        if padding_mode == "zeros":
            inb = ((yy >= 0) & (yy <= h - 1) & (xx >= 0) & (xx <= w - 1))
            vals = vals * inb[:, None].astype(vals.dtype)
        return vals

    if mode == "nearest":
        return gather(_jnp.round(fy), _jnp.round(fx))
    y0 = _jnp.floor(fy)
    x0 = _jnp.floor(fx)
    wy = (fy - y0)[:, None]
    wx = (fx - x0)[:, None]
    return (gather(y0, x0) * (1 - wy) * (1 - wx)
            + gather(y0, x0 + 1) * (1 - wy) * wx
            + gather(y0 + 1, x0) * wy * (1 - wx)
            + gather(y0 + 1, x0 + 1) * wy * wx)


@register_onnx_op("GridSample")
def _onnx_grid_sample(sd, ins, attrs, node):
    mode = attrs.get("mode", "linear") or "linear"
    mode = {"bilinear": "linear"}.get(mode, mode)
    if mode not in ("linear", "nearest"):
        raise NotImplementedError(f"GridSample mode={mode}")
    pad = attrs.get("padding_mode", "zeros") or "zeros"
    if pad not in ("zeros", "border"):
        raise NotImplementedError(f"GridSample padding_mode={pad}")
    return sd._record("onnx_grid_sample", ins, {
        "mode": "nearest" if mode == "nearest" else "linear",
        "padding_mode": pad,
        "align_corners": bool(int(attrs.get("align_corners", 0)))})
