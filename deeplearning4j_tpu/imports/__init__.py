"""Model import — TF GraphDef → SameDiff (samediff-import role)."""

from deeplearning4j_tpu.imports.tf_import import (
    TensorflowImporter,
    import_frozen_graph,
    register_tf_op,
)
