"""Model import — TF GraphDef / ONNX ModelProto → SameDiff
(samediff-import role: shared IR layer + per-framework dialect tables)."""

from deeplearning4j_tpu.imports.ir import IRGraph, IRImporter, IRNode
from deeplearning4j_tpu.imports.tf_import import (
    TensorflowImporter,
    import_frozen_graph,
    register_tf_op,
)
from deeplearning4j_tpu.imports.onnx_import import (
    OnnxImporter,
    import_onnx,
    register_onnx_op,
)
from deeplearning4j_tpu.imports.graph_runner import GraphRunner
from deeplearning4j_tpu.imports.keras_import import (
    KerasLayerMapper,
    import_keras_model,
    import_keras_sequential_model_and_weights,
    import_keras_model_and_weights,
    register_custom_layer,
)
