"""Keras model import — deeplearning4j-modelimport parity.

Reference parity:
  * org/deeplearning4j/nn/modelimport/keras/KerasModelImport.java,
    KerasModel/KerasSequentialModel, layers/** (~100 per-layer mappers),
    utils/Hdf5Archive.java — parse Keras HDF5 (architecture JSON + weight
    groups) into a DL4J network.

Scope: Sequential models over the common layer set (Dense, Conv2D,
MaxPooling2D/AveragePooling2D, Flatten, Dropout, BatchNormalization,
Activation, Embedding, LSTM, GlobalAveragePooling2D) → MultiLayerNetwork.
Weights transpose from Keras layouts to ours (kernel HWIO already matches;
LSTM gate order i,f,c,o → our i,f,o,g reordering).

Supports both legacy HDF5 (.h5) files and in-memory keras model objects
(`import_keras_model`), so golden tests build models with in-env tf.keras.
"""

from __future__ import annotations

import json
import warnings
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import conf as C

_ACT_MAP = {
    "relu": "relu", "softmax": "softmax", "tanh": "tanh", "sigmoid": "sigmoid",
    "linear": "identity", "elu": "elu", "selu": "selu", "gelu": "gelu",
    "softplus": "softplus", "softsign": "softsign", "swish": "swish",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
}


def _act(cfg) -> str:
    a = cfg.get("activation", "linear")
    if isinstance(a, dict):
        a = a.get("class_name", "linear").lower()
    return _ACT_MAP.get(a, a)


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class KerasLayerMapper:
    """Registry of per-layer-class mappers (KerasLayer subclass table)."""

    MAPPERS: Dict[str, Any] = {}

    @classmethod
    def register(cls, name):
        def wrap(fn):
            cls.MAPPERS[name] = fn
            return fn

        return wrap


@KerasLayerMapper.register("Dense")
def _dense(cfg, weights):
    lc = nn.DenseLayer(n_out=cfg["units"], activation=_act(cfg),
                       has_bias=cfg.get("use_bias", True), name=cfg.get("name"))
    p = {"W": weights[0]}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("Conv2D")
def _conv2d(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    lc = nn.ConvolutionLayer(
        n_out=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=pad,
        dilation=_pair(cfg.get("dilation_rate", 1)), activation=_act(cfg),
        has_bias=cfg.get("use_bias", True), name=cfg.get("name"))
    p = {"W": weights[0]}  # keras kernel is HWIO — matches our layout
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("MaxPooling2D")
def _maxpool(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    return nn.SubsamplingLayer(
        pooling_type="max", kernel=_pair(cfg.get("pool_size", 2)),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
        convolution_mode=pad, name=cfg.get("name")), {}


@KerasLayerMapper.register("AveragePooling2D")
def _avgpool(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    return nn.SubsamplingLayer(
        pooling_type="avg", kernel=_pair(cfg.get("pool_size", 2)),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
        convolution_mode=pad, name=cfg.get("name")), {}


@KerasLayerMapper.register("GlobalAveragePooling2D")
def _gap(cfg, weights):
    return nn.GlobalPoolingLayer(pooling_type="avg", name=cfg.get("name")), {}


@KerasLayerMapper.register("Flatten")
def _flatten(cfg, weights):
    return "FLATTEN", {}


@KerasLayerMapper.register("Dropout")
def _dropout(cfg, weights):
    return nn.DropoutLayer(rate=cfg.get("rate", 0.5), name=cfg.get("name")), {}


@KerasLayerMapper.register("Activation")
def _activation(cfg, weights):
    return nn.ActivationLayer(activation=_act(cfg), name=cfg.get("name")), {}


@KerasLayerMapper.register("BatchNormalization")
def _bn(cfg, weights):
    lc = nn.BatchNormalization(eps=cfg.get("epsilon", 1e-3),
                               decay=cfg.get("momentum", 0.99),
                               name=cfg.get("name"))
    # keras order: gamma, beta, moving_mean, moving_variance
    p = {"gamma": weights[0], "beta": weights[1]}
    state = {"mean": weights[2], "var": weights[3]}
    return lc, {"__params__": p, "__state__": state}


@KerasLayerMapper.register("Embedding")
def _embedding(cfg, weights):
    lc = nn.EmbeddingSequenceLayer(n_in=cfg["input_dim"], n_out=cfg["output_dim"],
                                   name=cfg.get("name"))
    return lc, {"W": weights[0]}


@KerasLayerMapper.register("LSTM")
def _lstm(cfg, weights):
    units = cfg["units"]
    if cfg.get("go_backwards", False):
        raise NotImplementedError("LSTM import with go_backwards=True")
    lc = nn.LSTM(n_out=units, activation=_act(cfg),
                 gate_activation=_ACT_MAP.get(cfg.get("recurrent_activation",
                                                      "sigmoid"), "sigmoid"),
                 forget_gate_bias_init=0.0, name=cfg.get("name"))
    kernel, recurrent, bias = weights[0], weights[1], weights[2]

    def regate(w):
        # keras gate order [i, f, c, o] → ours [i, f, o, g(c)]
        i, f, c, o = np.split(w, 4, axis=-1)
        return np.concatenate([i, f, o, c], axis=-1)

    p = {"W": regate(kernel), "RW": regate(recurrent), "b": regate(bias)}
    if not cfg.get("return_sequences", False):
        # keras default emits the LAST step only → wrap in LastTimeStep
        from deeplearning4j_tpu.nn import conf as _C

        return _C.LastTimeStep(fwd=lc.to_dict(), name=cfg.get("name")), \
            {"inner": p}
    return lc, p


def _assemble_sequential(specs, input_type,
                         validate: bool = True) -> nn.MultiLayerNetwork:
    """Shared Sequential assembly + weight grafting: specs are
    (class_name, layer_cfg, weights) triples from EITHER a live keras model
    or an own-parsed h5 config. Keras flattens conv activations HWC-major
    while our CnnToFeedForward preprocessor flattens CHW-major, so the
    input rows of a Dense W sitting right after that preprocessor are
    reordered during grafting."""
    import jax.numpy as jnp

    layer_confs: List[C.LayerConf] = []
    params_list: List[Dict[str, Any]] = []
    states_list: List[Dict[str, Any]] = []
    for cls, cfg, weights in specs:
        mapper = KerasLayerMapper.MAPPERS.get(cls)
        if mapper is None:
            raise NotImplementedError(
                f"Keras layer '{cls}' has no import mapper; register one on "
                f"KerasLayerMapper")
        out = mapper(cfg, weights)
        # a mapper may expand ONE keras layer into several of ours
        # (RNN(cell=StackedRNNCells) → one recurrent layer per cell)
        items = out if isinstance(out, list) else [out]
        for lc, p in items:
            if lc == "FLATTEN":
                continue  # shape inference inserts CnnToFeedForward automatically
            state = {}
            if isinstance(p, dict) and "__params__" in p:
                state = p["__state__"]
                p = p["__params__"]
            layer_confs.append(lc)
            params_list.append(p)
            states_list.append(state)
    b = nn.builder().list()
    for lc in layer_confs:
        b.layer(lc)
    conf = b.set_input_type(input_type).build()
    net = nn.MultiLayerNetwork(conf).init()
    for i, (lc, p, st) in enumerate(zip(layer_confs, params_list, states_list)):
        pre = net.conf.preprocessors.get(i)
        for k, w in p.items():
            if (k == "W" and isinstance(pre, C.CnnToFeedForwardPreProcessor)
                    and hasattr(w, "ndim") and w.ndim == 2
                    and w.shape[0] == pre.height * pre.width * pre.channels):
                w = (w.reshape(pre.height, pre.width, pre.channels, -1)
                     .transpose(2, 0, 1, 3)
                     .reshape(w.shape[0], -1))
            if (k == "W"
                    and isinstance(pre, C.Cnn3DToFeedForwardPreProcessor)
                    and hasattr(w, "ndim") and w.ndim == 2
                    and w.shape[0] == pre.depth * pre.height * pre.width
                    * pre.channels):
                # keras flattens NDHWC; our 3-D preprocessor is channel-major
                w = (w.reshape(pre.depth, pre.height, pre.width,
                               pre.channels, -1)
                     .transpose(3, 0, 1, 2, 4)
                     .reshape(w.shape[0], -1))
            # arbitrary nesting (Bidirectional-in-LastTimeStep wraps two
            # levels deep): graft every leaf
            import jax

            net.params[i][k] = jax.tree.map(jnp.asarray, w)
        for k, v in st.items():
            net.net_state[i][k] = jnp.asarray(v)
    # graftcheck (docs/ANALYSIS.md): same verify-after-import contract as
    # the ONNX/TF frontends — provable layer shape errors raise here with
    # layer provenance, not at first forward (validate=False opts out,
    # matching import_onnx/TensorflowImporter)
    if validate:
        from deeplearning4j_tpu.analysis import check_network

        net.last_check_report = check_network(
            net, graph_name="keras:sequential")
        net.last_check_report.raise_on_errors()
    return net


def import_keras_model(model, input_type: Optional[C.InputType] = None,
                       validate: bool = True):
    """In-memory tf.keras model → MultiLayerNetwork (Sequential) or
    ComputationGraph (functional) — the KerasModelImport.importKeras*
    dispatch for live models."""
    if not any(c.__name__ == "Sequential" for c in type(model).__mro__):
        weights_map = {kl.name: [np.asarray(w) for w in kl.get_weights()]
                       for kl in model.layers}
        config = {"class_name": "Functional", "config": model.get_config()}
        return import_keras_functional_config(config, weights_map,
                                              validate=validate)
    specs = []
    for kl in model.layers:
        cls = type(kl).__name__
        if cls == "InputLayer":
            continue
        specs.append((cls, kl.get_config(),
                      [np.asarray(w) for w in kl.get_weights()]))
    if input_type is None:
        input_type = _infer_input_type_from_shape(model.input_shape)
    return _assemble_sequential(specs, input_type, validate=validate)


def import_keras_sequential_model_and_weights(
        h5_path: str, validate: bool = True) -> nn.MultiLayerNetwork:
    """KerasModelImport entry: load a saved .h5/.keras file via in-env keras,
    then convert."""
    import tensorflow as tf

    model = tf.keras.models.load_model(h5_path, compile=False)
    return import_keras_model(model, validate=validate)


# ---------------------------------------------------------------------------
# Widened mapper table (round 3): conv variants, poolings, RNNs, advanced
# activations — KerasLayer subclass coverage toward the reference's ~100.
# ---------------------------------------------------------------------------


@KerasLayerMapper.register("DepthwiseConv2D")
def _depthwise(cfg, weights):
    k = _pair(cfg["kernel_size"])
    dw = weights[0]  # (kh, kw, C, mult) — matches our layout
    lc = C.DepthwiseConvolution2D(
        n_in=dw.shape[2], n_out=dw.shape[2] * dw.shape[3], kernel=k,
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=cfg.get("padding", "valid"),
        activation=_act(cfg), has_bias=cfg.get("use_bias", True),
        depth_multiplier=dw.shape[3])
    p = {"W": dw}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("SeparableConv2D")
def _separable(cfg, weights):
    k = _pair(cfg["kernel_size"])
    dw, pw = weights[0], weights[1]  # (kh,kw,C,mult), (1,1,C*mult,out)
    lc = C.SeparableConvolution2D(
        n_in=dw.shape[2], n_out=pw.shape[3], kernel=k,
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=cfg.get("padding", "valid"),
        activation=_act(cfg), has_bias=cfg.get("use_bias", True),
        depth_multiplier=dw.shape[3])
    p = {"dW": dw, "pW": pw}
    if cfg.get("use_bias", True) and len(weights) > 2:
        p["b"] = weights[2]
    return lc, p


@KerasLayerMapper.register("Conv2DTranspose")
def _deconv(cfg, weights):
    k = _pair(cfg["kernel_size"])
    w = weights[0]  # keras: (kh, kw, out, in) → ours: (kh, kw, in, out)
    lc = C.Deconvolution2D(
        n_in=w.shape[3], n_out=w.shape[2], kernel=k,
        stride=_pair(cfg.get("strides", 1)),
        convolution_mode=cfg.get("padding", "valid"),
        activation=_act(cfg), has_bias=cfg.get("use_bias", True))
    p = {"W": w.transpose(0, 1, 3, 2)}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("GlobalMaxPooling2D")
def _gmp(cfg, weights):
    return C.GlobalPoolingLayer(pooling_type="max"), {}


@KerasLayerMapper.register("UpSampling2D")
def _upsampling(cfg, weights):
    return C.Upsampling2D(size=_pair(cfg.get("size", 2))), {}


@KerasLayerMapper.register("SimpleRNN")
def _simple_rnn(cfg, weights):
    w, rw, b = weights[0], weights[1], (weights[2] if len(weights) > 2
                                        else np.zeros(weights[0].shape[1]))
    lc = C.SimpleRnn(n_in=w.shape[0], n_out=w.shape[1],
                     activation=_act(cfg))
    return lc, {"W": w, "RW": rw, "b": b}


@KerasLayerMapper.register("Bidirectional")
def _bidirectional(cfg, weights):
    inner_spec = cfg["layer"]
    if inner_spec["class_name"] != "LSTM":
        raise NotImplementedError(
            f"Bidirectional({inner_spec['class_name']}) import")
    half = len(weights) // 2
    inner_cfg = inner_spec["config"]
    fwd_lc, fwd_p = _lstm(inner_cfg, weights[:half])
    _, bwd_p = _lstm(inner_cfg, weights[half:])
    merge = cfg.get("merge_mode", "concat")
    mode = {"sum": "add", "ave": "average", "mul": "mul",
            "concat": "concat", "add": "add", "average": "average"}.get(merge)
    if mode is None:
        raise NotImplementedError(
            f"Bidirectional merge_mode={merge!r} import (None means "
            "two-output mode, which MultiLayerNetwork cannot represent)")
    lc = C.Bidirectional(fwd=fwd_lc.to_dict(), mode=mode)
    return lc, {"fwd": fwd_p, "bwd": bwd_p}


@KerasLayerMapper.register("LeakyReLU")
def _leaky_relu(cfg, weights):
    # keras defaults alpha=0.3 (ours 0.01) — bind the exact slope as a
    # callable activation (get_activation passes callables through)
    import functools

    from deeplearning4j_tpu.ops.activations import leakyrelu

    alpha = float(cfg.get("negative_slope", cfg.get("alpha", 0.3)))
    return C.ActivationLayer(
        activation=functools.partial(leakyrelu, alpha=alpha)), {}


@KerasLayerMapper.register("ReLU")
def _relu_layer(cfg, weights):
    if cfg.get("max_value") not in (None, 0) or cfg.get("threshold", 0):
        raise NotImplementedError("ReLU with max_value/threshold import")
    slope = float(cfg.get("negative_slope", 0) or 0)
    if slope:
        import functools

        from deeplearning4j_tpu.ops.activations import leakyrelu

        return C.ActivationLayer(
            activation=functools.partial(leakyrelu, alpha=slope)), {}
    return C.ActivationLayer(activation="relu"), {}


@KerasLayerMapper.register("ELU")
def _elu_layer(cfg, weights):
    return C.ActivationLayer(activation="elu"), {}


@KerasLayerMapper.register("Softmax")
def _softmax_layer(cfg, weights):
    return C.ActivationLayer(activation="softmax"), {}


@KerasLayerMapper.register("SpatialDropout2D")
def _spatial_dropout(cfg, weights):
    return C.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                          mode="spatial", name=cfg.get("name")), {}


@KerasLayerMapper.register("GaussianDropout")
def _gaussian_dropout(cfg, weights):
    return C.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                          mode="gaussian", name=cfg.get("name")), {}


# ---------------------------------------------------------------------------
# Own HDF5 reading (Hdf5Archive.java analog) — no tf.keras deserialization
# ---------------------------------------------------------------------------


def read_keras_h5(path: str):
    """Parse a legacy Keras .h5 file with h5py directly: returns
    (model_config dict, {layer_name: [weight arrays in weight_names order]}).

    The reference's Hdf5Archive reads the same two pieces (model_config
    JSON attr + model_weights groups) through the HDF5 C API."""
    import h5py

    with h5py.File(path, "r") as f:
        raw = f.attrs["model_config"]
        if isinstance(raw, bytes):
            raw = raw.decode("utf-8")
        config = json.loads(raw)
        weights: Dict[str, List[np.ndarray]] = {}
        mw = f["model_weights"]
        for lname in mw:
            g = mw[lname]
            names = [n.decode() if isinstance(n, bytes) else str(n)
                     for n in g.attrs.get("weight_names", [])]
            arrs = []
            for n in names:
                node = g[n] if n in g else f["model_weights"][n]
                arrs.append(np.asarray(node))
            weights[lname] = arrs
    return config, weights


def _layer_specs_from_config(config):
    """[(class_name, layer_cfg, layer_name)] from a Sequential config."""
    out = []
    for entry in config["config"]["layers"]:
        cls = entry["class_name"]
        cfg = entry.get("config", {})
        out.append((cls, cfg, cfg.get("name", entry.get("name", ""))))
    return out


def _infer_input_type_from_shape(shape):
    shape = tuple(shape)
    if len(shape) == 2:
        return C.InputType.feed_forward(shape[1])
    if len(shape) == 4:
        return C.InputType.convolutional(shape[1], shape[2], shape[3])
    if len(shape) == 3:
        # keep the static sequence length when keras declares one — layers
        # like Permute/LocallyConnected1D need it for shape inference
        return C.InputType.recurrent(shape[2], shape[1] or -1)
    if len(shape) == 5:
        return C.InputType.convolutional3d(shape[1], shape[2], shape[3],
                                           shape[4])
    raise ValueError(f"cannot infer InputType from {shape}")


def import_keras_sequential_config(config, weights_map,
                                   validate: bool = True
                                   ) -> nn.MultiLayerNetwork:
    """Sequential model_config + weights dict → MultiLayerNetwork (the
    own-h5 path; shares _assemble_sequential with the live-model path)."""
    specs = []
    input_shape = None
    for cls, cfg, name in _layer_specs_from_config(config):
        if cls == "InputLayer":
            input_shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
            continue
        if input_shape is None and "batch_input_shape" in cfg:
            input_shape = cfg["batch_input_shape"]
        specs.append((cls, cfg, weights_map.get(name, [])))
    return _assemble_sequential(
        specs, _infer_input_type_from_shape(input_shape), validate=validate)


# ---------------------------------------------------------------------------
# Functional-API import → ComputationGraph (KerasModel.java analog)
# ---------------------------------------------------------------------------

_MERGE_LAYERS = {
    "Add": ("elementwise", "add"),
    "Subtract": ("elementwise", "subtract"),
    "Multiply": ("elementwise", "product"),
    "Average": ("elementwise", "average"),
    "Maximum": ("elementwise", "max"),
    "Minimum": ("elementwise", "min"),
    "Concatenate": ("merge", None),
}


def _inbound_names(layer) -> List[str]:
    """Input layer-names of a functional-config layer — handles both the
    keras-3 __keras_tensor__ args form and the legacy nested-list form."""
    names: List[str] = []

    def walk(o):
        if isinstance(o, dict):
            if o.get("class_name") == "__keras_tensor__":
                names.append(o["config"]["keras_history"][0])
            else:
                for v in o.values():
                    walk(v)
        elif isinstance(o, (list, tuple)):
            if (len(o) >= 3 and isinstance(o[0], str)
                    and isinstance(o[1], int)):
                names.append(o[0])  # legacy ["name", node_idx, tensor_idx, {}]
            else:
                for v in o:
                    walk(v)

    walk(layer.get("inbound_nodes") or [])
    return names


def _out_names(spec) -> List[str]:
    """Normalize input_layers/output_layers: 'n' | ['n',0,0] | [['n',0,0],…]."""
    if isinstance(spec, str):
        return [spec]
    if (isinstance(spec, (list, tuple)) and spec
            and isinstance(spec[0], str)):
        return [spec[0]]
    return [s[0] if isinstance(s, (list, tuple)) else s for s in (spec or [])]


def import_keras_functional_config(config, weights_map,
                                   validate: bool = True):
    """Functional model_config + weights → ComputationGraph."""
    from deeplearning4j_tpu.nn import graph as G

    gcfg = config["config"]
    gb = G.graph_builder()
    params_by_name: Dict[str, Dict[str, Any]] = {}
    input_types: Dict[str, Any] = {}

    for entry in gcfg["layers"]:
        cls = entry["class_name"]
        cfg = entry.get("config", {})
        name = cfg.get("name", entry.get("name", ""))
        inputs = _inbound_names(entry)
        if cls == "InputLayer":
            shape = cfg.get("batch_shape") or cfg.get("batch_input_shape")
            gb.add_inputs(name)
            input_types[name] = _infer_input_type_from_shape(shape)
            continue
        if cls in _MERGE_LAYERS:
            kind, op = _MERGE_LAYERS[cls]
            if kind == "merge":
                gb.add_vertex(name, G.MergeVertex(), *inputs)
            else:
                gb.add_vertex(name, G.ElementWiseVertex(op=op), *inputs)
            continue
        if cls == "Dot":
            axes = cfg.get("axes", -1)
            if isinstance(axes, (list, tuple)):
                if len(set(axes)) != 1:
                    raise NotImplementedError(
                        "Dot merge with differing per-input axes import")
                axes = axes[0]
            gb.add_vertex(name, G.DotProductVertex(
                axes=int(axes), normalize=bool(cfg.get("normalize", False))),
                *inputs)
            continue
        if cls == "Flatten":
            # our conv activations are NHWC like keras's — a batch-preserving
            # flatten keeps keras Dense weight order (no CHW reorder needed)
            gb.add_vertex(name, G.FlattenVertex(), *inputs)
            continue
        mapper = KerasLayerMapper.MAPPERS.get(cls)
        if mapper is None:
            raise NotImplementedError(
                f"Keras layer '{cls}' has no import mapper (functional)")
        out = mapper(cfg, weights_map.get(name, []))
        if isinstance(out, list):
            if len(out) != 1:
                raise NotImplementedError(
                    f"Keras layer '{cls}' ({name}) expands to {len(out)} "
                    f"layers (StackedRNNCells) — supported in Sequential "
                    f"models only; restructure the functional graph with "
                    f"explicit RNN layers")
            out = out[0]
        lc, p = out
        state = {}
        if isinstance(p, dict) and "__params__" in p:
            state = p["__state__"]
            p = p["__params__"]
        gb.add_layer(name, lc, *inputs)
        params_by_name[name] = {"params": p, "state": state}

    for out in _out_names(gcfg.get("output_layers")):
        gb.set_outputs(out)
    gb.set_input_types(**input_types)
    net = G.ComputationGraph(gb.build()).init()

    import jax.numpy as jnp

    for name, blob in params_by_name.items():
        for k, w in blob["params"].items():
            net.params[name][k] = (
                {kk: jnp.asarray(vv) for kk, vv in w.items()}
                if isinstance(w, dict) else jnp.asarray(w))
        for k, v in blob["state"].items():
            net.net_state[name][k] = jnp.asarray(v)
    # graftcheck (docs/ANALYSIS.md): verify the imported DAG statically,
    # matching the ONNX/TF importers' contract (validate=False opts out)
    if validate:
        from deeplearning4j_tpu.analysis import check_network

        net.last_check_report = check_network(
            net, graph_name="keras:functional")
        net.last_check_report.raise_on_errors()
    return net


def import_keras_model_and_weights(path: str, validate: bool = True):
    """KerasModelImport.importKerasModelAndWeights analog: reads legacy .h5
    OR the Keras-3 .keras zip with own parsing (h5py + zipfile — no
    tf.keras deserialization), dispatches Sequential → MultiLayerNetwork /
    Functional → ComputationGraph."""
    import zipfile

    if zipfile.is_zipfile(path):
        config, weights = read_keras_v3(path)
    else:
        config, weights = read_keras_h5(path)
    if config.get("class_name") == "Sequential":
        return import_keras_sequential_config(config, weights,
                                              validate=validate)
    return import_keras_functional_config(config, weights,
                                          validate=validate)


# layer classes that legitimately save no weight group in a .keras zip
_WEIGHTLESS_KERAS_LAYERS = {
    "InputLayer", "Dropout", "SpatialDropout1D", "SpatialDropout2D",
    "SpatialDropout3D", "Flatten", "Reshape", "Permute", "RepeatVector",
    "Activation", "ActivityRegularization", "Masking", "Lambda",
    "Add", "Subtract", "Multiply", "Average", "Maximum", "Minimum",
    "Concatenate", "Dot", "MaxPooling1D", "MaxPooling2D", "MaxPooling3D",
    "AveragePooling1D", "AveragePooling2D", "AveragePooling3D",
    "GlobalMaxPooling1D", "GlobalMaxPooling2D", "GlobalMaxPooling3D",
    "GlobalAveragePooling1D", "GlobalAveragePooling2D",
    "GlobalAveragePooling3D", "UpSampling1D", "UpSampling2D", "UpSampling3D",
    "ZeroPadding1D", "ZeroPadding2D", "ZeroPadding3D", "Cropping1D",
    "Cropping2D", "Cropping3D", "Resizing", "CenterCrop", "Rescaling",
    "GaussianNoise", "GaussianDropout", "AlphaDropout",
    "LeakyReLU", "ELU", "ThresholdedReLU", "ReLU", "Softmax",
}


def _keras_snake_case(name: str) -> str:
    """Keras's to_snake_case: the rule behind .keras weight-group names."""
    import re

    name = re.sub(r"\W+", "", name)
    name = re.sub(r"(.)([A-Z][a-z]+)", r"\1_\2", name)
    return re.sub(r"([a-z])([A-Z])", r"\1_\2", name).lower()


def read_keras_v3(path: str):
    """Parse a Keras-3 ``.keras`` zip (config.json + model.weights.h5)
    WITHOUT tf.keras. Weight groups are keyed by snake_case(class_name)
    with a per-class counter in MODEL order (NOT layer.name — verified
    empirically against in-env keras saves), so the mapping is re-derived
    from the config's layer sequence. Returns (model_config, weights_map
    keyed by the config layer NAMES — what the assembly paths expect)."""
    import io
    import zipfile

    import h5py

    with zipfile.ZipFile(path) as z:
        config = json.loads(z.read("config.json"))
        with z.open("model.weights.h5") as f:
            h5buf = io.BytesIO(f.read())

    weights_map: Dict[str, List[np.ndarray]] = {}
    with h5py.File(h5buf, "r") as h:
        layers_grp = h.get("layers")
        counters: Dict[str, int] = {}
        for entry in config.get("config", {}).get("layers", []):
            cls = entry.get("class_name", "")
            name = entry.get("config", {}).get("name", cls)
            snake = _keras_snake_case(cls)
            idx = counters.get(snake, 0)
            counters[snake] = idx + 1
            gname = snake if idx == 0 else f"{snake}_{idx}"
            if layers_grp is None or gname not in layers_grp:
                # a weightless layer (Dropout/Flatten/…) legitimately has no
                # group; for anything else a naming divergence from keras's
                # saving_lib would silently leave the layer on random init —
                # warn loudly (ADVICE r4 #4)
                if cls not in _WEIGHTLESS_KERAS_LAYERS:
                    warnings.warn(
                        f"keras-3 import: no weight group '{gname}' in "
                        f"model.weights.h5 for layer '{name}' ({cls}); the "
                        f"layer will use random initialization", stacklevel=2)
                continue
            grp = layers_grp[gname]
            ws: List[np.ndarray] = []

            def collect(g):
                # direct vars first, then sublayers in get_weights() order:
                # RNNs store under cell/vars; Bidirectional under
                # forward_layer then backward_layer
                vg = g.get("vars")
                if vg is not None:
                    for k in sorted(vg, key=lambda s: int(s)):
                        ws.append(np.asarray(vg[k]))
                priority = ["cell", "forward_layer", "backward_layer"]
                subs = [s for s in priority if s in g] + sorted(
                    s for s in g
                    if s not in priority and s != "vars"
                    and isinstance(g[s], type(g)))
                for s in subs:
                    collect(g[s])

            collect(grp)
            weights_map[name] = ws
    return config, weights_map


@KerasLayerMapper.register("Conv1D")
def _conv1d(cfg, weights):
    w = weights[0]  # (k, C_in, C_out) — matches our layout
    k = cfg["kernel_size"]
    k = int(k[0] if isinstance(k, (list, tuple)) else k)
    st = cfg.get("strides", 1)
    st = int(st[0] if isinstance(st, (list, tuple)) else st)
    if cfg.get("padding") == "causal":
        raise NotImplementedError("causal Conv1D import")
    lc = C.Convolution1D(
        n_in=w.shape[1], n_out=w.shape[2], kernel=k, stride=st,
        convolution_mode=cfg.get("padding", "valid"),
        dilation=int(np.atleast_1d(cfg.get("dilation_rate", 1))[0]),
        activation=_act(cfg))
    p = {"W": w}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


def _triple(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v, v)


@KerasLayerMapper.register("Conv3D")
def _conv3d(cfg, weights):
    w = weights[0]  # (kd, kh, kw, C_in, C_out) — matches our layout
    lc = C.Convolution3D(
        n_in=w.shape[3], n_out=w.shape[4],
        kernel=tuple(int(x) for x in cfg["kernel_size"]),
        stride=tuple(int(x) for x in _triple(cfg.get("strides", (1, 1, 1)))),
        convolution_mode=cfg.get("padding", "valid"),
        activation=_act(cfg))
    p = {"W": w}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("MaxPooling3D")
def _maxpool3d(cfg, weights):
    return C.Subsampling3DLayer(
        kernel=tuple(int(x) for x in _triple(cfg.get("pool_size", 2))),
        stride=tuple(int(x) for x in _triple(cfg.get("strides")
                                             or cfg.get("pool_size", 2))),
        pooling_type="max"), {}


@KerasLayerMapper.register("AveragePooling3D")
def _avgpool3d(cfg, weights):
    return C.Subsampling3DLayer(
        kernel=tuple(int(x) for x in _triple(cfg.get("pool_size", 2))),
        stride=tuple(int(x) for x in _triple(cfg.get("strides")
                                             or cfg.get("pool_size", 2))),
        pooling_type="avg"), {}


@KerasLayerMapper.register("PReLU")
def _prelu_keras(cfg, weights):
    alpha = weights[0]
    if alpha.ndim > 1:
        if not np.allclose(alpha, alpha.reshape(-1, alpha.shape[-1])[0]):
            raise NotImplementedError(
                "PReLU with non-broadcast (per-position) alpha import")
        alpha = alpha.reshape(-1, alpha.shape[-1])[0]
    lc = C.PReLULayer(n_in=alpha.shape[-1])
    return lc, {"alpha": alpha}


@KerasLayerMapper.register("GlobalAveragePooling1D")
def _gap1d(cfg, weights):
    return C.GlobalPoolingLayer(pooling_type="avg"), {}


@KerasLayerMapper.register("GlobalMaxPooling1D")
def _gmp1d(cfg, weights):
    return C.GlobalPoolingLayer(pooling_type="max"), {}


# ---------------------------------------------------------------------------
# Mapper table, round 3 continued: padding/cropping/upsampling, 1-D pooling,
# Conv3DTranspose, RepeatVector, Masking, TimeDistributed, noise dropouts.
# ---------------------------------------------------------------------------


@KerasLayerMapper.register("ZeroPadding1D")
def _zeropad1d(cfg, weights):
    return C.ZeroPadding1DLayer(padding=_pair(cfg.get("padding", 1))), {}


@KerasLayerMapper.register("ZeroPadding2D")
def _zeropad2d(cfg, weights):
    p = cfg.get("padding", 1)
    if isinstance(p, (list, tuple)):
        (t, b), (l, r) = (_pair(p[0]), _pair(p[1]))
    else:
        t = b = l = r = int(p)
    return C.ZeroPaddingLayer(padding=(t, b, l, r)), {}


@KerasLayerMapper.register("ZeroPadding3D")
def _zeropad3d(cfg, weights):
    p = cfg.get("padding", 1)
    if isinstance(p, (list, tuple)):
        (a, b), (c, d), (e, f) = (_pair(p[0]), _pair(p[1]), _pair(p[2]))
    else:
        a = b = c = d = e = f = int(p)
    return C.ZeroPadding3DLayer(padding=(a, b, c, d, e, f)), {}


@KerasLayerMapper.register("Cropping1D")
def _crop1d(cfg, weights):
    return C.Cropping1D(cropping=_pair(cfg.get("cropping", 1))), {}


@KerasLayerMapper.register("Cropping2D")
def _crop2d(cfg, weights):
    p = cfg.get("cropping", 1)
    if isinstance(p, (list, tuple)):
        (t, b), (l, r) = (_pair(p[0]), _pair(p[1]))
    else:
        t = b = l = r = int(p)
    return C.Cropping2D(cropping=(t, b, l, r)), {}


@KerasLayerMapper.register("Cropping3D")
def _crop3d(cfg, weights):
    p = cfg.get("cropping", 1)
    if isinstance(p, (list, tuple)):
        (a, b), (c, d), (e, f) = (_pair(p[0]), _pair(p[1]), _pair(p[2]))
    else:
        a = b = c = d = e = f = int(p)
    return C.Cropping3D(cropping=(a, b, c, d, e, f)), {}


@KerasLayerMapper.register("UpSampling1D")
def _upsampling1d(cfg, weights):
    return C.Upsampling1D(size=int(cfg.get("size", 2))), {}


@KerasLayerMapper.register("UpSampling3D")
def _upsampling3d(cfg, weights):
    return C.Upsampling3D(size=_triple(cfg.get("size", 2))), {}


@KerasLayerMapper.register("MaxPooling1D")
def _maxpool1d(cfg, weights):
    ps = cfg.get("pool_size", 2)
    ps = int(ps[0] if isinstance(ps, (list, tuple)) else ps)
    st = cfg.get("strides") or ps
    st = int(st[0] if isinstance(st, (list, tuple)) else st)
    return C.Subsampling1DLayer(
        kernel=ps, stride=st, pooling_type="max",
        convolution_mode=cfg.get("padding", "valid")), {}


@KerasLayerMapper.register("AveragePooling1D")
def _avgpool1d(cfg, weights):
    ps = cfg.get("pool_size", 2)
    ps = int(ps[0] if isinstance(ps, (list, tuple)) else ps)
    st = cfg.get("strides") or ps
    st = int(st[0] if isinstance(st, (list, tuple)) else st)
    return C.Subsampling1DLayer(
        kernel=ps, stride=st, pooling_type="avg",
        convolution_mode=cfg.get("padding", "valid")), {}


@KerasLayerMapper.register("GlobalAveragePooling3D")
def _gap3d(cfg, weights):
    return C.GlobalPoolingLayer(pooling_type="avg"), {}


@KerasLayerMapper.register("GlobalMaxPooling3D")
def _gmp3d(cfg, weights):
    return C.GlobalPoolingLayer(pooling_type="max"), {}


@KerasLayerMapper.register("Conv3DTranspose")
def _deconv3d(cfg, weights):
    w = weights[0]  # keras: (kd, kh, kw, out, in) → ours: (kd, kh, kw, in, out)
    lc = C.Deconvolution3D(
        n_in=w.shape[4], n_out=w.shape[3],
        kernel=tuple(int(x) for x in cfg["kernel_size"]),
        stride=tuple(int(x) for x in _triple(cfg.get("strides", (1, 1, 1)))),
        convolution_mode=cfg.get("padding", "valid"),
        activation=_act(cfg))
    p = {"W": w.transpose(0, 1, 2, 4, 3)}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("RepeatVector")
def _repeat_vector(cfg, weights):
    return C.RepeatVector(n=int(cfg["n"])), {}


@KerasLayerMapper.register("Masking")
def _masking(cfg, weights):
    # keras Masking emits a downstream mask for steps != mask_value; our
    # MaskZeroLayer derives the same mask — wrap an identity layer so the
    # mask propagates through the sequential stack
    return C.MaskZeroLayer(
        underlying=C.ActivationLayer(activation="identity"),
        mask_value=float(cfg.get("mask_value", 0.0))), {"inner": {}}


@KerasLayerMapper.register("TimeDistributed")
def _time_distributed(cfg, weights):
    inner = cfg["layer"]
    if inner["class_name"] != "Dense":
        raise NotImplementedError(
            f"TimeDistributed({inner['class_name']}) import — only Dense is "
            "time-broadcastable in a sequential stack")
    # our DenseLayer broadcasts over (N, T, F) natively
    return KerasLayerMapper.MAPPERS["Dense"](inner["config"], weights)


@KerasLayerMapper.register("SpatialDropout1D")
@KerasLayerMapper.register("SpatialDropout3D")
def _spatial_dropout_1d3d(cfg, weights):
    # mask broadcasts over every non-batch, non-channel dim, so one
    # spatial mode covers 1D/2D/3D (KerasSpatialDropout analog)
    return C.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                          mode="spatial", name=cfg.get("name")), {}


@KerasLayerMapper.register("AlphaDropout")
def _alpha_dropout(cfg, weights):
    return C.DropoutLayer(rate=float(cfg.get("rate", 0.5)),
                          mode="alpha", name=cfg.get("name")), {}


@KerasLayerMapper.register("GaussianNoise")
def _gaussian_noise(cfg, weights):
    # train-time-only additive noise: identity at inference (import targets
    # inference parity; DL4J maps this to its GaussianNoise IDropout the
    # same way)
    return C.ActivationLayer(activation="identity"), {}


def register_custom_layer(name: str):
    """KerasLayer.registerCustomLayer analog — decorate a mapper
    ``fn(cfg, weights) -> (LayerConf, params)`` for a custom Keras layer
    class name so import resolves it like a built-in:

        @register_custom_layer("MyAttention")
        def _my_attention(cfg, weights):
            return nn.SelfAttentionLayer(...), {"Wq": weights[0], ...}
    """
    return KerasLayerMapper.register(name)


@KerasLayerMapper.register("GRU")
def _gru(cfg, weights):
    """Keras GRU (reset_after=True, the TF2 default) → nn.GRU. Keras gate
    order is [z, r, h]; ours (the gru_cell op / PyTorch convention) is
    [r, z, n] — columns reorder, and the (2, 3H) bias splits into the
    input/recurrent halves."""
    if not cfg.get("reset_after", True):
        raise NotImplementedError(
            "GRU import with reset_after=False (legacy CuDNN-incompatible "
            "variant) — re-export with reset_after=True")
    if cfg.get("go_backwards", False):
        raise NotImplementedError("GRU import with go_backwards=True")
    if _act(cfg) != "tanh" or _ACT_MAP.get(
            cfg.get("recurrent_activation", "sigmoid"),
            cfg.get("recurrent_activation")) != "sigmoid":
        raise NotImplementedError(
            "GRU import requires tanh/sigmoid activations (gru_cell ABI)")
    units = cfg["units"]
    kernel, recurrent = weights[0], weights[1]
    if cfg.get("use_bias", True) and len(weights) > 2:
        b = np.asarray(weights[2])  # reset_after=True ⇒ always (2, 3H)
        b_in, b_rec = b[0], b[1]
    else:
        b_in = np.zeros(3 * units, np.float32)
        b_rec = np.zeros(3 * units, np.float32)

    def regate(w):
        z, r, h = np.split(w, 3, axis=-1)
        return np.concatenate([r, z, h], axis=-1)

    lc = nn.GRU(n_in=kernel.shape[0], n_out=units, name=cfg.get("name"))
    p = {"W": regate(kernel), "RW": regate(recurrent),
         "b": regate(b_in), "rb": regate(b_rec)}
    if not cfg.get("return_sequences", False):
        # keras default emits the LAST step only → wrap in LastTimeStep
        return C.LastTimeStep(fwd=lc.to_dict(), name=cfg.get("name")), \
            {"inner": p}
    return lc, p


# ---------------------------------------------------------------------------
# Widened mapper table (round 4): normalization, shape ops, ConvLSTM2D,
# locally-connected, attention, preprocessing layers — toward the
# reference's ~100 KerasLayer mappers (SURVEY §3.3).
# ---------------------------------------------------------------------------


@KerasLayerMapper.register("LayerNormalization")
def _layer_norm(cfg, weights):
    axis = cfg.get("axis", -1)
    if isinstance(axis, (list, tuple)):
        if len(axis) != 1:
            raise NotImplementedError("LayerNormalization over multiple axes")
        axis = axis[0]
    if axis not in (-1,):
        raise NotImplementedError("LayerNormalization import requires the "
                                  "trailing axis (keras default)")
    lc = C.LayerNormalization(eps=float(cfg.get("epsilon", 1e-3)),
                              activation="identity", name=cfg.get("name"))
    p = {}
    idx = 0
    if cfg.get("scale", True):
        p["gain"] = weights[idx]; idx += 1
    if cfg.get("center", True):
        p["b"] = weights[idx]
    return lc, p


@KerasLayerMapper.register("GroupNormalization")
def _group_norm(cfg, weights):
    if cfg.get("axis", -1) not in (-1,):
        raise NotImplementedError("GroupNormalization import requires the "
                                  "trailing (channels_last) axis")
    lc = C.GroupNormalization(groups=int(cfg.get("groups", 32)),
                              eps=float(cfg.get("epsilon", 1e-3)),
                              activation="identity", name=cfg.get("name"))
    p = {}
    idx = 0
    if cfg.get("scale", True):
        p["gamma"] = weights[idx]; idx += 1
    if cfg.get("center", True):
        p["beta"] = weights[idx]
    return lc, p


@KerasLayerMapper.register("Permute")
def _permute(cfg, weights):
    return C.PermuteLayer(dims=tuple(cfg["dims"]), name=cfg.get("name")), {}


@KerasLayerMapper.register("Reshape")
def _reshape_layer(cfg, weights):
    return C.ReshapeLayer(target_shape=tuple(cfg["target_shape"]),
                          name=cfg.get("name")), {}


@KerasLayerMapper.register("UnitNormalization")
def _unit_norm(cfg, weights):
    return C.UnitNormLayer(name=cfg.get("name")), {}


@KerasLayerMapper.register("Rescaling")
def _rescaling(cfg, weights):
    return C.RescaleLayer(scale=cfg.get("scale", 1.0),
                          offset=cfg.get("offset", 0.0),
                          name=cfg.get("name")), {}


@KerasLayerMapper.register("Normalization")
def _normalization(cfg, weights):
    # adapted Normalization stores mean/variance as weights [mean, var(, count)]
    if len(weights) >= 2:
        mean, var = np.asarray(weights[0]), np.asarray(weights[1])
    else:
        mean = np.asarray(cfg.get("mean", 0.0))
        var = np.asarray(cfg.get("variance", 1.0))
    inv = 1.0 / np.sqrt(var + 1e-12)
    return C.RescaleLayer(scale=inv.tolist(), offset=(-mean * inv).tolist(),
                          name=cfg.get("name")), {}


@KerasLayerMapper.register("ThresholdedReLU")
def _thresholded_relu(cfg, weights):
    if float(cfg.get("theta", 1.0)) != 1.0:
        raise NotImplementedError("ThresholdedReLU import with theta != 1.0")
    return C.ActivationLayer(activation="thresholdedrelu",
                             name=cfg.get("name")), {}


@KerasLayerMapper.register("ActivityRegularization")
def _activity_reg(cfg, weights):
    import warnings

    warnings.warn("ActivityRegularization imports as identity: activation "
                  "penalties do not transfer (inference parity only)",
                  stacklevel=2)
    return C.ActivationLayer(activation="identity", name=cfg.get("name")), {}


@KerasLayerMapper.register("Identity")
def _identity_layer(cfg, weights):
    return C.ActivationLayer(activation="identity", name=cfg.get("name")), {}


# train-time data-augmentation layers: identity at inference by definition
for _aug in ("RandomFlip", "RandomRotation", "RandomZoom",
             "RandomTranslation", "RandomContrast", "RandomBrightness"):
    def _aug_mapper(cfg, weights, _cls=_aug):
        import warnings

        warnings.warn(f"{_cls} imports as identity (augmentation is "
                      "train-time only; re-augment in your input pipeline)",
                      stacklevel=2)
        return C.ActivationLayer(activation="identity",
                                 name=cfg.get("name")), {}

    KerasLayerMapper.register(_aug)(_aug_mapper)


@KerasLayerMapper.register("LocallyConnected1D")
def _locally_connected_1d(cfg, weights):
    lc = C.LocallyConnected1D(
        n_out=int(cfg["filters"]),
        kernel=int(cfg["kernel_size"][0] if isinstance(cfg["kernel_size"],
                                                       (list, tuple))
                   else cfg["kernel_size"]),
        stride=int(cfg.get("strides", [1])[0] if isinstance(
            cfg.get("strides", 1), (list, tuple)) else cfg.get("strides", 1)),
        activation=_act(cfg), name=cfg.get("name"))
    p = {"W": weights[0]}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("LocallyConnected2D")
def _locally_connected_2d(cfg, weights):
    if cfg.get("padding", "valid") != "valid":
        raise NotImplementedError("LocallyConnected2D 'same' padding import")
    kh, kw = _pair(cfg["kernel_size"])
    lc = C.LocallyConnected2D(
        n_out=int(cfg["filters"]), kernel=(kh, kw),
        stride=_pair(cfg.get("strides", 1)), activation=_act(cfg),
        name=cfg.get("name"))
    w = np.asarray(weights[0])  # (oh*ow, kh*kw*cin, filters), (kh,kw,C) order
    pos, feat, fo = w.shape
    cin = feat // (kh * kw)
    # our impl consumes conv_general_dilated_patches features in (C, kh, kw)
    # order — permute the keras (kh, kw, C) flatten accordingly
    w = w.reshape(pos, kh, kw, cin, fo).transpose(0, 3, 1, 2, 4)
    p = {"W": w.reshape(pos, feat, fo)}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("ConvLSTM2D")
def _conv_lstm_2d(cfg, weights):
    if cfg.get("go_backwards", False):
        raise NotImplementedError("ConvLSTM2D with go_backwards=True")
    if _pair(cfg.get("dilation_rate", 1)) != (1, 1):
        raise NotImplementedError("ConvLSTM2D import with dilation_rate != 1")
    strides = cfg.get("strides", (1, 1))
    if _pair(strides) != (1, 1):
        raise NotImplementedError("ConvLSTM2D import with strides != 1")
    lc = C.ConvLSTM2D(
        filters=int(cfg["filters"]), kernel=_pair(cfg["kernel_size"]),
        padding="same" if cfg.get("padding", "valid") == "same" else "truncate",
        return_sequences=bool(cfg.get("return_sequences", False)),
        activation=_ACT_MAP.get(cfg.get("activation", "tanh"), "tanh"),
        gate_activation=_ACT_MAP.get(cfg.get("recurrent_activation",
                                             "hard_sigmoid"), "hardsigmoid"),
        name=cfg.get("name"))

    def regate(w):
        i, f, c, o = np.split(w, 4, axis=-1)  # keras i,f,c,o -> ours i,f,o,g
        return np.concatenate([i, f, o, c], axis=-1)

    p = {"W": regate(weights[0]), "RW": regate(weights[1])}
    if cfg.get("use_bias", True) and len(weights) > 2:
        p["b"] = regate(weights[2])
    return lc, p


@KerasLayerMapper.register("SeparableConv1D")
def _separable_conv1d(cfg, weights):
    dil = cfg.get("dilation_rate", 1)
    if int(dil[0] if isinstance(dil, (list, tuple)) else dil) != 1:
        raise NotImplementedError("SeparableConv1D import with dilation_rate != 1")
    k = cfg["kernel_size"]
    k = int(k[0] if isinstance(k, (list, tuple)) else k)
    s = cfg.get("strides", 1)
    s = int(s[0] if isinstance(s, (list, tuple)) else s)
    lc = C.SeparableConvolution1D(
        n_out=int(cfg["filters"]), kernel=k, stride=s,
        convolution_mode="same" if cfg.get("padding", "valid") == "same"
        else "truncate",
        depth_multiplier=int(cfg.get("depth_multiplier", 1)),
        activation=_act(cfg), has_bias=cfg.get("use_bias", True),
        name=cfg.get("name"))
    dw = np.asarray(weights[0])  # keras (k, cin, mult)
    kk, cin, mult = dw.shape
    p = {"dW": dw.reshape(kk, 1, cin * mult),
         "pW": np.asarray(weights[1])}  # (1, cin*mult, cout)
    if cfg.get("use_bias", True) and len(weights) > 2:
        p["b"] = weights[2]
    return lc, p


_KERAS_LAMBDAS: Dict[str, Any] = {}


def register_lambda(name: str, layer_conf_factory):
    """KerasLambda parity: the reference requires user-registered lambda
    implementations (KerasLayer.registerLambdaLayer). Register a factory
    ``fn(cfg, weights) -> (LayerConf, params)`` under the Lambda layer's
    NAME."""
    _KERAS_LAMBDAS[name] = layer_conf_factory
    return layer_conf_factory


@KerasLayerMapper.register("Lambda")
def _lambda_layer(cfg, weights):
    name = cfg.get("name")
    factory = _KERAS_LAMBDAS.get(name)
    if factory is None:
        raise NotImplementedError(
            f"Keras Lambda layer '{name}' needs a registered implementation "
            f"— call keras_import.register_lambda('{name}', factory) first "
            f"(the reference's registerLambdaLayer contract)")
    return factory(cfg, weights)


@KerasLayerMapper.register("MultiHeadAttention")
def _multi_head_attention(cfg, weights):
    """Keras MHA → AttentionVertex (multi-input graph layer; functional
    models wire (query, value[, key]) — keras_order handles the swap).
    Keras kernels (d, H, hd) / (H, hd, d_out) flatten to our 2-D Wq..Wo."""
    heads = int(cfg["num_heads"])
    key_dim = int(cfg["key_dim"])
    value_dim = cfg.get("value_dim")
    if value_dim is not None and int(value_dim) != key_dim:
        raise NotImplementedError(
            "MultiHeadAttention import with value_dim != key_dim")
    d = heads * key_dim
    use_bias = bool(cfg.get("use_bias", True))
    ws = [np.asarray(w) for w in weights]
    if use_bias:
        wq, bq, wk, bk, wv, bv, wo, bo = ws[:8]
    else:
        wq, wk, wv, wo = ws[:4]
        bq = bk = bv = bo = None
    lc = C.AttentionVertex(n_out=d, n_heads=heads, keras_order=True,
                           has_bias=use_bias, d_out=wo.shape[-1],
                           name=cfg.get("name"))
    p = {"Wq": wq.reshape(wq.shape[0], d), "Wk": wk.reshape(wk.shape[0], d),
         "Wv": wv.reshape(wv.shape[0], d), "Wo": wo.reshape(d, wo.shape[-1])}
    if use_bias:
        p.update({"bq": bq.reshape(d), "bk": bk.reshape(d),
                  "bv": bv.reshape(d), "bo": bo.reshape(-1)})
    return lc, p


@KerasLayerMapper.register("Attention")
def _attention_layer(cfg, weights):
    scale = np.asarray(weights[0]) if (cfg.get("use_scale") and weights) \
        else None
    if cfg.get("score_mode", "dot") != "dot":
        raise NotImplementedError("Keras Attention score_mode != 'dot'")
    return C.DotAttentionLayer(use_scale=bool(cfg.get("use_scale", False)),
                               additive=False,
                               scale=None if scale is None else scale.tolist(),
                               name=cfg.get("name")), {}


@KerasLayerMapper.register("AdditiveAttention")
def _additive_attention_layer(cfg, weights):
    scale = np.asarray(weights[0]).tolist() if (cfg.get("use_scale", True)
                                                and weights) else None
    return C.DotAttentionLayer(use_scale=bool(cfg.get("use_scale", True)),
                               additive=True, scale=scale,
                               name=cfg.get("name")), {}


@KerasLayerMapper.register("Conv1DTranspose")
def _conv1d_transpose(cfg, weights):
    dil = cfg.get("dilation_rate", 1)
    if int(dil[0] if isinstance(dil, (list, tuple)) else dil) != 1:
        raise NotImplementedError("Conv1DTranspose import with dilation_rate != 1")
    op = cfg.get("output_padding")
    if op not in (None, [None]) and any(v for v in (op if isinstance(op, (list, tuple)) else [op])):
        raise NotImplementedError("Conv1DTranspose import with output_padding")
    k = cfg["kernel_size"]
    k = int(k[0] if isinstance(k, (list, tuple)) else k)
    s = cfg.get("strides", 1)
    s = int(s[0] if isinstance(s, (list, tuple)) else s)
    w = np.asarray(weights[0])  # keras: (k, out, in)
    lc = C.Deconvolution1D(
        n_in=w.shape[2], n_out=w.shape[1], kernel=k, stride=s,
        convolution_mode="same" if cfg.get("padding", "valid") == "same"
        else "truncate",
        activation=_act(cfg), has_bias=cfg.get("use_bias", True),
        name=cfg.get("name"))
    p = {"W": w.transpose(0, 2, 1)}  # (k, in, out)
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("Resizing")
def _resizing(cfg, weights):
    method = cfg.get("interpolation", "bilinear")
    if method not in ("bilinear", "nearest", "bicubic"):
        raise NotImplementedError(f"Resizing interpolation={method} import")
    if cfg.get("crop_to_aspect_ratio") or cfg.get("pad_to_aspect_ratio"):
        raise NotImplementedError("Resizing with aspect-ratio fitting import")
    return C.ResizeLayer(height=int(cfg["height"]), width=int(cfg["width"]),
                         method=method, name=cfg.get("name")), {}


@KerasLayerMapper.register("CenterCrop")
def _center_crop(cfg, weights):
    return C.CenterCropLayer(height=int(cfg["height"]),
                             width=int(cfg["width"]),
                             name=cfg.get("name")), {}


# ---------------------------------------------------------------------------
# Legacy recurrent forms (round 5, verdict item 9): CuDNNLSTM/CuDNNGRU (the
# tf.keras v1 CuDNN-backed layers common in older h5 files) and the generic
# RNN(cell=...) / StackedRNNCells wrappers. Reference: keras-import's
# KerasLstm/KerasSimpleRnn layer table (SURVEY §3.3).
# ---------------------------------------------------------------------------


@KerasLayerMapper.register("CuDNNLSTM")
def _cudnn_lstm(cfg, weights):
    """CuDNNLSTM ≡ LSTM(activation=tanh, recurrent_activation=sigmoid,
    unit_forget_bias) with a CuDNN weight layout: bias is the (8H,) stack of
    input+recurrent biases (or (2,4H)) — they sum into the standard (4H,)."""
    w = list(weights)
    if len(w) > 2:
        b = np.asarray(w[2])
        units = int(cfg.get("units", 0))
        if b.ndim == 2:                      # (2, 4H)
            b = b[0] + b[1]
        elif b.ndim == 1 and units and b.size == 8 * units:  # (8H,)
            # only an exact 8H stack is the CuDNN input+recurrent pair; a
            # fused (4H,) bias with even H is also divisible by 8 and must
            # pass through unchanged (round-5 advice)
            half = b.size // 2
            b = b[:half] + b[half:]
        w[2] = b
    cfg = dict(cfg)
    cfg.setdefault("activation", "tanh")
    cfg.setdefault("recurrent_activation", "sigmoid")
    return KerasLayerMapper.MAPPERS["LSTM"](cfg, w)


@KerasLayerMapper.register("CuDNNGRU")
def _cudnn_gru(cfg, weights):
    """CuDNNGRU ≡ GRU(reset_after=True, tanh/sigmoid). Bias arrives as
    (6H,) or (2, 3H); the GRU mapper wants the (2, 3H) split form."""
    w = list(weights)
    if len(w) > 2:
        b = np.asarray(w[2])
        if b.ndim == 1:
            b = b.reshape(2, -1)
        w[2] = b
    cfg = dict(cfg)
    cfg.setdefault("activation", "tanh")
    cfg.setdefault("recurrent_activation", "sigmoid")
    cfg["reset_after"] = True
    return KerasLayerMapper.MAPPERS["GRU"](cfg, w)


_RNN_CELL_TO_LAYER = {"LSTMCell": "LSTM", "GRUCell": "GRU",
                      "SimpleRNNCell": "SimpleRNN"}


def _cell_spec(cell):
    cls = cell.get("class_name")
    layer = _RNN_CELL_TO_LAYER.get(cls)
    if layer is None:
        raise NotImplementedError(
            f"RNN(cell={cls}) import: no mapper for this cell type")
    return layer, dict(cell.get("config", {}))


@KerasLayerMapper.register("RNN")
def _rnn_wrapper(cfg, weights):
    """keras.layers.RNN(cell=...) — delegate to the cell's layer mapper
    with the wrapper's sequence semantics (return_sequences/go_backwards).
    StackedRNNCells expands to one layer per cell (weights are concatenated
    in cell order, 3 arrays per cell when biased)."""
    cell = cfg.get("cell") or {}
    if cell.get("class_name") == "StackedRNNCells":
        cells = cell.get("config", {}).get("cells", [])
        out = []
        off = 0
        for ci, c in enumerate(cells):
            layer, ccfg = _cell_spec(c)
            n_w = 3 if ccfg.get("use_bias", True) else 2
            ccfg["name"] = f"{cfg.get('name', 'rnn')}_cell{ci}"
            # every stacked cell but the LAST returns the full sequence
            ccfg["return_sequences"] = (True if ci < len(cells) - 1
                                        else cfg.get("return_sequences", False))
            ccfg["go_backwards"] = cfg.get("go_backwards", False)
            out.append(KerasLayerMapper.MAPPERS[layer](
                ccfg, list(weights[off:off + n_w])))
            off += n_w
        return out  # list of (conf, params) — sequential assembly expands
    layer, ccfg = _cell_spec(cell)
    ccfg["name"] = cfg.get("name")
    ccfg["return_sequences"] = cfg.get("return_sequences", False)
    ccfg["go_backwards"] = cfg.get("go_backwards", False)
    return KerasLayerMapper.MAPPERS[layer](ccfg, weights)


@KerasLayerMapper.register("EinsumDense")
def _einsum_dense(cfg, weights):
    """keras.layers.EinsumDense → nn.EinsumDenseLayer (the keras-nlp
    transformer projection)."""
    out_shape = cfg.get("output_shape")
    out_shape = (tuple(out_shape) if isinstance(out_shape, (list, tuple))
                 else (out_shape,))
    # None entries are batch/sequence dims preserved by the equation —
    # only concrete (weight-bearing) dims size the kernel
    out_shape = tuple(s for s in out_shape if s is not None)
    bias_axes = cfg.get("bias_axes")
    lc = nn.EinsumDenseLayer(
        equation=cfg["equation"], out_shape=tuple(int(s) for s in out_shape),
        bias_shape=tuple(np.asarray(weights[1]).shape) if
        (bias_axes and len(weights) > 1) else (),
        activation=_act(cfg), name=cfg.get("name"))
    p = {"W": weights[0]}
    if bias_axes and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("RandomCrop")
def _random_crop(cfg, weights):
    # keras-3 inference semantics: RandomCrop is a PASSTHROUGH (it only
    # crops in training; keras 2 did an aspect-crop+resize — models that
    # relied on that must resize explicitly). Passthrough keeps parity
    # with the installed keras and fails shapes loudly downstream exactly
    # where keras itself would.
    return nn.ActivationLayer(activation="identity",
                              name=cfg.get("name")), {}


def _keras_reject(name, why):
    def mapper(cfg, weights):
        raise NotImplementedError(
            f"Keras layer '{name}': {why}. Apply this preprocessing outside "
            f"the imported graph (DataVec transforms cover the same role).")

    return mapper


for _nm, _why in [
        ("StringLookup", "string-tensor vocabularies are unsupported"),
        ("Hashing", "string hashing is unsupported"),
        ("TextVectorization", "string tokenization inside the graph is "
                              "unsupported (use nlp.wordpiece)")]:
    KerasLayerMapper.MAPPERS[_nm] = _keras_reject(_nm, _why)


@KerasLayerMapper.register("Discretization")
def _discretization(cfg, weights):
    bounds = cfg.get("bin_boundaries") or []
    if not bounds:
        raise NotImplementedError(
            "Discretization without explicit bin_boundaries (adapt()-ed "
            "state) — re-export with the learned boundaries in the config")
    if list(bounds) != sorted(float(b) for b in bounds):
        raise ValueError(
            f"Discretization: bin_boundaries must be ascending, got "
            f"{bounds} (searchsorted semantics require sorted bounds)")
    return nn.DiscretizationLayer(
        bin_boundaries=tuple(float(b) for b in bounds),
        name=cfg.get("name")), {}


@KerasLayerMapper.register("CategoryEncoding")
def _category_encoding(cfg, weights):
    mode = cfg.get("output_mode", "multi_hot")
    if mode not in ("one_hot", "multi_hot", "count"):
        raise NotImplementedError(f"CategoryEncoding output_mode={mode}")
    return nn.CategoryEncodingLayer(
        num_tokens=int(cfg["num_tokens"]), output_mode=mode,
        name=cfg.get("name")), {}
