"""Keras model import — deeplearning4j-modelimport parity.

Reference parity:
  * org/deeplearning4j/nn/modelimport/keras/KerasModelImport.java,
    KerasModel/KerasSequentialModel, layers/** (~100 per-layer mappers),
    utils/Hdf5Archive.java — parse Keras HDF5 (architecture JSON + weight
    groups) into a DL4J network.

Scope: Sequential models over the common layer set (Dense, Conv2D,
MaxPooling2D/AveragePooling2D, Flatten, Dropout, BatchNormalization,
Activation, Embedding, LSTM, GlobalAveragePooling2D) → MultiLayerNetwork.
Weights transpose from Keras layouts to ours (kernel HWIO already matches;
LSTM gate order i,f,c,o → our i,f,o,g reordering).

Supports both legacy HDF5 (.h5) files and in-memory keras model objects
(`import_keras_model`), so golden tests build models with in-env tf.keras.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import numpy as np

from deeplearning4j_tpu import nn
from deeplearning4j_tpu.nn import conf as C

_ACT_MAP = {
    "relu": "relu", "softmax": "softmax", "tanh": "tanh", "sigmoid": "sigmoid",
    "linear": "identity", "elu": "elu", "selu": "selu", "gelu": "gelu",
    "softplus": "softplus", "softsign": "softsign", "swish": "swish",
    "hard_sigmoid": "hardsigmoid", "leaky_relu": "leakyrelu",
}


def _act(cfg) -> str:
    a = cfg.get("activation", "linear")
    if isinstance(a, dict):
        a = a.get("class_name", "linear").lower()
    return _ACT_MAP.get(a, a)


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (v, v)


class KerasLayerMapper:
    """Registry of per-layer-class mappers (KerasLayer subclass table)."""

    MAPPERS: Dict[str, Any] = {}

    @classmethod
    def register(cls, name):
        def wrap(fn):
            cls.MAPPERS[name] = fn
            return fn

        return wrap


@KerasLayerMapper.register("Dense")
def _dense(cfg, weights):
    lc = nn.DenseLayer(n_out=cfg["units"], activation=_act(cfg),
                       has_bias=cfg.get("use_bias", True), name=cfg.get("name"))
    p = {"W": weights[0]}
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("Conv2D")
def _conv2d(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    lc = nn.ConvolutionLayer(
        n_out=cfg["filters"], kernel=_pair(cfg["kernel_size"]),
        stride=_pair(cfg.get("strides", 1)), convolution_mode=pad,
        dilation=_pair(cfg.get("dilation_rate", 1)), activation=_act(cfg),
        has_bias=cfg.get("use_bias", True), name=cfg.get("name"))
    p = {"W": weights[0]}  # keras kernel is HWIO — matches our layout
    if cfg.get("use_bias", True) and len(weights) > 1:
        p["b"] = weights[1]
    return lc, p


@KerasLayerMapper.register("MaxPooling2D")
def _maxpool(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    return nn.SubsamplingLayer(
        pooling_type="max", kernel=_pair(cfg.get("pool_size", 2)),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
        convolution_mode=pad, name=cfg.get("name")), {}


@KerasLayerMapper.register("AveragePooling2D")
def _avgpool(cfg, weights):
    pad = "same" if cfg.get("padding", "valid") == "same" else "truncate"
    return nn.SubsamplingLayer(
        pooling_type="avg", kernel=_pair(cfg.get("pool_size", 2)),
        stride=_pair(cfg.get("strides") or cfg.get("pool_size", 2)),
        convolution_mode=pad, name=cfg.get("name")), {}


@KerasLayerMapper.register("GlobalAveragePooling2D")
def _gap(cfg, weights):
    return nn.GlobalPoolingLayer(pooling_type="avg", name=cfg.get("name")), {}


@KerasLayerMapper.register("Flatten")
def _flatten(cfg, weights):
    return "FLATTEN", {}


@KerasLayerMapper.register("Dropout")
def _dropout(cfg, weights):
    return nn.DropoutLayer(rate=cfg.get("rate", 0.5), name=cfg.get("name")), {}


@KerasLayerMapper.register("Activation")
def _activation(cfg, weights):
    return nn.ActivationLayer(activation=_act(cfg), name=cfg.get("name")), {}


@KerasLayerMapper.register("BatchNormalization")
def _bn(cfg, weights):
    lc = nn.BatchNormalization(eps=cfg.get("epsilon", 1e-3),
                               decay=cfg.get("momentum", 0.99),
                               name=cfg.get("name"))
    # keras order: gamma, beta, moving_mean, moving_variance
    p = {"gamma": weights[0], "beta": weights[1]}
    state = {"mean": weights[2], "var": weights[3]}
    return lc, {"__params__": p, "__state__": state}


@KerasLayerMapper.register("Embedding")
def _embedding(cfg, weights):
    lc = nn.EmbeddingSequenceLayer(n_in=cfg["input_dim"], n_out=cfg["output_dim"],
                                   name=cfg.get("name"))
    return lc, {"W": weights[0]}


@KerasLayerMapper.register("LSTM")
def _lstm(cfg, weights):
    units = cfg["units"]
    lc = nn.LSTM(n_out=units, activation=_act(cfg),
                 gate_activation=_ACT_MAP.get(cfg.get("recurrent_activation",
                                                      "sigmoid"), "sigmoid"),
                 forget_gate_bias_init=0.0, name=cfg.get("name"))
    kernel, recurrent, bias = weights[0], weights[1], weights[2]

    def regate(w):
        # keras gate order [i, f, c, o] → ours [i, f, o, g(c)]
        i, f, c, o = np.split(w, 4, axis=-1)
        return np.concatenate([i, f, o, c], axis=-1)

    return lc, {"W": regate(kernel), "RW": regate(recurrent), "b": regate(bias)}


def import_keras_model(model, input_type: Optional[C.InputType] = None) -> nn.MultiLayerNetwork:
    """In-memory tf.keras Sequential → MultiLayerNetwork (the
    KerasModelImport.importKerasSequentialModelAndWeights role)."""
    layer_confs: List[C.LayerConf] = []
    params_list: List[Dict[str, Any]] = []
    states_list: List[Dict[str, Any]] = []
    input_shape = None
    for kl in model.layers:
        cfg = kl.get_config()
        cls = type(kl).__name__
        if cls == "InputLayer":
            continue
        mapper = KerasLayerMapper.MAPPERS.get(cls)
        if mapper is None:
            raise NotImplementedError(
                f"Keras layer '{cls}' has no import mapper; register one on "
                f"KerasLayerMapper")
        weights = [np.asarray(w) for w in kl.get_weights()]
        lc, p = mapper(cfg, weights)
        if lc == "FLATTEN":
            continue  # shape inference inserts CnnToFeedForward automatically
        state = {}
        if isinstance(p, dict) and "__params__" in p:
            state = p["__state__"]
            p = p["__params__"]
        layer_confs.append(lc)
        params_list.append(p)
        states_list.append(state)
    if input_type is None:
        shape = model.input_shape  # (None, ...) tuple
        if len(shape) == 2:
            input_type = C.InputType.feed_forward(shape[1])
        elif len(shape) == 4:
            input_type = C.InputType.convolutional(shape[1], shape[2], shape[3])
        elif len(shape) == 3:
            input_type = C.InputType.recurrent(shape[2])
        else:
            raise ValueError(f"cannot infer InputType from {shape}")
    b = nn.builder().list()
    for lc in layer_confs:
        b.layer(lc)
    conf = b.set_input_type(input_type).build()
    net = nn.MultiLayerNetwork(conf).init()
    # graft imported weights. Keras flattens conv activations HWC-major; our
    # CnnToFeedForward preprocessor flattens CHW-major — reorder the input
    # rows of any Dense W that sits right after that preprocessor.
    import jax.numpy as jnp

    for i, (lc, p, st) in enumerate(zip(layer_confs, params_list, states_list)):
        pre = net.conf.preprocessors.get(i)
        for k, w in p.items():
            if (k == "W" and isinstance(pre, C.CnnToFeedForwardPreProcessor)
                    and w.ndim == 2
                    and w.shape[0] == pre.height * pre.width * pre.channels):
                w = (w.reshape(pre.height, pre.width, pre.channels, -1)
                     .transpose(2, 0, 1, 3)
                     .reshape(w.shape[0], -1))
            net.params[i][k] = jnp.asarray(w)
        for k, v in st.items():
            net.net_state[i][k] = jnp.asarray(v)
    return net


def import_keras_sequential_model_and_weights(h5_path: str) -> nn.MultiLayerNetwork:
    """KerasModelImport entry: load a saved .h5/.keras file via in-env keras,
    then convert."""
    import tensorflow as tf

    model = tf.keras.models.load_model(h5_path, compile=False)
    return import_keras_model(model)
