"""Framework-neutral import IR — the samediff-import framework analog.

Reference parity: the reference's Kotlin IR import stack
(nd4j/samediff-import/samediff-import-api — FrameworkImporter,
MappingProcess, IRGraph/IRNode abstractions) normalizes TF and ONNX graphs
into one node/attribute shape, then per-op mapping rules translate to
SameDiff. This module is that layer: TF GraphDefs and ONNX ModelProtos
both lower into :class:`IRGraph`, and :class:`IRImporter` owns the shared
walk (constants → variables, placeholders, topological dispatch, output
renaming) that was previously TF-private — so a new frontend only writes
(a) a parser to IRGraph and (b) a dialect rule table.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from deeplearning4j_tpu.autodiff.samediff import SameDiff, SDVariable


@dataclasses.dataclass
class IRNode:
    """One computation node, framework-normalized."""

    name: str
    op_type: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    # back-compat shim: TF mappers historically read node.input[i]
    @property
    def input(self) -> List[str]:
        return self.inputs


# "slice to the end" sentinel shared by the TF/ONNX slice rules: the
# strided_slice backend executes via Python/jnp slicing, which CLAMPS
# out-of-range bounds — both dialects rely on that contract through this
# one constant.
SLICE_TO_END = 2**31 - 1


@dataclasses.dataclass
class IRGraph:
    """Normalized graph: nodes in topological-ish file order + tensors."""

    nodes: List[IRNode]
    initializers: Dict[str, np.ndarray]
    inputs: List[Tuple[str, Optional[Tuple[Optional[int], ...]]]]
    outputs: List[str]
    name: str = "imported"


class IRImporter:
    """Shared rule-dispatch walker (MappingProcess executor analog).

    ``rules``: op_type -> fn(sd, ins, attrs, node, const_values=...) -> SDVariable.
    Rules listed in ``needs_consts`` additionally receive the raw numpy
    values of constant operands (shape/perm/axis inputs).
    """

    def __init__(self, rules: Dict[str, Callable[..., Any]],
                 needs_consts: Sequence[str] = (),
                 trainable_consts: bool = True,
                 needs_scope: Sequence[str] = (),
                 optimize: bool = True,
                 validate: bool = True):
        self.rules = dict(rules)
        self.needs_consts = set(needs_consts)
        self.trainable_consts = trainable_consts
        # ops whose rule receives scope= (the live name→SDVariable map built
        # so far) — ONNX Loop/If/Scan subgraphs capture outer-scope tensors
        # by name, unlike TF function-style control flow
        self.needs_scope = set(needs_scope)
        # pre-trace graph optimizer (autodiff/optimize.py): imported graphs
        # carry the most redundancy (verbatim source nodes, per-layer
        # duplicated chains, no-op Identity/Dropout), so every frontend
        # that lowers through this walker gets the optimizer by default —
        # including the fusion tier that routes attention/matmul-epilogue
        # chains onto the registry fast kernels (docs/OPTIMIZER.md;
        # DL4J_TPU_FUSION=0 opts fusion out without losing the rest)
        self.optimize = optimize
        # graftcheck (analysis/ — docs/ANALYSIS.md): imported graphs are
        # where shape/dtype bugs enter, so every frontend verifies the
        # finished SameDiff statically; provable errors raise
        # GraphCheckError with node provenance AT IMPORT, not as an XLA
        # tracer error at first execution
        self.validate = validate

    def supported_ops(self) -> List[str]:
        return sorted(self.rules)

    def run_import(self, ir: IRGraph) -> SameDiff:
        sd = SameDiff.create(optimize=self.optimize)
        produced: Dict[str, SDVariable] = {}
        const_values: Dict[str, np.ndarray] = dict(ir.initializers)

        for name, arr in ir.initializers.items():
            if (self.trainable_consts and
                    np.issubdtype(arr.dtype, np.floating) and arr.size > 1):
                produced[name] = sd.var(name, arr)
            else:
                produced[name] = sd.constant(name, arr)
        for name, shape in ir.inputs:
            produced[name] = sd.placeholder(name, shape=shape)

        for node in ir.nodes:
            rule = self.rules.get(node.op_type)
            if rule is None:
                raise NotImplementedError(
                    f"op '{node.op_type}' (node {node.name}) has no mapping "
                    f"rule; register one in the {ir.name} dialect table")
            # empty names are ONNX's explicit "optional input absent" slots
            missing = [n for n in node.inputs if n and n not in produced]
            if missing:
                # a silently dropped operand would misalign the positional
                # `ins` and surface as an arity error far from the cause —
                # typically an unregistered multi-output slot (e.g. a mapper
                # that returns fewer outputs than the source op produces)
                raise ValueError(
                    f"node '{node.name}' ({node.op_type}) consumes "
                    f"unresolved input(s) {missing} — its producer's mapping "
                    f"rule may not register that output slot")
            ins = [produced[n] for n in node.inputs if n]
            kw = {}
            if node.op_type in self.needs_consts:
                kw["const_values"] = const_values
            if node.op_type in self.needs_scope:
                kw["scope"] = produced
            out = rule(sd, ins, node.attrs, node, **kw)
            if out is None:
                continue
            outs = out if isinstance(out, (list, tuple)) else [out]
            names = node.outputs or [node.name]
            for o, oname in zip(outs, names):
                if o.vtype == "ARRAY" and oname not in sd._vars:
                    o.rename(oname)
                produced[oname] = o
            # extra outputs beyond the declared names resolve by slot — the
            # TF "op:N" addressing (graphdef_to_ir preserves N > 0 slots)
            for j in range(len(names), len(outs)):
                produced[f"{node.name}:{j}"] = outs[j]
            # the node's own name also resolves (TF addressing convention)
            produced.setdefault(node.name, outs[0])
        # record the graph IO signature (GraphRunner uses it for default
        # fetches; TF GraphDefs carry no explicit outputs → terminal nodes)
        outs = list(ir.outputs)
        if not outs:
            consumed = {i for node in ir.nodes for i in node.inputs}
            # only nodes that actually produced a value — rules may return
            # None for utility nodes (NoOp/init), which never materialize
            outs = [n.name for n in ir.nodes
                    if n.name not in consumed and n.name in produced]
        for oname in outs:
            if oname not in sd._vars and oname in produced:
                # output name resolves to a var that could not be renamed
                # (a placeholder passthrough, e.g. a While body returning a
                # loop-invariant arg via Identity) — alias it explicitly so
                # execution can fetch it by the graph's output name
                sd._record("identity", [produced[oname]]).rename(oname)
        sd.graph_inputs = [n for n, _ in ir.inputs]
        sd.graph_outputs = outs
        if self.validate:
            from deeplearning4j_tpu.analysis import check_samediff

            report = check_samediff(sd, graph_name=ir.name)
            sd.last_check_report = report
            report.raise_on_errors()
        return sd
