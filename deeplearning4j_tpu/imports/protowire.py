"""Minimal protobuf wire-format codec (no protobuf dependency).

Reference parity: the reference's import stack links protobuf to read TF
GraphDefs and ONNX ModelProtos (nd4j-backends protobuf shading;
samediff-import-onnx's onnx.proto bindings). This environment has no onnx
package, so the ONNX front end decodes the wire format directly — which is
small and stable: varint tags, four wire types, length-delimited messages
(https://protobuf.dev/programming-guides/encoding/ — public spec).

The writer exists for the golden tests: they hand-assemble ONNX ModelProto
bytes (the reference generates goldens with real frameworks; here the env
has no ONNX producer either, so tests build models at the byte level and
check the imported graph against an independently coded numpy forward).
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Tuple

# wire types
VARINT, I64, LEN, I32 = 0, 1, 2, 5


def read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    out = 0
    while True:
        b = buf[i]
        i += 1
        out |= (b & 0x7F) << shift
        if not b & 0x80:
            return out, i
        shift += 7


def parse_message(buf: bytes) -> Dict[int, List[Tuple[int, Any]]]:
    """Decode one message into {field_number: [(wire_type, raw_value), ...]}.

    LEN fields stay as bytes (caller interprets as sub-message, string, or
    packed scalars); VARINT as int; I32/I64 as raw 4/8 bytes.
    """
    fields: Dict[int, List[Tuple[int, Any]]] = {}
    i = 0
    n = len(buf)
    while i < n:
        tag, i = read_varint(buf, i)
        field, wt = tag >> 3, tag & 7
        if wt == VARINT:
            v, i = read_varint(buf, i)
        elif wt == LEN:
            ln, i = read_varint(buf, i)
            v = buf[i:i + ln]
            i += ln
        elif wt == I64:
            v = buf[i:i + 8]
            i += 8
        elif wt == I32:
            v = buf[i:i + 4]
            i += 4
        else:  # pragma: no cover - groups are long-dead
            raise ValueError(f"unsupported wire type {wt}")
        fields.setdefault(field, []).append((wt, v))
    return fields


# -- typed accessors ---------------------------------------------------------


def get_varints(fields, num) -> List[int]:
    return [v for wt, v in fields.get(num, []) if wt == VARINT]


def get_varint(fields, num, default=0) -> int:
    vs = get_varints(fields, num)
    return vs[-1] if vs else default


def get_bytes(fields, num) -> List[bytes]:
    return [v for wt, v in fields.get(num, []) if wt == LEN]


def get_byte(fields, num, default=b"") -> bytes:
    vs = get_bytes(fields, num)
    return vs[-1] if vs else default


def get_string(fields, num, default="") -> str:
    return get_byte(fields, num, default.encode()).decode("utf-8", "replace")


def get_float(fields, num, default=0.0) -> float:
    for wt, v in fields.get(num, []):
        if wt == I32:
            return struct.unpack("<f", v)[0]
    return default


def get_packed_or_repeated_varints(fields, num) -> List[int]:
    """int64/int32 repeated fields arrive packed (proto3) or one-per-tag."""
    out: List[int] = []
    for wt, v in fields.get(num, []):
        if wt == VARINT:
            out.append(v)
        elif wt == LEN:
            i = 0
            while i < len(v):
                x, i = read_varint(v, i)
                out.append(x)
    return [_to_signed64(x) for x in out]


def get_packed_floats(fields, num) -> List[float]:
    out: List[float] = []
    for wt, v in fields.get(num, []):
        if wt == I32:
            out.append(struct.unpack("<f", v)[0])
        elif wt == LEN:
            out.extend(struct.unpack(f"<{len(v) // 4}f", v))
    return out


def _to_signed64(x: int) -> int:
    return x - (1 << 64) if x >= (1 << 63) else x


# -- writer (for golden-test model assembly) ---------------------------------


def _varint(x: int) -> bytes:
    if x < 0:
        x += 1 << 64
    out = bytearray()
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def field_varint(num: int, val: int) -> bytes:
    return _varint(num << 3 | VARINT) + _varint(val)


def field_bytes(num: int, val: bytes) -> bytes:
    return _varint(num << 3 | LEN) + _varint(len(val)) + val


def field_string(num: int, val: str) -> bytes:
    return field_bytes(num, val.encode())


def field_float(num: int, val: float) -> bytes:
    return _varint(num << 3 | I32) + struct.pack("<f", val)


def field_packed_varints(num: int, vals) -> bytes:
    body = b"".join(_varint(v if v >= 0 else v + (1 << 64)) for v in vals)
    return field_bytes(num, body)
