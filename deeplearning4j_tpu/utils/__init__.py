"""Cross-cutting utilities — profiling/tracing + UI stats (SURVEY §6.1, §6.5)."""

from deeplearning4j_tpu.utils.profiling import (
    OpProfiler,
    ChromeTraceWriter,
    ProfilingListener,
    ProfileAnalyzer,
    device_trace,
)
from deeplearning4j_tpu.utils.stats import (
    StatsStorage,
    FileStatsStorage,
    StatsListener,
)
