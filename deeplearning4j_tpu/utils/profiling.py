"""Tracing / profiling — SURVEY §6.1 parity.

Reference parity:
  * ND4J OpProfiler (org/nd4j/linalg/profiler/OpProfiler.java): per-op-name
    invocation counts + timings, NaN/Inf panic modes.
  * SameDiff ProfilingListener (autodiff/listeners/profiler/): Chrome
    trace-event JSON; ProfileAnalyzer diffs two traces.
  * DL4J PerformanceListener: samples/sec + memory (in nn/listeners.py).

TPU-native realization: ops fuse into one XLA program, so per-op WALL times
don't exist at runtime — the op-level profile is collected at TRACE time
(registry exec counts) and the runtime profile is per-STEP plus the jax
profiler (XPlane, viewable in tensorboard) for intra-step breakdown.
Chrome-trace JSON output is kept as the user-facing parity artifact.
"""

from __future__ import annotations

import contextlib
import json
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from deeplearning4j_tpu.observe.tracing import SpanTracer


class OpProfiler:
    """Op invocation counting — OpProfiler.java analog (trace-time).

    Enable with ``OpProfiler.instance().start()``; the op registry reports
    each exec. ``stats()`` pretty-prints counts like the reference's
    printOutDashboard.
    """

    _instance: Optional["OpProfiler"] = None

    def __init__(self):
        self.counts: Dict[str, int] = defaultdict(int)
        self.times: Dict[str, float] = defaultdict(float)
        self.enabled = False

    @classmethod
    def instance(cls) -> "OpProfiler":
        if cls._instance is None:
            cls._instance = OpProfiler()
        return cls._instance

    def start(self):
        self.enabled = True
        return self

    def stop(self):
        self.enabled = False
        return self

    def reset(self):
        self.counts.clear()
        self.times.clear()

    def record(self, op_name: str, seconds: float = 0.0):
        if self.enabled:
            self.counts[op_name] += 1
            self.times[op_name] += seconds

    def stats(self) -> str:
        lines = ["Op profile (trace-time invocations):"]
        for name, c in sorted(self.counts.items(), key=lambda kv: -kv[1]):
            lines.append(f"  {name:<40} {c:>8}  {1000*self.times[name]:.2f} ms")
        return "\n".join(lines)


class ChromeTraceWriter(SpanTracer):
    """Chrome trace-event JSON accumulation (ProfilingListener's format).

    Since the observe/ telemetry layer landed this is a thin subclass of
    :class:`deeplearning4j_tpu.observe.tracing.SpanTracer` — profiling
    artifacts and runtime telemetry spans share ONE trace format (same
    event schema, same monotonic clock, same ``write()`` output), so a
    ProfilingListener trace and an ``observe.tracer()`` dump merge cleanly
    in chrome://tracing / Perfetto. Unbounded by default: an explicit
    artifact writer must keep the whole run, unlike the bounded
    process-wide default tracer."""

    def __init__(self, max_events=None):
        super().__init__(max_events=max_events)


class ProfilingListener:
    """Per-iteration profiling → chrome trace (ProfilingListener.java).

    Attach via net.set_listeners(ProfilingListener(out="trace.json")).
    Records one complete-event per training iteration with the score; on
    epoch end (or .close()) writes chrome://tracing-compatible JSON.
    """

    def __init__(self, output_path: str):
        self.output_path = output_path
        self.trace = ChromeTraceWriter()
        self._iter_start: Optional[float] = None

    def on_epoch_start(self, model):
        self.trace.instant("epoch_start", epoch=getattr(model, "epoch_count", -1))

    def iteration_done(self, model, iteration, epoch, score):
        now = self.trace._us()
        if self._iter_start is not None:
            self.trace.events.append({
                "name": f"iteration_{iteration}", "cat": "train_step", "ph": "X",
                "ts": self._iter_start, "dur": now - self._iter_start,
                "pid": 0, "tid": 0, "args": {"iteration": iteration}})
        self._iter_start = now

    def on_epoch_end(self, model):
        self.trace.instant("epoch_end", epoch=getattr(model, "epoch_count", -1))
        self.close()

    def close(self):
        self.trace.write(self.output_path)


class ProfileAnalyzer:
    """comparison/ProfileAnalyzer analog: aggregate + diff chrome traces."""

    @staticmethod
    def load(path: str) -> Dict[str, float]:
        with open(path) as f:
            data = json.load(f)
        agg: Dict[str, float] = defaultdict(float)
        for e in data.get("traceEvents", []):
            if e.get("ph") == "X":
                agg[e.get("cat", e["name"])] += e.get("dur", 0.0)
        return dict(agg)

    @staticmethod
    def compare(path_a: str, path_b: str) -> Dict[str, Dict[str, float]]:
        a, b = ProfileAnalyzer.load(path_a), ProfileAnalyzer.load(path_b)
        out = {}
        for k in set(a) | set(b):
            out[k] = {"a_us": a.get(k, 0.0), "b_us": b.get(k, 0.0),
                      "ratio": (a.get(k, 0.0) / b[k]) if b.get(k) else float("inf")}
        return out


@contextlib.contextmanager
def device_trace(log_dir: str):
    """jax profiler (XPlane/tensorboard) wrapper — the intra-step breakdown
    the reference gets from per-op native timers."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
