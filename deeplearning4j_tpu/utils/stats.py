"""Training UI stats pipeline — SURVEY §6.5 parity.

Reference parity:
  * deeplearning4j-ui-model StatsListener.java → StatsStorage (in-memory or
    MapDB file) → VertxUIServer charts (score, param/update ratios,
    histograms, system metrics); RemoteUIStatsStorageRouter posts over HTTP.

TPU-native realization: StatsListener collects the same per-iteration
quantities (score, per-layer param/gradient/update norms + mean-magnitude
ratios — the signature dead-LR debugging chart); storage is in-memory or
JSON-lines file. A tensorboard scalar writer rides alongside (tensorboardX
role); the web server itself is out of scope (tensorboard covers it), but
the listener→storage protocol is the parity surface.
"""

from __future__ import annotations

import json
import os
import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

import numpy as np


class StatsStorage:
    """In-memory StatsStorage.java analog."""

    def __init__(self):
        self.records: List[Dict[str, Any]] = []

    def put(self, record: Dict[str, Any]) -> None:
        self.records.append(record)

    def session_scores(self) -> List[float]:
        return [r["score"] for r in self.records if "score" in r]

    def latest(self) -> Optional[Dict[str, Any]]:
        return self.records[-1] if self.records else None


class FileStatsStorage(StatsStorage):
    """MapDB FileStatsStorage analog: JSON-lines file."""

    def __init__(self, path: str):
        super().__init__()
        self.path = path
        if os.path.exists(path):
            with open(path) as f:
                for line in f:
                    if line.strip():
                        self.records.append(json.loads(line))

    def put(self, record):
        super().put(record)
        with open(self.path, "a") as f:
            f.write(json.dumps(record) + "\n")


class StatsListener:
    """StatsListener.java analog: per-iteration stats into a StatsStorage."""

    def __init__(self, storage: StatsStorage, frequency: int = 1,
                 collect_histograms: bool = False,
                 collect_activations: bool = False):
        self.storage = storage
        self.frequency = max(1, frequency)
        self.collect_histograms = collect_histograms
        # per-layer activation mean-magnitude/stdev (the reference model
        # view's activation charts) — costs one extra forward per report,
        # exactly as the reference's stats collection does
        self.collect_activations = collect_activations
        self._prev_params: Optional[List[Dict[str, np.ndarray]]] = None
        self._sent_static = False

    def _static_info(self, model) -> Optional[Dict[str, Any]]:
        """One-time model topology — the reference dashboard's model-graph
        pane (StatsInitializationReport static info, SURVEY §6.5)."""
        def nparams(p):
            return int(sum(np.asarray(a).size for _, a in _leaves(p)))

        conf = getattr(model, "conf", None)
        if hasattr(model, "layers") and isinstance(model.layers, dict):
            # ComputationGraph: real DAG edges from the config
            nodes, edges = [], []
            for inp in getattr(conf, "network_inputs", []):
                nodes.append({"name": inp, "type": "Input", "params": 0})
            gnodes = getattr(conf, "nodes", None) or []
            for gn in gnodes:
                kind = getattr(gn, "kind", "layer")
                name = getattr(gn, "name", "?")
                if kind == "layer":
                    lc = model.layers.get(name)
                    tname = type(lc.lc).__name__ if lc is not None else "?"
                    np_ = nparams(model.params.get(name, {}))
                else:
                    tname = type(getattr(gn, "vertex", None)).__name__                         if getattr(gn, "vertex", None) is not None else "Vertex"
                    np_ = 0
                nodes.append({"name": name, "type": tname, "params": np_})
                for i in getattr(gn, "inputs", []):
                    edges.append([i, name])
            return {"kind": "graph", "nodes": nodes, "edges": edges}
        if hasattr(model, "layers") and isinstance(model.layers, list):
            nodes, edges = [{"name": "input", "type": "Input", "params": 0}], []
            prev = "input"
            for i, layer in enumerate(model.layers):
                lc = layer.lc
                name = lc.name or f"layer_{i}"
                nodes.append({"name": name, "type": type(lc).__name__,
                              "params": nparams(model.params[i])})
                edges.append([prev, name])
                prev = name
            return {"kind": "sequential", "nodes": nodes, "edges": edges}
        return None

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def iteration_done(self, model, iteration, epoch, score):
        if not self._sent_static:
            self._sent_static = True
            info = self._static_info(model)
            if info is not None:
                self.storage.put({"static_model_info": info,
                                  "iteration": -1})
        if iteration % self.frequency != 0:
            return
        rec: Dict[str, Any] = {
            "iteration": iteration, "epoch": epoch, "score": float(score),
            "timestamp": time.time(),
        }
        params = model.params
        layer_stats = {}
        # params may be a list (MLN) or dict (ComputationGraph)
        items = (enumerate(params) if isinstance(params, list)
                 else params.items())
        prev = self._prev_params
        for key, p in items:
            for pname, arr in _leaves(p):
                a = np.asarray(arr)
                name = f"{key}_{pname}"
                st = {"mean_magnitude": float(np.abs(a).mean()),
                      "norm2": float(np.linalg.norm(a))}
                if prev is not None:
                    prev_arr = _lookup(prev, key, pname)
                    if prev_arr is not None and prev_arr.shape == a.shape:
                        upd = a - prev_arr
                        st["update_mean_magnitude"] = float(np.abs(upd).mean())
                        # THE ratio chart: mean|update| / mean|param|
                        st["update_ratio"] = float(
                            np.abs(upd).mean() / max(np.abs(a).mean(), 1e-12))
                if self.collect_histograms:
                    hist, edges = np.histogram(a, bins=20)
                    st["histogram"] = {"counts": hist.tolist(),
                                       "edges": edges.tolist()}
                layer_stats[name] = st
        rec["layers"] = layer_stats
        if self.collect_activations:
            acts = self._activation_stats(model)
            if acts:
                rec["activations"] = acts
        self.storage.put(rec)
        self._prev_params = _snapshot(params)

    def _activation_stats(self, model):
        """Per-layer activation mean|a|/std via one feed_forward on the
        model's last-seen batch (stashed by fit); MLN only — graph
        activations are a dict of DAG nodes and chart the same way when
        exposed."""
        feats = getattr(model, "_last_features", None)
        if feats is None or not hasattr(model, "feed_forward"):
            return None
        try:
            acts = model.feed_forward(np.asarray(feats), train=False)
        except Exception:
            return None
        out = {}
        for i, a in enumerate(acts):
            arr = np.asarray(a)
            lc = model.layers[i].lc if i < len(model.layers) else None
            name = (getattr(lc, "name", None) or f"layer_{i}")
            out[name] = {"mean_magnitude": float(np.abs(arr).mean()),
                         "stdev": float(arr.std())}
        return out


def _leaves(tree, prefix=""):
    out = []
    if isinstance(tree, dict):
        for k, v in tree.items():
            if isinstance(v, dict):
                out.extend(_leaves(v, f"{prefix}{k}/"))
            else:
                out.append((f"{prefix}{k}", v))
    return out


def _snapshot(params):
    if isinstance(params, list):
        return [{k: np.asarray(v).copy() for k, v in _leaves(p)} for p in params]
    return {key: {k: np.asarray(v).copy() for k, v in _leaves(p)}
            for key, p in params.items()}


def _lookup(prev, key, pname):
    try:
        if isinstance(prev, list):
            return prev[key].get(pname)
        return prev[key].get(pname)
    except (KeyError, IndexError, TypeError):
        return None


class TensorboardStatsWriter:
    """Scalar export to tensorboard event files (rides on the in-env
    tensorboard; the reference's UI-server charts equivalent view)."""

    def __init__(self, log_dir: str):
        from torch.utils.tensorboard import SummaryWriter  # torch-cpu in env

        self.writer = SummaryWriter(log_dir)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        self.writer.flush()

    def iteration_done(self, model, iteration, epoch, score):
        self.writer.add_scalar("score", float(score), iteration)


class RemoteUIStatsStorageRouter(StatsStorage):
    """RemoteUIStatsStorageRouter.java analog: a StatsStorage whose ``put``
    POSTs each record to a remote UIServer's ``/remote`` endpoint, so
    launcher workers / other hosts stream their training stats into process
    0's dashboard (SURVEY §6.5; round-4 missing #4).

    Drop-in for the local storage: ``StatsListener(RemoteUIStatsStorageRouter
    ("http://host:9000"))``. Failed posts buffer and retry on the next put
    (``max_buffer`` newest kept), so a UI restart loses nothing recent and
    training never blocks on the dashboard."""

    def __init__(self, url: str, timeout: float = 2.0, max_buffer: int = 1000):
        super().__init__()
        self.url = url.rstrip("/") + "/remote"
        self.timeout = timeout
        self.max_buffer = max_buffer
        self._pending: List[Dict[str, Any]] = []

    def put(self, record: Dict[str, Any]) -> None:
        super().put(record)  # keep the local mirror (scores/latest work)
        self._pending.append(_jsonable(record))
        self._pending = self._pending[-self.max_buffer:]
        self._flush()

    def _flush(self) -> None:
        import urllib.request

        if not self._pending:
            return
        body = json.dumps(self._pending).encode()
        req = urllib.request.Request(
            self.url, data=body, method="POST",
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                if resp.status == 200:
                    self._pending = []
        except Exception:
            pass  # buffered; retried on the next put


def _jsonable(x):
    if isinstance(x, dict):
        return {k: _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    if isinstance(x, (np.integer,)):
        return int(x)
    if isinstance(x, (np.floating,)):
        return float(x)
    if isinstance(x, np.ndarray):
        return x.tolist()
    return x
