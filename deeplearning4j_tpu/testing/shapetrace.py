"""shapetrace — runtime ledger tracer cross-validating graftshape.

The static jit-boundary inventory (:func:`..lint.rules_shape.
static_shape_inventory`) is an over-approximation built from the AST;
this module is the under-approximation built from execution: snapshot
the :class:`~deeplearning4j_tpu.observe.RecompileLedger` before a
workload, run it, then hold every ``CompileEvent`` recorded since
against the inventory. The honesty contract, checked by
:meth:`ShapeTracer.check`:

* every event's ``callsite`` must land inside a statically known
  registration span (a ``note_jit_signature`` / ``ledger.record`` call
  expression) of a scanned module — an event with no callsite, or with
  a callsite the static scan never saw, means a registration path the
  analyzer's dataflow missed (a graftshape blind spot to fix in
  ``rules_shape``, not to baseline away); events attributed to files
  OUTSIDE the scanned roots (tests, tools) are counted separately as
  ``external`` and do not fail the check;
* every ``new_shape`` event must attribute to a module the static scan
  flagged as a shape hazard (a raw GS finding, justified or not) — a
  ``new_shape`` rising out of a statically CLEAN module means either
  the module's bucketing contract broke at runtime or the analyzer has
  a false negative; both are failures.

The two directions together are the same bargain locktrace strikes for
locks: static says "nothing outside this boundary can happen", runtime
says "here is what did happen", and the gate fails unless runtime ⊆
static.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

from deeplearning4j_tpu.lint.rules_shape import (
    ShapeInventory, static_shape_inventory)

__all__ = ["ShapeTracer", "static_shape_inventory"]


class ShapeTracer:
    """Ledger-window recorder: construction snapshots the event count,
    :meth:`check` judges everything recorded since."""

    def __init__(self) -> None:
        from deeplearning4j_tpu import observe
        self._start = len(observe.ledger().events())

    def events(self) -> List[Any]:
        from deeplearning4j_tpu import observe
        return list(observe.ledger().events()[self._start:])

    def check(self, repo_root: str,
              inventory: Optional[ShapeInventory] = None,
              roots: Sequence[str] = ("deeplearning4j_tpu",)
              ) -> Dict[str, Any]:
        """Cross-validate the ledger window against the static
        inventory. Returns a report dict whose ``ok`` is True iff every
        in-root event attributes to a registration span AND every
        ``new_shape`` lands in a statically flagged hazard module."""
        if inventory is None:
            inventory = static_shape_inventory(repo_root, roots=roots)
        evs = self.events()
        unattributed: List[Dict[str, Any]] = []
        external = 0
        new_shape_unexplained: List[Dict[str, Any]] = []
        new_shape_total = 0
        for ev in evs:
            cs = getattr(ev, "callsite", None)
            if cs is None:
                unattributed.append({"graph": ev.graph, "key": ev.key,
                                     "cause": ev.cause, "callsite": None})
                continue
            path = cs.rpartition(":")[0]
            in_roots = any(path == r or path.startswith(r + "/")
                           for r in roots)
            if not in_roots:
                external += 1
            elif not inventory.attributes_callsite(cs):
                unattributed.append({"graph": ev.graph, "key": ev.key,
                                     "cause": ev.cause, "callsite": cs})
            if ev.cause == "new_shape":
                new_shape_total += 1
                if in_roots and not inventory.hazard_module(path):
                    new_shape_unexplained.append(
                        {"graph": ev.graph, "key": ev.key,
                         "callsite": cs})
        by_cause: Dict[str, int] = {}
        for ev in evs:
            by_cause[ev.cause] = by_cause.get(ev.cause, 0) + 1
        return {
            "ok": not unattributed and not new_shape_unexplained,
            "events": len(evs),
            "by_cause": dict(sorted(by_cause.items())),
            "external": external,
            "unattributed": unattributed,
            "new_shape_total": new_shape_total,
            "new_shape_unexplained": new_shape_unexplained,
            "registration_span_files": len(inventory.registration_spans),
            "jit_sites": len(inventory.jit_sites),
            "hazard_modules": len(inventory.hazards),
            "clean_modules": len(inventory.clean_modules),
        }
