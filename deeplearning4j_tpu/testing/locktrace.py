"""locktrace — runtime shadow-lock tracer cross-validating graftlock.

The static lock-order graph (:func:`..lint.rules_concurrency.
static_lock_order`) is an over-approximation built from the AST; this
module is the under-approximation built from execution: wrap the real
locks of a live system in :class:`ShadowLock`, run the threaded
workload, and every acquisition records "held -> acquired" edges into a
shared :class:`LockTracer`. The honesty contract, checked by
:meth:`LockTracer.check`:

* every edge actually observed must lie inside the TRANSITIVE CLOSURE of
  the static graph (the tracer records an edge per held lock, so a
  hold-through-two-levels surfaces as the composed edge the static graph
  only has in two hops), and
* the union of static and observed edges must stay acyclic.

An observed edge outside the static closure means the analyzer's call
graph missed an acquisition path — a graftlock blind spot that must be
fixed in ``rules_concurrency``, not baselined away.

Wrapping is transparent: ``ShadowLock`` delegates ``acquire`` /
``release`` / context management to the wrapped primitive, so it can
replace a ``Lock`` or ``RLock`` attribute in place
(:func:`instrument_lock`), and a fresh ``Condition`` built over a shadow
lock replaces condition-variable attributes (:func:`instrument_
condition`) — ``Condition.wait`` then releases/reacquires through the
shadow, which is exactly the semantics the tracer must see. Instrument
BEFORE the object's threads start.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu.lint.rules_concurrency import (
    LockGraph, _find_cycle, static_lock_order)

__all__ = ["ShadowLock", "LockTracer", "instrument_lock",
           "instrument_condition", "static_lock_order"]


class LockTracer:
    """Shared edge recorder: thread-local held stacks, global edge set."""

    def __init__(self):
        self._local = threading.local()
        self._mu = threading.Lock()  # guards _edges/_sites only
        self._edges: Set[Tuple[str, str]] = set()
        self._sites: Dict[Tuple[str, str], str] = {}

    def _stack(self) -> List[str]:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    # -- ShadowLock callbacks ------------------------------------------------
    def on_acquired(self, node: str) -> None:
        st = self._stack()
        held = [h for h in st if h != node]  # RLock re-entry is not an edge
        st.append(node)
        if not held:
            return
        with self._mu:
            for h in held:
                if (h, node) not in self._edges:
                    self._edges.add((h, node))
                    self._sites[(h, node)] = threading.current_thread().name

    def on_released(self, node: str) -> None:
        st = self._stack()
        # remove the INNERMOST occurrence — out-of-order releases exist
        # (e.g. lock handoff) and re-entrant locks release outside-in
        for i in range(len(st) - 1, -1, -1):
            if st[i] == node:
                del st[i]
                return

    # -- results -------------------------------------------------------------
    def edges(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self._edges)

    def check(self, static: Optional[LockGraph] = None,
              repo_root: str = ".") -> Dict:
        """The cross-validation verdict: ok iff the static graph is
        acyclic, every observed edge is in its transitive closure, and
        static ∪ observed stays acyclic."""
        if static is None:
            static = static_lock_order(repo_root)
        observed = self.edges()
        closure = static.closure()
        static_cycle = static.cycle()
        unknown = sorted(e for e in observed if e not in closure)
        combined_cycle = _find_cycle(static.edges | observed)
        ok = (static_cycle is None and not unknown
              and combined_cycle is None)
        return {
            "ok": ok,
            "observed_edges": sorted(observed),
            "static_edges": len(static.edges),
            "static_cycle": static_cycle,
            "unknown_edges": [
                {"edge": list(e),
                 "thread": self._sites.get(e, "?")} for e in unknown],
            "combined_cycle": combined_cycle,
        }


class ShadowLock:
    """A recording proxy around a real lock primitive.

    Only ``acquire``/``release``/``__enter__``/``__exit__``/``locked``
    are proxied — enough for ``Lock``, ``RLock``, and for serving as the
    lock under a ``threading.Condition`` (whose default ``wait`` releases
    and reacquires via these exact methods)."""

    def __init__(self, inner, node: str, tracer: LockTracer):
        self._inner = inner
        self._node = node
        self._tracer = tracer

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracer.on_acquired(self._node)
        return got

    def release(self) -> None:
        # record BEFORE the real release: after it, another thread may
        # already be inside and the stack would misattribute holds
        self._tracer.on_released(self._node)
        self._inner.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"ShadowLock({self._node}, {self._inner!r})"


def instrument_lock(obj, attr: str, node: str,
                    tracer: LockTracer) -> ShadowLock:
    """Replace ``obj.<attr>`` (a Lock/RLock) with a recording shadow.
    Call before any thread touches the lock."""
    shadow = ShadowLock(getattr(obj, attr), node, tracer)
    setattr(obj, attr, shadow)
    return shadow


def instrument_condition(obj, attr: str, node: str,
                         tracer: LockTracer) -> threading.Condition:
    """Replace ``obj.<attr>`` (a Condition) with a fresh Condition over a
    shadowed plain Lock. The OLD condition's lock is abandoned, so this
    must run before any thread waits on it."""
    cv = threading.Condition(ShadowLock(threading.Lock(), node, tracer))
    setattr(obj, attr, cv)
    return cv
