"""lifetrace — runtime resource tracer cross-validating graftlife.

The static ownership analyzer (:mod:`..lint.rules_lifecycle`, rules
GR001–GR005) is an over-approximation built from the AST; this module is
the under-approximation built from execution: wrap a live
:class:`~deeplearning4j_tpu.serving.cache.PagedKVCache`'s allocator
methods in recording proxies, track every submitted request future and
every started thread, run a (faults-armed) workload, and then hold the
observed lifecycle against four contracts (:meth:`ResourceTracer.check`):

* **rc-clean pages** — every page ends the leg free XOR tree-held, with
  the refcount bookkeeping exactly balanced: observed acquisitions
  (``alloc_page`` successes + ``retain``) minus observed ``release``
  calls equals the live refcount mass, and
  :meth:`PagedKVCache.check_invariants` (with the prefix tree's per-page
  refs when available) holds;
* **exactly-once terminals** — every tracked request future is done and
  the ``dl4j_tpu_serving_evicted_total`` family grew by exactly one
  count per tracked request (the funnel discipline GR003 polices,
  observed end-to-end);
* **no leaked threads** — every thread started after :meth:`begin` is
  dead again by check time (a bounded settle-join absorbs shutdown
  stragglers — the GR004 contract);
* **observed ⊆ static inventory** — every acquire/release callsite the
  wrappers saw lies inside a function span of
  :func:`..lint.rules_lifecycle.static_ownership_inventory`. An
  observed callsite outside the inventory means the analyzer's
  vocabulary missed a lifecycle operation — a graftlife blind spot to
  fix in ``rules_lifecycle``, not to baseline away.

Wrapping is instance-level (the bound methods are replaced on the one
cache object), so internal composites stay honest without double
counting: ``cow_page`` routes through the wrapped ``alloc_page``,
``map_shared`` through the wrapped ``retain`` and ``free_slot`` through
the wrapped ``release`` — refcount deltas are counted ONLY on the three
primitives, while every wrapper records its caller's callsite.
Instrument BEFORE the workload's threads start.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from deeplearning4j_tpu import observe
from deeplearning4j_tpu.lint.rules_lifecycle import (
    OwnershipInventory, static_ownership_inventory)

__all__ = ["ResourceTracer", "static_ownership_inventory"]

_TERMINAL_FAMILY = "dl4j_tpu_serving_evicted_total"

# refcount deltas are counted on the primitives only — composites
# (cow_page/map_shared/free_slot/ensure_capacity) reach them through the
# instance-level wrappers and would otherwise double-count
_PRIMITIVE_DELTA = {"alloc_page": +1, "retain": +1, "release": -1}
_WRAPPED_OPS = ("alloc_page", "retain", "release", "cow_page",
                "map_shared", "free_slot")


class ResourceTracer:
    """Lifecycle recorder: page refcount ledger, request-future registry,
    thread baseline, and acquire/release callsite log."""

    def __init__(self):
        self._mu = threading.Lock()
        # (cache, name, tree_refs_fn, baseline_refcount_mass)
        self._caches: List[Tuple[object, str, Optional[Callable], int]] = []
        self._acquires = 0
        self._releases = 0
        # (op, absolute_file, line)
        self._sites: Set[Tuple[str, str, int]] = set()
        self._futures: List[object] = []
        self._future_ids: Set[int] = set()
        self._threads_before: Set[int] = set()
        self._terminals_before = 0.0
        self.begin()

    # -- baselines -----------------------------------------------------------
    def begin(self) -> None:
        """(Re)snapshot the thread set and the terminal-counter mass.
        Called by ``__init__``; call again to re-baseline mid-session."""
        with self._mu:
            self._threads_before = {id(t) for t in threading.enumerate()}
            self._terminals_before = observe.metrics().family_total(
                _TERMINAL_FAMILY)

    # -- instrumentation -----------------------------------------------------
    def attach_cache(self, cache, name: str = "cache",
                     tree_refs: Optional[Callable] = None) -> None:
        """Wrap ``cache``'s allocator methods in recording proxies.
        ``tree_refs`` (e.g. ``prefix.page_refs``) supplies the prefix
        tree's per-page reference counts for the exact-invariant check."""
        with self._mu:
            self._caches.append(
                (cache, name, tree_refs, sum(cache.refcount)))
        for op in _WRAPPED_OPS:
            setattr(cache, op, self._wrap(getattr(cache, op), op))

    def _wrap(self, bound, op: str):
        delta = _PRIMITIVE_DELTA.get(op)

        def recorded(*args, **kwargs):
            frame = sys._getframe(1)
            site = (op, frame.f_code.co_filename, frame.f_lineno)
            result = bound(*args, **kwargs)
            with self._mu:
                self._sites.add(site)
                if delta is not None:
                    # a failed alloc_page (pool exhausted -> None)
                    # acquired nothing
                    if not (op == "alloc_page" and result is None):
                        if delta > 0:
                            self._acquires += 1
                        else:
                            self._releases += 1
            return result

        return recorded

    def attach_engine(self, eng, name: str = "engine") -> None:
        """Convenience: track every future ``eng.submit_request`` returns
        (``submit`` delegates to it through the instance attribute, so
        one wrap sees both entry points — including the cluster router's
        pin re-warm submissions) and attach its cache with the prefix
        tree's refs when the engine has one."""
        tree_refs = eng.prefix.page_refs if eng.prefix is not None else None
        self.attach_cache(eng.cache, name=f"{name}.cache",
                          tree_refs=tree_refs)
        inner = eng.submit_request

        def tracked_submit(req):
            fut = inner(req)
            self.track_future(fut)
            return fut

        eng.submit_request = tracked_submit

    def track_future(self, fut) -> None:
        """Register a request future for the exactly-once terminal check
        (idempotent per future object)."""
        with self._mu:
            if id(fut) not in self._future_ids:
                self._future_ids.add(id(fut))
                self._futures.append(fut)

    # -- results -------------------------------------------------------------
    def observed_sites(self) -> Set[Tuple[str, str, int]]:
        with self._mu:
            return set(self._sites)

    def check(self, repo_root: str = ".",
              inventory: Optional[OwnershipInventory] = None,
              settle_s: float = 5.0,
              build_inventory: bool = True) -> Dict:
        """The cross-validation verdict (see module docstring). Pass
        ``build_inventory=False`` to skip the static-inventory callsite
        validation (the chaos legs do — they assert the runtime contracts
        on every run without paying an AST walk)."""
        # threads: give shutdown stragglers a bounded settle window
        deadline = time.perf_counter() + settle_s
        while time.perf_counter() < deadline:
            leaked = [t for t in threading.enumerate()
                      if id(t) not in self._threads_before and t.is_alive()]
            if not leaked:
                break
            time.sleep(0.02)
        leaked = [t for t in threading.enumerate()
                  if id(t) not in self._threads_before and t.is_alive()]

        with self._mu:
            caches = list(self._caches)
            acquires, releases = self._acquires, self._releases
            futures = list(self._futures)
            sites = set(self._sites)
            terminals_before = self._terminals_before

        # pages: live mass balances the ledger, invariants hold
        live_mass = 0
        invariant_errors: List[str] = []
        for cache, name, tree_refs, baseline in caches:
            live_mass += sum(cache.refcount) - baseline
            try:
                cache.check_invariants(
                    tree_refs() if tree_refs is not None else None)
            except AssertionError as e:
                invariant_errors.append(f"{name}: {e}")
        rc_balanced = (acquires - releases) == live_mass

        # terminals: exactly one count per tracked request
        undone = sum(1 for f in futures if not f.done())
        terminal_delta = (observe.metrics().family_total(_TERMINAL_FAMILY)
                          - terminals_before)
        exactly_once = undone == 0 and terminal_delta == len(futures)

        # callsites: observed ⊆ static inventory
        unknown_sites: List[Dict] = []
        if inventory is None and build_inventory:
            inventory = static_ownership_inventory(repo_root)
        if inventory is not None:
            root = os.path.abspath(repo_root)
            for op, fname, line in sorted(sites):
                rel = os.path.relpath(os.path.abspath(fname), root)
                if not inventory.attributes_callsite(rel, line):
                    unknown_sites.append(
                        {"op": op, "path": rel, "line": line})

        ok = (rc_balanced and not invariant_errors and exactly_once
              and not leaked and not unknown_sites)
        return {
            "ok": ok,
            "pages": {
                "caches": [name for _, name, _, _ in caches],
                "acquires": acquires,
                "releases": releases,
                "live_refs": live_mass,
                "rc_balanced": rc_balanced,
                "invariant_errors": invariant_errors,
            },
            "terminals": {
                "tracked": len(futures),
                "undone": undone,
                "counted": terminal_delta,
                "exactly_once": exactly_once,
            },
            "threads": {"leaked": [t.name for t in leaked]},
            "callsites": {
                "observed": len(sites),
                "validated": inventory is not None,
                "unknown": unknown_sites,
            },
        }
