"""ONNX ModelProto assembly at the protobuf wire level — shared builder.

No ONNX producer exists in this environment (no onnx package), so test and
bench models are assembled with the same wire codec the importer uses for
decoding (imports/protowire.py) — public onnx.proto3 field numbers. This
module is the canonical home of the assembly helpers (the golden tests
import them from here) plus :func:`bert_onnx_model`, a parameterizable
BERT-base-style encoder carrying the redundancy real per-module tracing
exporters emit — re-inlined attention-mask expansion chains, Dropout and
Identity no-ops, per-layer foldable scale chains, decomposed erf-gelu — the
exact surface the graph optimizer's pass pipeline and fusion tier attack
(docs/OPTIMIZER.md; BENCH_MODEL=bert_import).
"""

from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.imports import protowire as pw

_NP_DT = {np.dtype(np.float32): 1, np.dtype(np.int64): 7,
          np.dtype(np.int32): 6, np.dtype(np.float64): 11,
          np.dtype(np.uint8): 2, np.dtype(np.int8): 3}


def tensor_proto(name, arr):
    arr = np.ascontiguousarray(arr)
    out = pw.field_packed_varints(1, arr.shape) if arr.ndim else b""
    out += pw.field_varint(2, _NP_DT[arr.dtype])
    out += pw.field_string(8, name)
    out += pw.field_bytes(9, arr.tobytes())
    return out


def attr_proto(name, val):
    out = pw.field_string(1, name)
    if isinstance(val, float):
        out += pw.field_float(2, val) + pw.field_varint(20, 1)
    elif isinstance(val, int):
        out += pw.field_varint(3, val) + pw.field_varint(20, 2)
    elif isinstance(val, str):
        out += pw.field_bytes(4, val.encode()) + pw.field_varint(20, 3)
    elif isinstance(val, np.ndarray):
        out += pw.field_bytes(5, tensor_proto("", val)) + pw.field_varint(20, 4)
    elif isinstance(val, (list, tuple)) and val and isinstance(val[0], float):
        out += b"".join(pw.field_float(7, v) for v in val) + pw.field_varint(20, 6)
    elif isinstance(val, (list, tuple)):
        out += pw.field_packed_varints(8, val) + pw.field_varint(20, 7)
    else:
        raise TypeError(type(val))
    return out


def node_proto(op_type, inputs, outputs, name="", **attrs):
    out = b"".join(pw.field_string(1, i) for i in inputs)
    out += b"".join(pw.field_string(2, o) for o in outputs)
    out += pw.field_string(3, name or outputs[0] + "_node")
    out += pw.field_string(4, op_type)
    out += b"".join(pw.field_bytes(5, attr_proto(k, v))
                    for k, v in attrs.items())
    return out


def value_info(name, shape):
    dims = b"".join(pw.field_bytes(1, pw.field_varint(1, d)) for d in shape)
    shape_p = pw.field_bytes(2, dims)
    tensor_t = pw.field_varint(1, 1) + shape_p  # elem_type=FLOAT
    type_p = pw.field_bytes(1, tensor_t)
    return pw.field_string(1, name) + pw.field_bytes(2, type_p)


def build_model(nodes, inputs, outputs, initializers):
    """nodes: list of node_proto bytes; inputs/outputs: [(name, shape)];
    initializers: {name: array}."""
    g = b"".join(pw.field_bytes(1, n) for n in nodes)
    g += pw.field_string(2, "test_graph")
    g += b"".join(pw.field_bytes(5, tensor_proto(n, a))
                  for n, a in initializers.items())
    g += b"".join(pw.field_bytes(11, value_info(n, s)) for n, s in inputs)
    g += b"".join(pw.field_bytes(12, value_info(n, s)) for n, s in outputs)
    m = pw.field_varint(1, 8)  # ir_version
    m += pw.field_bytes(7, g)
    m += pw.field_bytes(8, pw.field_string(1, "") + pw.field_varint(2, 13))
    return m


def bert_onnx_model(*, layers: int = 12, batch: int = 1, seq: int = 16,
                    d: int = 768, heads: int = 12, ff: int = 3072,
                    vocab: int = 512, seed: int = 0) -> bytes:
    """A BERT-style encoder ModelProto with exporter-shaped redundancy.

    Every layer re-inlines the attention-mask expansion chain (the CSE
    target), carries Dropout/Identity no-op nodes, computes its scale from
    constants (the fold target), emits the verbatim matmul→scale→mask→
    softmax→matmul attention chain with transpose/reshape head splits (the
    attention-fusion target) and the decomposed erf-gelu FF (the epilogue-
    fusion target). Inputs: ``ids``/``mask`` of shape (batch, seq);
    output: ``y`` of shape (batch, seq, 2)."""
    hd = d // heads
    r = np.random.RandomState(seed)
    nodes = []
    init = {
        "emb": (r.randn(vocab, d) * 0.02).astype(np.float32),
        "pos": (r.randn(seq, d) * 0.02).astype(np.float32),
        "cls_w": (r.randn(d, 2) * 0.02).astype(np.float32),
        "shape_split": np.asarray([batch, seq, heads, hd], np.int64),
        "shape_merge": np.asarray([batch, seq, d], np.int64),
        "one": np.float32(1.0),
        "half": np.float32(0.5),
        "two": np.float32(2.0),
        "neg_big": np.float32(-10000.0),
        "hd_f": np.float32(hd),
        "eps": np.float32(1e-6),
    }

    def n(op, ins, outs, **attrs):
        nodes.append(node_proto(op, ins, outs, **attrs))
        return outs[0]

    def layer_norm(p, x):
        mu = n("ReduceMean", [x], [f"{p}_mu"], axes=[-1], keepdims=1)
        dd = n("Sub", [x, mu], [f"{p}_d"])
        sq = n("Pow", [dd, "two"], [f"{p}_sq"])
        var = n("ReduceMean", [sq], [f"{p}_var"], axes=[-1], keepdims=1)
        ve = n("Add", [var, "eps"], [f"{p}_ve"])
        std = n("Sqrt", [ve], [f"{p}_std"])
        norm = n("Div", [dd, std], [f"{p}_norm"])
        g = n("Mul", [norm, f"{p}_g"], [f"{p}_gn"])
        return n("Add", [g, f"{p}_b"], [f"{p}_out"])

    x = n("Gather", ["emb", "ids"], ["embedded"], axis=0)
    x = n("Add", [x, "pos"], ["h0"])

    for i in range(layers):
        p = f"l{i}"
        for nm, shape in [("wq", (d, d)), ("wk", (d, d)), ("wv", (d, d)),
                          ("wo", (d, d)), ("w1", (d, ff)), ("w2", (ff, d))]:
            init[f"{p}_{nm}"] = (r.randn(*shape) * 0.02).astype(np.float32)
        for nm, size in [("bq", d), ("bk", d), ("bv", d), ("bo", d),
                         ("b1", ff), ("b2", d)]:
            init[f"{p}_{nm}"] = np.zeros(size, np.float32)
        for ln in ("ln1", "ln2"):
            init[f"{p}_{ln}_g"] = np.ones(d, np.float32)
            init[f"{p}_{ln}_b"] = np.zeros(d, np.float32)

        # the attention-mask expansion chain, re-inlined per layer exactly
        # as per-module tracing exporters do — the CSE target
        mu = n("Unsqueeze", ["mask"], [f"{p}_mask_u"], axes=[1, 2])
        mc = n("Cast", [mu], [f"{p}_mask_c"], to=1)
        mi = n("Sub", ["one", mc], [f"{p}_mask_i"])
        pen = n("Mul", [mi, "neg_big"], [f"{p}_mask_pen"])

        h = {}
        for t in ("q", "k", "v"):
            mm = n("MatMul", [x, f"{p}_w{t}"], [f"{p}_{t}mm"])
            a = n("Add", [mm, f"{p}_b{t}"], [f"{p}_{t}"])
            rs = n("Reshape", [a, "shape_split"], [f"{p}_{t}r"])
            h[t] = n("Transpose", [rs], [f"{p}_{t}h"], perm=[0, 2, 1, 3])
        kt = n("Transpose", [h["k"]], [f"{p}_kt"], perm=[0, 1, 3, 2])
        scores = n("MatMul", [h["q"], kt], [f"{p}_scores"])
        scale = n("Sqrt", ["hd_f"], [f"{p}_scale"])  # foldable const chain
        scaled = n("Div", [scores, scale], [f"{p}_scaled"])
        masked = n("Add", [scaled, pen], [f"{p}_masked"])
        probs = n("Softmax", [masked], [f"{p}_probs"], axis=-1)
        probs = n("Dropout", [probs], [f"{p}_probs_d"])  # no-op at inference
        ctx = n("MatMul", [probs, h["v"]], [f"{p}_ctx"])
        ctx = n("Transpose", [ctx], [f"{p}_ctxt"], perm=[0, 2, 1, 3])
        ctx = n("Reshape", [ctx, "shape_merge"], [f"{p}_ctxm"])
        proj = n("MatMul", [ctx, f"{p}_wo"], [f"{p}_projmm"])
        proj = n("Add", [proj, f"{p}_bo"], [f"{p}_proj"])
        proj = n("Dropout", [proj], [f"{p}_proj_d"])
        res = n("Add", [x, proj], [f"{p}_res1"])
        x1 = layer_norm(f"{p}_ln1", res)

        # FF with the decomposed-gelu chain exporters emit
        h1 = n("MatMul", [x1, f"{p}_w1"], [f"{p}_ffmm"])
        h1 = n("Add", [h1, f"{p}_b1"], [f"{p}_ff1"])
        s2 = n("Sqrt", ["two"], [f"{p}_sqrt2"])  # foldable const chain
        e = n("Div", [h1, s2], [f"{p}_ge_div"])
        e = n("Erf", [e], [f"{p}_ge_erf"])
        e = n("Add", [e, "one"], [f"{p}_ge_add"])
        e = n("Mul", [h1, e], [f"{p}_ge_mul"])
        g = n("Mul", [e, "half"], [f"{p}_gelu"])
        h2 = n("MatMul", [g, f"{p}_w2"], [f"{p}_ff2mm"])
        h2 = n("Add", [h2, f"{p}_b2"], [f"{p}_ff2"])
        h2 = n("Dropout", [h2], [f"{p}_ff2_d"])
        res2 = n("Add", [x1, h2], [f"{p}_res2"])
        x = layer_norm(f"{p}_ln2", res2)
        x = n("Identity", [x], [f"{p}_out"])  # exporter block boundary

    logits = n("MatMul", [x, "cls_w"], ["logits"])
    n("Softmax", [logits], ["y"], axis=-1)
    return build_model(nodes, [("ids", (batch, seq)), ("mask", (batch, seq))],
                       [("y", (batch, seq, 2))], init)
