"""Testing utilities — cross-backend consistency (SURVEY §5.2)."""

from deeplearning4j_tpu.testing.consistency import run_all as run_consistency
