"""CPU-vs-TPU consistency suite — SURVEY §5.2's "single most reusable test
idea": the reference cross-checks cuDNN helpers against built-in CPU impls
(CuDNNGradientChecks, ValidateCuDNN); here every case runs on the CPU
backend (the de-facto reference implementation) and on the TPU chip, and the
results must agree at bf16-MXU-aware tolerances.

Run standalone (`python -m deeplearning4j_tpu.testing.consistency`) on a
host with a TPU attached, or via tests/test_tpu_consistency.py (which spawns
this in a subprocess so the unit suite's CPU pin doesn't apply).

Both backends live in one process: JAX registers cpu alongside the TPU
plugin, and ``jax.default_device`` scopes each run.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Case:
    name: str
    make: Callable[[], Any]  # () -> (fn, args); fn pure, jit-able
    rtol: float = 2e-2  # bf16 MXU default
    atol: float = 1e-2
    grad: bool = False  # also compare jax.grad wrt float args


def _cases() -> List[Case]:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.ops import nn_ops, losses as loss_lib, exec_op
    from deeplearning4j_tpu.ops.activations import get_activation

    r = np.random.RandomState(0)

    def arr(*shape):
        return jnp.asarray(r.randn(*shape).astype(np.float32))

    cases: List[Case] = []

    def add(name, fn, *args, rtol=2e-2, atol=1e-2, grad=False):
        cases.append(Case(name, lambda fn=fn, args=args: (fn, args),
                          rtol=rtol, atol=atol, grad=grad))

    x4 = arr(4, 16, 16, 8)
    w = arr(3, 3, 8, 16)
    add("conv2d", lambda x, w: nn_ops.conv2d.fn(x, w, stride=1, padding="same"),
        x4, w, grad=True)
    add("conv2d_strided", lambda x, w: nn_ops.conv2d.fn(x, w, stride=2,
                                                        padding="valid"), x4, w)
    add("depthwise_conv2d",
        lambda x, w: nn_ops.depthwise_conv2d.fn(x, w), x4, arr(3, 3, 8, 1))
    add("deconv2d", lambda x, w: nn_ops.deconv2d.fn(x, w, stride=2),
        arr(2, 8, 8, 4), arr(2, 2, 4, 8))
    add("maxpool2d", lambda x: nn_ops.maxpool2d.fn(x, kernel=2, stride=2), x4,
        grad=True)
    add("avgpool2d", lambda x: nn_ops.avgpool2d.fn(x, kernel=2, stride=2), x4)
    add("batchnorm_infer",
        lambda x, m, v, g, b: nn_ops.batchnorm.fn(x, m, v, g, b),
        x4, arr(8), jnp.abs(arr(8)) + 0.5, arr(8), arr(8))
    add("batchnorm_train",
        lambda x, g, b: nn_ops.batch_norm_train(
            x, g, b, jnp.zeros(8), jnp.ones(8), axis=(0, 1, 2))[0],
        x4, arr(8), arr(8), grad=True)
    add("layer_norm", lambda x, g, b: nn_ops.layer_norm.fn(x, g, b),
        arr(4, 32), arr(32), arr(32), grad=True)
    add("lrn", lambda x: nn_ops.local_response_normalization.fn(x), x4)
    add("dense_gelu", lambda x, w, b: get_activation("gelu")(x @ w + b),
        arr(8, 32), arr(32, 16), arr(16), grad=True)
    add("lstm_cell", lambda x, h, c, wi, wh, b: nn_ops.lstm_cell.fn(
        x, h, c, wi, wh, b)[0],
        arr(4, 8), arr(4, 16), arr(4, 16), arr(8, 64), arr(16, 64), arr(64),
        grad=True)
    add("gru_cell", lambda x, h, wi, wh, bi, bh: nn_ops.gru_cell.fn(
        x, h, wi, wh, bi, bh),
        arr(4, 8), arr(4, 16), arr(8, 48), arr(16, 48), arr(48), arr(48))
    add("softmax", lambda x: jax.nn.softmax(x, axis=-1), arr(8, 64))
    add("log_softmax", lambda x: jax.nn.log_softmax(x, axis=-1), arr(8, 64))
    y = jnp.asarray(np.eye(10, dtype=np.float32)[r.randint(0, 10, 8)])
    add("mcxent", lambda p, y: loss_lib.softmax_cross_entropy_with_logits(p, y),
        arr(8, 10), y, grad=True)
    add("mse", lambda p, y: loss_lib.mse(p, y), arr(8, 10), arr(8, 10))
    add("sigmoid_xent",
        lambda p, y: loss_lib.sigmoid_cross_entropy_with_logits(p, y),
        arr(8, 10), jnp.abs(y))
    add("matmul_big", lambda a, b: a @ b, arr(64, 128), arr(128, 64), grad=True)
    add("erf", lambda x: jax.lax.erf(x), arr(4, 64))
    add("tanh", lambda x: jnp.tanh(x), arr(4, 64))
    add("attention_generic",
        lambda q, k, v: exec_op("dot_product_attention", q, k, v),
        arr(4, 32, 16), arr(4, 32, 16), arr(4, 32, 16), grad=True)
    add("reduce_stats", lambda x: jnp.stack([jnp.mean(x), jnp.var(x),
                                             jnp.max(x), jnp.min(x)]),
        arr(32, 32))
    add("cumsum", lambda x: jnp.cumsum(x, axis=1), arr(8, 32))

    # Pallas flash vs itself across backends (interpret on CPU, Mosaic on TPU)
    from deeplearning4j_tpu.ops.pallas_attention import flash_attention

    add("flash_attention",
        lambda q, k, v: flash_attention(q, k, v, causal=True, block_q=64,
                                        block_k=64),
        arr(4, 128, 32), arr(4, 128, 32), arr(4, 128, 32), grad=True)

    # in-kernel dropout: the hash mask is deterministic in (seed, position),
    # so CPU-interpret and TPU-Mosaic must produce IDENTICAL outputs
    seed = jnp.asarray([[17]], jnp.int32)
    add("flash_attention_dropout",
        lambda q, k, v: flash_attention(q, k, v, None, seed, block_q=64,
                                        block_k=64, dropout_rate=0.2),
        arr(4, 128, 32), arr(4, 128, 32), arr(4, 128, 32), grad=True)

    # ---- new declarable-op families (round 3): one representative
    # CPU-vs-TPU case per family, exercising the same registry path users
    # hit via exec_op ------------------------------------------------------
    idx = jnp.asarray(np.array([5, 0, 2], np.int32))
    add("scatter_add_op", lambda ref, u: exec_op("scatter_add", ref, idx, u),
        arr(6, 8), arr(3, 8))
    seg_ids = jnp.asarray(np.array([0, 0, 1, 2, 2, 2], np.int32))
    add("segment_sum_op", lambda d: exec_op("segment_sum", d, seg_ids,
                                            num_segments=3), arr(6, 16))
    add("top_k_op", lambda x: exec_op("top_k", x, k=4)[0], arr(8, 32))
    add("resize_bilinear_op",
        lambda x: exec_op("resize_bilinear", jnp.abs(x), size=(7, 9)),
        arr(2, 14, 18, 3))
    add("cholesky_op",
        lambda a: exec_op("cholesky", a @ a.T + 8 * jnp.eye(8)), arr(8, 8),
        rtol=5e-2, atol=5e-2)  # decomposition conditioning, not MXU error
    add("solve_op",
        lambda a, b: exec_op("solve", a @ a.T + 8 * jnp.eye(8), b),
        arr(8, 8), arr(8, 2))
    ctc_logits = arr(2, 12, 6)
    ctc_labels = jnp.asarray(np.array([[1, 2, 3], [4, 5, 0]], np.int32))
    add("ctc_loss_op",
        lambda lg: exec_op("ctc_loss", lg, ctc_labels,
                           jnp.asarray(np.array([12, 10], np.int32)),
                           jnp.asarray(np.array([3, 2], np.int32))),
        ctc_logits, grad=True)
    add("cumprod_op", lambda x: exec_op("cumprod", x, axis=1, exclusive=True),
        arr(4, 16))
    add("space_to_depth_op",
        lambda x: exec_op("space_to_depth", x, block_size=2), arr(2, 8, 8, 4))
    add("reduce_logsumexp_op",
        lambda x: exec_op("reduce_logsumexp", x, axis=1), arr(8, 64))

    # full-layer forward: LeNet-sized conv net output
    def lenet_fwd():
        from deeplearning4j_tpu import models

        net = models.LeNet(num_classes=10).init()

        def fn(x):
            return net._forward(net.params, net.net_state, x, None,
                                train=False, rng=None)[0]

        return fn, (jnp.asarray(r.rand(4, 784).astype(np.float32)),)

    cases.append(Case("lenet_forward", lenet_fwd, rtol=2e-2, atol=1e-2))

    # round-5 layers: MoE routing (argmax gates could tie-break differently
    # across backends — the case proves they don't on realistic data) and
    # the dueling-Q aggregation
    def moe_fwd():
        from deeplearning4j_tpu import nn

        b = (nn.builder().seed(3).updater(nn.Sgd(learning_rate=0.1)).list()
             .layer(nn.MoELayer(d_hidden=16, n_experts=4, top_k=2,
                                capacity_factor=2.0, activation="relu"))
             .layer(nn.OutputLayer(n_out=3, activation="softmax",
                                   loss="mcxent")))
        net = nn.MultiLayerNetwork(
            b.set_input_type(nn.InputType.feed_forward(8)).build()).init()

        def fn(x):
            return net._forward(net.params, net.net_state, x, None,
                                train=False, rng=None)[0]

        return fn, (jnp.asarray(r.rand(16, 8).astype(np.float32)),)

    cases.append(Case("moe_layer_forward", moe_fwd, rtol=2e-2, atol=1e-2))

    def dueling_fwd():
        from deeplearning4j_tpu.rl.dqn import dueling_q_net

        net = dueling_q_net(6, 3, hidden=16, seed=2)

        def fn(x):
            return net._forward(net.params, net.net_state, x, None,
                                train=False, rng=None)[0]

        return fn, (jnp.asarray(r.rand(5, 6).astype(np.float32)),)

    cases.append(Case("dueling_q_forward", dueling_fwd, rtol=2e-2, atol=1e-2))
    return cases


def _run_case(case: Case, cpu_dev, tpu_dev) -> List[str]:
    import jax
    import jax.numpy as jnp

    from deeplearning4j_tpu.nn import dtype as DT

    failures: List[str] = []
    fn, args = case.make()

    def run_on(dev, f, args):
        # The cases are float32 — the reference FLOAT policy — so the run
        # inherits that policy's matmul precision ('highest'): f32 math must
        # be f32 math on the MXU, not silently bf16 (round-2 weak #2).
        with jax.default_device(dev), DT.precision_scope("float32"):
            args_d = jax.tree.map(lambda a: jax.device_put(a, dev), args)
            # graftshape: justified(GS001): per-case throwaway jit — each consistency case compiles once per device and is discarded; the harness's own pass/fail report is the attribution
            return jax.tree.map(np.asarray, jax.jit(f)(*args_d))

    try:
        ref = run_on(cpu_dev, fn, args)
        got = run_on(tpu_dev, fn, args)
    except Exception as e:  # a crash is a recorded failure, not an abort
        return [f"{case.name}: FORWARD crash: {type(e).__name__}: {str(e)[:300]}"]
    try:
        jax.tree.map(lambda a, b: np.testing.assert_allclose(
            a, b, rtol=case.rtol, atol=case.atol), ref, got)
    except AssertionError as e:
        failures.append(f"{case.name}: FORWARD mismatch: {str(e)[:300]}")

    if case.grad:
        float_idx = tuple(i for i, a in enumerate(args)
                          if hasattr(a, "dtype") and
                          jnp.issubdtype(a.dtype, jnp.inexact))

        def scalar(f):
            def g(*a):
                out = f(*a)
                leaves = jax.tree.leaves(out)
                return sum(jnp.sum(jnp.cos(l.astype(jnp.float32))) for l in leaves)
            return g

        gfn = jax.grad(scalar(fn), argnums=float_idx)
        try:
            gref = run_on(cpu_dev, gfn, args)
            ggot = run_on(tpu_dev, gfn, args)
        except Exception as e:
            failures.append(
                f"{case.name}: GRADIENT crash: {type(e).__name__}: {str(e)[:300]}")
            return failures
        try:
            jax.tree.map(lambda a, b: np.testing.assert_allclose(
                a, b, rtol=max(case.rtol, 3e-2), atol=max(case.atol, 2e-2)),
                gref, ggot)
        except AssertionError as e:
            failures.append(f"{case.name}: GRADIENT mismatch: {str(e)[:300]}")
    return failures


def run_all(verbose: bool = True) -> Dict[str, Any]:
    """Run every case on CPU and TPU; returns a summary dict and raises
    AssertionError listing all mismatches if any case disagrees."""
    import jax

    tpu_devs = [d for d in jax.devices() if d.platform == "tpu"]
    if not tpu_devs:
        raise RuntimeError("no TPU device visible — consistency suite needs "
                           "the real chip (run without the CPU test pin)")
    cpu_devs = jax.devices("cpu")
    cpu_dev, tpu_dev = cpu_devs[0], tpu_devs[0]

    cases = _cases()
    failures: List[str] = []
    passed = 0
    for case in cases:
        try:
            errs = _run_case(case, cpu_dev, tpu_dev)
        except Exception as e:  # defense in depth: never abort the gate
            errs = [f"{case.name}: CASE crash: {type(e).__name__}: {str(e)[:300]}"]
        if errs:
            failures.extend(errs)
            if verbose:
                print(f"  FAIL {case.name}")
        else:
            passed += 1
            if verbose:
                print(f"  ok   {case.name}" + ("  (+grad)" if case.grad else ""))
    summary = {"cases": len(cases), "passed": passed, "failed": len(failures)}
    if verbose:
        print(f"consistency: {passed}/{len(cases)} cases agree CPU-vs-TPU")
    if failures:
        raise AssertionError("CPU-vs-TPU mismatches:\n" + "\n".join(failures))
    return summary


if __name__ == "__main__":
    import json

    s = run_all()
    print(json.dumps(s))
