"""Process-level configuration: the framework's single documented flag registry.

Reference parity: ND4J's ``ND4JSystemProperties`` / ``ND4JEnvironmentVars``
(nd4j-common, org.nd4j.common.config) and libnd4j's ``Environment`` singleton
(libnd4j/include/system/Environment.h) expose debug/verbose/profiling switches,
memory limits, and backend selection as JVM system properties + env vars.

TPU-native realization: one Python singleton backed by ``DL4J_TPU_*`` env vars,
plus passthroughs to the JAX config plane (``jax_debug_nans``,
``jax_default_matmul_precision``) which play the role the CUDA environment
(CudaEnvironment.getConfiguration()) played in the reference.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, Dict, Optional

_PREFIX = "DL4J_TPU_"


def _env_bool(name: str, default: bool) -> bool:
    v = os.environ.get(_PREFIX + name)
    if v is None:
        return default
    return v.strip().lower() in ("1", "true", "yes", "on")


def _env_str(name: str, default: str) -> str:
    return os.environ.get(_PREFIX + name, default)


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(_PREFIX + name)
    return int(v) if v is not None else default


@dataclasses.dataclass
class Environment:
    """Global runtime flags. Mirrors libnd4j Environment + ND4JSystemProperties.

    Access via :func:`environment` — a process-wide singleton.
    """

    # -- debug plane (libnd4j Environment::setDebug/setVerbose) --------------
    debug: bool = dataclasses.field(default_factory=lambda: _env_bool("DEBUG", False))
    verbose: bool = dataclasses.field(default_factory=lambda: _env_bool("VERBOSE", False))
    # NaN/Inf panic: ND4J OpProfiler checkForNAN/checkForINF analog; routes to
    # jax.config.jax_debug_nans when enabled.
    check_nan: bool = dataclasses.field(default_factory=lambda: _env_bool("CHECK_NAN", False))

    # -- numeric policy -------------------------------------------------------
    # Default floating dtype for parameters (DL4J: DataType.FLOAT default;
    # gradient checks switch to DOUBLE — tests do the same via set_default_dtype).
    default_dtype: str = dataclasses.field(default_factory=lambda: _env_str("DTYPE", "float32"))
    # Compute dtype for matmul/conv-heavy paths; bfloat16 keeps the MXU fed.
    compute_dtype: str = dataclasses.field(default_factory=lambda: _env_str("COMPUTE_DTYPE", "bfloat16"))
    matmul_precision: str = dataclasses.field(
        default_factory=lambda: _env_str("MATMUL_PRECISION", "default")
    )

    # -- layout policy (SURVEY §8.3 hard part 3) ------------------------------
    # Reference is NCHW-default (cuDNN heritage). Internally we are NHWC for
    # TPU-friendly layouts; NCHW is accepted at the API edge and transposed.
    prefer_nhwc: bool = dataclasses.field(default_factory=lambda: _env_bool("PREFER_NHWC", True))

    # -- profiling plane (OpProfiler / ProfilingListener) ---------------------
    profiling: bool = dataclasses.field(default_factory=lambda: _env_bool("PROFILING", False))
    profile_dir: str = dataclasses.field(default_factory=lambda: _env_str("PROFILE_DIR", "/tmp/dl4j_tpu_profile"))

    # -- platform-helper selection (cuDNN helper analog, SURVEY §3.1) ---------
    # "auto": pick Pallas kernels on TPU where registered, XLA elsewhere.
    # "xla": force XLA lowering. "pallas": force custom kernels where available.
    helper_mode: str = dataclasses.field(default_factory=lambda: _env_str("HELPERS", "auto"))
    log_helper_selection: bool = dataclasses.field(
        default_factory=lambda: _env_bool("LOG_HELPERS", False)
    )

    # -- distributed ----------------------------------------------------------
    coordinator_address: Optional[str] = dataclasses.field(
        default_factory=lambda: os.environ.get(_PREFIX + "COORDINATOR") or None
    )
    num_processes: int = dataclasses.field(default_factory=lambda: _env_int("NUM_PROCESSES", 1))
    process_id: int = dataclasses.field(default_factory=lambda: _env_int("PROCESS_ID", 0))

    def apply_jax_config(self) -> None:
        """Push flags into the JAX config plane. Call once at startup."""
        import jax

        if self.check_nan:
            jax.config.update("jax_debug_nans", True)
        if self.matmul_precision != "default":
            jax.config.update("jax_default_matmul_precision", self.matmul_precision)
        if self.default_dtype == "float64":
            jax.config.update("jax_enable_x64", True)

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


_INSTANCE: Optional[Environment] = None


def environment() -> Environment:
    """The process-wide Environment singleton (libnd4j Environment::getInstance)."""
    global _INSTANCE
    if _INSTANCE is None:
        _INSTANCE = Environment()
    return _INSTANCE


def reset_environment() -> Environment:
    """Re-read env vars (tests only)."""
    global _INSTANCE
    _INSTANCE = Environment()
    return _INSTANCE
