"""deeplearning4j_tpu — a TPU-native deep-learning framework with the
capabilities of Deeplearning4j (reference: Willdata/deeplearning4j).

Architecture (SURVEY.md §8): whole-model training steps compile to single XLA
computations via jax/pjit; the reference's per-op JNI dispatch, workspaces,
and Aeron gradient mesh are replaced by XLA fusion, buffer donation, and
ICI/DCN collectives emitted from sharding annotations.
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.environment import environment, Environment
