"""deeplearning4j_tpu — a TPU-native deep-learning framework with the
capabilities of Deeplearning4j (reference: Willdata/deeplearning4j).

Architecture (SURVEY.md §8): whole-model training steps compile to single XLA
computations via jax/pjit; the reference's per-op JNI dispatch, workspaces,
and Aeron gradient mesh are replaced by XLA fusion, buffer donation, and
ICI/DCN collectives emitted from sharding annotations.

Package map (reference layer in parens — SURVEY §2):
  ops/        tensor-op catalog + platform-helper table   (ND4J + libnd4j)
  nn/         layer configs, MultiLayerNetwork, updaters,
              listeners, serde, early stopping, transfer  (DL4J-nn/-core)
  autodiff/   SameDiff-style graph engine + gradcheck     (nd4j autodiff)
  models/     zoo (LeNet…ResNet-50, UNet) + BERT          (zoo + SameDiff-BERT)
  parallel/   mesh DP/TP, ring attention, checkpoints,
              multi-host bootstrap                        (scaleout + param-server)
  datasets/   DataSet/iterators/normalizers, images       (nd4j dataset + datavec-image)
  datavec/    schema'd transform DSL, CSV readers         (datavec-api)
  nlp/        wordpiece/BERT pipeline, word2vec           (deeplearning4j-nlp)
  rl/         DQN / actor-critic                          (rl4j)
  eval/       Evaluation/ROC/regression                   (nd4j evaluation)
  imports/    TF frozen-graph importer                    (samediff-import)
  native_ops/ C++ host-side codecs via ctypes             (libnd4j native role)
  observe/    unified runtime telemetry: metrics registry,
              span tracer, recompile ledger               (listener/profiler fragments, unified)
  utils/      profiling (chrome trace), UI stats shim     (OpProfiler/UI)
  arbiter     hyperparameter search                       (arbiter-core)
"""

__version__ = "0.1.0"

from deeplearning4j_tpu.environment import environment, Environment
