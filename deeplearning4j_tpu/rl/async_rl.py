"""A3C-equivalent parallel-environment training + MDP adapters.

Reference parity:
  * rl4j-core async/** — AsyncLearning, A3CDiscrete(Dense/Conv),
    AsyncGlobal + per-thread AsyncThread workers doing hogwild updates.
  * rl4j-gym org.deeplearning4j.gym.GymEnv — the gym-API MDP adapter.
  * HistoryProcessor.java — frame skip/stack preprocessing for pixel MDPs.

TPU-native realization (documented divergence, same as the sync
ActorCritic in rl/dqn.py): the reference's N async hogwild CPU threads
become N SYNCHRONOUS parallel environments whose observations are stacked
into ONE batch — policy/value forwards and the gradient step run as a
single jitted computation over the (n_envs·n_steps) batch, which is how
the same worker-parallelism maps onto a single accelerator (big batches
on the MXU instead of lock-free tiny updates)."""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork, apply_layer_updates
from deeplearning4j_tpu.rl.dqn import MDP


class GymMDP(MDP):
    """GymEnv analog: wraps any gym-style env (reset() → obs | (obs, info);
    step(a) → (obs, reward, done[, truncated], info]) into the rl4j MDP
    interface."""

    def __init__(self, env: Any, obs_size: Optional[int] = None,
                 num_actions: Optional[int] = None):
        self.env = env
        self._obs_size = obs_size
        self._num_actions = num_actions

    def reset(self) -> np.ndarray:
        out = self.env.reset()
        obs = out[0] if isinstance(out, tuple) else out
        return np.asarray(obs, np.float32).ravel()

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        out = self.env.step(int(action))
        if len(out) == 5:  # gymnasium: obs, reward, terminated, truncated, info
            obs, reward, term, trunc, _ = out
            done = bool(term or trunc)
        else:  # classic gym: obs, reward, done, info
            obs, reward, done = out[0], out[1], bool(out[2])
        return np.asarray(obs, np.float32).ravel(), float(reward), done

    @property
    def num_actions(self) -> int:
        if self._num_actions is not None:
            return self._num_actions
        return int(self.env.action_space.n)

    @property
    def obs_size(self) -> int:
        if self._obs_size is not None:
            return self._obs_size
        space = self.env.observation_space
        return int(np.prod(space.shape))


class HistoryProcessor:
    """HistoryProcessor.java analog: skip frames and stack the last
    ``history_length`` kept frames into one observation (the DQN-on-pixels
    preprocessing). ``record`` every raw frame; ``get_history`` returns the
    (history_length, *frame_shape) stack (zero-padded until warm)."""

    def __init__(self, history_length: int = 4, skip_frames: int = 4):
        self.history_length = history_length
        self.skip_frames = max(1, skip_frames)
        self._frames: List[np.ndarray] = []
        self._count = 0

    def reset(self) -> None:
        self._frames = []
        self._count = 0

    def record(self, frame: np.ndarray) -> bool:
        """Returns True when the frame was KEPT (every skip_frames-th)."""
        keep = self._count % self.skip_frames == 0
        self._count += 1
        if keep:
            self._frames.append(np.asarray(frame, np.float32))
            if len(self._frames) > self.history_length:
                self._frames.pop(0)
        return keep

    def get_history(self) -> np.ndarray:
        if not self._frames:
            raise ValueError("record() at least one frame first")
        shape = self._frames[0].shape
        pad = self.history_length - len(self._frames)
        frames = [np.zeros(shape, np.float32)] * pad + self._frames
        return np.stack(frames)


class A3CDiscrete:
    """A3CDiscrete analog: n_envs parallel MDPs, batched advantage
    actor-critic updates (one jitted step per rollout)."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 policy_net: MultiLayerNetwork,
                 value_net: MultiLayerNetwork, n_envs: int = 8,
                 n_steps: int = 8, gamma: float = 0.99,
                 entropy_coef: float = 0.01, seed: int = 0):
        self.envs = [mdp_factory() for _ in range(n_envs)]
        self.policy_net = policy_net
        self.value_net = value_net
        self.n_envs = n_envs
        self.n_steps = n_steps
        self.gamma = gamma
        self.entropy_coef = entropy_coef
        self.rng = np.random.RandomState(seed)
        self._obs = [e.reset() for e in self.envs]
        self._ep_rewards = np.zeros(n_envs)
        self.episode_rewards: List[float] = []
        # graftshape: justified(GS001): actor-side policy forward — n_envs-shaped, fixed for the worker's lifetime
        self._policy_fwd = jax.jit(
            lambda p, s: policy_net._forward(p, policy_net.net_state, s, None,
                                             train=False, rng=None)[0])
        # graftshape: justified(GS001): actor-side value forward — n_envs-shaped, fixed for the worker's lifetime
        self._value_fwd = jax.jit(
            lambda p, s: value_net._forward(p, value_net.net_state, s, None,
                                            train=False, rng=None)[0][:, 0])
        self._step = self._make_step()

    def _make_step(self):
        pnet, vnet = self.policy_net, self.value_net
        ent_c = self.entropy_coef

        def step_fn(p_params, v_params, p_opt, v_opt, step, s, a, ret):
            def v_loss(vp):
                v = vnet._forward(vp, vnet.net_state, s, None, train=False,
                                  rng=None)[0][:, 0]
                return jnp.mean((ret - v) ** 2)

            v_l, v_grads = jax.value_and_grad(v_loss)(v_params)
            v_now = vnet._forward(v_params, vnet.net_state, s, None,
                                  train=False, rng=None)[0][:, 0]
            adv = jax.lax.stop_gradient(ret - v_now)

            def p_loss(pp):
                probs = pnet._forward(pp, pnet.net_state, s, None,
                                      train=False, rng=None)[0]
                logp = jnp.log(probs + 1e-8)
                chosen = jnp.take_along_axis(logp, a[:, None], axis=1)[:, 0]
                entropy = -jnp.sum(probs * logp, axis=1)
                return -jnp.mean(chosen * adv + ent_c * entropy)

            p_l, p_grads = jax.value_and_grad(p_loss)(p_params)
            pu = apply_layer_updates(pnet.conf,
                                     zip(p_params, p_grads, p_opt,
                                         pnet.updaters, pnet.conf.layers),
                                     step, pnet._normalize_gradient)
            vu = apply_layer_updates(vnet.conf,
                                     zip(v_params, v_grads, v_opt,
                                         vnet.updaters, vnet.conf.layers),
                                     step, vnet._normalize_gradient)
            return ([p for p, _ in pu], [st for _, st in pu],
                    [p for p, _ in vu], [st for _, st in vu], p_l + v_l)

        # graftshape: justified(GS001): A2C fused update — rollout geometry (n_envs x n_steps) is fixed config
        return jax.jit(step_fn, donate_argnums=(0, 1, 2, 3))

    def _rollout(self):
        """Step all envs n_steps with ONE batched policy forward per step."""
        obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
        for _ in range(self.n_steps):
            batch = jnp.asarray(np.stack(self._obs))
            probs = np.asarray(self._policy_fwd(self.policy_net.params, batch))
            acts = [int(self.rng.choice(len(p), p=p / p.sum())) for p in probs]
            obs_buf.append(np.stack(self._obs))
            act_buf.append(acts)
            rews, dones = [], []
            for k, env in enumerate(self.envs):
                nxt, r, d = env.step(acts[k])
                self._ep_rewards[k] += r
                if d:
                    self.episode_rewards.append(self._ep_rewards[k])
                    self._ep_rewards[k] = 0.0
                    nxt = env.reset()
                self._obs[k] = nxt
                rews.append(r)
                dones.append(d)
            rew_buf.append(rews)
            done_buf.append(dones)
        return (np.asarray(obs_buf, np.float32), np.asarray(act_buf, np.int32),
                np.asarray(rew_buf, np.float32), np.asarray(done_buf))

    def train_batch(self, step: int) -> float:
        """One rollout + one batched update; returns the combined loss."""
        obs, acts, rews, dones = self._rollout()
        boot = np.asarray(self._value_fwd(
            self.value_net.params, jnp.asarray(np.stack(self._obs))))
        rets = np.zeros_like(rews)
        running = boot
        for t in reversed(range(self.n_steps)):
            running = rews[t] + self.gamma * running * (~dones[t])
            rets[t] = running
        flat = lambda x: x.reshape((-1,) + x.shape[2:])
        (self.policy_net.params, p_opt, self.value_net.params, v_opt,
         loss) = self._step(self.policy_net.params, self.value_net.params,
                            self.policy_net.opt_state,
                            self.value_net.opt_state,
                            jnp.asarray(step, jnp.int32),
                            jnp.asarray(flat(obs)), jnp.asarray(flat(acts)),
                            jnp.asarray(flat(rets)))
        self.policy_net.opt_state = p_opt
        self.value_net.opt_state = v_opt
        return float(loss)

    def train(self, batches: int = 100) -> List[float]:
        return [self.train_batch(i) for i in range(batches)]


class AsyncNStepQLearningDiscrete:
    """AsyncNStepQLearningDiscrete analog (RL4J async/nstep/discrete):
    n_envs parallel MDPs, eps-greedy behavior from the online Q-net, n-step
    bootstrapped targets from a periodically-synced target net, one batched
    MSE update per rollout (the worker-thread gradient exchange of the
    reference collapses into one SPMD step, like A3C above)."""

    def __init__(self, mdp_factory: Callable[[], MDP],
                 q_net: MultiLayerNetwork, n_envs: int = 8,
                 n_steps: int = 5, gamma: float = 0.99,
                 target_update_freq: int = 40,
                 eps_start: float = 1.0, eps_min: float = 0.1,
                 eps_anneal_batches: int = 200, seed: int = 0):
        self.envs = [mdp_factory() for _ in range(n_envs)]
        self.net = q_net
        self.n_envs = n_envs
        self.n_steps = n_steps
        self.gamma = gamma
        self.target_update_freq = target_update_freq
        self.eps_start, self.eps_min = eps_start, eps_min
        self.eps_anneal = eps_anneal_batches
        self.rng = np.random.RandomState(seed)
        self._obs = [e.reset() for e in self.envs]
        self._ep_rewards = np.zeros(n_envs)
        self.episode_rewards: List[float] = []
        self.target_params = jax.tree.map(jnp.asarray, q_net.params)
        # graftshape: justified(GS001): async-DQN online forward — n_envs-shaped, fixed for the worker's lifetime
        self._fwd = jax.jit(
            lambda p, s: q_net._forward(p, q_net.net_state, s, None,
                                        train=False, rng=None)[0])
        self._step = self._make_step()
        self._batches = 0

    def _eps(self) -> float:
        f = min(1.0, self._batches / max(1, self.eps_anneal))
        return self.eps_start + (self.eps_min - self.eps_start) * f

    def _make_step(self):
        net = self.net

        def step_fn(params, opt_state, step, s, a, ret):
            def loss_of(p):
                q = net._forward(p, net.net_state, s, None, train=False,
                                 rng=None)[0]
                q_sa = jnp.take_along_axis(q, a[:, None], axis=1)[:, 0]
                return jnp.mean((q_sa - ret) ** 2)

            loss, grads = jax.value_and_grad(loss_of)(params)
            upd = apply_layer_updates(
                net.conf, zip(params, grads, opt_state, net.updaters,
                              net.conf.layers),
                step, net._normalize_gradient)
            return ([p for p, _ in upd], [st for _, st in upd], loss)

        # graftshape: justified(GS001): async-DQN update step — replay minibatch shape is fixed config
        return jax.jit(step_fn)

    def train_batch(self) -> float:
        eps = self._eps()
        obs_buf, act_buf, rew_buf, done_buf = [], [], [], []
        for _ in range(self.n_steps):
            batch = jnp.asarray(np.stack(self._obs))
            q = np.asarray(self._fwd(self.net.params, batch))
            acts = [int(self.rng.randint(q.shape[1]))
                    if self.rng.rand() < eps else int(np.argmax(q[k]))
                    for k in range(self.n_envs)]
            obs_buf.append(np.stack(self._obs))
            act_buf.append(acts)
            rews, dones = [], []
            for k, env in enumerate(self.envs):
                nxt, r, d = env.step(acts[k])
                self._ep_rewards[k] += r
                if d:
                    self.episode_rewards.append(self._ep_rewards[k])
                    self._ep_rewards[k] = 0.0
                    nxt = env.reset()
                self._obs[k] = nxt
                rews.append(r)
                dones.append(d)
            rew_buf.append(rews)
            done_buf.append(dones)
        obs = np.asarray(obs_buf, np.float32)
        acts = np.asarray(act_buf, np.int32)
        rews = np.asarray(rew_buf, np.float32)
        dones = np.asarray(done_buf)
        # n-step returns bootstrapped from the TARGET net's max-Q
        q_boot = np.asarray(self._fwd(self.target_params,
                                      jnp.asarray(np.stack(self._obs))))
        running = q_boot.max(axis=1)
        rets = np.zeros_like(rews)
        for t in reversed(range(self.n_steps)):
            running = rews[t] + self.gamma * running * (~dones[t])
            rets[t] = running
        flat = lambda x: x.reshape((-1,) + x.shape[2:])
        self.net.params, self.net.opt_state, loss = self._step(
            self.net.params, self.net.opt_state,
            jnp.asarray(self._batches, jnp.int32),
            jnp.asarray(flat(obs)), jnp.asarray(flat(acts)),
            jnp.asarray(flat(rets)))
        self._batches += 1
        if self._batches % self.target_update_freq == 0:
            self.target_params = jax.tree.map(jnp.asarray, self.net.params)
        return float(loss)

    def train(self, batches: int = 100) -> List[float]:
        return [self.train_batch() for _ in range(batches)]

    def play(self, mdp: MDP, max_steps: int = 200) -> float:
        obs = mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            q = np.asarray(self._fwd(self.net.params,
                                     jnp.asarray(obs[None])))[0]
            obs, r, done = mdp.step(int(np.argmax(q)))
            total += r
            if done:
                break
        return total
