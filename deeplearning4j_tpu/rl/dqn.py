"""Reinforcement learning — the RL4J role.

Reference parity (SURVEY §3.4):
  * rl4j-core learning/sync/qlearning/discrete/QLearningDiscrete.java — DQN
    with experience replay, target network, ε-greedy annealing, double-DQN
    flag, reward clipping.
  * policy/* — EpsGreedy, BoltzmannPolicy (policies over a Q-network).
  * MDP interface (rl4j-api): reset/step/isDone/actionSpace.
  * learning/async/a3c — async advantage actor-critic: realized here as a
    SYNCHRONOUS batched advantage actor-critic (`ActorCritic`): hogwild
    thread-async has no TPU analog; batched sync updates are the idiomatic
    replacement (documented divergence, same objective).

Q/policy networks are MultiLayerNetworks; the TD/AC update is its own fused
jitted step over the network's params (replay minibatch in, params out).
"""

from __future__ import annotations

import dataclasses
import random
from collections import deque
from typing import Any, Callable, Deque, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork


class MDP:
    """rl4j-api MDP interface."""

    def reset(self) -> np.ndarray:
        raise NotImplementedError

    def step(self, action: int) -> Tuple[np.ndarray, float, bool]:
        """returns (observation, reward, done)."""
        raise NotImplementedError

    @property
    def num_actions(self) -> int:
        raise NotImplementedError

    @property
    def obs_size(self) -> int:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Policies (rl4j policy/*)
# ---------------------------------------------------------------------------


class EpsGreedy:
    """EpsGreedy.java: anneal ε from eps_start to eps_min over anneal_steps."""

    def __init__(self, eps_start: float = 1.0, eps_min: float = 0.05,
                 anneal_steps: int = 1000, seed: int = 0):
        self.eps_start = eps_start
        self.eps_min = eps_min
        self.anneal = anneal_steps
        self.rng = np.random.RandomState(seed)
        self.step_count = 0

    def epsilon(self) -> float:
        f = min(1.0, self.step_count / max(1, self.anneal))
        return self.eps_start + f * (self.eps_min - self.eps_start)

    def next_action(self, q_values: np.ndarray) -> int:
        self.step_count += 1
        if self.rng.rand() < self.epsilon():
            return int(self.rng.randint(len(q_values)))
        return int(np.argmax(q_values))


class BoltzmannPolicy:
    """BoltzmannQPolicy.java: sample ∝ softmax(Q/T)."""

    def __init__(self, temperature: float = 1.0, seed: int = 0):
        self.temperature = temperature
        self.rng = np.random.RandomState(seed)

    def next_action(self, q_values: np.ndarray) -> int:
        z = q_values / max(self.temperature, 1e-6)
        z = z - z.max()
        p = np.exp(z) / np.exp(z).sum()
        return int(self.rng.choice(len(q_values), p=p))


class GreedyPolicy:
    def next_action(self, q_values: np.ndarray) -> int:
        return int(np.argmax(q_values))


# ---------------------------------------------------------------------------
# Replay buffer (learning/sync/ExpReplay.java)
# ---------------------------------------------------------------------------


class ExpReplay:
    def __init__(self, max_size: int = 10000, batch_size: int = 32, seed: int = 0):
        self.buf: Deque = deque(maxlen=max_size)
        self.batch_size = batch_size
        self.rng = random.Random(seed)

    def store(self, transition) -> None:
        self.buf.append(transition)

    def sample(self):
        batch = self.rng.sample(list(self.buf), min(self.batch_size, len(self.buf)))
        s, a, r, s2, d = zip(*batch)
        return (np.stack(s).astype(np.float32), np.asarray(a, np.int32),
                np.asarray(r, np.float32), np.stack(s2).astype(np.float32),
                np.asarray(d, np.float32))

    def __len__(self):
        return len(self.buf)


# ---------------------------------------------------------------------------
# DQN (QLearningDiscrete)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class QLearningConfiguration:
    """QLearning.QLConfiguration analog."""

    gamma: float = 0.99
    batch_size: int = 32
    target_update_freq: int = 100
    start_size: int = 64
    max_replay: int = 10000
    double_dqn: bool = True
    reward_clip: Optional[float] = None
    eps_start: float = 1.0
    eps_min: float = 0.05
    eps_anneal_steps: int = 1000
    seed: int = 0


class QLearningDiscrete:
    """QLearningDiscrete.java: DQN trainer over an MDP."""

    def __init__(self, mdp: MDP, net: MultiLayerNetwork,
                 config: QLearningConfiguration = QLearningConfiguration()):
        self.mdp = mdp
        self.net = net
        self.cfg = config
        self.policy = EpsGreedy(config.eps_start, config.eps_min,
                                config.eps_anneal_steps, config.seed)
        self.replay = ExpReplay(config.max_replay, config.batch_size, config.seed)
        self.target_params = jax.tree.map(jnp.asarray, net.params)
        self._td_step = self._make_td_step()
        self.total_steps = 0
        self.episode_rewards: List[float] = []

    def _make_td_step(self):
        cfg = self.cfg
        net = self.net

        def td_step(params, target_params, opt_state, step, s, a, r, s2, d):
            def loss_of(p):
                q = net._forward(p, net.net_state, s, None, train=False, rng=None)[0]
                q_sa = jnp.take_along_axis(q, a[:, None], axis=1)[:, 0]
                q_next_target = net._forward(target_params, net.net_state, s2,
                                             None, train=False, rng=None)[0]
                if cfg.double_dqn:
                    q_next_online = net._forward(p, net.net_state, s2, None,
                                                 train=False, rng=None)[0]
                    a_star = jnp.argmax(q_next_online, axis=1)
                    q_next = jnp.take_along_axis(
                        q_next_target, a_star[:, None], axis=1)[:, 0]
                else:
                    q_next = jnp.max(q_next_target, axis=1)
                target = r + cfg.gamma * (1.0 - d) * jax.lax.stop_gradient(q_next)
                return jnp.mean((q_sa - target) ** 2)

            loss, grads = jax.value_and_grad(loss_of)(params)
            from deeplearning4j_tpu.nn.multilayer import apply_layer_updates

            updated = apply_layer_updates(
                net.conf, zip(params, grads, opt_state, net.updaters, net.conf.layers),
                step, net._normalize_gradient)
            return ([p for p, _ in updated], [s_ for _, s_ in updated], loss)

        # no donation: params and target_params alias right after a target
        # sync (donating an aliased buffer is an XLA error), and RL nets are
        # small enough that the copy is irrelevant
        # graftshape: justified(GS001): TD step over a fixed-size replay minibatch — one compile per run
        return jax.jit(td_step)

    def q_values(self, obs: np.ndarray) -> np.ndarray:
        return self.net.output(obs[None].astype(np.float32))[0]

    def train_episode(self, max_steps: int = 200) -> float:
        obs = self.mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            action = self.policy.next_action(self.q_values(obs))
            obs2, reward, done = self.mdp.step(action)
            total += reward
            r = reward
            if self.cfg.reward_clip:
                r = float(np.clip(r, -self.cfg.reward_clip, self.cfg.reward_clip))
            self.replay.store((obs, action, r, obs2, float(done)))
            obs = obs2
            self.total_steps += 1
            if len(self.replay) >= self.cfg.start_size:
                s, a, r_, s2, d = self.replay.sample()
                self.net.params, self.net.opt_state, _ = self._td_step(
                    self.net.params, self.target_params, self.net.opt_state,
                    jnp.asarray(self.net.iteration_count, jnp.int32),
                    jnp.asarray(s), jnp.asarray(a), jnp.asarray(r_),
                    jnp.asarray(s2), jnp.asarray(d))
                self.net.iteration_count += 1
            if self.total_steps % self.cfg.target_update_freq == 0:
                self.target_params = jax.tree.map(jnp.asarray, self.net.params)
            if done:
                break
        self.episode_rewards.append(total)
        return total

    def train(self, episodes: int, max_steps: int = 200) -> List[float]:
        return [self.train_episode(max_steps) for _ in range(episodes)]

    def play(self, max_steps: int = 200) -> float:
        """Greedy rollout (Policy.play)."""
        policy = GreedyPolicy()
        obs = self.mdp.reset()
        total = 0.0
        for _ in range(max_steps):
            obs, r, done = self.mdp.step(policy.next_action(self.q_values(obs)))
            total += r
            if done:
                break
        return total


# ---------------------------------------------------------------------------
# Advantage actor-critic (the A3C-equivalent, synchronous)
# ---------------------------------------------------------------------------


class ActorCritic:
    """Batched synchronous advantage actor-critic (A3CDiscrete-equivalent;
    async hogwild replaced by batched sync updates — documented divergence)."""

    def __init__(self, mdp: MDP, policy_net: MultiLayerNetwork,
                 value_net: MultiLayerNetwork, gamma: float = 0.99,
                 n_steps: int = 16, entropy_coef: float = 0.01, seed: int = 0):
        self.mdp = mdp
        self.policy_net = policy_net
        self.value_net = value_net
        self.gamma = gamma
        self.n_steps = n_steps
        self.entropy_coef = entropy_coef
        self.rng = np.random.RandomState(seed)
        self._step = self._make_step()
        self.episode_rewards: List[float] = []
        self._obs = None
        self._ep_reward = 0.0

    def _make_step(self):
        pnet, vnet = self.policy_net, self.value_net
        ent_c = self.entropy_coef

        def step_fn(p_params, v_params, p_opt, v_opt, step, s, a, ret):
            def v_loss(vp):
                v = vnet._forward(vp, vnet.net_state, s, None, train=False, rng=None)[0][:, 0]
                return jnp.mean((ret - v) ** 2)

            v_l, v_grads = jax.value_and_grad(v_loss)(v_params)
            v_now = vnet._forward(v_params, vnet.net_state, s, None,
                                  train=False, rng=None)[0][:, 0]
            adv = jax.lax.stop_gradient(ret - v_now)

            def p_loss(pp):
                probs = pnet._forward(pp, pnet.net_state, s, None, train=False, rng=None)[0]
                logp = jnp.log(probs + 1e-8)
                chosen = jnp.take_along_axis(logp, a[:, None], axis=1)[:, 0]
                entropy = -jnp.sum(probs * logp, axis=1)
                return -jnp.mean(chosen * adv + ent_c * entropy)

            p_l, p_grads = jax.value_and_grad(p_loss)(p_params)
            from deeplearning4j_tpu.nn.multilayer import apply_layer_updates

            pu = apply_layer_updates(pnet.conf, zip(p_params, p_grads, p_opt,
                                                    pnet.updaters, pnet.conf.layers),
                                     step, pnet._normalize_gradient)
            vu = apply_layer_updates(vnet.conf, zip(v_params, v_grads, v_opt,
                                                    vnet.updaters, vnet.conf.layers),
                                     step, vnet._normalize_gradient)
            return ([p for p, _ in pu], [s_ for _, s_ in pu],
                    [p for p, _ in vu], [s_ for _, s_ in vu], p_l + v_l)

        # graftshape: justified(GS001): double-DQN fused step — replay minibatch shape is fixed config, one compile per run
        return jax.jit(step_fn, donate_argnums=(0, 1, 2, 3))

    def _action(self, obs) -> int:
        probs = self.policy_net.output(obs[None].astype(np.float32))[0]
        probs = np.clip(probs, 1e-8, 1.0)
        probs = probs / probs.sum()
        return int(self.rng.choice(len(probs), p=probs))

    def train_steps(self, total_steps: int, max_episode_steps: int = 200) -> None:
        if self._obs is None:
            self._obs = self.mdp.reset()
        steps_done = 0
        ep_steps = 0
        while steps_done < total_steps:
            states, actions, rewards, cuts = [], [], [], []
            for _ in range(self.n_steps):
                a = self._action(self._obs)
                obs2, r, done = self.mdp.step(a)
                states.append(self._obs)
                actions.append(a)
                rewards.append(r)
                self._ep_reward += r
                self._obs = obs2
                steps_done += 1
                ep_steps += 1
                truncated = ep_steps >= max_episode_steps
                # a truncation reset must also CUT the return recurrence, or
                # the new episode's rewards leak into the old one's targets
                cuts.append(done or truncated)
                if done or truncated:
                    self.episode_rewards.append(self._ep_reward)
                    self._ep_reward = 0.0
                    ep_steps = 0
                    self._obs = self.mdp.reset()
            # n-step returns (bootstrap with V(s_T) unless the chain was cut)
            v_last = float(self.value_net.output(
                self._obs[None].astype(np.float32))[0, 0])
            ret = v_last if not cuts[-1] else 0.0
            returns = []
            for r, c in zip(reversed(rewards), reversed(cuts)):
                ret = r + self.gamma * ret * (1.0 - float(c))
                returns.append(ret)
            returns.reverse()
            (self.policy_net.params, self.policy_net.opt_state,
             self.value_net.params, self.value_net.opt_state, _) = self._step(
                self.policy_net.params, self.value_net.params,
                self.policy_net.opt_state, self.value_net.opt_state,
                jnp.asarray(self.policy_net.iteration_count, jnp.int32),
                jnp.asarray(np.stack(states).astype(np.float32)),
                jnp.asarray(np.asarray(actions, np.int32)),
                jnp.asarray(np.asarray(returns, np.float32)))
            self.policy_net.iteration_count += 1


def dueling_q_net(obs_size: int, n_actions: int, hidden: int = 64,
                  seed: int = 0, learning_rate: float = 5e-3):
    """Dueling-DQN network builder (reference QLearning dueling config):
    shared trunk → nn.DuelingQLayer head (Q = V + A − mean A). Drop-in for
    the plain Q-network in QLearningDiscrete."""
    from deeplearning4j_tpu import nn

    return MultiLayerNetwork(
        nn.builder().seed(seed).updater(nn.Adam(learning_rate=learning_rate))
        .list()
        .layer(nn.DenseLayer(n_out=hidden, activation="relu"))
        .layer(nn.DuelingQLayer(n_actions=n_actions, activation="identity"))
        .set_input_type(nn.InputType.feed_forward(obs_size)).build()
    ).init()
