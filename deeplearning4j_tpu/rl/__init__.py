"""RL — DQN/actor-critic (RL4J role, SURVEY §3.4)."""

from deeplearning4j_tpu.rl.dqn import (
    MDP,
    EpsGreedy,
    BoltzmannPolicy,
    GreedyPolicy,
    ExpReplay,
    QLearningConfiguration,
    QLearningDiscrete,
    ActorCritic,
)
from deeplearning4j_tpu.rl.async_rl import (
    A3CDiscrete,
    GymMDP,
    HistoryProcessor,
)
