"""RL — DQN/actor-critic (RL4J role, SURVEY §3.4)."""

from deeplearning4j_tpu.rl.dqn import (
    MDP,
    EpsGreedy,
    BoltzmannPolicy,
    GreedyPolicy,
    ExpReplay,
    QLearningConfiguration,
    QLearningDiscrete,
    ActorCritic,
)
