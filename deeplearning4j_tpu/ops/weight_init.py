"""Weight initialization — parity with DL4J's WeightInit enum.

Reference: org.deeplearning4j.nn.weights.WeightInit + WeightInitUtil
(deeplearning4j-nn). fanIn/fanOut semantics follow the reference: for a dense
W[in, out], fanIn=in, fanOut=out; for conv kernels [kH,kW,in,out],
fanIn=kH*kW*in, fanOut=kH*kW*out.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp


def _fans(shape: Sequence[int]) -> Tuple[float, float]:
    if len(shape) == 1:
        return float(shape[0]), float(shape[0])
    if len(shape) == 2:
        return float(shape[0]), float(shape[1])
    receptive = 1
    for s in shape[:-2]:
        receptive *= s
    return float(receptive * shape[-2]), float(receptive * shape[-1])


def init_weights(key, shape, scheme: str = "xavier", *, dtype=jnp.float32,
                 distribution=None, gain: float = 1.0):
    """Initialize an array per a WeightInit scheme name."""
    scheme = str(scheme).lower()
    fan_in, fan_out = _fans(shape)
    shape = tuple(int(s) for s in shape)

    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("identity init requires square 2-D shape")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "normal":
        # Reference NORMAL: N(0, 1/sqrt(fanIn))
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "uniform":
        # Reference UNIFORM: U(-a, a), a = 1/sqrt(fanIn)
        a = 1.0 / math.sqrt(fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier":
        # Reference XAVIER: N(0, 2/(fanIn+fanOut))
        std = math.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "xavier_uniform":
        a = math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "xavier_fan_in":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "xavier_legacy":
        std = math.sqrt(1.0 / (fan_in + fan_out))
        return std * jax.random.normal(key, shape, dtype)
    if scheme == "relu":
        # He init: N(0, 2/fanIn)
        return math.sqrt(2.0 / fan_in) * jax.random.normal(key, shape, dtype)
    if scheme == "relu_uniform":
        a = math.sqrt(6.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "sigmoid_uniform":
        a = 4.0 * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "lecun_normal":
        return jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "lecun_uniform":
        a = math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "var_scaling_normal_fan_in":
        return gain * jax.random.normal(key, shape, dtype) / math.sqrt(fan_in)
    if scheme == "var_scaling_normal_fan_out":
        return gain * jax.random.normal(key, shape, dtype) / math.sqrt(fan_out)
    if scheme == "var_scaling_normal_fan_avg":
        return gain * jax.random.normal(key, shape, dtype) / math.sqrt((fan_in + fan_out) / 2)
    if scheme == "var_scaling_uniform_fan_in":
        a = gain * math.sqrt(3.0 / fan_in)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "var_scaling_uniform_fan_out":
        a = gain * math.sqrt(3.0 / fan_out)
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "var_scaling_uniform_fan_avg":
        a = gain * math.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(key, shape, dtype, -a, a)
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution spec")
        return distribution_init(key, shape, distribution, dtype=dtype)
    raise ValueError(f"unknown weight init scheme '{scheme}'")


def distribution_init(key, shape, spec, *, dtype=jnp.float32):
    """Distribution spec: dict like {"type": "normal", "mean": 0, "std": 0.01}
    (reference org.deeplearning4j.nn.conf.distribution.*)."""
    t = spec.get("type", "normal").lower()
    shape = tuple(int(s) for s in shape)
    if t == "normal" or t == "gaussian":
        return spec.get("mean", 0.0) + spec.get("std", 1.0) * jax.random.normal(key, shape, dtype)
    if t == "uniform":
        return jax.random.uniform(key, shape, dtype, spec.get("lower", -1.0), spec.get("upper", 1.0))
    if t == "truncated_normal":
        return spec.get("mean", 0.0) + spec.get("std", 1.0) * jax.random.truncated_normal(
            key, -2.0, 2.0, shape, dtype)
    if t == "orthogonal":
        return spec.get("gain", 1.0) * jax.nn.initializers.orthogonal()(key, shape, dtype)
    if t == "constant":
        return jnp.full(shape, spec.get("value", 0.0), dtype)
    if t == "binomial":
        return jax.random.bernoulli(key, spec.get("prob", 0.5), shape).astype(dtype)
    raise ValueError(f"unknown distribution type '{t}'")
