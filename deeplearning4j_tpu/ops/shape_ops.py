"""Shape / layout / indexing ops.

Reference parity: libnd4j's shape DynamicCustomOps
(include/ops/declarable/generic/shape/** — reshape, permute, expand_dims,
squeeze, …; generic/parity_ops/** — stack, unstack, pad, reverse, tile,
gather_nd, …; Java surface org.nd4j.linalg.api.ops.impl.shape.*). Names
preserved; bodies lower to jnp/lax, where XLA folds most of them into
layout changes that cost nothing at runtime (SURVEY §3.1).

Every op registers a numpy-oracle validation case (ops/validation.py).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()


def _op(name, doc=""):
    def deco(fn):
        _REG.register(name, fn, doc=doc or fn.__doc__ or "")
        return fn

    return deco


@_op("reshape")
def reshape(x, *, shape):
    """reshape (generic/shape/reshape.cpp)."""
    return jnp.reshape(x, shape)


@_op("permute")
def permute(x, *, axes):
    """permute/transpose with explicit axes (generic/shape/permute.cpp)."""
    return jnp.transpose(x, axes)


@_op("transpose")
def transpose(x):
    """full transpose — reverse all axes (generic/shape/transpose.cpp)."""
    return jnp.transpose(x)


@_op("expand_dims")
def expand_dims(x, *, axis: int):
    """expand_dims (generic/shape/expand_dims.cpp)."""
    return jnp.expand_dims(x, axis)


@_op("squeeze")
def squeeze(x, *, axis=None):
    """squeeze (generic/shape/squeeze.cpp)."""
    return jnp.squeeze(x, axis=axis)


@_op("concat")
def concat(*xs, axis: int = 0):
    """concat (generic/transforms/concat.cpp)."""
    return jnp.concatenate(xs, axis=axis)


@_op("stack")
def stack(*xs, axis: int = 0):
    """stack (generic/parity_ops/stack.cpp). Stays in NUMPY when no input
    is traced (shape-chain arithmetic keeps trace-time concreteness)."""
    import numpy as np
    from jax.core import Tracer

    def host_ok(x):
        # scalars (incl. concrete baked jnp constants) and host arrays may
        # stack on host; a NON-scalar device array must stay on device
        return not isinstance(x, jax.Array) or np.ndim(x) == 0

    if (not any(isinstance(x, Tracer) for x in xs)
            and all(host_ok(x) for x in xs)):
        # shape_of chains: stays concrete under jit traces, no device
        # round-trip for host values
        return np.stack([np.asarray(x) for x in xs], axis=axis)
    return jnp.stack(xs, axis=axis)


@_op("unstack")
def unstack(x, *, axis: int = 0):
    """unstack → tuple of arrays (generic/parity_ops/unstack.cpp)."""
    return tuple(jnp.moveaxis(x, axis, 0))


@_op("split")
def split(x, *, num_split: int, axis: int = 0):
    """split into equal parts (generic/parity_ops/split.cpp)."""
    return tuple(jnp.split(x, num_split, axis=axis))


@_op("split_v")
def split_v(x, *, sizes, axis: int = 0):
    """split by explicit sizes (generic/parity_ops/split_v.cpp)."""
    # np over the static sizes kwarg — never traced data
    idx = np.cumsum(sizes)[:-1]  # graftlint: disable=GL009
    return tuple(jnp.split(x, idx, axis=axis))


@_op("slice")
def slice_op(x, *, begin, size):
    """slice by begin/size (generic/parity_ops/slice.cpp)."""
    import jax

    size = [x.shape[i] - b if s == -1 else s
            for i, (b, s) in enumerate(zip(begin, size))]
    return jax.lax.dynamic_slice(x, begin, size)


@_op("strided_slice")
def strided_slice(x, *, begin, end, strides=None):
    """strided_slice (generic/parity_ops/strided_slice.cpp) — basic form."""
    strides = strides or [1] * len(begin)
    sl = tuple(slice(b, e, s) for b, e, s in zip(begin, end, strides))
    return x[sl]


@_op("gather_nd")
def gather_nd(x, indices):
    """gather_nd (generic/parity_ops/gather_nd.cpp)."""
    return x[tuple(jnp.moveaxis(indices, -1, 0))]


@_op("repeat")
def repeat(x, *, repeats: int, axis: int = 0):
    """repeat elements along axis (NDArray::repeat analog)."""
    return jnp.repeat(x, repeats, axis=axis)


@_op("tile")
def tile(x, *, reps):
    """tile (generic/transforms/tile.cpp)."""
    return jnp.tile(x, reps)


@_op("pad")
def pad(x, *, paddings, mode: str = "constant", constant: float = 0.0):
    """pad with CONSTANT/REFLECT/SYMMETRIC modes (generic/transforms/pad.cpp)."""
    mode = mode.lower()
    if mode == "constant":
        return jnp.pad(x, paddings, constant_values=constant)
    return jnp.pad(x, paddings, mode={"reflect": "reflect",
                                      "symmetric": "symmetric"}[mode])


@_op("reverse")
def reverse(x, *, axis):
    """reverse along axes (generic/transforms/reverse.cpp)."""
    return jnp.flip(x, axis=axis)


@_op("rank")
def rank(x):
    """rank (generic/shape/rank.cpp)."""
    return jnp.asarray(x.ndim, jnp.int32)


@_op("shape_of")
def shape_of(x):
    """shape_of (generic/shape/shape.cpp). Returns NUMPY: shapes are static
    under XLA, and keeping the result un-traced lets imported
    tf.shape→Pack→Reshape chains recover concrete ints at trace time
    (reshape_dynamic); jnp consumers auto-convert."""
    import numpy as np

    dt = np.int64 if max(x.shape, default=0) > 2**31 else np.int32
    return np.asarray(x.shape, dt)


@_op("size")
def size(x):
    """total element count (generic/shape/size.cpp)."""
    # np on x.shape only — static ints, never traced data
    return jnp.asarray(int(np.prod(x.shape)), jnp.int32)  # graftlint: disable=GL009


@_op("zeros_like")
def zeros_like(x):
    """zeros_like (generic/parity_ops/zeros_as.cpp)."""
    return jnp.zeros_like(x)


@_op("ones_like")
def ones_like(x):
    """ones_like (generic/parity_ops/ones_as.cpp)."""
    return jnp.ones_like(x)


@_op("fill")
def fill(*, shape, value, dtype=jnp.float32):
    """fill (generic/parity_ops/fill.cpp)."""
    return jnp.full(shape, value, dtype=dtype)


@_op("linspace")
def linspace(*, start, stop, num, dtype=jnp.float32):
    """linspace (Nd4j.linspace analog)."""
    return jnp.linspace(start, stop, num, dtype=dtype)


@_op("range")
def range_op(*, start, limit, delta=1, dtype=jnp.float32):
    """range (generic/parity_ops/range.cpp)."""
    return jnp.arange(start, limit, delta, dtype=dtype)


@_op("broadcast_to")
def broadcast_to(x, *, shape):
    """broadcast_to (generic/shape/broadcast_to.cpp)."""
    return jnp.broadcast_to(x, shape)


@_op("space_to_depth")
def space_to_depth(x, *, block_size: int, data_format: str = "NHWC"):
    """space_to_depth (generic/parity_ops/space_to_depth.cpp)."""
    if data_format == "NCHW":
        x = x.transpose(0, 2, 3, 1)
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h // b, b, w // b, b, c).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, h // b, w // b, b * b * c)
    if data_format == "NCHW":
        x = x.transpose(0, 3, 1, 2)
    return x


@_op("depth_to_space")
def depth_to_space(x, *, block_size: int, data_format: str = "NHWC"):
    """depth_to_space (generic/parity_ops/depth_to_space.cpp)."""
    if data_format == "NCHW":
        x = x.transpose(0, 2, 3, 1)
    n, h, w, c = x.shape
    b = block_size
    x = x.reshape(n, h, w, b, b, c // (b * b)).transpose(0, 1, 3, 2, 4, 5)
    x = x.reshape(n, h * b, w * b, c // (b * b))
    if data_format == "NCHW":
        x = x.transpose(0, 3, 1, 2)
    return x


@_op("batch_to_space")
def batch_to_space(x, *, block_shape, crops):
    """batch_to_space_nd (generic/parity_ops/batch_to_space_nd.cpp)."""
    return _b2s(x, block_shape, crops)


def _b2s(x, block_shape, crops):
    n = x.shape[0]
    block = list(block_shape)
    prod = int(np.prod(block))
    spatial = x.shape[1:1 + len(block)]
    rest = x.shape[1 + len(block):]
    x = x.reshape(tuple(block) + (n // prod,) + tuple(spatial) + tuple(rest))
    perm = [len(block)]
    for i in range(len(block)):
        perm += [len(block) + 1 + i, i]
    perm += list(range(2 * len(block) + 1, x.ndim))
    x = x.transpose(perm)
    shape = (n // prod,) + tuple(s * b for s, b in zip(spatial, block)) + tuple(rest)
    x = x.reshape(shape)
    sl = [slice(None)]
    for (lo, hi), dim in zip(crops, shape[1:1 + len(block)]):
        sl.append(slice(lo, dim - hi))
    sl += [slice(None)] * len(rest)
    return x[tuple(sl)]


@_op("space_to_batch")
def space_to_batch(x, *, block_shape, paddings):
    """space_to_batch_nd (generic/parity_ops/space_to_batch_nd.cpp)."""
    block = list(block_shape)
    pads = [(0, 0)] + [tuple(p) for p in paddings] + \
        [(0, 0)] * (x.ndim - 1 - len(block))
    x = jnp.pad(x, pads)
    n = x.shape[0]
    spatial = x.shape[1:1 + len(block)]
    rest = x.shape[1 + len(block):]
    shape = (n,)
    for s, b in zip(spatial, block):
        shape += (s // b, b)
    shape += tuple(rest)
    x = x.reshape(shape)
    perm = []
    for i in range(len(block)):
        perm.append(2 + 2 * i)
    perm.append(0)
    for i in range(len(block)):
        perm.append(1 + 2 * i)
    perm += list(range(1 + 2 * len(block), x.ndim))
    x = x.transpose(perm)
    # np over the static block_shape kwarg — never traced data
    return x.reshape((n * int(np.prod(block)),) +  # graftlint: disable=GL009
                     tuple(s // b for s, b in zip(spatial, block)) + tuple(rest))


@_op("diag")
def diag(x):
    """vector → diagonal matrix (generic/parity_ops/diag.cpp)."""
    return jnp.diag(x)


@_op("diag_part")
def diag_part(x):
    """matrix diagonal (generic/parity_ops/diag_part.cpp)."""
    return jnp.diagonal(x)


@_op("matrix_diag")
def matrix_diag(x):
    """batched vector → diagonal matrices (parity_ops/matrix_diag.cpp)."""
    eye = jnp.eye(x.shape[-1], dtype=x.dtype)
    return x[..., None] * eye


@_op("matrix_band_part")
def matrix_band_part(x, *, num_lower: int, num_upper: int):
    """keep a band of the matrix (parity_ops/matrix_band_part.cpp);
    negative bound = keep whole triangle."""
    m, n = x.shape[-2], x.shape[-1]
    rows = jnp.arange(m)[:, None]
    cols = jnp.arange(n)[None, :]
    keep = jnp.ones((m, n), bool)
    if num_lower >= 0:
        keep = keep & (rows - cols <= num_lower)
    if num_upper >= 0:
        keep = keep & (cols - rows <= num_upper)
    return jnp.where(keep, x, jnp.zeros((), x.dtype))


@_op("trace")
def trace(x):
    """matrix trace (NDArray trace analog)."""
    return jnp.trace(x, axis1=-2, axis2=-1)


@_op("eye")
def eye(*, rows: int, cols=None, dtype=jnp.float32):
    """identity matrix (generic/parity_ops/eye.cpp)."""
    return jnp.eye(rows, cols, dtype=dtype)


@_op("sequence_mask")
def sequence_mask(lengths, *, maxlen: int, dtype=jnp.float32):
    """sequence_mask (generic/parity_ops/sequence_mask.cpp)."""
    return (jnp.arange(maxlen)[None, :] < lengths[:, None]).astype(dtype)


@_op("reverse_sequence")
def reverse_sequence(x, lengths, *, seq_axis: int = 1, batch_axis: int = 0):
    """reverse the first lengths[i] entries of every sequence
    (generic/parity_ops/reverse_sequence.cpp)."""
    xm = jnp.moveaxis(x, (batch_axis, seq_axis), (0, 1))
    t = xm.shape[1]
    idx = jnp.arange(t)[None, :]
    rev = lengths[:, None] - 1 - idx
    take = jnp.where(idx < lengths[:, None], rev, idx)
    out = jnp.take_along_axis(
        xm, take.reshape(take.shape + (1,) * (xm.ndim - 2)), axis=1)
    return jnp.moveaxis(out, (0, 1), (batch_axis, seq_axis))


# --------------------------------------------------------------------------
# validation cases
# --------------------------------------------------------------------------


def _r(seed=0):
    return np.random.RandomState(seed)


def _add(name, fn):
    validation.add_case(name, fn)


_add("reshape", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("reshape", jnp.arange(12), shape=(3, 4))),
    np.arange(12).reshape(3, 4)))
_add("permute", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("permute", jnp.asarray(_r().randn(2, 3, 4).astype(np.float32)), axes=(2, 0, 1))),
    _r().randn(2, 3, 4).astype(np.float32).transpose(2, 0, 1)))
_add("transpose", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("transpose", jnp.asarray(_r(1).randn(2, 5).astype(np.float32)))),
    _r(1).randn(2, 5).astype(np.float32).T))
_add("expand_dims", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("expand_dims", jnp.arange(4), axis=0)).shape, (1, 4)))
_add("squeeze", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("squeeze", jnp.zeros((2, 1, 3)))).shape, (2, 3)))
_add("concat", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("concat", jnp.ones((2, 2)), jnp.zeros((1, 2)), axis=0)),
    np.concatenate([np.ones((2, 2)), np.zeros((1, 2))], 0)))
_add("stack", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("stack", jnp.ones(3), jnp.zeros(3), axis=0)),
    np.stack([np.ones(3), np.zeros(3)])))


@validation.case("unstack")
def _check_unstack():
    x = _r(2).randn(3, 4).astype(np.float32)
    parts = _REG.exec("unstack", jnp.asarray(x), axis=0)
    assert len(parts) == 3
    for i, p in enumerate(parts):
        np.testing.assert_array_equal(np.asarray(p), x[i])


@validation.case("split")
def _check_split():
    x = _r(3).randn(6, 4).astype(np.float32)
    parts = _REG.exec("split", jnp.asarray(x), num_split=3, axis=0)
    for got, want in zip(parts, np.split(x, 3, axis=0)):
        np.testing.assert_array_equal(np.asarray(got), want)


@validation.case("split_v")
def _check_split_v():
    x = _r(4).randn(7, 2).astype(np.float32)
    parts = _REG.exec("split_v", jnp.asarray(x), sizes=[2, 4, 1], axis=0)
    for got, want in zip(parts, np.split(x, [2, 6], axis=0)):
        np.testing.assert_array_equal(np.asarray(got), want)


@validation.case("slice")
def _check_slice():
    x = _r(5).randn(5, 6).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("slice", jnp.asarray(x), begin=[1, 2], size=[3, -1])),
        x[1:4, 2:])


@validation.case("strided_slice")
def _check_strided():
    x = _r(6).randn(6, 8).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("strided_slice", jnp.asarray(x),
                             begin=[0, 1], end=[5, 7], strides=[2, 3])),
        x[0:5:2, 1:7:3])


@validation.case("gather_nd")
def _check_gather_nd():
    x = _r(7).randn(4, 5).astype(np.float32)
    idx = np.asarray([[0, 1], [3, 2]], np.int32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("gather_nd", jnp.asarray(x), jnp.asarray(idx))),
        x[idx[:, 0], idx[:, 1]])


_add("repeat", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("repeat", jnp.arange(3), repeats=2, axis=0)),
    np.repeat(np.arange(3), 2)))
_add("tile", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("tile", jnp.arange(3), reps=(2,))),
    np.tile(np.arange(3), 2)))


@validation.case("pad")
def _check_pad():
    x = _r(8).randn(2, 3).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("pad", jnp.asarray(x), paddings=[(1, 0), (0, 2)],
                             constant=7.0)),
        np.pad(x, [(1, 0), (0, 2)], constant_values=7.0))
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("pad", jnp.asarray(x), paddings=[(1, 1), (1, 1)],
                             mode="reflect")),
        np.pad(x, [(1, 1), (1, 1)], mode="reflect"))


_add("reverse", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("reverse", jnp.arange(6).reshape(2, 3), axis=1)),
    np.flip(np.arange(6).reshape(2, 3), 1)))
_add("rank", lambda: np.testing.assert_array_equal(
    int(_REG.exec("rank", jnp.zeros((2, 3, 4)))), 3))
_add("shape_of", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("shape_of", jnp.zeros((2, 3)))), [2, 3]))
_add("size", lambda: np.testing.assert_array_equal(
    int(_REG.exec("size", jnp.zeros((2, 3)))), 6))
_add("zeros_like", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("zeros_like", jnp.ones((2, 2)))), np.zeros((2, 2))))
_add("ones_like", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("ones_like", jnp.zeros((2, 2)))), np.ones((2, 2))))
_add("fill", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("fill", shape=(2, 3), value=5.0)), np.full((2, 3), 5.0)))
_add("linspace", lambda: np.testing.assert_allclose(
    np.asarray(_REG.exec("linspace", start=0.0, stop=1.0, num=5)),
    np.linspace(0, 1, 5, dtype=np.float32), rtol=1e-6))
_add("range", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("range", start=1, limit=7, delta=2)),
    np.arange(1, 7, 2, dtype=np.float32)))
_add("broadcast_to", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("broadcast_to", jnp.arange(3), shape=(2, 3))),
    np.broadcast_to(np.arange(3), (2, 3))))


@validation.case("space_to_depth")
def _check_s2d():
    import tensorflow as tf

    x = _r(9).randn(2, 4, 4, 3).astype(np.float32)
    got = np.asarray(_REG.exec("space_to_depth", jnp.asarray(x), block_size=2))
    want = tf.nn.space_to_depth(x, 2).numpy()
    np.testing.assert_array_equal(got, want)


@validation.case("depth_to_space")
def _check_d2s():
    import tensorflow as tf

    x = _r(10).randn(2, 2, 2, 12).astype(np.float32)
    got = np.asarray(_REG.exec("depth_to_space", jnp.asarray(x), block_size=2))
    want = tf.nn.depth_to_space(x, 2).numpy()
    np.testing.assert_array_equal(got, want)


@validation.case("space_to_batch")
def _check_s2b():
    import tensorflow as tf

    x = _r(11).randn(1, 4, 4, 2).astype(np.float32)
    got = np.asarray(_REG.exec("space_to_batch", jnp.asarray(x),
                               block_shape=[2, 2], paddings=[(0, 0), (0, 0)]))
    want = tf.space_to_batch_nd(x, [2, 2], [[0, 0], [0, 0]]).numpy()
    np.testing.assert_array_equal(got, want)


@validation.case("batch_to_space")
def _check_b2s():
    import tensorflow as tf

    x = _r(12).randn(4, 2, 2, 3).astype(np.float32)
    got = np.asarray(_REG.exec("batch_to_space", jnp.asarray(x),
                               block_shape=[2, 2], crops=[(0, 0), (0, 0)]))
    want = tf.batch_to_space(x, [2, 2], [[0, 0], [0, 0]]).numpy()
    np.testing.assert_array_equal(got, want)


_add("diag", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("diag", jnp.arange(3))), np.diag(np.arange(3))))
_add("diag_part", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("diag_part", jnp.arange(9).reshape(3, 3))),
    np.diagonal(np.arange(9).reshape(3, 3))))


@validation.case("matrix_diag")
def _check_matrix_diag():
    x = _r(13).randn(2, 3).astype(np.float32)
    got = np.asarray(_REG.exec("matrix_diag", jnp.asarray(x)))
    want = np.stack([np.diag(row) for row in x])
    np.testing.assert_array_equal(got, want)


@validation.case("matrix_band_part")
def _check_band():
    import tensorflow as tf

    x = _r(14).randn(5, 5).astype(np.float32)
    got = np.asarray(_REG.exec("matrix_band_part", jnp.asarray(x),
                               num_lower=1, num_upper=2))
    want = tf.linalg.band_part(x, 1, 2).numpy()
    np.testing.assert_array_equal(got, want)


_add("trace", lambda: np.testing.assert_allclose(
    float(_REG.exec("trace", jnp.arange(9.0).reshape(3, 3))),
    np.trace(np.arange(9.0).reshape(3, 3)), rtol=1e-6))
_add("eye", lambda: np.testing.assert_array_equal(
    np.asarray(_REG.exec("eye", rows=3, cols=4)), np.eye(3, 4)))


@validation.case("sequence_mask")
def _check_seq_mask():
    got = np.asarray(_REG.exec("sequence_mask", jnp.asarray([1, 3]), maxlen=4))
    np.testing.assert_array_equal(got, [[1, 0, 0, 0], [1, 1, 1, 0]])


@validation.case("reverse_sequence")
def _check_rev_seq():
    import tensorflow as tf

    x = _r(15).randn(3, 5, 2).astype(np.float32)
    lengths = np.asarray([2, 5, 3], np.int32)
    got = np.asarray(_REG.exec("reverse_sequence", jnp.asarray(x),
                               jnp.asarray(lengths)))
    want = tf.reverse_sequence(x, lengths, seq_axis=1, batch_axis=0).numpy()
    np.testing.assert_array_equal(got, want)


@_op("strided_slice_spec")
def strided_slice_spec(x, *, begin, end, strides, begin_mask: int = 0,
                       end_mask: int = 0, shrink_mask: int = 0,
                       new_axis_mask: int = 0, ellipsis_mask: int = 0):
    """TF StridedSlice with the FULL mask set, resolved at trace time when
    x.ndim is known — supports t[None], t[..., None], shrink indexing, and
    every Python-slicing combination (TFGraphMapper strided-slice parity)."""
    idx = []
    for i in range(len(begin)):
        if ellipsis_mask & (1 << i):
            idx.append(Ellipsis)
        elif new_axis_mask & (1 << i):
            idx.append(None)
        elif shrink_mask & (1 << i):
            idx.append(int(begin[i]))
        else:
            b = None if (begin_mask & (1 << i)) else int(begin[i])
            e = None if (end_mask & (1 << i)) else int(end[i])
            idx.append(slice(b, e, int(strides[i])))
    return x[tuple(idx)]


def _check_strided_slice_spec():
    import numpy as np

    r = np.random.RandomState(0)
    x = r.rand(3, 4, 5).astype(np.float32)
    xj = jnp.asarray(x)
    # t[..., None]: spec [ellipsis, new_axis]
    got = strided_slice_spec(xj, begin=[0, 0], end=[0, 0], strides=[1, 1],
                             ellipsis_mask=0b01, new_axis_mask=0b10)
    np.testing.assert_array_equal(np.asarray(got), x[..., None])
    # t[:, None, 1:, 0]: [full, new, slice(1,None), shrink 0]
    got = strided_slice_spec(xj, begin=[0, 0, 1, 0], end=[0, 0, 0, 0],
                             strides=[1, 1, 1, 1], begin_mask=0b0001,
                             end_mask=0b0101, new_axis_mask=0b0010,
                             shrink_mask=0b1000)
    np.testing.assert_array_equal(np.asarray(got), x[:, None, 1:, 0])
    # reverse stride t[::-1]
    got = strided_slice_spec(xj, begin=[0], end=[0], strides=[-1],
                             begin_mask=1, end_mask=1)
    np.testing.assert_array_equal(np.asarray(got), x[::-1])


validation.add_case("strided_slice_spec", _check_strided_slice_spec)


@_op("reshape_dynamic")
def reshape_dynamic(x, shape):
    """Reshape where the target arrives as a tensor operand (TF Reshape
    with a tf.shape(...)-derived input). Requires the shape chain to be
    trace-time concrete — true whenever it derives from shape_of + consts."""
    import numpy as np

    try:
        # deliberately numpy-static, same family as shape_of/stack: the
        # shape operand must be trace-time concrete (tracers are refused
        # loudly below), so np here is the contract, not a fallback
        # graftshape: justified(GS003): the shape operand is REQUIRED to be trace-time concrete — np.asarray is the concreteness probe, and a leaked tracer is converted to a loud NotImplementedError below
        dims = tuple(int(s) for s in np.asarray(shape))  # graftlint: disable=GL009
    except Exception as e:  # a tracer leaked into the shape chain
        raise NotImplementedError(
            "reshape_dynamic: target shape is data-dependent (not derivable "
            "from static shapes) — XLA cannot express it") from e
    return x.reshape(dims)


@validation.case("reshape_dynamic")
def _check_reshape_dynamic():
    import numpy as np

    import jax

    x = jnp.arange(12.0)
    got = reshape_dynamic(x, np.asarray([3, 4]))
    assert got.shape == (3, 4)
    # stays concrete THROUGH a jit trace when derived from shape_of
    # (numpy) + the numpy-preserving stack op
    @jax.jit
    def f(a):
        s = _REG.exec("shape_of", a)
        tgt = _REG.exec("stack", s[0] * s[1])
        return reshape_dynamic(a, tgt)

    from deeplearning4j_tpu import observe

    x34 = jnp.zeros((3, 4))
    observe.note_jit_signature(
        f, graph="ops", key="reshape_dynamic_check",
        signature=observe.signature_of(a=x34))
    assert f(x34).shape == (12,)


@validation.case("space_to_batch")
def _check_space_to_batch_oracle():
    import numpy as np

    r = np.random.RandomState(0)
    x = r.rand(2, 4, 6, 3).astype(np.float32)
    bh, bw = 2, 3
    got = np.asarray(_REG.exec("space_to_batch", jnp.asarray(x),
                               block_shape=(bh, bw),
                               paddings=((0, 0), (0, 0))))
    # per-pixel oracle straight from the TF spec
    n, h, w, c = x.shape
    want = np.zeros((bh * bw * n, h // bh, w // bw, c), np.float32)
    for i in range(bh):
        for j in range(bw):
            for b in range(n):
                want[(i * bw + j) * n + b] = x[b, i::bh, j::bw, :]
    np.testing.assert_allclose(got, want)
    back = np.asarray(_REG.exec("batch_to_space", jnp.asarray(got),
                                block_shape=(bh, bw),
                                crops=((0, 0), (0, 0))))
    np.testing.assert_allclose(back, x)
