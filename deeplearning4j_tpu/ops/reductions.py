"""Reduction / accumulation ops.

Reference parity: libnd4j legacy reduce ops (reduce_same/reduce_float kinds
in include/loops/legacy_ops.h) and the custom reduce DynamicCustomOps
(include/ops/declarable/generic/reduce/**; Java surface
org.nd4j.linalg.api.ops.impl.reduce.*). Names preserved; bodies lower to
jnp reductions, which XLA maps to tree-reductions over the VPU (SURVEY
§3.1: legacy loop kernels dissolve into XLA HLO reduce).

Each table entry auto-registers a numpy-oracle validation case.
"""

from __future__ import annotations

import functools

import numpy as np
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()

# name -> (jax fn(x, axis, keepdims), numpy oracle, needs_float)
_REDUCE = {
    "reduce_sum": (jnp.sum, np.sum),
    "reduce_mean": (jnp.mean, np.mean),
    "reduce_max": (jnp.max, np.max),
    "reduce_min": (jnp.min, np.min),
    "reduce_prod": (jnp.prod, np.prod),
    "reduce_norm1": (lambda x, **k: jnp.sum(jnp.abs(x), **k),
                     lambda x, **k: np.sum(np.abs(x), **k)),
    "reduce_norm2": (lambda x, **k: jnp.sqrt(jnp.sum(jnp.square(x), **k)),
                     lambda x, **k: np.sqrt(np.sum(np.square(x), **k))),
    "reduce_norm_max": (lambda x, **k: jnp.max(jnp.abs(x), **k),
                        lambda x, **k: np.max(np.abs(x), **k)),
    "reduce_sqnorm": (lambda x, **k: jnp.sum(jnp.square(x), **k),
                      lambda x, **k: np.sum(np.square(x), **k)),
    "reduce_variance": (jnp.var, np.var),
    "reduce_stdev": (jnp.std, np.std),
    "reduce_logsumexp": (None, None),  # special-cased below
    "amax": (lambda x, **k: jnp.max(jnp.abs(x), **k),
             lambda x, **k: np.max(np.abs(x), **k)),
    "amin": (lambda x, **k: jnp.min(jnp.abs(x), **k),
             lambda x, **k: np.min(np.abs(x), **k)),
    "amean": (lambda x, **k: jnp.mean(jnp.abs(x), **k),
              lambda x, **k: np.mean(np.abs(x), **k)),
    "asum": (lambda x, **k: jnp.sum(jnp.abs(x), **k),
             lambda x, **k: np.sum(np.abs(x), **k)),
    "reduce_any": (jnp.any, np.any),
    "reduce_all": (jnp.all, np.all),
}


def _reduce_apply(jfn, x, *, axis=None, keepdims: bool = False):
    return jfn(x, axis=axis, keepdims=keepdims)


def _check_reduce(name, oracle):
    r = np.random.RandomState(0)
    x = r.randn(4, 6, 5).astype(np.float32)
    if name in ("reduce_any", "reduce_all"):
        x = x > 0.5
    for axis in (None, 1, (0, 2)):
        got = np.asarray(_REG.exec(name, jnp.asarray(x), axis=axis))
        want = oracle(x, axis=axis)
        if got.dtype == np.bool_:
            np.testing.assert_array_equal(got, want)
        else:
            np.testing.assert_allclose(got, want.astype(got.dtype),
                                       rtol=2e-5, atol=1e-6)


for _name, (_jfn, _npfn) in _REDUCE.items():
    if _jfn is None:
        continue
    _REG.register(_name, functools.partial(_reduce_apply, _jfn),
                  doc=f"{_name} reduction (libnd4j legacy reduce op)")
    validation.add_case(_name, functools.partial(_check_reduce, _name, _npfn))


def _logsumexp(x, *, axis=None, keepdims: bool = False):
    """reduce_logsumexp — stable log-sum-exp (generic/reduce family)."""
    import jax

    return jax.nn.logsumexp(x, axis=axis, keepdims=keepdims)


_REG.register("reduce_logsumexp", _logsumexp, doc=_logsumexp.__doc__)


@validation.case("reduce_logsumexp")
def _check_lse():
    x = np.random.RandomState(1).randn(5, 7).astype(np.float32) * 10
    got = np.asarray(_REG.exec("reduce_logsumexp", jnp.asarray(x), axis=1))
    m = x.max(axis=1, keepdims=True)
    want = (np.log(np.sum(np.exp(x - m), axis=1)) + m[:, 0])
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


# ---- index reductions ------------------------------------------------------


def _argmax(x, *, axis=None, keepdims: bool = False):
    """argmax (libnd4j indexreduce IMax)."""
    return jnp.argmax(x, axis=axis, keepdims=keepdims)


def _argmin(x, *, axis=None, keepdims: bool = False):
    """argmin (libnd4j indexreduce IMin)."""
    return jnp.argmin(x, axis=axis, keepdims=keepdims)


_REG.register("argmax", _argmax, doc=_argmax.__doc__)
_REG.register("argmin", _argmin, doc=_argmin.__doc__)


@validation.case("argmax")
def _check_argmax():
    x = np.random.RandomState(2).randn(6, 9).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("argmax", jnp.asarray(x), axis=1)),
        np.argmax(x, axis=1))


@validation.case("argmin")
def _check_argmin():
    x = np.random.RandomState(3).randn(6, 9).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("argmin", jnp.asarray(x), axis=0)),
        np.argmin(x, axis=0))


# ---- counting / moments / cumulative --------------------------------------


def _count_nonzero(x, *, axis=None, keepdims: bool = False):
    """count_nonzero (generic/reduce/countNonZero analog)."""
    return jnp.count_nonzero(x, axis=axis, keepdims=keepdims)


def _count_zero(x, *, axis=None, keepdims: bool = False):
    """count_zero (generic/reduce/countZero analog)."""
    # np over x.shape/axis only — static ints, never traced data
    total = np.prod([x.shape[a] for a in (  # graftlint: disable=GL009
        range(x.ndim) if axis is None else np.atleast_1d(axis))], dtype=int)  # graftlint: disable=GL009
    return total - jnp.count_nonzero(x, axis=axis, keepdims=keepdims)


def _moments(x, *, axis=None, keepdims: bool = False):
    """moments: (mean, variance) pair (generic/reduce/moments.cpp analog)."""
    return (jnp.mean(x, axis=axis, keepdims=keepdims),
            jnp.var(x, axis=axis, keepdims=keepdims))


def _cumsum(x, *, axis: int = 0, exclusive: bool = False,
            reverse: bool = False):
    """cumsum with the reference's exclusive/reverse flags
    (generic/parity_ops/cumsum.cpp analog)."""
    if reverse:
        x = jnp.flip(x, axis=axis)
    out = jnp.cumsum(x, axis=axis)
    if exclusive:
        out = out - x
    if reverse:
        out = jnp.flip(out, axis=axis)
    return out


def _cumprod(x, *, axis: int = 0, exclusive: bool = False,
             reverse: bool = False):
    """cumprod with exclusive/reverse flags (generic/parity_ops/cumprod).
    Exclusive form shifts the input right by one (identity=1) before the
    scan — robust to zeros, unlike the divide-out trick."""
    if reverse:
        x = jnp.flip(x, axis=axis)
    if exclusive:
        pad = [(0, 0)] * x.ndim
        pad[axis] = (1, 0)
        sl = [slice(None)] * x.ndim
        sl[axis] = slice(0, x.shape[axis])
        x = jnp.pad(x, pad, constant_values=1)[tuple(sl)]
    out = jnp.cumprod(x, axis=axis)
    if reverse:
        out = jnp.flip(out, axis=axis)
    return out


_REG.register("count_nonzero", _count_nonzero, doc=_count_nonzero.__doc__)
_REG.register("count_zero", _count_zero, doc=_count_zero.__doc__)
_REG.register("moments", _moments, doc=_moments.__doc__)
_REG.register("cumsum", _cumsum, doc=_cumsum.__doc__)
_REG.register("cumprod", _cumprod, doc=_cumprod.__doc__)


@validation.case("count_nonzero")
def _check_cnz():
    x = np.asarray([[0, 1, 2], [3, 0, 0]], np.float32)
    assert int(_REG.exec("count_nonzero", jnp.asarray(x))) == 3
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("count_nonzero", jnp.asarray(x), axis=1)), [2, 1])


@validation.case("count_zero")
def _check_cz():
    x = np.asarray([[0, 1, 2], [3, 0, 0]], np.float32)
    assert int(_REG.exec("count_zero", jnp.asarray(x))) == 3


@validation.case("moments")
def _check_moments():
    x = np.random.RandomState(4).randn(8, 5).astype(np.float32)
    m, v = _REG.exec("moments", jnp.asarray(x), axis=0)
    np.testing.assert_allclose(np.asarray(m), x.mean(axis=0), rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(v), x.var(axis=0), rtol=1e-5, atol=1e-6)


@validation.case("cumsum")
def _check_cumsum():
    x = np.random.RandomState(5).randn(4, 6).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("cumsum", jnp.asarray(x), axis=1)),
        np.cumsum(x, axis=1), rtol=1e-5, atol=1e-6)
    # exclusive: [0, x0, x0+x1, ...]
    got = np.asarray(_REG.exec("cumsum", jnp.asarray(x), axis=1, exclusive=True))
    want = np.cumsum(x, axis=1) - x
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    # reverse: suffix sums
    got = np.asarray(_REG.exec("cumsum", jnp.asarray(x), axis=1, reverse=True))
    np.testing.assert_allclose(got, np.flip(np.cumsum(np.flip(x, 1), 1), 1),
                               rtol=1e-5, atol=1e-6)


@validation.case("cumprod")
def _check_cumprod():
    x = np.random.RandomState(6).rand(3, 5).astype(np.float32) + 0.5
    np.testing.assert_allclose(
        np.asarray(_REG.exec("cumprod", jnp.asarray(x), axis=1)),
        np.cumprod(x, axis=1), rtol=1e-5, atol=1e-6)


def _bincount(x, *, weights=None, minlength: int = 0, maxlength: int = None):
    """bincount (generic/parity_ops/bincount.cpp analog).

    XLA needs a static output shape, so the caller must bound the value
    range: pass minlength (or maxlength) >= max(x)+1. Counts for values
    beyond the bound would be silently dropped by the underlying scatter,
    so an unbounded call is an error rather than a wrong answer."""
    if maxlength is None and minlength <= 0:
        raise ValueError(
            "bincount needs a static length: pass minlength (or maxlength) "
            ">= max(x)+1 — XLA cannot size the output from data")
    length = minlength if maxlength is None else maxlength
    return jnp.bincount(x, weights=weights, length=length)


_REG.register("bincount", _bincount, doc=_bincount.__doc__)


@validation.case("bincount")
def _check_bincount():
    x = np.asarray([0, 1, 1, 3, 2, 1], np.int32)
    got = np.asarray(_REG.exec("bincount", jnp.asarray(x), minlength=5))
    np.testing.assert_array_equal(got, np.bincount(x, minlength=5))
    try:
        _REG.exec("bincount", jnp.asarray(x))
    except ValueError:
        pass
    else:
        raise AssertionError("unbounded bincount must raise, not truncate")
