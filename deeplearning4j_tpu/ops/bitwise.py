"""Bitwise / integer ops.

Reference parity: libnd4j bitwise DynamicCustomOps
(include/ops/declarable/generic/bitwise/** — and.cpp, or.cpp, xor.cpp,
shift.cpp, cyclic_shift.cpp, toggle_bits.cpp, bits_hamming_distance.cpp;
Java surface org.nd4j.linalg.api.ops.impl.transforms.custom.*Bitwise*).
Integer ops run on the VPU; XLA lowers them directly.

Every op registers a numpy-oracle validation case.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()

_BINARY = {
    "bitwise_and": (jnp.bitwise_and, np.bitwise_and),
    "bitwise_or": (jnp.bitwise_or, np.bitwise_or),
    "bitwise_xor": (jnp.bitwise_xor, np.bitwise_xor),
}


def _apply(jfn, x, y):
    return jfn(x, y)


def _check_binary(name, npfn):
    r = np.random.RandomState(0)
    x = r.randint(0, 1 << 16, (4, 9)).astype(np.int32)
    y = r.randint(0, 1 << 16, (4, 9)).astype(np.int32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec(name, jnp.asarray(x), jnp.asarray(y))),
        npfn(x, y))


for _name, (_jfn, _npfn) in _BINARY.items():
    _REG.register(_name, functools.partial(_apply, _jfn),
                  doc=f"{_name} (generic/bitwise family)")
    validation.add_case(_name, functools.partial(_check_binary, _name, _npfn))


def _toggle_bits(x):
    """bitwise not (generic/bitwise/toggle_bits.cpp)."""
    return jnp.bitwise_not(x)


def _shift_bits(x, *, shift: int):
    """left shift (generic/bitwise/shift.cpp)."""
    return jnp.left_shift(x, shift)


def _rshift_bits(x, *, shift: int):
    """arithmetic right shift (generic/bitwise/shift.cpp)."""
    return jnp.right_shift(x, shift)


def _cyclic_shift_bits(x, *, shift: int):
    """cyclic (rotate) left shift on 32-bit lanes
    (generic/bitwise/cyclic_shift.cpp)."""
    xu = x.astype(jnp.uint32)
    rot = jnp.bitwise_or(jnp.left_shift(xu, shift),
                         jnp.right_shift(xu, 32 - shift))
    return rot.astype(x.dtype)


def _cyclic_rshift_bits(x, *, shift: int):
    """cyclic right shift on 32-bit lanes (generic/bitwise/cyclic_shift.cpp)."""
    xu = x.astype(jnp.uint32)
    rot = jnp.bitwise_or(jnp.right_shift(xu, shift),
                         jnp.left_shift(xu, 32 - shift))
    return rot.astype(x.dtype)


def _bits_hamming_distance(x, y):
    """total popcount of x^y (generic/bitwise/bits_hamming_distance.cpp)."""
    return jnp.sum(jax.lax.population_count(jnp.bitwise_xor(x, y)))


for _fn, _name in [(_toggle_bits, "toggle_bits"),
                   (_shift_bits, "shift_bits"),
                   (_rshift_bits, "rshift_bits"),
                   (_cyclic_shift_bits, "cyclic_shift_bits"),
                   (_cyclic_rshift_bits, "cyclic_rshift_bits"),
                   (_bits_hamming_distance, "bits_hamming_distance")]:
    _REG.register(_name, _fn, doc=_fn.__doc__)


@validation.case("toggle_bits")
def _check_toggle():
    x = np.asarray([0, 1, -1, 7], np.int32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("toggle_bits", jnp.asarray(x))), ~x)


@validation.case("shift_bits")
def _check_shift():
    x = np.asarray([1, 2, 3], np.int32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("shift_bits", jnp.asarray(x), shift=3)), x << 3)


@validation.case("rshift_bits")
def _check_rshift():
    x = np.asarray([16, -16, 7], np.int32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("rshift_bits", jnp.asarray(x), shift=2)), x >> 2)


@validation.case("cyclic_shift_bits")
def _check_cyclic():
    x = np.asarray([0x80000001], np.uint32).astype(np.int32)
    got = np.asarray(_REG.exec("cyclic_shift_bits", jnp.asarray(x), shift=1))
    assert np.uint32(got[0]) == np.uint32(0x00000003)


@validation.case("cyclic_rshift_bits")
def _check_cyclic_r():
    x = np.asarray([0x00000003], np.int32)
    got = np.asarray(_REG.exec("cyclic_rshift_bits", jnp.asarray(x), shift=1))
    assert np.uint32(got[0]) == np.uint32(0x80000001)


@validation.case("bits_hamming_distance")
def _check_hamming():
    x = np.asarray([0b1010, 0b1111], np.int32)
    y = np.asarray([0b0011, 0b1111], np.int32)
    assert int(_REG.exec("bits_hamming_distance", jnp.asarray(x),
                         jnp.asarray(y))) == 2
