"""Neural-net op catalog: conv / pooling / normalization / recurrent / attention.

Reference parity: libnd4j declarable ops (include/ops/declarable/generic/nn/**)
— conv2d.cpp, depthwiseConv2d, deconv2d, maxpool2d/avgpool2d/pnormpool2d,
batchnorm, layer_norm, lstmLayer, gruCell, dot_product_attention,
multi_head_dot_product_attention — plus the cuDNN platform helpers
(platform/cudnn/*.cu) that override them on GPU.

TPU-native realization: every op lowers to XLA HLO via jax.lax. Convs hit
``lax.conv_general_dilated`` (MXU), pooling hits ``lax.reduce_window``;
nothing here is a Python-level loop. Layout: all internal convs are NHWC /
HWIO (TPU-friendly); the NCHW acceptance happens at the layer-API edge
(see nn/conf). The platform-helper role (cuDNN) is played by Pallas kernels
registered in deeplearning4j_tpu.kernels via the registry's platform table.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.ops.registry import op

IntPair = Union[int, Tuple[int, int]]


def _pair(v: IntPair) -> Tuple[int, int]:
    if isinstance(v, (tuple, list)):
        return (int(v[0]), int(v[1]))
    return (int(v), int(v))


def _padding(mode, kernel, stride, dilation):
    """Resolve reference padding modes: 'same' | 'valid' | explicit (ph, pw).

    Reference conv2d takes ``isSameMode`` int-arg + explicit pad pair
    (ConvolutionMode.{Same,Truncate,Causal} at the DL4J layer level).
    """
    if isinstance(mode, str):
        m = mode.upper()
        if m in ("SAME", "TRUNCATE", "VALID"):
            return "SAME" if m == "SAME" else "VALID"
        raise ValueError(f"unknown padding mode {mode}")
    if (isinstance(mode, (tuple, list)) and len(mode) == 2
            and isinstance(mode[0], (tuple, list))):
        return tuple((int(a), int(b)) for a, b in mode)  # ((ph,ph),(pw,pw)) form
    ph, pw = _pair(mode)
    return ((ph, ph), (pw, pw))


# --------------------------------------------------------------------------
# Convolutions (reference: generic/nn/convo/*.cpp; helper im2col+gemm path
# replaced wholesale by XLA ConvGeneralDilated on the MXU).
# --------------------------------------------------------------------------


@op("conv2d")
def conv2d(
    x,
    w,
    b=None,
    *,
    stride: IntPair = 1,
    padding="same",
    dilation: IntPair = 1,
    feature_group_count: int = 1,
    precision=None,
):
    """2-D convolution. x: [N,H,W,C_in], w: [kH,kW,C_in/groups,C_out]."""
    s = _pair(stride)
    d = _pair(dilation)
    pad = _padding(padding, (w.shape[0], w.shape[1]), s, d)
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=s,
        padding=pad,
        rhs_dilation=d,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=feature_group_count,
        precision=precision,
    )
    if b is not None:
        out = out + b
    return out


@op("conv1d")
def conv1d(x, w, b=None, *, stride: int = 1, padding="same", dilation: int = 1):
    """1-D convolution. x: [N,W,C], w: [k,C_in,C_out]."""
    pad = padding
    if not isinstance(padding, str):
        p = int(padding) if not isinstance(padding, (tuple, list)) else int(padding[0])
        pad = ((p, p),)
    else:
        pad = "SAME" if padding.upper() == "SAME" else "VALID"
    out = lax.conv_general_dilated(
        x,
        w,
        window_strides=(int(stride),),
        padding=pad,
        rhs_dilation=(int(dilation),),
        dimension_numbers=("NWC", "WIO", "NWC"),
    )
    if b is not None:
        out = out + b
    return out


@op("conv3d")
def conv3d(x, w, b=None, *, stride=1, padding="same", dilation=1):
    """3-D convolution. x: [N,D,H,W,C], w: [kD,kH,kW,C_in,C_out] (NDHWC)."""

    def triple(v):
        return tuple(int(a) for a in v) if isinstance(v, (tuple, list)) else (int(v),) * 3

    s, d = triple(stride), triple(dilation)
    if isinstance(padding, str):
        pad = "SAME" if padding.upper() == "SAME" else "VALID"
    else:
        pad = tuple((int(p), int(p)) for p in triple(padding))
    out = lax.conv_general_dilated(
        x, w, window_strides=s, padding=pad, rhs_dilation=d,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"),
    )
    if b is not None:
        out = out + b
    return out


@op("depthwise_conv2d")
def depthwise_conv2d(x, w, b=None, *, stride: IntPair = 1, padding="same", dilation: IntPair = 1):
    """Depthwise conv. x: [N,H,W,C], w: [kH,kW,C,mult]."""
    c = x.shape[-1]
    kh, kw, wc, mult = w.shape
    w2 = jnp.reshape(w, (kh, kw, 1, wc * mult))
    return conv2d.fn(x, w2, b, stride=stride, padding=padding, dilation=dilation,
                     feature_group_count=c)


@op("sconv2d")
def separable_conv2d(x, depth_w, point_w, b=None, *, stride: IntPair = 1, padding="same"):
    """Separable conv (reference sconv2d): depthwise then 1x1 pointwise."""
    y = depthwise_conv2d.fn(x, depth_w, None, stride=stride, padding=padding)
    return conv2d.fn(y, point_w, b, stride=1, padding="valid")


@op("deconv2d")
def deconv2d(x, w, b=None, *, stride: IntPair = 1, padding="same"):
    """Transposed conv, TF conv_transpose semantics at every stride.
    x: [N,H,W,C_in], w: [kH,kW,C_in,C_out] (same HWIO layout conv2d uses;
    the op swaps channels internally for the gradient-form kernel)."""
    s = _pair(stride)
    pad = "SAME" if (isinstance(padding, str) and padding.upper() == "SAME") else (
        "VALID" if isinstance(padding, str) else tuple((int(p), int(p)) for p in _pair(padding))
    )
    # transpose_kernel=True gives the exact gradient-of-conv semantics TF/
    # keras Conv2DTranspose uses — without it, stride>1 results diverge
    # (stride-1 outputs are identical either way)
    out = lax.conv_transpose(
        x, jnp.swapaxes(w, 2, 3), strides=s, padding=pad,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), transpose_kernel=True
    )
    if b is not None:
        out = out + b
    return out


@op("upsampling2d")
def upsampling2d(x, *, size: IntPair = 2):
    sh, sw = _pair(size)
    return jnp.repeat(jnp.repeat(x, sh, axis=1), sw, axis=2)


@op("im2col")
def im2col(x, *, kernel: IntPair, stride: IntPair = 1, padding="valid", dilation: IntPair = 1):
    """Patch extraction (reference helpers/im2col) — exposed for parity; the
    conv path does NOT use it (XLA convs are direct)."""
    kh, kw = _pair(kernel)
    s = _pair(stride)
    d = _pair(dilation)
    pad = _padding(padding, (kh, kw), s, d)
    return lax.conv_general_dilated_patches(
        x, filter_shape=(kh, kw), window_strides=s, padding=pad,
        rhs_dilation=d, dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


# --------------------------------------------------------------------------
# Pooling (reference: maxpool2d/avgpool2d/pnormpool2d + cudnn helpers)
# --------------------------------------------------------------------------


def _pool(x, kernel, stride, padding, init, reduce_fn):
    kh, kw = _pair(kernel)
    s = _pair(stride if stride is not None else kernel)
    if isinstance(padding, str):
        pad = "SAME" if padding.upper() == "SAME" else "VALID"
    else:
        (pht, phb), (pwl, pwr) = _padding(padding, kernel, stride, 1)
        pad = ((0, 0), (pht, phb), (pwl, pwr), (0, 0))
    return lax.reduce_window(x, init, reduce_fn, (1, kh, kw, 1), (1, s[0], s[1], 1), pad)


@op("maxpool2d")
def maxpool2d(x, *, kernel: IntPair, stride: Optional[IntPair] = None, padding="valid"):
    return _pool(x, kernel, stride, padding, -jnp.inf, lax.max)


@op("avgpool2d")
def avgpool2d(x, *, kernel: IntPair, stride: Optional[IntPair] = None, padding="valid",
              count_include_pad: bool = True):
    kh, kw = _pair(kernel)
    summed = _pool(x, kernel, stride, padding, 0.0, lax.add)
    if count_include_pad or (isinstance(padding, str) and padding.upper() == "VALID"):
        return summed / (kh * kw)
    ones = jnp.ones_like(x)
    counts = _pool(ones, kernel, stride, padding, 0.0, lax.add)
    return summed / counts


@op("pnormpool2d")
def pnormpool2d(x, *, kernel: IntPair, stride: Optional[IntPair] = None, padding="valid",
                p: float = 2.0):
    kh, kw = _pair(kernel)
    summed = _pool(jnp.abs(x) ** p, kernel, stride, padding, 0.0, lax.add)
    return summed ** (1.0 / p)


@op("global_avg_pool")
def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))


@op("global_max_pool")
def global_max_pool(x):
    return jnp.max(x, axis=(1, 2))


# --------------------------------------------------------------------------
# Normalization (reference: batchnorm.cpp, layer_norm.cpp + cudnn batchnorm)
# --------------------------------------------------------------------------


@op("batchnorm")
def batchnorm(x, mean, var, gamma=None, beta=None, *, eps: float = 1e-5):
    """Normalize with given statistics (inference form of reference batchnorm).

    Dtype-stable under mixed precision: the scale/shift are folded in (at
    least) float32 and cast to x.dtype, so a bfloat16 activation stream stays
    bfloat16 while the statistics math keeps f32 accuracy. Under x64 (gradient
    checks) the stats stay f64 — a hard f32 cast would quantize the
    finite-difference perturbations of the parameters."""
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    scale = lax.rsqrt(var.astype(f32) + eps)
    if gamma is not None:
        scale = scale * gamma.astype(f32)
    shift = -mean.astype(f32) * scale
    if beta is not None:
        shift = shift + beta.astype(f32)
    return x * scale.astype(x.dtype) + shift.astype(x.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _bn_core(x, gamma, beta, stat_shift, eps):
    """Channel-last training batchnorm with a hand-written backward — the
    platform-helper role the reference fills with cudnnBatchNormalization*
    (platform/cudnn/batchnorm.cu). Autodiff of the naive two-pass variance
    costs ~2× the HBM traffic of the canonical two-reduction backward; on
    TPU, where ResNet training is bandwidth-bound, that is the whole game.

    Returns (out, mean, biased_var) — the stats are produced for the running
    buffers and are NON-differentiable (reference semantics: running stats
    are buffers excluded from gradients). ``stat_shift`` (the running mean)
    enables the one-pass bf16 statistics path below."""
    out, mean, var, _, _ = _bn_fwd_math(x, gamma, beta, stat_shift, eps)
    return out, mean, var


def _bn_fwd_math(x, gamma, beta, stat_shift, eps):
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    axes = tuple(range(x.ndim - 1))
    xf = x.astype(f32)
    if x.dtype == jnp.bfloat16 and stat_shift is not None:
        # ONE-pass shifted moments for the bf16 perf path: E[(x−s)] and
        # E[(x−s)²] are independent reductions over one fused elementwise
        # input, so XLA emits a single multi-output HBM pass instead of the
        # two dependent passes below (~40% of a ResNet-50 step was BN stat
        # reductions). Shifting by the RUNNING mean keeps the
        # var = E[c²] − E[c]² form stable: cancellation only bites when
        # E[c]² ≈ E[c²], i.e. |batch_mean − shift| ≈ std, which a tracking
        # running mean prevents; bf16 inputs carry ~3 decimal digits anyway.
        sf = lax.stop_gradient(stat_shift.astype(f32))
        xc = xf - sf
        m1 = jnp.mean(xc, axis=axes)
        m2 = jnp.mean(jnp.square(xc), axis=axes)
        mean = m1 + sf
        var = jnp.maximum(m2 - jnp.square(m1), 0.0)
    else:
        # two-pass statistics: E[(x-E[x])²] — the unshifted one-pass
        # E[x²]−E[x]² form is catastrophic-cancellation-prone in f32 and
        # broke gradient checks (round-2 regression)
        mean = jnp.mean(xf, axis=axes)
        var = jnp.mean(jnp.square(xf - mean), axis=axes)
    inv = lax.rsqrt(var + eps)
    scale = inv if gamma is None else inv * gamma.astype(f32)
    shift = -mean * scale
    if beta is not None:
        shift = shift + beta.astype(f32)
    out = x * scale.astype(x.dtype) + shift.astype(x.dtype)
    return out, mean, var, inv, scale


def _bn_core_fwd(x, gamma, beta, stat_shift, eps):
    out, mean, var, inv, _ = _bn_fwd_math(x, gamma, beta, stat_shift, eps)
    return (out, mean, var), (x, gamma, beta, mean, inv)


def _bn_core_bwd(eps, res, cts):
    dy = cts[0]  # stats cotangents ignored: running buffers are non-diff
    x, gamma, beta, mean, inv = res
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    axes = tuple(range(x.ndim - 1))
    n = x.size // x.shape[-1]
    dyf = dy.astype(f32)
    xhat = (x.astype(f32) - mean) * inv
    sum_dy = jnp.sum(dyf, axis=axes)
    sum_dy_xhat = jnp.sum(dyf * xhat, axis=axes)
    g = inv if gamma is None else inv * gamma.astype(f32)
    dx = g * (dyf - sum_dy / n - xhat * (sum_dy_xhat / n))
    dgamma = None if gamma is None else sum_dy_xhat.astype(gamma.dtype)
    dbeta = None if beta is None else sum_dy.astype(beta.dtype)
    return dx.astype(x.dtype), dgamma, dbeta, None  # stat_shift non-diff


_bn_core.defvjp(_bn_core_fwd, _bn_core_bwd)


def batch_norm_train(x, gamma, beta, running_mean, running_var, *,
                     axis=(0,), eps: float = 1e-5, momentum: float = 0.9):
    """Training-mode batch norm: returns (out, new_running_mean, new_running_var).

    Matches DL4J BatchNormalization 'decay' semantics:
    running = momentum * running + (1-momentum) * batch_stat.
    Batch statistics are accumulated in float32 even for bf16 activations
    (the running-state buffers keep the parameter dtype). The channel-last
    case (the layer path) uses the fused custom-VJP kernel; other axes fall
    back to autodiff."""
    if tuple(axis) == tuple(range(x.ndim - 1)):
        out, mean, var = _bn_core(x, gamma, beta, running_mean, eps)
    else:
        xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
        mean = jnp.mean(xf, axis=axis)
        var = jnp.var(xf, axis=axis)
        out = batchnorm.fn(x, mean, var, gamma, beta, eps=eps)
    n = x.size // mean.size
    unbiased = var * n / max(n - 1, 1)
    rdt = running_mean.dtype
    new_mean = momentum * running_mean + (1.0 - momentum) * lax.stop_gradient(mean).astype(rdt)
    new_var = momentum * running_var + (1.0 - momentum) * lax.stop_gradient(unbiased).astype(rdt)
    return out, new_mean, new_var


@op("layer_norm")
def layer_norm(x, gain, bias=None, *, axis: int = -1, eps: float = 1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    var = jnp.var(x, axis=axis, keepdims=True)
    out = (x - mean) * lax.rsqrt(var + eps) * gain
    if bias is not None:
        out = out + bias
    return out


@op("lrn")
def local_response_normalization(x, *, depth: int = 5, bias: float = 1.0,
                                 alpha: float = 1e-4, beta: float = 0.75):
    """LRN over channels (reference lrn op; AlexNet-era)."""
    half = depth // 2
    sq = x * x
    c = x.shape[-1]
    pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
    padded = jnp.pad(sq, pads)
    window = sum(
        lax.slice_in_dim(padded, i, i + c, axis=x.ndim - 1) for i in range(depth)
    )
    return x / (bias + alpha * window) ** beta


@op("dropout")
def dropout(x, key, *, rate: float, deterministic: bool = False):
    """Inverted dropout (reference dropout_bp pairs with DL4J Dropout)."""
    if deterministic or rate <= 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


# --------------------------------------------------------------------------
# Linear algebra / embedding
# --------------------------------------------------------------------------


@op("matmul")
def matmul(a, b, *, transpose_a: bool = False, transpose_b: bool = False):
    if transpose_a:
        a = jnp.swapaxes(a, -1, -2)
    if transpose_b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


@op("xw_plus_b")
def xw_plus_b(x, w, b):
    """Dense layer primitive (reference xw_plus_b.cpp)."""
    return jnp.matmul(x, w) + b


@op("gather")
def gather(params, indices, *, axis: int = 0):
    return jnp.take(params, indices, axis=axis)


@op("embedding_lookup")
def embedding_lookup(table, ids):
    return jnp.take(table, ids, axis=0)


@op("one_hot")
def one_hot(indices, *, depth: int, on_value: float = 1.0, off_value: float = 0.0,
            dtype=jnp.float32):
    oh = jax.nn.one_hot(indices, depth, dtype=dtype)
    return oh * on_value + (1.0 - oh) * off_value


# --------------------------------------------------------------------------
# Attention (reference: dot_product_attention.cpp,
# multi_head_dot_product_attention.cpp — materialized softmax O(L^2); our
# generic impl is the same math XLA-fused; Pallas flash attention registers as
# the TPU platform helper in deeplearning4j_tpu.kernels.attention)
# --------------------------------------------------------------------------


@op("dot_product_attention")
def dot_product_attention(q, k, v, mask=None, *, scaled: bool = True,
                          causal: bool = False,
                          dropout_rate: float = 0.0, dropout_rng=None):
    """q:[...,Lq,Dk] k:[...,Lk,Dk] v:[...,Lk,Dv] -> [...,Lq,Dv].

    ``causal``: lower-triangular mask (decoder prefill); composes with
    ``mask``. ``dropout_rate``/``dropout_rng``: post-softmax attention-prob
    dropout (the reference's attention dropout order); the Pallas platform
    helper implements the same semantics in-kernel."""
    scores = jnp.einsum("...qd,...kd->...qk", q, k)
    if scaled:
        scores = scores / jnp.sqrt(jnp.asarray(q.shape[-1], scores.dtype))
    if mask is not None:
        scores = jnp.where(mask, scores, jnp.asarray(-1e9, scores.dtype))
    if causal:
        l_q, l_k = scores.shape[-2], scores.shape[-1]
        tri = jnp.tril(jnp.ones((l_q, l_k), bool), k=l_k - l_q)
        scores = jnp.where(tri, scores, jnp.asarray(-1e9, scores.dtype))
    weights = jax.nn.softmax(scores, axis=-1)
    if dropout_rate > 0.0:
        if dropout_rng is None:
            raise ValueError(
                "dot_product_attention: dropout_rate > 0 requires dropout_rng "
                "(pass None rate for eval mode)")
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate,
                                    weights.shape)
        weights = jnp.where(keep, weights / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("...qk,...kd->...qd", weights, v)


@op("multi_head_dot_product_attention")
def multi_head_dot_product_attention(q, k, v, wq, wk, wv, wo, mask=None, *,
                                     num_heads: int, scaled: bool = True,
                                     bq=None, bk=None, bv=None, bo=None):
    """Projected multi-head attention, q/k/v: [B, L, D]; w*: [D, D].
    Optional per-projection biases (Keras MultiHeadAttention use_bias)."""

    def split(x, w, bias):
        y = jnp.einsum("bld,de->ble", x, w)
        if bias is not None:
            y = y + bias
        b, l, d = y.shape
        return y.reshape(b, l, num_heads, d // num_heads).transpose(0, 2, 1, 3)

    qh, kh, vh = split(q, wq, bq), split(k, wk, bk), split(v, wv, bv)
    m = None
    if mask is not None:
        m = mask[:, None, None, :].astype(bool)
    # route through the DESCRIPTOR so the Pallas flash platform helper can
    # override on TPU (calling .fn would pin the generic XLA path)
    out = dot_product_attention(qh, kh, vh, m, scaled=scaled)
    b, h, l, d = out.shape
    out = out.transpose(0, 2, 1, 3).reshape(b, l, h * d)
    out = jnp.einsum("ble,ed->bld", out, wo)
    return out if bo is None else out + bo


# Activation epilogues the fused matmul understands. "gelu" is the tanh
# approximation (what the GRAPH_OPS/registry `gelu` op computes — jax.nn
# default); "gelu_exact" is the erf formula the decomposed ONNX/TF exporter
# chains (x·0.5·(1+erf(x/√2))) lower to. The optimizer's epilogue-fusion
# matcher (autodiff/optimize.py) picks the variant that matches the
# replaced subgraph bit-for-bit at f32.
FUSED_MATMUL_ACTIVATIONS = ("none", "relu", "tanh", "gelu", "gelu_exact")


def apply_fused_activation(y, activation: str):
    if activation == "none":
        return y
    if activation == "relu":
        return jax.nn.relu(y)
    if activation == "tanh":
        return jnp.tanh(y)
    if activation == "gelu":
        return jax.nn.gelu(y)
    if activation == "gelu_exact":
        return jax.nn.gelu(y, approximate=False)
    raise ValueError(
        f"fused_matmul_bias_act: unknown activation '{activation}'; "
        f"valid: {list(FUSED_MATMUL_ACTIVATIONS)}")


@op("fused_matmul_bias_act")
def fused_matmul_bias_act(x, w, b=None, *, activation: str = "none",
                          transpose_a: bool = False,
                          transpose_b: bool = False):
    """act(x @ w + b) — the matmul-epilogue fusion target.

    x:[...,M,K] w:[K,N] b:[N] -> [...,M,N]. ``activation`` is one of
    :data:`FUSED_MATMUL_ACTIVATIONS`. The generic impl is the exact op
    chain it replaces (XLA fuses the epilogue into the dot); the Pallas
    TPU platform helper (ops/pallas_matmul.py) runs one MXU kernel with
    f32 accumulation and the bias+activation applied in VMEM before the
    result is written to HBM."""
    if transpose_a:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_b:
        w = jnp.swapaxes(w, -1, -2)
    y = jnp.matmul(x, w)
    if b is not None:
        y = y + b
    return apply_fused_activation(y, activation)


# --------------------------------------------------------------------------
# Recurrent cells (reference: lstmLayer.cpp/.cu helpers, gruCell.cpp,
# sruCell.cpp; cuDNN lstm helper). Full-sequence scan versions live in
# nn/layers/recurrent.py — these are the single-step cell mathematics.
# --------------------------------------------------------------------------


@op("lstm_cell")
def lstm_cell(x, h_prev, c_prev, w_ih, w_hh, b, *, forget_bias: float = 0.0):
    """Standard LSTM cell. Gate order: i, f, g(cell), o (reference lstmLayer
    gate layout). x:[B,I], h/c:[B,H], w_ih:[I,4H], w_hh:[H,4H], b:[4H]."""
    z = x @ w_ih + h_prev @ w_hh + b
    i, f, g, o = jnp.split(z, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f + forget_bias)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    return h, c


@op("gru_cell")
def gru_cell(x, h_prev, w_ih, w_hh, b_ih, b_hh):
    """GRU cell. Gate order: r, z, n. x:[B,I], h:[B,H], w_ih:[I,3H], w_hh:[H,3H]."""
    gi = x @ w_ih + b_ih
    gh = h_prev @ w_hh + b_hh
    i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
    h_r, h_z, h_n = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(i_r + h_r)
    z = jax.nn.sigmoid(i_z + h_z)
    n = jnp.tanh(i_n + r * h_n)
    return (1.0 - z) * n + z * h_prev


@op("simple_rnn_cell")
def simple_rnn_cell(x, h_prev, w_ih, w_hh, b, *, activation=jnp.tanh):
    return activation(x @ w_ih + h_prev @ w_hh + b)


# --------------------------------------------------------------------------
# Misc transforms used by layers/losses
# --------------------------------------------------------------------------


@op("softmax_op")
def softmax_op(x, *, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


@op("log_softmax_op")
def log_softmax_op(x, *, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


@op("standardize")
def standardize(x, *, axis=-1, eps: float = 1e-5):
    mean = jnp.mean(x, axis=axis, keepdims=True)
    std = jnp.std(x, axis=axis, keepdims=True)
    return (x - mean) / (std + eps)


@op("clip_by_norm")
def clip_by_norm(x, *, clip_norm: float, axis=None):
    n = jnp.sqrt(jnp.sum(x * x, axis=axis, keepdims=axis is not None))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(n, 1e-12))
    return x * scale


@op("clip_by_value")
def clip_by_value(x, *, min_value: float, max_value: float):
    return jnp.clip(x, min_value, max_value)


@op("lstm_sequence")
def lstm_sequence(x, w_ih, w_hh, b, h0=None, c0=None):
    """Full-sequence LSTM over lstm_cell (gate order i, f, g, o) — ONE
    lax.scan, batch-major x:[N,T,I]. Returns (ys:[N,T,H], h_T, c_T).
    The samediff-import surface for ONNX/TF LSTM nodes (reference
    lstmLayer.cpp full-sequence mode)."""
    h_dim = w_hh.shape[0]
    n = x.shape[0]
    h = jnp.zeros((n, h_dim), x.dtype) if h0 is None else h0
    c = jnp.zeros((n, h_dim), x.dtype) if c0 is None else c0

    def step(carry, xt):
        h, c = carry
        h, c = lstm_cell.fn(xt, h, c, w_ih, w_hh, b)
        return (h, c), h

    (h, c), ys = lax.scan(step, (h, c), jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1), h, c


@op("gru_sequence")
def gru_sequence(x, w_ih, w_hh, b_ih, b_hh, h0=None, *,
                 linear_before_reset: bool = True):
    """Full-sequence GRU, gate order r, z, n; batch-major x:[N,T,I].
    Returns (ys:[N,T,H], h_T). linear_before_reset=True matches gru_cell
    (and keras reset_after); False is the ONNX GRU default
    (h_n = tanh(Wn x + Rn (r*h) + b))."""
    h_dim = w_hh.shape[0]
    n = x.shape[0]
    h = jnp.zeros((n, h_dim), x.dtype) if h0 is None else h0

    def step(h, xt):
        if linear_before_reset:
            h_new = gru_cell.fn(xt, h, w_ih, w_hh, b_ih, b_hh)
        else:
            gi = xt @ w_ih + b_ih
            i_r, i_z, i_n = jnp.split(gi, 3, axis=-1)
            r = jax.nn.sigmoid(i_r + h @ w_hh[:, :h_dim] + b_hh[:h_dim])
            z = jax.nn.sigmoid(i_z + h @ w_hh[:, h_dim:2 * h_dim]
                               + b_hh[h_dim:2 * h_dim])
            nn = jnp.tanh(i_n + (r * h) @ w_hh[:, 2 * h_dim:]
                          + b_hh[2 * h_dim:])
            h_new = (1.0 - z) * nn + z * h
        return h_new, h_new

    h, ys = lax.scan(step, h, jnp.swapaxes(x, 0, 1))
    return jnp.swapaxes(ys, 0, 1), h


def _check_lstm_sequence():
    import numpy as np

    r = np.random.RandomState(0)
    n, t, i, h = 2, 5, 3, 4
    x = r.randn(n, t, i).astype(np.float32)
    w_ih = r.randn(i, 4 * h).astype(np.float32)
    w_hh = r.randn(h, 4 * h).astype(np.float32)
    b = r.randn(4 * h).astype(np.float32)
    ys, hT, cT = lstm_sequence.fn(jnp.asarray(x), jnp.asarray(w_ih),
                                  jnp.asarray(w_hh), jnp.asarray(b))
    # numpy oracle
    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    hh = np.zeros((n, h), np.float32)
    cc = np.zeros((n, h), np.float32)
    want = np.zeros((n, t, h), np.float32)
    for s in range(t):
        z = x[:, s] @ w_ih + hh @ w_hh + b
        ig, fg, gg, og = np.split(z, 4, axis=-1)
        cc = sig(fg) * cc + sig(ig) * np.tanh(gg)
        hh = sig(og) * np.tanh(cc)
        want[:, s] = hh
    np.testing.assert_allclose(np.asarray(ys), want, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(hT), hh, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(cT), cc, rtol=1e-5, atol=1e-5)


def _check_gru_sequence():
    import numpy as np

    r = np.random.RandomState(1)
    n, t, i, h = 2, 4, 3, 5
    x = r.randn(n, t, i).astype(np.float32)
    w_ih = r.randn(i, 3 * h).astype(np.float32)
    w_hh = r.randn(h, 3 * h).astype(np.float32)
    b_ih = r.randn(3 * h).astype(np.float32)
    b_hh = r.randn(3 * h).astype(np.float32)

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    for lbr in (True, False):
        ys, hT = gru_sequence.fn(jnp.asarray(x), jnp.asarray(w_ih),
                                 jnp.asarray(w_hh), jnp.asarray(b_ih),
                                 jnp.asarray(b_hh),
                                 linear_before_reset=lbr)
        hh = np.zeros((n, h), np.float32)
        want = np.zeros((n, t, h), np.float32)
        for s in range(t):
            gi = x[:, s] @ w_ih + b_ih
            i_r, i_z, i_n = np.split(gi, 3, axis=-1)
            if lbr:
                gh = hh @ w_hh + b_hh
                h_r, h_z, h_n = np.split(gh, 3, axis=-1)
                rr = sig(i_r + h_r)
                zz = sig(i_z + h_z)
                nn = np.tanh(i_n + rr * h_n)
            else:
                rr = sig(i_r + hh @ w_hh[:, :h] + b_hh[:h])
                zz = sig(i_z + hh @ w_hh[:, h:2 * h] + b_hh[h:2 * h])
                nn = np.tanh(i_n + (rr * hh) @ w_hh[:, 2 * h:]
                             + b_hh[2 * h:])
            hh = (1.0 - zz) * nn + zz * hh
            want[:, s] = hh
        np.testing.assert_allclose(np.asarray(ys), want, rtol=1e-5,
                                   atol=1e-5, err_msg=f"lbr={lbr}")
        np.testing.assert_allclose(np.asarray(hT), hh, rtol=1e-5, atol=1e-5)


from deeplearning4j_tpu.ops import validation as _validation

_validation.add_case("lstm_sequence", _check_lstm_sequence)
_validation.add_case("gru_sequence", _check_gru_sequence)
