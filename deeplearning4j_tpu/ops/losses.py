"""Loss-function catalog — parity with ND4J ILossFunction implementations.

Reference: org/nd4j/linalg/lossfunctions/impl/* (LossMCXENT, LossMSE,
LossBinaryXENT, LossL1/L2, LossHinge, LossSquaredHinge, LossKLD, LossMAPE,
LossMSLE, LossPoisson, LossCosineProximity, LossNegativeLogLikelihood,
LossSparseMCXENT, LossWasserstein, LossFMeasure...). Each reference impl
hand-codes computeGradient; here gradients are autodiff'd, so a loss is a pure
function (predictions, labels, mask) -> scalar mean score per example,
averaged like the reference's computeScore(average=true).

All losses accept an optional per-example (or per-timestep) mask array and a
per-output weight vector, matching ILossFunction's signature
(labels, preOutput, activationFn, mask). Activation is applied by the caller
(output layer) — except the fused softmax/sigmoid cross-entropy paths which
mirror the reference's numerically-stable special cases.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def _apply_mask_and_mean(per_example, mask):
    """per_example: [B] or [B,T] score per example; mask broadcastable."""
    if mask is not None:
        m = mask.astype(per_example.dtype)
        while m.ndim > per_example.ndim:
            m = m.squeeze(-1)
        per_example = per_example * m
        return jnp.sum(per_example) / jnp.maximum(jnp.sum(m), 1.0)
    return jnp.mean(per_example)


def _reduce_feature_axis(x, weights=None):
    if weights is not None:
        x = x * weights
    return jnp.sum(x, axis=-1)


def mcxent(probs, labels, mask=None, weights=None, *, eps: float = 1e-8):
    """Multi-class cross entropy on probabilities (LossMCXENT)."""
    ll = labels * jnp.log(jnp.clip(probs, eps, 1.0))
    return _apply_mask_and_mean(-_reduce_feature_axis(ll, weights), mask)


def softmax_cross_entropy_with_logits(logits, labels, mask=None, weights=None):
    """Fused stable softmax+CE (the path LossMCXENT takes with softmax)."""
    lse = jax.nn.log_softmax(logits, axis=-1)
    return _apply_mask_and_mean(-_reduce_feature_axis(labels * lse, weights), mask)


def sparse_mcxent(logits, label_ids, mask=None):
    """LossSparseMCXENT: integer labels, stable log-softmax gather."""
    lse = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(lse, label_ids[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return _apply_mask_and_mean(-ll, mask)


def negative_log_likelihood(probs, labels, mask=None, weights=None):
    """LossNegativeLogLikelihood — same math as MCXENT in the reference."""
    return mcxent(probs, labels, mask, weights)


def binary_xent(probs, labels, mask=None, weights=None, *, eps: float = 1e-8):
    """LossBinaryXENT on probabilities (sigmoid applied by caller)."""
    p = jnp.clip(probs, eps, 1.0 - eps)
    ll = labels * jnp.log(p) + (1.0 - labels) * jnp.log(1.0 - p)
    return _apply_mask_and_mean(-_reduce_feature_axis(ll, weights), mask)


def sigmoid_cross_entropy_with_logits(logits, labels, mask=None, weights=None):
    per = jnp.maximum(logits, 0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    return _apply_mask_and_mean(_reduce_feature_axis(per, weights), mask)


def mse(preds, labels, mask=None, weights=None):
    """LossMSE: mean over feature axis is a SUM in the reference (per-example
    score = sum of squared errors / nOut handled via MEAN_SQUARED naming);
    DL4J LossMSE averages over the output dimension."""
    per = (preds - labels) ** 2
    if weights is not None:
        per = per * weights
    return _apply_mask_and_mean(jnp.mean(per, axis=-1), mask)


def l2(preds, labels, mask=None, weights=None):
    """LossL2: sum of squared errors (no /nOut)."""
    return _apply_mask_and_mean(_reduce_feature_axis((preds - labels) ** 2, weights), mask)


def mae(preds, labels, mask=None, weights=None):
    per = jnp.abs(preds - labels)
    if weights is not None:
        per = per * weights
    return _apply_mask_and_mean(jnp.mean(per, axis=-1), mask)


def l1(preds, labels, mask=None, weights=None):
    return _apply_mask_and_mean(_reduce_feature_axis(jnp.abs(preds - labels), weights), mask)


def mape(preds, labels, mask=None, weights=None, *, eps: float = 1e-8):
    per = jnp.abs((labels - preds) / jnp.maximum(jnp.abs(labels), eps)) * 100.0
    if weights is not None:
        per = per * weights
    return _apply_mask_and_mean(jnp.mean(per, axis=-1), mask)


def msle(preds, labels, mask=None, weights=None):
    per = (jnp.log1p(jnp.maximum(preds, -1 + 1e-7)) - jnp.log1p(jnp.maximum(labels, -1 + 1e-7))) ** 2
    if weights is not None:
        per = per * weights
    return _apply_mask_and_mean(jnp.mean(per, axis=-1), mask)


def poisson(preds, labels, mask=None, weights=None, *, eps: float = 1e-8):
    per = preds - labels * jnp.log(jnp.maximum(preds, eps))
    return _apply_mask_and_mean(_reduce_feature_axis(per, weights), mask)


def kl_divergence(preds, labels, mask=None, weights=None, *, eps: float = 1e-8):
    per = labels * (jnp.log(jnp.clip(labels, eps, 1.0)) - jnp.log(jnp.clip(preds, eps, 1.0)))
    return _apply_mask_and_mean(_reduce_feature_axis(per, weights), mask)


def hinge(preds, labels, mask=None, weights=None):
    """LossHinge: labels in {-1, +1}."""
    per = jnp.maximum(0.0, 1.0 - labels * preds)
    return _apply_mask_and_mean(_reduce_feature_axis(per, weights), mask)


def squared_hinge(preds, labels, mask=None, weights=None):
    per = jnp.maximum(0.0, 1.0 - labels * preds) ** 2
    return _apply_mask_and_mean(_reduce_feature_axis(per, weights), mask)


def cosine_proximity(preds, labels, mask=None, weights=None, *, eps: float = 1e-8):
    pn = preds / jnp.maximum(jnp.linalg.norm(preds, axis=-1, keepdims=True), eps)
    ln = labels / jnp.maximum(jnp.linalg.norm(labels, axis=-1, keepdims=True), eps)
    per = -jnp.sum(pn * ln, axis=-1)
    return _apply_mask_and_mean(per, mask)


def wasserstein(preds, labels, mask=None, weights=None):
    """LossWasserstein: mean(labels * preds) (critic loss form)."""
    per = jnp.mean(labels * preds, axis=-1)
    return _apply_mask_and_mean(per, mask)


# Name table mirrors DL4J's LossFunctions.LossFunction enum.
def yolo2(pred, target, mask=None, *, lambda_coord: float = 5.0,
          lambda_noobj: float = 0.5, anchors=None):
    """YOLOv2 multi-part sum-squared objective
    (conf/layers/objdetect/Yolo2OutputLayer.java computeScore analog) —
    THE single implementation; Yolo2OutputLayer and the zoo TinyYOLO both
    route here.

    pred: raw head output (N, H, W, B*(5+C)) or (N, H, W, B, 5+C);
    target: (N, H, W, B, 5+C) with [x, y, w, h, objectness, class-onehot…].
    Box count B and class count C are taken from the target shape. ``mask``
    (N, H, W) optionally excludes grid cells entirely. When ``anchors``
    ((B, 2) prior sizes) are given, predicted w/h decode as
    anchor·exp(t) (the reference's anchor-box parameterization); without
    them the raw activations are compared directly."""
    import jax

    n, gh, gw = target.shape[0], target.shape[1], target.shape[2]
    bx, depth = target.shape[3], target.shape[4]
    p = pred.reshape(n, gh, gw, bx, depth)
    xy = jax.nn.sigmoid(p[..., 0:2])
    if anchors is not None:
        a = jnp.asarray(anchors, p.dtype).reshape(1, 1, 1, bx, 2)
        wh = a * jnp.exp(p[..., 2:4])
    else:
        wh = p[..., 2:4]
    obj = jax.nn.sigmoid(p[..., 4])
    cls = jax.nn.softmax(p[..., 5:], axis=-1)
    t_obj = target[..., 4]
    if mask is not None:
        cell = mask.reshape(n, gh, gw, 1)
        t_obj = t_obj * cell
        noobj_w = (1 - target[..., 4]) * cell
    else:
        noobj_w = 1 - t_obj
    coord = jnp.sum(t_obj[..., None] * ((xy - target[..., 0:2]) ** 2
                                        + (wh - target[..., 2:4]) ** 2))
    obj_term = jnp.sum(t_obj * (obj - 1.0) ** 2)
    noobj = jnp.sum(noobj_w * obj ** 2)
    cls_term = jnp.sum(t_obj[..., None] * (cls - target[..., 5:]) ** 2)
    return (lambda_coord * coord + obj_term + lambda_noobj * noobj
            + cls_term) / n


LOSSES: Dict[str, Callable] = {
    "mcxent": mcxent,
    "negativeloglikelihood": negative_log_likelihood,
    "sparse_mcxent": sparse_mcxent,
    "xent": binary_xent,
    "mse": mse,
    "squared_loss": mse,
    "l2": l2,
    "mean_absolute_error": mae,
    "l1": l1,
    "mean_absolute_percentage_error": mape,
    "mean_squared_logarithmic_error": msle,
    "poisson": poisson,
    "kl_divergence": kl_divergence,
    "reconstruction_crossentropy": binary_xent,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "cosine_proximity": cosine_proximity,
    "wasserstein": wasserstein,
    "yolo2": yolo2,
}


def get_loss(name_or_fn) -> Callable:
    if callable(name_or_fn):
        return name_or_fn
    name = str(name_or_fn).lower()
    try:
        return LOSSES[name]
    except KeyError:
        raise ValueError(f"unknown loss '{name_or_fn}'; known: {sorted(LOSSES)}") from None
