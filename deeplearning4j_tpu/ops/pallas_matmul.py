"""Pallas TPU fused matmul epilogue — the platform helper for
``fused_matmul_bias_act`` (the optimizer's matmul+bias(+activation) fusion
target, docs/OPTIMIZER.md § Fusion tier).

XLA already fuses a bias add and an elementwise activation into the dot's
epilogue, but it materializes the f32 accumulator cast at the output dtype
boundary and (for bf16 policies) re-reads the result for the activation
pass when the consumer graph splits. This kernel makes the contract
explicit and unconditional: one MXU matmul in the operands' NATIVE dtype
with an f32 VMEM accumulator, bias and activation applied to the f32
accumulator in VMEM, ONE HBM write of the finished tile — the cuDNN
ScaleBiasActivation epilogue pattern (SURVEY §3.1), same design as
``ops/pallas_convbn.py``.

Forward runs Pallas; backward is the hand-derived two-matmul VJP (the same
passes XLA emits for the unfused chain, computed via plain XLA dots —
matmul backward is already MXU-optimal, the fusion win is the forward
epilogue). Runs in interpret mode off-TPU so CPU tests exercise the same
code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.ops.nn_ops import (
    FUSED_MATMUL_ACTIVATIONS, apply_fused_activation, fused_matmul_bias_act)


def _pick_block(size: int, candidates=(512, 256, 128)) -> int:
    for c in candidates:
        if size % c == 0:
            return c
    return size


def _kernel(x_ref, w_ref, b_ref, o_ref, acc_ref, *, n_k: int,
            activation: str, has_bias: bool):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # native-dtype MXU dot with f32 accumulation (an up-front f32 cast
    # would force Mosaic's multi-pass f32 path — see pallas_attention._mm)
    acc_ref[:] += jax.lax.dot_general(
        x_ref[0], w_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(k == n_k - 1)
    def _():
        y = acc_ref[:]                          # (bm, bn) f32
        if has_bias:
            y = y + b_ref[0]
        y = apply_fused_activation(y, activation)
        o_ref[0] = y.astype(o_ref.dtype)


def fused_matmul_bias_act_pallas(x, w, b=None, *, activation: str = "none",
                                 transpose_a: bool = False,
                                 transpose_b: bool = False,
                                 block_m: int = 0, block_n: int = 0,
                                 block_k: int = 0,
                                 interpret=None):
    """Pallas forward for act(x @ w + b); same contract as the generic.

    Accepts 2-D or 3-D ``x`` (leading batch folded into rows); transpose
    flags are rejected by the usable() gate but handled here defensively
    by materializing the transpose before the kernel."""
    if interpret is None:
        from deeplearning4j_tpu.ops.registry import current_platform

        interpret = current_platform() != "tpu"
    if transpose_a:
        x = jnp.swapaxes(x, -1, -2)
    if transpose_b:
        w = jnp.swapaxes(w, -1, -2)
    lead = x.shape[:-2]
    m = 1
    for d in x.shape[:-1]:
        m *= d
    k_dim = x.shape[-1]
    n = w.shape[1]
    x2 = x.reshape(m, k_dim)
    # measured block sizes (ops/tuning.py) when the caller passed none —
    # validated against the real dims, falling back to the static pick
    from deeplearning4j_tpu.ops import tuning

    bucket = tuning.bucket_mkn(m, k_dim, n)
    bm = block_m or tuning.tuned_block(
        "fused_matmul_bias_act", "block_m", m, bucket,
        lambda s: _pick_block(s, (256, 128, 64, 32, 16, 8)))
    bn = block_n or tuning.tuned_block(
        "fused_matmul_bias_act", "block_n", n, bucket,
        lambda s: _pick_block(s, (256, 128)))
    bk = block_k or tuning.tuned_block(
        "fused_matmul_bias_act", "block_k", k_dim, bucket,
        lambda s: _pick_block(s, (512, 256, 128)))
    if m % bm or n % bn or k_dim % bk:
        raise ValueError(f"shape ({m},{k_dim})x({k_dim},{n}) not divisible "
                         f"by blocks ({bm},{bk},{bn})")
    grid = (m // bm, n // bn, k_dim // bk)
    has_bias = b is not None
    bias = (b if has_bias else jnp.zeros((n,), jnp.float32)) \
        .astype(jnp.float32)
    kern = functools.partial(_kernel, n_k=grid[2], activation=activation,
                             has_bias=has_bias)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((1, m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bm, bk), lambda i, j, k: (0, i, k)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k: (0, k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, bm, bn), lambda i, j, k: (0, i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x2[None], w[None], bias[None])
    return out[0].reshape(lead + (x.shape[-2], n))


# ---------------------------------------------------------------------------
# differentiable wrapper: Pallas forward, XLA-math backward
# ---------------------------------------------------------------------------


def _act_grad(pre, activation: str):
    """d act(pre) / d pre, from the saved pre-activation (f32)."""
    if activation == "none":
        return jnp.ones_like(pre)
    return jax.grad(lambda p: jnp.sum(apply_fused_activation(p, activation)))(
        pre)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_mm(x, w, b, activation, transpose_a, transpose_b):
    return fused_matmul_bias_act_pallas(
        x, w, b, activation=activation,
        transpose_a=transpose_a, transpose_b=transpose_b)


def _fused_fwd(x, w, b, activation, transpose_a, transpose_b):
    out = _fused_mm(x, w, b, activation, transpose_a, transpose_b)
    return out, (x, w, b)


def _fused_bwd(activation, transpose_a, transpose_b, res, g):
    x, w, b = res
    xa = jnp.swapaxes(x, -1, -2) if transpose_a else x
    wa = jnp.swapaxes(w, -1, -2) if transpose_b else w
    f32 = jnp.float32
    # recompute the pre-activation via plain XLA (no saved (M,N) f32 tensor)
    pre = jnp.matmul(xa, wa, preferred_element_type=f32)
    if b is not None:
        pre = pre + b.astype(f32)
    dpre = (g.astype(f32) * _act_grad(pre, activation))
    dx = jnp.matmul(dpre, jnp.swapaxes(wa, -1, -2),
                    preferred_element_type=f32).astype(x.dtype)
    red = tuple(range(dpre.ndim - 2))
    dw = jnp.sum(jnp.matmul(jnp.swapaxes(xa, -1, -2).astype(dpre.dtype),
                            dpre, preferred_element_type=f32),
                 axis=red).astype(w.dtype)
    if transpose_a:
        dx = jnp.swapaxes(dx, -1, -2)
    if transpose_b:
        dw = jnp.swapaxes(dw, -1, -2)
    db = None if b is None else \
        jnp.sum(dpre, axis=tuple(range(dpre.ndim - 1))).astype(b.dtype)
    return dx, dw, db


_fused_mm.defvjp(_fused_fwd, _fused_bwd)


def fused_matmul_helper(x, w, b=None, *, activation: str = "none",
                        transpose_a: bool = False, transpose_b: bool = False):
    """The registered TPU platform impl: differentiable Pallas forward."""
    return _fused_mm(x, w, b, activation, transpose_a, transpose_b)


def _usable(x, w, b=None, **kw):
    """PlatformHelper::isUsable: documented ranks, Mosaic-aligned tiles,
    no transpose flags (the matcher never emits them aligned; the generic
    handles the rest), a known activation."""
    if kw.get("transpose_a") or kw.get("transpose_b"):
        return False
    if kw.get("activation", "none") not in FUSED_MATMUL_ACTIVATIONS:
        return False
    if getattr(x, "ndim", 0) not in (2, 3) or getattr(w, "ndim", 0) != 2:
        return False
    for a in (x, w):  # integer matmuls stay on the (exact) XLA generic
        dt = getattr(a, "dtype", None)
        # jnp.issubdtype, NOT np: numpy classifies bf16 as non-floating
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            return False
    if b is not None and getattr(b, "ndim", 0) != 1:
        return False
    m = 1
    for d in x.shape[:-1]:
        m *= d
    k_dim, n = w.shape
    from deeplearning4j_tpu.ops import tuning

    if m < int(tuning.tuned("fused_matmul_bias_act", "pallas_min_m", 8)):
        return False  # measured crossover: tiny row counts stay on XLA
    return m % 8 == 0 and k_dim % 128 == 0 and n % 128 == 0


def _check_fused_matmul_bias_act():
    """Validation case (ops.validation ratchet): generic XLA impl vs a
    numpy oracle, and the Pallas interpret kernel vs both, across the
    activation catalog."""
    import math

    import numpy as np

    r = np.random.RandomState(11)
    x = r.randn(16, 128).astype(np.float32)
    w = r.randn(128, 128).astype(np.float32) * 0.1
    b = r.randn(128).astype(np.float32)

    def oracle(act):
        y = x @ w + b
        if act == "relu":
            return np.maximum(y, 0.0)
        if act == "tanh":
            return np.tanh(y)
        if act == "gelu_exact":
            return y * 0.5 * (1.0 + np.vectorize(math.erf)(y / math.sqrt(2)))
        return y

    for act in ("none", "relu", "tanh", "gelu_exact"):
        want = oracle(act)
        got = fused_matmul_bias_act(jnp.asarray(x), jnp.asarray(w),
                                    jnp.asarray(b), activation=act)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=1e-4, atol=1e-5)
        got_pl = fused_matmul_bias_act_pallas(
            jnp.asarray(x), jnp.asarray(w), jnp.asarray(b), activation=act,
            interpret=True)
        np.testing.assert_allclose(np.asarray(got_pl), want,
                                   rtol=1e-4, atol=1e-5)


def register_platform_fused_matmul() -> None:
    """Install the Pallas fused-epilogue kernel as the TPU platform
    override for fused_matmul_bias_act (cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops import validation as _validation
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()
    if "fused_matmul_bias_act" in reg:
        desc = reg.get("fused_matmul_bias_act")
        if "tpu" not in desc.platform_impls:
            reg.register_platform("fused_matmul_bias_act", "tpu",
                                  fused_matmul_helper, _usable)
            _validation.add_case("fused_matmul_bias_act",
                                 _check_fused_matmul_bias_act)
