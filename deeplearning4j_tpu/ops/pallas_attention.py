"""Pallas flash-attention — the cuDNN-platform-helper analog for attention.

Reference parity: libnd4j exposes dot_product_attention as a materialized
O(T²)-memory generic op (SURVEY §6.7 — the reference has NO flash/blockwise
attention). This kernel is the TPU "platform helper" upgrade: blockwise
online-softmax attention that never materializes the (T, T) score matrix,
registered into the op registry's platform table exactly where a cuDNN
helper would override the generic impl (registry.resolve — SURVEY §8.1).

Kernel design (per pallas_guide.md):
  * grid = (batch*heads, T_q/block_q); each program owns one q block in VMEM.
  * inner fori_loop walks k/v blocks, carrying (acc, running max m, running
    denom l) — the FlashAttention-2 recurrence; both matmuls per step hit
    the MXU at (block_q × d) @ (d × block_k) and (block_q × block_k) @
    (block_k × d).
  * forward-only: backward falls back to the XLA generic op (jax.custom_vjp
    recomputes with the generic path), so training still differentiates.

Runs in interpret mode off-TPU so CPU tests exercise the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU-capable builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, scale: float,
                 causal: bool, block_q: int, kv_len: int):
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    t_kv = k_ref.shape[1]
    n_kb = t_kv // block_k
    qi = pl.program_id(1)

    def body(ki, carry):
        acc, m, l = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        s = q @ kblk.T  # (block_q, block_k)
        k_pos = ki * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        if kv_len < t_kv:  # zero-padded keys must not receive softmax mass
            s = jnp.where(k_pos < kv_len, s, -1e30)
        if causal:
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, -1e30)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + p @ vblk
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((q.shape[0], v_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((q.shape[0], 1), -1e30, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def _flash_fwd(q, k, v, *, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    block_q = min(block_q, t_q)
    block_k = min(block_k, t_kv)
    pad_q = (-t_q) % block_q
    pad_k = (-t_kv) % block_k
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        # padded kv keys must never win the softmax: pad k with -inf-ish is
        # unsafe for matmul; instead pad normally and mask via causal-style
        # position check — simpler: pad and rely on explicit length masking
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    grid = (bh, (t_q + pad_q) // block_q)
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, scale=scale, causal=causal,
        block_q=block_q, kv_len=t_kv)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((bh, t_q + pad_q, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, k.shape[1], d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, v.shape[1], d), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v)
    return out[:, :t_q]


def _reference_attention(q, k, v, *, scale: float, causal: bool):
    """The generic O(T²) path (libnd4j dot_product_attention math) — used
    for the backward pass and as the platform fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(q, k, v, scale: Optional[float] = None, causal: bool = False,
                    block_q: int = 128, block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blockwise attention over (BH, T, D) tensors (fold batch×heads first).

    Forward runs the Pallas kernel; backward re-computes through the XLA
    generic path (standard flash-training trades FLOPs for HBM)."""
    return _flash_call(q, k, v, scale, causal, block_q, block_k, interpret)


def _resolve_interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _flash_call(q, k, v, scale, causal, block_q, block_k, interpret):
    if causal and q.shape[1] != k.shape[1]:
        # the kernel's causal mask is start-aligned on raw positions; the
        # backward/reference path is end-aligned — they only agree for
        # t_q == t_kv, so reject the ambiguous case instead of silently
        # training against a different attention pattern
        raise ValueError(
            f"causal flash attention requires t_q == t_kv, got "
            f"{q.shape[1]} vs {k.shape[1]}")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, scale=scale, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=_resolve_interpret(interpret))


def _fwd(q, k, v, scale, causal, block_q, block_k, interpret):
    return _flash_call(q, k, v, scale, causal, block_q, block_k, interpret), (q, k, v)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])

    def ref(q, k, v):
        return _reference_attention(q, k, v, scale=s, causal=causal)

    _, vjp = jax.vjp(ref, q, k, v)
    return vjp(g)


flash_attention.defvjp(_fwd, _bwd)


def flash_mha(q, k, v, *, num_heads: int, causal: bool = False,
              interpret: Optional[bool] = None):
    """(N, T, H*dh) convenience wrapper: split heads, run flash, re-merge."""
    n, t, d = q.shape
    dh = d // num_heads

    def split(a):
        return a.reshape(n, a.shape[1], num_heads, dh).transpose(0, 2, 1, 3) \
                .reshape(n * num_heads, a.shape[1], dh)

    out = flash_attention(split(q), split(k), split(v), None, causal,
                          128, 128, interpret)
    return out.reshape(n, num_heads, t, dh).transpose(0, 2, 1, 3).reshape(n, t, d)


def register_platform_attention() -> None:
    """Install flash attention as the TPU platform override for the generic
    dot_product_attention op (the cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()

    def flash_dpa(q, k, v, mask=None, *, scaled: bool = True):
        # usable() guarantees mask is None and q is 3-D (BH, T, D)
        scale = (1.0 / math.sqrt(q.shape[-1])) if scaled else 1.0
        return flash_attention(q, k, v, scale, False, 128, 128, None)

    def usable(q, k, v, mask=None, **kw):
        return mask is None and q.ndim == 3 and q.shape[-1] % 8 == 0

    if "dot_product_attention" in reg:
        reg.register_platform("dot_product_attention", "tpu", flash_dpa, usable)
