"""Pallas flash-attention — the cuDNN-platform-helper analog for attention.

Reference parity: libnd4j exposes dot_product_attention as a materialized
O(T²)-memory generic op (SURVEY §6.7 — the reference has NO flash/blockwise
attention). This kernel is the TPU "platform helper" upgrade: blockwise
online-softmax attention that never materializes the (T, T) score matrix,
registered into the op registry's platform table exactly where a cuDNN
helper would override the generic impl (registry.resolve — SURVEY §8.1).
Registration happens at package import (deeplearning4j_tpu.ops), the analog
of libnd4j's OpRegistrator static init.

Kernel design (per pallas_guide.md):
  * grid = (batch*heads, T_q/block_q, T_kv/block_k) with the kv walk as the
    innermost 'arbitrary' dimension: Mosaic streams ONE (block_k, d) k/v
    tile per step, so VMEM stays O(block) no matter how long the sequence
    is (whole-sequence kv refs OOM'd scoped VMEM at T=8192). The
    FlashAttention-2 running state (acc, row max m, denom l) lives in VMEM
    scratch across the kv iterations of a q block; both matmuls per step
    hit the MXU in the operands' NATIVE dtype with f32 accumulation (an
    up-front f32 cast forces Mosaic's multi-pass f32 path — measured ~8×
    slower for bf16 inputs). The forward also emits log-sum-exp rows.
  * backward is Pallas too (FlashAttention-2 backward): a dq kernel and a
    dk/dv kernel with the same streaming-grid shape, recomputing
    p = exp(s - lse) blockwise so the (T, T) score matrix never exists in
    HBM in either direction.
  * layouts avoid lane-1 tensors: the key mask rides (BH, n_blocks, 8,
    block_k) full-trailing-dim blocks (kv positions on the lane axis) and
    lse/delta ride (…, 8) broadcast buffers. Lane-1 ((T, 1)) masks/rows
    force padded tiles and in-kernel transposes — measured 9× end-to-end
    slowdown and spurious scoped-VMEM OOMs at wide blocks.
  * attention-prob dropout runs INSIDE the kernel (counter-based hash on
    absolute (head, row, col) positions → threshold-on-uniform), so the
    backward kernels regenerate the identical keep mask from the same seed
    instead of materializing a (T, T) mask in HBM. The softmax denominator
    is accumulated un-dropped (dropout applies after normalization,
    matching the reference's post-softmax dropout semantics).
  * block sizes default to 512 (capped to T): fewer, fatter grid steps
    amortize per-step overhead. Speedups vs the XLA generic are recorded
    per-round in BENCH_HISTORY.json (attention entries), not claimed here.

Runs in interpret mode off-TPU so CPU tests exercise the same code path.
"""

from __future__ import annotations

import functools
import logging
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# pltpu ships with jax's pallas package and is needed even in interpret mode
# (VMEM scratch allocations); a build without it cannot run these kernels.
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

# Shortest kv length at which the Pallas kernel beats the XLA fused /
# generic materialized paths on-chip. BENCH_HISTORY.json 'attention_sweep'
# shows flash at 0.65-0.99x vs XLA below t=4096 (grid overhead dominates),
# so the fallback crossover is 4096; the measured per-device value lives in
# the tuning table (ops/tuning.py, refreshed by tools/tune.py or
# tools/bench_attention_sweep.py) and DL4J_TPU_FLASH_MIN_T still wins.
FLASH_MIN_T_DEFAULT = 4096

# parse-once cache: (raw env string, device kind, resolved threshold).
# Re-parsing (and re-warning) on every resolve call was the round-9 bugfix
# target; the raw string keys the cache so env re-pointing and
# monkeypatching stay live, and the device kind keys it because the tuned
# fallback is per-device — a CPU-scoped resolve (the consistency suite
# runs under jax.default_device(cpu)) must not pin the CPU table's
# threshold for subsequent TPU resolves.
_FLASH_MIN_T_CACHE: "Optional[tuple]" = None


def reset_flash_min_t_cache() -> None:
    """Test seam + tuning-table invalidation hook."""
    global _FLASH_MIN_T_CACHE
    _FLASH_MIN_T_CACHE = None


def _tuned_flash_min_t() -> int:
    from deeplearning4j_tpu.ops import tuning

    return int(tuning.tuned("dot_product_attention", "flash_min_t",
                            FLASH_MIN_T_DEFAULT))


def flash_min_t() -> int:
    """Live dispatch threshold: kv lengths below this use the XLA path.

    Resolution order: ``DL4J_TPU_FLASH_MIN_T`` env override, then the
    measured tuning table for the target device kind, then the checked-in
    default. The parsed value is cached against the raw env string, so a
    serving process can still be re-pointed without code changes but the
    parse (and the invalid-value warning) happen once per distinct value,
    not once per resolve call."""
    import os

    from deeplearning4j_tpu.ops import tuning

    global _FLASH_MIN_T_CACHE
    raw = os.environ.get("DL4J_TPU_FLASH_MIN_T")
    # kind participates even with the env set: the invalid-raw fallback is
    # the tuned (per-device) value too. jax memoizes the devices() probe.
    kind = tuning.current_device_kind()
    if _FLASH_MIN_T_CACHE is not None and _FLASH_MIN_T_CACHE[:2] == (raw,
                                                                    kind):
        return _FLASH_MIN_T_CACHE[2]
    if raw:
        try:
            val = int(raw)
        except ValueError:
            val = _tuned_flash_min_t()
            logger.warning(
                "invalid DL4J_TPU_FLASH_MIN_T=%r — falling back to the "
                "tuned/default threshold %d", raw, val)
    else:
        val = _tuned_flash_min_t()
    _FLASH_MIN_T_CACHE = (raw, kind, val)
    return val


def _keep_mask(seed, bh, q0, k0, *, block_q: int, block_k: int, rate: float):
    """Deterministic per-element keep mask for one (block_q, block_k) tile.

    Counter-based: a murmur-style integer mix of (seed, batch·head, absolute
    row, absolute col) thresholded against the rate. Both backward kernels
    call this with the same absolute coordinates, regenerating the exact
    forward mask — the FlashAttention dropout recipe, with a stateless hash
    instead of saved RNG state so it runs identically under Mosaic and
    interpret mode."""
    rows = q0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    cols = k0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    h = seed + bh * jnp.int32(7919) \
        + rows * jnp.int32(1103515245) + cols * jnp.int32(1299709)
    h = h ^ jax.lax.shift_right_logical(h, 13)
    h = h * jnp.int32(1274126177)
    h = h ^ jax.lax.shift_right_logical(h, 16)
    u = (h & jnp.int32(0xFFFFFF)).astype(jnp.float32) * (1.0 / (1 << 24))
    return u >= rate


def _mm(a, b, dims):
    """MXU matmul with f32 accumulation in the operands' NATIVE dtype.

    Casting operands up to f32 before the dot forces Mosaic's multi-pass
    f32 MXU path (~8× slower); bf16 inputs should hit the native bf16 MXU
    with an f32 accumulator. Mixed-dtype pairs cast the wider operand DOWN
    to the narrower one — the FlashAttention convention for p @ v (the f32
    softmax probs drop to the input dtype for the second matmul)."""
    if a.dtype != b.dtype:
        narrow = a.dtype if a.dtype.itemsize <= b.dtype.itemsize else b.dtype
        a, b = a.astype(narrow), b.astype(narrow)
    # precision pinned explicitly: an ambient default_matmul_precision
    # context (the f32 dtype policy sets 'high') must not leak into the
    # kernel — Mosaic only lowers DEFAULT/HIGHEST, and operand dtype plus
    # the f32 accumulator already define this kernel's numerics
    return jax.lax.dot_general(a, b, (dims, ((), ())),
                               preferred_element_type=jnp.float32,
                               precision=jax.lax.Precision.DEFAULT)


def _mm_nt(a, b):  # a @ b.T
    return _mm(a, b, ((1,), (1,)))


def _mm_nn(a, b):  # a @ b
    return _mm(a, b, ((1,), (0,)))


def _mm_tn(a, b):  # a.T @ b
    return _mm(a, b, ((0,), (0,)))


def _mask_scores(s, qi, ki_start, mblk, *, block_q: int, block_k: int,
                 causal: bool):
    """Apply the kv mask row and the causal mask to one (block_q, block_k)
    tile. mblk: (1, block_k) 0/1 — covers both user key-padding and kv
    zero-padding."""
    s = jnp.where(mblk > 0.5, s, -1e30)
    if causal:
        k_pos = ki_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = jnp.where(q_pos >= k_pos, s, -1e30)
    return s


def _attn_kernel(q_ref, k_ref, v_ref, m_ref, seed_ref, o_ref, lse_ref,
                 acc_ref, mx_ref, l_ref, *, block_k: int, scale: float,
                 causal: bool, block_q: int, dropout_rate: float):
    """One (q-block, kv-block) grid step. The kv walk is the innermost
    ('arbitrary') grid dimension so Mosaic streams one (block_k, d) k/v tile
    per step — VMEM stays O(block) regardless of T (whole-sequence kv refs
    blew the 16 MB scoped-VMEM budget at T=8192). The FlashAttention-2
    running state (acc, row max, denom) lives in VMEM scratch across the kv
    iterations of one q block."""
    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        mx_ref[:] = jnp.full_like(mx_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        vblk = v_ref[0]
        mblk = m_ref[0, 0, :1]  # (1, block_k)
        s = _mm_nt(q_ref[0], k_ref[0]) * scale  # f32 (block_q, block_k)
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        m_prev = mx_ref[:, :1]
        m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        # denominator accumulates UN-dropped p: softmax normalizes first,
        # dropout hits the normalized probs (reference post-softmax order)
        l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0, 0], bh, qi * block_q, ki * block_k,
                              block_q=block_q, block_k=block_k,
                              rate=dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
        acc_ref[:] = acc_ref[:] * alpha + _mm_nn(p, vblk)
        mx_ref[:, :1] = m_new

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # lse rides a 128-lane buffer (value broadcast) to dodge lane-1 tiles
        lse_ref[0] = jnp.broadcast_to(mx_ref[:, :1] + jnp.log(l),
                                      lse_ref.shape[1:])


def _dq_kernel(q_ref, k_ref, v_ref, m_ref, seed_ref, do_ref, lse_ref,
               delta_ref, dq_ref, acc_ref, *, block_k: int, scale: float,
               causal: bool, block_q: int, dropout_rate: float):
    """dq_i = scale * Σ_j p_ij (dO_i·v_j·keep/(1-r) - Δ_i) k_j, p from lse.
    Grid (bh, q blocks, kv blocks): kv streams innermost, dq accumulates in
    VMEM scratch."""
    bh, qi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        lse = lse_ref[0][:, :1]  # (block_q, 1) row of the 128-lane buffer
        delta = delta_ref[0][:, :1]
        kblk = k_ref[0]
        mblk = m_ref[0, 0, :1]  # (1, block_k)
        s = _mm_nt(q_ref[0], kblk) * scale
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        p = jnp.exp(s - lse)
        dp = _mm_nt(do_ref[0], v_ref[0])
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0, 0], bh, qi * block_q, ki * block_k,
                              block_q=block_q, block_k=block_k,
                              rate=dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)
        acc_ref[:] = acc_ref[:] + _mm_nn(ds, kblk)

    @pl.when(ki == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = (acc_ref[:] * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, m_ref, seed_ref, do_ref, lse_ref,
                delta_ref, dk_ref, dv_ref, dk_acc, dv_acc, *, block_q: int,
                scale: float, causal: bool, block_k: int,
                dropout_rate: float):
    """dk_j = Σ_i ds_ij (scale·q_i); dv_j = Σ_i p̃_ij dO_i. Grid (bh, kv
    blocks, q blocks): q streams innermost, dk/dv accumulate in VMEM scratch
    (zero-padded q rows contribute nothing since their dO rows are zero).
    p̃ is the dropped/rescaled prob when dropout is on."""
    bh, ki, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    run = (ki * block_k <= qi * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _step():
        kblk = k_ref[0]  # (block_k, d)
        mblk = m_ref[0, 0, :1]  # (1, block_k)
        qblk = q_ref[0]
        do = do_ref[0]
        lse = lse_ref[0][:, :1]  # (block_q, 1) row of the 128-lane buffer
        delta = delta_ref[0][:, :1]
        s = _mm_nt(qblk, kblk) * scale  # (block_q, block_k)
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        p = jnp.exp(s - lse)
        dp = _mm_nt(do, v_ref[0])
        if dropout_rate > 0.0:
            keep = _keep_mask(seed_ref[0, 0], bh, qi * block_q, ki * block_k,
                              block_q=block_q, block_k=block_k,
                              rate=dropout_rate)
            p_drop = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_drop = p
        ds = p * (dp - delta) * scale  # fold dk's scale factor in here
        dk_acc[:] = dk_acc[:] + _mm_tn(ds, qblk)
        dv_acc[:] = dv_acc[:] + _mm_tn(p_drop, do)

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _pad_to_blocks(q, k, v, kv_mask, block_q, block_k):
    """Pad sequence dims to block multiples; fold kv padding and the user
    key mask into one (BH, T_kv_padded) 0/1 f32 tensor; builders reshape it
    to (BH, n_kv_blocks, 8, block_k) (8 broadcast sublanes — Mosaic requires
    the last two block dims divisible by (8, 128) or full) so each grid step
    gets its mask row as a FULL trailing-dim block — the kv positions stay on the lane
    axis (a lane-1 (T_kv, 1) layout forces padded tiles and in-kernel
    transposes; measured 9× slower end-to-end) and no Mosaic lane-alignment
    constraint applies at any block size."""
    bh, t_q, d = q.shape
    t_kv = k.shape[1]

    def clamp(block, t):
        # cap to the (rounded-up) seq len, then round up to a multiple of 8
        # — Pallas requires sublane-dim blocks divisible by 8
        return -(-min(block, max(t, 8)) // 8) * 8

    block_q = clamp(block_q, t_q)
    block_k = clamp(block_k, t_kv)
    pad_q = (-t_q) % block_q
    pad_k = (-t_kv) % block_k
    if kv_mask is None:
        m = jnp.ones((bh, t_kv), jnp.float32)
    else:
        m = jnp.broadcast_to(kv_mask.reshape(bh, t_kv).astype(jnp.float32),
                             (bh, t_kv))
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
        m = jnp.pad(m, ((0, 0), (0, pad_k)))  # padded keys masked out
    return q, k, v, m, block_q, block_k, pad_q, pad_k


def _default_blocks(block_q, block_k, t_kv: Optional[int] = None):
    """Default tile size 512 (capped to T by _pad_to_blocks): fewer, fatter
    grid steps amortize per-step overhead — measured 14.8 ms vs 26 ms
    (block 128) for a T=8192 d=64 forward on a v5e. The lane-1 mask/lse
    layouts were what made wide blocks OOM scoped VMEM before; with 128-lane
    buffers every probed shape (T=512…8192, fwd+bwd) compiles at 512.

    When the caller passed no explicit block, the measured tuning table
    (ops/tuning.py, keyed on device kind + kv-length bucket) overrides the
    512 fallback — the autotuner's winners feed real dispatch."""
    if block_q is None or block_k is None:
        from deeplearning4j_tpu.ops import tuning

        bucket = tuning.bucket_t(t_kv) if t_kv else None
        if block_q is None:
            block_q = int(tuning.tuned("dot_product_attention", "block_q",
                                       512, bucket=bucket))
        if block_k is None:
            block_k = int(tuning.tuned("dot_product_attention", "block_k",
                                       512, bucket=bucket))
    return block_q, block_k


def _compiler_params(interpret):
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary"))


def _flash_fwd(q, k, v, kv_mask, seed, *, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool,
               dropout_rate: float):
    bh, t_q, d = q.shape
    q, k, v, m, block_q, block_k, pad_q, _ = _pad_to_blocks(
        q, k, v, kv_mask, block_q, block_k)
    tkv_p = k.shape[1]
    m = jnp.broadcast_to(m.reshape(bh, tkv_p // block_k, 1, block_k),
                         (bh, tkv_p // block_k, 8, block_k))
    grid = (bh, (t_q + pad_q) // block_q, tkv_p // block_k)
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, scale=scale, causal=causal,
        block_q=block_q, dropout_rate=dropout_rate)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q + pad_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q + pad_q, 8), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, 8, block_k), lambda b, i, j: (b, j, 0, 0)),
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, m, seed)
    return out[:, :t_q], lse[:, :t_q]


def _flash_bwd(q, k, v, kv_mask, seed, out, lse, g, *, scale: float,
               causal: bool, block_q: int, block_k: int, interpret: bool,
               dropout_rate: float):
    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, t_q, 1)
    delta = jnp.broadcast_to(delta, (bh, t_q, 8))  # 8-lane buffer
    q, k, v, m, block_q, block_k, pad_q, pad_k = _pad_to_blocks(
        q, k, v, kv_mask, block_q, block_k)
    if pad_q:
        g = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, pad_q), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, pad_q), (0, 0)))
    tq_p, tkv_p = t_q + pad_q, t_kv + pad_k
    m = jnp.broadcast_to(m.reshape(bh, tkv_p // block_k, 1, block_k),
                         (bh, tkv_p // block_k, 8, block_k))

    dq_kernel = functools.partial(
        _dq_kernel, block_k=block_k, scale=scale, causal=causal,
        block_q=block_q, dropout_rate=dropout_rate)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
        grid=(bh, tq_p // block_q, tkv_p // block_k),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, 1, 8, block_k), lambda b, i, j: (b, j, 0, 0)),
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, m, seed, g, lse, delta)

    dkv_kernel = functools.partial(
        _dkv_kernel, block_q=block_q, scale=scale, causal=causal,
        block_k=block_k, dropout_rate=dropout_rate)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkv_p, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tkv_p, d), v.dtype),
        ],
        grid=(bh, tkv_p // block_k, tq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, 1, 8, block_k), lambda b, j, i: (b, j, 0, 0)),
            pl.BlockSpec((1, 1), lambda b, j, i: (0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 8), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(q, k, v, m, seed, g, lse, delta)
    return dq[:, :t_q], dk[:, :t_kv], dv[:, :t_kv]


def _reference_attention(q, k, v, *, scale: float, causal: bool, kv_mask=None):
    """The generic O(T²) path (libnd4j dot_product_attention math) — used
    as oracle and platform fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask.reshape(q.shape[0], 1, k.shape[1]) > 0.5, s, -1e30)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def flash_attention(q, k, v, kv_mask=None, dropout_seed=None,
                    scale: Optional[float] = None, causal: bool = False,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None,
                    dropout_rate: float = 0.0):
    """Blockwise attention over (BH, T, D) tensors (fold batch×heads first).

    ``kv_mask``: optional (BH, T_kv) 0/1 key-padding mask (1 = attend).
    ``dropout_rate``/``dropout_seed``: post-softmax attention-prob dropout
    applied inside the kernels (seed: any int32 array; None with rate>0 is an
    error). block_q/block_k=None picks VMEM-safe defaults. Forward AND
    backward run Pallas kernels (FlashAttention-2 recurrences); neither the
    (T, T) score matrix nor the dropout mask ever reaches HBM."""
    return _flash_call(q, k, v, kv_mask, dropout_seed, scale, causal,
                       block_q, block_k, interpret, dropout_rate)[0]


def _resolve_interpret(interpret):
    if interpret is not None:
        return interpret
    from deeplearning4j_tpu.ops.registry import current_platform

    return current_platform() != "tpu"


def _norm_seed(dropout_seed, dropout_rate):
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("flash attention dropout_rate > 0 needs dropout_seed")
    if dropout_seed is None:
        return jnp.zeros((1, 1), jnp.int32)
    return jnp.asarray(dropout_seed).reshape(-1)[:1].astype(jnp.int32) \
              .reshape(1, 1)


def _flash_call(q, k, v, kv_mask, dropout_seed, scale, causal, block_q,
                block_k, interpret, dropout_rate):
    if causal and q.shape[1] != k.shape[1]:
        # the kernel's causal mask is start-aligned on raw positions; the
        # reference path is end-aligned — they only agree for t_q == t_kv,
        # so reject the ambiguous case instead of silently training against
        # a different attention pattern
        raise ValueError(
            f"causal flash attention requires t_q == t_kv, got "
            f"{q.shape[1]} vs {k.shape[1]}")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k = _default_blocks(block_q, block_k, k.shape[1])
    seed = _norm_seed(dropout_seed, dropout_rate)
    return _flash_fwd(q, k, v, kv_mask, seed, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k,
                      interpret=_resolve_interpret(interpret),
                      dropout_rate=dropout_rate)


def _fwd(q, k, v, kv_mask, dropout_seed, scale, causal, block_q, block_k,
         interpret, dropout_rate):
    out, lse = _flash_call(q, k, v, kv_mask, dropout_seed, scale, causal,
                           block_q, block_k, interpret, dropout_rate)
    return out, (q, k, v, kv_mask, dropout_seed, out, lse)


def _bwd(scale, causal, block_q, block_k, interpret, dropout_rate, res, g):
    q, k, v, kv_mask, dropout_seed, out, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    block_q, block_k = _default_blocks(block_q, block_k, k.shape[1])
    seed = _norm_seed(dropout_seed, dropout_rate)
    dq, dk, dv = _flash_bwd(q, k, v, kv_mask, seed, out, lse, g, scale=s,
                            causal=causal, block_q=block_q, block_k=block_k,
                            interpret=_resolve_interpret(interpret),
                            dropout_rate=dropout_rate)
    return dq, dk, dv, None, None


flash_attention.defvjp(_fwd, _bwd)


def rng_to_seed(rng):
    """Fold a JAX PRNG key (typed or raw uint32) into a (1,1) int32 kernel
    seed. None passes through."""
    if rng is None:
        return None
    try:
        data = jax.random.key_data(rng)
    except Exception:
        data = jnp.asarray(rng)
    return data.reshape(-1)[-1:].astype(jnp.int32).reshape(1, 1)


def flash_mha(q, k, v, *, num_heads: int, causal: bool = False,
              kv_mask=None, interpret: Optional[bool] = None,
              dropout_rate: float = 0.0, dropout_rng=None):
    """(N, T, H*dh) convenience wrapper: split heads, run flash, re-merge.
    ``kv_mask``: optional (N, T_kv) key-padding mask; ``dropout_rng``: a JAX
    PRNG key enabling in-kernel attention-prob dropout."""
    n, t, d = q.shape
    dh = d // num_heads

    def split(a):
        return a.reshape(n, a.shape[1], num_heads, dh).transpose(0, 2, 1, 3) \
                .reshape(n * num_heads, a.shape[1], dh)

    m = None
    if kv_mask is not None:
        m = jnp.repeat(kv_mask.astype(jnp.float32), num_heads, axis=0)
    out = flash_attention(split(q), split(k), split(v), m,
                          rng_to_seed(dropout_rng), None, causal,
                          None, None, interpret, dropout_rate)
    return out.reshape(n, num_heads, t, dh).transpose(0, 2, 1, 3).reshape(n, t, d)


# ---------------------------------------------------------------------------
# Paged decode attention — the serving-side kernel (docs/SERVING.md)
# ---------------------------------------------------------------------------
#
# Generation serves ONE query token per sequence against a block-paged KV
# cache (vLLM/PagedAttention layout): K/V live in fixed-size pages
# (num_pages, page_size, heads, head_dim) and each sequence owns a page-table
# row of page indices. The decode step therefore needs a gather-attention:
# softmax(q · K[pages]) · V[pages] with positions >= seq_len masked out.
#
# Two implementations, selected through the registry platform table exactly
# like flash attention above:
#   * `paged_decode_attention_xla` — generic: gather the page table with
#     fancy indexing and run masked attention; runs anywhere (the CPU-host
#     fallback) at the cost of materializing the gathered (S, T_max, H, D)
#     keys in HBM.
#   * `_paged_decode_call` — Pallas: grid (slot, page) with the page walk
#     innermost; the page table rides scalar-prefetch (PrefetchScalarGridSpec)
#     so each grid step DMAs exactly ONE (page_size, H, D) K/V tile straight
#     from its paged HBM home — the gathered contiguous copy never exists.
#     Online-softmax running state lives in VMEM scratch across the page
#     walk of one slot (the FlashAttention-2 recurrence, page-granular).


def paged_decode_attention_xla(q, k_pages, v_pages, page_table, seq_lens, *,
                               scale: Optional[float] = None):
    """Generic gather path: q:[S,H,D], k/v_pages:[P,page,H,D],
    page_table:[S,max_pages] int32, seq_lens:[S] int32 -> [S,H,D].

    Scores accumulate in f32 regardless of cache dtype (matches the Pallas
    kernel's preferred_element_type accumulators)."""
    s_n, h, d = q.shape
    page = k_pages.shape[1]
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    k = k_pages[page_table].reshape(s_n, max_pages * page, h, d)
    v = v_pages[page_table].reshape(s_n, max_pages * page, h, d)
    s = jnp.einsum("shd,sthd->sht", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    pos = jnp.arange(max_pages * page)
    s = jnp.where(pos[None, None, :] < seq_lens[:, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("sht,sthd->shd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def _paged_decode_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                         acc_ref, mx_ref, l_ref, *, page: int, scale: float,
                         heads: int):
    """One (slot, page) grid step. The per-head q·K dots run as unrolled 2D
    matmuls (heads is static and small at decode) — Mosaic lowers plain 2D
    dots reliably where a batched dot_general would not; M=1 rows waste MXU
    lanes but decode is memory-bound on the K/V stream, not FLOP-bound."""
    s_idx, j = pl.program_id(0), pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        mx_ref[:] = jnp.full_like(mx_ref, -1e30)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]        # (H, D)
    kblk = k_ref[0]     # (page, H, D)
    vblk = v_ref[0]
    seq_len = sl_ref[s_idx]

    rows = [_mm_nt(q[h:h + 1, :], kblk[:, h, :]) for h in range(heads)]
    s = jnp.concatenate(rows, axis=0) * scale   # f32 (H, page)
    pos = j * page + jax.lax.broadcasted_iota(jnp.int32, (heads, page), 1)
    s = jnp.where(pos < seq_len, s, -1e30)

    m_prev = mx_ref[:, :1]
    m_new = jnp.maximum(m_prev, s.max(axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    alpha = jnp.exp(m_prev - m_new)
    l_ref[:, :1] = l_ref[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
    outs = [_mm_nn(p[h:h + 1, :], vblk[:, h, :]) for h in range(heads)]
    acc_ref[:] = acc_ref[:] * alpha + jnp.concatenate(outs, axis=0)
    mx_ref[:, :1] = m_new

    @pl.when(j == pl.num_programs(1) - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _paged_decode_call(q, k_pages, v_pages, page_table, seq_lens, *,
                       scale: Optional[float] = None,
                       interpret: Optional[bool] = None):
    """Pallas paged decode. Same contract as paged_decode_attention_xla."""
    s_n, h, d = q.shape
    page = k_pages.shape[1]
    max_pages = page_table.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    interpret = _resolve_interpret(interpret)
    kernel = functools.partial(_paged_decode_kernel, page=page, scale=scale,
                               heads=h)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(s_n, max_pages),
        in_specs=[
            pl.BlockSpec((1, h, d), lambda s, j, pt, sl: (s, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda s, j, pt, sl: (pt[s, j], 0, 0, 0)),
            pl.BlockSpec((1, page, h, d),
                         lambda s, j, pt, sl: (pt[s, j], 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, h, d), lambda s, j, pt, sl: (s, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((h, d), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
            pltpu.VMEM((h, 128), jnp.float32),
        ],
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_n, h, d), q.dtype),
        interpret=interpret,
    )(page_table.astype(jnp.int32), seq_lens.astype(jnp.int32),
      q, k_pages, v_pages)


def _paged_usable(q, k_pages, v_pages, page_table, seq_lens, **kw):
    """PlatformHelper::isUsable for the Pallas paged path: shapes must be
    the documented ranks, the page/head-dim tiles Mosaic-aligned, and the
    page walk long enough to beat the XLA gather (measured min_pages from
    the tuning table; default 1 = always, matching pre-tuning behavior)."""
    if getattr(q, "ndim", 0) != 3 or getattr(k_pages, "ndim", 0) != 4:
        return False
    if getattr(page_table, "ndim", 0) != 2 or getattr(seq_lens, "ndim", 0) != 1:
        return False
    from deeplearning4j_tpu.ops import tuning

    if page_table.shape[1] < int(tuning.tuned("paged_decode_attention",
                                              "min_pages", 1)):
        return False
    return q.shape[-1] % 8 == 0 and k_pages.shape[1] % 8 == 0


def _check_paged_decode_attention():
    """Validation case (ops.validation ratchet): XLA gather path vs a
    straight numpy oracle, and the Pallas interpret kernel vs both."""
    import numpy as np

    r = np.random.RandomState(7)
    s_n, h, d, page, n_pages, max_pages = 3, 4, 16, 8, 10, 3
    q = r.randn(s_n, h, d).astype(np.float32)
    kp = r.randn(n_pages, page, h, d).astype(np.float32)
    vp = r.randn(n_pages, page, h, d).astype(np.float32)
    pt = np.stack([r.choice(n_pages, max_pages, replace=False)
                   for _ in range(s_n)]).astype(np.int32)
    sl = np.array([5, 17, 24], np.int32)
    scale = 1.0 / math.sqrt(d)
    want = np.zeros_like(q)
    for i in range(s_n):
        gk = kp[pt[i]].reshape(-1, h, d)[:sl[i]]
        gv = vp[pt[i]].reshape(-1, h, d)[:sl[i]]
        for hh in range(h):
            sc = gk[:, hh] @ q[i, hh] * scale
            p = np.exp(sc - sc.max())
            p = p / p.sum()
            want[i, hh] = p @ gv[:, hh]
    got = paged_decode_attention_xla(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(pt), jnp.asarray(sl))
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)
    got_pl = _paged_decode_call(
        jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
        jnp.asarray(pt), jnp.asarray(sl), interpret=True)
    np.testing.assert_allclose(np.asarray(got_pl), want, rtol=1e-4, atol=1e-5)


def register_platform_attention() -> None:
    """Install flash attention as the TPU platform override for the generic
    dot_product_attention op, and register the paged decode-attention op
    (generic gather impl + Pallas TPU helper) — the cuDNN PlatformHelper
    pattern both times."""
    from deeplearning4j_tpu.ops.registry import registry
    from deeplearning4j_tpu.ops import validation as _validation

    reg = registry()

    if "paged_decode_attention" not in reg:
        reg.register(
            "paged_decode_attention", paged_decode_attention_xla,
            doc="decode-step attention over a block-paged KV cache "
                "(q:[S,H,D], k/v_pages:[P,page,H,D], page_table:[S,max_pages],"
                " seq_lens:[S] -> [S,H,D])")
        reg.register_platform("paged_decode_attention", "tpu",
                              _paged_decode_call, _paged_usable)
        _validation.add_case("paged_decode_attention",
                             _check_paged_decode_attention)

    def flash_dpa(q, k, v, mask=None, *, scaled: bool = True,
                  causal: bool = False,
                  dropout_rate: float = 0.0, dropout_rng=None):
        scale = (1.0 / math.sqrt(q.shape[-1])) if scaled else 1.0
        if dropout_rate > 0.0 and dropout_rng is None:
            raise ValueError(
                "dot_product_attention: dropout_rate > 0 requires dropout_rng "
                "(pass None rate for eval mode)")
        seed = rng_to_seed(dropout_rng) if dropout_rate > 0.0 else None
        rate = dropout_rate
        if q.ndim == 4:  # (B, H, T, D) + key mask broadcast (B, 1, 1, Tk)
            b, h, t, d = q.shape
            tk = k.shape[2]
            fold = lambda a: a.reshape(b * h, a.shape[2], a.shape[3])
            m = None
            if mask is not None:
                m = jnp.repeat(mask.reshape(b, tk).astype(jnp.float32), h, axis=0)
            out = flash_attention(fold(q), fold(k), fold(v), m, seed, scale,
                                  causal, None, None, None, rate)
            return out.reshape(b, h, t, q.shape[-1])
        m = None if mask is None else mask.reshape(q.shape[0], k.shape[1])
        return flash_attention(q, k, v, m, seed, scale, causal, None, None,
                               None, rate)

    def usable(q, k, v, mask=None, **kw):
        # Measured crossover (BENCH_HISTORY.json 'attention_sweep'): below
        # the flash_min_t() threshold the materialized paths are FASTER
        # than the Pallas kernel (grid overhead dominates); above, Pallas
        # wins 1.5-3.6x vs XLA fused (the 19-25x rows at T=8192 are an XLA
        # shape pathology, not the typical win). Defer below the
        # crossover — PlatformHelper::isUsable (SURVEY §3.1). EXCEPT with
        # attention-prob dropout: the generic path materializes a (T, T)
        # bernoulli mask in HBM while flash regenerates it in-kernel,
        # which flips the crossover (BERT-base seq 512 w/ dropout 0.1:
        # 108k tok/s flash vs 77k generic — BENCH_HISTORY bert, round 4).
        t_kv = k.shape[2] if q.ndim == 4 else k.shape[1]
        if t_kv < flash_min_t() and not kw.get("dropout_rate", 0.0):
            return False
        if kw.get("causal"):
            # the kernel's causal mask is start-aligned; only t_q == t_kv
            # agrees with the reference end-aligned convention
            t_q = q.shape[2] if q.ndim == 4 else q.shape[1]
            if t_q != t_kv:
                return False
        if q.ndim == 3:
            mask_ok = mask is None or (
                hasattr(mask, "ndim") and mask.ndim in (2, 3)
                and mask.shape[-1] == k.shape[1]
                and (mask.ndim == 2 or mask.shape[1] == 1))
        elif q.ndim == 4:
            # key-padding broadcast mask only: (B, 1, 1, Tk)
            mask_ok = mask is None or (
                hasattr(mask, "ndim") and mask.ndim == 4
                and mask.shape[1] == 1 and mask.shape[2] == 1
                and mask.shape[-1] == k.shape[2])
        else:
            return False
        return mask_ok and q.shape[-1] % 8 == 0

    if "dot_product_attention" in reg:
        reg.register_platform("dot_product_attention", "tpu", flash_dpa, usable)


# tuned-value invalidation: a fresh tuning table (autotune save, test
# reset) must drop the memoized flash_min_t parse along with the tables.
from deeplearning4j_tpu.ops import tuning as _tuning

_tuning.on_reset(reset_flash_min_t_cache)
