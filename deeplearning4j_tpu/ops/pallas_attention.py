"""Pallas flash-attention — the cuDNN-platform-helper analog for attention.

Reference parity: libnd4j exposes dot_product_attention as a materialized
O(T²)-memory generic op (SURVEY §6.7 — the reference has NO flash/blockwise
attention). This kernel is the TPU "platform helper" upgrade: blockwise
online-softmax attention that never materializes the (T, T) score matrix,
registered into the op registry's platform table exactly where a cuDNN
helper would override the generic impl (registry.resolve — SURVEY §8.1).
Registration happens at package import (deeplearning4j_tpu.ops), the analog
of libnd4j's OpRegistrator static init.

Kernel design (per pallas_guide.md):
  * grid = (batch*heads, T_q/block_q); each program owns one q block in VMEM.
  * inner fori_loop walks k/v blocks, carrying (acc, running max m, running
    denom l) — the FlashAttention-2 recurrence; both matmuls per step hit
    the MXU. The forward also emits the log-sum-exp rows.
  * backward is Pallas too (FlashAttention-2 backward): a dq kernel gridded
    over q blocks and a dk/dv kernel gridded over kv blocks, both
    recomputing p = exp(s - lse) blockwise so the (T, T) score matrix never
    exists in HBM in either direction.
  * key-padding masks (BERT-style) ride a (BH, T_kv, 1) 0/1 tensor that the
    kernels consult per kv block; kv zero-padding folds into the same mask.

Measured on TPU v5 lite (d=64, causal, fwd+bwd): 1.2× the XLA generic at
T=1024, 2.4× at T=4096, 3.1× at T=8192.

Runs in interpret mode off-TPU so CPU tests exercise the same code path.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu only resolves on TPU-capable builds; interpret mode needs none
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except Exception:  # pragma: no cover
    pltpu = None
    _HAS_PLTPU = False


def _mask_scores(s, qi, ki_start, mblk, *, block_q: int, block_k: int,
                 causal: bool):
    """Apply the kv mask row and the causal mask to one (block_q, block_k)
    tile. mblk: (block_k, 1) 0/1 — covers both user key-padding and kv
    zero-padding."""
    s = jnp.where(mblk.reshape(1, block_k) > 0.5, s, -1e30)
    if causal:
        k_pos = ki_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        s = jnp.where(q_pos >= k_pos, s, -1e30)
    return s


def _attn_kernel(q_ref, k_ref, v_ref, m_ref, o_ref, lse_ref, *, block_k: int,
                 scale: float, causal: bool, block_q: int):
    q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
    t_kv = k_ref.shape[1]
    n_kb = t_kv // block_k
    qi = pl.program_id(1)

    def body(ki, carry):
        acc, m, l = carry
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        mblk = m_ref[0, pl.ds(ki * block_k, block_k), :]
        s = q @ kblk.T  # (block_q, block_k)
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + p @ vblk
        return acc_new, m_new, l_new

    acc0 = jnp.zeros((q.shape[0], v_ref.shape[-1]), jnp.float32)
    m0 = jnp.full((q.shape[0], 1), -1e30, jnp.float32)
    l0 = jnp.zeros((q.shape[0], 1), jnp.float32)
    acc, m, l = jax.lax.fori_loop(0, n_kb, body, (acc0, m0, l0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)
    lse_ref[0] = m + jnp.log(jnp.maximum(l, 1e-30))  # (block_q, 1)


def _dq_kernel(q_ref, k_ref, v_ref, m_ref, do_ref, lse_ref, delta_ref,
               dq_ref, *, block_k: int, scale: float, causal: bool,
               block_q: int):
    """dq_i = scale * Σ_j p_ij (dO_i·v_j - Δ_i) k_j, p recomputed from lse."""
    qs = q_ref[0].astype(jnp.float32) * scale
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0]  # (block_q, 1)
    delta = delta_ref[0]
    t_kv = k_ref.shape[1]
    n_kb = t_kv // block_k
    qi = pl.program_id(1)

    def body(ki, acc):
        kblk = k_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[0, pl.ds(ki * block_k, block_k), :].astype(jnp.float32)
        mblk = m_ref[0, pl.ds(ki * block_k, block_k), :]
        s = qs @ kblk.T
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        p = jnp.exp(s - lse)
        dp = do @ vblk.T
        ds = p * (dp - delta)
        return acc + ds @ kblk

    acc0 = jnp.zeros(qs.shape, jnp.float32)
    acc = jax.lax.fori_loop(0, n_kb, body, acc0)
    dq_ref[0] = (acc * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, m_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, block_q: int, scale: float, causal: bool,
                block_k: int):
    """dk_j = Σ_i ds_ij (scale·q_i); dv_j = Σ_i p_ij dO_i — kv-block grid,
    loop over q blocks (zero-padded q rows contribute nothing since their
    dO rows are zero)."""
    kblk = k_ref[0].astype(jnp.float32)  # (block_k, d)
    vblk = v_ref[0].astype(jnp.float32)
    mblk = m_ref[0]  # (block_k, 1)
    t_q = q_ref.shape[1]
    n_qb = t_q // block_q
    ki = pl.program_id(1)

    def body(qi, carry):
        dk, dv = carry
        qs = q_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32) * scale
        do = do_ref[0, pl.ds(qi * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, pl.ds(qi * block_q, block_q), :]  # (block_q, 1)
        delta = delta_ref[0, pl.ds(qi * block_q, block_q), :]
        s = qs @ kblk.T  # (block_q, block_k)
        s = _mask_scores(s, qi, ki * block_k, mblk, block_q=block_q,
                         block_k=block_k, causal=causal)
        p = jnp.exp(s - lse)
        dp = do @ vblk.T
        ds = p * (dp - delta)
        return dk + ds.T @ qs, dv + p.T @ do

    z = jnp.zeros(kblk.shape, jnp.float32)
    dk, dv = jax.lax.fori_loop(0, n_qb, body, (z, z))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _pad_to_blocks(q, k, v, kv_mask, block_q, block_k):
    """Pad sequence dims to block multiples; fold kv padding and the user
    key mask into one (BH, T_kv_padded, 1) 0/1 f32 tensor."""
    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    block_q = min(block_q, max(t_q, 8))
    block_k = min(block_k, max(t_kv, 8))
    pad_q = (-t_q) % block_q
    pad_k = (-t_kv) % block_k
    if kv_mask is None:
        m = jnp.ones((bh, t_kv), jnp.float32)
    else:
        m = jnp.broadcast_to(kv_mask.reshape(bh, t_kv).astype(jnp.float32),
                             (bh, t_kv))
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
        m = jnp.pad(m, ((0, 0), (0, pad_k)))  # padded keys masked out
    return q, k, v, m[..., None], block_q, block_k, pad_q, pad_k


def _flash_fwd(q, k, v, kv_mask, *, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    bh, t_q, d = q.shape
    q, k, v, m, block_q, block_k, pad_q, _ = _pad_to_blocks(
        q, k, v, kv_mask, block_q, block_k)
    tkv_p = k.shape[1]
    grid = (bh, (t_q + pad_q) // block_q)
    kernel = functools.partial(
        _attn_kernel, block_k=block_k, scale=scale, causal=causal,
        block_q=block_q)
    out, lse = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, t_q + pad_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, t_q + pad_q, 1), jnp.float32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tkv_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tkv_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tkv_p, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        interpret=interpret,
    )(q, k, v, m)
    return out[:, :t_q], lse[:, :t_q]


def _flash_bwd(q, k, v, kv_mask, out, lse, g, *, scale: float, causal: bool,
               block_q: int, block_k: int, interpret: bool):
    bh, t_q, d = q.shape
    t_kv = k.shape[1]
    delta = jnp.sum(g.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)  # (bh, t_q, 1)
    q, k, v, m, block_q, block_k, pad_q, pad_k = _pad_to_blocks(
        q, k, v, kv_mask, block_q, block_k)
    if pad_q:
        g = jnp.pad(g, ((0, 0), (0, pad_q), (0, 0)))
        lse = jnp.pad(lse, ((0, 0), (0, pad_q), (0, 0)))
        delta = jnp.pad(delta, ((0, 0), (0, pad_q), (0, 0)))
    tq_p, tkv_p = t_q + pad_q, t_kv + pad_k

    dq_kernel = functools.partial(
        _dq_kernel, block_k=block_k, scale=scale, causal=causal,
        block_q=block_q)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
        grid=(bh, tq_p // block_q),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tkv_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tkv_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tkv_p, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i: (b, i, 0)),
        interpret=interpret,
    )(q, k, v, m, g, lse, delta)

    dkv_kernel = functools.partial(
        _dkv_kernel, block_q=block_q, scale=scale, causal=causal,
        block_k=block_k)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tkv_p, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tkv_p, d), v.dtype),
        ],
        grid=(bh, tkv_p // block_k),
        in_specs=[
            pl.BlockSpec((1, tq_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, 1), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, tq_p, d), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tq_p, 1), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, tq_p, 1), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i: (b, i, 0)),
        ],
        interpret=interpret,
    )(q, k, v, m, g, lse, delta)
    return dq[:, :t_q], dk[:, :t_kv], dv[:, :t_kv]


def _reference_attention(q, k, v, *, scale: float, causal: bool, kv_mask=None):
    """The generic O(T²) path (libnd4j dot_product_attention math) — used
    as oracle and platform fallback."""
    s = jnp.einsum("bqd,bkd->bqk", q, k) * scale
    if kv_mask is not None:
        s = jnp.where(kv_mask.reshape(q.shape[0], 1, k.shape[1]) > 0.5, s, -1e30)
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((t_q, t_k), bool), k=t_k - t_q)
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, kv_mask=None, scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 512,
                    block_k: int = 512, interpret: Optional[bool] = None):
    """Blockwise attention over (BH, T, D) tensors (fold batch×heads first).

    ``kv_mask``: optional (BH, T_kv) 0/1 key-padding mask (1 = attend).
    Forward AND backward run Pallas kernels (FlashAttention-2 recurrences);
    the (T, T) score matrix never reaches HBM in either direction."""
    return _flash_call(q, k, v, kv_mask, scale, causal, block_q, block_k,
                       interpret)[0]


def _resolve_interpret(interpret):
    if interpret is not None:
        return interpret
    return jax.default_backend() != "tpu"


def _flash_call(q, k, v, kv_mask, scale, causal, block_q, block_k, interpret):
    if causal and q.shape[1] != k.shape[1]:
        # the kernel's causal mask is start-aligned on raw positions; the
        # reference path is end-aligned — they only agree for t_q == t_kv,
        # so reject the ambiguous case instead of silently training against
        # a different attention pattern
        raise ValueError(
            f"causal flash attention requires t_q == t_kv, got "
            f"{q.shape[1]} vs {k.shape[1]}")
    scale = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    return _flash_fwd(q, k, v, kv_mask, scale=scale, causal=causal,
                      block_q=block_q, block_k=block_k,
                      interpret=_resolve_interpret(interpret))


def _fwd(q, k, v, kv_mask, scale, causal, block_q, block_k, interpret):
    out, lse = _flash_call(q, k, v, kv_mask, scale, causal, block_q, block_k,
                           interpret)
    return out, (q, k, v, kv_mask, out, lse)


def _bwd(scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, kv_mask, out, lse = res
    s = scale if scale is not None else 1.0 / math.sqrt(q.shape[-1])
    dq, dk, dv = _flash_bwd(q, k, v, kv_mask, out, lse, g, scale=s,
                            causal=causal, block_q=block_q, block_k=block_k,
                            interpret=_resolve_interpret(interpret))
    return dq, dk, dv, None


flash_attention.defvjp(_fwd, _bwd)


def flash_mha(q, k, v, *, num_heads: int, causal: bool = False,
              kv_mask=None, interpret: Optional[bool] = None):
    """(N, T, H*dh) convenience wrapper: split heads, run flash, re-merge.
    ``kv_mask``: optional (N, T_kv) key-padding mask."""
    n, t, d = q.shape
    dh = d // num_heads

    def split(a):
        return a.reshape(n, a.shape[1], num_heads, dh).transpose(0, 2, 1, 3) \
                .reshape(n * num_heads, a.shape[1], dh)

    m = None
    if kv_mask is not None:
        m = jnp.repeat(kv_mask.astype(jnp.float32), num_heads, axis=0)
    out = flash_attention(split(q), split(k), split(v), m, None, causal,
                          512, 512, interpret)
    return out.reshape(n, num_heads, t, dh).transpose(0, 2, 1, 3).reshape(n, t, d)


def register_platform_attention() -> None:
    """Install flash attention as the TPU platform override for the generic
    dot_product_attention op (the cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()

    def flash_dpa(q, k, v, mask=None, *, scaled: bool = True):
        scale = (1.0 / math.sqrt(q.shape[-1])) if scaled else 1.0
        if q.ndim == 4:  # (B, H, T, D) + key mask broadcast (B, 1, 1, Tk)
            b, h, t, d = q.shape
            tk = k.shape[2]
            fold = lambda a: a.reshape(b * h, a.shape[2], a.shape[3])
            m = None
            if mask is not None:
                m = jnp.repeat(mask.reshape(b, tk).astype(jnp.float32), h, axis=0)
            out = flash_attention(fold(q), fold(k), fold(v), m, scale)
            return out.reshape(b, h, t, q.shape[-1])
        m = None if mask is None else mask.reshape(q.shape[0], k.shape[1])
        return flash_attention(q, k, v, m, scale)

    def usable(q, k, v, mask=None, **kw):
        if q.ndim == 3:
            mask_ok = mask is None or (
                hasattr(mask, "ndim") and mask.ndim in (2, 3)
                and mask.shape[-1] == k.shape[1]
                and (mask.ndim == 2 or mask.shape[1] == 1))
        elif q.ndim == 4:
            # key-padding broadcast mask only: (B, 1, 1, Tk)
            mask_ok = mask is None or (
                hasattr(mask, "ndim") and mask.ndim == 4
                and mask.shape[1] == 1 and mask.shape[2] == 1
                and mask.shape[-1] == k.shape[2])
        else:
            return False
        return mask_ok and q.shape[-1] % 8 == 0

    if "dot_product_attention" in reg:
        reg.register_platform("dot_product_attention", "tpu", flash_dpa, usable)
