"""Int8 quantized matmul — the cheap high-QPS serving path.

Seeded from the ``ops/compression.py`` design (threshold codec: scale-based
encode with a residual, static shapes under jit): weights are quantized
ONCE offline to symmetric int8 with a per-output-channel f32 scale
(``quantize_int8``), activations are quantized dynamically per row at call
time, and ``matmul_int8`` runs the int8×int8 dot with wide accumulation
before de-scaling back to the activation dtype:

    w_q, w_scale = quantize_int8(w, axis=0)          # offline, per column
    y = matmul_int8(x, w_q, w_scale)                 # serving hot path

* **generic impl**: XLA int8 ``dot_general`` with an int32 accumulator
  (exact), de-scaled in f32 — runs anywhere.
* **Pallas TPU helper**: the ``pallas_matmul`` block layout with int8 MXU
  tiles and an f32 VMEM accumulator; the per-row/per-column de-scale is the
  epilogue, so the int32/f32 intermediate never reaches HBM. int8 tiles
  want (32, 128) alignment (pallas_guide.md tiling table) — the usable()
  gate and the tuned block sizes (``ops/tuning.py``) enforce it.
* **gradients**: straight-through on the activation quantization — the
  backward is ``g @ dequantize(w).T``, exactly the f32 matmul backward
  against the dequantized weights (weights are frozen int8 at serving
  time; no weight gradient is defined).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# shared divisibility-first block picker (one definition; drift between
# per-module copies is how alignment fixes get lost)
from deeplearning4j_tpu.ops.pallas_matmul import _pick_block
from deeplearning4j_tpu.ops.registry import op

_QMAX = 127.0


@op("quantize_int8")
def quantize_int8(x, *, axis=None):
    """Symmetric int8 quantization: ``(q, scale)`` with
    ``x ≈ q * scale``. ``axis``: reduction axis/axes the scale is SHARED
    over (None = one per-tensor scale; ``axis=0`` on a (K, N) weight gives
    one scale per output column — the matmul_int8 layout)."""
    amax = jnp.max(jnp.abs(x), axis=axis, keepdims=axis is not None)
    scale = jnp.maximum(amax, 1e-12).astype(jnp.float32) / _QMAX
    q = jnp.clip(jnp.round(x / scale.astype(x.dtype)), -_QMAX, _QMAX) \
        .astype(jnp.int8)
    return q, scale


@op("dequantize_int8")
def dequantize_int8(q, scale):
    """Densify: ``q * scale`` in f32 (broadcasts the saved scale layout)."""
    return q.astype(jnp.float32) * scale


def _row_quantize(x):
    """Dynamic per-row activation quantization ((…, K) -> int8 + (…, 1)
    row scales), inlined on the hot path (axis=-1 keepdims layout)."""
    amax = jnp.max(jnp.abs(x), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-12).astype(jnp.float32) / _QMAX
    q = jnp.clip(jnp.round(x / scale.astype(x.dtype)), -_QMAX, _QMAX) \
        .astype(jnp.int8)
    return q, scale


def _matmul_int8_raw(x, w_q, w_scale):
    xq, xs = _row_quantize(x)
    acc = jax.lax.dot_general(
        xq, w_q, (((xq.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    y = acc.astype(jnp.float32) * xs * w_scale.reshape(1, -1)
    return y.astype(x.dtype)


@jax.custom_vjp
def _mm8(x, w_q, w_scale):
    return _matmul_int8_raw(x, w_q, w_scale)


def _mm8_fwd(x, w_q, w_scale):
    return _mm8(x, w_q, w_scale), (x, w_q, w_scale)


def _mm8_bwd(res, g):
    x, w_q, w_scale = res
    w_deq = w_q.astype(jnp.float32) * w_scale.reshape(1, -1)
    dx = jnp.matmul(g.astype(jnp.float32),
                    w_deq.T).astype(x.dtype)
    # int8 weights take float0 cotangents (non-differentiable integers);
    # the frozen serving scale gets a symbolic zero
    return (dx, np.zeros(w_q.shape, jax.dtypes.float0),
            jnp.zeros_like(w_scale))


_mm8.defvjp(_mm8_fwd, _mm8_bwd)


@op("matmul_int8")
def matmul_int8(x, w_q, w_scale):
    """``x @ dequantize(w_q, w_scale)`` computed in int8.

    x: (…, M, K) float; w_q: (K, N) int8; w_scale: (N,) f32 per-column.
    Activations quantize dynamically per row (straight-through for
    gradients); the int8×int8 dot accumulates wide and de-scales by
    ``row_scale · column_scale`` — the compression.py scale discipline
    applied to the MXU."""
    return _mm8(x, w_q, w_scale)


# ---------------------------------------------------------------------------
# Pallas TPU kernel
# ---------------------------------------------------------------------------


def _kernel(xq_ref, xs_ref, wq_ref, ws_ref, o_ref, acc_ref, *, n_k: int):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    # int8 MXU tiles with an f32 VMEM accumulator: K is bounded by the
    # f32 mantissa for exactness (|acc| <= K·127² must stay < 2^24 per
    # block step — block_k <= 1024 guarantees it), and f32 scratch keeps
    # the epilogue de-scale a pure in-register multiply
    acc_ref[:] += jax.lax.dot_general(
        xq_ref[0], wq_ref[0], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.DEFAULT)

    @pl.when(k == n_k - 1)
    def _():
        y = acc_ref[:] * xs_ref[0] * ws_ref[0]
        o_ref[0] = y.astype(o_ref.dtype)


def matmul_int8_pallas(x, w_q, w_scale, *, block_m: int = 0,
                       block_n: int = 0, block_k: int = 0, interpret=None):
    """Pallas forward for matmul_int8: quantize rows via XLA, then one
    blocked int8 MXU kernel with the de-scale epilogue in VMEM."""
    if interpret is None:
        from deeplearning4j_tpu.ops.registry import current_platform

        interpret = current_platform() != "tpu"
    from deeplearning4j_tpu.ops import tuning

    lead = x.shape[:-2] if x.ndim > 2 else ()
    m = 1
    for d in x.shape[:-1]:
        m *= d
    k_dim = x.shape[-1]
    n = w_q.shape[1]
    bucket = tuning.bucket_mkn(m, k_dim, n)
    bm = block_m or tuning.tuned_block(
        "matmul_int8", "block_m", m, bucket,
        lambda s: _pick_block(s, (256, 128, 64, 32)))
    bn = block_n or tuning.tuned_block(
        "matmul_int8", "block_n", n, bucket,
        lambda s: _pick_block(s, (256, 128)))
    bk = block_k or tuning.tuned_block(
        "matmul_int8", "block_k", k_dim, bucket,
        lambda s: _pick_block(s, (512, 256, 128)))
    if m % bm or n % bn or k_dim % bk:
        raise ValueError(f"shape ({m},{k_dim})x({k_dim},{n}) not divisible "
                         f"by blocks ({bm},{bk},{bn})")
    xq, xs = _row_quantize(x.reshape(m, k_dim))
    grid = (m // bm, n // bn, k_dim // bk)
    out = pl.pallas_call(
        functools.partial(_kernel, n_k=grid[2]),
        out_shape=jax.ShapeDtypeStruct((1, m, n), x.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bm, bk), lambda i, j, k: (0, i, k)),
            pl.BlockSpec((1, bm, 1), lambda i, j, k: (0, i, 0)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k: (0, k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=pl.BlockSpec((1, bm, bn), lambda i, j, k: (0, i, j)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(xq[None], xs[None], w_q[None], w_scale.reshape(1, n))
    return out[0].reshape(lead + (x.shape[-2], n))


@jax.custom_vjp
def _mm8_pl(x, w_q, w_scale):
    return matmul_int8_pallas(x, w_q, w_scale)


def _mm8_pl_fwd(x, w_q, w_scale):
    return _mm8_pl(x, w_q, w_scale), (x, w_q, w_scale)


_mm8_pl.defvjp(_mm8_pl_fwd, _mm8_bwd)  # same XLA backward as the generic


def matmul_int8_helper(x, w_q, w_scale):
    """The registered TPU platform impl: differentiable Pallas forward."""
    return _mm8_pl(x, w_q, w_scale)


def _usable(x, w_q, w_scale, **kw):
    """PlatformHelper::isUsable: 2-D/3-D float x, int8 (K, N) weights,
    Mosaic int8 tile alignment, and the measured min-rows crossover."""
    if getattr(x, "ndim", 0) not in (2, 3) or getattr(w_q, "ndim", 0) != 2:
        return False
    dt = getattr(x, "dtype", None)
    if dt is None or not jnp.issubdtype(dt, jnp.floating):
        return False
    if getattr(w_q, "dtype", None) != jnp.int8:
        return False
    m = 1
    for d in x.shape[:-1]:
        m *= d
    k_dim, n = w_q.shape
    from deeplearning4j_tpu.ops import tuning

    if m < int(tuning.tuned("matmul_int8", "pallas_min_m", 32)):
        return False
    return m % 32 == 0 and k_dim % 128 == 0 and n % 128 == 0


def _check_matmul_int8():
    """Validation case (ops.validation ratchet): scale round-trip vs a
    numpy int8 oracle, generic vs Pallas interpret, quantize/dequantize
    round-trip error bounded by the scale quantum."""
    r = np.random.RandomState(17)
    x = r.randn(32, 128).astype(np.float32)
    w = (r.randn(128, 128) * 128 ** -0.5).astype(np.float32)

    wq, ws = quantize_int8.fn(jnp.asarray(w), axis=0)
    # quantize/dequantize round trip: error <= scale/2 per entry
    w_rt = np.asarray(dequantize_int8.fn(wq, ws))
    np.testing.assert_array_less(
        np.abs(w_rt - w),
        np.broadcast_to(np.asarray(ws) / 2 + 1e-9, w.shape))

    # numpy oracle of the exact same quantized math
    xs = np.maximum(np.abs(x).max(-1, keepdims=True), 1e-12) / 127.0
    xq = np.clip(np.round(x / xs), -127, 127).astype(np.int8)
    want = (xq.astype(np.int64) @ np.asarray(wq).astype(np.int64)) \
        .astype(np.float32) * xs * np.asarray(ws).reshape(1, -1)
    got = matmul_int8.fn(jnp.asarray(x), wq, ws)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)
    got_pl = matmul_int8_pallas(jnp.asarray(x), wq, ws, block_m=32,
                                block_k=128, block_n=128, interpret=True)
    np.testing.assert_allclose(np.asarray(got_pl), want, rtol=1e-5,
                               atol=1e-6)


def _check_quantize_round_trip():
    """Validation case (ops.validation ratchet): symmetric quantize /
    dequantize round trip vs a numpy oracle, per-tensor and per-axis —
    error bounded by half the scale quantum, extremes map to ±127."""
    r = np.random.RandomState(23)
    x = r.randn(8, 16).astype(np.float32)
    for axis in (None, 0, 1):
        q, s = quantize_int8.fn(jnp.asarray(x), axis=axis)
        qn, sn = np.asarray(q), np.asarray(s)
        amax = np.abs(x).max() if axis is None else \
            np.abs(x).max(axis=axis, keepdims=True)
        np.testing.assert_allclose(sn, np.maximum(amax, 1e-12) / 127.0,
                                   rtol=1e-6)
        assert qn.dtype == np.int8 and np.abs(qn).max() <= 127
        back = np.asarray(dequantize_int8.fn(q, s))
        assert (np.abs(back - x) <= np.broadcast_to(sn / 2 + 1e-9,
                                                    x.shape)).all()


def register_platform_quantized() -> None:
    """Install the Pallas int8 kernel as the TPU platform override for
    matmul_int8 (cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops import validation as _validation
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()
    desc = reg.get("matmul_int8")
    if "tpu" not in desc.platform_impls:
        reg.register_platform("matmul_int8", "tpu", matmul_int8_helper,
                              _usable)
        _validation.add_case("matmul_int8", _check_matmul_int8)
        _validation.add_case("quantize_int8", _check_quantize_round_trip)
        _validation.add_case("dequantize_int8", _check_quantize_round_trip)
