"""Kernel autotuner + measured dispatch tables (docs/KERNELS.md).

The platform-helper table (``ops/registry.py``) picks kernels by *backend*;
the bench trajectory shows the right unit is *(device kind, op, shape
bucket)*: BENCH_HISTORY's attention sweep has the Pallas flash kernel at 25×
over XLA at t=8192 yet 0.65–0.99× below t=4096 — one hardcoded
``FLASH_MIN_T_DEFAULT`` cannot serve both a v5e and a v5p. This module owns

* the **tuning table**: a JSON document keyed on device kind holding, per
  op, pallas-vs-XLA crossover thresholds and per-shape-bucket Pallas block
  sizes. A checked-in default table (``ops/tuning_tables/default.json``)
  keeps CPU/untuned hosts deterministic; a measured table in the cache dir
  (``DL4J_TPU_TUNING_DIR``, default ``~/.cache/dl4j_tpu/tuning``) overlays
  it; ``DL4J_TPU_*`` env overrides (read by the dispatch sites) still win.
* the **autotuner** (:func:`autotune`): times candidate configurations with
  AOT lowering — ``jax.jit(fn).lower(*args).compile()`` — so measurement
  runs never contaminate the process jit cache (the SNIPPETS AOT idiom),
  and persists the winners. ``tools/tune.py`` is the CLI;
  ``make tune-smoke`` runs a tiny-shape pass that must exit 0 anywhere.
* the **dispatch feed**: ``flash_min_t()``, the Pallas block pickers in
  ``pallas_attention``/``pallas_matmul``/``pallas_convbn``/``quantized``,
  and the ``usable()`` gates consult :func:`tuned` so resolve decisions are
  measured, not guessed. Decisions are visible in the
  ``dl4j_tpu_helper_dispatch_total{op,impl,reason}`` counter family.

Schema (one document per device kind)::

    {"schema": "dl4j_tpu_tuning_v1",
     "device_kind": "cpu",
     "entries": {
       "dot_product_attention": {
         "flash_min_t": 4096,
         "blocks": {"t4096": {"block_q": 512, "block_k": 512}}},
       "fused_matmul_bias_act": {
         "pallas_min_m": 8,
         "blocks": {"m512_k512_n512": {"block_m": 256, ...}}},
       ...}}

Fragments emitted by ``tools/bench_attention_sweep.py`` /
``tools/bench_convbn_fusion.py`` use the same schema and merge into the
committed default table via :meth:`TuningTable.merge`.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

logger = logging.getLogger(__name__)

SCHEMA = "dl4j_tpu_tuning_v1"
ENV_DIR = "DL4J_TPU_TUNING_DIR"

_PACKAGE_TABLE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                  "tuning_tables")

# memoized per-device-kind merged tables + once-only warnings for corrupt
# files; reset_tables() is the test seam and runs after autotune() saves
_ACTIVE: Dict[str, "TuningTable"] = {}
_WARNED_PATHS: set = set()
_RESET_CALLBACKS: List[Callable[[], None]] = []


# ---------------------------------------------------------------------------
# keys: device kinds and shape buckets
# ---------------------------------------------------------------------------


def normalize_device_kind(kind: str) -> str:
    """``'TPU v5 lite'`` -> ``'tpu_v5_lite'`` — filesystem- and JSON-safe."""
    return re.sub(r"[^a-z0-9]+", "_", str(kind).strip().lower()).strip("_") \
        or "unknown"


def current_device_kind() -> str:
    """Device kind of the device computation will actually target — honors
    an enclosing ``jax.default_device(...)`` scope like
    ``registry.current_platform`` does."""
    import jax

    dev = jax.config.jax_default_device
    if dev is not None and getattr(dev, "device_kind", None):
        return normalize_device_kind(dev.device_kind)
    try:
        # justified: tuned() runs at op-resolve time, strictly after the
        # caller has already initialized/touched the backend — a probe that
        # could hang would have hung the caller's own computation first
        return normalize_device_kind(jax.devices()[0].device_kind)  # graftlint: disable=GL002
    except Exception:  # pragma: no cover - backendless probe
        return normalize_device_kind(jax.default_backend())


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (>= 1) — the shape-bucket unit. Kernel
    timing varies smoothly inside a 2× band; per-exact-shape entries would
    never generalize past the bench shapes."""
    n = max(int(n), 1)
    p = 1
    while p < n:
        p *= 2
    return p


def bucket_t(t: int) -> str:
    """Sequence-length bucket for attention-shaped ops."""
    return f"t{pow2_bucket(t)}"


def bucket_mkn(m: int, k: int, n: int) -> str:
    """(M, K, N) bucket for matmul-shaped ops."""
    return f"m{pow2_bucket(m)}_k{pow2_bucket(k)}_n{pow2_bucket(n)}"


def bucket_rows(rows: int) -> str:
    """Row-count bucket for row-parallel elementwise kernels (LayerNorm,
    the fused updater step)."""
    return f"r{pow2_bucket(rows)}"


# ---------------------------------------------------------------------------
# the table
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TuningTable:
    """One device kind's measured dispatch configuration."""

    device_kind: str
    entries: Dict[str, Dict[str, Any]] = dataclasses.field(default_factory=dict)
    source: str = ""

    # -- reads ---------------------------------------------------------------
    def get(self, op: str, key: str, default: Any = None) -> Any:
        return self.entries.get(op, {}).get(key, default)

    def get_block(self, op: str, bucket: str, key: str,
                  default: Any = None) -> Any:
        return self.entries.get(op, {}).get("blocks", {}) \
            .get(bucket, {}).get(key, default)

    # -- writes --------------------------------------------------------------
    def set(self, op: str, key: str, value: Any) -> None:
        self.entries.setdefault(op, {})[key] = value

    def set_block(self, op: str, bucket: str, key: str, value: Any) -> None:
        self.entries.setdefault(op, {}).setdefault("blocks", {}) \
            .setdefault(bucket, {})[key] = value

    def merge(self, other: "TuningTable") -> None:
        """Overlay ``other`` onto this table (other wins; blocks deep-merge
        per bucket). Used default-then-cache and by sweep-tool fragments."""
        for op, entry in other.entries.items():
            mine = self.entries.setdefault(op, {})
            for key, val in entry.items():
                if key == "blocks":
                    blocks = mine.setdefault("blocks", {})
                    for bucket, cfg in val.items():
                        blocks.setdefault(bucket, {}).update(cfg)
                else:
                    mine[key] = val

    # -- serde ---------------------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {"schema": SCHEMA, "device_kind": self.device_kind,
                "entries": self.entries}

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "TuningTable":
        if not isinstance(d, dict) or d.get("schema") != SCHEMA:
            raise ValueError(
                f"not a {SCHEMA} document (schema={d.get('schema') if isinstance(d, dict) else type(d).__name__!r})")
        entries = d.get("entries")
        if not isinstance(entries, dict) or not all(
                isinstance(v, dict) for v in entries.values()):
            raise ValueError("tuning table 'entries' must map op -> dict")
        for op_name, entry in entries.items():
            # a schema-valid but malformed blocks value ("blocks": null, or
            # bucket -> scalar) must be rejected HERE so it lands in the
            # corrupt-table warn-once fallback instead of crashing merge()
            # inside every dispatch site's tuned() read
            if "blocks" in entry:
                blocks = entry["blocks"]
                if not isinstance(blocks, dict) or not all(
                        isinstance(cfg, dict) for cfg in blocks.values()):
                    raise ValueError(
                        f"tuning table entry '{op_name}': 'blocks' must "
                        f"map bucket -> dict")
        return TuningTable(device_kind=str(d.get("device_kind", "unknown")),
                           entries=entries)

    @staticmethod
    def load(path: str) -> "TuningTable":
        with open(path) as f:
            table = TuningTable.from_dict(json.load(f))
        table.source = path
        return table

    def save(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_dict(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)  # atomic: a concurrent reader never sees half
        return path


# ---------------------------------------------------------------------------
# loading: checked-in default, then measured cache overlay
# ---------------------------------------------------------------------------


def tuning_dir() -> str:
    d = os.environ.get(ENV_DIR)
    if d:
        return d
    return os.path.join(os.path.expanduser("~"), ".cache", "dl4j_tpu",
                        "tuning")


def cache_path(device_kind: Optional[str] = None) -> str:
    kind = device_kind or current_device_kind()
    return os.path.join(tuning_dir(), f"{kind}.json")


def default_table_paths(device_kind: str) -> List[str]:
    """Checked-in defaults: the generic table always, a per-kind table on
    top when one was committed after a device sweep."""
    paths = [os.path.join(_PACKAGE_TABLE_DIR, "default.json")]
    per_kind = os.path.join(_PACKAGE_TABLE_DIR, f"{device_kind}.json")
    if os.path.exists(per_kind):
        paths.append(per_kind)
    return paths


def _load_or_warn(table: TuningTable, path: str) -> None:
    if not os.path.exists(path):
        return
    try:
        table.merge(TuningTable.load(path))
    except (ValueError, TypeError, AttributeError, OSError,
            json.JSONDecodeError) as e:
        # corrupt measured table: fall back to the checked-in defaults —
        # dispatch must stay deterministic, never crash. Warn once per path.
        if path not in _WARNED_PATHS:
            _WARNED_PATHS.add(path)
            logger.warning("ignoring corrupt tuning table %s: %s", path, e)


def active_table(device_kind: Optional[str] = None) -> TuningTable:
    """The merged (default ⊕ measured) table for a device kind, memoized."""
    kind = device_kind or current_device_kind()
    cached = _ACTIVE.get(kind)
    if cached is not None:
        return cached
    table = TuningTable(device_kind=kind)
    for path in default_table_paths(kind):
        _load_or_warn(table, path)
    _load_or_warn(table, cache_path(kind))
    _ACTIVE[kind] = table
    return table


def tuned(op: str, key: str, default: Any = None,
          bucket: Optional[str] = None) -> Any:
    """One measured value: the shape-bucket entry when present, else the
    op-level entry, else ``default``. This is THE read API every dispatch
    site uses; env overrides are applied by the caller (they must win)."""
    table = active_table()
    if bucket is not None:
        v = table.get_block(op, bucket, key)
        if v is not None:
            return v
    return table.get(op, key, default)


def tuned_block(op: str, key: str, size: int, bucket: str,
                fallback: Callable[[int], int]) -> int:
    """A measured block size, validated against the actual dimension — a
    tuned block that does not divide ``size`` falls back (tables describe
    buckets; a ragged real shape inside the bucket may not divide)."""
    v = tuned(op, key, None, bucket=bucket)
    if v:
        v = int(v)
        if size % v == 0:
            return v
    return fallback(size)


def on_reset(cb: Callable[[], None]) -> None:
    """Register a cache-invalidation hook (dispatch sites memoize derived
    values — e.g. ``flash_min_t`` — and must drop them with the tables)."""
    _RESET_CALLBACKS.append(cb)


def reset_tables() -> None:
    """Drop memoized tables (test seam; called after autotune() saves)."""
    _ACTIVE.clear()
    _WARNED_PATHS.clear()
    for cb in _RESET_CALLBACKS:
        cb()


# ---------------------------------------------------------------------------
# measurement: AOT-compiled timing that never touches the jit cache
# ---------------------------------------------------------------------------


def aot_time(fn: Callable, args: Sequence[Any], iters: int = 3,
             reps: int = 2) -> float:
    """Seconds per call, min over ``reps`` of ``iters`` calls each.

    The candidate is lowered and compiled AOT (``jit(fn).lower().compile()``
    — the SNIPPETS.md [1] idiom): the compiled executable is invoked
    directly, so candidate configurations never populate the process jit
    cache with entries real dispatch would then collide with."""
    import jax

    # graftshape: justified(GS001): AOT-timed candidate executables are deliberately cache-free and discarded after timing — ledgering them would record one first_compile per ladder rung as if it were serving traffic
    compiled = jax.jit(fn).lower(*args).compile()
    out = compiled(*args)
    jax.block_until_ready(out)  # warm + fail loudly before timing
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = compiled(*args)
        jax.block_until_ready(out)
        best = min(best, (time.perf_counter() - t0) / iters)
    return best


def _crossover(ladder: Sequence[int], pallas_ms: Dict[int, float],
               xla_ms: Dict[int, float]) -> int:
    """Smallest ladder point where the Pallas candidate wins; ladder points
    are scanned in order and the first win is sticky (the sweep shows wins
    are monotone in T past the crossover). If Pallas never wins —the CPU
    interpret-mode case — the threshold lands at 2× the largest measured
    point: pessimistic, deterministic, and re-measurable on a real chip."""
    for t in sorted(ladder):
        if pallas_ms[t] <= xla_ms[t]:
            return t
    return 2 * max(ladder)


@dataclasses.dataclass
class TuneReport:
    """What one autotune() pass measured (CLI/JSON surface)."""

    device_kind: str
    ops: List[str] = dataclasses.field(default_factory=list)
    measurements: int = 0
    seconds: float = 0.0
    table_path: Optional[str] = None

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def _span(op: str):
    from deeplearning4j_tpu import observe

    observe.metrics().counter("dl4j_tpu_tuning_runs_total", op=op).inc()
    return observe.tracer().span(f"tuning_{op}", category="tuning")


# -- per-op tuners -----------------------------------------------------------


def _tune_attention(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.pallas_attention import flash_attention
    from deeplearning4j_tpu.ops.registry import registry

    generic = registry().get("dot_product_attention").fn
    ladder = (32, 64) if smoke else (512, 1024, 2048, 4096, 8192)
    cands = ((8, 8), (16, 16)) if smoke else ((256, 256), (512, 512))
    bh, d = (2, 8) if smoke else (8, 64)
    r = np.random.RandomState(0)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("dot_product_attention"):
        for t in ladder:
            q = jnp.asarray(r.randn(bh, t, d).astype(np.float32))
            xla_ms[t] = aot_time(lambda q: generic(q, q, q), (q,))
            n += 1
            best = None
            for bq, bk in cands:
                sec = aot_time(
                    lambda q, _bq=bq, _bk=bk: flash_attention(
                        q, q, q, None, None, None, False, _bq, _bk, None,
                        0.0),
                    (q,))
                n += 1
                if best is None or sec < best[0]:
                    best = (sec, bq, bk)
            pallas_ms[t] = best[0]
            table.set_block("dot_product_attention", bucket_t(t),
                            "block_q", best[1])
            table.set_block("dot_product_attention", bucket_t(t),
                            "block_k", best[2])
        table.set("dot_product_attention", "flash_min_t",
                  _crossover(ladder, pallas_ms, xla_ms))
    return n


def _tune_fused_matmul(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.nn_ops import fused_matmul_bias_act
    from deeplearning4j_tpu.ops.pallas_matmul import \
        fused_matmul_bias_act_pallas

    shapes = ((16, 128, 128),) if smoke else \
        ((256, 512, 512), (512, 1024, 1024))
    cands = ((8, 128, 128), (16, 128, 128)) if smoke else \
        ((128, 256, 256), (256, 256, 512))
    r = np.random.RandomState(1)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("fused_matmul_bias_act"):
        for m, k, nn_ in shapes:
            x = jnp.asarray(r.randn(m, k).astype(np.float32))
            w = jnp.asarray((r.randn(k, nn_) * k ** -0.5).astype(np.float32))
            b = jnp.asarray(r.randn(nn_).astype(np.float32))
            xla_ms[m] = aot_time(
                lambda x, w, b: fused_matmul_bias_act.fn(
                    x, w, b, activation="gelu"), (x, w, b))
            n += 1
            best = None
            for bm, bk, bn in cands:
                if m % bm or k % bk or nn_ % bn:
                    continue
                sec = aot_time(
                    lambda x, w, b, _bm=bm, _bk=bk, _bn=bn:
                    fused_matmul_bias_act_pallas(
                        x, w, b, activation="gelu", block_m=_bm,
                        block_n=_bn, block_k=_bk),
                    (x, w, b))
                n += 1
                if best is None or sec < best[0]:
                    best = (sec, bm, bk, bn)
            if best is None:
                continue
            pallas_ms[m] = best[0]
            bucket = bucket_mkn(m, k, nn_)
            for key, val in (("block_m", best[1]), ("block_k", best[2]),
                             ("block_n", best[3])):
                table.set_block("fused_matmul_bias_act", bucket, key, val)
        if pallas_ms:
            table.set("fused_matmul_bias_act", "pallas_min_m",
                      _crossover(sorted(pallas_ms), pallas_ms, xla_ms))
    return n


def _tune_layernorm(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.pallas_layernorm import (
        fused_layer_norm, fused_layer_norm_pallas)

    shapes = ((16, 128),) if smoke else ((1024, 512), (8192, 1024))
    cands = (8, 16) if smoke else (64, 256)
    r = np.random.RandomState(2)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("fused_layer_norm"):
        for rows, d in shapes:
            x = jnp.asarray(r.randn(rows, d).astype(np.float32))
            g = jnp.asarray(r.rand(d).astype(np.float32) + 0.5)
            b = jnp.asarray(r.randn(d).astype(np.float32))
            xla_ms[rows] = aot_time(
                lambda x, g, b: fused_layer_norm.fn(x, g, b,
                                                    activation="gelu"),
                (x, g, b))
            n += 1
            best = None
            for br in cands:
                if rows % br:
                    continue
                sec = aot_time(
                    lambda x, g, b, _br=br: fused_layer_norm_pallas(
                        x, g, b, activation="gelu", block_rows=_br),
                    (x, g, b))
                n += 1
                if best is None or sec < best[0]:
                    best = (sec, br)
            if best is None:
                continue
            pallas_ms[rows] = best[0]
            table.set_block("fused_layer_norm", bucket_rows(rows),
                            "block_rows", best[1])
        if pallas_ms:
            table.set("fused_layer_norm", "min_rows",
                      _crossover(sorted(pallas_ms), pallas_ms, xla_ms))
    return n


def _tune_updater(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.pallas_updater import (
        fused_updater_step, fused_updater_helper)

    sizes = (1024,) if smoke else (1 << 16, 1 << 20)
    r = np.random.RandomState(3)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("fused_updater_step"):
        for size in sizes:
            p = jnp.asarray(r.randn(size).astype(np.float32))
            g = jnp.asarray(r.randn(size).astype(np.float32) * 0.01)
            z = jnp.zeros((size,), jnp.float32)
            lr = jnp.float32(1e-3)
            step = jnp.float32(0.0)
            args = (p, g, lr, step, z, z)
            xla_ms[size] = aot_time(
                lambda *a: fused_updater_step.fn(*a, kind="Adam"), args)
            sec = aot_time(
                lambda *a: fused_updater_helper(*a, kind="Adam"), args)
            n += 2
            pallas_ms[size] = sec
        table.set("fused_updater_step", "min_size",
                  _crossover(sizes, pallas_ms, xla_ms))
    return n


def _tune_int8(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.quantized import (
        matmul_int8, matmul_int8_pallas, quantize_int8)

    shapes = ((32, 128, 128),) if smoke else ((256, 512, 512),)
    cands = ((32, 128, 128),) if smoke else ((128, 256, 256), (256, 512, 256))
    r = np.random.RandomState(4)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("matmul_int8"):
        for m, k, nn_ in shapes:
            x = jnp.asarray(r.randn(m, k).astype(np.float32))
            wq, ws = quantize_int8.fn(
                jnp.asarray((r.randn(k, nn_) * k ** -0.5)
                            .astype(np.float32)), axis=0)
            xla_ms[m] = aot_time(
                lambda x, wq, ws: matmul_int8.fn(x, wq, ws), (x, wq, ws))
            n += 1
            best = None
            for bm, bk, bn in cands:
                if m % bm or k % bk or nn_ % bn:
                    continue
                sec = aot_time(
                    lambda x, wq, ws, _bm=bm, _bk=bk, _bn=bn:
                    matmul_int8_pallas(x, wq, ws, block_m=_bm, block_k=_bk,
                                       block_n=_bn),
                    (x, wq, ws))
                n += 1
                if best is None or sec < best[0]:
                    best = (sec, bm, bk, bn)
            if best is None:
                continue
            pallas_ms[m] = best[0]
            bucket = bucket_mkn(m, k, nn_)
            for key, val in (("block_m", best[1]), ("block_k", best[2]),
                             ("block_n", best[3])):
                table.set_block("matmul_int8", bucket, key, val)
        if pallas_ms:
            table.set("matmul_int8", "pallas_min_m",
                      _crossover(sorted(pallas_ms), pallas_ms, xla_ms))
    return n


def _tune_paged_decode(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.pallas_attention import (
        _paged_decode_call, paged_decode_attention_xla)

    ladders = (2, 4) if smoke else (4, 16, 64)
    s_n, h, d, page = (2, 2, 8, 8) if smoke else (8, 8, 64, 16)
    r = np.random.RandomState(5)
    n = 0
    pallas_ms: Dict[int, float] = {}
    xla_ms: Dict[int, float] = {}
    with _span("paged_decode_attention"):
        for max_pages in ladders:
            n_pages = max_pages * s_n + 1
            q = jnp.asarray(r.randn(s_n, h, d).astype(np.float32))
            kp = jnp.asarray(
                r.randn(n_pages, page, h, d).astype(np.float32))
            pt = jnp.asarray(
                r.randint(0, n_pages, (s_n, max_pages)).astype(np.int32))
            sl = jnp.asarray(
                np.full((s_n,), max_pages * page, np.int32))
            args = (q, kp, kp, pt, sl)
            xla_ms[max_pages] = aot_time(paged_decode_attention_xla, args)
            pallas_ms[max_pages] = aot_time(_paged_decode_call, args)
            n += 2
        table.set("paged_decode_attention", "min_pages",
                  _crossover(ladders, pallas_ms, xla_ms))
    return n


def _tune_convbn(table: TuningTable, smoke: bool) -> int:
    import jax.numpy as jnp
    import numpy as np

    from deeplearning4j_tpu.ops.pallas_convbn import fused_bn_matmul_stats

    shapes = ((16, 128, 128),) if smoke else ((4096, 256, 256),)
    cands = (8, 16) if smoke else (128, 256, 512)
    r = np.random.RandomState(6)
    n = 0
    with _span("fused_bn_matmul_stats"):
        for m, k, nn_ in shapes:
            x = jnp.asarray(r.randn(m, k).astype(np.float32))
            sc = jnp.asarray(r.rand(k).astype(np.float32) + 0.5)
            sh = jnp.asarray(r.randn(k).astype(np.float32) * 0.1)
            w = jnp.asarray((r.randn(k, nn_) * k ** -0.5).astype(np.float32))
            ss = jnp.asarray(r.randn(nn_).astype(np.float32) * 0.1)
            interpret = current_device_kind().find("tpu") < 0
            best = None
            for bm in cands:
                if m % bm:
                    continue
                sec = aot_time(
                    lambda *a, _bm=bm: fused_bn_matmul_stats(
                        *a, block_m=_bm, interpret=interpret),
                    (x, sc, sh, w, ss))
                n += 1
                if best is None or sec < best[0]:
                    best = (sec, bm)
            if best is not None:
                table.set_block("fused_bn_matmul_stats", bucket_mkn(m, k, nn_),
                                "block_m", best[1])
    return n


_TUNERS: Tuple[Tuple[str, Callable[[TuningTable, bool], int]], ...] = (
    ("dot_product_attention", _tune_attention),
    ("fused_matmul_bias_act", _tune_fused_matmul),
    ("fused_layer_norm", _tune_layernorm),
    ("fused_updater_step", _tune_updater),
    ("matmul_int8", _tune_int8),
    ("paged_decode_attention", _tune_paged_decode),
    ("fused_bn_matmul_stats", _tune_convbn),
)


def autotune(ops: Optional[Sequence[str]] = None, smoke: bool = False,
             save: bool = True,
             device_kind: Optional[str] = None) -> Tuple[TuningTable,
                                                         TuneReport]:
    """Measure candidate configurations and build a tuning table.

    ``smoke`` shrinks every ladder to shapes that finish in seconds on a
    CPU interpret-mode host (the ``make tune-smoke`` contract: exits 0
    anywhere, produces a valid table). ``save`` writes the table to the
    cache dir and invalidates the memoized readers so the measurement is
    live in the same process."""
    kind = device_kind or current_device_kind()
    table = TuningTable(device_kind=kind)
    report = TuneReport(device_kind=kind)
    t0 = time.perf_counter()
    wanted = set(ops) if ops else None
    for name, tuner in _TUNERS:
        if wanted is not None and name not in wanted:
            continue
        report.measurements += tuner(table, smoke)
        report.ops.append(name)
    report.seconds = round(time.perf_counter() - t0, 3)
    if save:
        # merge onto the existing cache table: an --ops subset re-tune must
        # refresh only what it measured, not discard every other op's
        # previously measured entries
        merged = TuningTable(device_kind=kind)
        _load_or_warn(merged, cache_path(kind))
        merged.merge(table)
        report.table_path = merged.save(cache_path(kind))
        reset_tables()
    return table, report
