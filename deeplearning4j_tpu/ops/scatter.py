"""Scatter / segment ops.

Reference parity: libnd4j scatter family
(include/ops/declarable/generic/parity_ops/scatter_*.cpp — scatter_add/
sub/mul/div/max/min/upd, scatter_nd*) and segment family
(generic/parity_ops/segment_*.cpp, unsorted_segment_*.cpp; Java surface
org.nd4j.linalg.api.ops.impl.scatter.* / .transforms.segment.*).

TPU-native realization: scatter lowers to jax .at[] indexed updates (XLA
scatter HLO); segment ops lower to jax.ops.segment_* which XLA turns into
sorted-segment reductions — no serial loops. Duplicate indices follow XLA
scatter semantics (adds combine; updates pick one winner), matching the
reference's documented "undefined order for duplicate updates".

Every op registers a numpy-oracle validation case.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()

# name -> (at-method, numpy combine)
_SCATTER = {
    "scatter_add": ("add", np.add),
    "scatter_sub": ("subtract", np.subtract),
    "scatter_mul": ("multiply", np.multiply),
    "scatter_div": ("divide", np.divide),
    "scatter_max": ("max", np.maximum),
    "scatter_min": ("min", np.minimum),
    "scatter_upd": ("set", None),
}


def _scatter_apply(method, ref, indices, updates):
    return getattr(ref.at[indices], method)(updates)


def _check_scatter(name, method, combine):
    r = np.random.RandomState(0)
    ref = r.randn(6, 4).astype(np.float32)
    if name == "scatter_div":
        updates = (np.abs(r.randn(3, 4)) + 0.5).astype(np.float32)
    else:
        updates = r.randn(3, 4).astype(np.float32)
    idx = np.asarray([5, 0, 2], np.int32)  # unique rows → order-free oracle
    got = np.asarray(_REG.exec(name, jnp.asarray(ref), jnp.asarray(idx),
                               jnp.asarray(updates)))
    want = ref.copy()
    for i, row in zip(idx, updates):
        want[i] = row if combine is None else combine(want[i], row)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


for _name, (_method, _combine) in _SCATTER.items():
    _REG.register(_name, functools.partial(_scatter_apply, _method),
                  doc=f"{_name}(ref, indices, updates) — row-indexed scatter "
                      "(generic/parity_ops/scatter_*.cpp)")
    validation.add_case(_name, functools.partial(
        _check_scatter, _name, _method, _combine))


def _scatter_nd(indices, updates, *, shape):
    """scatter_nd: build a zeros(shape) tensor with updates at nd-indices
    (generic/parity_ops/scatter_nd.cpp)."""
    z = jnp.zeros(shape, updates.dtype)
    return z.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


def _scatter_nd_add(ref, indices, updates):
    """scatter_nd_add (generic/parity_ops/scatter_nd_add.cpp)."""
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].add(updates)


def _scatter_nd_update(ref, indices, updates):
    """scatter_nd_update (generic/parity_ops/scatter_nd_update.cpp)."""
    return ref.at[tuple(jnp.moveaxis(indices, -1, 0))].set(updates)


_REG.register("scatter_nd", _scatter_nd, doc=_scatter_nd.__doc__)
_REG.register("scatter_nd_add", _scatter_nd_add, doc=_scatter_nd_add.__doc__)
_REG.register("scatter_nd_update", _scatter_nd_update,
              doc=_scatter_nd_update.__doc__)


@validation.case("scatter_nd")
def _check_scatter_nd():
    idx = np.asarray([[0, 1], [2, 3]], np.int32)
    upd = np.asarray([5.0, 7.0], np.float32)
    got = np.asarray(_REG.exec("scatter_nd", jnp.asarray(idx),
                               jnp.asarray(upd), shape=(3, 4)))
    want = np.zeros((3, 4), np.float32)
    want[0, 1], want[2, 3] = 5.0, 7.0
    np.testing.assert_array_equal(got, want)


@validation.case("scatter_nd_add")
def _check_scatter_nd_add():
    ref = np.ones((3, 4), np.float32)
    idx = np.asarray([[1, 1]], np.int32)
    got = np.asarray(_REG.exec("scatter_nd_add", jnp.asarray(ref),
                               jnp.asarray(idx), jnp.asarray([2.0], np.float32)))
    want = ref.copy(); want[1, 1] += 2.0
    np.testing.assert_array_equal(got, want)


@validation.case("scatter_nd_update")
def _check_scatter_nd_update():
    ref = np.zeros((2, 2), np.float32)
    idx = np.asarray([[0, 0]], np.int32)
    got = np.asarray(_REG.exec("scatter_nd_update", jnp.asarray(ref),
                               jnp.asarray(idx), jnp.asarray([9.0], np.float32)))
    assert got[0, 0] == 9.0 and got.sum() == 9.0


# ---- segment reductions ----------------------------------------------------

_SEGMENT = {
    "segment_sum": (jax.ops.segment_sum, np.add.reduceat),
    "segment_max": (jax.ops.segment_max, None),
    "segment_min": (jax.ops.segment_min, None),
    "segment_prod": (jax.ops.segment_prod, None),
}


def _segment_apply(jfn, data, segment_ids, *, num_segments: int):
    return jfn(data, segment_ids, num_segments=num_segments)


def _segment_mean(data, segment_ids, *, num_segments: int):
    """segment_mean (generic/parity_ops/segment_mean.cpp)."""
    s = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    n = jax.ops.segment_sum(jnp.ones_like(data, jnp.float32), segment_ids,
                            num_segments=num_segments)
    return s / jnp.maximum(n, 1)


def _np_segment(npfn, data, ids, n):
    out = []
    for s in range(n):
        rows = data[ids == s]
        out.append(npfn(rows, axis=0) if len(rows) else np.zeros(data.shape[1:]))
    return np.stack(out)


def _check_segment(name, npfn):
    # name is the REGISTRY entry to exec (sorted or unsorted_ prefixed);
    # the numpy oracle is shared
    r = np.random.RandomState(1)
    data = r.randn(8, 3).astype(np.float32)
    ids = np.asarray([0, 0, 1, 1, 1, 3, 3, 0], np.int32)  # sorted not required
    got = np.asarray(_REG.exec(name, jnp.asarray(data), jnp.asarray(ids),
                               num_segments=4))
    want = _np_segment(npfn, data, ids, 4).astype(np.float32)
    base = name.replace("unsorted_", "")
    if base == "segment_max":
        want[2] = -np.inf  # empty segment identity
    if base == "segment_min":
        want[2] = np.inf
    if base == "segment_prod":
        want[2] = 1.0
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


_NPFN = {"segment_sum": np.sum, "segment_max": np.max, "segment_min": np.min,
         "segment_prod": np.prod, "segment_mean": np.mean}

for _name, (_jfn, _) in _SEGMENT.items():
    _REG.register(_name, functools.partial(_segment_apply, _jfn),
                  doc=f"{_name}(data, segment_ids, num_segments) — "
                      "(generic/parity_ops segment family); ids need not be "
                      "sorted (unsorted_segment_* alias)")
    _REG.register("unsorted_" + _name,
                  functools.partial(_segment_apply, _jfn),
                  doc=f"unsorted_{_name} — same lowering (XLA scatter-reduce)")
    validation.add_case(_name, functools.partial(
        _check_segment, _name, _NPFN[_name]))
    validation.add_case("unsorted_" + _name, functools.partial(
        _check_segment, "unsorted_" + _name, _NPFN[_name]))

_REG.register("segment_mean", _segment_mean, doc=_segment_mean.__doc__)
_REG.register("unsorted_segment_mean", _segment_mean,
              doc="unsorted segment mean — same lowering")
validation.add_case("segment_mean", functools.partial(
    _check_segment, "segment_mean", np.mean))
validation.add_case("unsorted_segment_mean", functools.partial(
    _check_segment, "unsorted_segment_mean", np.mean))


def _unsorted_segment_sqrt_n(data, segment_ids, *, num_segments: int):
    """unsorted_segment_sqrt_n: sum / sqrt(count)
    (generic/parity_ops/unsorted_segment_sqrt_n.cpp)."""
    s = jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)
    n = jax.ops.segment_sum(jnp.ones_like(data, jnp.float32), segment_ids,
                            num_segments=num_segments)
    return s / jnp.sqrt(jnp.maximum(n, 1))


_REG.register("unsorted_segment_sqrt_n", _unsorted_segment_sqrt_n,
              doc=_unsorted_segment_sqrt_n.__doc__)


@validation.case("unsorted_segment_sqrt_n")
def _check_sqrt_n():
    data = np.asarray([[2.0], [4.0], [6.0]], np.float32)
    ids = np.asarray([0, 0, 1], np.int32)
    got = np.asarray(_REG.exec("unsorted_segment_sqrt_n", jnp.asarray(data),
                               jnp.asarray(ids), num_segments=2))
    np.testing.assert_allclose(got, [[6.0 / np.sqrt(2)], [6.0]], rtol=1e-6)


# ---- dynamic partition / stitch -------------------------------------------


def _dynamic_partition(data, partitions, *, num_partitions: int):
    """dynamic_partition (generic/parity_ops/dynamic_parition.cpp [sic]).
    XLA needs static shapes, so each partition is returned padded to the
    full data length with a parallel 0/1 validity mask:
    returns ([part_0..part_{P-1}], [mask_0..mask_{P-1}])."""
    outs, masks = [], []
    n = data.shape[0]
    for p in range(num_partitions):
        sel = partitions == p
        cnt = jnp.sum(sel)
        idx_sorted = jnp.argsort(~sel, stable=True)  # members first
        outs.append(data[idx_sorted])
        masks.append((jnp.arange(n) < cnt).astype(jnp.int32))
    return outs, masks


def _dynamic_stitch(indices, parts):
    """dynamic_stitch (generic/parity_ops/dynamic_stitch.cpp)."""
    idx = jnp.concatenate([jnp.ravel(i) for i in indices])
    flat = jnp.concatenate([p.reshape((-1,) + p.shape[i.ndim:])
                            for i, p in zip(indices, parts)])
    n = int(idx.shape[0])
    out = jnp.zeros((n,) + flat.shape[1:], flat.dtype)
    return out.at[idx].set(flat)


_REG.register("dynamic_partition", _dynamic_partition,
              doc=_dynamic_partition.__doc__)
_REG.register("dynamic_stitch", _dynamic_stitch, doc=_dynamic_stitch.__doc__)


@validation.case("dynamic_partition")
def _check_dyn_part():
    data = np.asarray([[1.0], [2.0], [3.0], [4.0]], np.float32)
    parts = np.asarray([1, 0, 1, 0], np.int32)
    outs, masks = _REG.exec("dynamic_partition", jnp.asarray(data),
                            jnp.asarray(parts), num_partitions=2)
    m0 = np.asarray(masks[0]).astype(bool)
    np.testing.assert_array_equal(np.asarray(outs[0])[m0], [[2.0], [4.0]])
    m1 = np.asarray(masks[1]).astype(bool)
    np.testing.assert_array_equal(np.asarray(outs[1])[m1], [[1.0], [3.0]])


@validation.case("dynamic_stitch")
def _check_dyn_stitch():
    idx = [np.asarray([0, 2], np.int32), np.asarray([1, 3], np.int32)]
    parts = [np.asarray([[10.0], [30.0]], np.float32),
             np.asarray([[20.0], [40.0]], np.float32)]
    got = np.asarray(_REG.exec("dynamic_stitch",
                               [jnp.asarray(i) for i in idx],
                               [jnp.asarray(p) for p in parts]))
    np.testing.assert_array_equal(got, [[10.0], [20.0], [30.0], [40.0]])
