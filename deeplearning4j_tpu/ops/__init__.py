"""Tensor/op layer — the ND4J + libnd4j role, collapsed.

The reference's L4 (INDArray/op classes) + L2 (libnd4j kernels) layers become:
jax.Array + a named op catalog lowering to XLA. Importing this package
populates the global op registry.
"""

from deeplearning4j_tpu.ops.registry import registry, op, exec_op, OpRegistry
from deeplearning4j_tpu.ops import nn_ops, activations, losses, random, compression, weight_init
# declarable-op catalog breadth (each module registers its family + a
# numpy-oracle validation case per op — the OpValidation ratchet)
from deeplearning4j_tpu.ops import (
    transforms, reductions, shape_ops, scatter, linalg_ops, bitwise,
    image_ops, misc_ops, validation,
)
from deeplearning4j_tpu.ops.activations import get_activation, ACTIVATIONS
from deeplearning4j_tpu.ops.losses import get_loss, LOSSES
from deeplearning4j_tpu.ops.weight_init import init_weights

# Install the Pallas platform helpers (the cuDNN-helper-registration analog:
# the reference registers platform overrides at library load — libnd4j
# OpRegistrator static init). Deferred import keeps pallas optional.
from deeplearning4j_tpu.ops import tuning
from deeplearning4j_tpu.ops.pallas_attention import register_platform_attention
from deeplearning4j_tpu.ops.pallas_matmul import register_platform_fused_matmul
from deeplearning4j_tpu.ops.pallas_layernorm import (
    register_platform_fused_layernorm)
from deeplearning4j_tpu.ops.pallas_updater import (
    register_platform_fused_updater)
from deeplearning4j_tpu.ops.quantized import register_platform_quantized

register_platform_attention()
register_platform_fused_matmul()
register_platform_fused_layernorm()
register_platform_fused_updater()
register_platform_quantized()

__all__ = [
    "registry", "op", "exec_op", "OpRegistry", "tuning",
    "nn_ops", "activations", "losses", "random", "compression", "weight_init",
    "get_activation", "ACTIVATIONS", "get_loss", "LOSSES", "init_weights",
]
