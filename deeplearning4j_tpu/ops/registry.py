"""Named-op registry: the compat surface of libnd4j's ~270 "declarable ops".

Reference parity:
  * libnd4j ``OpRegistrator`` (include/ops/declarable/OpRegistrator.h) maps op
    names -> DeclarableOp instances; each op carries a shape function.
  * Platform helpers (include/ops/declarable/platform/cudnn/*) override the
    generic implementation when usable, chosen at exec time via
    ``PlatformHelper::isUsable``.

TPU-native realization: ops are pure Python callables lowering to jax.lax /
jax.numpy (hence XLA HLO). The registry exists for (a) the *name catalog* —
what users of the reference could call by name via DynamicCustomOp — and
(b) the platform-helper table: an op may have an alternate Pallas kernel
implementation selected on TPU backends. Shape functions come for free from
``jax.eval_shape`` (the analog of the reference's calculateOutputShape JNI
round-trip, but at trace time, not per step).
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Any, Callable, Dict, List, Optional

import jax

from deeplearning4j_tpu.environment import environment

logger = logging.getLogger(__name__)


def current_platform() -> str:
    """Platform the computation will actually target.

    Unlike ``jax.default_backend()`` (process-global), this honors an
    enclosing ``jax.default_device(...)`` scope — the CPU-vs-TPU consistency
    suite runs its CPU half that way on a TPU host, and helper selection
    must follow the *target* device, not the process default (round-2
    verdict weak #2: keying off the global backend lowered Pallas kernels
    non-interpret on CPU).
    """
    dev = jax.config.jax_default_device
    if dev is not None:
        plat = getattr(dev, "platform", None)
        if plat is not None:
            return plat
        return str(dev).split(":")[0]
    return jax.default_backend()


def _note_dispatch(op: str, impl: str, reason: str) -> None:
    """Dispatch-decision counter (dl4j_tpu_helper_dispatch_total) — only
    helper-carrying ops call this, so the family stays small. Resolve runs
    at trace time, so the increment costs nothing per executed step; a
    pallas-vs-XLA routing regression shows up in /metrics, obsreport and
    the bench JSON line instead of silently flipping throughput."""
    from deeplearning4j_tpu import observe

    observe.metrics().counter("dl4j_tpu_helper_dispatch_total",
                              op=op, impl=impl, reason=reason).inc()


@dataclasses.dataclass
class OpDescriptor:
    """One declarable op: generic impl + optional platform (Pallas) overrides."""

    name: str
    fn: Callable[..., Any]
    doc: str = ""
    # platform -> (impl, is_usable predicate on kwargs)
    platform_impls: Dict[str, Callable[..., Any]] = dataclasses.field(default_factory=dict)
    platform_usable: Dict[str, Callable[..., bool]] = dataclasses.field(default_factory=dict)

    def resolve(self, *args: Any, **kwargs: Any) -> Callable[..., Any]:
        """Pick the implementation — the PlatformHelper::isUsable analog."""
        if not self.platform_impls:
            return self.fn  # helper-less op: no decision to make or count
        env = environment()
        if env.helper_mode == "xla":
            _note_dispatch(self.name, "generic", "forced_xla")
            return self.fn
        backend = current_platform()
        impl_key = backend
        impl = self.platform_impls.get(backend)
        if impl is None and env.helper_mode == "pallas":
            impl_key = "tpu"
            impl = self.platform_impls.get("tpu")
        if impl is None:
            _note_dispatch(self.name, "generic", "no_helper")
            return self.fn
        # the usable() gate must come from the SAME table entry as the
        # impl — looking it up under the current backend would silently
        # skip the gate for the forced-pallas fallback path
        usable = self.platform_usable.get(impl_key, lambda *a, **k: True)
        try:
            ok = usable(*args, **kwargs)
            reason = "usable" if ok else "not_usable"
        except Exception:  # pragma: no cover - defensive
            ok = False
            reason = "usable_error"
        if ok:
            if env.log_helper_selection:
                logger.info("op %s: selected %s platform helper", self.name, backend)
            _note_dispatch(self.name, impl_key, reason)
            return impl
        _note_dispatch(self.name, "generic", reason)
        return self.fn

    def __call__(self, *args: Any, **kwargs: Any) -> Any:
        return self.resolve(*args, **kwargs)(*args, **kwargs)


class OpRegistry:
    """Global name -> op table (libnd4j OpRegistrator analog)."""

    def __init__(self) -> None:
        self._ops: Dict[str, OpDescriptor] = {}

    def register(self, name: str, fn: Callable[..., Any], doc: str = "") -> OpDescriptor:
        if name in self._ops:
            raise ValueError(f"op '{name}' already registered")
        desc = OpDescriptor(name=name, fn=fn, doc=doc or (fn.__doc__ or ""))
        self._ops[name] = desc
        return desc

    def register_platform(
        self,
        name: str,
        platform: str,
        fn: Callable[..., Any],
        usable: Optional[Callable[..., bool]] = None,
    ) -> None:
        desc = self._ops[name]
        desc.platform_impls[platform] = fn
        if usable is not None:
            desc.platform_usable[platform] = usable

    def get(self, name: str) -> OpDescriptor:
        try:
            return self._ops[name]
        except KeyError:
            raise KeyError(
                f"unknown op '{name}' — known ops: {sorted(self._ops)[:20]}..."
            ) from None

    def __contains__(self, name: str) -> bool:
        return name in self._ops

    def names(self) -> List[str]:
        return sorted(self._ops)

    def exec(self, name: str, *args: Any, **kwargs: Any) -> Any:
        """Execute a named op (Nd4j.exec(DynamicCustomOp) analog)."""
        return self.get(name)(*args, **kwargs)

    def calculate_output_shape(self, name: str, *args: Any, **kwargs: Any):
        """Abstract-eval an op (DeclarableOp shape-function analog)."""
        return jax.eval_shape(functools.partial(self.get(name).fn, **kwargs), *args)


_REGISTRY = OpRegistry()


def registry() -> OpRegistry:
    return _REGISTRY


def op(name: str, doc: str = "") -> Callable[[Callable[..., Any]], OpDescriptor]:
    """Decorator: register a function as a named declarable op."""

    def wrap(fn: Callable[..., Any]) -> OpDescriptor:
        return _REGISTRY.register(name, fn, doc)

    return wrap


def exec_op(name: str, *args: Any, **kwargs: Any) -> Any:
    return _REGISTRY.exec(name, *args, **kwargs)
