"""Image ops.

Reference parity: libnd4j image DynamicCustomOps
(include/ops/declarable/generic/images/** and parity_ops —
resize_bilinear.cpp, resize_neighbor.cpp, resize_bicubic.cpp,
crop_and_resize.cpp, non_max_suppression.cpp, extract_image_patches.cpp,
adjust_contrast.cpp, adjust_hue.cpp, adjust_saturation.cpp, rgb_to_hsv /
hsv_to_rgb (color models); Java surface org.nd4j.linalg.api.ops.custom.*).

TPU-native realization: resizes lower to jax.image (XLA gather/dot
compositions); NMS runs a lax.fori_loop over the static max_output count —
no dynamic shapes. Oracles: tensorflow's reference image kernels.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()


def _op(name):
    def deco(fn):
        _REG.register(name, fn, doc=fn.__doc__ or "")
        return fn

    return deco


def _resize(x, size, method, antialias=False):
    shape = (x.shape[0], int(size[0]), int(size[1]), x.shape[3])
    return jax.image.resize(x, shape, method=method, antialias=antialias)


@_op("resize_bilinear")
def resize_bilinear(x, *, size):
    """NHWC bilinear resize (generic/parity_ops/resize_bilinear.cpp)."""
    return _resize(x, size, "bilinear")


@_op("resize_nearest_neighbor")
def resize_nearest_neighbor(x, *, size):
    """NHWC nearest resize (generic/parity_ops/resize_neighbor.cpp)."""
    return _resize(x, size, "nearest")


@_op("resize_bicubic")
def resize_bicubic(x, *, size):
    """NHWC bicubic resize (generic/parity_ops/resize_bicubic.cpp)."""
    return _resize(x, size, "cubic")


@_op("crop_and_resize")
def crop_and_resize(image, boxes, box_indices, *, crop_size):
    """crop normalized boxes then bilinear-resize each to crop_size
    (generic/images/crop_and_resize.cpp). image: (N,H,W,C); boxes (B,4)
    as [y1,x1,y2,x2] in [0,1]; box_indices (B,) into N."""
    n, h, w, c = image.shape
    ch, cw = crop_size

    def one(box, bi):
        y1, x1, y2, x2 = box
        # TF sampling rule: size-1 crop dims sample the box CENTER, larger
        # dims linspace corner-to-corner
        if ch > 1:
            ys = y1 * (h - 1) + jnp.arange(ch) / (ch - 1) * (y2 - y1) * (h - 1)
        else:
            ys = 0.5 * (y1 + y2) * (h - 1) + jnp.zeros((1,))
        if cw > 1:
            xs = x1 * (w - 1) + jnp.arange(cw) / (cw - 1) * (x2 - x1) * (w - 1)
        else:
            xs = 0.5 * (x1 + x2) * (w - 1) + jnp.zeros((1,))
        img = image[bi]
        y0 = jnp.clip(jnp.floor(ys), 0, h - 1).astype(jnp.int32)
        y1i = jnp.clip(y0 + 1, 0, h - 1)
        x0 = jnp.clip(jnp.floor(xs), 0, w - 1).astype(jnp.int32)
        x1i = jnp.clip(x0 + 1, 0, w - 1)
        wy = (ys - y0)[:, None, None]
        wx = (xs - x0)[None, :, None]
        tl = img[y0][:, x0]
        tr = img[y0][:, x1i]
        bl = img[y1i][:, x0]
        br = img[y1i][:, x1i]
        top = tl * (1 - wx) + tr * wx
        bot = bl * (1 - wx) + br * wx
        return top * (1 - wy) + bot * wy

    return jax.vmap(one)(boxes, box_indices)


@_op("non_max_suppression")
def non_max_suppression(boxes, scores, *, max_output_size: int,
                        iou_threshold: float = 0.5,
                        score_threshold: float = -np.inf):
    """greedy IoU NMS (generic/images [parity_ops]/non_max_suppression.cpp).

    Static shapes for XLA: returns (indices[max_output_size], valid 0/1 mask)
    — the reference returns a dynamic-length index list; the mask carries the
    same information with a compilable shape. boxes: (N,4) [y1,x1,y2,x2]."""
    n = boxes.shape[0]
    y1, x1, y2, x2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(y2 - y1, 0) * jnp.maximum(x2 - x1, 0)

    def iou_row(i):
        yy1 = jnp.maximum(y1[i], y1)
        xx1 = jnp.maximum(x1[i], x1)
        yy2 = jnp.minimum(y2[i], y2)
        xx2 = jnp.minimum(x2[i], x2)
        inter = jnp.maximum(yy2 - yy1, 0) * jnp.maximum(xx2 - xx1, 0)
        return inter / jnp.maximum(area[i] + area - inter, 1e-9)

    live = scores > score_threshold

    def body(k, carry):
        sel_idx, sel_mask, live = carry
        s = jnp.where(live, scores, -jnp.inf)
        i = jnp.argmax(s)
        ok = s[i] > -jnp.inf
        sel_idx = sel_idx.at[k].set(jnp.where(ok, i, -1))
        sel_mask = sel_mask.at[k].set(ok.astype(jnp.int32))
        suppress = iou_row(i) > iou_threshold
        live = live & jnp.where(ok, ~suppress, live) & \
            (jnp.arange(n) != i)
        return sel_idx, sel_mask, live

    idx0 = jnp.full((max_output_size,), -1, jnp.int32)
    m0 = jnp.zeros((max_output_size,), jnp.int32)
    sel_idx, sel_mask, _ = jax.lax.fori_loop(0, max_output_size, body,
                                             (idx0, m0, live))
    return sel_idx, sel_mask


@_op("extract_image_patches")
def extract_image_patches(x, *, kernel, strides, rates=(1, 1),
                          padding: str = "VALID"):
    """extract_image_patches (generic/images [parity_ops]/
    extract_image_patches.cpp) — NHWC, returns (N, H', W', kh*kw*C)."""
    kh, kw = kernel
    sh, sw = strides
    rh, rw = rates
    c = x.shape[3]
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), padding,
        rhs_dilation=(rh, rw),
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # conv_general_dilated_patches emits channel-major (C, kh, kw) feature
    # order; the reference (TF semantics) wants (kh, kw, C) — re-interleave.
    n, oh, ow, _ = patches.shape
    patches = patches.reshape(n, oh, ow, c, kh * kw)
    return jnp.swapaxes(patches, 3, 4).reshape(n, oh, ow, kh * kw * c)


@_op("adjust_contrast")
def adjust_contrast(x, *, factor: float):
    """scale distance from per-channel mean (custom/adjust_contrast.cpp)."""
    mean = jnp.mean(x, axis=(-3, -2), keepdims=True)
    return (x - mean) * factor + mean


@_op("rgb_to_hsv")
def rgb_to_hsv(x):
    """RGB→HSV on the last axis (generic/images/rgb_to_hsv.cpp)."""
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    mx = jnp.maximum(jnp.maximum(r, g), b)
    mn = jnp.minimum(jnp.minimum(r, g), b)
    d = mx - mn
    safe = jnp.where(d == 0, 1.0, d)
    h = jnp.where(
        mx == r, (g - b) / safe % 6.0,
        jnp.where(mx == g, (b - r) / safe + 2.0, (r - g) / safe + 4.0)) / 6.0
    h = jnp.where(d == 0, 0.0, h)
    s = jnp.where(mx == 0, 0.0, d / jnp.where(mx == 0, 1.0, mx))
    return jnp.stack([h, s, mx], axis=-1)


@_op("hsv_to_rgb")
def hsv_to_rgb(x):
    """HSV→RGB on the last axis (generic/images/hsv_to_rgb.cpp)."""
    h, s, v = x[..., 0], x[..., 1], x[..., 2]
    i = jnp.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(jnp.int32) % 6
    r = jnp.choose(i, [v, q, p, p, t, v], mode="clip")
    g = jnp.choose(i, [t, v, v, q, p, p], mode="clip")
    b = jnp.choose(i, [p, p, t, v, v, q], mode="clip")
    return jnp.stack([r, g, b], axis=-1)


@_op("adjust_hue")
def adjust_hue(x, *, delta: float):
    """rotate hue by delta (custom/adjust_hue.cpp)."""
    hsv = rgb_to_hsv(x)
    h = (hsv[..., 0] + delta) % 1.0
    return hsv_to_rgb(jnp.stack([h, hsv[..., 1], hsv[..., 2]], axis=-1))


@_op("adjust_saturation")
def adjust_saturation(x, *, factor: float):
    """scale saturation (custom/adjust_saturation.cpp)."""
    hsv = rgb_to_hsv(x)
    s = jnp.clip(hsv[..., 1] * factor, 0.0, 1.0)
    return hsv_to_rgb(jnp.stack([hsv[..., 0], s, hsv[..., 2]], axis=-1))


@_op("rgb_to_grs")
def rgb_to_grs(x):
    """RGB→grayscale, ITU-R 601 weights (generic/images/rgb_to_grs.cpp)."""
    w = jnp.asarray([0.2989, 0.5870, 0.1140], x.dtype)
    return jnp.sum(x * w, axis=-1, keepdims=True)


# --------------------------------------------------------------------------


def _img(seed=0, shape=(2, 8, 8, 3)):
    return np.random.RandomState(seed).rand(*shape).astype(np.float32)


@validation.case("resize_bilinear")
def _check_bilinear():
    x = _img(0)
    got = np.asarray(_REG.exec("resize_bilinear", jnp.asarray(x), size=(4, 4)))
    assert got.shape == (2, 4, 4, 3)
    # downscale-by-2 bilinear == 2x2 average at aligned half-pixel centers
    import tensorflow as tf

    want = tf.image.resize(x, (4, 4), method="bilinear").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@validation.case("resize_nearest_neighbor")
def _check_nearest():
    x = _img(1)
    got = np.asarray(_REG.exec("resize_nearest_neighbor", jnp.asarray(x),
                               size=(16, 16)))
    np.testing.assert_array_equal(got[:, ::2, ::2], x)


@validation.case("resize_bicubic")
def _check_bicubic():
    x = _img(2)
    got = np.asarray(_REG.exec("resize_bicubic", jnp.asarray(x), size=(16, 16)))
    assert got.shape == (2, 16, 16, 3) and np.isfinite(got).all()


@validation.case("crop_and_resize")
def _check_crop_resize():
    import tensorflow as tf

    x = _img(3, (2, 10, 10, 1))
    boxes = np.asarray([[0.0, 0.0, 0.5, 0.5], [0.2, 0.2, 0.9, 0.8]], np.float32)
    bi = np.asarray([0, 1], np.int32)
    got = np.asarray(_REG.exec("crop_and_resize", jnp.asarray(x),
                               jnp.asarray(boxes), jnp.asarray(bi),
                               crop_size=(4, 4)))
    want = tf.image.crop_and_resize(x, boxes, bi, (4, 4)).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    # size-1 crop dims sample the box center (TF rule)
    got1 = np.asarray(_REG.exec("crop_and_resize", jnp.asarray(x),
                                jnp.asarray(boxes), jnp.asarray(bi),
                                crop_size=(1, 1)))
    want1 = tf.image.crop_and_resize(x, boxes, bi, (1, 1)).numpy()
    np.testing.assert_allclose(got1, want1, rtol=1e-3, atol=1e-4)


@validation.case("non_max_suppression")
def _check_nms():
    import tensorflow as tf

    r = np.random.RandomState(4)
    base = r.rand(12, 2).astype(np.float32)
    boxes = np.concatenate([base, base + 0.3 + 0.2 * r.rand(12, 2).astype(np.float32)], 1)
    scores = r.rand(12).astype(np.float32)
    idx, mask = _REG.exec("non_max_suppression", jnp.asarray(boxes),
                          jnp.asarray(scores), max_output_size=5,
                          iou_threshold=0.5)
    got = np.asarray(idx)[np.asarray(mask).astype(bool)]
    want = tf.image.non_max_suppression(boxes, scores, 5, 0.5).numpy()
    np.testing.assert_array_equal(got, want)


@validation.case("extract_image_patches")
def _check_patches():
    import tensorflow as tf

    x = _img(5, (1, 6, 6, 2))
    got = np.asarray(_REG.exec("extract_image_patches", jnp.asarray(x),
                               kernel=(3, 3), strides=(2, 2)))
    want = tf.image.extract_patches(x, [1, 3, 3, 1], [1, 2, 2, 1],
                                    [1, 1, 1, 1], "VALID").numpy()
    np.testing.assert_allclose(got, want, rtol=1e-6)


@validation.case("adjust_contrast")
def _check_contrast():
    import tensorflow as tf

    x = _img(6)
    got = np.asarray(_REG.exec("adjust_contrast", jnp.asarray(x), factor=1.7))
    want = tf.image.adjust_contrast(x, 1.7).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@validation.case("rgb_to_hsv")
def _check_rgb_hsv():
    import tensorflow as tf

    x = _img(7)
    got = np.asarray(_REG.exec("rgb_to_hsv", jnp.asarray(x)))
    want = tf.image.rgb_to_hsv(x).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


@validation.case("hsv_to_rgb")
def _check_hsv_rgb():
    import tensorflow as tf

    x = _img(8)
    hsv = tf.image.rgb_to_hsv(x).numpy()
    got = np.asarray(_REG.exec("hsv_to_rgb", jnp.asarray(hsv)))
    np.testing.assert_allclose(got, x, rtol=1e-3, atol=1e-4)


@validation.case("adjust_hue")
def _check_hue():
    import tensorflow as tf

    x = _img(9)
    got = np.asarray(_REG.exec("adjust_hue", jnp.asarray(x), delta=0.15))
    want = tf.image.adjust_hue(x, 0.15).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


@validation.case("adjust_saturation")
def _check_sat():
    import tensorflow as tf

    x = _img(10)
    got = np.asarray(_REG.exec("adjust_saturation", jnp.asarray(x), factor=0.6))
    want = tf.image.adjust_saturation(x, 0.6).numpy()
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)


@validation.case("rgb_to_grs")
def _check_grs():
    x = _img(11)
    got = np.asarray(_REG.exec("rgb_to_grs", jnp.asarray(x)))
    want = (x * np.asarray([0.2989, 0.5870, 0.1140])).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
