"""Elementwise transform ops — the largest declarable-op family.

Reference parity: libnd4j's legacy transform ops plus the custom elementwise
DynamicCustomOps (include/ops/declarable/generic/transforms/**,
legacy ops enumerated in include/loops/legacy_ops.h; Java surface
org.nd4j.linalg.api.ops.impl.transforms.*). The catalog below preserves the
reference op NAMES (what Nd4j.exec(new DynamicCustomOp("floor", ...)) could
call) while each body is a one-line lowering to jax.numpy/jax.lax — XLA
fuses these into surrounding computations, so there is no per-op kernel to
hand-write (SURVEY §3.1: legacy loop kernels dissolve into XLA elementwise
fusion).

Every table entry auto-registers a numpy-oracle validation case
(ops/validation.py), so the catalog can't grow without coverage.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()


def _posify(x):
    return np.abs(x) + 0.5


def _unit(x):
    return np.clip(x, -0.95, 0.95)


# name -> (jax fn, numpy oracle, input-domain transform)
_UNARY = {
    "abs": (jnp.abs, np.abs, None),
    "ceil": (jnp.ceil, np.ceil, None),
    "floor": (jnp.floor, np.floor, None),
    "rint": (jnp.rint, np.rint, None),
    "round": (jnp.round, np.round, None),
    "exp": (jnp.exp, np.exp, None),
    "expm1": (jnp.expm1, np.expm1, None),
    "log": (jnp.log, np.log, _posify),
    "log1p": (jnp.log1p, np.log1p, _posify),
    "log2": (jnp.log2, np.log2, _posify),
    "sqrt": (jnp.sqrt, np.sqrt, _posify),
    "rsqrt": (jax.lax.rsqrt, lambda x: 1.0 / np.sqrt(x), _posify),
    "square": (jnp.square, np.square, None),
    "cube": (lambda x: x * x * x, lambda x: x ** 3, None),
    "reciprocal": (jnp.reciprocal, lambda x: 1.0 / x, _posify),
    "neg": (jnp.negative, np.negative, None),
    "sign": (jnp.sign, np.sign, None),
    "sin": (jnp.sin, np.sin, None),
    "cos": (jnp.cos, np.cos, None),
    "tan": (jnp.tan, np.tan, _unit),
    "asin": (jnp.arcsin, np.arcsin, _unit),
    "acos": (jnp.arccos, np.arccos, _unit),
    "atan": (jnp.arctan, np.arctan, None),
    "sinh": (jnp.sinh, np.sinh, None),
    "cosh": (jnp.cosh, np.cosh, None),
    "tanh": (jnp.tanh, np.tanh, None),
    "asinh": (jnp.arcsinh, np.arcsinh, None),
    "acosh": (jnp.arccosh, np.arccosh, lambda x: np.abs(x) + 1.5),
    "atanh": (jnp.arctanh, np.arctanh, _unit),
    "erf": (jax.lax.erf, None, None),  # scipy-free oracle below
    "erfc": (jax.lax.erfc, None, None),
    "sigmoid": (jax.nn.sigmoid, lambda x: 1.0 / (1.0 + np.exp(-x)), None),
    "softsign": (jax.nn.soft_sign, lambda x: x / (1.0 + np.abs(x)), None),
    "softplus": (jax.nn.softplus, lambda x: np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0), None),
    "relu6": (jax.nn.relu6, lambda x: np.minimum(np.maximum(x, 0), 6), None),
    "hard_sigmoid": (jax.nn.hard_sigmoid, lambda x: np.clip(x / 6.0 + 0.5, 0, 1), None),
    "hard_tanh": (jax.nn.hard_tanh, lambda x: np.clip(x, -1, 1), None),
    "selu": (jax.nn.selu, None, None),
    "elu": (jax.nn.elu, lambda x: np.where(x > 0, x, np.expm1(x)), None),
    "gelu": (functools.partial(jax.nn.gelu, approximate=False), None, None),
    "swish": (jax.nn.swish, lambda x: x / (1.0 + np.exp(-x)), None),
    "mish": (jax.nn.mish, lambda x: x * np.tanh(np.log1p(np.exp(-np.abs(x))) + np.maximum(x, 0)), None),
    "identity": (lambda x: x, lambda x: x, None),
    "isnan": (jnp.isnan, np.isnan, None),
    "isinf": (jnp.isinf, np.isinf, None),
    "isfinite": (jnp.isfinite, np.isfinite, None),
}

_BINARY = {
    "add": (jnp.add, np.add, False),
    "subtract": (jnp.subtract, np.subtract, False),
    "multiply": (jnp.multiply, np.multiply, False),
    "divide": (jnp.divide, np.divide, True),
    "reversesubtract": (lambda x, y: y - x, lambda x, y: y - x, False),
    "reversedivide": (lambda x, y: y / x, lambda x, y: y / x, True),
    "maximum": (jnp.maximum, np.maximum, False),
    "minimum": (jnp.minimum, np.minimum, False),
    "squaredsubtract": (lambda x, y: jnp.square(x - y), lambda x, y: (x - y) ** 2, False),
    "atan2": (jnp.arctan2, np.arctan2, False),
    "mod": (jnp.mod, np.mod, True),
    "floormod": (jnp.mod, np.mod, True),
    "truncatemod": (jnp.fmod, np.fmod, True),
    "floordiv": (jnp.floor_divide, np.floor_divide, True),
    "truncatediv": (lambda x, y: jnp.trunc(x / y), lambda x, y: np.trunc(x / y), True),
    "pow": (jnp.power, np.power, "pow"),
}

_COMPARE = {
    "equals": (lambda x, y: x == y, np.equal),
    "not_equals": (lambda x, y: x != y, np.not_equal),
    "less": (lambda x, y: x < y, np.less),
    "less_equal": (lambda x, y: x <= y, np.less_equal),
    "greater": (lambda x, y: x > y, np.greater),
    "greater_equal": (lambda x, y: x >= y, np.greater_equal),
    "boolean_and": (jnp.logical_and, np.logical_and),
    "boolean_or": (jnp.logical_or, np.logical_or),
    "boolean_xor": (jnp.logical_xor, np.logical_xor),
    "boolean_not": (jnp.logical_not, np.logical_not),
}


def _register_unary():
    from scipy import special as _sp  # in-env scipy as independent oracle

    oracles = {"erf": _sp.erf, "erfc": _sp.erfc,
               "selu": lambda x: 1.0507009873554805 * np.where(
                   x > 0, x, 1.6732632423543772 * np.expm1(x)),
               "gelu": lambda x: x * 0.5 * (1.0 + _sp.erf(x / np.sqrt(2.0)))}

    for name, (jfn, npfn, domain) in _UNARY.items():
        _REG.register(name, functools.partial(_unary_apply, jfn),
                      doc=f"elementwise {name} (libnd4j legacy transform)")
        oracle = npfn or oracles[name]
        validation.add_case(name, functools.partial(
            _check_unary, name, oracle, domain))


def _unary_apply(jfn, x):
    return jfn(x)


def _check_unary(name, oracle, domain):
    import jax.numpy as jnp

    r = np.random.RandomState(0)
    x = r.randn(4, 33).astype(np.float32)
    if domain is not None:
        x = domain(x).astype(np.float32)
    got = np.asarray(_REG.exec(name, jnp.asarray(x)))
    want = oracle(x)
    if got.dtype == np.bool_:
        np.testing.assert_array_equal(got, want)
    else:
        kw = {"rtol": 2e-4, "atol": 1e-5} if name == "gelu" else \
             {"rtol": 1e-5, "atol": 1e-6}
        np.testing.assert_allclose(got, want.astype(got.dtype), **kw)


def _register_binary():
    for name, (jfn, npfn, mode) in _BINARY.items():
        _REG.register(name, functools.partial(_binary_apply, jfn),
                      doc=f"elementwise pairwise {name} (libnd4j pairwise transform)")
        validation.add_case(name, functools.partial(
            _check_binary, name, npfn, mode))


def _binary_apply(jfn, x, y):
    return jfn(x, y)


def _check_binary(name, oracle, mode):
    import jax.numpy as jnp

    r = np.random.RandomState(1)
    x = r.randn(3, 17).astype(np.float32)
    y = r.randn(3, 17).astype(np.float32)
    if mode is True:  # divisor-safe
        y = (np.abs(y) + 0.5).astype(np.float32)
    elif mode == "pow":
        x = (np.abs(x) + 0.1).astype(np.float32)
    got = np.asarray(_REG.exec(name, jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(got, oracle(x, y).astype(got.dtype),
                               rtol=1e-5, atol=1e-6)


def _register_compare():
    for name, (jfn, npfn) in _COMPARE.items():
        if name == "boolean_not":
            _REG.register(name, lambda x: jnp.logical_not(x),
                          doc="elementwise logical not")
            validation.add_case(name, functools.partial(_check_bool_unary, name, npfn))
            continue
        _REG.register(name, functools.partial(_binary_apply, jfn),
                      doc=f"elementwise comparison {name} (libnd4j broadcast comparison)")
        validation.add_case(name, functools.partial(_check_compare, name, npfn))


def _check_compare(name, oracle):
    import jax.numpy as jnp

    r = np.random.RandomState(2)
    if name.startswith("boolean"):
        x = r.rand(4, 9) > 0.5
        y = r.rand(4, 9) > 0.5
    else:
        x = r.randint(-3, 3, (4, 9)).astype(np.float32)
        y = r.randint(-3, 3, (4, 9)).astype(np.float32)
    got = np.asarray(_REG.exec(name, jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_array_equal(got, oracle(x, y))


def _check_bool_unary(name, oracle):
    import jax.numpy as jnp

    x = np.random.RandomState(3).rand(5, 7) > 0.5
    np.testing.assert_array_equal(
        np.asarray(_REG.exec(name, jnp.asarray(x))), oracle(x))


# ---- select / where -------------------------------------------------------


def _register_select():
    def select(cond, x, y):
        """reference Select op (generic/transforms/select.cpp analog)."""
        return jnp.where(cond, x, y)

    def where_op(cond):
        """reference Where (index form): returns indices of nonzero entries.
        Dynamic output size is not XLA-expressible; mirrors jnp.argwhere with
        the size= escape hatch (padded with fill_value=-1)."""
        # np on cond.shape only — static ints, never traced data
        n = int(np.prod(cond.shape))  # graftlint: disable=GL009
        return jnp.argwhere(cond, size=n, fill_value=-1)

    def select_v1(cond, x, y):
        """TF v1 Select semantics: a rank-1 cond broadcasts over the FIRST
        dimension of higher-rank x/y (unlike SelectV2's numpy-style
        trailing broadcast)."""
        if cond.ndim == 1 and x.ndim > 1:
            cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(cond, x, y)

    _REG.register("select", select, doc=select.__doc__)
    _REG.register("select_v1", select_v1, doc=select_v1.__doc__)
    _REG.register("where", where_op, doc=where_op.__doc__)

    def check_select():
        r = np.random.RandomState(4)
        c = r.rand(4, 5) > 0.5
        x = r.randn(4, 5).astype(np.float32)
        y = r.randn(4, 5).astype(np.float32)
        np.testing.assert_array_equal(
            np.asarray(_REG.exec("select", jnp.asarray(c), jnp.asarray(x), jnp.asarray(y))),
            np.where(c, x, y))

    def check_where():
        c = np.asarray([[True, False], [False, True]])
        got = np.asarray(_REG.exec("where", jnp.asarray(c)))
        valid = got[(got >= 0).all(axis=1)]
        np.testing.assert_array_equal(valid, np.argwhere(c))

    def check_select_v1():
        r = np.random.RandomState(5)
        c = r.rand(3) > 0.5
        x = r.randn(3, 4).astype(np.float32)
        y = r.randn(3, 4).astype(np.float32)
        got = np.asarray(_REG.exec("select_v1", jnp.asarray(c),
                                   jnp.asarray(x), jnp.asarray(y)))
        want = np.where(c[:, None], x, y)
        np.testing.assert_array_equal(got, want)
        # rank-matched cond: plain elementwise select
        cm = r.rand(3, 4) > 0.5
        got2 = np.asarray(_REG.exec("select_v1", jnp.asarray(cm),
                                    jnp.asarray(x), jnp.asarray(y)))
        np.testing.assert_array_equal(got2, np.where(cm, x, y))

    validation.add_case("select", check_select)
    validation.add_case("select_v1", check_select_v1)
    validation.add_case("where", check_where)


_register_unary()
_register_binary()
_register_compare()
_register_select()
