"""Activation catalog — parity with ND4J's IActivation implementations.

Reference: org.nd4j.linalg.activations.Activation enum + impl classes
(nd4j-api, org/nd4j/linalg/activations/impl/*). Each reference impl carries a
hand-written backprop method; here gradients come from jax autodiff, so an
activation is just a pure function. The *name set* below matches the
reference's Activation enum so JSON configs round-trip.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp


def identity(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jax.nn.relu6(x)


def leakyrelu(x, alpha: float = 0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


def elu(x, alpha: float = 1.0):
    return jax.nn.elu(x, alpha=alpha)


def selu(x):
    return jax.nn.selu(x)


def gelu(x):
    # Reference GELU (ActivationGELU) uses the tanh approximation by default.
    return jax.nn.gelu(x, approximate=True)


def swish(x):
    return jax.nn.silu(x)


def mish(x):
    return jax.nn.mish(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def tanh(x):
    return jnp.tanh(x)


def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


def rationaltanh(x):
    # ActivationRationalTanh: 1.7159 * tanh_approx(2x/3) using a rational
    # approximation f(x) = clip-free algebraic tanh; we follow the published
    # formula tanh_approx(y) = sign(y) * (1 - 1/(1+|y|+y^2+1.41645*y^4)).
    y = 2.0 * x / 3.0
    a = jnp.abs(y)
    approx = 1.0 - 1.0 / (1.0 + a + y * y + 1.41645 * (y ** 4))
    return 1.7159 * jnp.sign(y) * approx


def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def cube(x):
    return x ** 3


def softmax(x, axis: int = -1):
    return jax.nn.softmax(x, axis=axis)


def logsoftmax(x, axis: int = -1):
    return jax.nn.log_softmax(x, axis=axis)


def thresholdedrelu(x, theta: float = 1.0):
    return jnp.where(x > theta, x, 0.0)


def rrelu(x, lower: float = 1.0 / 8.0, upper: float = 1.0 / 3.0):
    # Deterministic (inference-mode) RReLU: slope = mean of the range, matching
    # the reference's test-time behavior of ActivationRReLU.
    return jnp.where(x >= 0, x, x * ((lower + upper) / 2.0))


def prelu(x, alpha):
    return jnp.where(x >= 0, x, alpha * x)


# Name table == Activation enum surface (lowercased, as Jackson serializes).
ACTIVATIONS: Dict[str, Callable] = {
    "identity": identity,
    "linear": identity,
    "relu": relu,
    "relu6": relu6,
    "leakyrelu": leakyrelu,
    "elu": elu,
    "selu": selu,
    "gelu": gelu,
    "swish": swish,
    "mish": mish,
    "sigmoid": sigmoid,
    "hardsigmoid": hardsigmoid,
    "tanh": tanh,
    "hardtanh": hardtanh,
    "rationaltanh": rationaltanh,
    "rectifiedtanh": rectifiedtanh,
    "softplus": softplus,
    "softsign": softsign,
    "cube": cube,
    "softmax": softmax,
    "logsoftmax": logsoftmax,
    "thresholdedrelu": thresholdedrelu,
    "rrelu": rrelu,
}


def get_activation(name_or_fn) -> Callable:
    """Resolve an activation by enum name (case-insensitive) or callable."""
    if callable(name_or_fn):
        return name_or_fn
    name = str(name_or_fn).lower()
    try:
        return ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation '{name_or_fn}'; known: {sorted(ACTIVATIONS)}"
        ) from None
