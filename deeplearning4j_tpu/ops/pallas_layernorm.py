"""Pallas TPU fused LayerNorm(+activation) — `fused_layer_norm`.

XLA computes layer_norm as separate reduce (mean), reduce (var), and
normalize passes; with a downstream GELU the normalized tensor is re-read a
third time. For the transformer block layout (LN → GELU appears in imported
MLP heads and the optimizer's fusion tier routes the chain here —
docs/OPTIMIZER.md § Fusion tier) this kernel makes the one-pass contract
explicit: each (block_rows, D) tile is read from HBM once, mean/variance
reduce on the lane axis in VMEM, the normalize + affine + activation all
apply to the in-register f32 tile, and the finished activation is written
once.

Forward runs Pallas; backward is the custom_vjp XLA path — ``jax.vjp`` of
the generic math (the exact chain XLA already emits fused for the backward;
the fusion win is the forward's eliminated reduce/normalize round-trips),
recomputing from the saved inputs so no (rows, D) f32 residual is stored.
Same design as ``ops/pallas_matmul.py``. Runs in interpret mode off-TPU.

Dispatch: registered as the TPU platform helper for the generic registry
op; the usable() gate requires a Mosaic-aligned trailing dim and at least
the tuning table's measured ``min_rows`` (``ops/tuning.py``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops.nn_ops import (
    FUSED_MATMUL_ACTIVATIONS, apply_fused_activation)
from deeplearning4j_tpu.ops.registry import op


@op("fused_layer_norm")
def fused_layer_norm(x, gain, bias=None, *, axis: int = -1,
                     eps: float = 1e-5, activation: str = "none"):
    """act(layer_norm(x) * gain + bias) — the LN-epilogue fusion target.

    Same contract as the catalog ``layer_norm`` op plus an ``activation``
    epilogue from :data:`FUSED_MATMUL_ACTIVATIONS` (the optimizer's fusion
    tier emits the gelu variants). The generic impl is the exact op chain
    it replaces; the Pallas TPU helper runs it in one HBM pass.

    Trailing-axis only: the (N,)-shaped gain/bias broadcast along the last
    axis, so a non-trailing ``axis`` would normalize one axis and scale
    another — rejected loudly instead of returning silently wrong values
    (the fusion matcher and the graftcheck rule enforce the same)."""
    if axis not in (-1, x.ndim - 1):
        raise ValueError(
            f"fused_layer_norm normalizes the trailing axis only "
            f"(gain/bias are per-last-dim); got axis={axis} for rank "
            f"{x.ndim} — use the catalog layer_norm for other axes")
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps) * gain
    if bias is not None:
        out = out + bias
    return apply_fused_activation(out, activation)


# ---------------------------------------------------------------------------
# Pallas forward kernel
# ---------------------------------------------------------------------------


def _kernel(x_ref, g_ref, b_ref, o_ref, *, eps: float, activation: str,
            has_bias: bool):
    """One (block_rows, D) tile: mean/var lane reductions in f32, then
    normalize + affine + activation on the in-VMEM tile, one write."""
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    c = x - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps) * g_ref[...].astype(jnp.float32)
    if has_bias:
        y = y + b_ref[...].astype(jnp.float32)
    y = apply_fused_activation(y, activation)
    o_ref[...] = y.astype(o_ref.dtype)


def fused_layer_norm_pallas(x, gain, bias=None, *, eps: float = 1e-5,
                            activation: str = "none", block_rows: int = 0,
                            interpret=None):
    """Pallas forward for act(LN(x)·gain+bias) over the trailing axis.

    Leading dims fold into rows; rows must divide by the (tuned) row block
    and D by 128 — the usable() gate guarantees both on the dispatch path."""
    if interpret is None:
        from deeplearning4j_tpu.ops.registry import current_platform

        interpret = current_platform() != "tpu"
    lead = x.shape[:-1]
    d = x.shape[-1]
    rows = 1
    for s in lead:
        rows *= s
    if not block_rows:
        from deeplearning4j_tpu.ops import tuning

        block_rows = tuning.tuned_block(
            "fused_layer_norm", "block_rows", rows,
            tuning.bucket_rows(rows),
            lambda r: next((c for c in (256, 64, 8) if r % c == 0), r))
    if rows % block_rows:
        raise ValueError(f"rows {rows} not divisible by row block "
                         f"{block_rows}")
    x2 = x.reshape(rows, d)
    has_bias = bias is not None
    b = (bias if has_bias else jnp.zeros((d,), x.dtype)).reshape(1, d)
    kern = functools.partial(_kernel, eps=eps, activation=activation,
                             has_bias=has_bias)
    out = pl.pallas_call(
        kern,
        out_shape=jax.ShapeDtypeStruct((rows, d), x.dtype),
        grid=(rows // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        interpret=interpret,
    )(x2, gain.reshape(1, d), b)
    return out.reshape(lead + (d,))


# ---------------------------------------------------------------------------
# differentiable wrapper: Pallas forward, XLA-math backward
# ---------------------------------------------------------------------------


def _generic_f32(x, gain, bias, eps, activation):
    """The reference math at f32 — the backward's recompute target."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    c = xf - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps) * gain.astype(jnp.float32)
    y = y + bias.astype(jnp.float32)
    return apply_fused_activation(y, activation)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _fused_ln(x, gain, bias, eps, activation):
    return fused_layer_norm_pallas(x, gain, bias, eps=eps,
                                   activation=activation)


def _fused_ln_fwd(x, gain, bias, eps, activation):
    return _fused_ln(x, gain, bias, eps, activation), (x, gain, bias)


def _fused_ln_bwd(eps, activation, res, g):
    x, gain, bias = res
    # jax.vjp of the f32 reference math: the same backward XLA derives for
    # the unfused chain, recomputed from inputs (no saved residuals)
    _, vjp = jax.vjp(
        lambda xx, gg, bb: _generic_f32(xx, gg, bb, eps, activation),
        x, gain, bias)
    dx, dg, db = vjp(g.astype(jnp.float32))
    return (dx.astype(x.dtype), dg.astype(gain.dtype),
            db.astype(bias.dtype))


_fused_ln.defvjp(_fused_ln_fwd, _fused_ln_bwd)


def fused_layer_norm_helper(x, gain, bias=None, *, axis: int = -1,
                            eps: float = 1e-5, activation: str = "none"):
    """The registered TPU platform impl: differentiable Pallas forward."""
    b = bias if bias is not None else jnp.zeros((x.shape[-1],), x.dtype)
    return _fused_ln(x, gain, b, eps, activation)


def _usable(x, gain, bias=None, **kw):
    """PlatformHelper::isUsable: trailing-axis norm only, Mosaic-aligned
    tiles, a known activation, and at least the measured min_rows."""
    ax = kw.get("axis", -1)
    nd = getattr(x, "ndim", 0)
    if nd < 2 or ax not in (-1, nd - 1):
        return False
    if kw.get("activation", "none") not in FUSED_MATMUL_ACTIVATIONS:
        return False
    for a in (x, gain) + (() if bias is None else (bias,)):
        dt = getattr(a, "dtype", None)
        if dt is None or not jnp.issubdtype(dt, jnp.floating):
            return False
    if getattr(gain, "ndim", 0) != 1 or gain.shape[0] != x.shape[-1]:
        return False
    if bias is not None and (getattr(bias, "ndim", 0) != 1
                             or bias.shape[0] != x.shape[-1]):
        return False
    rows = 1
    for s in x.shape[:-1]:
        rows *= s
    from deeplearning4j_tpu.ops import tuning

    if rows < int(tuning.tuned("fused_layer_norm", "min_rows", 8)):
        return False
    return x.shape[-1] % 128 == 0 and rows % 8 == 0


def _check_fused_layer_norm():
    """Validation case (ops.validation ratchet): generic impl vs a numpy
    oracle, and the Pallas interpret kernel vs both, across activations."""
    import math

    import numpy as np

    r = np.random.RandomState(13)
    x = r.randn(16, 128).astype(np.float32)
    g = (r.rand(128) + 0.5).astype(np.float32)
    b = r.randn(128).astype(np.float32)
    eps = 1e-5

    def oracle(act):
        mean = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        y = (x - mean) / np.sqrt(var + eps) * g + b
        if act == "gelu":
            return 0.5 * y * (1.0 + np.tanh(
                math.sqrt(2.0 / math.pi) * (y + 0.044715 * y ** 3)))
        if act == "gelu_exact":
            return y * 0.5 * (1.0 + np.vectorize(math.erf)(y / math.sqrt(2)))
        return y

    for act in ("none", "gelu", "gelu_exact"):
        want = oracle(act)
        got = fused_layer_norm.fn(jnp.asarray(x), jnp.asarray(g),
                                  jnp.asarray(b), eps=eps, activation=act)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4,
                                   atol=1e-5)
        got_pl = fused_layer_norm_pallas(
            jnp.asarray(x), jnp.asarray(g), jnp.asarray(b), eps=eps,
            activation=act, block_rows=8, interpret=True)
        np.testing.assert_allclose(np.asarray(got_pl), want, rtol=1e-4,
                                   atol=1e-5)


def register_platform_fused_layernorm() -> None:
    """Install the Pallas fused LN(+activation) kernel as the TPU platform
    override for fused_layer_norm (cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops import validation as _validation
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()
    desc = reg.get("fused_layer_norm")
    if "tpu" not in desc.platform_impls:
        reg.register_platform("fused_layer_norm", "tpu",
                              fused_layer_norm_helper, _usable)
        _validation.add_case("fused_layer_norm", _check_fused_layer_norm)
