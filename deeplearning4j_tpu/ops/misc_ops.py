"""Remaining declarable-op families: top-k, CTC, set/histogram, norms.

Reference parity:
  * top_k / in_top_k — generic/parity_ops/top_k.cpp, in_top_k.cpp
  * ctc_loss — generic/nn/ctc_loss.cpp (+ the cuDNN ctcloss platform helper;
    SURVEY §3.1 lists ctc among the cuDNN-helper ops)
  * unique, listdiff — generic/parity_ops/unique.cpp, listdiff.cpp
  * nth_element — generic/parity_ops/nth_element.cpp
  * confusion_matrix — generic/parity_ops/confusion_matrix.cpp
  * histogram, histogram_fixed_width — generic/parity_ops/histogram*.cpp
  * clip_by_global_norm / clip_by_avg_norm — generic/transforms/clip ops
  * l2_normalize, zeta, polygamma, digamma, lgamma, igamma —
    generic/parity_ops math specials

The CTC forward is a log-semiring alpha recursion under lax.scan — static
shapes, no host loop; oracle is optax.ctc_loss.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()


def _op(name):
    def deco(fn):
        _REG.register(name, fn, doc=fn.__doc__ or "")
        return fn

    return deco


@_op("top_k")
def top_k(x, *, k: int, sorted: bool = True):
    """top_k → (values, indices) along the last axis
    (generic/parity_ops/top_k.cpp)."""
    return jax.lax.top_k(x, k)


@_op("in_top_k")
def in_top_k(predictions, targets, *, k: int):
    """whether targets[i] ranks in the top-k of predictions[i]
    (generic/parity_ops/in_top_k.cpp)."""
    target_logit = jnp.take_along_axis(
        predictions, targets[:, None].astype(jnp.int32), axis=1)[:, 0]
    rank = jnp.sum(predictions > target_logit[:, None], axis=1)
    return rank < k


@_op("ctc_loss")
def ctc_loss(logits, labels, logit_lengths, label_lengths, *, blank: int = 0):
    """CTC negative log-likelihood (generic/nn/ctc_loss.cpp; cuDNN ctcloss
    helper analog). logits: (B, T, C) unnormalized; labels: (B, S) int
    (padded); lengths: (B,). Returns per-example loss (B,).

    Log-semiring alpha recursion over the blank-interleaved extended label
    sequence, scanned over time with lax.scan — the whole computation is one
    XLA program (no host loop), so it fuses and runs on the VPU."""
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    b, t_max, _ = logits.shape
    s_max = labels.shape[1]
    neg_inf = -1e30

    def one(lp, lab, t_len, s_len):
        # extended labels: [blank, l1, blank, l2, ..., blank] — length 2S+1
        ext = jnp.full((2 * s_max + 1,), blank, lab.dtype)
        ext = ext.at[1::2].set(lab)
        n_ext = 2 * s_len + 1
        # can skip from s-2 when ext[s] is a label differing from ext[s-2]
        can_skip = jnp.zeros((2 * s_max + 1,), bool)
        if s_max > 1:
            can_skip = can_skip.at[3::2].set(lab[1:] != lab[:-1])

        alpha0 = jnp.full((2 * s_max + 1,), neg_inf)
        alpha0 = alpha0.at[0].set(lp[0, blank])
        if s_max >= 1:
            alpha0 = alpha0.at[1].set(lp[0, ext[1]])

        def step(alpha, lp_t):
            prev1 = jnp.concatenate([jnp.full((1,), neg_inf), alpha[:-1]])
            prev2 = jnp.concatenate([jnp.full((2,), neg_inf), alpha[:-2]])
            prev2 = jnp.where(can_skip, prev2, neg_inf)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, prev1), prev2)
            return merged + lp_t[ext]

        # scan all steps, freezing alpha once t >= t_len (padded frames)
        def scan_step(carry, lp_t):
            alpha, t = carry
            new_alpha = step(alpha, lp_t)
            alpha = jnp.where(t < t_len, new_alpha, alpha)
            return (alpha, t + 1), None

        (alpha, _), _ = jax.lax.scan(scan_step, (alpha0, jnp.asarray(1)),
                                     lp[1:])
        last = alpha[n_ext - 1]
        second = jnp.where(n_ext >= 2, alpha[n_ext - 2], neg_inf)
        return -jnp.logaddexp(last, second)

    return jax.vmap(one)(log_probs, labels, logit_lengths, label_lengths)


@_op("unique")
def unique(x, *, size: int = None, fill_value=0):
    """unique values + inverse indices (generic/parity_ops/unique.cpp).
    XLA needs static shapes: pass size (defaults to len(x)); extras padded
    with fill_value."""
    # np on x.shape only — static ints, never traced data
    size = size if size is not None else int(np.prod(x.shape))  # graftlint: disable=GL009
    vals, inv = jnp.unique(x.ravel(), return_inverse=True, size=size,
                           fill_value=fill_value)
    return vals, inv.reshape(x.shape)


@_op("listdiff")
def listdiff(x, y, *, size: int = None):
    """elements of x not in y (generic/parity_ops/listdiff.cpp): returns
    (values padded to ``size``, 0/1 validity mask)."""
    size = size if size is not None else int(x.shape[0])
    keep = ~jnp.isin(x, y)
    order = jnp.argsort(~keep, stable=True)
    vals = x[order]
    mask = (jnp.arange(x.shape[0]) < jnp.sum(keep)).astype(jnp.int32)
    vals = jnp.where(mask.astype(bool), vals, 0)
    return vals[:size], mask[:size]


@_op("nth_element")
def nth_element(x, *, n: int, reverse: bool = False):
    """n-th order statistic along the last axis
    (generic/parity_ops/nth_element.cpp)."""
    s = jnp.sort(x, axis=-1)
    if reverse:
        s = jnp.flip(s, axis=-1)
    return s[..., n]


@_op("confusion_matrix")
def confusion_matrix(labels, predictions, *, num_classes: int, weights=None):
    """confusion matrix (generic/parity_ops/confusion_matrix.cpp)."""
    idx = labels.astype(jnp.int32) * num_classes + predictions.astype(jnp.int32)
    w = jnp.ones_like(idx, jnp.float32) if weights is None else weights
    flat = jnp.zeros((num_classes * num_classes,), w.dtype).at[idx].add(w)
    return flat.reshape(num_classes, num_classes)


@_op("histogram")
def histogram(x, *, num_bins: int):
    """equal-width histogram over [min, max]
    (generic/parity_ops/histogram.cpp)."""
    lo, hi = jnp.min(x), jnp.max(x)
    width = jnp.maximum(hi - lo, 1e-12)
    bins = jnp.clip(((x - lo) / width * num_bins).astype(jnp.int32),
                    0, num_bins - 1)
    return jnp.zeros((num_bins,), jnp.int32).at[bins.ravel()].add(1)


@_op("histogram_fixed_width")
def histogram_fixed_width(x, *, range, num_bins: int = 100):
    """histogram over an explicit [lo, hi] range
    (generic/parity_ops/histogram_fixed_width.cpp)."""
    lo, hi = range
    width = (hi - lo) / num_bins
    bins = jnp.clip(((x - lo) / width).astype(jnp.int32), 0, num_bins - 1)
    return jnp.zeros((num_bins,), jnp.int32).at[bins.ravel()].add(1)


@_op("clip_by_global_norm")
def clip_by_global_norm(*xs, clip_norm: float):
    """scale a tensor list so the joint L2 norm <= clip_norm
    (generic/transforms/clip_by_global_norm analog)."""
    gn = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in xs))
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(gn, 1e-12))
    return tuple(x * scale for x in xs)


@_op("clip_by_avg_norm")
def clip_by_avg_norm(x, *, clip_norm: float):
    """clip by mean-normalized L2 norm (generic/transforms/clipbyavgnorm)."""
    n = x.size
    avg = jnp.linalg.norm(x.ravel()) / n
    scale = jnp.minimum(1.0, clip_norm / jnp.maximum(avg, 1e-12))
    return x * scale


@_op("l2_normalize")
def l2_normalize(x, *, axis=-1, eps: float = 1e-12):
    """x / ||x||_2 along axis (TF l2_normalize parity)."""
    return x / jnp.sqrt(jnp.maximum(
        jnp.sum(jnp.square(x), axis=axis, keepdims=True), eps))


@_op("lgamma")
def lgamma(x):
    """log-gamma (generic/parity_ops/lgamma.cpp)."""
    return jax.lax.lgamma(x)


@_op("digamma")
def digamma(x):
    """digamma ψ (generic/parity_ops/digamma.cpp)."""
    return jax.lax.digamma(x)


@_op("igamma")
def igamma(a, x):
    """regularized lower incomplete gamma (generic/parity_ops/igamma.cpp)."""
    return jax.lax.igamma(a, x)


@_op("igammac")
def igammac(a, x):
    """regularized upper incomplete gamma (generic/parity_ops/igammac.cpp)."""
    return jax.lax.igammac(a, x)


@_op("betainc")
def betainc(a, b, x):
    """regularized incomplete beta (generic/parity_ops/betainc.cpp)."""
    return jax.lax.betainc(a, b, x)


@_op("zeta")
def zeta(x, q):
    """Hurwitz zeta (generic/parity_ops/zeta.cpp)."""
    return jax.lax.zeta(x, q)


@_op("polygamma")
def polygamma(n, x):
    """polygamma ψ⁽ⁿ⁾ (generic/parity_ops/polygamma.cpp)."""
    return jax.lax.polygamma(n.astype(x.dtype), x)


# --------------------------------------------------------------------------


@validation.case("top_k")
def _check_top_k():
    x = np.random.RandomState(0).randn(4, 10).astype(np.float32)
    vals, idx = _REG.exec("top_k", jnp.asarray(x), k=3)
    want = np.sort(x, axis=1)[:, ::-1][:, :3]
    np.testing.assert_allclose(np.asarray(vals), want, rtol=1e-6)
    np.testing.assert_array_equal(np.take_along_axis(x, np.asarray(idx), 1),
                                  want)


@validation.case("in_top_k")
def _check_in_top_k():
    import tensorflow as tf

    r = np.random.RandomState(1)
    preds = r.randn(6, 8).astype(np.float32)
    targets = r.randint(0, 8, 6).astype(np.int32)
    got = np.asarray(_REG.exec("in_top_k", jnp.asarray(preds),
                               jnp.asarray(targets), k=3))
    want = tf.math.in_top_k(targets, preds, 3).numpy()
    np.testing.assert_array_equal(got, want)


@validation.case("ctc_loss")
def _check_ctc():
    import optax

    r = np.random.RandomState(2)
    b, t, c, s = 3, 12, 6, 4
    logits = r.randn(b, t, c).astype(np.float32)
    labels = r.randint(1, c, (b, s)).astype(np.int32)  # 0 is blank
    logit_lengths = np.asarray([12, 9, 11], np.int32)
    label_lengths = np.asarray([4, 2, 3], np.int32)
    got = np.asarray(_REG.exec(
        "ctc_loss", jnp.asarray(logits), jnp.asarray(labels),
        jnp.asarray(logit_lengths), jnp.asarray(label_lengths)))
    logit_pad = (np.arange(t)[None, :] >= logit_lengths[:, None]).astype(np.float32)
    label_pad = (np.arange(s)[None, :] >= label_lengths[:, None]).astype(np.float32)
    want = np.asarray(optax.ctc_loss(jnp.asarray(logits), jnp.asarray(logit_pad),
                                     jnp.asarray(labels), jnp.asarray(label_pad)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


@validation.case("ctc_loss")
def _check_ctc_grad():
    # gradient exists and is finite (the loss trains)
    r = np.random.RandomState(3)
    logits = jnp.asarray(r.randn(2, 8, 5).astype(np.float32))
    labels = jnp.asarray(r.randint(1, 5, (2, 3)).astype(np.int32))

    def loss(lg):
        return jnp.sum(_REG.exec("ctc_loss", lg, labels,
                                 jnp.asarray([8, 8]), jnp.asarray([3, 2])))

    g = jax.grad(loss)(logits)
    assert np.isfinite(np.asarray(g)).all()


@validation.case("unique")
def _check_unique():
    x = np.asarray([3, 1, 3, 2, 1], np.int32)
    vals, inv = _REG.exec("unique", jnp.asarray(x), size=5, fill_value=0)
    want_vals, want_inv = np.unique(x, return_inverse=True)
    np.testing.assert_array_equal(np.asarray(vals)[:3], want_vals)
    np.testing.assert_array_equal(np.asarray(inv), want_inv)


@validation.case("listdiff")
def _check_listdiff():
    x = np.asarray([1, 2, 3, 4, 5], np.int32)
    y = np.asarray([2, 4], np.int32)
    vals, mask = _REG.exec("listdiff", jnp.asarray(x), jnp.asarray(y))
    got = np.asarray(vals)[np.asarray(mask).astype(bool)]
    np.testing.assert_array_equal(got, [1, 3, 5])


@validation.case("nth_element")
def _check_nth():
    x = np.random.RandomState(4).randn(5, 9).astype(np.float32)
    got = np.asarray(_REG.exec("nth_element", jnp.asarray(x), n=2))
    np.testing.assert_allclose(got, np.sort(x, axis=-1)[:, 2], rtol=1e-6)


@validation.case("confusion_matrix")
def _check_confusion():
    labels = np.asarray([0, 1, 2, 1], np.int32)
    preds = np.asarray([0, 2, 2, 1], np.int32)
    got = np.asarray(_REG.exec("confusion_matrix", jnp.asarray(labels),
                               jnp.asarray(preds), num_classes=3))
    want = np.zeros((3, 3), np.float32)
    for l, p in zip(labels, preds):
        want[l, p] += 1
    np.testing.assert_array_equal(got, want)


@validation.case("histogram")
def _check_histogram():
    x = np.random.RandomState(5).rand(100).astype(np.float32)
    got = np.asarray(_REG.exec("histogram", jnp.asarray(x), num_bins=10))
    assert got.sum() == 100 and got.shape == (10,)


@validation.case("histogram_fixed_width")
def _check_hfw():
    x = np.asarray([0.1, 0.5, 0.9, 0.55], np.float32)
    got = np.asarray(_REG.exec("histogram_fixed_width", jnp.asarray(x),
                               range=(0.0, 1.0), num_bins=2))
    np.testing.assert_array_equal(got, [1, 3])


@validation.case("clip_by_global_norm")
def _check_cgn():
    a = jnp.asarray([3.0, 4.0])
    b = jnp.asarray([0.0])
    ca, cb = _REG.exec("clip_by_global_norm", a, b, clip_norm=1.0)
    total = np.sqrt(np.sum(np.asarray(ca) ** 2) + np.sum(np.asarray(cb) ** 2))
    np.testing.assert_allclose(total, 1.0, rtol=1e-5)


@validation.case("clip_by_avg_norm")
def _check_can():
    x = jnp.asarray([3.0, 4.0])
    got = np.asarray(_REG.exec("clip_by_avg_norm", x, clip_norm=1.0))
    np.testing.assert_allclose(got, np.asarray([3.0, 4.0]) * (1.0 / 2.5),
                               rtol=1e-5)


@validation.case("l2_normalize")
def _check_l2n():
    x = np.random.RandomState(6).randn(3, 4).astype(np.float32)
    got = np.asarray(_REG.exec("l2_normalize", jnp.asarray(x)))
    np.testing.assert_allclose(np.linalg.norm(got, axis=-1), 1.0, rtol=1e-5)


@validation.case("lgamma")
def _check_lgamma():
    from scipy import special

    x = np.abs(np.random.RandomState(7).randn(10).astype(np.float32)) + 0.2
    np.testing.assert_allclose(np.asarray(_REG.exec("lgamma", jnp.asarray(x))),
                               special.gammaln(x), rtol=1e-4, atol=1e-5)


@validation.case("digamma")
def _check_digamma():
    from scipy import special

    x = np.abs(np.random.RandomState(8).randn(10).astype(np.float32)) + 0.5
    np.testing.assert_allclose(np.asarray(_REG.exec("digamma", jnp.asarray(x))),
                               special.digamma(x), rtol=1e-3, atol=1e-4)


@validation.case("igamma")
def _check_igamma():
    from scipy import special

    a = np.asarray([1.0, 2.0, 3.0], np.float32)
    x = np.asarray([0.5, 2.0, 1.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("igamma", jnp.asarray(a), jnp.asarray(x))),
        special.gammainc(a, x), rtol=1e-4, atol=1e-5)


@validation.case("igammac")
def _check_igammac():
    from scipy import special

    a = np.asarray([1.0, 2.0], np.float32)
    x = np.asarray([0.5, 2.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("igammac", jnp.asarray(a), jnp.asarray(x))),
        special.gammaincc(a, x), rtol=1e-4, atol=1e-5)


@validation.case("betainc")
def _check_betainc():
    from scipy import special

    a = np.asarray([1.0, 2.0], np.float32)
    b = np.asarray([2.0, 3.0], np.float32)
    x = np.asarray([0.3, 0.7], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("betainc", jnp.asarray(a), jnp.asarray(b),
                             jnp.asarray(x))),
        special.betainc(a, b, x), rtol=1e-4, atol=1e-5)


@validation.case("zeta")
def _check_zeta():
    from scipy import special

    x = np.asarray([2.0, 3.0], np.float32)
    q = np.asarray([1.0, 2.0], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("zeta", jnp.asarray(x), jnp.asarray(q))),
        special.zeta(x, q), rtol=1e-4, atol=1e-5)


@validation.case("polygamma")
def _check_polygamma():
    from scipy import special

    n = np.asarray([1, 2], np.int32)
    x = np.asarray([1.5, 2.5], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("polygamma", jnp.asarray(n), jnp.asarray(x))),
        special.polygamma(n, x).astype(np.float32), rtol=1e-3, atol=1e-4)


# ---------------------------------------------------------------------------
# round-3 tail: ordering/layout ops completing the ~270-name catalog
# (generic/parity_ops — sort, argsort [dynamic_]stitch done above, roll,
# triu/tril, invert_permutation, meshgrid, stop_gradient, identity_n)
# ---------------------------------------------------------------------------


@_op("sort")
def sort(x, *, axis: int = -1, descending: bool = False):
    """sort along axis (generic/parity_ops/sort.cpp). Descending uses the
    native stable descending sort (ties keep order; NaNs sort FIRST in
    descending order, matching XLA's total order — not numpy's NaN-last)."""
    return jnp.sort(x, axis=axis, descending=descending)


@_op("argsort")
def argsort(x, *, axis: int = -1, descending: bool = False):
    """argsort along axis (Nd4j.sortWithIndices role); stable for ties in
    both directions."""
    return jnp.argsort(x, axis=axis, descending=descending)


@_op("roll")
def roll(x, *, shift, axis=None):
    """cyclic roll (generic/transforms/roll.cpp)."""
    return jnp.roll(x, shift, axis=axis)


@_op("triu")
def triu(x, *, diag: int = 0):
    """upper triangle (generic/parity_ops/triu.cpp)."""
    return jnp.triu(x, k=diag)


@_op("tril")
def tril(x, *, diag: int = 0):
    """lower triangle (generic/parity_ops analog of triu)."""
    return jnp.tril(x, k=diag)


@_op("invert_permutation")
def invert_permutation(x):
    """inverse permutation vector (generic/parity_ops/invertPermutation)."""
    n = x.shape[0]
    return jnp.zeros((n,), x.dtype).at[x].set(jnp.arange(n, dtype=x.dtype))


@_op("meshgrid")
def meshgrid(*xs, indexing: str = "xy"):
    """meshgrid (generic/parity_ops/meshgrid.cpp)."""
    return tuple(jnp.meshgrid(*xs, indexing=indexing))


@_op("stop_gradient")
def stop_gradient(x):
    """gradient barrier (StopGradient op)."""
    return jax.lax.stop_gradient(x)


@_op("identity_n")
def identity_n(*xs):
    """identity over a tensor list (generic/parity_ops/identity_n.cpp)."""
    return tuple(xs)


@_op("mirror_pad")
def mirror_pad(x, *, paddings, mode: str = "reflect"):
    """mirror_pad (generic/parity_ops/mirror_pad.cpp): REFLECT|SYMMETRIC."""
    return jnp.pad(x, paddings, mode=mode.lower())


@_op("batch_gather")
def batch_gather(params, indices):
    """per-batch gather (TF batch_gather parity): gathers along axis
    ``indices.ndim - 1`` of params, broadcasting over params' trailing
    dims — params (B, N, ...) + indices (B, M) → (B, M, ...)."""
    idx = indices.astype(jnp.int32)
    axis = idx.ndim - 1
    expanded = idx.reshape(idx.shape + (1,) * (params.ndim - idx.ndim))
    return jnp.take_along_axis(params, expanded, axis=axis)


@_op("log_sigmoid")
def log_sigmoid(x):
    """log σ(x) (legacy transform)."""
    return jax.nn.log_sigmoid(x)


@_op("cosine_similarity")
def cosine_similarity(a, b, *, axis: int = -1, eps: float = 1e-12):
    """reduce3 cosine similarity (libnd4j reduce3/CosineSimilarity)."""
    num = jnp.sum(a * b, axis=axis)
    den = jnp.linalg.norm(a, axis=axis) * jnp.linalg.norm(b, axis=axis)
    return num / jnp.maximum(den, eps)


@_op("euclidean_distance")
def euclidean_distance(a, b, *, axis: int = -1):
    """reduce3 EuclideanDistance."""
    return jnp.sqrt(jnp.sum(jnp.square(a - b), axis=axis))


@_op("manhattan_distance")
def manhattan_distance(a, b, *, axis: int = -1):
    """reduce3 ManhattanDistance."""
    return jnp.sum(jnp.abs(a - b), axis=axis)


@_op("hamming_distance")
def hamming_distance(a, b, *, axis: int = -1):
    """reduce3 HammingDistance (count of unequal entries)."""
    return jnp.sum((a != b).astype(jnp.float32), axis=axis)


@validation.case("sort")
def _check_sort():
    x = np.random.RandomState(20).randn(4, 7).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("sort", jnp.asarray(x))), np.sort(x, -1))
    np.testing.assert_allclose(
        np.asarray(_REG.exec("sort", jnp.asarray(x), descending=True)),
        -np.sort(-x, -1))
    # NaNs sort first in descending order (XLA total order); stable ties
    got = np.asarray(_REG.exec("sort",
                               jnp.asarray([1.0, np.nan, 3.0]),
                               descending=True))
    assert np.isnan(got[0]) and list(got[1:]) == [3.0, 1.0]
    tie_idx = np.asarray(_REG.exec("argsort",
                                   jnp.asarray([3.0, 1.0, 1.0]),
                                   descending=True))
    np.testing.assert_array_equal(tie_idx, [0, 1, 2])


@validation.case("argsort")
def _check_argsort():
    x = np.random.RandomState(21).randn(3, 6).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("argsort", jnp.asarray(x))), np.argsort(x, -1))


@validation.case("roll")
def _check_roll():
    x = np.arange(12).reshape(3, 4)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("roll", jnp.asarray(x), shift=2, axis=1)),
        np.roll(x, 2, axis=1))


@validation.case("triu")
def _check_triu():
    x = np.random.RandomState(22).randn(4, 4).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("triu", jnp.asarray(x), diag=1)), np.triu(x, 1))


@validation.case("tril")
def _check_tril():
    x = np.random.RandomState(23).randn(4, 4).astype(np.float32)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("tril", jnp.asarray(x))), np.tril(x))


@validation.case("invert_permutation")
def _check_invperm():
    p = np.asarray([2, 0, 3, 1], np.int32)
    got = np.asarray(_REG.exec("invert_permutation", jnp.asarray(p)))
    np.testing.assert_array_equal(got[p], np.arange(4))


@validation.case("meshgrid")
def _check_meshgrid():
    a, b = _REG.exec("meshgrid", jnp.arange(3), jnp.arange(2))
    wa, wb = np.meshgrid(np.arange(3), np.arange(2))
    np.testing.assert_array_equal(np.asarray(a), wa)
    np.testing.assert_array_equal(np.asarray(b), wb)


@validation.case("stop_gradient")
def _check_stopgrad():
    g = jax.grad(lambda x: jnp.sum(_REG.exec("stop_gradient", x) * x))(
        jnp.ones(3))
    np.testing.assert_allclose(np.asarray(g), 1.0)  # only the outer x


@validation.case("identity_n")
def _check_idn():
    a, b = _REG.exec("identity_n", jnp.ones(2), jnp.zeros(3))
    assert np.asarray(a).shape == (2,) and np.asarray(b).shape == (3,)


@validation.case("mirror_pad")
def _check_mirror_pad():
    x = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_array_equal(
        np.asarray(_REG.exec("mirror_pad", jnp.asarray(x),
                             paddings=[(1, 1), (1, 1)], mode="symmetric")),
        np.pad(x, [(1, 1), (1, 1)], mode="symmetric"))


@validation.case("batch_gather")
def _check_batch_gather():
    x = np.random.RandomState(24).randn(3, 5).astype(np.float32)
    idx = np.asarray([[0, 2], [1, 1], [4, 0]], np.int32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("batch_gather", jnp.asarray(x), jnp.asarray(idx))),
        np.take_along_axis(x, idx, axis=-1))
    # the canonical higher-rank case: (B, N, D) + (B, M) → (B, M, D)
    p3 = np.random.RandomState(25).randn(2, 4, 3).astype(np.float32)
    i2 = np.asarray([[0, 3], [2, 1]], np.int32)
    got = np.asarray(_REG.exec("batch_gather", jnp.asarray(p3),
                               jnp.asarray(i2)))
    want = np.stack([p3[b][i2[b]] for b in range(2)])
    np.testing.assert_allclose(got, want)


@validation.case("log_sigmoid")
def _check_log_sigmoid():
    x = np.random.RandomState(25).randn(8).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("log_sigmoid", jnp.asarray(x))),
        -np.log1p(np.exp(-x)), rtol=1e-3, atol=1e-5)  # chip-tolerant


@validation.case("cosine_similarity")
def _check_cos_sim():
    r = np.random.RandomState(26)
    a = r.randn(4, 8).astype(np.float32)
    b = r.randn(4, 8).astype(np.float32)
    want = (a * b).sum(-1) / (np.linalg.norm(a, axis=-1)
                              * np.linalg.norm(b, axis=-1))
    np.testing.assert_allclose(
        np.asarray(_REG.exec("cosine_similarity", jnp.asarray(a), jnp.asarray(b))),
        want, rtol=1e-5, atol=1e-6)


@validation.case("euclidean_distance")
def _check_euclid():
    a = np.asarray([[0.0, 0.0], [1.0, 1.0]], np.float32)
    b = np.asarray([[3.0, 4.0], [1.0, 1.0]], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("euclidean_distance", jnp.asarray(a), jnp.asarray(b))),
        [5.0, 0.0], rtol=1e-6)


@validation.case("manhattan_distance")
def _check_manhattan():
    a = np.asarray([[0.0, 0.0]], np.float32)
    b = np.asarray([[3.0, -4.0]], np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("manhattan_distance", jnp.asarray(a), jnp.asarray(b))),
        [7.0], rtol=1e-6)


@validation.case("hamming_distance")
def _check_hamming_dist():
    a = np.asarray([1, 2, 3, 4], np.int32)
    b = np.asarray([1, 0, 3, 0], np.int32)
    assert float(_REG.exec("hamming_distance", jnp.asarray(a),
                           jnp.asarray(b), axis=0)) == 2.0
