"""Threshold gradient compression — the Strom-2015 codec the reference uses
for asynchronous gradient sharing.

Reference: native ops encode_threshold / decode_threshold (+ encode_bitmap)
in libnd4j (ops/declarable/generic/compression/threshold.cpp [M]) driven by
DL4J's EncodedGradientsAccumulator + AdaptiveThresholdAlgorithm
(org/deeplearning4j/optimize/solvers/accumulation/**).

TPU-native disposition (SURVEY §3.5/§6.8): the *synchronous* ICI all-reduce
path doesn't need compression at all; this codec survives as an optional
DCN-crossing compressor and as capability parity. On TPU we keep the encoded
form DENSE-shaped (fixed-size index buffer) so shapes stay static under jit:
``encode_threshold`` returns (indices[int32, K], signs[int8, K], count) with K
a static capacity, plus the residual; entries beyond ``count`` are -1 padding.
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op


class ThresholdEncoded(NamedTuple):
    indices: jax.Array  # int32 [capacity], -1 padded
    signs: jax.Array    # int8 [capacity]
    count: jax.Array    # int32 scalar — number of valid entries
    threshold: jax.Array  # f32 scalar — the tau used


@op("encode_threshold")
def encode_threshold(grad, *, threshold: float, capacity: int) -> Tuple[ThresholdEncoded, jax.Array]:
    """Sparse-encode entries with |g| > tau as (index, sign); residual keeps the
    rest PLUS the sub-threshold remainder of encoded entries, exactly like the
    reference: decoded value is +/- tau, residual = g - decoded.

    Returns (encoded, residual). Static shapes: capacity bounds the number of
    encoded entries; overflow entries stay in the residual (matches the
    reference's behavior of bounding message size).
    """
    flat = grad.reshape(-1)
    tau = jnp.asarray(threshold, flat.dtype)
    mask = jnp.abs(flat) > tau
    # Rank entries: all above-threshold first, in index order (stable).
    order = jnp.argsort(~mask, stable=True)  # True(above) sorts first
    top = order[:capacity]
    valid = mask[top]
    count = jnp.sum(mask).astype(jnp.int32)
    kept = jnp.minimum(count, capacity)
    indices = jnp.where(valid, top.astype(jnp.int32), -1)
    signs = jnp.where(valid, jnp.sign(flat[top]), 0.0).astype(jnp.int8)
    decoded_vals = jnp.where(valid, jnp.sign(flat[top]) * tau, 0.0)
    residual = flat.at[jnp.where(valid, top, flat.shape[0] - 1)].add(
        jnp.where(valid, -decoded_vals, 0.0)
    )
    enc = ThresholdEncoded(indices=indices, signs=signs, count=kept,
                           threshold=tau.astype(jnp.float32))
    return enc, residual.reshape(grad.shape)


@op("decode_threshold")
def decode_threshold(encoded: ThresholdEncoded, *, shape) -> jax.Array:
    """Densify an encoded update: out[idx] += sign * tau."""
    size = 1
    for s in shape:
        size *= int(s)
    out = jnp.zeros((size,), jnp.float32)
    valid = encoded.indices >= 0
    safe_idx = jnp.where(valid, encoded.indices, 0)
    vals = jnp.where(valid, encoded.signs.astype(jnp.float32) * encoded.threshold, 0.0)
    out = out.at[safe_idx].add(vals)
    return out.reshape(shape)


@op("encode_bitmap")
def encode_bitmap(grad, *, threshold: float):
    """Bitmap variant (reference encode_bitmap): 2-bit code per entry
    {0: below, 1: +tau, 2: -tau}; here an int8 map + residual."""
    tau = jnp.asarray(threshold, grad.dtype)
    code = jnp.where(grad > tau, 1, jnp.where(grad < -tau, 2, 0)).astype(jnp.int8)
    decoded = jnp.where(code == 1, tau, jnp.where(code == 2, -tau, 0.0))
    residual = grad - decoded
    return code, residual


@op("decode_bitmap")
def decode_bitmap(code, *, threshold: float, dtype=jnp.float32):
    tau = jnp.asarray(threshold, dtype)
    return jnp.where(code == 1, tau, jnp.where(code == 2, -tau, 0.0)).astype(dtype)


class AdaptiveThreshold:
    """AdaptiveThresholdAlgorithm parity: adjusts tau toward a target sparsity.

    Reference keeps the last iteration's encoding ratio and multiplies/divides
    tau by a decay factor to chase a target fraction of encoded elements.
    Pure-python state, used at orchestration level.
    """

    def __init__(self, initial: float = 1e-3, target_sparsity: float = 1e-3,
                 decay: float = 1.2, min_threshold: float = 1e-6,
                 max_threshold: float = 1.0):
        self.threshold = float(initial)
        self.target = float(target_sparsity)
        self.decay = float(decay)
        self.min = float(min_threshold)
        self.max = float(max_threshold)

    def update(self, encoded_fraction: float) -> float:
        if encoded_fraction > self.target * 1.5:
            self.threshold = min(self.threshold * self.decay, self.max)
        elif encoded_fraction < self.target / 1.5:
            self.threshold = max(self.threshold / self.decay, self.min)
        return self.threshold
