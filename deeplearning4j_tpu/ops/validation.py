"""Per-op validation registry — the OpValidation ratchet (SURVEY §5.2).

Reference parity: ND4J's OpValidation framework
(nd4j/nd4j-backends/nd4j-tests/.../OpValidationSuite) tracks which declarable
ops have gradient/equality checks and FAILS the build for ops with none —
"coverage is asserted, not hoped for". Here every registered op must own at
least one validation case: a callable that executes the op and asserts
against an independent oracle (usually numpy). tests/test_op_validation.py
enforces the ratchet:

  * every name in the op registry has >= 1 case,
  * every case passes on the CPU backend,
  * (chip runs) table-driven cases double as CPU-vs-TPU consistency fodder.

Cases register via :func:`case` (decorator) or :func:`add_case`.
"""

from __future__ import annotations

from typing import Callable, Dict, List

_CASES: Dict[str, List[Callable[[], None]]] = {}


def case(op_name: str):
    """Decorator: register fn as a validation case for ``op_name``."""

    def deco(fn: Callable[[], None]) -> Callable[[], None]:
        _CASES.setdefault(op_name, []).append(fn)
        return fn

    return deco


def add_case(op_name: str, fn: Callable[[], None]) -> None:
    _CASES.setdefault(op_name, []).append(fn)


def cases() -> Dict[str, List[Callable[[], None]]]:
    """All registered validation cases (name -> list of runnables)."""
    return _CASES


def uncovered_ops() -> List[str]:
    """Registered ops with no validation case — the ratchet's red list."""
    from deeplearning4j_tpu.ops.registry import registry

    return [n for n in registry().names() if not _CASES.get(n)]
