"""Fused updater step — one elementwise kernel for the whole optimizer math.

The reference applies updaters as a separate pass over the flattened
gradient view (``BaseMultiLayerUpdater.update``); our train steps apply the
same math leaf-wise with jnp ops, which XLA usually fuses — but each leaf's
chain still reads param/grad/state from HBM and writes param/state back as
separate fusions, and under bf16 policies XLA splits the chain at dtype
boundaries. ``fused_updater_step`` makes the one-HBM-pass contract explicit:

    new_param, *new_state = fused_updater_step(param, grad, lr, step,
                                               *state, kind="Adam", ...)

* the **generic impl** runs the exact ``nn/updater.py`` math (it calls the
  same ``Updater.apply``), so trajectories are bit-identical to the unfused
  step everywhere — the op is safe on the default train path.
* the **Pallas TPU helper** flattens the leaf to (rows, 128) lanes and runs
  the identical ``apply`` math inside one kernel: param, grad and every
  state buffer are read once, new param + state written once. All 11
  updater kinds (Sgd…AmsGrad) share this one kernel — the per-kind math is
  traced into the kernel body from the same dataclasses.
* dispatch consults the tuning table (``fused_updater_step.min_size``):
  below the measured crossover the generic XLA chain wins (kernel launch
  overhead), above it the fused kernel does — ``ops/tuning.py``.

``Updater.apply_fused`` (nn/updater.py) is the train-step entry: MLN/
ComputationGraph (``apply_layer_updates``) and the SameDiff training
session route through it, with ``DL4J_TPU_FUSED_UPDATER=0`` as the opt-out.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from deeplearning4j_tpu.ops.registry import op

LANES = 128


@functools.lru_cache(maxsize=None)
def _updater_and_keys(kind: str, hyper_items: Tuple[Tuple[str, object], ...]):
    """Resolve (updater instance, canonical state-key order) for a static
    (kind, hyperparams) pair. Lazy import: nn.updater must not load during
    ops package init (layer modules import ops back)."""
    from deeplearning4j_tpu.nn.updater import UPDATERS

    if kind not in UPDATERS:
        raise ValueError(f"fused_updater_step: unknown updater kind '{kind}'"
                         f"; valid: {sorted(UPDATERS)}")
    upd = UPDATERS[kind](**dict(hyper_items))
    keys = tuple(sorted(upd.init_state(jnp.zeros((), jnp.float32))))
    return upd, keys


@op("fused_updater_step")
def fused_updater_step(param, grad, lr, step, *state, kind: str = "Sgd",
                       **hyper):
    """One optimizer step for one leaf: ``(new_param, *new_state)``.

    ``state`` rides positionally in SORTED-key order (Adam: m, v); ``kind``
    names an ``nn/updater.py`` updater class and ``hyper`` its constructor
    fields (``learning_rate`` excluded — ``lr`` is the already-scheduled
    traced scalar). The generic impl IS the reference math: it calls the
    same ``Updater.apply`` the unfused train step calls, then applies the
    ``params -= update`` convention."""
    upd, keys = _updater_and_keys(kind, tuple(sorted(hyper.items())))
    if len(state) != len(keys):
        raise ValueError(
            f"fused_updater_step[{kind}]: expected {len(keys)} state "
            f"arrays {list(keys)}, got {len(state)}")
    u, new = upd.apply(grad, dict(zip(keys, state)), lr, step)
    return (param - u,) + tuple(new[k] for k in keys)


# ---------------------------------------------------------------------------
# Pallas TPU helper
# ---------------------------------------------------------------------------


def _kernel(lr_ref, step_ref, p_ref, g_ref, *refs, apply_fn, keys):
    """One (block_rows, 128) tile: the full updater chain, traced from the
    same dataclass ``apply`` as the generic impl — the kernel cannot drift
    from the reference math because it IS the reference math. Stores cast
    back to the ref dtype: the f32 lr/step scalars promote the chain, and
    an un-cast f32 store into a bf16 param ref is a Mosaic trace error."""
    n = len(keys)
    state_refs, out_refs = refs[:n], refs[n:]
    lr = lr_ref[0, 0]
    step = step_ref[0, 0]
    st = {k: r[...] for k, r in zip(keys, state_refs)}
    u, new = apply_fn(g_ref[...], st, lr, step)
    out_refs[0][...] = (p_ref[...] - u).astype(out_refs[0].dtype)
    for k, r in zip(keys, out_refs[1:]):
        r[...] = new[k].astype(r.dtype)


def _rows_for(size: int, block_rows: int) -> Tuple[int, int]:
    rows = -(-size // LANES)
    rows = -(-rows // block_rows) * block_rows
    return rows, rows * LANES


def fused_updater_helper(param, grad, lr, step, *state, kind: str = "Sgd",
                         block_rows: int = 0, interpret=None, **hyper):
    """Pallas forward for :func:`fused_updater_step` — same contract.

    The leaf is flattened and padded to (rows, 128) full-lane tiles (pad
    cells compute garbage that is sliced off; every updater's denominators
    carry an eps, so pads cannot NaN). One grid dimension walks row
    blocks; param/grad/state stream through VMEM once."""
    if interpret is None:
        from deeplearning4j_tpu.ops.registry import current_platform

        interpret = current_platform() != "tpu"
    upd, keys = _updater_and_keys(kind, tuple(sorted(hyper.items())))
    if len(state) != len(keys):
        raise ValueError(
            f"fused_updater_step[{kind}]: expected {len(keys)} state "
            f"arrays {list(keys)}, got {len(state)}")
    if not block_rows:
        from deeplearning4j_tpu.ops import tuning

        block_rows = int(tuning.tuned("fused_updater_step", "block_rows",
                                      256))
    shape, size = param.shape, param.size
    rows, padded = _rows_for(size, block_rows)

    def to_tile(a):
        flat = a.reshape(-1)
        if padded != size:
            flat = jnp.pad(flat, (0, padded - size))
        return flat.reshape(rows, LANES)

    tiles = [to_tile(a) for a in (param, grad) + tuple(state)]
    scalar = lambda v: jnp.asarray(v, jnp.float32).reshape(1, 1)
    grid = (rows // block_rows,)
    tile_spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0))
    scalar_spec = pl.BlockSpec((1, 1), lambda i: (0, 0))
    n_out = 1 + len(keys)
    outs = pl.pallas_call(
        functools.partial(_kernel, apply_fn=upd.apply, keys=keys),
        out_shape=[jax.ShapeDtypeStruct((rows, LANES), t.dtype)
                   for t in tiles[:1] + tiles[2:]],
        grid=grid,
        in_specs=[scalar_spec, scalar_spec] + [tile_spec] * len(tiles),
        out_specs=[tile_spec] * n_out,
        interpret=interpret,
    )(scalar(lr), scalar(step), *tiles)
    if n_out == 1:
        outs = [outs] if not isinstance(outs, (list, tuple)) else outs
    return tuple(o.reshape(-1)[:size].reshape(shape) for o in outs)


def _usable(param, grad, lr, step, *state, **kw):
    """PlatformHelper::isUsable: floating same-shape leaves, and a leaf
    large enough that one fused HBM pass beats the XLA chain (measured
    ``min_size`` crossover from the tuning table)."""
    shape = getattr(param, "shape", None)
    dt = getattr(param, "dtype", None)
    if shape is None or dt is None or not jnp.issubdtype(dt, jnp.floating):
        return False
    for a in (grad,) + state:
        if getattr(a, "shape", None) != shape:
            return False
    try:
        _, keys = _updater_and_keys(
            kw.get("kind", "Sgd"),
            tuple(sorted((k, v) for k, v in kw.items()
                         if k not in ("kind", "block_rows", "interpret"))))
    except (ValueError, TypeError):
        return False
    if len(state) != len(keys):
        return False
    from deeplearning4j_tpu.ops import tuning

    return param.size >= int(tuning.tuned("fused_updater_step", "min_size",
                                          65536))


def _check_fused_updater_step():
    """Validation case (ops.validation ratchet): generic vs the literal
    nn/updater.py math, and the Pallas interpret kernel vs both, for a
    stateful kind (Adam) and a stateless one (Sgd)."""
    import numpy as np

    from deeplearning4j_tpu.nn.updater import Adam, Sgd

    r = np.random.RandomState(3)
    p = jnp.asarray(r.randn(37).astype(np.float32))  # ragged: exercises pad
    g = jnp.asarray(r.randn(37).astype(np.float32))
    lr, step = jnp.float32(1e-2), jnp.float32(4.0)

    adam = Adam(beta1=0.85)
    st = {"m": jnp.asarray(r.randn(37).astype(np.float32)),
          "v": jnp.asarray(np.abs(r.randn(37)).astype(np.float32))}
    u, new = adam.apply(g, st, lr, step)
    want = (np.asarray(p - u), np.asarray(new["m"]), np.asarray(new["v"]))
    got = fused_updater_step.fn(p, g, lr, step, st["m"], st["v"],
                                kind="Adam", beta1=0.85)
    got_pl = fused_updater_helper(p, g, lr, step, st["m"], st["v"],
                                  kind="Adam", beta1=0.85, block_rows=8,
                                  interpret=True)
    for w, a, b in zip(want, got, got_pl):
        np.testing.assert_allclose(np.asarray(a), w, rtol=1e-6, atol=1e-7)
        np.testing.assert_allclose(np.asarray(b), w, rtol=1e-6, atol=1e-7)

    u, _ = Sgd(learning_rate=0.1).apply(g, {}, lr, step)
    got = fused_updater_step.fn(p, g, lr, step, kind="Sgd")
    got_pl = fused_updater_helper(p, g, lr, step, kind="Sgd", block_rows=8,
                                  interpret=True)
    np.testing.assert_allclose(np.asarray(got[0]), np.asarray(p - u),
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(np.asarray(got_pl[0]), np.asarray(p - u),
                               rtol=1e-6, atol=1e-7)


def register_platform_fused_updater() -> None:
    """Install the Pallas kernel as the TPU platform override for
    fused_updater_step (cuDNN PlatformHelper pattern)."""
    from deeplearning4j_tpu.ops import validation as _validation
    from deeplearning4j_tpu.ops.registry import registry

    reg = registry()
    desc = reg.get("fused_updater_step")
    if "tpu" not in desc.platform_impls:
        reg.register_platform("fused_updater_step", "tpu",
                              fused_updater_helper, _usable)
        _validation.add_case("fused_updater_step", _check_fused_updater_step)
