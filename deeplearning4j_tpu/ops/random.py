"""RNG + distribution ops.

Reference: ND4J org/nd4j/linalg/api/rng (Nd4jRandom), native Philox-style
generator (libnd4j helpers/RandomLauncher.h), distribution ops
(random/uniform, normal, bernoulli, truncated_normal, dropout RNG).

TPU-native: JAX's counter-based threefry/rbg PRNG is the Philox analog —
explicit splittable keys instead of a stateful global generator. For API
parity with Nd4j.getRandom().setSeed(...) we keep a thin stateful wrapper
that hands out split keys; everything inside jit takes explicit keys.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import op


class RandomSource:
    """Stateful key dispenser (Nd4j.getRandom() analog, trace-unsafe by design:
    use only at orchestration level, never inside jit)."""

    def __init__(self, seed: int = 0):
        # LAZY: creating a key initializes the XLA backend, and importing
        # the package must not do that (jax.distributed.initialize has to
        # run first in multi-process jobs — SURVEY §4.4 bootstrap order)
        self._seed = seed
        self._key = None

    def _ensure(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)

    def set_seed(self, seed: int) -> None:
        self._seed = seed
        self._key = None  # stays lazy: no backend init before jax.distributed

    def next_key(self):
        self._ensure()
        self._key, sub = jax.random.split(self._key)
        return sub

    def split(self, n: int):
        self._ensure()
        self._key, *subs = jax.random.split(self._key, n + 1)
        return subs


_DEFAULT = RandomSource(123)


def default_rng() -> RandomSource:
    return _DEFAULT


@op("random_uniform")
def random_uniform(key, *, shape: Sequence[int], minval: float = 0.0, maxval: float = 1.0,
                   dtype=jnp.float32):
    return jax.random.uniform(key, tuple(shape), dtype, minval, maxval)


@op("random_normal")
def random_normal(key, *, shape: Sequence[int], mean: float = 0.0, stddev: float = 1.0,
                  dtype=jnp.float32):
    return mean + stddev * jax.random.normal(key, tuple(shape), dtype)


@op("random_truncated_normal")
def random_truncated_normal(key, *, shape: Sequence[int], mean: float = 0.0,
                            stddev: float = 1.0, dtype=jnp.float32):
    return mean + stddev * jax.random.truncated_normal(key, -2.0, 2.0, tuple(shape), dtype)


@op("random_bernoulli")
def random_bernoulli(key, *, shape: Sequence[int], prob: float = 0.5, dtype=jnp.float32):
    return jax.random.bernoulli(key, prob, tuple(shape)).astype(dtype)


@op("random_gamma")
def random_gamma(key, *, shape: Sequence[int], alpha: float = 1.0, beta: float = 1.0,
                 dtype=jnp.float32):
    return jax.random.gamma(key, alpha, tuple(shape), dtype) / beta


@op("random_exponential")
def random_exponential(key, *, shape: Sequence[int], rate: float = 1.0, dtype=jnp.float32):
    return jax.random.exponential(key, tuple(shape), dtype) / rate
