"""Linear-algebra ops.

Reference parity: libnd4j linalg DynamicCustomOps
(include/ops/declarable/generic/linalg/** — cholesky.cpp, qr.cpp, svd.cpp,
solve.cpp, triangular_solve.cpp, lstsq.cpp, matrix_inverse.cpp,
matrix_determinant.cpp, lup.cpp, cross.cpp, tensormmul.cpp; Java surface
org.nd4j.linalg.api.ops.custom.*). Bodies lower to jnp.linalg /
jax.scipy.linalg, which XLA routes to its native decomposition custom-calls
on TPU.

Every op registers a numpy.linalg-oracle validation case.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from deeplearning4j_tpu.ops.registry import registry
from deeplearning4j_tpu.ops import validation

_REG = registry()


def _op(name):
    def deco(fn):
        _REG.register(name, fn, doc=fn.__doc__ or "")
        return fn

    return deco


def _spd(r, n):
    a = r.randn(n, n).astype(np.float32)
    return a @ a.T + n * np.eye(n, dtype=np.float32)


@_op("cholesky")
def cholesky(x):
    """lower-triangular Cholesky factor (generic/linalg/cholesky.cpp)."""
    return jnp.linalg.cholesky(x)


@_op("qr")
def qr(x, *, full_matrices: bool = False):
    """QR decomposition → (Q, R) (generic/linalg/qr.cpp)."""
    return jnp.linalg.qr(x, mode="complete" if full_matrices else "reduced")


@_op("svd")
def svd(x, *, full_matrices: bool = False, compute_uv: bool = True):
    """singular value decomposition (generic/linalg/svd.cpp)."""
    return jnp.linalg.svd(x, full_matrices=full_matrices,
                          compute_uv=compute_uv)


@_op("solve")
def solve(a, b):
    """linear system solve Ax=b (generic/linalg/solve.cpp)."""
    return jnp.linalg.solve(a, b)


@_op("triangular_solve")
def triangular_solve(a, b, *, lower: bool = True, adjoint: bool = False):
    """triangular solve (generic/linalg/triangular_solve.cpp)."""
    return jax.scipy.linalg.solve_triangular(a, b, lower=lower,
                                             trans=1 if adjoint else 0)


@_op("lstsq")
def lstsq(a, b):
    """least-squares solution (generic/linalg/lstsq.cpp)."""
    return jnp.linalg.lstsq(a, b)[0]


@_op("matrix_inverse")
def matrix_inverse(x):
    """matrix inverse (generic/linalg/matrix_inverse.cpp)."""
    return jnp.linalg.inv(x)


@_op("matrix_determinant")
def matrix_determinant(x):
    """determinant (generic/linalg/matrixDeterminant.cpp)."""
    return jnp.linalg.det(x)


@_op("log_matrix_determinant")
def log_matrix_determinant(x):
    """(sign, log|det|) (generic/linalg/logMatrixDeterminant analog)."""
    return jnp.linalg.slogdet(x)


@_op("lu")
def lu(x):
    """LU with partial pivoting → (lu_packed, pivots) (generic/linalg/lup.cpp)."""
    lu_, piv = jax.scipy.linalg.lu_factor(x)
    return lu_, piv


@_op("cross")
def cross(a, b):
    """3-vector cross product (generic/linalg/cross.cpp)."""
    return jnp.cross(a, b)


@_op("tensormmul")
def tensormmul(a, b, *, axes_a, axes_b):
    """tensordot (generic/linalg/tensormmul.cpp)."""
    return jnp.tensordot(a, b, axes=(tuple(axes_a), tuple(axes_b)))


@_op("matrix_set_diag")
def matrix_set_diag(x, diag_vals):
    """replace the main diagonal (generic/parity_ops/matrix_set_diag.cpp)."""
    n = min(x.shape[-2], x.shape[-1])
    idx = jnp.arange(n)
    return x.at[..., idx, idx].set(diag_vals[..., :n])


# --------------------------------------------------------------------------


@validation.case("cholesky")
def _check_chol():
    a = _spd(np.random.RandomState(0), 4)
    got = np.asarray(_REG.exec("cholesky", jnp.asarray(a)))
    np.testing.assert_allclose(got @ got.T, a, rtol=1e-4, atol=1e-4)
    assert np.allclose(got, np.tril(got))


@validation.case("qr")
def _check_qr():
    a = np.random.RandomState(1).randn(5, 3).astype(np.float32)
    q, r = _REG.exec("qr", jnp.asarray(a))
    np.testing.assert_allclose(np.asarray(q) @ np.asarray(r), a,
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(q).T @ np.asarray(q), np.eye(3),
                               rtol=1e-4, atol=1e-4)


@validation.case("svd")
def _check_svd():
    a = np.random.RandomState(2).randn(4, 3).astype(np.float32)
    u, s, vt = _REG.exec("svd", jnp.asarray(a))
    rec = np.asarray(u) @ np.diag(np.asarray(s)) @ np.asarray(vt)
    np.testing.assert_allclose(rec, a, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(np.asarray(s),
                               np.linalg.svd(a, compute_uv=False),
                               rtol=1e-4, atol=1e-5)


@validation.case("solve")
def _check_solve():
    r = np.random.RandomState(3)
    a = _spd(r, 4)
    b = r.randn(4, 2).astype(np.float32)
    got = np.asarray(_REG.exec("solve", jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, np.linalg.solve(a, b), rtol=1e-3, atol=1e-3)


@validation.case("triangular_solve")
def _check_tri_solve():
    r = np.random.RandomState(4)
    a = np.tril(r.randn(4, 4).astype(np.float32)) + 4 * np.eye(4, dtype=np.float32)
    b = r.randn(4, 2).astype(np.float32)
    got = np.asarray(_REG.exec("triangular_solve", jnp.asarray(a),
                               jnp.asarray(b), lower=True))
    np.testing.assert_allclose(a @ got, b, rtol=1e-4, atol=1e-4)


@validation.case("lstsq")
def _check_lstsq():
    r = np.random.RandomState(5)
    a = r.randn(6, 3).astype(np.float32)
    b = r.randn(6).astype(np.float32)
    got = np.asarray(_REG.exec("lstsq", jnp.asarray(a), jnp.asarray(b)))
    want = np.linalg.lstsq(a, b, rcond=None)[0]
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-3)


@validation.case("matrix_inverse")
def _check_inv():
    a = _spd(np.random.RandomState(6), 4)
    got = np.asarray(_REG.exec("matrix_inverse", jnp.asarray(a)))
    np.testing.assert_allclose(a @ got, np.eye(4), rtol=1e-3, atol=1e-3)


@validation.case("matrix_determinant")
def _check_det():
    a = _spd(np.random.RandomState(7), 3)
    got = float(_REG.exec("matrix_determinant", jnp.asarray(a)))
    np.testing.assert_allclose(got, np.linalg.det(a), rtol=1e-3)


@validation.case("log_matrix_determinant")
def _check_slogdet():
    a = _spd(np.random.RandomState(8), 3)
    sign, logdet = _REG.exec("log_matrix_determinant", jnp.asarray(a))
    s, l = np.linalg.slogdet(a)
    np.testing.assert_allclose(float(sign), s, rtol=1e-5)
    np.testing.assert_allclose(float(logdet), l, rtol=1e-4)


@validation.case("lu")
def _check_lu():
    import scipy.linalg as sla

    a = _spd(np.random.RandomState(9), 4)
    lu_, piv = _REG.exec("lu", jnp.asarray(a))
    want_lu, want_piv = sla.lu_factor(a)
    np.testing.assert_allclose(np.asarray(lu_), want_lu, rtol=1e-3, atol=1e-3)


@validation.case("cross")
def _check_cross():
    r = np.random.RandomState(10)
    a = r.randn(3).astype(np.float32)
    b = r.randn(3).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(_REG.exec("cross", jnp.asarray(a), jnp.asarray(b))),
        np.cross(a, b), rtol=1e-5, atol=1e-6)


@validation.case("tensormmul")
def _check_tensormmul():
    r = np.random.RandomState(11)
    a = r.randn(2, 3, 4).astype(np.float32)
    b = r.randn(4, 3, 5).astype(np.float32)
    got = np.asarray(_REG.exec("tensormmul", jnp.asarray(a), jnp.asarray(b),
                               axes_a=[1, 2], axes_b=[1, 0]))
    np.testing.assert_allclose(got, np.tensordot(a, b, axes=([1, 2], [1, 0])),
                               rtol=1e-4, atol=1e-4)


@validation.case("matrix_set_diag")
def _check_set_diag():
    x = np.zeros((3, 3), np.float32)
    got = np.asarray(_REG.exec("matrix_set_diag", jnp.asarray(x),
                               jnp.asarray([1.0, 2.0, 3.0], dtype=jnp.float32)))
    np.testing.assert_array_equal(got, np.diag([1.0, 2.0, 3.0]))


@_op("einsum")
def einsum(*operands, equation: str):
    """General tensor contraction (TF/ONNX Einsum parity) — XLA lowers
    straight onto dot_general/MXU."""
    return jnp.einsum(equation, *operands)


@validation.case("einsum")
def _check_einsum():
    r = np.random.RandomState(0)
    a = r.randn(3, 4).astype(np.float32)
    b = r.randn(4, 5).astype(np.float32)
    got = np.asarray(einsum(jnp.asarray(a), jnp.asarray(b),
                            equation="ij,jk->ik"))
    np.testing.assert_allclose(got, a @ b, rtol=1e-5, atol=1e-5)
    c = r.randn(2, 3, 4).astype(np.float32)
    got2 = np.asarray(einsum(jnp.asarray(c), equation="bij->bji"))
    np.testing.assert_allclose(got2, c.transpose(0, 2, 1))
