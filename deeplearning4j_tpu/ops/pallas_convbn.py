"""Pallas TPU prototype: fused BN-apply → 1×1-conv (matmul) → BN-stats.

The ResNet perf analysis (docs/PERF_ANALYSIS.md) shows the training step is
HBM-bound on BatchNorm activation traffic: per conv+BN pair XLA emits three
separate full-activation passes (conv write, stats reduce read, normalize
read+write) because TPU convolutions cannot take fused operands. A 1×1
convolution is a plain matmul over (N·H·W, C) — which Pallas *can* fuse:

    z = relu(x · scale + shift) @ W        # prologue: previous BN's affine
    csum, csq = Σ(z − s), Σ(z − s)²        # epilogue: this BN's shifted stats

reads the raw previous-conv output ONCE and writes z ONCE, eliminating the
standalone normalize pass (read+write) and the stats pass (read) entirely —
a 3×-read/2×-write chain becomes 1×/1×.

Stats use the same running-mean-shifted one-pass moments as
``ops/nn_ops._bn_fwd_math`` (the unshifted E[x²]−E[x]² form is
catastrophic-cancellation-prone; shifting by the running mean keeps it
stable). Per-(m-block, n) partial sums are emitted and tree-reduced by the
caller, so f32 accumulation error stays at the XLA reduce level.

This is the round-5 committed prototype for the "conv+BN epilogue fusion"
lever: `tools/bench_convbn_fusion.py` measures time and XLA cost-analysis
bytes for this kernel vs the unfused XLA chain on real bottleneck shapes.

Reference role: cuDNN's fused ConvScaleBiasActivation / BNStatsFinalize
kernel pairs (platform helpers, SURVEY §3.1); re-designed as a Pallas MXU
matmul with prologue/epilogue fusion rather than a translated kernel.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _pick_block(size: int, candidates=(512, 384, 256, 128)) -> int:
    for c in candidates:
        if size % c == 0:
            return c
    return size


def _kernel(x_ref, sc_ref, sh_ref, w_ref, stat_shift_ref,
            z_ref, csum_ref, csq_ref, acc_ref, *, n_k: int, relu: bool,
            fuse_prologue: bool):
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    x = x_ref[0]                                   # (bm, bk) bf16
    if fuse_prologue:
        xf = x.astype(jnp.float32)
        y = xf * sc_ref[0] + sh_ref[0]             # previous BN affine
        if relu:
            y = jnp.maximum(y, 0.0)
        y = y.astype(x.dtype)
    else:
        y = x
    acc_ref[:] += jnp.dot(y, w_ref[0], preferred_element_type=jnp.float32)

    @pl.when(k == n_k - 1)
    def _():
        z = acc_ref[:]                             # (bm, bn) f32
        z_ref[0] = z.astype(z_ref.dtype)
        c = z - stat_shift_ref[0]                  # shifted moments
        csum_ref[0, 0] = jnp.sum(c, axis=0)
        csq_ref[0, 0] = jnp.sum(c * c, axis=0)


def fused_bn_matmul_stats(x, scale, shift, w, stat_shift, *, relu: bool = True,
                          fuse_prologue: bool = True, block_m: int = 0,
                          block_n: int = 0, block_k: int = 0,
                          interpret: bool = False):
    """relu(x·scale+shift) @ w with shifted-stats epilogue, one HBM pass.

    x: (M, K) activations (bf16; raw previous-conv output when
    ``fuse_prologue``). scale/shift: (K,) f32 — the previous BN's folded
    affine (γ·inv, β−μ·γ·inv). w: (K, N). stat_shift: (N,) f32 — this BN's
    running mean. Returns (z (M,N), mean (N,), var (N,)) where mean/var are
    this conv's biased batch statistics, ready for the BN running-buffer
    update and normalize scale.
    """
    m, k_dim = x.shape
    n = w.shape[1]
    from deeplearning4j_tpu.ops import tuning

    bucket = tuning.bucket_mkn(m, k_dim, n)
    bm = block_m or tuning.tuned_block("fused_bn_matmul_stats", "block_m",
                                       m, bucket, _pick_block)
    bn = block_n or tuning.tuned_block(
        "fused_bn_matmul_stats", "block_n", n, bucket,
        lambda s: _pick_block(s, (256, 128, 64)))
    bk = block_k or tuning.tuned_block(
        "fused_bn_matmul_stats", "block_k", k_dim, bucket,
        lambda s: _pick_block(s, (512, 256, 128, 64)))
    if m % bm or n % bn or k_dim % bk:
        raise ValueError(f"shape ({m},{k_dim})x({k_dim},{n}) not divisible by "
                         f"blocks ({bm},{bk},{bn})")
    grid = (m // bm, n // bn, k_dim // bk)
    f32 = jnp.float32
    kern = functools.partial(_kernel, n_k=grid[2], relu=relu,
                             fuse_prologue=fuse_prologue)
    z, csum, csq = pl.pallas_call(
        kern,
        out_shape=[
            jax.ShapeDtypeStruct((1, m, n), x.dtype),
            jax.ShapeDtypeStruct((grid[0], 1, n), f32),
            jax.ShapeDtypeStruct((grid[0], 1, n), f32),
        ],
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, bm, bk), lambda i, j, k: (0, i, k)),
            pl.BlockSpec((1, bk), lambda i, j, k: (0, k)),
            pl.BlockSpec((1, bk), lambda i, j, k: (0, k)),
            pl.BlockSpec((1, bk, bn), lambda i, j, k: (0, k, j)),
            pl.BlockSpec((1, bn), lambda i, j, k: (0, j)),
        ],
        out_specs=[
            pl.BlockSpec((1, bm, bn), lambda i, j, k: (0, i, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j, k: (i, 0, j)),
            pl.BlockSpec((1, 1, bn), lambda i, j, k: (i, 0, j)),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), f32)],
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(x[None], scale.astype(f32)[None], shift.astype(f32)[None], w[None],
      stat_shift.astype(f32)[None])
    sf = stat_shift.astype(f32)
    m1 = jnp.sum(csum[:, 0], axis=0) / m
    m2 = jnp.sum(csq[:, 0], axis=0) / m
    mean = m1 + sf
    var = jnp.maximum(m2 - jnp.square(m1), 0.0)
    return z[0], mean, var


def _pallas_ok(x, w) -> bool:
    """Use the Pallas kernel only where it wins: TPU backend, bf16
    activations, block-divisible shapes. Everywhere else (CPU mesh, f32
    policy, ragged shapes) the reference XLA chain runs — same math."""
    if os.environ.get("DL4J_TPU_DISABLE_PALLAS_CONVBN") == "1":
        return False
    try:
        if jax.default_backend() != "tpu":
            return False
    except Exception:  # pragma: no cover
        return False
    m, k = x.shape
    n = w.shape[1]
    return (x.dtype == jnp.bfloat16 and m % 128 == 0 and k % 64 == 0
            and n % 64 == 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def fused_matmul_bn(x, a, b, w, stat_shift, prologue: bool, relu: bool):
    """Differentiable fused [affine+relu] → matmul → shifted-BN-stats.

    Forward runs the one-HBM-pass Pallas kernel on TPU (reference chain
    elsewhere); backward is the hand-derived two-matmul VJP below — the
    same passes XLA emits for the unfused chain, with no forward recompute.
    ALL THREE outputs (z, mean, var) are differentiable: mean/var feed the
    consumer's normalize affine, so their cotangents carry the batch-stats
    term of standard BN training (reference BatchNormalization backprop).
    ``stat_shift`` (the running mean) only stabilizes the one-pass moments
    and is non-differentiable, exactly like ``_bn_core``.
    """
    z, mean, var = _fused_fwd_dispatch(x, a, b, w, stat_shift, prologue, relu)
    return z, mean, var


def _fused_fwd_dispatch(x, a, b, w, stat_shift, prologue, relu):
    if _pallas_ok(x, w):
        return fused_bn_matmul_stats(x, a, b, w, stat_shift, relu=relu,
                                     fuse_prologue=prologue)
    return reference_bn_matmul_stats(x, a, b, w, stat_shift, relu=relu,
                                     fuse_prologue=prologue)


def _fused_fwd(x, a, b, w, stat_shift, prologue, relu):
    z, mean, var = _fused_fwd_dispatch(x, a, b, w, stat_shift, prologue, relu)
    return (z, mean, var), (x, a, b, w, z, mean)


def _fused_bwd(prologue, relu, res, cts):
    x, a, b, w, z, mean = res
    dz, dmean, dvar = cts
    f32 = jnp.float32
    m = x.shape[0]
    zf = z.astype(f32)
    # fold the stats cotangents into dz: ∂mean/∂z = 1/M,
    # ∂var/∂z = 2(z − mean)/M per column
    dz_eff = dz.astype(f32)
    if dmean is not None:
        dz_eff = dz_eff + dmean / m
    if dvar is not None:
        dz_eff = dz_eff + dvar * (2.0 / m) * (zf - mean)
    if prologue:
        u = x.astype(f32) * a.astype(f32) + b.astype(f32)
        y = jnp.maximum(u, 0.0) if relu else u
        yl = y.astype(x.dtype)
    else:
        yl = x
    dzl = dz_eff.astype(x.dtype)
    dw = jnp.dot(yl.T, dzl, preferred_element_type=f32).astype(w.dtype)
    dy = jnp.dot(dzl, w.T, preferred_element_type=f32)
    if prologue:
        du = jnp.where(u > 0, dy, 0.0) if relu else dy
        da = jnp.sum(du * x.astype(f32), axis=0).astype(a.dtype)
        db = jnp.sum(du, axis=0).astype(b.dtype)
        dx = (du * a.astype(f32)).astype(x.dtype)
    else:
        dx = dy.astype(x.dtype)
        da = jnp.zeros_like(a)
        db = jnp.zeros_like(b)
    # stat_shift is the running mean — non-diff (running buffers are
    # excluded from gradients, reference semantics)
    return dx, da, db, dw, None


fused_matmul_bn.defvjp(_fused_fwd, _fused_bwd)


def reference_bn_matmul_stats(x, scale, shift, w, stat_shift, *,
                              relu: bool = True, fuse_prologue: bool = True,
                              materialize: bool = False):
    """The same math as XLA would run it unfused (the control arm).

    ``materialize=True`` inserts optimization barriers after the affine pass
    and after the matmul — modelling the real full-model behavior, where the
    normalize output and the conv output are HBM-materialized tensors
    (convolutions cannot take fused operands on TPU, and the conv output is
    consumed by more than one downstream pass). Without the barriers XLA
    would fuse this microbenchmark more aggressively than it can fuse the
    actual model, understating the unfused cost.
    """
    f32 = jnp.float32
    if fuse_prologue:
        y = x.astype(f32) * scale.astype(f32) + shift.astype(f32)
        if relu:
            y = jnp.maximum(y, 0.0)
        y = y.astype(x.dtype)
    else:
        y = x
    if materialize:
        y = jax.lax.optimization_barrier(y)
    z = jnp.dot(y, w, preferred_element_type=f32).astype(x.dtype)
    if materialize:
        z = jax.lax.optimization_barrier(z)
    sf = stat_shift.astype(f32)
    c = z.astype(f32) - sf
    m1 = jnp.mean(c, axis=0)
    m2 = jnp.mean(c * c, axis=0)
    mean = m1 + sf
    var = jnp.maximum(m2 - jnp.square(m1), 0.0)
    return z, mean, var
