"""Native (C++) host-side ops consumed via ctypes (libnd4j's surviving role)."""

from deeplearning4j_tpu.native_ops.threshold import (
    threshold_encode,
    threshold_decode,
    bitmap_encode,
    bitmap_decode,
    native_available,
)
