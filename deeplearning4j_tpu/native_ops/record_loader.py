"""ctypes bindings for the native record loader (native/record_loader.cpp).

Reference parity: the reference's record readers bottom out in native
loaders (JavaCPP wrappers); here CSVRecordReader's all-numeric fast path
and the IDX (MNIST/EMNIST) readers delegate to C++ when the shared lib is
available, with a transparent numpy fallback otherwise.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Tuple

import numpy as np

from deeplearning4j_tpu.native_ops.threshold import _get_lib


def _loader_lib() -> Optional[ctypes.CDLL]:
    lib = _get_lib()
    if lib is None:
        return None
    if not getattr(lib, "_record_loader_bound", False):
        try:
            lib.csv_parse_floats.restype = ctypes.c_int64
            lib.csv_parse_floats.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_char,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_float)]
            lib.idx_parse.restype = ctypes.c_int64
            lib.idx_parse.argtypes = [
                ctypes.POINTER(ctypes.c_ubyte), ctypes.c_int64, ctypes.c_int,
                ctypes.POINTER(ctypes.c_float), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int)]
            lib._record_loader_bound = True
        except AttributeError:
            return None  # stale .so without the loader symbols
    return lib


def native_loader_available() -> bool:
    return _loader_lib() is not None


def csv_to_float_matrix(text: str, cols: int, *, delimiter: str = ",",
                        skip_rows: int = 0,
                        max_rows: Optional[int] = None) -> np.ndarray:
    """One-pass CSV → (rows, cols) float32; non-numeric/empty cells are NaN.
    Raises ValueError on ragged rows (same contract as the Python path)."""
    data = text.encode()
    cap = max_rows if max_rows is not None else \
        text.count("\n") + text.count("\r") + 1
    lib = _loader_lib()
    if lib is not None:
        out = np.empty((cap, cols), np.float32)
        n = lib.csv_parse_floats(
            data, len(data), delimiter.encode(), skip_rows, cols, cap,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if n < 0:
            raise ValueError("ragged CSV: a row does not have "
                             f"{cols} fields")
        return out[:n]
    # numpy fallback — same semantics
    rows = []
    for i, line in enumerate(text.splitlines()):
        if i < skip_rows or not line.strip():
            continue
        parts = line.split(delimiter)
        if len(parts) != cols:
            raise ValueError(f"ragged CSV: a row does not have {cols} fields")
        vals = []
        for p in parts:
            # same accepted syntax as the native parser: plain
            # decimal/scientific (no hex, no underscore separators)
            if "_" in p or "x" in p.lower():
                vals.append(float("nan"))
                continue
            try:
                vals.append(float(p))
            except ValueError:
                vals.append(float("nan"))
        rows.append(vals)
        if max_rows is not None and len(rows) >= max_rows:
            break
    return np.asarray(rows, np.float32).reshape(-1, cols)


def idx_to_array(buf: bytes, *, scale: bool = True) -> np.ndarray:
    """IDX ubyte container → float32 array (optionally scaled to [0,1]).
    Raises ValueError for malformed/truncated buffers."""
    import struct

    if len(buf) < 4 or buf[0] or buf[1] or buf[2] != 0x08:
        raise ValueError("not an unsigned-byte IDX buffer")
    if len(buf) < 4 + 4 * buf[3]:
        raise ValueError("truncated IDX header")
    lib = _loader_lib()
    if lib is not None:
        ndim = buf[3]
        if 1 <= ndim <= 4:
            dims = struct.unpack(f">{ndim}I", buf[4:4 + 4 * ndim])
            total = int(np.prod(dims))
            out = np.empty((total,), np.float32)
            shape_out = (ctypes.c_int64 * 4)()
            ndim_out = ctypes.c_int()
            arr = (ctypes.c_ubyte * len(buf)).from_buffer_copy(buf)
            n = lib.idx_parse(arr, len(buf), 1 if scale else 0,
                              out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
                              total, shape_out, ctypes.byref(ndim_out))
            if n == total:
                return out.reshape(dims)
    # numpy fallback
    ndim = buf[3]
    dims = struct.unpack(f">{ndim}I", buf[4:4 + 4 * ndim])
    if len(buf) < 4 + 4 * ndim + int(np.prod(dims)):
        raise ValueError("truncated IDX data")
    data = np.frombuffer(buf, np.uint8, offset=4 + 4 * ndim).astype(np.float32)
    if scale:
        data = data / 255.0
    return data.reshape(dims)
