"""ctypes binding for the native threshold codec.

Reference parity: the nd4j Java side calls libnd4j's encode/decode threshold
ops over JNI; here the host-side codec is a C++ shared lib consumed via
ctypes (SURVEY §8.1: native work = host-side codecs, not device kernels —
the device path is XLA). Auto-builds with cmake on first use (cached under
native/build); when no toolchain is available, numpy fallbacks in THIS module
mirror the C ABI bit-for-bit (signed 1-based index format). These are
distinct from ops/compression.py, whose jax ops use an in-graph
(indices, values) format for use INSIDE compiled steps; this module is the
host-side wire format for DCN gradient exchange.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_TRIED = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    build_dir = os.path.join(_NATIVE_DIR, "build")
    so = os.path.join(build_dir, "libdl4j_tpu_native.so")
    if not os.path.exists(so):
        try:
            subprocess.run(["cmake", "-S", _NATIVE_DIR, "-B", build_dir],
                           check=True, capture_output=True, timeout=120)
            subprocess.run(["cmake", "--build", build_dir, "-j"],
                           check=True, capture_output=True, timeout=300)
        except Exception:
            return None
    if not os.path.exists(so):
        return None
    lib = ctypes.CDLL(so)
    lib.threshold_encode.restype = ctypes.c_int64
    lib.threshold_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
        ctypes.POINTER(ctypes.c_float)]
    lib.threshold_decode.restype = None
    lib.threshold_decode.argtypes = [
        ctypes.POINTER(ctypes.c_int32), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64]
    lib.bitmap_encode.restype = ctypes.c_int64
    lib.bitmap_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_float)]
    lib.bitmap_decode.restype = None
    lib.bitmap_decode.argtypes = [
        ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64, ctypes.c_float,
        ctypes.POINTER(ctypes.c_float)]
    return lib


def native_available() -> bool:
    return _get_lib() is not None


def _get_lib() -> Optional[ctypes.CDLL]:
    global _LIB, _TRIED
    with _LOCK:
        if not _TRIED:
            _TRIED = True
            _LIB = _build_and_load()
        if _LIB is None and os.environ.get("DL4J_TPU_REQUIRE_NATIVE"):
            # the CI gate sets this: a broken native build must be RED,
            # not a silent numpy fallback (round-3 verdict weak #6)
            raise RuntimeError(
                "DL4J_TPU_REQUIRE_NATIVE is set but libdl4j_tpu_native.so "
                "could not be built/loaded — fix the native toolchain stage")
    return _LIB


def _fptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def threshold_encode(grad: np.ndarray, threshold: float,
                     capacity: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Returns (signed int32 indices, residual). Native when available."""
    grad = np.ascontiguousarray(grad, np.float32).reshape(-1)
    capacity = capacity if capacity is not None else grad.size
    lib = _get_lib()
    if lib is None:
        return _py_encode(grad, threshold, capacity)
    idx = np.empty(capacity, np.int32)
    residual = np.empty_like(grad)
    n = lib.threshold_encode(_fptr(grad), grad.size, ctypes.c_float(threshold),
                             idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                             capacity, _fptr(residual))
    return idx[:n].copy(), residual


def threshold_decode(indices: np.ndarray, threshold: float, size: int) -> np.ndarray:
    indices = np.ascontiguousarray(indices, np.int32)
    lib = _get_lib()
    out = np.zeros(size, np.float32)
    if lib is None:
        pos = indices[indices > 0] - 1
        neg = -indices[indices < 0] - 1
        np.add.at(out, pos, threshold)
        np.add.at(out, neg, -threshold)
        return out
    lib.threshold_decode(indices.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                         indices.size, ctypes.c_float(threshold), _fptr(out), size)
    return out


def bitmap_encode(grad: np.ndarray, threshold: float) -> Tuple[np.ndarray, np.ndarray, int]:
    grad = np.ascontiguousarray(grad, np.float32).reshape(-1)
    lib = _get_lib()
    bits = np.zeros((grad.size + 3) // 4, np.uint8)
    residual = np.empty_like(grad)
    if lib is None:
        return _py_bitmap_encode(grad, threshold, bits, residual)
    nz = lib.bitmap_encode(_fptr(grad), grad.size, ctypes.c_float(threshold),
                           bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                           _fptr(residual))
    return bits, residual, int(nz)


def bitmap_decode(bits: np.ndarray, threshold: float, size: int) -> np.ndarray:
    lib = _get_lib()
    out = np.zeros(size, np.float32)
    bits = np.ascontiguousarray(bits, np.uint8)
    if lib is None:
        for i in range(size):
            code = (bits[i // 4] >> (2 * (i % 4))) & 0x3
            if code == 1:
                out[i] += threshold
            elif code == 2:
                out[i] -= threshold
        return out
    lib.bitmap_decode(bits.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                      size, ctypes.c_float(threshold), _fptr(out))
    return out


# ---- numpy fallbacks (identical semantics) --------------------------------


def _py_encode(grad, threshold, capacity):
    residual = grad.copy()
    hits = np.where(np.abs(grad) > threshold)[0][:capacity]
    signs = np.sign(grad[hits])
    idx = ((hits + 1) * signs).astype(np.int32)
    residual[hits] -= signs.astype(np.float32) * threshold
    return idx, residual


def _py_bitmap_encode(grad, threshold, bits, residual):
    residual[:] = grad
    nz = 0
    for i, g in enumerate(grad):
        code = 0
        if g > threshold:
            code = 1
            residual[i] = g - threshold
            nz += 1
        elif g < -threshold:
            code = 2
            residual[i] = g + threshold
            nz += 1
        bits[i // 4] |= code << (2 * (i % 4))
    return bits, residual, nz
