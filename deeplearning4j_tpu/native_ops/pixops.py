"""ctypes bindings for the native pixel/hash kernels (native/pixops.cpp).

Reference parity:
  * ImagePreProcessingScaler / NormalizerStandardize: their elementwise
    loops are native in the reference (libnd4j legacy transform kernels).
    Here the HOST-side input pipeline normalizes uint8 image batches in C++
    before device_put, keeping byte-wrangling off Python; the device path
    stays XLA.
  * murmur3_32: nd4j-common HashUtil role — stable bytes/string hashing
    for vocab bucketing and shard assignment.

Numpy fallbacks mirror the C ABI exactly when no toolchain is available.
"""

from __future__ import annotations

import ctypes
from typing import Optional, Union

import numpy as np

from deeplearning4j_tpu.native_ops.threshold import _get_lib


def _pix_lib() -> Optional[ctypes.CDLL]:
    lib = _get_lib()
    if lib is None:
        return None
    if not getattr(lib, "_pixops_bound", False):
        try:
            lib.u8_normalize.restype = None
            lib.u8_normalize.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_float, ctypes.c_float,
                ctypes.POINTER(ctypes.c_float)]
            lib.u8_standardize.restype = None
            lib.u8_standardize.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_int64, ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float),
                ctypes.POINTER(ctypes.c_float)]
            lib.murmur3_32.restype = ctypes.c_uint32
            lib.murmur3_32.argtypes = [
                ctypes.POINTER(ctypes.c_uint8), ctypes.c_int64,
                ctypes.c_uint32]
            lib._pixops_bound = True
        except AttributeError:
            return None  # stale .so without pixops — fall back
    return lib


def u8_normalize(img: np.ndarray, scale: float, shift: float = 0.0) -> np.ndarray:
    """float32 out = u8 in * scale + shift (ImagePreProcessingScaler path)."""
    img = np.ascontiguousarray(img, dtype=np.uint8)
    lib = _pix_lib()
    out = np.empty(img.shape, np.float32)
    if lib is not None:
        lib.u8_normalize(
            img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), img.size,
            ctypes.c_float(scale), ctypes.c_float(shift),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    np.multiply(img, np.float32(scale), out=out)
    out += np.float32(shift)
    return out


def u8_standardize(img: np.ndarray, mean: np.ndarray,
                   std: np.ndarray) -> np.ndarray:
    """Channel-last z-score of a uint8 image batch (NormalizerStandardize
    path): out = (in - mean[c]) / std[c], c = trailing axis."""
    img = np.ascontiguousarray(img, dtype=np.uint8)
    c = img.shape[-1]
    mean = np.ascontiguousarray(np.broadcast_to(mean, (c,)), np.float32)
    inv = np.ascontiguousarray(
        1.0 / np.maximum(np.broadcast_to(std, (c,)).astype(np.float32), 1e-8))
    lib = _pix_lib()
    out = np.empty(img.shape, np.float32)
    if lib is not None:
        lib.u8_standardize(
            img.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), img.size, c,
            mean.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            inv.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        return out
    return ((img.astype(np.float32) - mean) * inv).astype(np.float32)


def _murmur3_py(data: bytes, seed: int) -> int:
    """Numpy-free MurmurHash3 x86-32 fallback, bit-exact vs the C kernel."""
    c1, c2 = 0xCC9E2D51, 0x1B873593
    h = seed & 0xFFFFFFFF
    n = len(data)
    for i in range(0, n - (n & 3), 4):
        k = int.from_bytes(data[i:i + 4], "little")
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
        h = ((h << 13) | (h >> 19)) & 0xFFFFFFFF
        h = (h * 5 + 0xE6546B64) & 0xFFFFFFFF
    k = 0
    tail = data[n - (n & 3):]
    if n & 3 >= 3:
        k ^= tail[2] << 16
    if n & 3 >= 2:
        k ^= tail[1] << 8
    if n & 3 >= 1:
        k ^= tail[0]
        k = (k * c1) & 0xFFFFFFFF
        k = ((k << 15) | (k >> 17)) & 0xFFFFFFFF
        k = (k * c2) & 0xFFFFFFFF
        h ^= k
    h ^= n
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def murmur3_32(data: Union[str, bytes], seed: int = 0) -> int:
    """Stable 32-bit hash (HashUtil analog). Strings hash as UTF-8."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    lib = _pix_lib()
    if lib is not None:
        buf = (ctypes.c_uint8 * len(data)).from_buffer_copy(data) if data \
            else (ctypes.c_uint8 * 1)()
        return int(lib.murmur3_32(buf, len(data), ctypes.c_uint32(seed)))
    return _murmur3_py(bytes(data), seed)
