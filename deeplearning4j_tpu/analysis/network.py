"""graftcheck over layer-level networks (MultiLayerNetwork /
ComputationGraph — the Keras import targets).

Keras models do not lower into SameDiff recordings; they assemble layer
stacks whose shape algebra is the ``InputType`` propagation in
``nn/conf.py``/``nn/graph.py``. This module replays that propagation
defensively and converts every failure into the same GC-coded
:class:`~deeplearning4j_tpu.analysis.report.CheckReport` the graph
interpreter produces, so ``import_keras_*`` gets the identical
verify-before-run contract as the ONNX/TF importers:

* a layer whose ``output_type`` raises (rank/arity mismatch) → GC001
* a layer whose declared ``n_in`` contradicts the propagated input size
  → GC002
* a DAG that fails to toposort (cycle / missing vertex input) → GC004
"""

from __future__ import annotations

from typing import List

from deeplearning4j_tpu.analysis.report import CheckReport, make_finding
from deeplearning4j_tpu.lint.core import Finding


def _layer_label(lc, i: int) -> str:
    return f"layer[{i}] {type(lc).__name__}"


def _check_layer_chain(conf, layers, itype, graph_name: str,
                       findings: List[Finding]) -> None:
    from deeplearning4j_tpu.nn import conf as C

    for i, lc in enumerate(layers):
        pre = getattr(conf, "preprocessors", {}).get(i) if conf else None
        if pre is not None and itype is not None:
            if isinstance(pre, C.FeedForwardToCnnPreProcessor):
                itype = C.InputType.convolutional(pre.height, pre.width,
                                                  pre.channels)
            elif isinstance(pre, C.CnnToFeedForwardPreProcessor):
                itype = C.InputType.feed_forward(
                    pre.height * pre.width * pre.channels)
        if itype is not None and itype.kind == "feedforward" and \
                isinstance(lc, (C.DenseLayer, C.OutputLayer)):
            declared = getattr(lc, "n_in", 0)
            if declared and itype.size and declared != itype.size:
                findings.append(make_finding(
                    graph_name, i, "GC002",
                    f"{_layer_label(lc, i)}: declared n_in={declared} but "
                    f"the propagated input size is {itype.size}"))
        try:
            itype = lc.output_type(itype) if itype is not None else None
        except Exception as exc:  # noqa: BLE001 — converted to a finding
            findings.append(make_finding(
                graph_name, i, "GC001",
                f"{_layer_label(lc, i)}: output_type failed on input "
                f"{itype}: {type(exc).__name__}: {exc}"))
            itype = None


def check_network(net, graph_name: str = "<network>") -> CheckReport:
    """Static shape check of a built MultiLayerNetwork / ComputationGraph
    (or a bare MultiLayerConfiguration)."""
    findings: List[Finding] = []
    conf = getattr(net, "conf", net)

    nodes = getattr(conf, "nodes", None)
    if nodes is not None:  # ComputationGraph(Configuration)
        from deeplearning4j_tpu.nn import conf as C

        itypes = {}
        for name in getattr(conf, "network_inputs", []):
            it = conf.input_types.get(name, C.InputType.feed_forward(0))
            if it.kind == "convolutionalflat":
                it = C.InputType.convolutional(it.height, it.width,
                                               it.channels)
            itypes[name] = it
        done = set(itypes)
        remaining = list(nodes)
        order = []
        while remaining:
            progress = False
            for n in list(remaining):
                if all(i in done for i in n.inputs):
                    order.append(n)
                    done.add(n.name)
                    remaining.remove(n)
                    progress = True
            if not progress:
                findings.append(make_finding(
                    graph_name, len(order), "GC004",
                    f"graph has a cycle or missing inputs: "
                    f"{[n.name for n in remaining]}"))
                break
        for i, node in enumerate(order):
            in_types = [itypes.get(x) for x in node.inputs]
            try:
                if node.kind == "vertex":
                    itypes[node.name] = node.vertex.output_type(in_types)
                else:
                    it = in_types[0]
                    needs_ff = isinstance(
                        node.layer, (C.DenseLayer, C.OutputLayer,
                                     C.EmbeddingLayer))
                    if it is not None and needs_ff and it.kind in (
                            "convolutional", "convolutional3d"):
                        # runtime inserts the flatten (graph._infer_layer)
                        it = C.InputType.feed_forward(it.flat_size())
                    itypes[node.name] = node.layer.output_type(it)
            except Exception as exc:  # noqa: BLE001 — converted to a finding
                findings.append(make_finding(
                    graph_name, i, "GC001",
                    f"node '{node.name}' ({node.kind}): output_type failed "
                    f"on {in_types}: {type(exc).__name__}: {exc}"))
                itypes[node.name] = None
        return CheckReport(graph_name, findings)

    layers = getattr(conf, "layers", None)
    if layers is None:
        return CheckReport(graph_name, findings)
    _check_layer_chain(conf, layers, getattr(conf, "input_type", None),
                       graph_name, findings)
    return CheckReport(graph_name, findings)
